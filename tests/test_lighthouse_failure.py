"""Control-plane failure resilience: the manager must survive a flaky or
dying lighthouse.

Parity targets:
- The reference's manager survives failed/refused quorum RPCs via its
  ``quorum_retries`` loop with client re-creation per attempt
  (reference: manager.rs:250-327), proven against a fault-injecting
  MockLighthouse that errors N requests then recovers
  (reference: manager.rs:1109-1217). Here the fault injector is a TCP
  proxy that kills N connections in front of a real lighthouse — the
  native manager re-creates its RpcClient per attempt
  (native/src/manager.cc:126-143), so each dropped connection exercises
  one retry.
- The lighthouse is restartable on the same address mid-job: training
  stalls bounded-ly and resumes with no lost commits and no survivor
  divergence (the control-plane-SPOF story behind the reference's
  standalone lighthouse binary, reference: src/bin/lighthouse.rs).
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict

import jax
import numpy as np
import optax
import pytest

from tests.ft_harness import (
    Runner,
    _batch_for,
    _grad_fn,
    _init_model_params,
)
from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.ddp import ft_allreduce_gradients
from torchft_tpu.manager import Manager
from torchft_tpu.optim import Optimizer
from torchft_tpu.parallel.process_group import (
    FakeProcessGroupWrapper,
    ProcessGroupDummy,
    ProcessGroupTCP,
)
from torchft_tpu.parallel.store import StoreClient, StoreServer
from torchft_tpu.utils import netem


class FaultInjectingLighthouse(netem.TCPFront):
    """The reference MockLighthouse analogue (manager.rs:1109-1217) on this
    repo's wire: a framed-protobuf TCP front that REFUSES the next N
    LIGHTHOUSE_QUORUM requests with a proper error-status response and
    forwards everything else to a real lighthouse. Because the refusal is
    a valid response frame, the RpcClient's stale-connection redial never
    triggers — each injected failure consumes exactly one attempt of the
    native manager's quorum_retries loop (native/src/manager.cc:126-143),
    deterministically. Connection plumbing shared with the emulated-DCN
    LatencyProxy via netem.TCPFront."""

    def __init__(self, target_addr: str) -> None:
        from torchft_tpu import coordination as co

        self._co = co
        self._fail_remaining = 0
        self.failures_injected = 0
        self._lock = threading.Lock()
        super().__init__(target_addr)

    def fail_next(self, n: int) -> None:
        with self._lock:
            self._fail_remaining = n

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    def handle(self, conn: socket.socket) -> None:
        import struct

        co = self._co
        conn.settimeout(30)
        try:
            while not self.stopping:
                header = self._recv_exact(conn, 6)
                magic, method, length = struct.unpack("!BBI", header)
                payload = self._recv_exact(conn, length) if length else b""
                inject = False
                if method == co.LIGHTHOUSE_QUORUM:
                    with self._lock:
                        if self._fail_remaining > 0:
                            self._fail_remaining -= 1
                            self.failures_injected += 1
                            inject = True
                if inject:
                    body = b"injected lighthouse failure"
                    conn.sendall(
                        struct.pack("!BBI", co._RESP_MAGIC, co._STATUS_ERROR, len(body))
                        + body
                    )
                    continue
                # Forward verbatim to the real lighthouse, relay the reply.
                with socket.create_connection(self.target, timeout=10) as up:
                    up.sendall(header + payload)
                    rh = self._recv_exact(up, 6)
                    _, _, rlen = struct.unpack("!BBI", rh)
                    rbody = self._recv_exact(up, rlen) if rlen else b""
                conn.sendall(rh + rbody)
        except (OSError, ConnectionError):
            pass
        finally:
            conn.close()


def _make_manager(lighthouse_addr: str, quorum_retries: int, store: StoreServer):
    client = StoreClient(store.address(), prefix="g0")
    state = {"w": np.zeros(2)}
    return Manager(
        pg=ProcessGroupDummy(0, 1),
        min_replica_size=1,
        store=client,
        store_addr=store.address(),
        load_state_dict=lambda sd: state.update(sd),
        state_dict=lambda: dict(state),
        replica_id="flaky_lh_test",
        lighthouse_addr=lighthouse_addr,
        group_rank=0,
        group_world_size=1,
        use_async_quorum=False,
        # No heartbeats during the test window: every proxied connection
        # drop must be consumed by a QUORUM attempt, deterministically.
        heartbeat_interval=3600.0,
        timeout=15.0,
        quorum_timeout=20.0,
        quorum_retries=quorum_retries,
    )


def test_quorum_retries_rides_out_dropped_lighthouse_rpcs() -> None:
    """quorum_retries > 0: with N connections killed in front of the
    lighthouse and retries > N, every step's quorum still forms and
    commits — the MockLighthouse fault-injection contract
    (reference: manager.rs:1109-1217)."""
    lh = LighthouseServer(bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=5000)
    proxy = FaultInjectingLighthouse(lh.address())
    store = StoreServer()
    mgr = _make_manager(proxy.address(), quorum_retries=3, store=store)
    try:
        for step in range(3):
            # Two of the (up to) four attempts are refused; the retry
            # loop's fresh-client reconnect carries the round.
            proxy.fail_next(2)
            mgr.start_quorum()
            assert mgr.should_commit() is True
        assert mgr.current_step() == 3
        assert proxy.failures_injected == 6  # each refusal ate one retry
    finally:
        mgr.shutdown()
        proxy.shutdown()
        lh.shutdown()


def test_quorum_without_retries_fails_on_dropped_rpc() -> None:
    """Control: quorum_retries=0 turns the same single dropped connection
    into a quorum failure surfaced at the step boundary (supervisor
    territory) — proving the resilience above is the retry loop, not
    accident."""
    lh = LighthouseServer(bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=5000)
    proxy = FaultInjectingLighthouse(lh.address())
    store = StoreServer()
    mgr = _make_manager(proxy.address(), quorum_retries=0, store=store)
    try:
        mgr.start_quorum()
        assert mgr.should_commit() is True

        proxy.fail_next(1)
        with pytest.raises(RuntimeError, match="lighthouse quorum failed"):
            mgr.start_quorum()
        assert proxy.failures_injected == 1
    finally:
        mgr.shutdown()
        proxy.shutdown()
        lh.shutdown()


# ---------------------------------------------------------------------------
# Real lighthouse SIGKILL + same-address restart mid-training
# ---------------------------------------------------------------------------


def _spawn_lighthouse(
    port: int,
    min_replicas: int = 2,
    join_timeout_ms: int = 3000,
    heartbeat_timeout_ms: int = 5000,
) -> subprocess.Popen:
    """Starts the real `python -m torchft_tpu.lighthouse` daemon and blocks
    until it accepts TCP connections (observed readiness, not a sleep).
    Also used by the chaos soak's lighthouse-restart fault."""
    # Fail as NativeToolchainMissing (-> a clean conftest skip) instead of
    # an opaque child rc=1 when the native plane cannot build here.
    from torchft_tpu import _native

    _native.ensure_built()
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "torchft_tpu.lighthouse",
            "--bind",
            f"127.0.0.1:{port}",
            "--min-replicas",
            str(min_replicas),
            "--join-timeout-ms",
            str(join_timeout_ms),
            "--heartbeat-timeout-ms",
            str(heartbeat_timeout_ms),
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        env={**os.environ, "TPUFT_LOG": "warn"},
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"lighthouse exited at startup: rc={proc.returncode}")
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.5):
                return proc
        except OSError:
            time.sleep(0.1)
    proc.kill()
    raise TimeoutError("lighthouse did not start accepting connections")


def _reporting_ddp_loop(
    runner: Runner,
    rank: int,
    store_client: StoreClient,
    store_addr: str,
    progress: Dict[int, int],
    hold: threading.Event,
    hold_at_step: int,
) -> Dict:
    """ddp_train_loop sized down, publishing each committed step into the
    shared ``progress`` map so the test can gate the lighthouse kill and
    the resume check on OBSERVED training progress (CLAUDE.md: never on
    sleeps). Parks at ``hold_at_step`` until the test releases ``hold`` —
    the deterministic window in which the lighthouse is killed."""
    pg = FakeProcessGroupWrapper(ProcessGroupTCP(timeout=10.0))
    manager = Manager(
        pg=pg,
        min_replica_size=2,
        store=store_client,
        store_addr=store_addr,
        use_async_quorum=False,
        group_rank=rank,
        group_world_size=runner.world_size,
        lighthouse_addr=runner.lighthouse_addr,
        replica_id=f"lhkill_{runner.replica_group}",
        heartbeat_interval=0.5,
        timeout=15.0,
        quorum_timeout=60.0,
        **runner.manager_args,
    )
    opt = Optimizer(manager, optax.sgd(0.05), _init_model_params())
    history = {}
    try:
        while manager.current_step() < runner.num_steps:
            step = manager.current_step()
            if step == hold_at_step:
                assert hold.wait(timeout=180), "test never released the hold"
            opt.begin_step()
            manager.wait_quorum()
            x, y = _batch_for(step, runner.replica_group)
            grads = _grad_fn(opt.params, x, y)
            avg = ft_allreduce_gradients(manager, grads)
            if opt.step(avg):
                history[manager.current_step()] = jax.tree_util.tree_map(
                    np.asarray, opt.params
                )
                progress[runner.replica_group] = manager.current_step()
        return {"history": history}
    finally:
        manager.shutdown(wait=False)
        pg.shutdown()


def test_lighthouse_sigkill_restart_mid_training() -> None:
    """SIGKILL the real lighthouse daemon mid-training and restart it on
    the same address: training stalls bounded-ly (the managers'
    quorum_retries loop keeps re-dialing), then resumes with no lost
    commits and bitwise-identical replica states."""
    with socket.create_server(("127.0.0.1", 0)) as s:
        port = s.getsockname()[1]
    proc = _spawn_lighthouse(port)
    addr = f"127.0.0.1:{port}"
    progress: Dict[int, int] = {0: 0, 1: 0}
    num_steps = 6
    hold_at_step = 3
    hold = threading.Event()
    runners = [
        Runner(
            replica_group=g,
            lighthouse_addr=addr,
            train_loop=_reporting_ddp_loop,
            num_steps=num_steps,
            use_async_quorum=False,
            # Enough fast-failing (connection-refused) attempts to bridge
            # the lighthouse's restart: ~10 attempts/s (100 ms inter-try
            # sleep), restart observed-ready in ~3-5 s on this box.
            manager_args={"quorum_retries": 150},
            train_loop_args={
                "progress": progress,
                "hold": hold,
                "hold_at_step": hold_at_step,
            },
        )
        for g in range(2)
    ]

    def _check_alive(futs) -> None:
        for f in futs:
            if f.done() and f.exception() is not None:
                raise f.exception()

    try:
        with ThreadPoolExecutor(max_workers=2, thread_name_prefix="lhkill") as pool:
            futs = [pool.submit(r.run_replica) for r in runners]

            # Both groups commit up to the hold point, then park.
            deadline = time.monotonic() + 120
            while min(progress.values()) < hold_at_step:
                _check_alive(futs)
                assert time.monotonic() < deadline, f"no progress: {progress}"
                time.sleep(0.1)

            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)  # observed death
            t_kill = time.monotonic()
            floor = dict(progress)

            # Release the replicas INTO the outage: their quorum_retries
            # loop hammers the dead address while the daemon restarts on
            # the same port.
            hold.set()
            proc = _spawn_lighthouse(port)  # observed restart (TCP accept)

            # Bounded stall: both groups commit a NEW step within the bound.
            resume_deadline = time.monotonic() + 150
            while not all(progress[g] > floor[g] for g in progress):
                _check_alive(futs)
                assert (
                    time.monotonic() < resume_deadline
                ), f"stall not bounded: {progress} vs {floor}"
                time.sleep(0.1)
            stall_s = time.monotonic() - t_kill

            results = [f.result(timeout=180) for f in futs]
    finally:
        proc.kill()

    h0 = results[0][0]["history"]
    h1 = results[1][0]["history"]
    # No lost commits: the step counter only advances on commit, so both
    # groups must hold every step 1..num_steps exactly once.
    assert sorted(h0) == list(range(1, num_steps + 1)), sorted(h0)
    assert sorted(h1) == list(range(1, num_steps + 1)), sorted(h1)
    # No survivor divergence: bitwise-identical params at every step.
    for step in h0:
        for (k, a), (_, b) in zip(
            sorted(h0[step].items()), sorted(h1[step].items())
        ):
            assert np.array_equal(a, b), f"divergence at step {step} key {k}"
    print(f"lighthouse kill->resume stall: {stall_s:.1f}s")
