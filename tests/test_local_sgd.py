"""LocalSGD / DiLoCo unit tests against mocked coordination (parity:
local_sgd_test.py) plus golden-file numerics regression (parity:
diloco_regression_test.py)."""

import json
import os
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from test_manager import make_manager, make_quorum

from torchft_tpu.local_sgd import DiLoCo, LocalSGD
from torchft_tpu.parallel.process_group import ProcessGroupDummy

FIXTURES = Path(__file__).parent / "fixtures"


def make_params():
    return {
        "w1": jnp.array([1.0, 2.0], dtype=jnp.float32),
        "w2": jnp.array([[3.0], [4.0]], dtype=jnp.float32),
        "b": jnp.array([0.5], dtype=jnp.float32),
    }


def fixed_grads(step: int):
    return {
        "w1": jnp.full(2, 0.1 * (step + 1), dtype=jnp.float32),
        "w2": jnp.full((2, 1), 0.2, dtype=jnp.float32),
        "b": jnp.array([0.05], dtype=jnp.float32),
    }


def scripted_manager(**kwargs):
    kwargs.setdefault("min_replica_size", 1)
    manager, client, pg, transport = make_manager(pg=ProcessGroupDummy(), **kwargs)
    client._quorum.return_value = make_quorum(replica_world_size=1, max_world_size=1)
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote
    return manager


# -- LocalSGD ---------------------------------------------------------------


def test_local_sgd_syncs_every_n_steps() -> None:
    manager = scripted_manager()
    algo = LocalSGD(manager, optax.sgd(0.1), make_params(), sync_every=2)

    assert not algo.step(fixed_grads(0))  # local only
    assert algo.step(fixed_grads(1))  # sync round commits
    # With a single participant averaging is identity: params equal plain SGD.
    expected = make_params()
    opt_state = optax.sgd(0.1).init(expected)
    for s in range(2):
        updates, opt_state = optax.sgd(0.1).update(fixed_grads(s), opt_state, expected)
        expected = optax.apply_updates(expected, updates)
    for key in expected:
        np.testing.assert_allclose(algo.params[key], expected[key], rtol=1e-6)


def test_local_sgd_sync_preserves_shardings() -> None:
    """The parameter-averaging sync rides the shard-preserving path: after
    a committed sync, sharded leaves keep their NamedShardings (a host
    round-trip that re-landed them replicated would desync multi-rank
    groups' jitted programs, and a whole-leaf fetch would raise outright
    on non-fully-addressable state)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("fsdp", "tp"))
    sharding = NamedSharding(mesh, P("fsdp", "tp"))
    params = {
        "w": jax.device_put(jnp.ones((4, 4), jnp.float32), sharding),
        "b": jnp.zeros((3,), jnp.float32),
    }
    manager = scripted_manager()
    algo = LocalSGD(manager, optax.sgd(0.1), params, sync_every=1)
    grads = {
        "w": jax.device_put(jnp.full((4, 4), 0.5, jnp.float32), sharding),
        "b": jnp.full((3,), 0.1, jnp.float32),
    }
    assert algo.step(grads)  # sync round commits
    assert algo.params["w"].sharding == sharding
    np.testing.assert_allclose(
        np.asarray(algo.params["w"]), np.full((4, 4), 0.95), rtol=1e-6
    )


def test_local_sgd_failed_commit_keeps_local_params() -> None:
    manager = scripted_manager()
    manager._client.should_commit.side_effect = None
    manager._client.should_commit.return_value = False
    algo = LocalSGD(manager, optax.sgd(0.1), make_params(), sync_every=1)
    committed = algo.step(fixed_grads(0))
    assert not committed
    # Local inner step still applied.
    assert not np.allclose(algo.params["w1"], make_params()["w1"])


# -- DiLoCo -----------------------------------------------------------------


def test_diloco_requires_sync_quorum() -> None:
    manager = scripted_manager(use_async_quorum=True)
    with pytest.raises(ValueError, match="synchronous quorum"):
        DiLoCo(manager, optax.sgd(0.1), optax.sgd(1.0), make_params(), sync_every=2)


def test_diloco_validations() -> None:
    manager = scripted_manager(use_async_quorum=False)
    with pytest.raises(ValueError, match="multiple"):
        DiLoCo(
            manager, optax.sgd(0.1), optax.sgd(1.0), make_params(),
            sync_every=3, n_fragments=2,
        )
    with pytest.raises(ValueError, match="synced before"):
        DiLoCo(
            manager, optax.sgd(0.1), optax.sgd(1.0), make_params(),
            sync_every=2, n_fragments=1, fragment_sync_delay=5,
        )


def test_diloco_outer_step_applies_averaged_pseudogradient() -> None:
    manager = scripted_manager(use_async_quorum=False)
    inner = optax.sgd(0.1)
    outer = optax.sgd(1.0)  # lr=1: global = backup - avg pseudograd exactly
    algo = DiLoCo(manager, inner, outer, make_params(), sync_every=2)

    p0 = make_params()
    assert not algo.step(fixed_grads(0))
    assert algo.step(fixed_grads(1))

    # Single participant: avg pseudograd == backup - local. Outer SGD(lr=1)
    # on the backup gives exactly the local params; alpha=0 takes the global.
    inner_state = inner.init(p0)
    local = p0
    for s in range(2):
        updates, inner_state = inner.update(fixed_grads(s), inner_state, local)
        local = optax.apply_updates(local, updates)
    for key in local:
        np.testing.assert_allclose(algo.params[key], local[key], rtol=1e-6)


def test_diloco_failed_commit_restores_global_params() -> None:
    manager = scripted_manager(use_async_quorum=False)
    manager._client.should_commit.side_effect = None
    manager._client.should_commit.return_value = False
    p0 = make_params()
    algo = DiLoCo(manager, optax.sgd(0.1), optax.sgd(0.7), p0, sync_every=1)
    committed = algo.step(fixed_grads(0))
    assert not committed
    # Failed sync resets the fragment to the last global state (= init).
    for key in p0:
        np.testing.assert_allclose(algo.params[key], p0[key], rtol=1e-6)


def test_diloco_fragments_rotate_and_cover_all_leaves() -> None:
    manager = scripted_manager(use_async_quorum=False)
    algo = DiLoCo(
        manager, optax.sgd(0.1), optax.sgd(1.0), make_params(),
        sync_every=2, n_fragments=2,
    )
    covered = sorted(i for frag in algo._fragments for i in frag.leaf_indices)
    assert covered == list(range(3))
    # Fragment choice keyed by manager step.
    assert algo._current_fragment() == 0
    manager._step = 1
    assert algo._current_fragment() == 1


def test_diloco_update_alpha_mixes_local_and_global() -> None:
    manager = scripted_manager(use_async_quorum=False)
    p0 = make_params()
    algo = DiLoCo(
        manager, optax.sgd(0.1), optax.sgd(1.0), p0, sync_every=1,
        fragment_update_alpha=1.0,  # keep local entirely
    )
    inner = optax.sgd(0.1)
    inner_state = inner.init(p0)
    updates, _ = inner.update(fixed_grads(0), inner_state, p0)
    local = optax.apply_updates(p0, updates)
    algo.step(fixed_grads(0))
    for key in local:
        np.testing.assert_allclose(algo.params[key], local[key], rtol=1e-6)


# -- golden-file regression (parity: diloco_regression_test.py) -------------


def check_or_regen_golden(name: str, history: list) -> None:
    """Compares a parameter history to the committed fixture (or regenerates
    it under TPUFT_REGEN_FIXTURES=1)."""
    path = FIXTURES / name
    if os.environ.get("TPUFT_REGEN_FIXTURES") == "1":
        FIXTURES.mkdir(exist_ok=True)
        path.write_text(json.dumps(history, indent=1))
        pytest.skip("regenerated fixture")
    assert path.exists(), f"fixture {name} missing; run with TPUFT_REGEN_FIXTURES=1"
    golden = json.loads(path.read_text())
    assert len(golden) == len(history), "fixture/history length mismatch"
    for step, (got, want) in enumerate(zip(history, golden)):
        for key in want:
            np.testing.assert_allclose(
                got[key], want[key], rtol=1e-6, err_msg=f"step {step} key {key}"
            )


@pytest.mark.parametrize(
    "n_fragments,sync_delay,alpha",
    [(1, 0, 0.0), (2, 0, 0.0), (2, 1, 0.0), (2, 0, 0.5)],
)
def test_diloco_golden_history(n_fragments, sync_delay, alpha) -> None:
    manager = scripted_manager(use_async_quorum=False)
    algo = DiLoCo(
        manager,
        optax.sgd(0.1),
        optax.sgd(0.7, momentum=0.9, nesterov=True),
        make_params(),
        sync_every=4,
        n_fragments=n_fragments,
        fragment_sync_delay=sync_delay,
        fragment_update_alpha=alpha,
    )
    history = []
    for step in range(12):
        algo.step(fixed_grads(step))
        history.append(
            {k: np.asarray(v).tolist() for k, v in sorted(algo.params.items())}
        )

    check_or_regen_golden(f"diloco_f{n_fragments}_d{sync_delay}_a{alpha}.json", history)


@pytest.mark.parametrize("fail_sync_index", [1])
def test_diloco_failure_timeline_golden(fail_sync_index: int) -> None:
    """Failure-recovery timeline numerics (parity: diloco_regression_test.py
    mocked failure timelines :288-639): a commit failure at sync round k
    resets the in-flight fragment to its last global state, and the
    subsequent history matches the committed fixture."""
    manager = scripted_manager(use_async_quorum=False)
    sync_calls = [0]

    def should_commit(rank, step, vote, timeout):
        sync_calls[0] += 1
        if sync_calls[0] - 1 == fail_sync_index:
            return False
        return vote

    manager._client.should_commit.side_effect = should_commit

    algo = DiLoCo(
        manager,
        optax.sgd(0.1),
        optax.sgd(0.7, momentum=0.9, nesterov=True),
        make_params(),
        sync_every=2,
        n_fragments=1,
    )
    history = []
    committed_flags = []
    for step in range(10):
        committed_flags.append(algo.step(fixed_grads(step)))
        history.append(
            {k: np.asarray(v).tolist() for k, v in sorted(algo.params.items())}
        )
    # The scripted failure lands at sync round fail_sync_index (sync rounds
    # commit on steps 2k+1 with sync_every=2).
    for sync_round in range(5):
        expected = sync_round != fail_sync_index
        assert committed_flags[2 * sync_round + 1] is expected, sync_round

    check_or_regen_golden(
        f"diloco_failure_timeline_{fail_sync_index}.json", history
    )


def test_heal_restore_preserves_shardings() -> None:
    """Healing restores state onto the EXISTING leaves' shardings: a
    joiner whose params carry fsdp/tp NamedShardings must not end up with
    replicated arrays after _load_inner/_load_state (replicated restores
    made the joiner's jitted programs partition differently from the
    donor's — one-ulp drift per sync, breaking the bitwise invariant)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchft_tpu.local_sgd import _restore_like

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("fsdp", "tp"))
    sharding = NamedSharding(mesh, P("fsdp", "tp"))
    params = {
        "w": jax.device_put(jnp.ones((4, 4), jnp.float32), sharding),
        "b": jnp.zeros((3,), jnp.float32),
    }

    manager = scripted_manager(use_async_quorum=False)
    algo = DiLoCo(
        manager, optax.sgd(1.0), optax.sgd(1.0), params,
        sync_every=2, n_fragments=2, should_quantize=True,
    )
    # Simulate a heal: host-numpy state (what the checkpoint wire carries).
    algo._load_inner(
        {
            "leaves": [np.full((3,), 7.0, np.float32), np.full((4, 4), 5.0, np.float32)],
            "opt_state": jax.tree_util.tree_map(
                lambda x: np.asarray(x) if hasattr(x, "shape") else x,
                algo.inner_opt_state,
            ),
        }
    )
    # Flatten order: "b" then "w" (sorted dict keys) — w is leaf 1.
    healed_w = algo._leaves[1]
    assert healed_w.sharding == sharding, healed_w.sharding
    np.testing.assert_array_equal(np.asarray(healed_w), np.full((4, 4), 5.0))

    # Quantized fragments keep device backups: heal restores their
    # shardings too (fragment 1 owns leaf index 1 = w).
    frag = algo._fragments[1]
    frag._load_state(
        {
            "original_parameters": [np.full((4, 4), 9.0, np.float32)],
            "outer_optimizer": jax.tree_util.tree_map(
                lambda x: np.asarray(x) if hasattr(x, "shape") else x,
                frag.outer_opt_state,
            ),
        }
    )
    assert frag.backup[0].sharding == sharding
    np.testing.assert_array_equal(np.asarray(frag.backup[0]), np.full((4, 4), 9.0))

    # Structure mismatch falls back to a plain restore instead of raising.
    out = _restore_like({"different": np.ones(2, np.float32)}, {"x": 1}, device=True)
    assert isinstance(out["different"], jax.Array)

    # LocalSGD heal restores the params' shardings the same way.
    algo2 = LocalSGD(manager, optax.sgd(1.0), params, sync_every=2, register_key="ls2")
    algo2._load_state(
        {
            "params": {
                "w": np.full((4, 4), 3.0, np.float32),
                "b": np.zeros((3,), np.float32),
            },
            "opt_state": jax.tree_util.tree_map(
                lambda x: np.asarray(x) if hasattr(x, "shape") else x,
                algo2.opt_state,
            ),
        }
    )
    assert algo2.params["w"].sharding == sharding


def test_diloco_fused_step_matches_grads_path() -> None:
    """make_step_fn (fused loss+update dispatch) produces bitwise the same
    trajectory as step(grads) with the same schedule."""

    def loss_fn(params, x):
        pred = x @ params["w2"] * params["w1"].sum() + params["b"]
        return (pred**2).mean()

    x = jnp.full((4, 2), 0.1, dtype=jnp.float32)

    managers = [scripted_manager(), scripted_manager()]
    algos = [
        DiLoCo(
            m,
            inner_tx=optax.sgd(0.01),
            outer_tx=optax.sgd(0.7, momentum=0.9, nesterov=True),
            params=make_params(),
            sync_every=4,
            n_fragments=2,
        )
        for m in managers
    ]
    fused = algos[1].make_step_fn(loss_fn)

    for step in range(8):
        grads = jax.grad(loss_fn)(algos[0].params, x)
        committed_a = algos[0].step(grads)
        loss, committed_b = fused(x)
        assert committed_a == committed_b
        assert float(loss) >= 0.0
    for leaf_a, leaf_b in zip(
        jax.tree_util.tree_leaves(algos[0].params),
        jax.tree_util.tree_leaves(algos[1].params),
    ):
        np.testing.assert_array_equal(np.asarray(leaf_a), np.asarray(leaf_b))


def test_local_sgd_make_step_fn_fused_matches_plain() -> None:
    """The fused inner step must reproduce the exact plain trajectory (one
    jitted program per step) and sync/commit at the boundary."""
    manager = scripted_manager()
    tx = optax.sgd(0.2, momentum=0.9)
    params = {"w": jnp.array([1.0, -2.0, 3.0], jnp.float32)}
    algo = LocalSGD(manager, tx, params, sync_every=3)

    def loss_fn(p, batch):
        return jnp.sum((p["w"] - batch) ** 2)

    step_fn = algo.make_step_fn(loss_fn)
    batches = [jnp.full((3,), 0.1 * i, jnp.float32) for i in range(6)]
    synced = []
    for batch in batches:
        _, s = step_fn(batch)
        synced.append(s)
    assert synced == [False, False, True, False, False, True]

    # Identically-structured fused plain program (single participant:
    # averaging is identity, so the trajectory must match bitwise).
    @jax.jit
    def fused(p, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(p, batch)
        updates, opt_state = tx.update(grads, opt_state, p)
        return loss, optax.apply_updates(p, updates), opt_state

    expected, opt_state = params, tx.init(params)
    for batch in batches:
        _, expected, opt_state = fused(expected, opt_state, batch)
    np.testing.assert_array_equal(
        np.asarray(algo.params["w"]), np.asarray(expected["w"])
    )
