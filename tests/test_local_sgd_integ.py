"""DiLoCo integration: multi-group semi-sync training with fault injection
(parity: local_sgd_integ_test.py — recovery, streaming fragments, asserting
per-fragment global state + outer optimizer equality across replicas)."""

import numpy as np
import jax
import pytest

from torchft_tpu.coordination import LighthouseServer

from ft_harness import EventInjector, Runner, diloco_train_loop, run_replica_groups


@pytest.fixture()
def lighthouse():
    server = LighthouseServer(
        min_replicas=1,
        join_timeout_ms=10000,
        heartbeat_timeout_ms=1000,
        quorum_tick_ms=20,
    )
    yield server
    server.shutdown()


def assert_equal_global_state(results) -> None:
    """Per-fragment backups and outer optimizer state bitwise equal across
    replica groups (parity: local_sgd_integ_test.assert_equal_global_state)."""
    reference = results[0][0]["global_state"]
    for group_result in results[1:]:
        state = group_result[0]["global_state"]
        assert len(state) == len(reference)
        for frag_ref, frag in zip(reference, state):
            for b_ref, b in zip(frag_ref["backup"], frag["backup"]):
                assert b_ref.tobytes() == b.tobytes(), "fragment backup differs"
            leaves_ref = jax.tree_util.tree_leaves(frag_ref["outer_opt"])
            leaves = jax.tree_util.tree_leaves(frag["outer_opt"])
            for l_ref, l in zip(leaves_ref, leaves):
                if hasattr(l_ref, "tobytes"):
                    assert np.asarray(l_ref).tobytes() == np.asarray(l).tobytes()


@pytest.mark.parametrize("n_fragments,delay", [(1, 0), (2, 0), (2, 1)])
def test_diloco_two_groups_healthy(lighthouse, n_fragments, delay) -> None:
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=diloco_train_loop,
            use_async_quorum=False,
            train_loop_args={
                "num_syncs": 4,
                "sync_every": 4,
                "n_fragments": n_fragments,
                "fragment_sync_delay": delay,
            },
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=180)
    for group_result in results:
        assert group_result[0]["manager_state"]["step"] == 4
    assert_equal_global_state(results)


def test_diloco_recovery_after_kill(lighthouse) -> None:
    injector = EventInjector().fail_at(group=1, step=1)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=diloco_train_loop,
            use_async_quorum=False,
            injector=injector,
            train_loop_args={"num_syncs": 4, "sync_every": 4, "n_fragments": 2},
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=240)
    assert injector.count == 1
    for group_result in results:
        assert group_result[0]["manager_state"]["step"] == 4
    assert_equal_global_state(results)
    # North star (BASELINE.md): the kill costs the surviving group at most
    # one outer step (the in-flight sync when its peer died).
    assert results[0][0]["failed_syncs"] <= 1, results[0][0]["failed_syncs"]


@pytest.mark.parametrize("wire", ["fp8", "int4"])
def test_diloco_quantized_two_groups(lighthouse, monkeypatch, wire) -> None:
    """The quantized device pipeline: pseudograds quantized on device, only
    the wire payload crosses the host boundary; global state must still
    converge bitwise across groups — for the default fp8 format and the
    packed-int4 half-width format alike (TPUFT_WIRE_DTYPE threads through
    the whole pipeline: device codec -> wire -> fused reduce)."""
    monkeypatch.setenv("TPUFT_WIRE_DTYPE", wire)
    # Spy on the device codec so a silent fallback to fp8 cannot pass the
    # int4 case: record the payload dtype the pipeline actually produces.
    import ml_dtypes

    from torchft_tpu.ops import quantization as q

    seen_dtypes = []
    orig_quantize = q.quantize_blocks_device

    def spy(x, block=q.BLOCK, wire=None):
        payload, scales = orig_quantize(x, block, wire=wire)
        seen_dtypes.append(np.dtype(payload.dtype))
        return payload, scales

    monkeypatch.setattr(q, "quantize_blocks_device", spy)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=diloco_train_loop,
            use_async_quorum=False,
            train_loop_args={
                "num_syncs": 3,
                "sync_every": 2,
                "n_fragments": 1,
                "should_quantize": True,
            },
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=180)
    for group_result in results:
        assert group_result[0]["manager_state"]["step"] == 3
    assert_equal_global_state(results)
    expected = np.uint8 if wire == "int4" else np.dtype(ml_dtypes.float8_e4m3fn)
    assert seen_dtypes and all(d == expected for d in seen_dtypes), seen_dtypes
