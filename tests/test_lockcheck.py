"""Runtime lock-order detector (torchft_tpu.utils.lockcheck) tier-1 suite.

The load-bearing case: a real A→B / B→A acquisition cycle across two
threads is detected (and raised) at the second thread's closing acquire.
Plus: the commit-barrier hold check, RWLock integration, creation-site
filtering, and clean disable semantics.
"""

import threading

import pytest

from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.utils import lockcheck


@pytest.fixture()
def detector():
    """Enables the detector with a clean graph; restores state after."""
    was_enabled = lockcheck.enabled()
    lockcheck.enable()
    lockcheck.reset()
    try:
        yield lockcheck
    finally:
        lockcheck.reset()
        if not was_enabled:
            lockcheck.disable()


def test_instrumented_creation_site_filter(detector) -> None:
    # Created from a tests/ frame: instrumented proxy.
    lock = threading.Lock()
    assert "test_lockcheck" in repr(lock)
    with lock:
        pass  # acquire/release roundtrip works


def test_cycle_across_two_threads_detected(detector) -> None:
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    # Distinct creation sites (one per line above) => distinct graph nodes.

    t1_done = threading.Event()
    errors = []

    def t1() -> None:
        # Establishes the order A -> B, then fully releases.
        with lock_a:
            with lock_b:
                pass
        t1_done.set()

    def t2() -> None:
        t1_done.wait(5)
        # B -> A closes the cycle: the inner acquire must raise.
        try:
            with lock_b:
                with lock_a:
                    pass
        except lockcheck.LockOrderError as e:
            errors.append(e)

    thread1 = threading.Thread(target=t1)
    thread2 = threading.Thread(target=t2)
    thread1.start()
    thread1.join(5)
    thread2.start()
    thread2.join(5)
    assert len(errors) == 1
    assert "lock-order cycle" in str(errors[0])
    assert lockcheck.violations()
    # The failed acquire must have released the inner lock: both locks
    # remain usable.
    with lock_a:
        pass
    with lock_b:
        pass


def test_same_site_instances_do_not_false_positive(detector) -> None:
    def make():
        return threading.Lock()

    first, second = make(), make()  # identical creation site
    with first:
        with second:
            pass
    with second:
        with first:
            pass  # reverse nesting of same-site instances: no order claim
    assert lockcheck.violations() == []


def test_barrier_check_flags_held_lock(detector) -> None:
    lock = threading.Lock()
    with pytest.raises(lockcheck.LockOrderError, match="commit barrier"):
        with lock:
            lockcheck.check_barrier("test-barrier")
    assert any("test-barrier" in v for v in lockcheck.violations())
    lockcheck.check_barrier("test-barrier")  # nothing held: clean


def test_rwlock_logical_hold_reported(detector) -> None:
    rwlock = RWLock()
    assert rwlock.w_acquire(timeout=1)
    try:
        with pytest.raises(lockcheck.LockOrderError, match="RWLock"):
            lockcheck.check_barrier("rwlock-barrier")
    finally:
        rwlock.w_release()
    lockcheck.check_barrier("rwlock-barrier")  # released: clean

    with rwlock.r_lock(timeout=1):
        with pytest.raises(lockcheck.LockOrderError):
            lockcheck.check_barrier("rwlock-read-barrier")
    lockcheck.check_barrier("rwlock-read-barrier")


def test_rwlock_in_cycle_with_plain_lock(detector) -> None:
    rwlock = RWLock()
    plain = threading.Lock()
    order_set = threading.Event()
    errors = []

    def t1() -> None:
        assert rwlock.w_acquire(timeout=1)
        with plain:
            pass
        rwlock.w_release()
        order_set.set()

    def t2() -> None:
        order_set.wait(5)
        with plain:
            try:
                rwlock.w_acquire(timeout=1)
                rwlock.w_release()
            except lockcheck.LockOrderError as e:
                errors.append(e)

    thread1 = threading.Thread(target=t1)
    thread2 = threading.Thread(target=t2)
    thread1.start()
    thread1.join(5)
    thread2.start()
    thread2.join(5)
    assert len(errors) == 1
    # The failed w_acquire rolled the writer state back: still acquirable.
    assert rwlock.w_acquire(timeout=1)
    rwlock.w_release()


def test_condition_wait_releases_hold(detector) -> None:
    cond = threading.Condition()
    hits = []

    def waiter() -> None:
        with cond:
            cond.wait_for(lambda: bool(hits), timeout=5)

    thread = threading.Thread(target=waiter)
    thread.start()
    # While the waiter sleeps inside wait_for it must NOT count as holding
    # the condition — this thread can acquire it.
    acquired = cond.acquire(timeout=2)
    assert acquired
    hits.append(1)
    cond.notify_all()
    cond.release()
    thread.join(5)


def test_disable_restores_plain_locks(detector) -> None:
    lockcheck.disable()
    try:
        lock = threading.Lock()
        assert not isinstance(lock, lockcheck._InstrumentedLock)
        lockcheck.check_barrier("noop")  # disabled: never raises
    finally:
        lockcheck.enable()


def test_manager_should_commit_runs_barrier_check(detector, monkeypatch) -> None:
    """The check is wired into the real Manager.should_commit (no native
    plane needed: everything it touches before the check is stubbed)."""
    from torchft_tpu.manager import Manager

    manager = Manager.__new__(Manager)  # bypass __init__ (needs servers)
    lock = threading.Lock()
    with lock:
        with pytest.raises(lockcheck.LockOrderError, match="should_commit"):
            Manager.should_commit(manager)
