"""Lock-order detector over REAL kill/heal drills: every ft_harness drill
runs with TPUFT_LOCK_CHECK on by default, so these assert the acceptance
property directly — a full kill/heal cycle in BOTH commit orderings
(strict per-step and pipelined depth-1) produces no lock-order cycles and
never holds a lock across a commit barrier."""

import pytest

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.utils import lockcheck

from ft_harness import (
    EventInjector,
    Runner,
    ddp_train_loop,
    pipelined_ddp_train_loop,
    run_replica_groups,
)


@pytest.fixture()
def lighthouse():
    # Generous join timeout: 1-core GIL scheduling (see test_manager_integ).
    server = LighthouseServer(
        min_replicas=1,
        join_timeout_ms=10000,
        heartbeat_timeout_ms=1000,
        quorum_tick_ms=20,
    )
    yield server
    server.shutdown()


@pytest.fixture(autouse=True)
def clean_detector():
    assert lockcheck.enabled(), "ft_harness import should have enabled lockcheck"
    before = set(lockcheck.violations())
    yield
    after = [v for v in lockcheck.violations() if v not in before]
    assert after == [], "lock-order violations during drill:\n" + "\n".join(after)


@pytest.mark.parametrize(
    "train_loop", [ddp_train_loop, pipelined_ddp_train_loop],
    ids=["strict", "pipelined"],
)
def test_kill_heal_drill_is_lock_clean(lighthouse, train_loop) -> None:
    injector = EventInjector().fail_at(group=1, step=1)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=train_loop,
            num_steps=4,
            injector=injector,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=180)
    assert injector.count == 1
    for group_result in results:
        assert group_result[0]["manager_state"]["step"] == 4
    # The drill exercised instrumented locks (RWLock holds at minimum);
    # the autouse fixture asserts zero violations on teardown.
