"""Manager state-machine tests against mocked coordination clients.

Parity target: the reference's manager_test.py — each test scripts a
QuorumResult on a mocked ManagerClient and asserts the per-step state
machine: configure-on-quorum-change, participation math, healing sync/async,
error funnel, commit/max_retries, FIXED_WITH_SPARES.
"""

import threading
from typing import Optional
from unittest.mock import MagicMock, create_autospec, patch

import numpy as np
import pytest

from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.coordination import QuorumResult
from torchft_tpu.manager import ExceptionWithTraceback, Manager, WorldSizeMode
from torchft_tpu.parallel.process_group import ProcessGroup, ProcessGroupDummy
from torchft_tpu.work import _DummyWork


class _FakeStore:
    def __init__(self) -> None:
        self.data = {
            "manager_addr": b"fake:1234",
            "replica_id": b"test_replica:uuid",
        }

    def get(self, key: str, timeout: float = 0, wait: bool = True):
        return self.data.get(key)

    def set(self, key: str, value: bytes, timeout: float = 0) -> None:
        self.data[key] = value


def make_quorum(
    quorum_id: int = 1,
    replica_rank: int = 0,
    replica_world_size: int = 2,
    heal: bool = False,
    max_step: int = 0,
    max_rank: Optional[int] = None,
    max_world_size: int = 2,
    recover_src_manager_address: str = "",
    recover_src_replica_rank: Optional[int] = None,
    recover_dst_replica_ranks=(),
    quorum=None,
) -> QuorumResult:
    if max_rank is None and not heal:
        max_rank = replica_rank
    return QuorumResult(
        quorum_id=quorum_id,
        replica_rank=replica_rank,
        replica_world_size=replica_world_size,
        recover_src_manager_address=recover_src_manager_address,
        recover_src_replica_rank=recover_src_replica_rank,
        recover_dst_replica_ranks=list(recover_dst_replica_ranks),
        store_address="store:0",
        max_step=max_step,
        max_rank=max_rank,
        max_world_size=max_world_size,
        heal=heal,
        quorum=quorum,
    )


def make_manager(
    pg=None,
    use_async_quorum: bool = False,
    min_replica_size: int = 2,
    world_size_mode: WorldSizeMode = WorldSizeMode.DYNAMIC,
    max_retries: Optional[int] = None,
    **kwargs,
):
    pg = pg if pg is not None else create_autospec(ProcessGroup, instance=True)
    transport = kwargs.pop("checkpoint_transport", None)
    if transport is None:
        transport = create_autospec(CheckpointTransport, instance=True)
        transport.metadata.return_value = "http://fake:0"
    with patch("torchft_tpu.manager.ManagerClient", autospec=True) as client_cls:
        manager = Manager(
            pg=pg,
            min_replica_size=min_replica_size,
            store=_FakeStore(),
            store_addr="store:0",
            use_async_quorum=use_async_quorum,
            group_rank=1,  # avoid spawning a native ManagerServer
            group_world_size=2,
            world_size_mode=world_size_mode,
            checkpoint_transport=transport,
            max_retries=max_retries,
            timeout=5.0,
            quorum_timeout=5.0,
            **kwargs,
        )
    manager.register_state_dict_fn(
        "model",
        load_state_dict=MagicMock(),
        state_dict=lambda: {"w": np.ones(2)},
    )
    return manager, manager._client, pg, transport


def test_quorum_configures_pg_and_tracks_participation() -> None:
    manager, client, pg, transport = make_manager()
    client._quorum.return_value = make_quorum(
        quorum_id=7, replica_rank=1, replica_world_size=3, max_rank=1, max_world_size=3
    )
    pg.errored.return_value = None

    manager.start_quorum()
    pg.configure.assert_called_once()
    store_addr, replica_id, rank, world = pg.configure.call_args[0]
    assert store_addr == "store:0/tpuft/7/1"
    assert rank == 1 and world == 3
    assert manager.num_participants() == 3
    assert manager.participating_rank() == 1
    assert manager.is_participating()

    # Same quorum id next step: no reconfigure.
    manager.start_quorum()
    assert pg.configure.call_count == 1


def test_allreduce_averages_by_participants() -> None:
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy())
    client._quorum.return_value = make_quorum(replica_world_size=2, max_world_size=2)
    client.should_commit.return_value = True
    manager.start_quorum()

    # Dummy PG echoes the input, so AVG == input / num_participants.
    out = manager.allreduce(np.array([4.0, 8.0])).wait()
    np.testing.assert_array_equal(out, np.array([2.0, 4.0]))

    tree = {"a": np.array([2.0]), "b": [np.array([6.0])]}
    out_tree = manager.allreduce_pytree(tree).wait()
    np.testing.assert_array_equal(out_tree["a"], np.array([1.0]))
    np.testing.assert_array_equal(out_tree["b"][0], np.array([3.0]))


def test_allreduce_after_error_is_noop() -> None:
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy())
    client._quorum.return_value = make_quorum()
    manager.start_quorum()
    manager.report_error(RuntimeError("boom"))
    work = manager.allreduce(np.array([1.0]))
    assert isinstance(work, _DummyWork)
    np.testing.assert_array_equal(work.wait(), np.array([1.0]))


def test_allreduce_error_reports_and_returns_default() -> None:
    pg = create_autospec(ProcessGroup, instance=True)
    pg.errored.return_value = None
    pg.allreduce.side_effect = RuntimeError("collective failed")
    manager, client, _, _ = make_manager(pg=pg)
    client._quorum.return_value = make_quorum()
    manager.start_quorum()
    work = manager.allreduce(np.array([1.0, 2.0]))
    np.testing.assert_array_equal(work.wait(), np.array([1.0, 2.0]))
    assert manager.errored() is not None


def test_healing_async_skips_participation_and_zeroes_grads() -> None:
    manager, client, pg, transport = make_manager(
        pg=ProcessGroupDummy(), use_async_quorum=True
    )
    client._quorum.return_value = make_quorum(
        quorum_id=2,
        replica_rank=1,
        replica_world_size=2,
        heal=True,
        max_step=5,
        max_rank=None,
        max_world_size=1,
        recover_src_manager_address="donor:1",
        recover_src_replica_rank=0,
    )
    client._checkpoint_metadata.return_value = "http://donor:0"
    client.should_commit.return_value = True
    transport.recv_checkpoint.return_value = {
        "user": {"model": {"w": np.full(2, 9.0)}},
        "tpuft": {"step": 5, "batches_committed": 10},
    }

    with patch("torchft_tpu.manager.ManagerClient", autospec=True) as primary_cls:
        primary_cls.return_value._checkpoint_metadata.return_value = "http://donor:0"
        manager.start_quorum()
        manager.wait_quorum()

    assert manager._healing
    assert not manager.is_participating()
    assert manager.num_participants() == 1
    # Healing replica contributes zeros.
    out = manager.allreduce(np.array([3.0, 3.0])).wait()
    np.testing.assert_array_equal(out, np.zeros(2))
    # Manager accounting restored from the donor.
    assert manager.current_step() == 5

    # should_commit applies the pending user state dict.
    load_fn = manager._load_state_dict_fns["model"]
    assert manager.should_commit()
    load_fn.assert_called_once()
    np.testing.assert_array_equal(load_fn.call_args[0][0]["w"], np.full(2, 9.0))
    assert manager.current_step() == 6


def test_healing_sync_applies_before_return() -> None:
    manager, client, pg, transport = make_manager(
        pg=ProcessGroupDummy(), use_async_quorum=False
    )
    client._quorum.return_value = make_quorum(
        quorum_id=3,
        replica_rank=1,
        replica_world_size=2,
        heal=True,
        max_step=2,
        recover_src_manager_address="donor:1",
        recover_src_replica_rank=0,
    )
    transport.recv_checkpoint.return_value = {
        "user": {"model": {"w": np.zeros(2)}},
        "tpuft": {"step": 2, "batches_committed": 4},
    }
    with patch("torchft_tpu.manager.ManagerClient", autospec=True):
        manager.start_quorum()
    # Sync mode: state applied eagerly, replica participates this step.
    assert not manager._healing
    load_fn = manager._load_state_dict_fns["model"]
    load_fn.assert_called_once()
    assert manager.is_participating()


def test_donor_sends_checkpoint() -> None:
    manager, client, pg, transport = make_manager(pg=ProcessGroupDummy())
    client._quorum.return_value = make_quorum(recover_dst_replica_ranks=[1])
    manager.start_quorum()
    manager.wait_quorum()
    transport.send_checkpoint.assert_called_once()
    kwargs = transport.send_checkpoint.call_args[1]
    assert kwargs["dst_ranks"] == [1]
    assert "user" in kwargs["state_dict"] and "tpuft" in kwargs["state_dict"]


def test_should_commit_false_without_enough_replicas() -> None:
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy(), min_replica_size=2)
    client._quorum.return_value = make_quorum(
        replica_world_size=1, max_world_size=1, replica_rank=0, max_rank=0
    )
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote
    manager.start_quorum()
    assert not manager.should_commit()
    assert manager.current_step() == 0


def test_pg_errored_blocks_commit() -> None:
    pg = ProcessGroupDummy()
    pg._errored = RuntimeError("pg broke")
    manager, client, _, _ = make_manager(pg=pg)
    client._quorum.return_value = make_quorum()
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote
    manager.start_quorum()
    assert not manager.should_commit()
    assert manager.errored() is not None


def test_commit_success_advances_step_and_batches() -> None:
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy())
    client._quorum.return_value = make_quorum(replica_world_size=2, max_world_size=2)
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote
    manager.start_quorum()
    assert manager.should_commit()
    assert manager.current_step() == 1
    assert manager.batches_committed() == 2


def test_max_retries_raises_after_consecutive_failures() -> None:
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy(), max_retries=1)
    client._quorum.return_value = make_quorum()
    client.should_commit.return_value = False
    manager.start_quorum()
    assert not manager.should_commit()  # failure 1
    manager.start_quorum()
    with pytest.raises(RuntimeError, match="max_retries"):
        manager.should_commit()  # failure 2 > max_retries=1


def test_fixed_with_spares_zeroes_spare() -> None:
    manager, client, _, _ = make_manager(
        pg=ProcessGroupDummy(),
        min_replica_size=2,
        world_size_mode=WorldSizeMode.FIXED_WITH_SPARES,
    )
    # This replica is rank 2 of 3 with min size 2: it is a spare.
    client._quorum.return_value = make_quorum(
        replica_rank=2, replica_world_size=3, max_rank=2, max_world_size=3
    )
    manager.start_quorum()
    assert manager.num_participants() == 2
    assert manager.participating_rank() is None
    assert not manager.is_participating()
    out = manager.allreduce(np.array([5.0, 5.0])).wait()
    # Spare contributes zeros (dummy echoes), averaged by 2.
    np.testing.assert_array_equal(out, np.zeros(2))


def test_wrap_work_swallows_error_into_default() -> None:
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy())
    client._quorum.return_value = make_quorum()
    manager.start_quorum()
    from concurrent.futures import Future

    from torchft_tpu.work import Work

    fut: Future = Future()
    wrapped = manager.wrap_work(Work(fut), default="fallback")
    fut.set_exception(RuntimeError("inner"))
    assert wrapped.wait(5) == "fallback"
    assert isinstance(manager.errored(), ExceptionWithTraceback)


def test_wrap_work_timeout() -> None:
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy())
    client._quorum.return_value = make_quorum()
    manager.start_quorum()
    from concurrent.futures import Future

    from torchft_tpu.work import Work

    fut: Future = Future()  # never resolves
    wrapped = manager.wrap_work(Work(fut), default="timed-out", timeout=0.1)
    assert wrapped.wait(5) == "timed-out"
    assert manager.errored() is not None


def test_state_dict_roundtrip() -> None:
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy())
    sd = manager.state_dict()
    assert sd == {"step": 0, "batches_committed": 0}
    manager.load_state_dict({"step": 42, "batches_committed": 84})
    assert manager.current_step() == 42
    assert manager.batches_committed() == 84


def test_quorum_happy_timeouts() -> None:
    """Per-call timeouts thread through to the coordination RPCs (parity:
    manager_test.py:625-652): an explicit start_quorum timeout reaches
    the quorum RPC, the ctor timeout is the should_commit default, and an
    explicit should_commit timeout overrides it."""
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy(), min_replica_size=1)
    client._quorum.return_value = make_quorum()
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote

    manager.start_quorum(timeout=12.5)
    assert client._quorum.call_args.kwargs["timeout"] == 12.5
    manager.start_quorum()  # falls back to the ctor quorum_timeout
    assert client._quorum.call_args.kwargs["timeout"] == 5.0

    manager.should_commit()
    assert client.should_commit.call_args.kwargs["timeout"] == 5.0
    manager.should_commit(timeout=3.25)
    assert client.should_commit.call_args.kwargs["timeout"] == 3.25


def test_quorum_skip_init() -> None:
    """init_sync=False threads through the quorum request (parity:
    manager_test.py:653-681 — the server-side plan then skips the step-0
    parameter mosaic)."""
    manager, client, _, _ = make_manager(
        pg=ProcessGroupDummy(), min_replica_size=1, init_sync=False
    )
    client._quorum.return_value = make_quorum()
    manager.start_quorum()
    assert client._quorum.call_args.kwargs["init_sync"] is False

    default_manager, default_client, _, _ = make_manager(
        pg=ProcessGroupDummy(), min_replica_size=1
    )
    default_client._quorum.return_value = make_quorum()
    default_manager.start_quorum()
    assert default_client._quorum.call_args.kwargs["init_sync"] is True


def test_quorum_checkpoint_errors() -> None:
    """A failing checkpoint fetch during healing funnels into report_error
    and blocks the commit instead of raising through the train loop
    (parity: manager_test.py:682-724)."""
    manager, client, _, transport = make_manager(
        pg=ProcessGroupDummy(), min_replica_size=1
    )
    client._quorum.return_value = make_quorum(
        heal=True,
        max_step=3,
        recover_src_manager_address="fake:1",
        recover_src_replica_rank=1,
    )
    transport.recv_checkpoint.side_effect = RuntimeError("fetch failed")
    with patch(
        "torchft_tpu.manager.ManagerClient", autospec=True
    ):  # the recovery-source client constructed inside _async_quorum
        manager.start_quorum()
    assert manager.errored() is not None
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote
    assert manager.should_commit() is False


def test_quorum_configure_errors() -> None:
    """A failing pg.configure funnels into report_error, leaves quorum_id
    unchanged (so the next quorum retries the reconfigure), and blocks the
    commit (parity: manager_test.py:725-754)."""
    pg = create_autospec(ProcessGroup, instance=True)
    pg.configure.side_effect = RuntimeError("configure failed")
    pg.errored.return_value = None
    manager, client, _, _ = make_manager(pg=pg, min_replica_size=1)
    client._quorum.return_value = make_quorum(quorum_id=7)
    manager.start_quorum()
    assert manager.errored() is not None
    assert manager._quorum_id != 7  # retried on the next quorum round
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote
    assert manager.should_commit() is False


def test_should_commit_async_overlaps_and_heals() -> None:
    """should_commit_async runs the full barrier on the manager's executor:
    the returned future resolves to the commit verdict, a pending heal is
    applied during resolution (not before), and step accounting matches
    the synchronous path."""
    import time as _time

    manager, client, _, transport = make_manager(
        pg=ProcessGroupDummy(), min_replica_size=1
    )
    client._quorum.return_value = make_quorum()
    manager.start_quorum()

    release = threading.Event()

    def slow_commit(rank, step, vote, timeout):
        release.wait(timeout=10)
        return vote

    client.should_commit.side_effect = slow_commit
    future = manager.should_commit_async()
    # The caller thread is free while the RPC is parked on the executor.
    assert not future.done()
    release.set()
    assert future.result(timeout=10) is True
    assert manager.current_step() == 1

    # A heal staged before the barrier is applied during resolution.
    client._quorum.return_value = make_quorum(
        heal=True,
        max_step=5,
        recover_src_manager_address="fake:1",
        recover_src_replica_rank=1,
    )
    healed = {"user": {"model": {"w": np.full(2, 9.0)}}, "tpuft": {"step": 5, "batches_committed": 5}}
    transport.recv_checkpoint.return_value = healed
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote
    with patch("torchft_tpu.manager.ManagerClient", autospec=True):
        manager.start_quorum(allow_heal=True)
    # Sync-mode quorum applies the heal eagerly at start_quorum; the async
    # barrier must still see the healed step and advance it.
    load_fn = manager._load_state_dict_fns["model"]
    load_fn.assert_called_once()
    assert manager.current_step() == 5
    assert manager.should_commit_async().result(timeout=10) is True
    assert manager.current_step() == 6


def test_start_quorum_drains_unresolved_commit_future() -> None:
    """start_quorum must not wipe the per-step error/heal flags while a
    should_commit_async future is unresolved: it drains the future first so
    the queued barrier votes with THIS step's flags (the ordering contract
    documented on should_commit_async, now enforced rather than advisory)."""
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy(), min_replica_size=1)
    client._quorum.return_value = make_quorum()
    manager.start_quorum()

    manager.report_error(RuntimeError("step math failed"))
    votes = []
    client.should_commit.side_effect = (
        lambda rank, step, vote, timeout: votes.append(vote) or vote
    )

    # Park the single-worker executor so the async barrier stays QUEUED —
    # the dangerous window where a misordered start_quorum used to wipe the
    # flags before the barrier ever read them.
    gate = threading.Event()
    manager._executor.submit(gate.wait, 10)
    future = manager.should_commit_async()

    started = threading.Event()
    finished = threading.Event()

    def second_quorum() -> None:
        started.set()
        manager.start_quorum()
        finished.set()

    t = threading.Thread(target=second_quorum, daemon=True)
    t.start()
    assert started.wait(timeout=5)
    # start_quorum is blocked draining the unresolved commit; the error
    # flag must still be live for the barrier to see.
    assert not finished.wait(timeout=0.5)
    assert manager.errored() is not None
    gate.set()
    t.join(timeout=10)
    assert finished.is_set()
    assert future.done()
    assert future.result() is False  # voted with the real (errored) flags
    assert votes == [False]
    assert manager.current_step() == 0  # the failed commit did not advance


def test_tracked_commit_future_timeout_is_not_consumption() -> None:
    """A result() wait that times out observed nothing: the future must
    stay unconsumed so a later drain still delivers the barrier outcome —
    while a delivered outcome (value or the barrier's own exception) marks
    it consumed."""
    import concurrent.futures

    from torchft_tpu.manager import _TrackedCommitFuture

    pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    gate = threading.Event()
    try:
        f = _TrackedCommitFuture(pool.submit(gate.wait, 10))
        # py3.10: concurrent.futures.TimeoutError is not yet the builtin.
        with pytest.raises((TimeoutError, concurrent.futures.TimeoutError)):
            f.result(timeout=0.05)
        assert not f.consumed
        gate.set()
        assert f.result(timeout=10) is True
        assert f.consumed

        boom = _TrackedCommitFuture(pool.submit(lambda: 1 / 0))
        with pytest.raises(ZeroDivisionError):
            boom.result(timeout=10)
        assert boom.consumed

        via_exc = _TrackedCommitFuture(pool.submit(lambda: 1 / 0))
        assert isinstance(via_exc.exception(timeout=10), ZeroDivisionError)
        assert via_exc.consumed
    finally:
        gate.set()
        pool.shutdown(wait=False)


def test_start_quorum_propagates_unconsumed_barrier_exception_once() -> None:
    """A barrier exception the caller never observed must surface from the
    drain (else e.g. the max_retries supervisor-restart signal is silently
    dropped) — but one the caller already resolved and handled must NOT
    replay on a later, healthy start_quorum."""
    manager, client, _, _ = make_manager(
        pg=ProcessGroupDummy(), min_replica_size=1, max_retries=0
    )
    client._quorum.return_value = make_quorum()
    client.should_commit.side_effect = lambda rank, step, vote, timeout: False

    # Unconsumed errored future -> the drain raises it.
    manager.start_quorum()
    future = manager.should_commit_async()
    with pytest.raises(RuntimeError, match="max_retries"):
        manager.start_quorum()
    assert future.done()

    # Consumed errored future -> the next start_quorum must NOT replay it.
    manager.start_quorum()
    future = manager.should_commit_async()
    with pytest.raises(RuntimeError, match="max_retries"):
        future.result(timeout=10)
    manager.start_quorum()  # caller handled it; no stale re-raise
    assert manager.errored() is None


def test_commit_pipeline_depth_env_and_validation(monkeypatch) -> None:
    """Depth plumbing: any int >= 0 is a legal window depth (an N-step
    bounded envelope), "auto" selects the adaptive controller starting at
    depth 1, TPUFT_COMMIT_PIPELINE_DEPTH wins over the legacy
    TPUFT_COMMIT_PIPELINE, and junk raises."""
    manager, _, _, _ = make_manager(pg=ProcessGroupDummy())
    assert manager.commit_pipeline_depth == 0
    assert not manager.commit_pipeline_adaptive

    manager, _, _, _ = make_manager(pg=ProcessGroupDummy(), commit_pipeline_depth=1)
    assert manager.commit_pipeline_depth == 1

    manager, _, _, _ = make_manager(pg=ProcessGroupDummy(), commit_pipeline_depth=4)
    assert manager.commit_pipeline_depth == 4

    manager, _, _, _ = make_manager(
        pg=ProcessGroupDummy(), commit_pipeline_depth="auto"
    )
    assert manager.commit_pipeline_adaptive
    assert manager.commit_pipeline_depth == 1  # deepens as evidence arrives

    monkeypatch.setenv("TPUFT_COMMIT_PIPELINE", "1")
    manager, _, _, _ = make_manager(pg=ProcessGroupDummy())
    assert manager.commit_pipeline_depth == 1

    # The new var wins over the legacy one.
    monkeypatch.setenv("TPUFT_COMMIT_PIPELINE_DEPTH", "3")
    manager, _, _, _ = make_manager(pg=ProcessGroupDummy())
    assert manager.commit_pipeline_depth == 3
    monkeypatch.setenv("TPUFT_COMMIT_PIPELINE_DEPTH", "auto")
    manager, _, _, _ = make_manager(pg=ProcessGroupDummy())
    assert manager.commit_pipeline_adaptive
    monkeypatch.delenv("TPUFT_COMMIT_PIPELINE_DEPTH")
    monkeypatch.delenv("TPUFT_COMMIT_PIPELINE")

    with pytest.raises(ValueError, match="commit_pipeline_depth"):
        make_manager(pg=ProcessGroupDummy(), commit_pipeline_depth=-1)
    with pytest.raises(ValueError, match="commit_pipeline_depth"):
        make_manager(pg=ProcessGroupDummy(), commit_pipeline_depth="bogus")


def test_quorum_change_hook_runs_before_reconfigure() -> None:
    """The registered quorum-change hook fires on the quorum thread BEFORE
    pg.configure (the pipelined-commit drain point: no reconfigure — and
    no donor send — while an uncommitted step is in flight), and only when
    the quorum id actually changes. Hook errors funnel into report_error
    instead of aborting the reconfigure."""
    events = []
    pg = create_autospec(ProcessGroup, instance=True)
    pg.errored.return_value = None
    pg.configure.side_effect = lambda *a, **k: events.append("configure")
    manager, client, _, _ = make_manager(pg=pg, min_replica_size=1)
    manager.register_quorum_change_hook(lambda: events.append("drain"))
    client._quorum.return_value = make_quorum(quorum_id=3)

    manager.start_quorum()
    manager.wait_quorum()
    assert events == ["drain", "configure"]

    # Same quorum id: neither fires again.
    manager.start_quorum()
    manager.wait_quorum()
    assert events == ["drain", "configure"]

    # A failing hook reports the error (blocking the commit) but the
    # reconfigure still happens for the new era.
    manager.register_quorum_change_hook(
        lambda: (_ for _ in ()).throw(RuntimeError("drain failed"))
    )
    client._quorum.return_value = make_quorum(quorum_id=4)
    manager.start_quorum()
    manager.wait_quorum()
    assert events == ["drain", "configure", "drain", "configure"]
    assert manager.errored() is not None


def test_allreduce_prequantized_zeroes_spare_contribution() -> None:
    """FIXED_WITH_SPARES: a spare's prequantized payload must contribute
    nothing (scales zeroed) and errors must short-circuit to None."""
    import jax.numpy as jnp

    from torchft_tpu.ops import quantization as q

    manager, client, _, _ = make_manager(
        pg=ProcessGroupDummy(),
        min_replica_size=2,
        world_size_mode=WorldSizeMode.FIXED_WITH_SPARES,
    )
    client._quorum.return_value = make_quorum(
        replica_rank=2, replica_world_size=3, max_rank=2, max_world_size=3
    )
    manager.start_quorum()
    assert not manager.is_participating()

    payload, scales = q.quantize_blocks(np.linspace(-2, 2, 512, dtype=np.float32))
    result = manager.allreduce_prequantized(jnp.asarray(payload), jnp.asarray(scales)).wait()
    out_payload, out_scales = result
    # Spare contribution fully zeroed via scales.
    assert np.all(np.asarray(out_scales) == 0)

    # Errored manager: immediate None without touching the PG.
    manager.report_error(RuntimeError("boom"))
    assert manager.allreduce_prequantized(payload, scales).wait() is None


def test_allreduce_pytree_buckets_mixed_dtypes() -> None:
    """Bucketed pytree sync: multiple dtype buckets reconstruct to the right
    leaves (shapes, dtypes), results don't alias each other, integer leaves
    raise (averaging would silently floor-divide — same contract as the
    scalar allreduce AVG path), and the quantized path stays per-leaf so fp8
    block scales never span parameter boundaries."""
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy(), min_replica_size=1)
    client._quorum.return_value = make_quorum(replica_world_size=2, max_world_size=2)
    manager.start_quorum()

    tree = {
        "w": np.full(5, 4.0, np.float32),
        "b": [np.full(3, 8.0, np.float32)],
        "scalar": np.float64(6.0),
    }
    out = manager.allreduce_pytree(tree).wait()
    np.testing.assert_array_equal(out["w"], np.full(5, 2.0, np.float32))
    np.testing.assert_array_equal(out["b"][0], np.full(3, 4.0, np.float32))
    assert float(out["scalar"]) == 3.0
    assert out["w"].dtype == np.float32

    # Integer leaf: ValueError BEFORE any wire op, step not poisoned.
    with pytest.raises(ValueError, match="floating"):
        manager.allreduce_pytree({"n": np.array([10], np.int64)})
    assert not manager.errored()

    # The check fires before every early return: a LONE replica raises
    # too — otherwise an int leaf would "work" single-replica and start
    # raising only once a second replica joins.
    lone, lone_client, _, _ = make_manager(
        pg=ProcessGroupDummy(), min_replica_size=1
    )
    lone_client._quorum.return_value = make_quorum(
        replica_world_size=1, max_world_size=1
    )
    lone.start_quorum()
    assert lone.is_lone_replica()
    with pytest.raises(ValueError, match="floating"):
        lone.allreduce_pytree({"n": np.array([10], np.int64)})
    # No aliasing between same-bucket leaves.
    out["w"][:] = -1
    np.testing.assert_array_equal(out["b"][0], np.full(3, 4.0, np.float32))

    # Quantized path: per-leaf quantization — a tiny-magnitude leaf next to a
    # huge one must survive (shared-bucket fp8 scales would zero it).
    tree2 = {"big": np.full(512, 300.0, np.float32), "small": np.full(512, 1e-4, np.float32)}
    out2 = manager.allreduce_pytree(tree2, should_quantize=True).wait()
    assert np.all(np.abs(out2["small"]) > 0), "small leaf crushed by shared fp8 scale"
    np.testing.assert_allclose(out2["small"], np.full(512, 5e-5), rtol=0.1)
    np.testing.assert_allclose(out2["big"], np.full(512, 150.0), rtol=0.1)
