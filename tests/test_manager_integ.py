"""Manager integration tests: multi-replica-group training with injected
faults, asserting the master invariant — bitwise state equality across
replica groups after recovery (parity: manager_integ_test.py:334-421)."""

import numpy as np
import jax
import pytest

from torchft_tpu.coordination import LighthouseServer

from ft_harness import (
    EventInjector,
    Runner,
    ddp_train_loop,
    pipelined_ddp_train_loop,
    run_replica_groups,
)


@pytest.fixture()
def lighthouse():
    # join_timeout must exceed worst-case step skew (GIL scheduling on the
    # 1-core CI box) so a slow-but-alive group is waited for instead of being
    # dropped — dropping it forks the gradient history, which is exactly what
    # the bitwise-equality invariant exists to catch. Dead replicas still
    # leave fast via the 1s heartbeat expiry.
    server = LighthouseServer(
        min_replicas=1,
        join_timeout_ms=10000,
        heartbeat_timeout_ms=1000,
        quorum_tick_ms=20,
    )
    yield server
    server.shutdown()


def assert_pytree_equal(a, b) -> None:
    leaves_a, tree_a = jax.tree_util.tree_flatten(a)
    leaves_b, tree_b = jax.tree_util.tree_flatten(b)
    assert tree_a == tree_b
    for la, lb in zip(leaves_a, leaves_b):
        if hasattr(la, "shape"):
            assert np.asarray(la).tobytes() == np.asarray(lb).tobytes(), "pytree leaves differ"
        else:
            assert la == lb


def assert_groups_converged(results, num_steps: int) -> None:
    """All replica groups reached num_steps with bitwise-identical params."""
    reference = results[0][0]["state_dict"]["params"]
    for group_result in results:
        rank_result = group_result[0]
        assert rank_result["manager_state"]["step"] == num_steps
        assert_pytree_equal(rank_result["state_dict"]["params"], reference)


@pytest.mark.parametrize("use_async_quorum", [True, False])
def test_ddp_two_groups_healthy(lighthouse, use_async_quorum) -> None:
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=3,
            use_async_quorum=use_async_quorum,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners)
    assert_groups_converged(results, 3)


def test_ddp_recovery_after_replica_kill(lighthouse) -> None:
    injector = EventInjector().fail_at(group=1, step=1)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=4,
            injector=injector,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=180)
    assert injector.count == 1
    assert_groups_converged(results, 4)
    # North star (BASELINE.md): a kill costs the survivor < 1 step — at most
    # the in-flight commit may fail when the peer vanishes mid-allreduce.
    assert results[0][0]["failed_commits"] <= 1, results[0][0]["failed_commits"]


def test_ddp_pipelined_two_groups_healthy(lighthouse) -> None:
    """Pipelined-commit FT-DDP across two replica groups: verdicts resolve
    one step late, batches ride the dispatch prediction, and the groups
    still end bitwise identical at exactly num_steps."""
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=pipelined_ddp_train_loop,
            num_steps=4,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=180)
    assert_groups_converged(results, 4)
    # A healthy run never rolls back.
    for group_result in results:
        assert group_result[0]["rollbacks"] == 0
        assert group_result[0]["failed_commits"] == 0


def test_ddp_pipelined_kill_rolls_back_uncommitted_step(lighthouse) -> None:
    """SIGKILL-equivalent (simulated process death, the harness's kill
    model) of one replica group while the survivor has a pipelined vote in
    flight: the survivor's in-flight step cannot commit once its peer
    vanishes mid-collective, so it must ROLL BACK the speculatively
    adopted update — and after the peer restarts and heals, both groups
    must be bitwise identical at the target step (the uncommitted
    speculation never leaked into committed history)."""
    injector = EventInjector().fail_at(group=1, step=2)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=pipelined_ddp_train_loop,
            num_steps=5,
            injector=injector,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=240)
    assert injector.count == 1
    assert_groups_converged(results, 5)
    survivor = results[0][0]
    # The survivor discovered the dead peer through a failed pipelined
    # commit and refused the speculative update (rollback >= 1); it lost
    # at most the in-flight step.
    assert survivor["rollbacks"] >= 1, survivor
    assert survivor["failed_commits"] >= 1, survivor
    assert survivor["failed_commits"] <= 2, survivor


def test_ddp_pipelined_depth2_two_groups_healthy(lighthouse) -> None:
    """Depth-2 speculative window across two replica groups: verdicts
    resolve TWO steps late, batches ride the dispatch prediction, and the
    groups still end bitwise identical at exactly num_steps."""
    import functools

    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=functools.partial(pipelined_ddp_train_loop, depth=2),
            num_steps=5,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=180)
    assert_groups_converged(results, 5)
    for group_result in results:
        assert group_result[0]["rollbacks"] == 0
        assert group_result[0]["failed_commits"] == 0


def test_ddp_pipelined_depth2_kill_drains_full_window(lighthouse) -> None:
    """Kill one replica group with the survivor holding a TWO-deep
    speculative window (votes in flight for both uncommitted steps): the
    refused commit must unwind the window — rollback + discard of the
    younger speculation — and the membership change must drain the FULL
    window before the PG reconfigures and the donor serves the rejoiner
    (the R7 invariant, exercised end to end). Both groups bitwise
    identical at the target step proves no speculative state leaked into
    committed history or the heal."""
    import functools

    injector = EventInjector().fail_at(group=1, step=2)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=functools.partial(pipelined_ddp_train_loop, depth=2),
            num_steps=6,
            injector=injector,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=240)
    assert injector.count == 1
    assert_groups_converged(results, 6)
    survivor = results[0][0]
    # The survivor discovered the dead peer through a failed pipelined
    # commit and unwound its window (>= 1 rollback); with two speculative
    # steps in flight it loses at most the whole window.
    assert survivor["rollbacks"] >= 1, survivor
    assert survivor["failed_commits"] >= 1, survivor
    assert survivor["failed_commits"] <= 3, survivor


def test_quorum_latency_north_star(lighthouse) -> None:
    """BASELINE.md north star: steady-state (fast-quorum) latency p50 stays
    within 2x the lighthouse tick. The first step is excluded — it includes
    the join/rendezvous round. Wall-clock on a 1-core GIL-scheduled box is
    noisy (CLAUDE.md), so a failing measurement is retried once before the
    assertion counts."""
    import statistics

    def measure() -> float:
        runners = [
            Runner(
                replica_group=i,
                lighthouse_addr=lighthouse.address(),
                train_loop=ddp_train_loop,
                num_steps=8,
                use_async_quorum=False,
            )
            for i in range(2)
        ]
        results = run_replica_groups(runners, timeout=180)
        assert_groups_converged(results, 8)
        steady = [t for group in results for t in group[0]["quorum_times"][1:]]
        return 1000 * statistics.median(steady)

    # Lighthouse tick is 100ms (native default, matching the reference's
    # quorum_tick_ms); fast quorum resolves without waiting a full tick.
    # Bounded retry: exactly one re-measure to damp transient 1-core machine
    # load, the first value is logged, and the SECOND measurement is
    # asserted strictly — a retry loop that hides a real regression is a
    # weaker invariant than the reference's hard bound
    # (manager_integ_test.py:539-551).
    p50_ms = measure()
    if p50_ms >= 200.0:
        print(f"first quorum p50 measurement {p50_ms:.1f}ms >= 200ms; re-measuring once")
        p50_ms = measure()
    assert p50_ms < 200.0, f"steady-state quorum p50 {p50_ms:.1f}ms >= 2x tick"


def test_ddp_recovery_after_allreduce_failure(lighthouse, tmp_path, monkeypatch) -> None:
    # Arm the flight recorder: the injected failure is guaranteed to reach
    # report_error, so exactly this test can assert the dump end to end.
    monkeypatch.setenv("TPUFT_FLIGHT_RECORDER", str(tmp_path / "fr"))
    injector = EventInjector().fail_allreduce_at(group=0, step=1)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=4,
            injector=injector,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=180)
    assert injector.count == 1
    assert_groups_converged(results, 4)

    import json

    dumps = list((tmp_path / "fr").glob("tpuft_fr_*.jsonl"))
    assert dumps, "injected allreduce failure produced no flight-recorder dump"
    entries = [json.loads(l) for l in dumps[0].read_text().splitlines()]
    assert "flight_recorder_dump_reason" in entries[0]
    assert any(e.get("source") == "manager" for e in entries[1:])


def test_ddp_three_groups_two_failures(lighthouse) -> None:
    injector = (
        EventInjector().fail_at(group=0, step=1).fail_allreduce_at(group=2, step=2)
    )
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=5,
            injector=injector,
        )
        for i in range(3)
    ]
    results = run_replica_groups(runners, timeout=240)
    assert injector.count == 2
    assert_groups_converged(results, 5)


def test_ddp_upscale_while_training(lighthouse) -> None:
    """A new replica group joins mid-run, heals from a donor, and converges
    (parity: local_sgd_integ_test upscale coverage). The joiner starts only
    once the running pair has visibly committed steps — sleep-based joining
    is flaky under jit-warmup variance."""
    import threading
    import time as _time

    from torchft_tpu.coordination import LighthouseClient

    num_steps = 60
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=num_steps,
        )
        for i in range(3)
    ]
    results: dict = {}

    def run(idx: int) -> None:
        results[idx] = runners[idx].run_replica()

    def run_late_joiner() -> None:
        client = LighthouseClient(lighthouse.address())
        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline:
            status = client.status()
            steps = [m.member.step for m in status.members if not m.joining]
            if steps and 2 <= max(steps) <= num_steps // 3:
                break
            _time.sleep(0.1)
        client.close()
        results[2] = runners[2].run_replica()

    threads = [
        threading.Thread(target=run, args=(0,)),
        threading.Thread(target=run, args=(1,)),
        threading.Thread(target=run_late_joiner),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    assert set(results) == {0, 1, 2}
    ordered = [results[i] for i in range(3)]
    # The joiner healed mid-run: it committed fewer batches than a
    # from-the-start member would have.
    assert results[2][0]["manager_state"]["batches_committed"] < num_steps * 3
    assert_groups_converged(ordered, num_steps)


def test_ddp_multi_rank_replica_groups(lighthouse) -> None:
    """2 replica groups x 2 local ranks: per-rank PGs spanning groups, the
    local-rank gather in the manager server, and the commit AND-barrier."""
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=3,
            world_size=2,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=240)
    # Every rank of every group reaches the step count; params equal across
    # groups (rank 0's view).
    for group_result in results:
        assert len(group_result) == 2
        for rank_result in group_result:
            assert rank_result["manager_state"]["step"] == 3
    assert_groups_converged(results, 3)


def test_quorum_and_commit_timeout_paths_are_fast(lighthouse) -> None:
    """Timeout paths return quickly (parity: manager_integ_test.py:539-551
    asserts <1s; allow CI slack)."""
    import time as _time

    from torchft_tpu.manager import Manager
    from torchft_tpu.parallel.process_group import ProcessGroupDummy
    from torchft_tpu.parallel.store import StoreClient, StoreServer

    store = StoreServer()
    manager = Manager(
        pg=ProcessGroupDummy(),
        min_replica_size=1,
        store=StoreClient(store.address()),
        store_addr=store.address(),
        group_rank=0,
        group_world_size=2,  # rank 1 never arrives -> gather can't complete
        lighthouse_addr=lighthouse.address(),
        replica_id="timeouts",
        heartbeat_interval=0.05,
        timeout=5.0,
    )
    try:
        start = _time.monotonic()
        manager.start_quorum(timeout=0.2)
        # The gather can never complete; the timeout must surface promptly
        # (reference semantics: the quorum error propagates to the train
        # loop, whose supervisor restarts it).
        with pytest.raises(Exception):
            manager.wait_quorum()
        elapsed = _time.monotonic() - start
        assert elapsed < 3.0
    finally:
        manager.shutdown(wait=False)
        store.shutdown()


def test_ddp_fp8_gradient_sync_two_groups(lighthouse, monkeypatch) -> None:
    """fp8 device-quantized DDP gradient sync: converges across groups within
    quantization tolerance and stays bitwise identical between replicas.
    The tiny bucket cap forces the quantized path through MULTIPLE pipelined
    wire messages (one per bucket), not one staged payload."""
    import threading

    monkeypatch.setenv("TPUFT_BUCKET_MB", "0.001")

    from torchft_tpu.ddp import ft_allreduce_gradients
    from torchft_tpu.manager import Manager
    from torchft_tpu.parallel.native_pg import ProcessGroupNative
    from torchft_tpu.parallel.store import StoreClient, StoreServer

    results = {}
    errors = {}

    def group(idx: int) -> None:
        store = StoreServer()
        pg = ProcessGroupNative(timeout=10.0)
        manager = Manager(
            pg=pg,
            min_replica_size=1,
            store=StoreClient(store.address()),
            store_addr=store.address(),
            group_rank=0,
            lighthouse_addr=lighthouse.address(),
            replica_id=f"fp8ddp_{idx}",
            heartbeat_interval=0.05,
            timeout=10.0,
            quorum_timeout=20.0,
            init_sync=False,
        )
        import jax.numpy as jnp

        try:
            grads = {"w": jnp.full((512,), float(idx + 1), jnp.float32),
                     "b": jnp.full((64,), -2.0 * (idx + 1), jnp.float32)}
            manager.start_quorum()
            avg = ft_allreduce_gradients(manager, grads, should_quantize=True)
            assert manager.should_commit()
            results[idx] = jax.tree_util.tree_map(np.asarray, avg)
        except BaseException as e:  # noqa: BLE001 — surfaced by the assert below
            errors[idx] = e
        finally:
            manager.shutdown(wait=False)
            pg.shutdown()
            store.shutdown()

    threads = [threading.Thread(target=group, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not any(t.is_alive() for t in threads), "replica group thread hung"
    assert not errors, f"replica group failed: {errors}"
    assert set(results) == {0, 1}
    # Average of 1s and 2s = 1.5; of -2s and -4s = -3 (fp8 exact for these).
    np.testing.assert_allclose(results[0]["w"], np.full(512, 1.5), rtol=0.05)
    np.testing.assert_allclose(results[0]["b"], np.full(64, -3.0), rtol=0.05)
    for key in results[0]:
        assert results[0][key].tobytes() == results[1][key].tobytes()


def _make_solo_manager(lighthouse, replica_id: str):
    """A world-size-1 Manager on a dummy PG with its own store (shared
    boilerplate for the coordination-focused integ tests)."""
    from torchft_tpu.manager import Manager
    from torchft_tpu.parallel.process_group import ProcessGroupDummy
    from torchft_tpu.parallel.store import StoreClient, StoreServer

    store = StoreServer()
    manager = Manager(
        pg=ProcessGroupDummy(),
        min_replica_size=1,
        store=StoreClient(store.address()),
        store_addr=store.address(),
        group_rank=0,
        lighthouse_addr=lighthouse.address(),
        replica_id=replica_id,
        heartbeat_interval=0.05,
        timeout=5.0,
        quorum_timeout=10.0,
        init_sync=False,
    )
    manager.register_state_dict_fn("s", lambda s: None, lambda: {"x": 1})
    return manager, store


def test_shrink_only_quorum_blocks_new_joiner(lighthouse) -> None:
    """shrink_only end to end: an established group requesting shrink-only
    quorums keeps a new joiner out until it stops shrinking (reference
    lighthouse.rs:195-200 behavior through the whole stack)."""
    import threading
    import time as _time

    from torchft_tpu.coordination import LighthouseClient

    mgr_a, store_a = _make_solo_manager(lighthouse, "shrink_0")
    mgr_b = store_b = None
    joiner_result = {}

    try:
        # Establish a prev quorum containing only A.
        mgr_a.start_quorum()
        mgr_a.wait_quorum()
        assert mgr_a.num_participants() == 1

        # B tries to join while A requests shrink-only quorums.
        mgr_b, store_b = _make_solo_manager(lighthouse, "shrink_1")

        def joiner() -> None:
            try:
                mgr_b.start_quorum()
                mgr_b.wait_quorum()
                joiner_result["participants"] = mgr_b.num_participants()
            except Exception as e:  # noqa: BLE001
                joiner_result["error"] = e

        t = threading.Thread(target=joiner)
        t.start()

        # Gate on OBSERVED state, not thread timing: wait until the
        # lighthouse reports B as a pending (joining) participant.
        client = LighthouseClient(lighthouse.address())
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            status = client.status()
            joining = [
                m.member.replica_id for m in status.members if m.joining
            ]
            if any(rid.startswith("shrink_1") for rid in joining):
                break
            _time.sleep(0.05)
        else:
            raise AssertionError("joiner never registered at the lighthouse")

        for _ in range(3):
            mgr_a.start_quorum(shrink_only=True)
            mgr_a.wait_quorum()
            # Shrink-only quorums never admit B.
            assert mgr_a.num_participants() == 1
            _time.sleep(0.1)

        # A relaxes: the next normal quorum admits B and unparks it.
        deadline = _time.monotonic() + 30
        while "participants" not in joiner_result and "error" not in joiner_result:
            mgr_a.start_quorum(shrink_only=False)
            mgr_a.wait_quorum()
            if _time.monotonic() > deadline:
                break
            _time.sleep(0.1)
        t.join(timeout=30)
        client.close()
        assert joiner_result.get("participants") == 2, joiner_result
    finally:
        if mgr_b is not None:
            mgr_b.shutdown(wait=False)
        if store_b is not None:
            store_b.shutdown()
        mgr_a.shutdown(wait=False)
        store_a.shutdown()


# ---------------------------------------------------------------------------
# Heal-path hardening drills (threads-as-replicas; see also the pure-Python
# transport-level versions in tests/test_heal_hardening.py, which carry the
# same properties in containers without the native toolchain).
# ---------------------------------------------------------------------------


def test_donor_dies_mid_heal_joiner_fails_over_and_resumes(lighthouse) -> None:
    """Kill one of three groups, then cut the donor's heal stream partway
    through (chunks 2+ of 4 die for longer than the joiner's fetch
    window — the SIGKILLed-donor shape as seen from the wire): the joiner
    must fail the attempt cleanly, re-enter quorum as joining, and
    complete the heal on a later assignment by re-fetching ONLY the
    missing chunks (the re-fetch counter pins that resume actually
    resumed). min_replica_size=3 freezes the survivors' commits while the
    joiner is out, so the heal target (step, digest) stays stable across
    attempts — the case resume exists for.

    Zero replica divergence is the master assertion, as always."""
    import threading
    import time as _time

    from ft_harness import ft_counter_delta, ft_counter_snapshot
    from torchft_tpu.checkpointing import HTTPTransport

    class DyingDonorHook:
        """Dies on chunks >= 2 for ``window`` seconds from the first death
        — longer than the joiner's 10 s fetch window, so heal attempt 1
        conclusively fails with chunks 0-1 verified and cached."""

        def __init__(self, window: float = 12.0) -> None:
            self.first_die = None
            self.window = window
            self.lock = threading.Lock()

        def __call__(self, step: int, index: int):
            if index < 2:
                return None
            with self.lock:
                now = _time.monotonic()
                if self.first_die is None:
                    self.first_die = now
                if now - self.first_die <= self.window:
                    return "die"
            return None

    hook = DyingDonorHook()

    def faulty_donor_transport(runner, rank):
        transport = HTTPTransport(num_chunks=4)
        if runner.replica_group != 2:  # healthy groups serve; 2 is killed
            transport._fault_hook = hook
        return transport

    injector = EventInjector().fail_at(group=2, step=1)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=4,
            injector=injector,
            train_loop_args={
                "min_replica_size": 3,
                "transport_factory": faulty_donor_transport,
            },
        )
        for i in range(3)
    ]
    before = ft_counter_snapshot()
    results = run_replica_groups(runners, timeout=240)
    delta = ft_counter_delta(before, ft_counter_snapshot())
    assert injector.count == 1
    assert_groups_converged(results, 4)
    assert hook.first_die is not None, "the donor fault never fired"
    # Resume exactness: chunks 0-1 were cached by the failed attempt, so
    # only the 2 missing chunks were ever re-transferred — dying-donor
    # connection cuts never reach the wire-transfer counter.
    assert delta["chunk_refetches"] == 2, delta
    assert delta["resumed_bytes"] > 0, delta
    # The data itself was never wrong.
    assert delta["checksum_failures"] == 0, delta


def test_corrupt_heal_stream_rejected_exactly_and_never_adopted(lighthouse) -> None:
    """Kill one of two groups and bit-flip the donor's first chunk-0 serve
    during the heal: the joiner must reject + re-fetch (checksum counter
    moves by EXACTLY the injected count) and both groups must end bitwise
    identical — corrupt state never enters committed history."""
    from ft_harness import ft_counter_delta, ft_counter_snapshot
    from torchft_tpu.checkpointing import HTTPTransport

    injected = []

    def corrupt_once(step: int, index: int):
        if index == 0 and not injected:
            injected.append(1)
            return "corrupt_stream"
        return None

    def faulty_donor_transport(runner, rank):
        transport = HTTPTransport(num_chunks=4)
        if runner.replica_group == 0:  # the survivor = the donor
            transport._fault_hook = corrupt_once
        return transport

    injector = EventInjector().fail_at(group=1, step=1)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=4,
            injector=injector,
            train_loop_args={"transport_factory": faulty_donor_transport},
        )
        for i in range(2)
    ]
    before = ft_counter_snapshot()
    results = run_replica_groups(runners, timeout=240)
    delta = ft_counter_delta(before, ft_counter_snapshot())
    assert injector.count == 1
    assert_groups_converged(results, 4)
    assert len(injected) == 1
    assert delta["checksum_failures"] == 1, delta  # exactly the injection


def test_drip_feeding_donor_fenced_by_watchdog(lighthouse, monkeypatch) -> None:
    """Kill one of two groups and make the donor's first heal serve drip
    below the progress floor: the joiner must fence it within the
    watchdog window (seconds) instead of stalling for the full fetch
    timeout, then complete the heal on a later clean serve. The drill's
    liveness bound IS the assertion: with a 10 s fetch timeout per chunk
    and a 240 s drill budget, an unfenced drip (256 B/s against ~16 KB of
    chunks = minutes per serve) would blow the budget."""
    from ft_harness import ft_counter_delta, ft_counter_snapshot
    from torchft_tpu.checkpointing import HTTPTransport
    from torchft_tpu.checkpointing import http_transport as ht

    monkeypatch.setenv(ht.ENV_HEAL_MIN_BPS, "100000")
    stalled = []

    def stall_once(step: int, index: int):
        if index == 0 and not stalled:
            stalled.append(1)
            return "stall_donor"
        return None

    def faulty_donor_transport(runner, rank):
        transport = HTTPTransport(num_chunks=4)
        if runner.replica_group == 0:
            transport._fault_hook = stall_once
        return transport

    injector = EventInjector().fail_at(group=1, step=1)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=4,
            injector=injector,
            train_loop_args={"transport_factory": faulty_donor_transport},
        )
        for i in range(2)
    ]
    before = ft_counter_snapshot()
    results = run_replica_groups(runners, timeout=240)
    delta = ft_counter_delta(before, ft_counter_snapshot())
    assert injector.count == 1
    assert_groups_converged(results, 4)
    assert len(stalled) == 1
    assert delta["stalled_fetches"] >= 1, delta
