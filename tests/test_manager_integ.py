"""Manager integration tests: multi-replica-group training with injected
faults, asserting the master invariant — bitwise state equality across
replica groups after recovery (parity: manager_integ_test.py:334-421)."""

import numpy as np
import jax
import pytest

from torchft_tpu.coordination import LighthouseServer

from ft_harness import (
    EventInjector,
    Runner,
    ddp_train_loop,
    run_replica_groups,
)


@pytest.fixture()
def lighthouse():
    # join_timeout must exceed worst-case step skew (GIL scheduling on the
    # 1-core CI box) so a slow-but-alive group is waited for instead of being
    # dropped — dropping it forks the gradient history, which is exactly what
    # the bitwise-equality invariant exists to catch. Dead replicas still
    # leave fast via the 1s heartbeat expiry.
    server = LighthouseServer(
        min_replicas=1,
        join_timeout_ms=10000,
        heartbeat_timeout_ms=1000,
        quorum_tick_ms=20,
    )
    yield server
    server.shutdown()


def assert_pytree_equal(a, b) -> None:
    leaves_a, tree_a = jax.tree_util.tree_flatten(a)
    leaves_b, tree_b = jax.tree_util.tree_flatten(b)
    assert tree_a == tree_b
    for la, lb in zip(leaves_a, leaves_b):
        if hasattr(la, "shape"):
            assert np.asarray(la).tobytes() == np.asarray(lb).tobytes(), "pytree leaves differ"
        else:
            assert la == lb


def assert_groups_converged(results, num_steps: int) -> None:
    """All replica groups reached num_steps with bitwise-identical params."""
    reference = results[0][0]["state_dict"]["params"]
    for group_result in results:
        rank_result = group_result[0]
        assert rank_result["manager_state"]["step"] == num_steps
        assert_pytree_equal(rank_result["state_dict"]["params"], reference)


@pytest.mark.parametrize("use_async_quorum", [True, False])
def test_ddp_two_groups_healthy(lighthouse, use_async_quorum) -> None:
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=3,
            use_async_quorum=use_async_quorum,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners)
    assert_groups_converged(results, 3)


def test_ddp_recovery_after_replica_kill(lighthouse) -> None:
    injector = EventInjector().fail_at(group=1, step=1)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=4,
            injector=injector,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=180)
    assert injector.count == 1
    assert_groups_converged(results, 4)


def test_ddp_recovery_after_allreduce_failure(lighthouse) -> None:
    injector = EventInjector().fail_allreduce_at(group=0, step=1)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=4,
            injector=injector,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=180)
    assert injector.count == 1
    assert_groups_converged(results, 4)


def test_ddp_three_groups_two_failures(lighthouse) -> None:
    injector = (
        EventInjector().fail_at(group=0, step=1).fail_allreduce_at(group=2, step=2)
    )
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=5,
            injector=injector,
        )
        for i in range(3)
    ]
    results = run_replica_groups(runners, timeout=240)
    assert injector.count == 2
    assert_groups_converged(results, 5)
