"""FTMesh / HSDP tests: sharded training inside each replica group (real
jax Mesh over virtual CPU devices) x fault-tolerant replica axis (manager).

Parity target: the reference's device_mesh_test.py + fsdp_test.py (FSDP2
fully_shard over ft_init_device_mesh).
"""

from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec

from test_manager import make_manager, make_quorum

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager
from torchft_tpu.optim import Optimizer
from torchft_tpu.parallel.mesh import FTMesh, ft_allreduce_sharded, ft_init_device_mesh
from torchft_tpu.parallel.process_group import ProcessGroupDummy, ProcessGroupTCP
from torchft_tpu.parallel.store import StoreClient, StoreServer


def scripted_manager(world: int = 2):
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy(), min_replica_size=1)
    client._quorum.return_value = make_quorum(
        replica_world_size=world, max_world_size=world
    )
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote
    manager.start_quorum()
    return manager


def test_ft_mesh_reports_dynamic_replica_axis() -> None:
    manager = scripted_manager(world=3)
    ft_mesh = ft_init_device_mesh(
        manager, mesh_shape=(2, 2), axis_names=("fsdp", "tp"), devices=jax.devices()[:4]
    )
    assert ft_mesh.axis_names == ("replica", "fsdp", "tp")
    assert ft_mesh.size("replica") == 3
    assert ft_mesh.size("fsdp") == 2
    assert ft_mesh.size() == 12
    assert "dynamic" in repr(ft_mesh)


def test_ft_mesh_rejects_replica_axis_in_mesh_or_spec() -> None:
    manager = scripted_manager()
    with pytest.raises(ValueError, match="virtual"):
        FTMesh(
            manager,
            jax.sharding.Mesh(np.array(jax.devices()[:2]).reshape(2), ("replica",)),
        )
    ft_mesh = ft_init_device_mesh(
        manager, mesh_shape=(2,), axis_names=("fsdp",), devices=jax.devices()[:2]
    )
    with pytest.raises(ValueError, match="replica axis"):
        ft_mesh.sharding("replica")


def test_ft_allreduce_sharded_preserves_sharding() -> None:
    manager = scripted_manager(world=2)
    ft_mesh = ft_init_device_mesh(
        manager, mesh_shape=(4,), axis_names=("fsdp",), devices=jax.devices()[:4]
    )
    sharding = ft_mesh.sharding("fsdp")
    x = jax.device_put(jnp.arange(16, dtype=jnp.float32).reshape(8, 2), sharding)
    grads = {"w": x}
    out = ft_allreduce_sharded(manager, grads)
    # Dummy PG echoes: average over 2 participants = x / 2.
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(x) / 2.0)
    assert out["w"].sharding == sharding
    assert [s.device for s in out["w"].addressable_shards] == [
        s.device for s in x.addressable_shards
    ]


def test_hsdp_two_groups_converge_bitwise() -> None:
    """2 replica groups (threads), each FSDP-sharding params over its own
    4-device sub-mesh; cross-group sync via ft_allreduce_sharded."""
    lighthouse = LighthouseServer(
        min_replicas=1, join_timeout_ms=10000, heartbeat_timeout_ms=1000
    )
    num_steps = 3

    def group_loop(group: int):
        devices = jax.devices()[group * 4 : (group + 1) * 4]
        store = StoreServer()
        client = StoreClient(store.address())
        pg = ProcessGroupTCP(timeout=10.0)
        manager = Manager(
            pg=pg,
            min_replica_size=1,
            store=client,
            store_addr=store.address(),
            group_rank=0,
            lighthouse_addr=lighthouse.address(),
            replica_id=f"hsdp_{group}",
            heartbeat_interval=0.05,
            timeout=10.0,
            quorum_timeout=20.0,
        )
        try:
            ft_mesh = ft_init_device_mesh(
                manager, mesh_shape=(4,), axis_names=("fsdp",), devices=devices
            )
            wsharding = ft_mesh.sharding("fsdp")
            params = {
                "w": jax.device_put(
                    jax.random.normal(jax.random.PRNGKey(0), (16, 8), jnp.float32),
                    wsharding,
                ),
                "b": jax.device_put(
                    jnp.zeros((8,), jnp.float32), ft_mesh.sharding()
                ),
            }
            opt = Optimizer(manager, optax.sgd(0.1), params)

            @jax.jit
            def loss_fn(p, x, y):
                return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

            grad_fn = jax.jit(jax.grad(loss_fn))
            while manager.current_step() < num_steps:
                step = manager.current_step()
                key = jax.random.PRNGKey(100 * group + step)
                kx, ky = jax.random.split(key)
                x = jax.random.normal(kx, (4, 16), jnp.float32)
                y = jax.random.normal(ky, (4, 8), jnp.float32)
                opt.begin_step()
                grads = grad_fn(opt.params, x, y)
                avg = ft_allreduce_sharded(manager, grads)
                # The averaged grads keep their FSDP sharding.
                assert avg["w"].sharding == wsharding
                opt.step(avg)
            return jax.tree_util.tree_map(np.asarray, opt.params)
        finally:
            manager.shutdown(wait=False)
            pg.shutdown()
            store.shutdown()

    try:
        with ThreadPoolExecutor(max_workers=2) as pool:
            results = list(pool.map(group_loop, range(2)))
        for key in results[0]:
            assert results[0][key].tobytes() == results[1][key].tobytes()
    finally:
        lighthouse.shutdown()
