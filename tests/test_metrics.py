"""Fleet metrics plane: registry semantics, exporters, the three export
surfaces (HTTP /metrics, snapshot/bench fields, store push), and the
observability satellites (flight-recorder trailer, doctor probe,
chrome-trace thread metadata, fleet table rendering).

The registry replaces what the reference delegates to an external OTel
collector (otel.py) — it must therefore be exactly right about the two
things collectors normally own: concurrent-writer atomicity and the
Prometheus exposition format.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import urllib.request

import pytest

from torchft_tpu import metrics
from torchft_tpu.metrics import (
    DEFAULT_TIME_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def test_counter_monotonic_and_rejects_negative() -> None:
    c = Counter()
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    assert c.value == 3.5


def test_gauge_set_and_inc() -> None:
    g = Gauge()
    g.set(7)
    g.inc(-2)
    assert g.value == 5.0


def test_histogram_bucket_edges_le_semantics() -> None:
    """Prometheus ``le`` semantics: a bucket counts observations <= its
    edge; cumulative across edges; +Inf counts everything."""
    h = Histogram(buckets=(0.1, 1.0, 10.0))
    for v in (0.1, 0.05, 1.0, 5.0, 100.0):
        h.observe(v)
    stats = h.stats()
    # 0.1 and 0.05 are <= 0.1; 1.0 lands exactly on the 1.0 edge; 5.0 in
    # the 10.0 bucket; 100.0 only in +Inf.
    assert stats["buckets"] == {"0.1": 2, "1": 3, "10": 4, "+Inf": 5}
    assert stats["count"] == 5
    assert stats["sum"] == pytest.approx(106.15)
    assert stats["mean"] == pytest.approx(106.15 / 5)


def test_histogram_requires_edges_and_sorts_them() -> None:
    with pytest.raises(ValueError):
        Histogram(buckets=())
    h = Histogram(buckets=(5.0, 1.0))
    assert h.edges == (1.0, 5.0)


def test_default_time_buckets_cover_phase_range() -> None:
    # Phases span acked-readiness probes (~100 us) to the 60 s RPC
    # timeout ceiling; both ends must land inside the edge range.
    assert DEFAULT_TIME_BUCKETS[0] <= 1e-4
    assert DEFAULT_TIME_BUCKETS[-1] >= 60.0
    assert list(DEFAULT_TIME_BUCKETS) == sorted(DEFAULT_TIME_BUCKETS)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_get_or_create_identity_and_kind_conflict() -> None:
    reg = Registry()
    a = reg.counter("x_total", replica_id="r0")
    b = reg.counter("x_total", replica_id="r0")
    c = reg.counter("x_total", replica_id="r1")
    assert a is b and a is not c
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total")


def test_registry_label_order_is_canonical() -> None:
    reg = Registry()
    a = reg.counter("y_total", a="1", b="2")
    b = reg.counter("y_total", b="2", a="1")
    assert a is b


def test_counter_total_partial_label_filter() -> None:
    reg = Registry()
    reg.counter("c_total", replica_id="r0", role="donor").inc(2)
    reg.counter("c_total", replica_id="r0", role="joiner").inc(3)
    reg.counter("c_total", replica_id="r1", role="donor").inc(10)
    assert reg.counter_total("c_total") == 15
    assert reg.counter_total("c_total", replica_id="r0") == 5
    assert reg.counter_total("c_total", role="donor") == 12
    assert reg.counter_total("c_total", replica_id="r0", role="donor") == 2
    assert reg.counter_total("missing_total") == 0


def test_histogram_stats_aggregates_label_sets() -> None:
    reg = Registry()
    reg.histogram("h_seconds", rank="0").observe(1.0)
    reg.histogram("h_seconds", rank="1").observe(3.0)
    agg = reg.histogram_stats("h_seconds")
    assert agg["count"] == 2 and agg["sum"] == 4.0 and agg["mean"] == 2.0
    assert reg.histogram_stats("h_seconds", rank="1")["mean"] == 3.0
    assert reg.histogram_stats("absent")["count"] == 0


def test_concurrent_increments_lose_no_updates() -> None:
    """The op-worker, quorum, and train-loop threads all write the same
    counters; under the GIL a bare += can still lose updates across the
    read-modify-write — the per-metric lock must not."""
    reg = Registry()
    n_threads, n_incs = 8, 2000
    barrier = threading.Barrier(n_threads)

    def worker(i: int) -> None:
        barrier.wait()
        for _ in range(n_incs):
            reg.counter("races_total").inc()
            reg.histogram("races_seconds").observe(0.001)
            reg.gauge("races_gauge", thread=str(i)).inc()

    threads = [
        threading.Thread(target=worker, args=(i,), name=f"opworker{i}")
        for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counter_total("races_total") == n_threads * n_incs
    assert reg.histogram_stats("races_seconds")["count"] == n_threads * n_incs


def test_registry_reset_drops_everything() -> None:
    reg = Registry()
    reg.counter("z_total").inc()
    reg.reset()
    assert reg.counter_total("z_total") == 0
    # A reset also releases the kind reservation.
    reg.gauge("z_total").set(1)


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


def test_prometheus_text_golden() -> None:
    reg = Registry()
    reg.counter("tpuft_commits_total", replica_id="r0", group_rank="0").inc(4)
    reg.gauge("tpuft_step").set(4)
    reg.histogram("tpuft_quorum_seconds", buckets=(0.5, 1.0)).observe(0.25)
    reg.histogram("tpuft_quorum_seconds", buckets=(0.5, 1.0)).observe(2.0)
    assert reg.prometheus_text() == (
        "# TYPE tpuft_commits_total counter\n"
        'tpuft_commits_total{group_rank="0",replica_id="r0"} 4\n'
        "# TYPE tpuft_quorum_seconds histogram\n"
        'tpuft_quorum_seconds_bucket{le="0.5"} 1\n'
        'tpuft_quorum_seconds_bucket{le="1"} 1\n'
        'tpuft_quorum_seconds_bucket{le="+Inf"} 2\n'
        "tpuft_quorum_seconds_sum 2.25\n"
        "tpuft_quorum_seconds_count 2\n"
        "# TYPE tpuft_step gauge\n"
        "tpuft_step 4\n"
    )


def test_prometheus_text_escapes_label_values() -> None:
    reg = Registry()
    reg.counter("esc_total", path='we"ird\\x\n').inc()
    text = reg.prometheus_text()
    assert 'path="we\\"ird\\\\x\\n"' in text


def test_snapshot_is_json_safe_and_structured() -> None:
    reg = Registry()
    reg.counter("a_total", k="v").inc(2)
    reg.gauge("b").set(1.5)
    reg.histogram("c_seconds").observe(0.01)
    snap = json.loads(json.dumps(reg.snapshot()))
    assert snap["counters"]["a_total"] == [{"labels": {"k": "v"}, "value": 2.0}]
    assert snap["gauges"]["b"][0]["value"] == 1.5
    assert snap["histograms"]["c_seconds"][0]["count"] == 1


def test_timer_records_elapsed_into_histogram() -> None:
    reg_before = metrics.histogram_stats("timer_test_seconds")["count"]
    with metrics.timer("timer_test_seconds", where="here"):
        pass
    stats = metrics.histogram_stats("timer_test_seconds")
    assert stats["count"] == reg_before + 1
    assert 0 <= stats["sum"] < 5.0


# ---------------------------------------------------------------------------
# HTTP surfaces
# ---------------------------------------------------------------------------


def _http_get(url: str):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type", ""), resp.read()


def test_standalone_metrics_http_server() -> None:
    reg = Registry()
    reg.counter("tpuft_commits_total", replica_id="srv").inc(3)
    server = metrics.start_http_server(0, registry=reg)
    try:
        status, ctype, body = _http_get(
            f"http://127.0.0.1:{server.port}/metrics"
        )
        assert status == 200 and ctype.startswith("text/plain")
        assert b'tpuft_commits_total{replica_id="srv"} 3' in body

        status, ctype, body = _http_get(
            f"http://127.0.0.1:{server.port}/metrics.json"
        )
        assert status == 200 and ctype == "application/json"
        payload = json.loads(body)
        assert payload["metrics"]["counters"]["tpuft_commits_total"]

        with pytest.raises(urllib.error.HTTPError) as err:
            _http_get(f"http://127.0.0.1:{server.port}/other")
        assert err.value.code == 404
    finally:
        server.shutdown()


def test_maybe_start_http_server_env_gated(monkeypatch) -> None:
    monkeypatch.setattr(metrics, "_HTTP_SERVER", None)
    monkeypatch.delenv(metrics.ENV_PORT, raising=False)
    assert metrics.maybe_start_http_server() is None

    monkeypatch.setenv(metrics.ENV_PORT, "not-a-port")
    assert metrics.maybe_start_http_server() is None  # logs, never raises

    monkeypatch.setenv(metrics.ENV_PORT, "0")
    server = metrics.maybe_start_http_server()
    try:
        assert server is not None
        # Idempotent: a second call reuses the process server.
        assert metrics.maybe_start_http_server() is server
    finally:
        if server is not None:
            server.shutdown()
        monkeypatch.setattr(metrics, "_HTTP_SERVER", None)


def test_checkpoint_transport_serves_metrics_route() -> None:
    """Every replica already listens on the checkpoint transport port for
    heals — the same port must answer scrapes, no extra server."""
    from torchft_tpu.checkpointing import HTTPTransport

    metrics.counter("tpuft_commits_total", replica_id="ckpt").inc()
    transport = HTTPTransport()
    try:
        port = transport._server.server_address[1]
        status, ctype, body = _http_get(f"http://127.0.0.1:{port}/metrics")
        assert status == 200 and ctype.startswith("text/plain")
        assert b"tpuft_commits_total" in body
        # Non-metrics routes still get the transport's own handling.
        with pytest.raises(urllib.error.HTTPError) as err:
            _http_get(f"http://127.0.0.1:{port}/bogus")
        assert err.value.code == 404
    finally:
        transport.shutdown()


def test_push_interval_env(monkeypatch) -> None:
    monkeypatch.delenv(metrics.ENV_PUSH_SEC, raising=False)
    assert metrics.push_interval_sec() == 10.0
    monkeypatch.setenv(metrics.ENV_PUSH_SEC, "2.5")
    assert metrics.push_interval_sec() == 2.5
    monkeypatch.setenv(metrics.ENV_PUSH_SEC, "junk")
    assert metrics.push_interval_sec() == 10.0  # malformed -> default


def test_manager_pushes_snapshot_into_group_store(monkeypatch) -> None:
    """The fleet-table feed: a commit publishes this process's snapshot
    under metrics/<full replica id>/<group_rank> — the key
    scripts/fleet_status.py derives from the lighthouse member list."""
    from test_manager import make_manager, make_quorum

    from torchft_tpu.parallel.process_group import ProcessGroupDummy

    monkeypatch.setenv(metrics.ENV_PUSH_SEC, "0.001")
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy())
    client._quorum.return_value = make_quorum(
        replica_world_size=2, max_world_size=2
    )
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote
    manager.start_quorum()
    assert manager.should_commit()
    key = f"metrics/{manager._replica_id}/{manager._group_rank}"
    raw = manager._store.data.get(key)
    assert raw is not None, sorted(manager._store.data)
    payload = json.loads(raw.decode())
    assert payload["step"] == 1
    assert payload["healing"] is False
    commits = payload["metrics"]["counters"]["tpuft_commits_total"]
    assert any(
        e["labels"]["replica_id"] == "test_replica" and e["value"] >= 1
        for e in commits
    )


def test_manager_push_disabled_and_failure_tolerant(monkeypatch) -> None:
    from test_manager import make_manager, make_quorum

    from torchft_tpu.parallel.process_group import ProcessGroupDummy

    # Disabled: no metrics/ key ever lands.
    monkeypatch.setenv(metrics.ENV_PUSH_SEC, "0")
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy())
    client._quorum.return_value = make_quorum(
        replica_world_size=2, max_world_size=2
    )
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote
    manager.start_quorum()
    assert manager.should_commit()
    assert not [k for k in manager._store.data if k.startswith("metrics/")]

    # A store that refuses writes must not poison the step.
    monkeypatch.setenv(metrics.ENV_PUSH_SEC, "0.001")
    manager, client, _, _ = make_manager(pg=ProcessGroupDummy())
    client._quorum.return_value = make_quorum(
        replica_world_size=2, max_world_size=2
    )
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote

    def broken_set(key, value, timeout=0):
        if key.startswith("metrics/"):
            raise ConnectionError("store down")
        manager._store.data[key] = value

    manager._store.set = broken_set
    manager.start_quorum()
    assert manager.should_commit()  # the push failure is swallowed
    assert manager.current_step() == 1


# ---------------------------------------------------------------------------
# satellites: flight recorder trailer, doctor probe, chrome-trace tids
# ---------------------------------------------------------------------------


def test_flight_recorder_dump_embeds_metrics_trailer(tmp_path) -> None:
    from torchft_tpu.utils import flight_recorder as fr

    metrics.counter("tpuft_commits_total", replica_id="frtest").inc(9)
    fr.record("test", "pre-abort")
    path = tmp_path / "fr.jsonl"
    fr.dump(str(path), reason="unit")
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    trailer = lines[-1]
    assert "metrics" in trailer and "ts" in trailer
    commits = trailer["metrics"]["counters"]["tpuft_commits_total"]
    assert any(
        e["labels"].get("replica_id") == "frtest" and e["value"] == 9.0
        for e in commits
    )
    # Event entries still precede the trailer.
    assert any(e.get("event") == "pre-abort" for e in lines[:-1])


def test_flight_recorder_malformed_size_env_imports_cleanly() -> None:
    """A typo'd TPUFT_FLIGHT_RECORDER_SIZE must not break package import
    (the recorder is imported from failure paths)."""
    env = dict(os.environ, TPUFT_FLIGHT_RECORDER_SIZE="not-a-number")
    out = subprocess.run(
        [
            sys.executable,
            "-c",
            "from torchft_tpu.utils import flight_recorder as fr; "
            "fr.record('t', 'ok'); print(fr._ring_size())",
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "2048"


def test_doctor_metrics_check(monkeypatch) -> None:
    from torchft_tpu import doctor

    # Feature off: PASS, never FAIL.
    monkeypatch.delenv(metrics.ENV_PORT, raising=False)
    status, detail = doctor._check_metrics()
    assert status == "PASS" and "off" in detail

    # Malformed port: WARN.
    monkeypatch.setenv(metrics.ENV_PORT, "eighty")
    status, _ = doctor._check_metrics()
    assert status == "WARN"

    # Configured but nothing listening: WARN, not FAIL.
    monkeypatch.setenv(metrics.ENV_PORT, "1")  # privileged: bind fails fast
    status, detail = doctor._check_metrics()
    assert status == "WARN" and "1" in detail

    # A live endpoint: PASS with a series count.
    server = metrics.start_http_server(0)
    try:
        monkeypatch.setenv(metrics.ENV_PORT, str(server.port))
        metrics.counter("tpuft_commits_total", replica_id="doctor").inc()
        status, detail = doctor._check_metrics()
        assert status == "PASS" and "serving" in detail
    finally:
        server.shutdown()


def test_chrome_trace_thread_names_and_span_args(tmp_path) -> None:
    """Chrome-trace events carry real tid metadata: one ``thread_name``
    "M" event per emitting thread, and span args (step/quorum_id) land in
    the event's args — without these the pipelined-commit spans (resolved
    on the quorum/op-worker threads) interleave unreadably."""
    from torchft_tpu.utils.profiling import chrome_trace, trace_span

    path = tmp_path / "trace.json"
    with chrome_trace(str(path)):
        with trace_span("tpuft::test::main", step=3, quorum_id=7):
            pass

        def other_thread() -> None:
            with trace_span("tpuft::test::worker", step=3):
                pass

        t = threading.Thread(target=other_thread, name="tpuft_quorum_0")
        t.start()
        t.join()

    events = json.loads(path.read_text())["traceEvents"]
    meta = [e for e in events if e["ph"] == "M" and e["name"] == "thread_name"]
    spans = [e for e in events if e["ph"] == "X"]
    assert len(meta) == 2  # one per emitting thread
    assert {m["args"]["name"] for m in meta} >= {"tpuft_quorum_0"}
    # tids are distinct and every span's tid has a name event.
    assert {s["tid"] for s in spans} == {m["tid"] for m in meta}
    main_span = next(s for s in spans if s["name"] == "tpuft::test::main")
    # Fleet-merge metadata (trace-plane satellite): every span also carries
    # the replica identity so a single-process capture drops cleanly into
    # a merged fleet trace.
    replica = main_span["args"].pop("replica_id")
    assert main_span["args"] == {"step": 3, "quorum_id": 7}
    worker_span = next(s for s in spans if s["name"] == "tpuft::test::worker")
    assert worker_span["args"].pop("replica_id") == replica
    assert worker_span["args"] == {"step": 3}
    payload = json.loads(path.read_text())
    assert payload["otherData"]["replica_id"] == replica
    assert "clock_offset_ms" in payload["otherData"]
    proc_meta = [
        e for e in events if e["ph"] == "M" and e["name"] == "process_name"
    ]
    assert len(proc_meta) == 1 and replica in proc_meta[0]["args"]["name"]


# ---------------------------------------------------------------------------
# fleet table (scripts/fleet_status.py — pure functions, no sockets)
# ---------------------------------------------------------------------------


def _load_fleet_status():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "fleet_status",
        Path(__file__).resolve().parent.parent / "scripts" / "fleet_status.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_status_render_and_extractors() -> None:
    fleet_status = _load_fleet_status()
    snap = {
        "ts": 100.0,
        "step": 12,
        "batches_committed": 24,
        "healing": False,
        "metrics": {
            "counters": {
                "tpuft_commits_total": [
                    {"labels": {"replica_id": "r0", "group_rank": "0"}, "value": 12.0}
                ]
            },
            "gauges": {
                "tpuft_last_commit_time": [{"labels": {}, "value": 99.0}],
                "tpuft_zero_num_shards": [{"labels": {}, "value": 8.0}],
                "tpuft_zero_owned_shards": [{"labels": {}, "value": 2.0}],
                "tpuft_heal_storm_joiners": [{"labels": {}, "value": 2.0}],
            },
            "histograms": {},
        },
    }
    assert fleet_status._counter_total(snap, "tpuft_commits_total") == 12.0
    assert fleet_status._counter_total(snap, "absent") is None
    assert fleet_status._gauge(snap, "tpuft_last_commit_time") == 99.0
    # ZeRO ownership column: "owned/num_shards"; None without the plane.
    assert fleet_status._shard_state(snap) == "2/8"
    assert fleet_status._shard_state({"metrics": {"gauges": {}}}) is None
    # Storm gauge feeding the JOINERS column.
    assert fleet_status._gauge(snap, "tpuft_heal_storm_joiners") == 2.0
    # Quantized-wire column: per-wire-class codec cells from the
    # tpuft_codec_wire gauges; None when every wire is fp32/absent.
    wire_snap = {
        "metrics": {
            "gauges": {
                "tpuft_codec_wire": [
                    {"labels": {"wire": "heal"}, "value": 2.0},   # int8
                    {"labels": {"wire": "zero"}, "value": 1.0},   # fp8
                    {"labels": {"wire": "serving"}, "value": 0.0},  # fp32
                ]
            }
        }
    }
    assert fleet_status._wire_state(wire_snap) == "heal:int8 zero:fp8"
    assert fleet_status._wire_state({"metrics": {"gauges": {}}}) is None
    # History rings feeding the HIST column: versions + bytes summed
    # across this process's rings (state + staged + relay).
    hist_snap = {
        "metrics": {
            "gauges": {
                "tpuft_history_versions": [
                    {"labels": {"ring": "state"}, "value": 3.0},
                    {"labels": {"ring": "staged"}, "value": 2.0},
                ],
                "tpuft_history_bytes": [
                    {"labels": {"ring": "state"}, "value": 8_000_000.0},
                    {"labels": {"ring": "staged"}, "value": 4_500_000.0},
                ],
            }
        }
    }
    assert fleet_status._history_state(hist_snap) == "5v/12.5MB"
    assert fleet_status._history_state({"metrics": {"gauges": {}}}) is None
    # HEALTH column: verdict state + ejection count + advisory accusation
    # from the tpuft_health_* gauges; None without the health plane.
    health_snap = {
        "metrics": {
            "gauges": {
                "tpuft_health_state": [{"labels": {}, "value": 2.0}],
                "tpuft_health_accuse": [
                    {"labels": {"accused": "train_9"}, "value": 0.0},
                    {"labels": {"accused": "train_7"}, "value": 1.0},
                ],
            },
            "counters": {
                "tpuft_health_ejections_total": [{"labels": {}, "value": 2.0}]
            },
        }
    }
    assert fleet_status._health_state(health_snap) == "degraded/e2>train_7"
    assert (
        fleet_status._health_state(
            {"metrics": {"gauges": {"tpuft_health_state": [{"labels": {}, "value": 0.0}]}}}
        )
        == "ok"
    )
    assert fleet_status._health_state({"metrics": {"gauges": {}}}) is None

    table = {
        "ts": 100.0,
        "lighthouse": "lh:1234",
        "quorum_id": 3,
        "has_quorum": True,
        "rows": [
            {
                "replica_id": "train_0:uuid",
                "rank": 0,
                "step": 12,
                "steps_per_sec": 1.25,
                "commits": 12.0,
                "commit_failures": 0.0,
                "heals": 1.0,
                "last_commit_age_s": 1.0,
                "healing": False,
                "heartbeat_age_ms": 52.1,
                "push_age_s": 0.4,
            },
            {"replica_id": "train_1:uuid", "rank": 0},  # store unreachable
        ],
    }
    text = fleet_status.render(table)
    lines = text.splitlines()
    assert "quorum_id=3" in lines[0] and "replicas=2" in lines[0]
    assert lines[1].split() == [
        "REPLICA", "RANK", "REGION", "STEP", "STEP/S", "COMMITS", "FAILED", "HEALS",
        "SERVE", "HEALTH", "GOODPUT", "SHARD", "WIRE", "PUBLISH", "ROLLOUT",
        "HIST", "RELAY",
        "LAG", "LAST", "COMMIT", "HEALING", "JOINERS", "HB", "AGE", "MS",
        "PUSH", "AGE",
    ]
    assert "train_0:uuid" in text and "1.25" in text and "1.0s" in text
    # The dead replica renders dashes, not a crash.
    dead_row = next(l for l in lines if l.startswith("train_1"))
    assert "-" in dead_row
