"""Metrics-plane integration: the FT phase counters must agree exactly
with the accounting the train loops observe, across a real (threads-as-
replicas) kill/heal drill, with the commit pipeline both on and off.

The counters are the operator's only view of a fleet (fleet_status.py,
/metrics scrapes): a commit counter that drifts from the committed-step
truth, or a heal counter that misses a recovery, makes every dashboard
built on them lie. These tests pin the agreement under the exact fault
the plane exists to observe.
"""

import pytest

from torchft_tpu.coordination import LighthouseServer

from ft_harness import (
    EventInjector,
    Runner,
    ddp_train_loop,
    ft_counter_delta,
    ft_counter_snapshot,
    pipelined_ddp_train_loop,
    run_replica_groups,
)


@pytest.fixture()
def lighthouse():
    # Same sizing rationale as test_manager_integ.py: join timeout above
    # worst-case GIL step skew, fast heartbeat expiry for dead replicas.
    server = LighthouseServer(
        min_replicas=1,
        join_timeout_ms=10000,
        heartbeat_timeout_ms=1000,
        quorum_tick_ms=20,
    )
    yield server
    server.shutdown()


def test_counters_exact_after_kill_heal_strict_ordering(
    lighthouse, monkeypatch
) -> None:
    """Strict (non-pipelined) ordering: kill group 1 at step 1, heal, run
    to step 4. Commits, commit failures, and heal roles must match the
    loop's own accounting exactly."""
    monkeypatch.setenv("TPUFT_STRICT_COMMIT", "1")
    num_steps = 4
    before = {g: ft_counter_snapshot(f"ddp_{g}") for g in range(2)}
    injector = EventInjector().fail_at(group=1, step=1)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=num_steps,
            injector=injector,
            # No step-0 init-sync mosaic: the only heal the counters see
            # is the one the kill causes, so the counts below are exact.
            train_loop_args={"init_sync": False},
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=180)
    assert injector.count == 1
    deltas = {
        g: ft_counter_delta(before[g], ft_counter_snapshot(f"ddp_{g}"))
        for g in range(2)
    }

    survivor, survivor_metrics = results[0][0], deltas[0]
    # The survivor never heals and commits every step it advances: its
    # step counter went 0 -> num_steps, one commit per increment.
    assert survivor["manager_state"]["step"] == num_steps
    assert survivor_metrics["commits"] == num_steps
    assert survivor_metrics["commit_failures"] == survivor["failed_commits"]
    assert survivor_metrics["heals_donor"] == 1  # one restart, one donation
    assert survivor_metrics["heals_joiner"] == 0
    assert survivor_metrics["rollbacks"] == 0  # pipeline off
    assert survivor_metrics["phantom_commits"] == 0

    killed_metrics = deltas[1]
    # The killed group healed exactly once (one injected death, one
    # restart). Its commits accumulate across both attempts: the steps it
    # committed before dying plus the post-heal steps — the heal adopts
    # the donor's step without committing, so the total can never exceed
    # num_steps, and the post-heal stretch guarantees at least one.
    assert killed_metrics["heals_joiner"] == 1
    assert killed_metrics["heals_donor"] == 0
    assert 1 <= killed_metrics["commits"] <= num_steps
    assert killed_metrics["rollbacks"] == 0
    assert killed_metrics["phantom_commits"] == 0


def test_counters_exact_after_kill_heal_pipelined(lighthouse) -> None:
    """Pipelined ordering (commit depth 1): the kill lands with the
    survivor's speculative vote in flight, so the survivor's rollback
    counter must match its reported rollback count exactly — plus the
    same commit/heal agreement as the strict drill."""
    num_steps = 5
    before = {g: ft_counter_snapshot(f"ddp_{g}") for g in range(2)}
    injector = EventInjector().fail_at(group=1, step=2)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=pipelined_ddp_train_loop,
            num_steps=num_steps,
            injector=injector,
            manager_args={"init_sync": False},
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=240)
    assert injector.count == 1
    deltas = {
        g: ft_counter_delta(before[g], ft_counter_snapshot(f"ddp_{g}"))
        for g in range(2)
    }

    survivor, survivor_metrics = results[0][0], deltas[0]
    assert survivor["manager_state"]["step"] == num_steps
    assert survivor_metrics["commits"] == num_steps
    assert survivor_metrics["commit_failures"] == survivor["failed_commits"]
    # The survivor discovered the death through a failed pipelined vote
    # and rolled back its speculative update; its counter and its own
    # accounting must agree exactly.
    assert survivor_metrics["rollbacks"] == survivor["rollbacks"]
    assert survivor["rollbacks"] >= 1
    assert survivor_metrics["heals_donor"] == 1
    assert survivor_metrics["heals_joiner"] == 0
    assert survivor_metrics["phantom_commits"] == 0

    killed, killed_metrics = results[1][0], deltas[1]
    assert killed_metrics["heals_joiner"] == 1
    assert killed_metrics["heals_donor"] == 0
    assert 1 <= killed_metrics["commits"] <= num_steps
    # The final attempt's rollbacks are reported; the dying attempt may
    # have added more (its drained pipeline), never fewer.
    assert killed_metrics["rollbacks"] >= killed["rollbacks"]
    assert killed_metrics["phantom_commits"] == 0


def test_counters_quiet_run_no_spurious_faults(lighthouse) -> None:
    """A healthy 2-group run contributes commits and nothing else — no
    heals, rollbacks, phantom commits, or errors (init-sync mosaic off).
    Guards against instrumentation on a hot path misfiring."""
    num_steps = 3
    before = {g: ft_counter_snapshot(f"ddp_{g}") for g in range(2)}
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=ddp_train_loop,
            num_steps=num_steps,
            train_loop_args={"init_sync": False},
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners)
    for g in range(2):
        delta = ft_counter_delta(before[g], ft_counter_snapshot(f"ddp_{g}"))
        assert delta["commits"] == num_steps
        assert delta["commit_failures"] == results[g][0]["failed_commits"]
        assert delta["heals_donor"] == 0 and delta["heals_joiner"] == 0
        assert delta["rollbacks"] == 0 and delta["phantom_commits"] == 0
        assert delta["errors"] == 0
