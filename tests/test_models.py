"""Model family + long-context tests: llama forward/grad, sharding plan on
the virtual 8-device mesh, ring attention vs dense reference."""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchft_tpu.models.llama import (
    CONFIGS,
    Llama,
    LlamaConfig,
    apply_sharding_plan,
    causal_attention,
    cross_entropy_loss,
    sharding_plan,
)
from torchft_tpu.ops.ring_attention import ring_attention_sharded


def test_llama_tiny_forward_and_grad() -> None:
    cfg = CONFIGS["tiny"]
    model = Llama(cfg)
    tokens = jnp.arange(32, dtype=jnp.int32).reshape(2, 16) % cfg.vocab_size
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = jax.jit(model.apply)(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32

    def loss(p):
        return cross_entropy_loss(model.apply(p, tokens), tokens)

    value, grads = jax.jit(jax.value_and_grad(loss))(params)
    assert np.isfinite(float(value))
    # Every param gets a finite gradient.
    for leaf in jax.tree_util.tree_leaves(grads):
        assert np.all(np.isfinite(np.asarray(leaf, dtype=np.float32)))


def test_llama_causal_masking() -> None:
    """Changing future tokens must not change past logits."""
    cfg = CONFIGS["tiny"]
    model = Llama(cfg)
    tokens = jnp.ones((1, 8), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits_a = model.apply(params, tokens)
    tokens_b = tokens.at[0, 6].set(3)
    logits_b = model.apply(params, tokens_b)
    np.testing.assert_allclose(
        np.asarray(logits_a[0, :6]), np.asarray(logits_b[0, :6]), rtol=1e-5
    )
    assert not np.allclose(np.asarray(logits_a[0, 6:]), np.asarray(logits_b[0, 6:]))


def test_gqa_grouping() -> None:
    b, s, h, kv, d = 2, 8, 4, 2, 16
    key = jax.random.PRNGKey(1)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, kv, d), jnp.float32)
    v = jax.random.normal(kv_, (b, s, kv, d), jnp.float32)
    out = causal_attention(q, k, v, d**-0.5)
    assert out.shape == (b, s, h, d)
    # Heads 0,1 share kv head 0: with identical q rows they'd match; with
    # distinct q they must differ from heads 2,3 (kv head 1).
    q_same = jnp.broadcast_to(q[:, :, :1], q.shape)
    out_same = causal_attention(q_same, k, v, d**-0.5)
    np.testing.assert_allclose(out_same[:, :, 0], out_same[:, :, 1], rtol=1e-5)
    assert not np.allclose(out_same[:, :, 0], out_same[:, :, 2])


def test_sharding_plan_applies_on_mesh() -> None:
    cfg = LlamaConfig(
        vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_hidden=128, max_seq_len=64, dtype=jnp.float32,
    )
    model = Llama(cfg)
    tokens = jnp.zeros((1, 16), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("fsdp", "tp"))
    sharded = apply_sharding_plan(params, mesh, sharding_plan())
    flat = jax.tree_util.tree_flatten_with_path(sharded)[0]
    specs = {
        "/".join(str(getattr(k, "key", k)) for k in path): leaf.sharding.spec
        for path, leaf in flat
    }
    # Column-parallel qkv kernels sharded (fsdp, tp, None).
    wq = next(spec for name, spec in specs.items() if "wq/kernel" in name)
    assert wq == P("fsdp", "tp", None)
    # Norm scales replicated.
    norm = next(spec for name, spec in specs.items() if "scale" in name)
    assert norm == P()
    # Forward still runs under jit with sharded params.
    with mesh:
        logits = jax.jit(model.apply)(sharded, tokens)
    assert logits.shape == (1, 16, cfg.vocab_size)


@pytest.mark.parametrize("sp_size", [2, 4])
def test_ring_attention_matches_dense(sp_size: int) -> None:
    b, s, h, kv, d = 2, 32, 4, 2, 16
    key = jax.random.PRNGKey(2)
    kq, kk, kvk = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, kv, d), jnp.float32)
    v = jax.random.normal(kvk, (b, s, kv, d), jnp.float32)

    dense = causal_attention(q, k, v, d**-0.5)

    mesh = Mesh(np.array(jax.devices()[:sp_size]), ("sp",))
    ring = ring_attention_sharded(q, k, v, mesh, axis_name="sp", scale=d**-0.5)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-4, atol=2e-5)


def test_llama_auto_ring_attention_under_sp_mesh() -> None:
    """With an sp axis in the mesh, the model's attention goes through the
    ring path and matches the dense single-device result."""
    cfg = LlamaConfig(
        vocab_size=128, dim=32, n_layers=1, n_heads=4, n_kv_heads=2,
        ffn_hidden=64, max_seq_len=64, dtype=jnp.float32,
    )
    model = Llama(cfg)
    tokens = (jnp.arange(32, dtype=jnp.int32) % cfg.vocab_size).reshape(1, 32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    dense_logits = model.apply(params, tokens)

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    from jax import shard_map

    def fwd(p, t, pos):
        return model.apply(p, t, pos)

    positions = jnp.broadcast_to(jnp.arange(32), (1, 32))
    sharded_fwd = shard_map(
        fwd,
        mesh=mesh,
        in_specs=(P(), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
            )
    with mesh:
        ring_logits = sharded_fwd(params, tokens, positions)
    np.testing.assert_allclose(
        np.asarray(ring_logits), np.asarray(dense_logits), rtol=2e-4, atol=2e-4
    )


def test_llama_ring_impl_without_bound_axis_fails_loudly() -> None:
    """attention_impl='ring' outside shard_map must raise (unbound axis
    name) at trace time — never silently compute per-shard local attention.
    A legacy ``with mesh:`` block does NOT bind the collective axis, so it
    must fail the same way; sp detection reads only public jax.sharding
    APIs (VERDICT r2 item 7)."""
    cfg = LlamaConfig(
        vocab_size=128, dim=32, n_layers=1, n_heads=4, n_kv_heads=2,
        ffn_hidden=64, max_seq_len=64, dtype=jnp.float32,
        attention_impl="ring",
    )
    model = Llama(cfg)
    tokens = (jnp.arange(32, dtype=jnp.int32) % cfg.vocab_size).reshape(1, 32)
    auto_model = Llama(LlamaConfig(
        vocab_size=128, dim=32, n_layers=1, n_heads=4, n_kv_heads=2,
        ffn_hidden=64, max_seq_len=64, dtype=jnp.float32,
    ))
    params = auto_model.init(jax.random.PRNGKey(0), tokens)
    with pytest.raises(NameError, match="axis name"):
        model.apply(params, tokens)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    with mesh, pytest.raises(NameError, match="axis name"):
        model.apply(params, tokens)
    # And auto under a bare legacy with-mesh picks a non-ring impl instead
    # of crashing: finishing without error is the assertion.
    with mesh:
        auto_model.apply(params, tokens)


def test_ring_attention_gradients_match_dense() -> None:
    """Training through ring attention: reverse-mode through the
    fori_loop + ppermute ring must match dense attention gradients.

    sp=2 (like the zigzag gradient test): the reverse-mode shard_map
    compile grows with ring hops and dominated suite time at sp=4; two
    hops already exercise every backward mechanism, and sp=4 forward
    coverage lives in test_ring_attention_matches_dense and the sp-mesh
    Llama tests."""
    b, s, h, kv, d = 2, 32, 4, 2, 16
    key = jax.random.PRNGKey(0)
    kq, kk, kvk = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, kv, d), jnp.float32)
    v = jax.random.normal(kvk, (b, s, kv, d), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, scale=d**-0.5) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v, d**-0.5) ** 2)

    grads_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    grads_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for ring_grad, dense_grad in zip(grads_ring, grads_dense):
        np.testing.assert_allclose(
            np.asarray(ring_grad), np.asarray(dense_grad), rtol=3e-4, atol=3e-5
        )


def test_ring_attention_fully_masked_rows_are_zero() -> None:
    """A query row positioned before every key (packed padding) must output
    exactly 0, not mean(V) — regardless of ring layout / causal skipping."""
    from jax import shard_map

    from torchft_tpu.ops.ring_attention import ring_attention

    b, s, h, kv, d = 1, 16, 2, 1, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, kv, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, kv, d), jnp.float32)
    qpos = jnp.broadcast_to(jnp.arange(s), (b, s)).at[0, 0].set(-100)
    kpos = jnp.broadcast_to(jnp.arange(s), (b, s))
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    spec = P(None, "sp", None, None)
    fn = shard_map(
        lambda q_, k_, v_, qp, kp: ring_attention(
            q_, k_, v_, "sp", scale=d**-0.5, q_positions=qp, k_positions=kp
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, P(None, "sp"), P(None, "sp")),
        out_specs=spec,
    )
    out = np.asarray(fn(q, k, v, qpos, kpos))
    assert np.all(out[0, 0] == 0.0)
    assert not np.all(out[0, 1] == 0.0)


def test_ring_attention_zigzag_matches_dense() -> None:
    """Load-balanced zigzag layout: natural-order inputs/outputs, balanced
    causal work per device, numerics identical to dense."""
    from torchft_tpu.ops.ring_attention import ring_attention_zigzag, zigzag_permutation

    b, s, h, kv, d = 2, 64, 4, 2, 16
    keys = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(keys[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(keys[1], (b, s, kv, d), jnp.float32)
    v = jax.random.normal(keys[2], (b, s, kv, d), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    zz = ring_attention_zigzag(q, k, v, mesh, scale=d**-0.5)
    dense = causal_attention(q, k, v, d**-0.5)
    np.testing.assert_allclose(np.asarray(zz), np.asarray(dense), rtol=3e-4, atol=3e-5)

    # Per-(q,kv) sub-chunk relevance counts are balanced across devices.
    sp = 4
    perm, inv = zigzag_permutation(s, sp)
    assert sorted(perm[inv].tolist()) == list(range(s))
    shard, half = s // sp, s // sp // 2
    counts = []
    for dev in range(sp):
        c = 0
        for qi in range(2):
            q_max = perm[dev * shard + qi * half : dev * shard + (qi + 1) * half].max()
            for src in range(sp):
                for ki in range(2):
                    lo = src * shard + ki * half
                    if perm[lo : lo + half].min() <= q_max:
                        c += 1
        counts.append(c)
    assert max(counts) - min(counts) <= 1, counts

    with pytest.raises(ValueError, match="divide"):
        zigzag_permutation(30, 4)


def test_ring_attention_zigzag_gradients_match_dense() -> None:
    """The balanced layout's backward pass (cond + sliced accumulators
    inside fori_loop) must match dense gradients.

    sp=2 deliberately: the reverse-mode shard_map program's compile time
    grows with ring hops and dominated the suite at sp=4 (~50s); two hops
    already exercise every backward mechanism (cond branches, sliced
    accumulators, the permuted layout), and the sp=4 forward is covered by
    test_ring_attention_zigzag_matches_dense."""
    from torchft_tpu.ops.ring_attention import ring_attention_zigzag

    b, s, h, kv, d = 2, 32, 4, 2, 16
    keys = jax.random.split(jax.random.PRNGKey(6), 3)
    q = jax.random.normal(keys[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(keys[1], (b, s, kv, d), jnp.float32)
    v = jax.random.normal(keys[2], (b, s, kv, d), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))

    def loss_zz(q, k, v):
        return jnp.sum(ring_attention_zigzag(q, k, v, mesh, scale=d**-0.5) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v, d**-0.5) ** 2)

    gz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gz, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=5e-4, atol=5e-5)


def test_blockwise_attention_matches_dense() -> None:
    """blockwise_attention (lax.scan over KV blocks, online softmax) is
    numerically equivalent to dense causal attention — forward and grad —
    including non-block-multiple sequence lengths and GQA."""
    from torchft_tpu.models.llama import causal_attention
    from torchft_tpu.ops.ring_attention import blockwise_attention

    # ONE case carrying every property at once (GQA h != kv AND a
    # non-block-multiple sequence): the second shape only re-compiled the
    # same fwd+vjp programs for ~7s of suite time with no new mechanism.
    for (b, s, h, kv, d, blk) in [(2, 100, 4, 2, 16, 32)]:
        kq, kk, kvk = jax.random.split(jax.random.PRNGKey(s), 3)
        q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
        k = jax.random.normal(kk, (b, s, kv, d), jnp.float32)
        v = jax.random.normal(kvk, (b, s, kv, d), jnp.float32)
        dense = causal_attention(q, k, v, d**-0.5)
        block = blockwise_attention(q, k, v, block_size=blk)
        np.testing.assert_allclose(
            np.asarray(block), np.asarray(dense), rtol=2e-5, atol=2e-5
        )
        # All three gradients (the custom_vjp backward recomputes blocks).
        weights = jnp.cos(jnp.arange(d))
        g_dense = jax.grad(
            lambda q, k, v: (causal_attention(q, k, v, d**-0.5) * weights).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_block = jax.grad(
            lambda q, k, v: (
                blockwise_attention(q, k, v, block_size=blk) * weights
            ).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for dense_grad, block_grad, name in zip(g_dense, g_block, "qkv"):
            np.testing.assert_allclose(
                np.asarray(block_grad),
                np.asarray(dense_grad),
                rtol=3e-4,
                atol=3e-5,
                err_msg=f"d{name}",
            )
        with pytest.raises(ValueError, match="attention_impl"):
            from torchft_tpu.models.llama import LlamaConfig

            LlamaConfig(attention_impl="flashiest")


def test_llama_blockwise_impl_matches_dense_model() -> None:
    """The model under attention_impl='blockwise' produces the same logits
    as 'dense' (same params), and 'auto' flips to blockwise past
    blockwise_min_seq."""
    from torchft_tpu.models.llama import Llama, LlamaConfig

    base = dict(
        vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_hidden=64, max_seq_len=96, dtype=jnp.float32,
        attention_block_size=32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 96), 0, 128)
    dense_model = Llama(LlamaConfig(**base, attention_impl="dense"))
    params = dense_model.init(jax.random.PRNGKey(1), tokens)
    dense_logits = dense_model.apply(params, tokens)
    block_model = Llama(LlamaConfig(**base, attention_impl="blockwise"))
    block_logits = block_model.apply(params, tokens)
    np.testing.assert_allclose(
        np.asarray(block_logits), np.asarray(dense_logits), rtol=3e-4, atol=3e-4
    )
    auto_model = Llama(
        LlamaConfig(**base, attention_impl="auto", blockwise_min_seq=64)
    )
    auto_logits = auto_model.apply(params, tokens)
    np.testing.assert_array_equal(
        np.asarray(auto_logits), np.asarray(block_logits)
    )


def test_llama_remat_matches_baseline() -> None:
    """remat='full'/'dots' change only the backward's memory/recompute
    schedule: same params, logits AND gradients must match the unremat
    model (allclose; fp32 tiny config)."""
    cfg = CONFIGS["tiny"]
    tokens = jnp.arange(32, dtype=jnp.int32).reshape(2, 16) % cfg.vocab_size
    base = Llama(cfg)
    params = base.init(jax.random.PRNGKey(0), tokens)

    def loss(model):
        return lambda p: cross_entropy_loss(model.apply(p, tokens), tokens)

    v0, g0 = jax.jit(jax.value_and_grad(loss(base)))(params)
    for mode in ("full", "dots"):
        model = Llama(replace(cfg, remat=mode))
        v1, g1 = jax.jit(jax.value_and_grad(loss(model)))(params)
        np.testing.assert_allclose(float(v1), float(v0), rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            g1, g0,
        )


def test_llama_scan_layers_matches_loop() -> None:
    """scan_layers=True is the same function: stacking the loop model's
    per-layer params into the scan layout reproduces its logits exactly,
    and gradients through the scanned stack are finite."""
    cfg = CONFIGS["tiny"]
    tokens = jnp.arange(32, dtype=jnp.int32).reshape(2, 16) % cfg.vocab_size
    loop_model = Llama(cfg)
    loop_params = loop_model.init(jax.random.PRNGKey(0), tokens)

    p = dict(loop_params["params"])
    layers = [p.pop(f"layer_{i}") for i in range(cfg.n_layers)]
    p["layers"] = {
        "block": jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    }
    scan_cfg = replace(cfg, scan_layers=True)
    scan_model = Llama(scan_cfg)
    scan_params = {"params": p}

    loop_logits = loop_model.apply(loop_params, tokens)
    scan_logits = scan_model.apply(scan_params, tokens)
    np.testing.assert_allclose(
        np.asarray(scan_logits), np.asarray(loop_logits), rtol=2e-5, atol=2e-5
    )

    # Fresh init has the scanned structure; remat composes under the scan.
    remat_cfg = replace(cfg, scan_layers=True, remat="dots")
    remat_model = Llama(remat_cfg)
    fresh = remat_model.init(jax.random.PRNGKey(1), tokens)
    wq = fresh["params"]["layers"]["block"]["attn"]["wq"]["kernel"]
    assert wq.shape[0] == cfg.n_layers

    def loss(p):
        return cross_entropy_loss(remat_model.apply(p, tokens), tokens)

    value, grads = jax.jit(jax.value_and_grad(loss))(fresh)
    assert np.isfinite(float(value))
    for leaf in jax.tree_util.tree_leaves(grads):
        assert np.all(np.isfinite(np.asarray(leaf, dtype=np.float32)))


def test_sharding_plan_applies_to_scanned_params() -> None:
    """The plan's per-layer specs shift right over the scanned stack's
    leading layer axis (replicated) and the forward still jits."""
    cfg = LlamaConfig(
        vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_hidden=128, max_seq_len=64, dtype=jnp.float32, scan_layers=True,
    )
    model = Llama(cfg)
    tokens = jnp.zeros((1, 16), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("fsdp", "tp"))
    sharded = apply_sharding_plan(params, mesh, sharding_plan())
    wq = sharded["params"]["layers"]["block"]["attn"]["wq"]["kernel"]
    assert wq.sharding.spec == P(None, "fsdp", "tp", None)
    scale = sharded["params"]["layers"]["block"]["attn_norm"]["scale"]
    assert scale.sharding.spec == P()
    with mesh:
        logits = jax.jit(model.apply)(sharded, tokens)
    assert logits.shape == (1, 16, cfg.vocab_size)


def test_all_fit_levers_compose_in_one_step() -> None:
    """scan_layers + dots-remat + fused CE + microbatch accumulation in a
    single jitted train step over the fsdp/tp mesh — the full 70B-class
    composition. Loss/grads stay finite and the update step runs; each
    lever alone is equivalence-tested elsewhere, this guards the
    cross-feature interactions (remat inside scan inside microbatch scan,
    custom-VJP CE under sharding)."""
    import optax

    from torchft_tpu.models.llama import apply_sharding_plan

    cfg = replace(
        CONFIGS["tiny"],
        scan_layers=True,
        remat="dots",
        loss_vocab_chunk=128,
    )
    model = Llama(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 17), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(1), tokens[:, :-1])
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("fsdp", "tp"))
    params = apply_sharding_plan(params, mesh, sharding_plan())

    def loss_fn(p, batch):
        return model.apply(p, batch[:, :-1], targets=batch[:, 1:])

    # The shipped fused step (Optimizer/LocalSGD's production path), not a
    # test-local variant.
    from torchft_tpu.optim import make_jit_fused_step

    tx = optax.adamw(1e-3)
    step = make_jit_fused_step(tx, loss_fn, num_microbatches=2)
    opt_state = tx.init(params)

    with mesh:
        loss, new_params, _ = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))
    for leaf in jax.tree_util.tree_leaves(new_params):
        assert np.all(np.isfinite(np.asarray(leaf, dtype=np.float32)))

    # Cross-check: the microbatched loss the step returned equals the
    # full-batch fused loss (equal chunks -> mean-of-means == mean).
    full_loss = model.apply(params, tokens[:, :-1], targets=tokens[:, 1:])
    np.testing.assert_allclose(float(loss), float(full_loss), rtol=1e-5)


def test_flash_shard_maps_itself_under_ambient_mesh(monkeypatch):
    """Under a bound mesh (jax.set_mesh — the sharded-train-step context)
    the flash dispatcher must shard_map the Pallas kernel over the
    batch/head axes itself: XLA SPMD refuses to partition Mosaic custom
    calls, so the bare kernel call fails to lower inside jit-with-mesh
    (test_mosaic_lowering.py's 8B gate pins the lowering half; this test
    pins numerics — the mapped kernel must match dense attention
    exactly where each (batch, head) shard computes independently)."""
    from torchft_tpu.models.llama import (
        _flash_under_ambient_mesh, causal_attention,
    )

    cfg = replace(
        CONFIGS["tiny"], attention_impl="flash",
        flash_batch_axes=("dp", "fsdp"), flash_tp_axis="tp",
    )
    b, s, h, kv, d = 4, 128, 4, 2, 64
    kq, kk, kvk = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, kv, d), jnp.float32)
    v = jax.random.normal(kvk, (b, s, kv, d), jnp.float32)

    mesh = jax.make_mesh((4, 2), ("fsdp", "tp"))
    with jax.set_mesh(mesh):
        out = jax.jit(
            lambda q, k, v: _flash_under_ambient_mesh(cfg, q, k, v, d**-0.5)
        )(q, k, v)
    ref = causal_attention(q, k, v, scale=d**-0.5)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )

    # Non-dividing dims must still compute correctly: the axes stay
    # manual (a bare pallas_call under the mesh is the lowering error
    # this wrapper avoids) but drop out of the specs, replicating the
    # kernel over them — 3 batch rows over fsdp=4 and 3 q-heads over
    # tp=2.
    q3 = jax.random.normal(kq, (3, s, 3, d), jnp.float32)
    k3 = jax.random.normal(kk, (3, s, 3, d), jnp.float32)
    with jax.set_mesh(mesh):
        out3 = jax.jit(
            lambda q, k, v: _flash_under_ambient_mesh(cfg, q, k, v, d**-0.5)
        )(q3, k3, k3)
    ref3 = causal_attention(q3, k3, k3, scale=d**-0.5)
    np.testing.assert_allclose(
        np.asarray(out3), np.asarray(ref3), rtol=2e-5, atol=2e-5
    )


def test_flash_mesh_fallback_keeps_largest_dividing_subset(caplog):
    """The non-dividing batch fallback is per-axis: batch 2 on a
    dp=2 x fsdp=2 mesh keeps dp sharded (product 4 does not divide, dp=2
    does) instead of replicating over both, and the drop to replication
    over fsdp logs a once-per-shape warning."""
    import logging as _logging

    from torchft_tpu.models import llama as llama_mod
    from torchft_tpu.models.llama import (
        _flash_under_ambient_mesh, causal_attention,
    )

    cfg = replace(
        CONFIGS["tiny"], attention_impl="flash",
        flash_batch_axes=("dp", "fsdp"), flash_tp_axis="tp",
    )
    s, h, kv, d = 128, 4, 2, 64
    kq, kk, kvk = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(kq, (2, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (2, s, kv, d), jnp.float32)
    v = jax.random.normal(kvk, (2, s, kv, d), jnp.float32)

    llama_mod._FLASH_REPLICATION_WARNED.clear()
    mesh = jax.make_mesh((2, 2, 2), ("dp", "fsdp", "tp"))
    with caplog.at_level(_logging.WARNING, logger="torchft_tpu.models.llama"):
        with jax.set_mesh(mesh):
            out = jax.jit(
                lambda q, k, v: _flash_under_ambient_mesh(cfg, q, k, v, d**-0.5)
            )(q, k, v)
            # Same shape again: the warning must not repeat.
            jax.jit(
                lambda q, k, v: _flash_under_ambient_mesh(cfg, q, k, v, d**-0.5)
            )(q, k, v)
    ref = causal_attention(q, k, v, scale=d**-0.5)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    warnings = [r for r in caplog.records if "replicates its compute" in r.message]
    assert len(warnings) == 1, [r.message for r in caplog.records]
    assert "fsdp=2" in warnings[0].message


def test_largest_dividing_subset_selection():
    """The pure fallback helper: keeps the max-shard-count dividing subset
    in spec order; all-or-nothing only when nothing divides."""
    from torchft_tpu.models.llama import _largest_dividing_subset

    sizes = {"dp": 2, "fsdp": 4}
    assert _largest_dividing_subset(("dp", "fsdp"), sizes, 8) == ("dp", "fsdp")
    assert _largest_dividing_subset(("dp", "fsdp"), sizes, 4) == ("fsdp",)
    assert _largest_dividing_subset(("dp", "fsdp"), sizes, 2) == ("dp",)
    assert _largest_dividing_subset(("dp", "fsdp"), sizes, 3) == ()
    # Ties prefer more axes (finer layout): 4 rows on 2x2 -> both axes.
    assert _largest_dividing_subset(
        ("dp", "fsdp"), {"dp": 2, "fsdp": 2}, 4
    ) == ("dp", "fsdp")
    # Order in the result is spec order regardless of subset enumeration.
    assert _largest_dividing_subset(
        ("a", "b", "c"), {"a": 3, "b": 2, "c": 2}, 12
    ) == ("a", "b", "c")


def test_flash_dispatcher_is_inert_inside_callers_shard_map():
    """Inside a caller's shard_map the fsdp/tp axes are Manual and shapes
    are already per-shard local: the dispatcher must use the plain kernel
    call (a nested map over local shapes would mis-divide them — caught
    by comparing AxisType.Manual, which its first version string-compared
    wrong)."""
    from torchft_tpu.models.llama import (
        _flash_under_ambient_mesh, causal_attention,
    )

    cfg = replace(CONFIGS["tiny"], attention_impl="flash")
    b, s, h, kv, d = 8, 128, 4, 2, 64
    kq, kk, kvk = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, kv, d), jnp.float32)
    v = jax.random.normal(kvk, (b, s, kv, d), jnp.float32)

    mesh = jax.make_mesh((4, 2), ("fsdp", "tp"))
    # kv heads shard over tp like q heads — splitting only q heads would
    # break the GLOBAL GQA pairing inside each shard (the dispatcher's
    # own mapped path uses the same paired layout for exactly this
    # reason).
    spec = P("fsdp", None, "tp", None)
    out = jax.jit(
        jax.shard_map(
            lambda q, k, v: _flash_under_ambient_mesh(cfg, q, k, v, d**-0.5),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
    )(q, k, v)
    # Each (batch, head) shard attends independently over the full local
    # sequence, so the mapped result equals unsharded dense attention.
    ref = causal_attention(q, k, v, scale=d**-0.5)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
