"""Cross-platform Mosaic lowering gates for every Pallas kernel.

Interpret mode skips Mosaic entirely, so a kernel whose block layout
violates TPU tiling (last two block dims must be multiple-of-8 /
multiple-of-128 or the whole array dim) passes every CPU test and then
fails its first real compile — exactly what happened to the round-1..4
flash kernels (heads squeezed into second-to-last block position; first
healthy relay probe rejected all three kernels, 2026-07-31).

jax's AOT path lowers for a TPU target WITHOUT a TPU attached
(``jit(f).trace(...).lower(lowering_platforms=("tpu",))`` — the
jax.export mechanism), and Pallas block-mapping validation runs during
that lowering. These tests pin the Mosaic-visible layout of each kernel
so the constraint class is caught in the default CPU suite, not on the
flaky relay. Execution semantics (numerics) stay covered by the
interpret-mode tests plus verify_on_chip(); this file only proves the
programs LOWER for real TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from torchft_tpu.ops import quantization
from torchft_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_partial,
    flash_attention_partial_bwd,
)


def _lower_tpu(fn, *args):
    """Lower ``fn`` for a TPU target on this CPU-only host; returns the
    Lowered object (raises ValueError on a Mosaic block-mapping error)."""
    return jax.jit(fn).trace(*args).lower(lowering_platforms=("tpu",))


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


# (b, s, h, kv_heads, d): verify_on_chip's GQA shape, kernel_bench's MHA
# shape, and a ragged sequence that exercises the padding path.
ATTN_SHAPES = [
    pytest.param(2, 256, 4, 2, 64, id="gqa-256x64"),
    pytest.param(4, 1024, 8, 8, 128, id="mha-1024x128"),
    pytest.param(1, 200, 4, 4, 64, id="ragged-200x64"),
]


@pytest.mark.parametrize("b,s,h,kv,d", ATTN_SHAPES)
def test_flash_forward_lowers_for_tpu(b, s, h, kv, d):
    q = _sds((b, s, h, d), jnp.bfloat16)
    k = _sds((b, s, kv, d), jnp.bfloat16)
    v = _sds((b, s, kv, d), jnp.bfloat16)
    _lower_tpu(lambda q, k, v: flash_attention(q, k, v, interpret=False), q, k, v)


@pytest.mark.parametrize("bq,bk", [(64, 64), (192, 192), (48, 512)])
def test_flash_forward_lowers_with_non128_blocks(bq, bk):
    # Public block sizes are rounded internally (block_q to the 16 sublane
    # tile, block_k to the 128 lane tile the kp row-tile needs) — a
    # non-128-multiple block_k must not reach Mosaic un-rounded.
    b, s, h, kv, d = 2, 256, 4, 2, 64
    q = _sds((b, s, h, d), jnp.bfloat16)
    k = _sds((b, s, kv, d), jnp.bfloat16)
    v = _sds((b, s, kv, d), jnp.bfloat16)
    _lower_tpu(
        lambda q, k, v: flash_attention(
            q, k, v, block_q=bq, block_k=bk, interpret=False
        ),
        q, k, v,
    )


def test_flash_backward_lowers_for_tpu():
    b, s, h, kv, d = 2, 256, 4, 2, 64
    q = _sds((b, s, h, d), jnp.bfloat16)
    k = _sds((b, s, kv, d), jnp.bfloat16)
    v = _sds((b, s, kv, d), jnp.bfloat16)

    def loss(q, k, v):
        out = flash_attention(q, k, v, interpret=False, use_pallas_bwd=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_flash_partial_and_partial_bwd_lower_for_tpu():
    # The ring-attention building blocks: a KV block smaller than the
    # query shard, with explicit (permuted-layout-capable) positions.
    b, sq, sk, h, kv, d = 1, 256, 128, 4, 2, 64
    q = _sds((b, sq, h, d), jnp.bfloat16)
    k = _sds((b, sk, kv, d), jnp.bfloat16)
    v = _sds((b, sk, kv, d), jnp.bfloat16)
    qp = _sds((b, sq), jnp.int32)
    kp = _sds((b, sk), jnp.int32)

    _lower_tpu(
        lambda q, k, v, qp, kp: flash_attention_partial(
            q, k, v, qp, kp, interpret=False
        ),
        q, k, v, qp, kp,
    )

    out = _sds((b, sq, h, d), jnp.bfloat16)
    lse = _sds((b, sq, h), jnp.float32)
    _lower_tpu(
        lambda q, k, v, do, out, lse, qp, kp: flash_attention_partial_bwd(
            q, k, v, do, out, lse, qp, kp,
            scale=d**-0.5, block_q=128, block_k=128, interpret=False,
        ),
        q, k, v, out, out, lse, qp, kp,
    )


@pytest.mark.parametrize("wire", ["fp8", "int8"])
@pytest.mark.parametrize("n_blocks", [3, 64, 1500, 2048])
def test_quant_kernels_lower_for_tpu(wire, n_blocks):
    # n_blocks=3 pins the rows_per_tile == whole-dim branch of the tiling
    # rule; 64 pins whole-dim above the old 8-row tiles; 1500 pins the
    # RAGGED 1024-row grid (a partial final tile — the common shape for
    # arbitrary gradient sizes) and 2048 the exact-multiple grid.
    x = _sds((n_blocks, quantization.BLOCK), jnp.float32)
    _lower_tpu(
        lambda x: quantization.quantize_blocks_pallas(
            x, interpret=False, wire=wire
        ),
        x,
    )

    pdtype = jnp.int8 if wire == "int8" else jnp.float8_e4m3fn
    payload = _sds((n_blocks, quantization.BLOCK), pdtype)
    scales = _sds((n_blocks,), jnp.float32)
    _lower_tpu(
        lambda p, s: quantization.dequantize_blocks_pallas(
            p, s, interpret=False
        ),
        payload,
        scales,
    )


def test_flagship_flash_train_step_lowers_for_tpu(monkeypatch):
    """Cross-lower the FULL ~445M large-bench train step (scan llama +
    dots-remat + Pallas flash fwd/bwd + fused CE + sgd update) for a TPU
    target — the integration-level version of the kernel gates above.
    bench.py's tpu-large attempt compiles exactly this program shape on
    the chip (TPUFT_BENCH_MODEL=large; the config comes from the shared
    ``large_bench_config()`` so the gate cannot drift from the bench);
    a lowering regression anywhere in that stack fails here instead of
    burning a relay window. Everything is abstract (jax.eval_shape) —
    no 445M params materialize.
    """
    import optax

    from torchft_tpu.models import llama as llama_mod
    from torchft_tpu.ops import flash_attention as fa_mod
    from torchft_tpu.models.llama import Llama, LlamaConfig

    # flash_attention auto-selects interpret mode off-TPU; the gate must
    # lower the real Mosaic program, so pretend the chip is attached for
    # the trace (lowering still targets TPU via lowering_platforms).
    monkeypatch.setattr(fa_mod, "on_tpu", lambda: True)
    monkeypatch.setattr(llama_mod, "on_tpu", lambda: True)

    # The SHARED flagship definition: the gate must lower exactly the
    # program bench.py's large mode runs (a copied config drifted when
    # the head geometry was retuned — review finding, round 5).
    config = llama_mod.large_bench_config()
    seq = config.max_seq_len
    model = Llama(config)
    tx = optax.sgd(0.01, momentum=0.9)
    tokens = _sds((1, seq + 1), jnp.int32)
    params = jax.eval_shape(
        lambda key, t: model.init(key, t),
        jax.random.PRNGKey(0), _sds((1, seq), jnp.int32),
    )
    opt_state = jax.eval_shape(tx.init, params)

    def train_step(p, s, batch_tokens):
        def loss_fn(p):
            return model.apply(p, batch_tokens[:, :-1], targets=batch_tokens[:, 1:])

        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, s = tx.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    lowered = _lower_tpu(train_step, params, opt_state, tokens)
    # The Mosaic kernels must actually be in the lowered program (the gate
    # would be vacuous if auto-selection fell back to the scan path).
    assert "tpu_custom_call" in lowered.as_text()


def test_ring_flash_under_sp_mesh_lowers_for_tpu():
    """The sequence-parallel path: shard_map(ring_attention_flash) over an
    AbstractMesh (no devices needed), forward and reverse, cross-lowered
    for TPU with the per-hop Pallas partials present in the module. This
    is the long-context stack's on-chip program — ppermute ring + flash
    partial kernels — gated without the relay."""
    from jax import shard_map
    from jax.sharding import AbstractMesh, PartitionSpec as P

    from torchft_tpu.ops.ring_attention import ring_attention_flash

    am = AbstractMesh((4,), ("sp",))
    b, s, h, kv, d = 1, 512, 4, 2, 64

    def f(q, k, v):
        return shard_map(
            lambda q, k, v: ring_attention_flash(
                q, k, v, axis_name="sp", interpret=False
            ),
            mesh=am,
            in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"),
        )(q, k, v)

    args = (
        _sds((b, s, h, d), jnp.bfloat16),
        _sds((b, s, kv, d), jnp.bfloat16),
        _sds((b, s, kv, d), jnp.bfloat16),
    )
    lowered = _lower_tpu(f, *args)
    assert "tpu_custom_call" in lowered.as_text()

    def loss(q, k, v):
        return jnp.sum(f(q, k, v).astype(jnp.float32) ** 2)

    lowered_bwd = _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), *args)
    assert "tpu_custom_call" in lowered_bwd.as_text()


def test_lowering_gate_catches_bad_block_layout():
    """Meta-test: the gate actually fires on the exact constraint class the
    round-1..4 flash kernels violated (squeezed dim in second-to-last block
    position). If jax ever stops validating block mappings during
    cross-platform lowering, this fails and the gate must move on-chip."""
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def bad(x):
        return pl.pallas_call(
            kern,
            grid=(4,),
            in_specs=[pl.BlockSpec((None, 128, None, 64), lambda i: (0, 0, i, 0))],
            out_specs=pl.BlockSpec((None, 128, None, 64), lambda i: (0, 0, i, 0)),
            out_shape=jax.ShapeDtypeStruct((2, 256, 4, 64), jnp.bfloat16),
        )(x)

    x = _sds((2, 256, 4, 64), jnp.bfloat16)
    with pytest.raises(ValueError, match="last two dimensions"):
        _lower_tpu(bad, x)

def test_8b_sharded_flash_train_step_lowers_for_tpu(monkeypatch):
    """The SCALE gate: the reference's production story is Llama-3 8B
    FT-DDP / 70B HSDP (BASELINE.md); this cross-lowers the full 8B
    config's SHARDED train step — scan + dots-remat + fused CE + the
    Pallas flash kernel — over an abstract fsdp=4 x tp=2 mesh for a TPU
    target, with params/opt-state sharded by the same plan_shardings the
    runtime uses. Two distinct failure classes land here instead of on a
    real pod: Mosaic block-mapping violations at 8B shapes, and the
    "Mosaic kernels cannot be automatically partitioned" lowering error
    the flash path hits under jit-with-mesh unless it shard_maps itself
    (models/llama.py _flash_under_ambient_mesh — found by exactly this
    lowering, round 5). Everything is abstract: 8.03B params eval_shape
    only, and the scanned stack keeps the lowered module ~0.2 MB."""
    from dataclasses import replace

    import optax

    from jax.sharding import AbstractMesh, NamedSharding, PartitionSpec as P

    from torchft_tpu.models import llama as llama_mod
    from torchft_tpu.models.llama import (
        CONFIGS, Llama, plan_shardings, sharding_plan,
    )
    from torchft_tpu.ops import flash_attention as fa_mod

    monkeypatch.setattr(fa_mod, "on_tpu", lambda: True)
    monkeypatch.setattr(llama_mod, "on_tpu", lambda: True)

    cfg = replace(
        CONFIGS["8b"], scan_layers=True, remat="dots", loss_vocab_chunk=4096,
        attention_impl="flash", max_seq_len=4096,
    )
    model = Llama(cfg)
    am = AbstractMesh((4, 2), ("fsdp", "tp"))
    B, S = 8, cfg.max_seq_len
    tokens = _sds((B, S + 1), jnp.int32)
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), _sds((B, S), jnp.int32))
    )
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    assert n_params > 8e9  # the real 8B, not a stand-in
    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = jax.eval_shape(tx.init, params)
    plan = sharding_plan("fsdp", "tp")
    p_sh = plan_shardings(params, am, plan)
    o_sh = plan_shardings(opt_state, am, plan)
    b_sh = NamedSharding(am, P("fsdp", None))

    def train_step(p, s, bt):
        def loss_fn(p):
            return model.apply(p, bt[:, :-1], targets=bt[:, 1:])

        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, s = tx.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    with jax.sharding.use_abstract_mesh(am):
        lowered = (
            jax.jit(train_step, in_shardings=(p_sh, o_sh, b_sh))
            .trace(params, opt_state, tokens)
            .lower(lowering_platforms=("tpu",))
        )
    assert "tpu_custom_call" in lowered.as_text()
