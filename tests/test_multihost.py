"""Multi-host support units: sharded checkpoint capture/restore, optimizer
state placement, group jax-cluster bootstrap, platform honoring.

The true multi-process paths are driven end-to-end by the launcher chaos
runs (verify drives); these tests pin the building blocks on the 8-device
single-process mesh, with a duck-typed stand-in for partially-addressable
arrays (single-process jax arrays are always fully addressable)."""

import io
from dataclasses import dataclass
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchft_tpu.checkpointing import _serialization
from torchft_tpu.checkpointing._serialization import ShardedLeaf, ShardedLeafMeta
from torchft_tpu.optim import Optimizer, _align_opt_state, _restore_leaf


class _FakeMultiHostArray:
    """Duck-typed partially-addressable array: only `local` shards visible."""

    def __init__(self, full: np.ndarray, mesh_size: int, local: List[int]) -> None:
        self._full = full
        self.shape = full.shape
        self.dtype = full.dtype
        self.is_fully_addressable = False
        rows = full.shape[0] // mesh_size

        @dataclass
        class Shard:
            index: Tuple[slice, ...]
            data: np.ndarray

        self.addressable_shards = [
            Shard(
                (slice(i * rows, (i + 1) * rows), slice(None)),
                full[i * rows : (i + 1) * rows],
            )
            for i in local
        ]


def test_sharded_leaf_capture_and_streaming_roundtrip() -> None:
    full = np.arange(32, dtype=np.float32).reshape(8, 4)
    fake = _FakeMultiHostArray(full, mesh_size=4, local=[0, 1])

    leaf = _serialization._to_host(fake)
    assert isinstance(leaf, ShardedLeaf)
    assert leaf.global_shape == (8, 4)
    assert len(leaf.shards) == 2  # only the local shards

    # Shard buffers ride the raw stream (meta carries sizes), not the header.
    state = {"w": fake, "plain": np.ones(3, np.float32)}
    treedef, metas, leaves = _serialization.state_dict_meta(state)
    sharded_metas = [m for m in metas if isinstance(m, ShardedLeafMeta)]
    assert len(sharded_metas) == 1
    assert sum(sharded_metas[0].shard_nbytes) == 2 * 2 * 4 * 4

    buf = io.BytesIO()
    _serialization.save_state_dict(state, buf)
    buf.seek(0)
    restored = _serialization.load_state_dict(buf)
    assert isinstance(restored["w"], ShardedLeaf)
    for (key, data), (rkey, rdata) in zip(leaf.shards, restored["w"].shards):
        assert key == rkey
        np.testing.assert_array_equal(data, rdata)
    np.testing.assert_array_equal(restored["plain"], np.ones(3, np.float32))


def test_restore_leaf_reassembles_against_current_sharding() -> None:
    mesh = Mesh(np.array(jax.devices()[:4]), ("fsdp",))
    sharding = NamedSharding(mesh, P("fsdp"))
    current = jax.device_put(jnp.zeros((8, 4), jnp.float32), sharding)

    donor_full = np.arange(32, dtype=np.float32).reshape(8, 4)
    donor = ShardedLeaf(
        (8, 4),
        "float32",
        [
            (((i * 2, (i + 1) * 2), (0, 4)), donor_full[i * 2 : (i + 1) * 2])
            for i in range(4)
        ],
    )
    restored = _restore_leaf(donor, current)
    assert restored.sharding == sharding
    np.testing.assert_array_equal(np.asarray(restored), donor_full)

    # Missing shard -> loud error, not silent corruption.
    partial = ShardedLeaf((8, 4), "float32", donor.shards[:2])
    with pytest.raises(ValueError, match="lacks shard"):
        _restore_leaf(partial, current)


def test_align_opt_state_replicates_scalars_over_params_mesh() -> None:
    mesh = Mesh(np.array(jax.devices()[:4]), ("fsdp",))
    params = {
        "w": jax.device_put(
            jnp.zeros((8, 4), jnp.float32), NamedSharding(mesh, P("fsdp"))
        )
    }
    tx = optax.adam(1e-3)
    aligned = _align_opt_state(tx.init(params), params)
    target = {d.id for d in params["w"].sharding.device_set}
    for leaf in jax.tree_util.tree_leaves(aligned):
        if isinstance(leaf, jax.Array):
            assert {d.id for d in leaf.sharding.device_set} == target

    # The jitted update accepts grads on the mesh without device conflicts.
    opt = object.__new__(Optimizer)
    from torchft_tpu.optim import make_jit_update

    update = make_jit_update(tx)
    grads = {"w": jax.device_put(jnp.ones((8, 4)), NamedSharding(mesh, P("fsdp")))}
    new_params, new_state = update(grads, aligned, params)
    assert jnp.isfinite(jax.tree_util.tree_leaves(new_params)[0]).all()


def test_init_group_jax_cluster_noop_without_coordinator(monkeypatch) -> None:
    from torchft_tpu.bootstrap import init_group_jax_cluster

    monkeypatch.delenv("TPUFT_JAX_COORDINATOR", raising=False)
    assert init_group_jax_cluster() is False


def test_honor_jax_platforms_env_noop_cases(monkeypatch) -> None:
    from torchft_tpu.utils.platform import honor_jax_platforms_env

    # Unset: no-op. Set after backend init: swallows the RuntimeError.
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    honor_jax_platforms_env()
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    honor_jax_platforms_env()  # backend already initialized by conftest


def test_launcher_rejects_coordinator_without_multirank() -> None:
    from torchft_tpu.launch import supervise

    with pytest.raises(ValueError, match="group-world-size"):
        supervise(
            ["true"], num_replica_groups=1, group_world_size=1,
            jax_coordinator_port_base=30000,
        )
