"""Multi-process streaming DiLoCo end-to-end: 2 replica groups x 2 jax
processes each (one jax.distributed CPU cluster per group, gloo
collectives), sharded params, 2 streaming fragments over the packed-int4
wire, a SIGKILLed rank mid-run, supervised group restart, live heal of
the DiLoCo state (inner leaves + fragment backups + outer optimizer),
and cross-process digest equality of the committed global state.

Completes the multi-process operational story: test_multiprocess_e2e.py
covers FT-DDP across real processes; this covers the semi-sync
(LocalSGD/DiLoCo) axis the reference exercises only in threads
(local_sgd_integ_test.py) or external slurm chaos."""

import json
import pathlib
import sys


_TRAIN_SCRIPT = r"""
import hashlib, json, os, pathlib, signal, sys, time
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
os.environ["TPUFT_WIRE_DTYPE"] = "int4"

from torchft_tpu.bootstrap import init_group_jax_cluster, init_manager

group = os.environ["REPLICA_GROUP_ID"]
rank = int(os.environ.get("GROUP_RANK", "0"))
out_dir = pathlib.Path(os.environ["E2E_OUT"])
marker = out_dir / "killed_once"

init_group_jax_cluster()

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchft_tpu.local_sgd import DiLoCo
from torchft_tpu.parallel.process_group import ProcessGroupTCP

pg = ProcessGroupTCP(timeout=15.0)
manager, store_server = init_manager(
    pg,
    min_replica_size=1,
    timeout=15.0,
    quorum_timeout=30.0,
    heartbeat_interval=0.1,
    use_async_quorum=False,  # DiLoCo requires sync quorum
    # Identical seeded init on every rank makes the step-0 parameter
    # mosaic redundant — and with 4 GIL-starved processes hitting the
    # mosaic in lockstep, a fetcher can lose the race against the donor's
    # commit closing the serve window, cascading into retry rounds this
    # 1-core box grinds through very slowly. The mid-run kill still
    # exercises the REAL heal (restarted group behind, live recovery).
    init_sync=False,
)

mesh = Mesh(np.array(jax.devices()), ("fsdp",))

def init_params():
    key = jax.random.PRNGKey(0)
    return {
        "w1": jax.device_put(
            jax.random.normal(key, (16, 8), jnp.float32) * 0.1,
            NamedSharding(mesh, P("fsdp", None)),
        ),
        "w2": jax.device_put(
            jnp.zeros((8, 4), jnp.float32), NamedSharding(mesh, P())
        ),
    }

SYNC_EVERY, N_SYNCS = 4, 8
algo = DiLoCo(
    manager,
    inner_tx=optax.sgd(0.05),
    outer_tx=optax.sgd(0.4, momentum=0.9, nesterov=True),
    params=init_params(),
    sync_every=SYNC_EVERY,
    n_fragments=2,
    should_quantize=True,  # packed-int4 wire (TPUFT_WIRE_DTYPE above)
)

def grad_for(step, pos):
    key = jax.random.PRNGKey(100 + 31 * step + pos)
    return {
        "w1": jax.device_put(
            jax.random.normal(key, (16, 8), jnp.float32) * 0.01,
            NamedSharding(mesh, P("fsdp", None)),
        ),
        "w2": jax.device_put(
            jnp.full((8, 4), 0.001 * pos, jnp.float32), NamedSharding(mesh, P())
        ),
    }

def digest_leaves(leaves):
    # Digest of this RANK's addressable shards (np.asarray on a
    # non-fully-addressable array raises; each rank digests its own shard
    # set, compared per-rank across groups).
    digest = hashlib.sha256()
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            for shard in sorted(
                leaf.addressable_shards,
                key=lambda s: tuple((sl.start or 0) for sl in s.index),
            ):
                digest.update(np.asarray(shard.data).tobytes())
        else:
            digest.update(np.asarray(leaf).tobytes())
    return digest.hexdigest()

# Gradients keyed on (committed step, position in cycle) — observed state,
# identical across groups, self-realigning after the heal.
# Observed-status pacing (CLAUDE.md: gate on state, not sleeps): the
# survivor must still be training when the killed group's restart (~15s
# of jax startup) rejoins, so inner steps are paced ONLY while the fleet
# is degraded (participants < 2 — the restart/heal window the kill
# opens; before the first quorum num_participants() is 0, which also
# paces the pre-kill warmup safely). The restarted group must LIVE-HEAL
# into the run, which the committed-steps assertion below verifies — a
# from-scratch solo replay would commit from step 1.
committed_steps = []
loop_started_unix = time.time()
while manager.current_step() < N_SYNCS:
    step = manager.current_step()
    if group == "1" and rank == 1 and step == 1 and not marker.exists():
        marker.write_text("x")
        os.kill(os.getpid(), signal.SIGKILL)  # hard death, no cleanup
    if algo.step(grad_for(step, algo._local_step)):
        committed_steps.append(manager.current_step())
    if manager.num_participants() < 2:
        time.sleep(0.5)

(out_dir / f"g{group}_r{rank}.json").write_text(
    json.dumps(
        {
            "step": manager.current_step(),
            # This incarnation's committed steps: a healed joiner's first
            # commit continues from the survivor's step, never from 1.
            "committed_steps": committed_steps,
            # Overlap detection for the heal assertion: the heal is only
            # physically possible if this incarnation's loop started while
            # the survivor was still training.
            "loop_started_unix": loop_started_unix,
            "finished_unix": time.time(),
            # Committed global state: fragment backups (host side already).
            "backup_digest": digest_leaves(
                [b for frag in algo._fragments for b in frag.backup]
            ),
            # Local leaves equal the merged globals right after the final
            # committed sync (alpha=0, loop exits at the sync boundary).
            "leaves_digest": digest_leaves(algo._leaves),
        }
    )
)
manager.shutdown(wait=False)
pg.shutdown()
if store_server is not None:
    store_server.shutdown()
"""


def test_two_groups_two_jax_procs_diloco_sigkill_recovery(tmp_path) -> None:
    from torchft_tpu.launch import supervise

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    script = tmp_path / "diloco_e2e_job.py"
    script.write_text(_TRAIN_SCRIPT.replace("@REPO@", repo))
    out_dir = tmp_path / "out"
    out_dir.mkdir()

    code = supervise(
        [sys.executable, str(script)],
        num_replica_groups=2,
        group_world_size=2,
        relaunch_interval=0.5,
        max_restarts=3,
        store_port_base=29850,
        jax_coordinator_port_base=29950,
        extra_env={"E2E_OUT": str(out_dir), "TPUFT_LOG": "warn"},
    )
    assert code == 0
    assert (out_dir / "killed_once").exists(), "the SIGKILL never fired"

    results = {}
    for group in range(2):
        for rank in range(2):
            path = out_dir / f"g{group}_r{rank}.json"
            assert path.exists(), f"missing result for group {group} rank {rank}"
            results[(group, rank)] = json.loads(path.read_text())
    for (group, rank), data in results.items():
        assert data["step"] == 8, (group, rank, data)
    # The restarted group's final incarnation must have HEALED into the
    # run, not replayed solo: the SIGKILL fires at outer step 1, so a
    # from-scratch incarnation's commits start at 1-2 while a healed one
    # starts at the survivor's step (>2 by the time ~15s of jax restart
    # has passed against the survivor's ~2s sync cadence). On a normal box
    # the restart always overlaps the paced survivor; under extreme load
    # the survivor can finish first, in which case a heal is physically
    # impossible (nothing left to heal from) and the solo replay is the
    # CORRECT elastic behavior — the digest checks above still hold. Gate
    # on observed overlap, not timing assumptions (CLAUDE.md).
    overlapped = (
        results[(1, 1)]["loop_started_unix"] < results[(0, 0)]["finished_unix"]
    )
    g1_first_commit = min(results[(1, 1)]["committed_steps"])
    if overlapped:
        assert g1_first_commit > 2, (
            f"group 1 replayed solo from step {g1_first_commit} despite "
            "overlapping the survivor — heal never ran"
        )
    else:
        import warnings

        warnings.warn(
            "survivor finished before the restart rejoined (loaded box): "
            "heal not exercised this run; digests still verified"
        )
    # Master invariant: committed DiLoCo global state (fragment backups)
    # and the merged local leaves (alpha=0: leaves == globals at the exit
    # boundary) bitwise identical ACROSS GROUPS, per rank — each rank
    # digests its own shard partitions, identical in both groups by the
    # HSDP layout contract.
    for rank in range(2):
        assert (
            results[(0, rank)]["backup_digest"]
            == results[(1, rank)]["backup_digest"]
        ), rank
        assert (
            results[(0, rank)]["leaves_digest"]
            == results[(1, rank)]["leaves_digest"]
        ), rank
