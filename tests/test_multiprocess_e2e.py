"""Real multi-process end-to-end test: 2 replica groups x 2 jax processes
each (one jax.distributed CPU cluster per group, gloo collectives), sharded
state over the group mesh, a SIGKILLed rank mid-run, supervised group
restart, live heal of sharded state, and cross-process digest equality.

This promotes the round-1 'manual launcher chaos drive' to CI (parity:
reference fsdp_test.py:96-120 — its only process-spawn test — plus kill
recovery, which the reference leaves to slurm chaos)."""

import hashlib
import json
import os
import pathlib
import sys

import pytest


_TRAIN_SCRIPT = r"""
import hashlib, json, os, pathlib, signal, sys, time
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from torchft_tpu.bootstrap import init_group_jax_cluster, init_manager

group = os.environ["REPLICA_GROUP_ID"]
rank = int(os.environ.get("GROUP_RANK", "0"))
out_dir = pathlib.Path(os.environ["E2E_OUT"])
marker = out_dir / "killed_once"

init_group_jax_cluster()

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchft_tpu.optim import Optimizer
from torchft_tpu.parallel.mesh import ft_allreduce_sharded
from torchft_tpu.parallel.process_group import ProcessGroupTCP

pg = ProcessGroupTCP(timeout=15.0)
manager, store_server = init_manager(
    pg,
    min_replica_size=1,
    timeout=15.0,
    quorum_timeout=30.0,
    heartbeat_interval=0.1,
)

mesh = Mesh(np.array(jax.devices()), ("fsdp",))

def init_params():
    key = jax.random.PRNGKey(0)
    return {
        "w": jax.device_put(
            jax.random.normal(key, (16, 8), jnp.float32) * 0.1,
            NamedSharding(mesh, P("fsdp", None)),
        ),
        "b": jax.device_put(
            jnp.zeros((8,), jnp.float32), NamedSharding(mesh, P())
        ),
    }

opt = Optimizer(manager, optax.sgd(0.05, momentum=0.9), init_params())

def grad_for(step):
    key = jax.random.PRNGKey(100 + step)
    return {
        "w": jax.device_put(
            jax.random.normal(key, (16, 8), jnp.float32) * 0.01,
            NamedSharding(mesh, P("fsdp", None)),
        ),
        "b": jax.device_put(
            jnp.full((8,), 0.001 * step, jnp.float32), NamedSharding(mesh, P())
        ),
    }

def digest_params(params):
    digest = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(params):
        for shard in sorted(
            leaf.addressable_shards,
            key=lambda s: tuple((sl.start or 0) for sl in s.index),
        ):
            digest.update(np.asarray(shard.data).tobytes())
    return digest.hexdigest()

history = {}
# Observed-status pacing (CLAUDE.md: gate on state, not sleeps): the
# survivor must still be training while the killed group restarts (~15s
# of jax startup), so steps are paced ONLY while the fleet is degraded
# (participants < 2 — the restart/heal window the kill opens). With both
# groups participating the loop runs at full speed, which is what keeps
# this e2e inside the suite budget.
N_STEPS = 60
while manager.current_step() < N_STEPS:
    step = manager.current_step()
    if group == "1" and rank == 1 and step == 2 and not marker.exists():
        marker.write_text("x")
        os.kill(os.getpid(), signal.SIGKILL)  # hard death, no cleanup
    opt.begin_step()
    avg = ft_allreduce_sharded(manager, grad_for(step))
    if opt.step(avg):
        history[manager.current_step()] = digest_params(opt.params)
    if manager.num_participants() < 2:
        time.sleep(0.25)

(out_dir / f"g{group}_r{rank}.json").write_text(
    json.dumps({"step": manager.current_step(), "digest": digest_params(opt.params),
                "history": history})
)
manager.shutdown(wait=False)
pg.shutdown()
if store_server is not None:
    store_server.shutdown()
"""


def test_two_groups_two_jax_procs_sigkill_recovery(tmp_path) -> None:
    from torchft_tpu.launch import supervise

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    script = tmp_path / "e2e_job.py"
    script.write_text(_TRAIN_SCRIPT.replace("@REPO@", repo))
    out_dir = tmp_path / "out"
    out_dir.mkdir()

    code = supervise(
        [sys.executable, str(script)],
        num_replica_groups=2,
        group_world_size=2,
        relaunch_interval=0.5,
        max_restarts=3,
        store_port_base=29650,
        jax_coordinator_port_base=29750,
        extra_env={"E2E_OUT": str(out_dir), "TPUFT_LOG": "warn"},
    )
    assert code == 0
    assert (out_dir / "killed_once").exists(), "the SIGKILL never fired"

    results = {}
    for group in range(2):
        for rank in range(2):
            path = out_dir / f"g{group}_r{rank}.json"
            assert path.exists(), f"missing result for group {group} rank {rank}"
            results[(group, rank)] = json.loads(path.read_text())
    for (group, rank), data in results.items():
        assert data["step"] == 60, (group, rank, data)
    # The restarted group's final incarnation must have HEALED into the run,
    # not retrained from scratch: the SIGKILL fires at step 2 before that
    # step commits, so a from-scratch incarnation's history starts at 0
    # while a healed one starts at the survivor's step, which is at least 3
    # (exactly 3 when the loaded box makes the survivor slow — still a heal).
    g1_first_commit = min(int(k) for k in results[(1, 1)]["history"])
    assert g1_first_commit > 2, f"group 1 retrained solo from step {g1_first_commit}"
    # Cross-GROUP digest equality per rank: each rank holds the same shard
    # partitions in both groups, and committed state must be bitwise equal.
    assert results[(0, 0)]["digest"] == results[(1, 0)]["digest"]
    assert results[(0, 1)]["digest"] == results[(1, 1)]["digest"]
