"""ProcessGroupNative conformance: the C++ ring-collective engine must match
the Python TCP backend on the full collective surface, bitwise determinism,
and the kill/reconfigure drill; plus it must slot into the Manager and the
quantized pipelines unchanged."""

from concurrent.futures import ThreadPoolExecutor
from typing import List

import numpy as np
import pytest

from test_process_group import fresh_prefix, run_on_all, store_server  # noqa: F401

from torchft_tpu.parallel.collectives import allreduce_quantized_wire
from torchft_tpu.parallel.native_pg import ProcessGroupNative
from torchft_tpu.parallel.process_group import ProcessGroup, ReduceOp
from torchft_tpu.ops import quantization as q


def make_native_group(store_server, world_size: int, timeout: float = 15.0):
    prefix = fresh_prefix()
    pgs = [ProcessGroupNative(timeout=timeout) for _ in range(world_size)]
    with ThreadPoolExecutor(max_workers=world_size) as pool:
        futures = [
            pool.submit(
                pg.configure,
                f"{store_server.address()}/{prefix}",
                f"native_{i}",
                i,
                world_size,
            )
            for i, pg in enumerate(pgs)
        ]
        for f in futures:
            f.result(timeout=30)
    return pgs


@pytest.mark.parametrize("world_size", [2, 3, 4])
def test_native_ring_allreduce(store_server, world_size) -> None:
    pgs = make_native_group(store_server, world_size)
    try:
        # Large enough that every rank owns a real ring chunk.
        results = run_on_all(
            pgs,
            lambda pg, i: pg.allreduce(
                [np.full(1000, float(i + 1), dtype=np.float32),
                 np.arange(7, dtype=np.float64) * (i + 1)],
                ReduceOp.SUM,
            ).wait(30),
        )
        total = sum(range(1, world_size + 1))
        for r in results:
            np.testing.assert_allclose(r[0], np.full(1000, float(total)))
            np.testing.assert_allclose(r[1], np.arange(7) * total)
        # Bitwise identical across ranks — the recovery invariant.
        for idx in range(2):
            assert all(
                r[idx].tobytes() == results[0][idx].tobytes() for r in results
            )

        avg = run_on_all(
            pgs,
            lambda pg, i: pg.allreduce(
                [np.full(10, float(i), dtype=np.float32)], ReduceOp.AVG
            ).wait(30),
        )
        mean = sum(range(world_size)) / world_size
        for r in avg:
            np.testing.assert_allclose(r[0], np.full(10, mean), rtol=1e-6)
    finally:
        for pg in pgs:
            pg.shutdown()


def test_native_bfloat16_and_int(store_server) -> None:
    import ml_dtypes

    pgs = make_native_group(store_server, 2)
    try:
        results = run_on_all(
            pgs,
            lambda pg, i: pg.allreduce(
                [np.full(600, 1.5 + i, dtype=ml_dtypes.bfloat16),
                 np.full(5, i + 1, dtype=np.int64)],
                ReduceOp.SUM,
            ).wait(30),
        )
        for r in results:
            assert r[0].dtype == ml_dtypes.bfloat16
            np.testing.assert_allclose(r[0].astype(np.float32), np.full(600, 4.0))
            np.testing.assert_array_equal(r[1], np.full(5, 3, dtype=np.int64))
    finally:
        for pg in pgs:
            pg.shutdown()


def test_native_allgather_broadcast_alltoall_sendrecv(store_server) -> None:
    pgs = make_native_group(store_server, 3)
    try:
        gathered = run_on_all(
            pgs, lambda pg, i: pg.allgather([np.full(i + 1, float(i))]).wait(30)
        )
        for per_rank in gathered:
            assert len(per_rank) == 3
            for i, arrays in enumerate(per_rank):
                np.testing.assert_array_equal(arrays[0], np.full(i + 1, float(i)))

        broadcasted = run_on_all(
            pgs, lambda pg, i: pg.broadcast([np.array([float(i), 7.0])], 2).wait(30)
        )
        for r in broadcasted:
            np.testing.assert_array_equal(r[0], np.array([2.0, 7.0]))

        exchanged = run_on_all(
            pgs,
            lambda pg, i: pg.alltoall(
                [np.array([i * 10.0 + j]) for j in range(3)]
            ).wait(30),
        )
        for i, per_rank in enumerate(exchanged):
            for j, arr in enumerate(per_rank):
                np.testing.assert_array_equal(arr, np.array([j * 10.0 + i]))

        def exchange(pg: ProcessGroup, i: int):
            if i == 0:
                pg.send([np.array([42.0]), np.ones((2, 2))], dst=1).wait(30)
                return None
            if i == 1:
                return pg.recv([np.empty(1)], src=0).wait(30)
            return None

        results = run_on_all(pgs, exchange)
        np.testing.assert_array_equal(results[1][0], np.array([42.0]))
        run_on_all(pgs, lambda pg, i: pg.barrier().wait(30))
    finally:
        for pg in pgs:
            pg.shutdown()


def test_native_resiliency_kill_and_reconfigure(store_server) -> None:
    world_size = 3
    pgs = make_native_group(store_server, world_size, timeout=3.0)
    try:
        run_on_all(pgs, lambda pg, i: pg.allreduce([np.ones(8)], ReduceOp.SUM).wait(30))
        pgs[-1].shutdown()

        def survivor(pg: ProcessGroup, i: int):
            if i == world_size - 1:
                return None
            with pytest.raises(Exception):
                pg.allreduce([np.ones(8)], ReduceOp.SUM).wait(20)
            return pg.errored()

        errors = run_on_all(pgs[:-1], survivor)
        assert all(e is not None for e in errors)

        prefix = fresh_prefix()
        run_on_all(
            pgs[:-1],
            lambda pg, i: pg.configure(
                f"{store_server.address()}/{prefix}", f"native_{i}", i, world_size - 1
            ),
        )
        results = run_on_all(
            pgs[:-1], lambda pg, i: pg.allreduce([np.ones(8)], ReduceOp.SUM).wait(30)
        )
        for r in results:
            np.testing.assert_array_equal(r[0], np.full(8, 2.0))
    finally:
        for pg in pgs:
            pg.shutdown()


def test_native_quantized_wire_pipeline(store_server) -> None:
    """The fp8 prequantized allreduce rides the native alltoall/allgather."""
    pgs = make_native_group(store_server, 2)
    rng = np.random.default_rng(0)
    inputs = [rng.normal(size=1024).astype(np.float32) for _ in range(2)]
    quantized = [q.quantize_blocks(x) for x in inputs]
    try:
        results = run_on_all(
            pgs,
            lambda pg, i: allreduce_quantized_wire(
                quantized[i][0], quantized[i][1], ReduceOp.SUM, pg
            ).wait(30),
        )
        expected = inputs[0] + inputs[1]
        for payload, scales in results:
            restored = q.dequantize_blocks(payload, scales, expected.shape, expected.dtype)
            np.testing.assert_allclose(restored, expected, rtol=0.2, atol=0.3)
        assert results[0][0].tobytes() == results[1][0].tobytes()
    finally:
        for pg in pgs:
            pg.shutdown()


def test_native_pg_with_manager_integration(store_server) -> None:
    """End to end: two Managers averaging gradients over ProcessGroupNative."""
    import threading

    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.parallel.store import StoreClient, StoreServer

    lighthouse = LighthouseServer(
        min_replicas=1, join_timeout_ms=10000, heartbeat_timeout_ms=1000
    )
    results = {}

    def group(idx: int) -> None:
        store = StoreServer()
        pg = ProcessGroupNative(timeout=10.0)
        manager = Manager(
            pg=pg,
            min_replica_size=1,
            store=StoreClient(store.address()),
            store_addr=store.address(),
            group_rank=0,
            lighthouse_addr=lighthouse.address(),
            replica_id=f"native_mgr_{idx}",
            heartbeat_interval=0.05,
            timeout=10.0,
            quorum_timeout=20.0,
        )
        state = {"x": np.zeros(1)}
        manager.register_state_dict_fn(
            "state", lambda s: state.update(s), lambda: dict(state)
        )
        try:
            for step in range(2):
                manager.start_quorum()
                out = manager.allreduce(np.full(2000, float(idx + 1), np.float32)).wait(30)
                assert manager.should_commit()
                results.setdefault(idx, []).append(out)
        finally:
            manager.shutdown(wait=False)
            pg.shutdown()
            store.shutdown()

    threads = [threading.Thread(target=group, args=(i,)) for i in range(2)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        # Step 0: the init_sync joiner contributes zeros while the cohort
        # divisor is 2 -> both groups see the same biased 0.5 (reference
        # semantics). Step 1: both participate -> true average 1.5.
        for idx in range(2):
            np.testing.assert_allclose(results[idx][1], np.full(2000, 1.5))
        for step in range(2):
            assert results[0][step].tobytes() == results[1][step].tobytes()
    finally:
        lighthouse.shutdown()
