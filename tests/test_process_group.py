"""ProcessGroup conformance + resiliency matrix.

Parity target: the reference's process_group_test.py — per-backend collective
smoke tests, a threads-as-replicas multi-PG harness over one store, and the
kill-one-rank / survivors-error / reconfigure-and-recover drill.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List

import numpy as np
import pytest

from torchft_tpu.parallel.process_group import (
    ErrorSwallowingProcessGroupWrapper,
    FakeProcessGroupWrapper,
    ProcessGroup,
    ProcessGroupDummy,
    ProcessGroupTCP,
    ReduceOp,
)
from torchft_tpu.parallel.store import StoreClient, StoreServer, create_store_client


@pytest.fixture(scope="module")
def store_server():
    server = StoreServer()
    yield server
    server.shutdown()


_prefix_counter = [0]


def fresh_prefix() -> str:
    _prefix_counter[0] += 1
    return f"test/{_prefix_counter[0]}"


def make_group(
    store_server: StoreServer, world_size: int, timeout: float = 10.0
) -> List[ProcessGroupTCP]:
    """Configures ``world_size`` ProcessGroupTCPs on threads over one store."""
    prefix = fresh_prefix()
    pgs = [ProcessGroupTCP(timeout=timeout) for _ in range(world_size)]
    with ThreadPoolExecutor(max_workers=world_size) as pool:
        futures = [
            pool.submit(
                pg.configure,
                f"{store_server.address()}/{prefix}",
                f"replica_{i}",
                i,
                world_size,
            )
            for i, pg in enumerate(pgs)
        ]
        for f in futures:
            f.result(timeout=30)
    return pgs


def run_on_all(pgs: List[ProcessGroup], fn: Callable[[ProcessGroup, int], object]) -> list:
    with ThreadPoolExecutor(max_workers=len(pgs)) as pool:
        futures = [pool.submit(fn, pg, i) for i, pg in enumerate(pgs)]
        return [f.result(timeout=30) for f in futures]


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------


def test_store_set_get_add(store_server) -> None:
    client = StoreClient(store_server.address(), prefix=fresh_prefix())
    client.set("k", b"v")
    assert client.get("k") == b"v"
    assert client.get("missing", wait=False) is None
    assert client.add("ctr") == 1
    assert client.add("ctr", 2) == 3
    assert client.delete("k")
    assert client.get("k", wait=False) is None
    client.close()


def test_store_blocking_get(store_server) -> None:
    client = StoreClient(store_server.address(), prefix=fresh_prefix())
    writer = StoreClient(store_server.address(), prefix=client._prefix)

    def write_later() -> None:
        time.sleep(0.2)
        writer.set("late", b"arrived")

    t = threading.Thread(target=write_later)
    t.start()
    assert client.get("late", timeout=5.0) == b"arrived"
    t.join()
    with pytest.raises(TimeoutError):
        client.get("never", timeout=0.2)
    client.close()
    writer.close()


def test_store_prefix_isolation(store_server) -> None:
    a = create_store_client(store_server.address() + "/jobA")
    b = create_store_client(store_server.address() + "/jobB")
    a.set("k", b"a")
    assert b.get("k", wait=False) is None
    a.close()
    b.close()


# ---------------------------------------------------------------------------
# collectives conformance (2 and 4 ranks)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world_size", [2, 4])
def test_allreduce_sum_avg(store_server, world_size) -> None:
    pgs = make_group(store_server, world_size)
    try:
        results = run_on_all(
            pgs,
            lambda pg, i: pg.allreduce(
                [np.full((4, 3), float(i + 1), dtype=np.float32)], ReduceOp.SUM
            ).wait(),
        )
        expected = sum(range(1, world_size + 1))
        for r in results:
            np.testing.assert_array_equal(r[0], np.full((4, 3), expected, np.float32))
        # All ranks bitwise identical.
        assert all(r[0].tobytes() == results[0][0].tobytes() for r in results)

        results = run_on_all(
            pgs,
            lambda pg, i: pg.allreduce(
                [np.full(5, float(i), dtype=np.float32)], ReduceOp.AVG
            ).wait(),
        )
        mean = sum(range(world_size)) / world_size
        for r in results:
            np.testing.assert_allclose(r[0], np.full(5, mean, np.float32))
    finally:
        for pg in pgs:
            pg.shutdown()


def test_allreduce_bfloat16(store_server) -> None:
    import ml_dtypes

    pgs = make_group(store_server, 2)
    try:
        results = run_on_all(
            pgs,
            lambda pg, i: pg.allreduce(
                [np.full(8, 1.5 + i, dtype=ml_dtypes.bfloat16)], ReduceOp.SUM
            ).wait(),
        )
        for r in results:
            assert r[0].dtype == ml_dtypes.bfloat16
            np.testing.assert_allclose(r[0].astype(np.float32), np.full(8, 4.0))
    finally:
        for pg in pgs:
            pg.shutdown()


def test_allgather_broadcast(store_server) -> None:
    pgs = make_group(store_server, 3)
    try:
        gathered = run_on_all(
            pgs, lambda pg, i: pg.allgather([np.array([i, i * 10])]).wait()
        )
        for per_rank in gathered:
            assert len(per_rank) == 3
            for i, arrays in enumerate(per_rank):
                np.testing.assert_array_equal(arrays[0], np.array([i, i * 10]))

        broadcasted = run_on_all(
            pgs,
            lambda pg, i: pg.broadcast([np.array([i, 7])], root=1).wait(),
        )
        for r in broadcasted:
            np.testing.assert_array_equal(r[0], np.array([1, 7]))
    finally:
        for pg in pgs:
            pg.shutdown()


def test_reduce_scatter_alltoall(store_server) -> None:
    pgs = make_group(store_server, 2)
    try:
        scattered = run_on_all(
            pgs,
            lambda pg, i: pg.reduce_scatter(
                [np.arange(4, dtype=np.float32) + i], ReduceOp.SUM
            ).wait(),
        )
        # total = [1, 3, 5, 7]; rank 0 gets [1, 3], rank 1 gets [5, 7]
        np.testing.assert_array_equal(scattered[0][0], np.array([1.0, 3.0]))
        np.testing.assert_array_equal(scattered[1][0], np.array([5.0, 7.0]))

        exchanged = run_on_all(
            pgs,
            lambda pg, i: pg.alltoall(
                [np.array([i * 10 + j]) for j in range(2)]
            ).wait(),
        )
        # result[j] on rank i came from rank j and is j*10 + i
        for i, per_rank in enumerate(exchanged):
            for j, arr in enumerate(per_rank):
                np.testing.assert_array_equal(arr, np.array([j * 10 + i]))
    finally:
        for pg in pgs:
            pg.shutdown()


def test_send_recv_barrier(store_server) -> None:
    pgs = make_group(store_server, 2)
    try:

        def exchange(pg: ProcessGroup, i: int):
            if i == 0:
                pg.send([np.array([42.0])], dst=1).wait()
                return None
            return pg.recv([np.empty(1)], src=0).wait()

        results = run_on_all(pgs, exchange)
        np.testing.assert_array_equal(results[1][0], np.array([42.0]))
        run_on_all(pgs, lambda pg, i: pg.barrier().wait())
    finally:
        for pg in pgs:
            pg.shutdown()


def test_collectives_overlap_in_order(store_server) -> None:
    """Multiple outstanding ops complete in submission order."""
    pgs = make_group(store_server, 2)
    try:

        def submit_many(pg: ProcessGroup, i: int):
            works = [
                pg.allreduce([np.full(2, float(k * (i + 1)))], ReduceOp.SUM)
                for k in range(5)
            ]
            return [w.wait()[0] for w in works]

        results = run_on_all(pgs, submit_many)
        for r in results:
            for k in range(5):
                np.testing.assert_array_equal(r[k], np.full(2, float(k * 1 + k * 2)))
    finally:
        for pg in pgs:
            pg.shutdown()


# ---------------------------------------------------------------------------
# resiliency: kill a rank, survivors error, reconfigure, recover
# ---------------------------------------------------------------------------


def test_resiliency_kill_and_reconfigure(store_server) -> None:
    world_size = 3
    pgs = make_group(store_server, world_size, timeout=2.0)
    try:
        # Baseline round works.
        run_on_all(pgs, lambda pg, i: pg.allreduce([np.ones(2)], ReduceOp.SUM).wait())

        # Kill the last rank mid-flight; survivors' next collective fails.
        pgs[-1].shutdown()

        def survivor_round(pg: ProcessGroup, i: int):
            if i == world_size - 1:
                return None
            with pytest.raises(Exception):
                pg.allreduce([np.ones(2)], ReduceOp.SUM).wait(timeout=10)
            return pg.errored()

        errors = run_on_all(pgs[:-1], survivor_round)
        assert all(e is not None for e in errors)

        # Reconfigure the survivors under a fresh prefix; collective recovers.
        prefix = fresh_prefix()
        run_on_all(
            pgs[:-1],
            lambda pg, i: pg.configure(
                f"{store_server.address()}/{prefix}", f"replica_{i}", i, world_size - 1
            ),
        )
        assert all(pg.errored() is None for pg in pgs[:-1])
        results = run_on_all(
            pgs[:-1], lambda pg, i: pg.allreduce([np.ones(2)], ReduceOp.SUM).wait()
        )
        for r in results:
            np.testing.assert_array_equal(r[0], np.full(2, 2.0))
    finally:
        for pg in pgs:
            pg.shutdown()


def test_abort_poisons_until_reconfigure(store_server) -> None:
    pgs = make_group(store_server, 2)
    try:
        pgs[0].abort()
        assert pgs[0].errored() is not None
        with pytest.raises(RuntimeError, match="error state"):
            pgs[0].allreduce([np.ones(1)])
    finally:
        for pg in pgs:
            pg.shutdown()


# ---------------------------------------------------------------------------
# dummy + wrappers
# ---------------------------------------------------------------------------


def test_dummy_pg_counts_and_loopback() -> None:
    pg = ProcessGroupDummy()
    out = pg.allreduce([np.array([1.0, 2.0])]).wait()
    np.testing.assert_array_equal(out[0], np.array([1.0, 2.0]))
    pg.barrier().wait()
    assert pg.op_counts == {"allreduce": 1, "barrier": 1}


def test_error_swallowing_wrapper() -> None:
    inner = ProcessGroupDummy()
    pg = ErrorSwallowingProcessGroupWrapper(inner)
    assert pg.errored() is None
    out = pg.allreduce([np.ones(2)]).wait()
    np.testing.assert_array_equal(out[0], np.ones(2))

    pg.report_error(RuntimeError("injected"))
    assert pg.errored() is not None
    # Ops after the error become dummies returning the input.
    out = pg.allreduce([np.full(2, 5.0)]).wait()
    np.testing.assert_array_equal(out[0], np.full(2, 5.0))
    # Reconfigure clears it.
    pg.configure("ignored:0/x", "r", 0, 1)
    assert pg.errored() is None


def test_fake_wrapper_injects_future_error() -> None:
    inner = ProcessGroupDummy()
    pg = FakeProcessGroupWrapper(inner)
    pg.report_future_error(RuntimeError("boom"))
    work = pg.allreduce([np.ones(1)])
    with pytest.raises(RuntimeError, match="boom"):
        work.wait()
    assert pg.errored() is not None
    # Only the next op was poisoned.
    pg.configure("ignored:0/x", "r", 0, 1)
    assert pg.errored() is None
    pg.allreduce([np.ones(1)]).wait()


def test_store_add_shares_keyspace_with_get(store_server) -> None:
    """TCPStore semantics: counters are visible to get/wait as decimal strings."""
    client = StoreClient(store_server.address(), prefix=fresh_prefix())
    assert client.add("ready") == 1
    assert client.get("ready", wait=False) == b"1"
    waiter = StoreClient(store_server.address(), prefix=client._prefix)
    assert waiter.get("ready", timeout=2.0) == b"1"
    client.set("ready", b"41")
    assert client.add("ready") == 42
    client.close()
    waiter.close()


def test_managed_pg_routes_through_manager() -> None:
    """ManagedProcessGroup parity (reference :1233-1266): every array routes
    through the manager individually; result is a list in input order."""
    from unittest.mock import MagicMock

    from torchft_tpu.parallel.process_group import ManagedProcessGroup

    manager = MagicMock()
    manager._pg = ProcessGroupDummy()
    manager.num_participants.return_value = 3
    from torchft_tpu.work import _DummyWork

    manager.allreduce.side_effect = lambda array, reduce_op: _DummyWork(array)
    manager.allreduce_pytree.side_effect = lambda arrays: _DummyWork(list(arrays))
    pg = ManagedProcessGroup(manager)
    # Default AVG goes through the bucketed pytree path in one call.
    out = pg.allreduce([np.ones(2), np.zeros((2, 2))]).wait()
    assert manager.allreduce_pytree.call_count == 1
    assert isinstance(out, list) and len(out) == 2
    np.testing.assert_array_equal(out[0], np.ones(2))
    np.testing.assert_array_equal(out[1], np.zeros((2, 2)))
    # SUM routes per-array through manager.allreduce.
    from torchft_tpu.parallel.process_group import ReduceOp as _Op

    out = pg.allreduce([np.ones(2), np.zeros((2, 2))], op=_Op.SUM).wait()
    assert manager.allreduce.call_count == 2
    np.testing.assert_array_equal(out[0], np.ones(2))
    assert pg.size() == 3
    assert pg.getBackendName() == "tpuft-managed"


def test_managed_pg_real_manager_end_to_end() -> None:
    """Non-mocked ManagedProcessGroup drill: heterogeneous-shape lists resolve
    to per-array results through a real Manager; non-AVG/SUM ops raise instead
    of silently averaging (round-1 advisor finding)."""
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.parallel.process_group import ManagedProcessGroup

    lighthouse = LighthouseServer(min_replicas=1, join_timeout_ms=100)
    store = StoreServer()
    inner = ProcessGroupTCP(timeout=30.0)
    manager = Manager(
        pg=inner,
        min_replica_size=1,
        store=StoreClient(store.address()),
        store_addr=store.address(),
        lighthouse_addr=lighthouse.address(),
        replica_id="managed-pg-test",
        timeout=30.0,
        quorum_timeout=60.0,
        use_async_quorum=False,
    )
    try:
        manager.start_quorum()
        pg = ManagedProcessGroup(manager)
        arrays = [np.ones(3, np.float32), np.full((2, 2), 4.0, np.float32)]
        out = pg.allreduce(arrays, op=ReduceOp.AVG).wait(timeout=30)
        assert isinstance(out, list) and len(out) == 2
        np.testing.assert_allclose(out[0], np.ones(3))
        np.testing.assert_allclose(out[1], np.full((2, 2), 4.0))
        summed = pg.allreduce(arrays, op=ReduceOp.SUM).wait(timeout=30)
        np.testing.assert_allclose(summed[1], np.full((2, 2), 4.0))
        with pytest.raises(ValueError, match="SUM/AVG"):
            pg.allreduce(arrays, op=ReduceOp.MAX)
        assert pg.size() == 1
        assert manager.should_commit()
    finally:
        manager.shutdown(wait=False)
        inner.shutdown()
        store.shutdown()
        lighthouse.shutdown()


@pytest.mark.parametrize("world_size", [2, 3])
def test_tcp_ring_allreduce_large_payloads(store_server, world_size) -> None:
    """Arrays >= the ring threshold take the bandwidth-optimal ring path;
    results are exact (SUM/AVG) and bitwise identical on every rank, with
    small arrays mixed into the same call via the root path."""
    pgs = make_group(store_server, world_size)
    big = 1 << 18  # 256k float32 = 1 MiB (>= default ring threshold)
    try:
        rng = np.random.default_rng(0)
        bases = [rng.standard_normal(big).astype(np.float32) for _ in range(world_size)]

        def call(pg, rank):
            arrays = [bases[rank], np.full(3, float(rank + 1), np.float32)]
            return pg.allreduce(arrays, ReduceOp.AVG).wait(60)

        results = run_on_all(pgs, call)
        expected_big = np.mean(bases, axis=0)
        expected_small = np.full(3, np.mean([r + 1 for r in range(world_size)]), np.float32)
        reference_bytes = results[0][0].tobytes()
        for res in results:
            np.testing.assert_allclose(res[0], expected_big, rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(res[1], expected_small, rtol=1e-6)
            # Bitwise identical across ranks (the master invariant).
            assert res[0].tobytes() == reference_bytes

        # SUM and bf16 (f32 accumulation) through the ring.
        def call_sum(pg, rank):
            import ml_dtypes

            arr = np.full(big, 0.5 * (rank + 1), dtype=ml_dtypes.bfloat16)
            return pg.allreduce([arr], ReduceOp.SUM).wait(60)

        sums = run_on_all(pgs, call_sum)
        expected = sum(0.5 * (r + 1) for r in range(world_size))
        for res in sums:
            np.testing.assert_allclose(
                np.asarray(res[0], dtype=np.float32), np.full(big, expected), rtol=1e-2
            )
            assert res[0].tobytes() == sums[0][0].tobytes()
    finally:
        for pg in pgs:
            pg.shutdown()


def test_flight_recorder_captures_collective_ops(store_server) -> None:
    """Real TCP PG ops land in the flight-recorder ring with the
    collective's name (submit + op_done), and abort records a failure."""
    from torchft_tpu.utils import flight_recorder as fr

    pgs = make_group(store_server, 2)
    try:
        prior = fr.snapshot()
        # seq-based cut, not index-based: the global ring may already be at
        # maxlen from earlier tests, where list indices stop advancing.
        last_seq = prior[-1]["seq"] if prior else -1
        run_on_all(
            pgs,
            lambda pg, i: pg.allreduce(
                [np.ones(8, np.float32)], ReduceOp.SUM
            ).wait(),
        )
        events = [e for e in fr.snapshot() if e["seq"] > last_seq]
        ops = [e for e in events if e["source"] == "pg_tcp"]
        assert any(e["event"] == "submit" and e["op"] == "allreduce" for e in ops)
        done = [e for e in ops if e["event"] == "op_done"]
        assert done and all(e["op"] == "allreduce" and e["ms"] >= 0 for e in done)
    finally:
        for pg in pgs:
            pg.shutdown()


def test_emulated_link_paces_and_respects_deadlines(store_server) -> None:
    """The netem shim on the TCP wire: a modest emulated link paces ops
    (lower-bounded by the injected latency — sleeps never undershoot),
    and a link too slow for the payload FAILS AT THE OP DEADLINE instead
    of stalling for the full emulated serialization time."""
    from torchft_tpu.utils import netem

    # Generous configure deadline (mesh setup under suite load on the
    # 1-core box), then a tight OP deadline via set_timeout.
    pgs = make_group(store_server, 2)
    for pg in pgs:
        pg.set_timeout(3.0)
    try:
        # Paced: gather-at-root for a tiny array = at least one proxied
        # message on the critical path; 400 ms RTT -> >= 200 ms injected,
        # well above this box's loopback scheduling noise (a silent no-op
        # netem would finish in tens of ms).
        netem.configure(rtt_ms=400, gbps=1.0)
        t0 = time.monotonic()
        outs = run_on_all(
            pgs,
            lambda pg, i: pg.allreduce([np.ones(4, np.float32)], ReduceOp.SUM).wait(),
        )
        dt = time.monotonic() - t0
        np.testing.assert_array_equal(outs[0][0], np.full(4, 2.0))
        assert dt >= 0.2, f"pacing not applied: {dt}"

        # Absurd link (~1 KB/s) vs a 4 MB payload: the emulated
        # serialization would take ~an hour; the op must fail AT its own
        # 3 s deadline (netem.pace_deadline raises socket.timeout there).
        # The wait(8) backstop must never be what fires — dt < 6 asserts
        # the failure came from the op deadline, not the wait.
        netem.configure(rtt_ms=0, gbps=1e-6)
        t0 = time.monotonic()
        errs = run_on_all(
            pgs,
            lambda pg, i: _expect_wire_failure(pg),
        )
        dt = time.monotonic() - t0
        assert all(errs), errs
        assert dt < 6, f"failed via the wait backstop, not the op deadline: {dt}"
    finally:
        netem.configure(0, 0)
        for pg in pgs:
            pg.shutdown()


def _expect_wire_failure(pg: ProcessGroup) -> str:
    try:
        pg.allreduce([np.ones(1_000_000, np.float32)], ReduceOp.SUM).wait(8)
    except Exception as e:  # noqa: BLE001
        return type(e).__name__
    return ""
