"""Quantization + quantized collective tests: fp8 + int8 (parity targets:
quantization_test.py + collectives_test.py; the dual wire format mirrors
the reference's fp8-on-SM90+/int8-below split) and the beyond-reference
packed int4 wire format (half the bytes, opt-in)."""

from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from test_process_group import fresh_prefix, make_group, run_on_all, store_server  # noqa: F401

from torchft_tpu.ops import quantization as q
from torchft_tpu.parallel.collectives import (
    allreduce_quantized,
    reduce_scatter_quantized,
)
from torchft_tpu.parallel.process_group import ReduceOp


# -- kernels (numpy reference) ------------------------------------------------


@pytest.mark.parametrize("wire", ["fp8", "int8", "int4"])
@pytest.mark.parametrize(
    "shape", [(7,), (256,), (1000,), (33, 17), (4, 4, 4)]
)
def test_quantize_roundtrip_accuracy(shape, wire) -> None:
    rng = np.random.default_rng(0)
    x = rng.normal(size=shape).astype(np.float32) * 10
    payload, scales = q.quantize_blocks(x, wire=wire)
    assert payload.dtype == q._WIRE_NP_DTYPES[wire]
    if wire == "int4":  # two values per byte
        assert payload.shape[1] == q.BLOCK // 2
    restored = q.dequantize_blocks(payload, scales, x.shape, x.dtype)
    if wire in ("int8", "int4"):
        # Round-to-nearest guarantee: error <= scale/2 per element.
        bound = np.max(scales) / 2 * 1.001
        assert float(np.max(np.abs(restored - x))) <= bound
    else:
        # fp8 e4m3 has ~2 decimal digits; blockwise scales keep it low.
        np.testing.assert_allclose(restored, x, rtol=0.07, atol=0.1)


def test_quantize_zero_block() -> None:
    x = np.zeros(512, dtype=np.float32)
    payload, scales = q.quantize_blocks(x)
    restored = q.dequantize_blocks(payload, scales, x.shape, x.dtype)
    np.testing.assert_array_equal(restored, x)


@pytest.mark.parametrize("wire", ["fp8", "int8", "int4"])
def test_reduce_quantized_matches_float_sum(wire) -> None:
    rng = np.random.default_rng(1)
    chunks = [rng.normal(size=(4, q.BLOCK)).astype(np.float32) for _ in range(3)]
    quantized = [q.quantize_blocks(c, wire=wire) for c in chunks]
    out_payload, out_scales = q.reduce_quantized(
        [p for p, _ in quantized], [s for _, s in quantized]
    )
    total = sum(
        q._decode_payload_np(p) * s[:, None] for p, s in quantized
    )
    restored = q._decode_payload_np(out_payload) * out_scales[:, None]
    if wire == "int4":
        # Analytic round-trip bound: one requant at out_scale resolution.
        bound = float(np.max(out_scales)) / 2 * 1.001
        assert float(np.max(np.abs(restored - total))) <= bound
    else:
        np.testing.assert_allclose(restored, total, rtol=0.07, atol=0.1)


@pytest.mark.parametrize("wire", ["fp8", "int8", "int4"])
def test_pack_unpack_roundtrip(wire) -> None:
    rng = np.random.default_rng(2)
    x = rng.normal(size=(5, q.BLOCK)).astype(np.float32)
    payload, scales = q.quantize_blocks(x.reshape(-1), wire=wire)
    buf = q.pack_arrays(payload, scales)
    payload2, scales2 = q.unpack_arrays(buf, payload.shape[0], wire=wire)
    assert payload2.dtype == payload.dtype
    np.testing.assert_array_equal(payload.view(np.uint8), payload2.view(np.uint8))
    np.testing.assert_array_equal(scales, scales2)


# -- pallas kernels (interpret mode on CPU) -----------------------------------


@pytest.mark.parametrize("wire", ["fp8", "int8"])
@pytest.mark.parametrize("n_blocks", [8, 1500])
def test_pallas_quantize_matches_numpy(wire, n_blocks) -> None:
    # n_blocks=8 is a single whole-dim tile; 1500 forces the ragged
    # 1024-row grid (partial final tile) the retiled kernels use for
    # arbitrary gradient sizes -- numeric proof that padded rows never
    # bleed into real rows' scales/payload (the lowering gate only proves
    # the shape compiles).
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    x = rng.normal(size=(n_blocks, q.BLOCK)).astype(np.float32) * 5
    payload_np, scales_np = q.quantize_blocks(x.reshape(-1), wire=wire)
    payload_pl, scales_pl = q.quantize_blocks_pallas(
        jnp.asarray(x), interpret=True, wire=wire
    )
    np.testing.assert_allclose(scales_pl, scales_np, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(payload_pl).astype(np.float32),
        payload_np.astype(np.float32),
        atol=1e-6,
    )
    restored = q.dequantize_blocks_pallas(payload_pl, scales_pl, interpret=True)
    np.testing.assert_allclose(np.asarray(restored), x, rtol=0.07, atol=0.1)


# -- quantized collectives over a real PG -------------------------------------


@pytest.mark.parametrize("world_size", [2, 4])
def test_allreduce_quantized_sum_avg(store_server, world_size) -> None:
    pgs = make_group(store_server, world_size)
    rng = np.random.default_rng(4)
    inputs = [
        [rng.normal(size=(40, 13)).astype(np.float32), rng.normal(size=300).astype(np.float32)]
        for _ in range(world_size)
    ]
    try:
        for op in (ReduceOp.SUM, ReduceOp.AVG):
            results = run_on_all(
                pgs, lambda pg, i: allreduce_quantized(inputs[i], op, pg).wait()
            )
            expected = [
                sum(inputs[r][idx] for r in range(world_size)) for idx in range(2)
            ]
            if op == ReduceOp.AVG:
                expected = [e / world_size for e in expected]
            for r in results:
                for idx in range(2):
                    assert r[idx].shape == expected[idx].shape
                    assert r[idx].dtype == expected[idx].dtype
                    # Two quantization passes: tolerance ~ 2x single pass.
                    np.testing.assert_allclose(
                        r[idx], expected[idx], rtol=0.2, atol=0.3
                    )
            # Bitwise identical across ranks.
            for idx in range(2):
                assert all(
                    r[idx].tobytes() == results[0][idx].tobytes() for r in results
                )
    finally:
        for pg in pgs:
            pg.shutdown()


def test_reduce_scatter_quantized(store_server) -> None:
    pgs = make_group(store_server, 2)
    rng = np.random.default_rng(5)
    inputs = [[rng.normal(size=1024).astype(np.float32)] for _ in range(2)]
    try:
        results = run_on_all(
            pgs,
            lambda pg, i: reduce_scatter_quantized(inputs[i], ReduceOp.SUM, pg).wait(),
        )
        total = inputs[0][0] + inputs[1][0]
        blocks = total.reshape(-1, q.BLOCK)
        # rank 0 gets blocks [0:2], rank 1 gets [2:4]
        for rank, result in enumerate(results):
            expected = blocks[rank * 2 : (rank + 1) * 2].reshape(-1)
            np.testing.assert_allclose(result[0], expected, rtol=0.2, atol=0.3)
    finally:
        for pg in pgs:
            pg.shutdown()


def test_manager_allreduce_quantized_path() -> None:
    """manager.allreduce(should_quantize=True) routes through the fp8 path."""
    from test_manager import make_manager, make_quorum
    from torchft_tpu.parallel.process_group import ProcessGroupDummy

    manager, client, _, _ = make_manager(pg=ProcessGroupDummy(), min_replica_size=1)
    client._quorum.return_value = make_quorum(replica_world_size=1, max_world_size=1)
    manager.start_quorum()
    x = np.linspace(-3, 3, 512, dtype=np.float32)
    out = manager.allreduce(x, should_quantize=True).wait()
    np.testing.assert_allclose(out, x, rtol=0.1, atol=0.1)


# -- int8 wire format (reference parity: fp8 on SM90+, int8 below) -----------


def test_default_wire_env(monkeypatch) -> None:
    monkeypatch.delenv(q.WIRE_DTYPE_ENV, raising=False)
    assert q.default_wire() == "fp8"
    monkeypatch.setenv(q.WIRE_DTYPE_ENV, "int8")
    assert q.default_wire() == "int8"
    payload, _ = q.quantize_blocks(np.ones(16, np.float32))
    assert payload.dtype == np.int8
    monkeypatch.setenv(q.WIRE_DTYPE_ENV, "fp4")
    with pytest.raises(ValueError, match="fp4"):
        q.default_wire()


def test_wire_of() -> None:
    assert q.wire_of(np.zeros(4, np.int8)) == "int8"
    assert q.wire_of(np.zeros(4, q._FP8)) == "fp8"
    assert q.wire_of(np.zeros(4, np.uint8)) == "int4"
    with pytest.raises(TypeError):
        q.wire_of(np.zeros(4, np.float32))


def test_allreduce_quantized_int8_wire(store_server) -> None:
    from torchft_tpu.parallel.collectives import allreduce_quantized

    pgs = make_group(store_server, 2)
    rng = np.random.default_rng(6)
    inputs = [[rng.normal(size=512).astype(np.float32)] for _ in range(2)]
    try:
        results = run_on_all(
            pgs,
            lambda pg, i: allreduce_quantized(
                inputs[i], ReduceOp.AVG, pg, wire_dtype="int8"
            ).wait(),
        )
        expected = (inputs[0][0] + inputs[1][0]) / 2
        for r in results:
            np.testing.assert_allclose(r[0], expected, rtol=0.1, atol=0.15)
        assert results[0][0].tobytes() == results[1][0].tobytes()
    finally:
        for pg in pgs:
            pg.shutdown()


def test_device_codec_int8_through_wire_allreduce(store_server) -> None:
    """A device codec built with wire='int8' flows through
    allreduce_quantized_wire end to end — the wire format is read from the
    payload dtype, not the env."""
    import jax.numpy as jnp

    from torchft_tpu.ops.quantization import make_tree_fp8_codec
    from torchft_tpu.parallel.collectives import allreduce_quantized_wire

    leaves = [jnp.linspace(-2, 2, 300, dtype=jnp.float32).reshape(30, 10)]
    quantize, dequantize = make_tree_fp8_codec(leaves, wire="int8")
    payload, scales = quantize(leaves)
    assert np.asarray(payload).dtype == np.int8

    pgs = make_group(store_server, 2)
    try:
        results = run_on_all(
            pgs,
            lambda pg, i: allreduce_quantized_wire(
                payload, scales, ReduceOp.AVG, pg
            ).wait(),
        )
        for out_payload, out_scales in results:
            assert out_payload.dtype == np.int8
            restored = dequantize(
                jnp.asarray(out_payload), jnp.asarray(out_scales)
            )
            np.testing.assert_allclose(
                np.asarray(restored[0]), np.asarray(leaves[0]), rtol=0.05, atol=0.05
            )
    finally:
        for pg in pgs:
            pg.shutdown()


def test_unpack_rejects_cross_format_buffer() -> None:
    """A peer that quantized with a different TPUFT_WIRE_DTYPE must be a
    hard error at decode, never a silent bit reinterpretation."""
    x = np.linspace(-1, 1, q.BLOCK, dtype=np.float32)
    payload, scales = q.quantize_blocks(x, wire="fp8")
    buf = q.pack_arrays(payload, scales)
    with pytest.raises(ValueError, match="wire format mismatch"):
        q.unpack_arrays(buf, payload.shape[0], wire="int8")
    with pytest.raises(ValueError, match="unknown wire format tag"):
        q.unpack_arrays(np.full(64, 255, np.uint8), 0)


def test_int4_pack_unpack_exact() -> None:
    """Nibble packing is lossless over the full [-7, 7] code space."""
    vals = np.tile(np.arange(-7, 8, dtype=np.int8), 35)[: 2 * q.BLOCK].reshape(
        2, q.BLOCK
    )
    packed = q._pack_int4_np(vals)
    assert packed.shape == (2, q.BLOCK // 2) and packed.dtype == np.uint8
    np.testing.assert_array_equal(q._unpack_int4_np(packed), vals)


def test_allreduce_quantized_int4_wire(store_server) -> None:
    """End-to-end int4 allreduce: half the wire bytes of int8, bitwise
    agreement across ranks, error within the 4-bit analytic bound."""
    from torchft_tpu.parallel.collectives import allreduce_quantized

    pgs = make_group(store_server, 2)
    rng = np.random.default_rng(7)
    inputs = [[rng.normal(size=512).astype(np.float32)] for _ in range(2)]
    p8, s8 = q.quantize_blocks(inputs[0][0], wire="int8")
    p4, s4 = q.quantize_blocks(inputs[0][0], wire="int4")
    assert p4.nbytes * 2 == p8.nbytes
    try:
        results = run_on_all(
            pgs,
            lambda pg, i: allreduce_quantized(
                inputs[i], ReduceOp.AVG, pg, wire_dtype="int4"
            ).wait(),
        )
        expected = (inputs[0][0] + inputs[1][0]) / 2
        # Per-element bound: input rounding (scale_i/2 each, averaged) +
        # the requant of the reduced chunk.
        bound = (float(np.max(s4)) + float(np.max(s4))) / 2 / 2 + float(
            np.max(s4)
        )
        for r in results:
            assert float(np.max(np.abs(r[0] - expected))) <= bound
        assert results[0][0].tobytes() == results[1][0].tobytes()
    finally:
        for pg in pgs:
            pg.shutdown()


def test_device_codec_int4_roundtrip_and_host_compat() -> None:
    """The jnp int4 device codec round-trips within the analytic bound and
    its packed payload decodes identically through the HOST kernels (one
    wire format across device/host paths)."""
    import jax.numpy as jnp

    from torchft_tpu.ops.quantization import (
        dequantize_blocks_device,
        make_tree_fp8_codec,
    )

    rng = np.random.default_rng(8)
    leaves = [
        rng.normal(size=(37, 11)).astype(np.float32),
        rng.normal(size=600).astype(np.float32) * 5,
    ]
    quantize, dequantize = make_tree_fp8_codec(
        [jnp.asarray(l) for l in leaves], wire="int4"
    )
    payload, scales = quantize([jnp.asarray(l) for l in leaves])
    assert np.dtype(payload.dtype) == np.uint8
    restored = dequantize(payload, scales)
    bound = float(np.max(np.asarray(scales))) / 2 * 1.001
    flat_in = np.concatenate([l.reshape(-1) for l in leaves])
    flat_out = np.concatenate([np.asarray(r).reshape(-1) for r in restored])
    assert float(np.max(np.abs(flat_out - flat_in))) <= bound

    # Host-side decode of the device payload matches the device decode.
    host = q.dequantize_blocks(
        np.asarray(payload), np.asarray(scales, dtype=np.float32),
        (flat_in.size,), np.float32,
    )
    dev = np.asarray(dequantize_blocks_device(payload, scales))[: flat_in.size]
    np.testing.assert_allclose(host, dev, rtol=0, atol=1e-7)


def test_device_codec_int4_through_wire_allreduce(store_server) -> None:
    """The packed-int4 device codec flows through allreduce_quantized_wire
    end to end (format read from the uint8 payload dtype)."""
    import jax.numpy as jnp

    from torchft_tpu.ops.quantization import make_tree_fp8_codec
    from torchft_tpu.parallel.collectives import allreduce_quantized_wire

    leaves = [jnp.linspace(-2, 2, 300, dtype=jnp.float32).reshape(30, 10)]
    quantize, dequantize = make_tree_fp8_codec(leaves, wire="int4")
    payload, scales = quantize(leaves)
    assert np.asarray(payload).dtype == np.uint8

    pgs = make_group(store_server, 2)
    try:
        results = run_on_all(
            pgs,
            lambda pg, i: allreduce_quantized_wire(
                payload, scales, ReduceOp.AVG, pg
            ).wait(),
        )
        for out_payload, out_scales in results:
            assert out_payload.dtype == np.uint8
            restored = dequantize(
                jnp.asarray(out_payload), jnp.asarray(out_scales)
            )
            # Both ranks contributed the identical tensor, so AVG is the
            # tensor itself up to two 4-bit roundings.
            bound = 2.0 * float(np.max(np.asarray(scales)))
            assert (
                float(np.max(np.abs(np.asarray(restored[0]) - np.asarray(leaves[0]))))
                <= bound
            )
        assert results[0][0].tobytes() == results[1][0].tobytes()
    finally:
        for pg in pgs:
            pg.shutdown()
