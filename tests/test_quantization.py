"""fp8 quantization + quantized collective tests (parity targets:
quantization_test.py + collectives_test.py)."""

from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from test_process_group import fresh_prefix, make_group, run_on_all, store_server  # noqa: F401

from torchft_tpu.ops import quantization as q
from torchft_tpu.parallel.collectives import (
    allreduce_quantized,
    reduce_scatter_quantized,
)
from torchft_tpu.parallel.process_group import ReduceOp


# -- kernels (numpy reference) ------------------------------------------------


@pytest.mark.parametrize(
    "shape", [(7,), (256,), (1000,), (33, 17), (4, 4, 4)]
)
def test_quantize_roundtrip_accuracy(shape) -> None:
    rng = np.random.default_rng(0)
    x = rng.normal(size=shape).astype(np.float32) * 10
    payload, scales = q.quantize_blocks(x)
    restored = q.dequantize_blocks(payload, scales, x.shape, x.dtype)
    # fp8 e4m3 has ~2 decimal digits; blockwise scales keep relative error low.
    np.testing.assert_allclose(restored, x, rtol=0.07, atol=0.1)


def test_quantize_zero_block() -> None:
    x = np.zeros(512, dtype=np.float32)
    payload, scales = q.quantize_blocks(x)
    restored = q.dequantize_blocks(payload, scales, x.shape, x.dtype)
    np.testing.assert_array_equal(restored, x)


def test_reduce_quantized_matches_float_sum() -> None:
    rng = np.random.default_rng(1)
    chunks = [rng.normal(size=(4, q.BLOCK)).astype(np.float32) for _ in range(3)]
    quantized = [q.quantize_blocks(c) for c in chunks]
    out_payload, out_scales = q.reduce_quantized(
        [p for p, _ in quantized], [s for _, s in quantized]
    )
    total = sum(
        p.astype(np.float32) * s[:, None] for p, s in quantized
    )
    restored = out_payload.astype(np.float32) * out_scales[:, None]
    np.testing.assert_allclose(restored, total, rtol=0.07, atol=0.1)


def test_pack_unpack_roundtrip() -> None:
    rng = np.random.default_rng(2)
    x = rng.normal(size=(5, q.BLOCK)).astype(np.float32)
    payload, scales = q.quantize_blocks(x.reshape(-1))
    buf = q.pack_arrays(payload, scales)
    payload2, scales2 = q.unpack_arrays(buf, payload.shape[0])
    np.testing.assert_array_equal(payload.view(np.uint8), payload2.view(np.uint8))
    np.testing.assert_array_equal(scales, scales2)


# -- pallas kernels (interpret mode on CPU) -----------------------------------


def test_pallas_quantize_matches_numpy() -> None:
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    x = rng.normal(size=(8, q.BLOCK)).astype(np.float32) * 5
    payload_np, scales_np = q.quantize_blocks(x.reshape(-1))
    payload_pl, scales_pl = q.quantize_blocks_pallas(jnp.asarray(x), interpret=True)
    np.testing.assert_allclose(scales_pl, scales_np, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(payload_pl).astype(np.float32),
        payload_np.astype(np.float32),
        atol=1e-6,
    )
    restored = q.dequantize_blocks_pallas(payload_pl, scales_pl, interpret=True)
    np.testing.assert_allclose(np.asarray(restored), x, rtol=0.07, atol=0.1)


# -- quantized collectives over a real PG -------------------------------------


@pytest.mark.parametrize("world_size", [2, 4])
def test_allreduce_quantized_sum_avg(store_server, world_size) -> None:
    pgs = make_group(store_server, world_size)
    rng = np.random.default_rng(4)
    inputs = [
        [rng.normal(size=(40, 13)).astype(np.float32), rng.normal(size=300).astype(np.float32)]
        for _ in range(world_size)
    ]
    try:
        for op in (ReduceOp.SUM, ReduceOp.AVG):
            results = run_on_all(
                pgs, lambda pg, i: allreduce_quantized(inputs[i], op, pg).wait()
            )
            expected = [
                sum(inputs[r][idx] for r in range(world_size)) for idx in range(2)
            ]
            if op == ReduceOp.AVG:
                expected = [e / world_size for e in expected]
            for r in results:
                for idx in range(2):
                    assert r[idx].shape == expected[idx].shape
                    assert r[idx].dtype == expected[idx].dtype
                    # Two quantization passes: tolerance ~ 2x single pass.
                    np.testing.assert_allclose(
                        r[idx], expected[idx], rtol=0.2, atol=0.3
                    )
            # Bitwise identical across ranks.
            for idx in range(2):
                assert all(
                    r[idx].tobytes() == results[0][idx].tobytes() for r in results
                )
    finally:
        for pg in pgs:
            pg.shutdown()


def test_reduce_scatter_quantized(store_server) -> None:
    pgs = make_group(store_server, 2)
    rng = np.random.default_rng(5)
    inputs = [[rng.normal(size=1024).astype(np.float32)] for _ in range(2)]
    try:
        results = run_on_all(
            pgs,
            lambda pg, i: reduce_scatter_quantized(inputs[i], ReduceOp.SUM, pg).wait(),
        )
        total = inputs[0][0] + inputs[1][0]
        blocks = total.reshape(-1, q.BLOCK)
        # rank 0 gets blocks [0:2], rank 1 gets [2:4]
        for rank, result in enumerate(results):
            expected = blocks[rank * 2 : (rank + 1) * 2].reshape(-1)
            np.testing.assert_allclose(result[0], expected, rtol=0.2, atol=0.3)
    finally:
        for pg in pgs:
            pg.shutdown()


def test_manager_allreduce_quantized_path() -> None:
    """manager.allreduce(should_quantize=True) routes through the fp8 path."""
    from test_manager import make_manager, make_quorum
    from torchft_tpu.parallel.process_group import ProcessGroupDummy

    manager, client, _, _ = make_manager(pg=ProcessGroupDummy(), min_replica_size=1)
    client._quorum.return_value = make_quorum(replica_world_size=1, max_world_size=1)
    manager.start_quorum()
    x = np.linspace(-3, 3, 512, dtype=np.float32)
    out = manager.allreduce(x, should_quantize=True).wait()
    np.testing.assert_allclose(out, x, rtol=0.1, atol=0.1)
