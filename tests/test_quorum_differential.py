"""Differential (randomized property) tests of the native quorum logic.

The native plane's two pure functions — ``quorum_compute`` and
``compute_quorum_results`` (native/src/quorum.cc) — carry the whole
coordination contract (reference lighthouse.rs:141-269, manager.rs:489-624).
The example-based ports of the reference's Rust unit tests live in
native/tests/quorum_test.cc; this file adds a second, independent layer:
a Python oracle implementing the documented contract, compared against the
C++ implementation over thousands of randomized cluster states. Any
divergence — crash, membership difference, recovery-plan difference — is a
contract bug in one of the two.
"""

from __future__ import annotations

import random

import pytest

from torchft_tpu.coordination import (
    Quorum,
    QuorumMember,
    SimParticipant,
    compute_quorum_results_sim,
    quorum_compute_sim,
)

# ---------------------------------------------------------------------------
# Oracles: written from the documented contract (SURVEY.md §2.1), not from
# the C++ code, so the two implementations are genuinely independent.
# ---------------------------------------------------------------------------


def oracle_quorum_compute(
    parts: list[SimParticipant],
    prev: Quorum | None,
    min_replicas: int,
    join_timeout_ms: int,
    heartbeat_timeout_ms: int,
) -> list[str] | None:
    """Returns the sorted replica_id list of the quorum, or None."""
    healthy = {
        p.member.replica_id
        for p in parts
        if p.heartbeat_age_ms < heartbeat_timeout_ms
    }
    joined = sorted(
        (p for p in parts if not p.heartbeat_only and p.member.replica_id in healthy),
        key=lambda p: p.member.replica_id,
    )
    candidates = list(joined)
    shrink_only = any(p.member.shrink_only for p in joined)

    if prev is not None:
        prev_ids = {m.replica_id for m in prev.participants}
        if shrink_only:
            candidates = [
                p for p in candidates if p.member.replica_id in prev_ids
            ]
        # Fast quorum: every previous member is healthy AND participating.
        joined_ids = {p.member.replica_id for p in joined}
        if prev_ids <= joined_ids:
            return [p.member.replica_id for p in candidates]

    if len(joined) < min_replicas:
        return None
    # Split-brain guard: strict majority of everything heartbeating.
    if len(joined) <= len(healthy) // 2:
        return None
    # Straggler wait: healthy non-participants get join_timeout_ms, measured
    # from the earliest participant join.
    if len(joined) < len(healthy):
        oldest_join_age = max((p.joined_age_ms for p in joined), default=0)
        if oldest_join_age < join_timeout_ms:
            return None
    return [p.member.replica_id for p in candidates]


def oracle_quorum_results(
    replica_id: str, group_rank: int, quorum: Quorum, init_sync: bool
) -> dict | None:
    members = sorted(quorum.participants, key=lambda m: m.replica_id)
    ids = [m.replica_id for m in members]
    if replica_id not in ids:
        return None
    replica_rank = ids.index(replica_id)

    max_step = max([m.step for m in members] + [0])
    max_cohort = [i for i, m in enumerate(members) if m.step == max_step]
    max_rank = None
    for j, i in enumerate(max_cohort):
        if members[i].replica_id == replica_id:
            max_rank = j
            break
    primary = members[max_cohort[group_rank % len(max_cohort)]]

    force_recover = init_sync and max_step == 0
    recover_dst = [
        i
        for i, m in enumerate(members)
        if m.step != max_step
        or (force_recover and m.replica_id != primary.replica_id)
    ]
    up_to_date = [i for i in range(len(members)) if i not in recover_dst]

    src_of: dict[int, int] = {}
    for j, dst in enumerate(recover_dst):
        src_of[dst] = up_to_date[(j + group_rank) % len(up_to_date)]
    my_src = src_of.get(replica_rank)
    my_dsts = sorted(d for d, s in src_of.items() if s == replica_rank)

    return {
        "replica_rank": replica_rank,
        "replica_world_size": len(members),
        "store_address": primary.store_address,
        "max_step": max_step,
        "max_rank": max_rank,
        "max_world_size": len(max_cohort),
        "heal": my_src is not None,
        "recover_src_replica_rank": my_src,
        "recover_src_manager_address": (
            members[my_src].address if my_src is not None else ""
        ),
        "recover_dst_replica_ranks": my_dsts,
        "commit_failures": max((m.commit_failures for m in members), default=0),
    }


# ---------------------------------------------------------------------------
# Randomized comparison
# ---------------------------------------------------------------------------


def _member(i: int, rng: random.Random) -> QuorumMember:
    return QuorumMember(
        replica_id=f"rep{i}",
        address=f"addr{i}:1",
        store_address=f"store{i}:2",
        step=rng.choice([0, 0, 1, 2, 5]),
        world_size=rng.choice([1, 2, 4]),
        shrink_only=rng.random() < 0.15,
        commit_failures=rng.choice([0, 0, 0, 1, 3]),
    )


@pytest.mark.parametrize("seed", range(8))
def test_quorum_compute_matches_oracle(seed):
    rng = random.Random(1000 + seed)
    hb_timeout = 5000
    for case in range(300):
        n = rng.randint(0, 6)
        parts = []
        for i in range(n):
            parts.append(
                SimParticipant(
                    member=_member(i, rng),
                    joined_age_ms=rng.choice([0, 10, 500, 5000, 70000, 120000]),
                    heartbeat_age_ms=rng.choice([0, 10, 4999, 5000, 9000]),
                    heartbeat_only=rng.random() < 0.25,
                )
            )
        prev = None
        if n and rng.random() < 0.5:
            prev_members = [
                p.member for p in parts if rng.random() < 0.6
            ]
            prev = Quorum(quorum_id=rng.randint(1, 9), participants=prev_members)
        min_replicas = rng.randint(1, 3)
        join_timeout = rng.choice([0, 1000, 60000])

        got_members, reason = quorum_compute_sim(
            parts,
            prev_quorum=prev,
            min_replicas=min_replicas,
            join_timeout_ms=join_timeout,
            heartbeat_timeout_ms=hb_timeout,
        )
        want = oracle_quorum_compute(
            parts, prev, min_replicas, join_timeout, hb_timeout
        )
        got = None if got_members is None else [m.replica_id for m in got_members]
        assert got == want, (
            f"case {case}: native={got} oracle={want} reason={reason!r} "
            f"parts={[(p.member.replica_id, p.joined_age_ms, p.heartbeat_age_ms, p.heartbeat_only, p.member.shrink_only) for p in parts]} "
            f"prev={None if prev is None else [m.replica_id for m in prev.participants]} "
            f"min={min_replicas} join_t={join_timeout}"
        )


@pytest.mark.parametrize("seed", range(8))
def test_compute_quorum_results_matches_oracle(seed):
    rng = random.Random(2000 + seed)
    for case in range(200):
        n = rng.randint(1, 6)
        members = [_member(i, rng) for i in range(n)]
        rng.shuffle(members)  # input order must not matter
        quorum = Quorum(quorum_id=rng.randint(1, 9), participants=members)
        group_rank = rng.randint(0, 3)
        init_sync = rng.random() < 0.7
        for m in members:
            want = oracle_quorum_results(
                m.replica_id, group_rank, quorum, init_sync
            )
            got = compute_quorum_results_sim(
                m.replica_id, group_rank, quorum, init_sync=init_sync
            )
            got_dict = {
                "replica_rank": got.replica_rank,
                "replica_world_size": got.replica_world_size,
                "store_address": got.store_address,
                "max_step": got.max_step,
                "max_rank": got.max_rank,
                "max_world_size": got.max_world_size,
                "heal": got.heal,
                "recover_src_replica_rank": got.recover_src_replica_rank,
                "recover_src_manager_address": got.recover_src_manager_address,
                "recover_dst_replica_ranks": got.recover_dst_replica_ranks,
                "commit_failures": got.commit_failures,
            }
            assert got_dict == want, (
                f"case {case} replica {m.replica_id} group_rank {group_rank} "
                f"init_sync {init_sync}: native={got_dict} oracle={want} "
                f"members={[(x.replica_id, x.step) for x in members]}"
            )
        # Outside member raises (matched so parse/buffer errors can't hide).
        with pytest.raises(RuntimeError, match="not participating"):
            compute_quorum_results_sim("ghost", group_rank, quorum)


def test_quorum_rejoin_after_shrink_then_grow():
    """Directed sequence: shrink-only drops a member, then (flag cleared) the
    join-timeout path readmits it — the membership timeline the lighthouse
    walks during a downscale+upscale drill, here as pure decisions."""
    m = lambda i, shrink=False: QuorumMember(
        replica_id=f"rep{i}", address=f"a{i}", store_address=f"s{i}",
        shrink_only=shrink,
    )
    full = Quorum(quorum_id=1, participants=[m(0), m(1), m(2)])
    # rep2 stops participating; rep0 sets shrink_only: candidates restricted.
    parts = [SimParticipant(m(0, shrink=True)), SimParticipant(m(1)),
             SimParticipant(m(2), heartbeat_only=True, joined_age_ms=0)]
    got, _ = quorum_compute_sim(
        parts, prev_quorum=full, min_replicas=1, join_timeout_ms=60000
    )
    # Fast path: all prev members still heartbeat... rep2 is healthy but not
    # participating -> NOT a fast quorum; straggler wait applies.
    assert got is None
    # After the join timeout expires, the shrunk quorum forms without rep2.
    parts_late = [
        SimParticipant(m(0, shrink=True), joined_age_ms=70000),
        SimParticipant(m(1), joined_age_ms=70000),
        SimParticipant(m(2), heartbeat_only=True),
    ]
    got, _ = quorum_compute_sim(
        parts_late, prev_quorum=full, min_replicas=1, join_timeout_ms=60000
    )
    assert [x.replica_id for x in got] == ["rep0", "rep1"]
    # rep2 re-requests against the shrunk prev quorum (shrink flag cleared):
    # fast quorum for prev members is irrelevant (rep2 new) -> grows via the
    # normal path once every healthy replica participates.
    shrunk = Quorum(quorum_id=2, participants=[m(0), m(1)])
    parts_regrow = [
        SimParticipant(m(0)), SimParticipant(m(1)), SimParticipant(m(2)),
    ]
    got, _ = quorum_compute_sim(
        parts_regrow, prev_quorum=shrunk, min_replicas=1, join_timeout_ms=60000
    )
    assert [x.replica_id for x in got] == ["rep0", "rep1", "rep2"]
