"""Mass-rejoin storm drills (pure Python — tier-1 in a toolchain-less
container):

- coordinated stripe plan: the storm rotation is a pure function of
  (joiner ordinal, group rank, quorum id); rotated plans stay complete,
  deterministic, and byte-balanced while seeding at different donors;
- ZeRO shard parts stripe like any other dedicated CRC'd chunk when the
  heal policy is ``fetch`` (byte-balanced assignment pinned);
- joiner ingress bound (``TPUFT_HEAL_INGRESS_GBPS``): a token bucket
  shared by every stripe worker of one heal attempt, whose injected
  sleep is credited back to the minimum-progress watchdog — self-pacing
  never fences a healthy donor;
- manager plumbing: concurrent joiners derive DISTINCT rotations from
  the same quorum view and hand them to ``recv_checkpoint``;
- punisher ``kill_half_fleet``: status-targeted, floor(n/2) victims,
  always >= 1 survivor;
- the flagship storm drill, threads-as-replicas over loopback HTTP in
  strict AND pipelined commit orderings: three stale rejoiners heal
  SIMULTANEOUSLY from the same two-donor set — every joiner lands
  bitwise identical, zero heal exhaustions, zero checksum failures,
  zero era rejects, and the whole default-run drill finishes inside the
  tier-1 budget (< 60 s wall, gated on observed state, never sleeps);
- ``--explain-step`` prints the per-joiner storm table when more than
  one joiner healed in the same era.
"""

import importlib.util
import random
import threading
import time
from pathlib import Path
from unittest.mock import MagicMock

import numpy as np
import pytest

from test_checkpointing import assert_state_equal
from test_heal_striping import (
    committed_state_dict,
    member,
    patched_manager_client,
    stripe_counters,
    wide_state,
)
from test_manager import make_manager, make_quorum
from torchft_tpu import metrics
from torchft_tpu.checkpointing import HTTPTransport
from torchft_tpu.checkpointing import http_transport as ht
from torchft_tpu.checkpointing.transport import HEAL_PART_PREFIX
from torchft_tpu.coordination import Quorum
from torchft_tpu.manager import storm_stripe_rotation
from torchft_tpu.parallel.process_group import ProcessGroupDummy
from torchft_tpu.punisher import kill_half_fleet


def storm_counters() -> dict:
    base = stripe_counters()
    base.update(
        {
            "ingress_paced_s": metrics.counter_total(
                "tpuft_heal_ingress_paced_seconds_total"
            ),
            "ingress_bytes": metrics.counter_total(
                "tpuft_heal_ingress_bytes_total"
            ),
            "heal_exhausted": metrics.counter_total(
                "tpuft_trace_incidents_total", kind="heal_exhausted"
            ),
        }
    )
    return base


# ---------------------------------------------------------------------------
# coordinated stripe plan (pure functions)
# ---------------------------------------------------------------------------


def test_storm_rotation_is_pure_and_distinct_per_joiner() -> None:
    joiners = ["grp2:u2", "grp0:u0", "grp5:u5"]
    rotations = {
        rid: storm_stripe_rotation(rid, joiners, group_rank=1, quorum_id=4)
        for rid in joiners
    }
    # Ordinals follow the SORTED id list, so every observer agrees.
    assert rotations == {"grp0:u0": 5, "grp2:u2": 6, "grp5:u5": 7}
    # Deterministic: same inputs, same answer — no negotiation anywhere.
    assert rotations["grp0:u0"] == storm_stripe_rotation(
        "grp0:u0", joiners, 1, 4
    )
    # A non-joiner (or lone joiner) degrades to (group rank + quorum id).
    assert storm_stripe_rotation("other:u", joiners, 1, 4) == 5
    assert storm_stripe_rotation("solo:u", ["solo:u"], 0, 7) == 7


def test_plan_stripes_rotation_seeds_different_donors() -> None:
    chunks = list(range(8))
    sizes = [100] * 8  # equal sizes: ties expose the rotation directly
    plan0 = ht._plan_stripes(chunks, sizes, 2, rotation=0)
    plan1 = ht._plan_stripes(chunks, sizes, 2, rotation=1)
    assert plan0 == [[0, 2, 4, 6], [1, 3, 5, 7]]
    assert plan1 == [[1, 3, 5, 7], [0, 2, 4, 6]]  # seeded at donor 1
    # Rotation wraps: a full cycle is the identity plan.
    assert ht._plan_stripes(chunks, sizes, 2, rotation=2) == plan0
    # Rotated plans keep every PR-8 property: complete, deterministic,
    # byte-balanced (LPT bound).
    uneven = [10, 80, 20, 70, 30, 60, 40, 50, 90]
    for rotation in range(4):
        a = ht._plan_stripes(list(range(9)), uneven, 3, rotation=rotation)
        assert a == ht._plan_stripes(list(range(9)), uneven, 3, rotation=rotation)
        assert sorted(i for s in a for i in s) == list(range(9))
        loads = [sum(uneven[i] for i in s) for s in a]
        assert max(loads) - min(loads) <= max(uneven)


def test_plan_stripes_rotation_round_robin_without_sizes() -> None:
    assert ht._plan_stripes([0, 1, 2, 3, 4, 5], None, 3, rotation=1) == [
        [2, 5],
        [0, 3],
        [1, 4],
    ]


# ---------------------------------------------------------------------------
# ZeRO shard parts inside the stripe plan (fetch mode)
# ---------------------------------------------------------------------------


def test_zero_shard_parts_stripe_byte_balanced() -> None:
    """``heal_part:zero_shard_*`` chunks are dedicated CRC'd chunks; in
    ``TPUFT_ZERO_HEAL_SHARDS=fetch`` mode (no skip_parts) they enter
    ``_plan_stripes`` like any other chunk — pinned here byte-balanced
    across the donor set, not lumped onto one donor."""
    state = wide_state(n_leaves=4, leaf_kb=64)
    for shard in range(4):
        state[f"{HEAL_PART_PREFIX}zero_shard_{shard}"] = {
            "m": np.full(64 * 256, float(shard), dtype=np.float32)
        }
    treedef, chunk_dicts, parts = ht._plan_chunks(state, 4)
    assert len(parts) == 4 and len(chunk_dicts) == 8
    prepared = [ht._serialization.prepare(c) for c in chunk_dicts]
    sizes = [int(p.total_size) for p in prepared]
    plan = ht._plan_stripes(list(range(8)), sizes, 2)
    part_chunks = set(parts.values())
    # Part chunks appear in the plan (complete) and are split across the
    # donors, byte-balanced within the LPT bound.
    assert sorted(i for s in plan for i in s) == list(range(8))
    per_donor_parts = [len(part_chunks & set(s)) for s in plan]
    assert all(n >= 1 for n in per_donor_parts)
    loads = [sum(sizes[i] for i in s) for s in plan]
    assert max(loads) - min(loads) <= max(sizes)


def test_zero_shard_parts_fetched_striped_across_donors() -> None:
    """Transport-level fetch-mode drill: with no skip_parts, shard parts
    ride the striped fetch and land bitwise identical."""
    state = wide_state(n_leaves=4, leaf_kb=64)
    state[f"{HEAL_PART_PREFIX}zero_shard_0"] = {
        "m": np.full(4096, 3.0, dtype=np.float32)
    }
    state[f"{HEAL_PART_PREFIX}zero_shard_1"] = {
        "m": np.full(4096, 4.0, dtype=np.float32)
    }
    donors = [HTTPTransport(num_chunks=4) for _ in range(2)]
    joiner = HTTPTransport()
    try:
        for d in donors:
            d.send_checkpoint([1], step=5, state_dict=state, timeout=10,
                              quorum_id=7)
        before = storm_counters()
        out = joiner.recv_checkpoint(
            0,
            donors[0].metadata(),
            5,
            timeout=10,
            quorum_id=7,
            donors=[donors[1].metadata()],
        )
        after = storm_counters()
        assert_state_equal(state, out)  # parts included, bitwise
        # All 6 chunks (4 base + 2 parts) rode the stripe path.
        assert after["stripe_chunks"] - before["stripe_chunks"] == 6
        assert after["checksum"] - before["checksum"] == 0
    finally:
        for d in donors:
            d.shutdown()
        joiner.shutdown()


# ---------------------------------------------------------------------------
# joiner ingress bound
# ---------------------------------------------------------------------------


def test_ingress_pacer_is_shared_across_streams() -> None:
    pacer = ht._IngressPacer(8.0)  # 1 GB/s
    d1 = pacer.debit(1 << 20)
    d2 = pacer.debit(1 << 20)
    # The second debit queues behind the first — one bucket, not one per
    # stream (striping across N donors must not multiply the bound).
    assert d2 > d1 >= 0.0
    assert 0.0015 <= d2 <= 0.01, d2


def test_ingress_bound_paces_without_tripping_watchdog(monkeypatch) -> None:
    """A joiner bounded BELOW the watchdog floor must still heal: the
    pacer's injected sleep is credited back to the progress window, so
    the floor judges donor throughput, not our own throttle. Without the
    credit, 6 parallel chunk streams sharing 1 MB/s against a 2 MB/s
    floor would fence every (healthy) donor."""
    monkeypatch.setenv(ht.ENV_HEAL_INGRESS, "0.008")  # 1 MB/s aggregate
    monkeypatch.setenv(ht.ENV_HEAL_MIN_BPS, "2000000")  # 2 MB/s floor
    state = wide_state(n_leaves=6, leaf_kb=512)  # ~3 MB payload
    payload = sum(v.nbytes for v in state.values())
    donor = HTTPTransport(num_chunks=6)
    joiner = HTTPTransport()
    try:
        donor.send_checkpoint([1], step=5, state_dict=state, timeout=30,
                              quorum_id=7)
        before = storm_counters()
        t0 = time.monotonic()
        out = joiner.recv_checkpoint(
            0, donor.metadata(), 5, timeout=30, quorum_id=7
        )
        wall = time.monotonic() - t0
        after = storm_counters()
        assert_state_equal(state, out)
        # The bound actually paced (~3 s for 3 MB at 1 MB/s)...
        assert wall >= 0.8 * payload / 1e6, wall
        assert after["ingress_paced_s"] - before["ingress_paced_s"] > 0.5
        assert after["ingress_bytes"] - before["ingress_bytes"] >= payload
        # ...and the watchdog never fenced the healthy donor.
        assert after["stalled"] - before["stalled"] == 0
        assert after["checksum"] - before["checksum"] == 0
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_ingress_unset_is_zero_cost(monkeypatch) -> None:
    monkeypatch.delenv(ht.ENV_HEAL_INGRESS, raising=False)
    state = wide_state(n_leaves=2, leaf_kb=64)
    donor = HTTPTransport(num_chunks=2)
    joiner = HTTPTransport()
    try:
        donor.send_checkpoint([1], step=5, state_dict=state, timeout=10,
                              quorum_id=7)
        before = storm_counters()
        out = joiner.recv_checkpoint(
            0, donor.metadata(), 5, timeout=10, quorum_id=7
        )
        after = storm_counters()
        assert_state_equal(state, out)
        assert after["ingress_bytes"] - before["ingress_bytes"] == 0
    finally:
        donor.shutdown()
        joiner.shutdown()


# ---------------------------------------------------------------------------
# punisher kill_half_fleet
# ---------------------------------------------------------------------------


def _lh_status(members) -> MagicMock:
    status = MagicMock()
    status.members = []
    for replica_id, joining in members:
        ms = MagicMock()
        ms.joining = joining
        ms.member.replica_id = replica_id
        status.members.append(ms)
    return status


def test_kill_half_fleet_kills_floor_half_with_survivors() -> None:
    client = MagicMock()
    client.status.return_value = _lh_status(
        [("r0", False), ("r1", False), ("r2", False), ("r3", False),
         ("j0", True)]
    )
    assert kill_half_fleet(client, random.Random(0)) is True
    victims = [call.args[0] for call in client.kill.call_args_list]
    assert len(victims) == 2 and len(set(victims)) == 2
    # Only non-joining members are targeted; >= half the fleet survives.
    assert set(victims) <= {"r0", "r1", "r2", "r3"}
    for call in client.kill.call_args_list:
        assert call.kwargs.get("mode") == "exit"


def test_kill_half_fleet_noops_below_two_members() -> None:
    client = MagicMock()
    client.status.return_value = _lh_status([("r0", False), ("j0", True)])
    assert kill_half_fleet(client, random.Random(0)) is False
    client.kill.assert_not_called()


# ---------------------------------------------------------------------------
# manager plumbing: distinct rotations from one quorum view
# ---------------------------------------------------------------------------


def storm_quorum(joiner_ids, quorum_id=2, max_step=7):
    participants = [
        member("ra", "donor_a:1", max_step),
        member("rb", "donor_b:1", max_step),
    ] + [member(rid, f"{rid}:addr", 3) for rid in joiner_ids]
    return make_quorum(
        quorum_id=quorum_id,
        replica_rank=1,
        replica_world_size=2,
        heal=True,
        max_step=max_step,
        recover_src_manager_address="donor_a:1",
        recover_src_replica_rank=0,
        quorum=Quorum(quorum_id=quorum_id, participants=participants),
    )


def test_concurrent_joiners_derive_distinct_rotations() -> None:
    """Two joiners observing the SAME quorum hand distinct, deterministic
    stripe rotations to their transports — the no-negotiation storm
    plan."""
    recv_result = {
        "user": {"model": {"w": np.zeros(2)}},
        "tpuft": {"step": 7, "batches_committed": 14},
    }
    rotations = {}
    for rid in ("stormA:u", "stormB:u"):
        manager, client, _, transport = make_manager(
            pg=ProcessGroupDummy(), min_replica_size=1
        )
        manager._replica_id = rid
        manager._metric_labels = {
            "replica_id": rid.split(":", 1)[0],
            "group_rank": "1",
        }
        transport.recv_checkpoint.return_value = recv_result
        with patched_manager_client(
            {"donor_a:1": "http://a:0", "donor_b:1": "http://b:0"}
        ):
            client._quorum.return_value = storm_quorum(
                ["stormA:u", "stormB:u"]
            )
            manager.start_quorum()
        assert manager.errored() is None
        kwargs = transport.recv_checkpoint.call_args[1]
        rotations[rid] = kwargs["stripe_rotation"]
        assert metrics.gauge_value(
            "tpuft_heal_storm_rotation", **manager._metric_labels
        ) == float(kwargs["stripe_rotation"])
        # Every member's view of the storm size rides the pushed gauges.
        assert metrics.gauge_value(
            "tpuft_heal_storm_joiners", **manager._metric_labels
        ) == 2.0
        manager.shutdown(wait=False)
    # stormA ordinal 0, stormB ordinal 1 (+ group_rank 1 + quorum_id 2).
    assert rotations == {"stormA:u": 3, "stormB:u": 4}


# ---------------------------------------------------------------------------
# the flagship storm drill (threads-as-replicas, both commit orderings)
# ---------------------------------------------------------------------------


def make_storm_rejoiner(tag: str, depth: int, stale_params: dict,
                        stale_step: int):
    """A rejoining replica with a REAL heal transport, a distinct storm
    identity, and registered stale state, in the requested ordering."""
    transport = HTTPTransport()
    manager, client, _, _ = make_manager(
        pg=ProcessGroupDummy(),
        min_replica_size=1,
        commit_pipeline_depth=depth,
        checkpoint_transport=transport,
    )
    manager._replica_id = f"{tag}:u"
    manager._metric_labels = {"replica_id": tag, "group_rank": "1"}
    holder = {"params": stale_params}
    healed: list = []

    def load(state):
        holder["params"] = state
        healed.append(state)

    manager.register_state_dict_fn(
        "params", load_state_dict=load, state_dict=lambda: holder["params"]
    )
    manager._step = stale_step
    return manager, client, transport, holder, healed


@pytest.mark.parametrize("depth", [0, 1], ids=["strict", "pipelined"])
def test_mass_rejoin_storm_drill(depth, monkeypatch) -> None:
    """THREE stale rejoiners heal SIMULTANEOUSLY from the same two-donor
    set (threads-as-replicas over loopback HTTP): every joiner reaches
    bitwise identity with the committed state, rotations are pairwise
    distinct, and the storm produces zero heal exhaustions, zero
    checksum failures, and zero era rejects — in strict AND pipelined
    commit orderings, inside the tier-1 wall budget."""
    monkeypatch.delenv("TPUFT_COMMIT_PIPELINE", raising=False)
    t_start = time.monotonic()
    committed = wide_state(n_leaves=6)
    donors = [HTTPTransport(num_chunks=12) for _ in range(2)]
    joiners = []
    try:
        for d in donors:
            d.send_checkpoint(
                [1], step=7, state_dict=committed_state_dict(committed, 7),
                timeout=10, quorum_id=2,
            )
        tags = ["stormA", "stormB", "stormC"]
        for j, tag in enumerate(tags):
            stale = {k: v.copy() for k, v in committed.items()}
            stale[f"w{j}"] = stale[f"w{j}"] + float(j + 1)  # per-joiner drift
            joiners.append(make_storm_rejoiner(tag, depth, stale, 3))
        joiner_ids = [m._replica_id for m, *_ in joiners]
        before = storm_counters()
        with patched_manager_client(
            {"donor_a:1": donors[0].metadata(),
             "donor_b:1": donors[1].metadata()}
        ):
            for manager, client, *_ in joiners:
                client._quorum.return_value = storm_quorum(joiner_ids)
            threads = [
                threading.Thread(target=m.start_quorum, name=f"storm-{i}")
                for i, (m, *_rest) in enumerate(joiners)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
                assert not t.is_alive(), "storm joiner wedged"
        after = storm_counters()

        rotations = set()
        for manager, client, transport, holder, healed in joiners:
            assert manager.errored() is None, manager.errored()
            assert manager.current_step() == 7
            assert len(healed) == 1
            assert_state_equal(committed, holder["params"])
            rotations.add(
                metrics.gauge_value(
                    "tpuft_heal_storm_rotation", **manager._metric_labels
                )
            )
        # Coordinated plan: three joiners, three distinct offsets.
        assert len(rotations) == 3, rotations
        # Storm hygiene: nothing exhausted, nothing corrupt, nothing
        # healed backwards, no cross-round retries needed.
        assert after["heal_exhausted"] - before["heal_exhausted"] == 0
        assert after["checksum"] - before["checksum"] == 0
        assert after["era"] - before["era"] == 0
        # Every donor served some stripe of the storm.
        for d in donors:
            assert d._served_event.is_set()
        # Tier-1 budget: the default-run storm drill must stay fast on
        # the 1-core box (gated on observed state above — no sleeps).
        assert time.monotonic() - t_start < 60.0
    finally:
        for d in donors:
            d.shutdown()
        for manager, *_rest in joiners:
            manager.shutdown(wait=False)


# ---------------------------------------------------------------------------
# --explain-step storm lines
# ---------------------------------------------------------------------------


def _load_fleet_trace():
    repo = Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "fleet_trace_storm", repo / "scripts" / "fleet_trace.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_explain_step_prints_per_joiner_storm_table() -> None:
    """With >1 joiner healing in the same era, the postmortem prints one
    row per joiner — chunks verified, bytes, and which donors served its
    stripes — plus each joiner's derived plan rotation."""
    fleet_trace = _load_fleet_trace()
    events = []
    seq = {"j1": 0, "j2": 0}

    def ev(proc, name, **args):
        seq[proc] += 1
        return {
            "name": name,
            "seq": seq[proc],
            "t_wall": 1000.0 + seq[proc],
            "replica_id": proc,
            "group_rank": 0,
            "step": 7,
            "quorum_id": 2,
            "args": args,
        }

    for proc, rotation in (("j1", 3), ("j2", 4)):
        events.append(
            ev(proc, "heal_stripe_plan", donors=2, rotation=rotation, chunks=4)
        )
        for chunk, donor in ((0, "http://a:1"), (1, "http://b:1")):
            events.append(
                ev(
                    proc,
                    "heal_chunk_recv",
                    chunk=chunk + (2 if proc == "j2" else 0),
                    bytes=1 << 20,
                    total_chunks=4,
                    donor=donor,
                )
            )
    merged = fleet_trace.merge_events(events, offsets={})
    out = fleet_trace.explain_step(merged, 7)
    assert "rejoin storm: 2 joiner(s)" in out
    assert "j1/0" in out and "j2/0" in out
    assert "rotation 3" in out and "rotation 4" in out
    # Donor attribution per joiner.
    assert out.count("http://a:1") >= 2 and out.count("http://b:1") >= 2
