"""Progressive-delivery plane drills (pure Python — carries tier-1 in a
container without the native toolchain):

- cohort purity: sha256 percent cohorts are a pure function of the
  tenant name — bitwise identical across processes (a subprocess
  re-derives every bucket), with EXACT percent boundaries;
- policy table: malformed entries degrade one entry, never the table
  (the serving_tenant_tokens discipline); precedence is explicit >
  ``*`` default > percent cohort > stable; shadow tenants are SERVED
  stable;
- wrong-stream refusal at every seam: the publisher announce (403 +
  seam="announce"), the relay (seam="relay"), and the reader's own
  client-side fence (seam="reader") — a misrouted canary descriptor is
  refused before the verification pipeline starts; tokenless chunk
  fetches (heal plane, relay-tree pulls) are never gated;
- shadow reads: the relay tees a shadow tenant's fetch to the resident
  canary, verifies the full integrity pipeline and reports divergence /
  failure counters WITHOUT serving it — a poisoned canary is evidence,
  never an error on the stable path;
- the verdict loop: RolloutEvaluator hysteresis is unit-pinned (the
  HealthScorer discipline — K consecutive windows past a multiplicative
  threshold AND an absolute gap floor; refusal on insufficient
  evidence; a transient blip can never retract), and RolloutDirector
  actuates at exactly one seam — auto-promotion after K healthy
  windows, auto-retraction (+ canary hold) on a poisoned wave,
  alerting-only suppression;
- the flagship churn drill in strict AND pipelined depth-2 orderings:
  a training manager publishes canary waves under an active policy
  while stable/canary/pinned readers poll; a punisher-armed
  poison_canary fires mid-run and the verdict loop auto-retracts the
  wave — stable readers never observe a canary or retracted version;
- observability goldens: the fleet_status ROLLOUT column and the
  fleet_trace --explain-step canary lines.
"""

import importlib.util
import json
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from test_ddp import scripted_manager
from test_serving import assert_version_is, state_for

from torchft_tpu import metrics, punisher
from torchft_tpu.optim import Optimizer
from torchft_tpu.serving import CachingRelay, WeightPublisher, WeightSubscriber
from torchft_tpu.serving import rollout
from torchft_tpu.utils import faultinject

TOKENS = (
    "tok-stable:team-stable,tok-canary:team-canary,"
    "tok-shadow:team-shadow,tok-pin:team-pin"
)

_ROLLOUT_COUNTERS = {
    "shadow_reads": "tpuft_rollout_shadow_reads_total",
    "shadow_failures": "tpuft_rollout_shadow_failures_total",
    "refused": "tpuft_rollout_verdicts_refused_total",
    "retractions": "tpuft_rollout_retractions_total",
    "promotions": "tpuft_rollout_promotions_total",
    "suppressed": "tpuft_rollout_alert_suppressed_total",
    "poisoned": "tpuft_rollout_poisoned_publishes_total",
    "auth_rejects": "tpuft_serving_auth_rejects_total",
}


def rollout_counters() -> dict:
    out = {k: metrics.counter_total(n) for k, n in _ROLLOUT_COUNTERS.items()}
    for seam in ("announce", "relay", "transport", "child", "reader"):
        out[f"wrong_{seam}"] = metrics.counter_total(
            "tpuft_rollout_wrong_stream_rejects_total", seam=seam
        )
    for action in ("retract", "promote"):
        out[f"verdict_{action}"] = metrics.counter_total(
            "tpuft_rollout_verdicts_total", action=action
        )
    return out


def wait_rollout_counters(predicate, deadline_s: float = 10.0) -> dict:
    """Gate on OBSERVED counters, never a sleep: the shadow tee runs on
    the relay handler thread strictly AFTER the stable response is on
    the wire, so its counters can land a beat after the client's poll
    returns."""
    deadline = time.monotonic() + deadline_s
    while True:
        counters = rollout_counters()
        if predicate(counters) or time.monotonic() >= deadline:
            return counters
        time.sleep(0.01)


def _loss_fn(p, b):
    return jnp.sum((p["w"] - b) ** 2)


def _get(url: str, token: str = None):
    req = urllib.request.Request(url)
    if token is not None:
        req.add_header("Authorization", f"Bearer {token}")
    return urllib.request.urlopen(req, timeout=5)


def _http_status(url: str, token: str = None) -> int:
    try:
        with _get(url, token) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        return e.code


# ---------------------------------------------------------------------------
# cohorts: a pure function of the tenant name
# ---------------------------------------------------------------------------


def test_cohort_bucket_deterministic_cross_process() -> None:
    """Same tenant -> same cohort bucket in THIS process and in a fresh
    subprocess that file-loads rollout.py (no package import, no shared
    state): cohort membership is never negotiated, exactly the
    zero.shard_assignment discipline applied to readers."""
    tenants = ["team-a", "team-b", "default", "x" * 64, "Ünïcode-tenant"]
    local = {t: rollout.cohort_bucket(t) for t in tenants}
    assert all(0 <= b < 10000 for b in local.values())
    # Stable within the process.
    assert local == {t: rollout.cohort_bucket(t) for t in tenants}
    # Tokenless pools under "default".
    assert rollout.cohort_bucket(None) == rollout.cohort_bucket("default")
    path = (
        Path(__file__).resolve().parent.parent
        / "torchft_tpu"
        / "serving"
        / "rollout.py"
    )
    code = (
        "import importlib.util, json, sys\n"
        "spec = importlib.util.spec_from_file_location('tpuft_rollout', sys.argv[1])\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(mod)\n"
        "print(json.dumps({t: mod.cohort_bucket(t) for t in json.loads(sys.argv[2])}))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code, str(path), json.dumps(tenants)],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout) == local


def test_cohort_percent_boundary_exact() -> None:
    """The percent boundary is exact: a tenant in bucket b joins the
    cohort at percent (b+1)/100 and not at b/100 — no float drift at
    the edge; 0% admits nobody, 100% everybody."""
    for tenant in ("team-a", "team-b", "edge-case", "default"):
        b = rollout.cohort_bucket(tenant)
        assert not rollout.in_canary_cohort(tenant, b / 100.0)
        assert rollout.in_canary_cohort(tenant, (b + 1) / 100.0)
        assert not rollout.in_canary_cohort(tenant, 0.0)
        assert rollout.in_canary_cohort(tenant, 100.0)
    # The documented example: 12.34% admits buckets [0, 1234).
    assert rollout.in_canary_cohort("t", 12.34) == (
        rollout.cohort_bucket("t") < 1234
    )


def test_parse_policy_skips_malformed_entries() -> None:
    entries, errors = rollout.parse_policy(
        "a:stable, junk ,b:pin@7,c:bogus,d:canary,e:shadow,:stable,f:"
    )
    assert entries == {
        "a": "stable",
        "b": "pin@7",
        "d": "canary",
        "e": "shadow",
    }
    assert len(errors) == 4  # junk, c:bogus, :stable, f:
    assert rollout.parse_pin("pin@7") == 7
    assert rollout.parse_pin("pin@x") is None
    assert rollout.parse_pin("stable") is None


def test_policy_precedence_and_shadow_resolves_stable() -> None:
    policy = rollout.RolloutPolicy(
        entries={"a": "canary", "s": "shadow", "*": "pin@3"},
        percent=100.0,
        shadows=frozenset({"teed"}),
    )
    assert policy.active()
    # Explicit entry beats the * default and the percent cohort.
    assert policy.resolve("a") == rollout.STREAM_CANARY
    # Shadow tenants are SERVED stable (tee is relay-side, never bytes).
    assert policy.resolve("s") == rollout.STREAM_STABLE
    assert policy.is_shadow("s") and policy.is_shadow("teed")
    # * default beats the percent cohort for unlisted tenants.
    assert policy.resolve("unlisted") == "pin@3"
    # Percent cohort is the fallback with no entry at all.
    cohort_only = rollout.RolloutPolicy(percent=100.0)
    assert cohort_only.resolve("anyone") == rollout.STREAM_CANARY
    assert rollout.RolloutPolicy(percent=0.0).resolve("anyone") == (
        rollout.STREAM_STABLE
    )
    assert not rollout.RolloutPolicy().active()


def test_resolve_view_semantics() -> None:
    inactive = rollout.RolloutPolicy()
    # Inactive plane: every request resolves to the full view — the
    # exact pre-rollout wire.
    assert rollout.resolve_view("anyone", None, inactive) == rollout.VIEW_ALL
    assert rollout.resolve_view(None, "canary", inactive) == rollout.VIEW_ALL
    policy = rollout.RolloutPolicy(
        entries={"a": "stable", "b": "canary", "p": "pin@5"}
    )
    # Tokenless infra pulls requesting the full view are never gated.
    assert (
        rollout.resolve_view(None, rollout.VIEW_ALL, policy) == rollout.VIEW_ALL
    )
    assert rollout.resolve_view("a", None, policy) == rollout.STREAM_STABLE
    with pytest.raises(rollout.WrongStreamError):
        rollout.resolve_view("a", "canary", policy)
    with pytest.raises(rollout.WrongStreamError):
        rollout.resolve_view("a", rollout.VIEW_ALL, policy)
    # Canary tenants may read any view (latest-1 baseline comparisons).
    assert rollout.resolve_view("b", None, policy) == rollout.STREAM_CANARY
    assert rollout.resolve_view("b", "stable", policy) == rollout.STREAM_STABLE
    assert rollout.resolve_view("p", None, policy) == "pin@5"
    with pytest.raises(rollout.WrongStreamError):
        rollout.resolve_view("p", "stable", policy)


def test_wrong_stream_chunk_reason_tokenless_never_gated() -> None:
    policy = rollout.RolloutPolicy(entries={"a": "stable", "p": "pin@5"})
    # Tokenless = the heal plane and relay-tree pulls: never gated.
    assert (
        rollout.wrong_stream_chunk_reason(
            None, 9, rollout.STREAM_CANARY, policy
        )
        is None
    )
    assert rollout.wrong_stream_chunk_reason(
        "a", 9, rollout.STREAM_CANARY, policy
    )
    assert (
        rollout.wrong_stream_chunk_reason("a", 9, rollout.STREAM_STABLE, policy)
        is None
    )
    assert rollout.wrong_stream_chunk_reason("p", 9, None, policy)
    assert rollout.wrong_stream_chunk_reason("p", 5, None, policy) is None


# ---------------------------------------------------------------------------
# evaluator: unit-pinned hysteresis
# ---------------------------------------------------------------------------


def test_evaluator_refuses_insufficient_evidence() -> None:
    ev = rollout.RolloutEvaluator(
        threshold=3.0, consecutive=2, min_samples=3, min_gap=0.05
    )
    before = rollout_counters()["refused"]
    verdict = ev.observe_window(canary_reads=2, canary_failures=2)
    assert verdict["judgeable"] is False and verdict["action"] is None
    assert ev.refusals == 1
    assert rollout_counters()["refused"] - before == 1
    # Streaks do not advance on evidence that is not there.
    assert ev.bad_streak == 0 and ev.good_streak == 0


def test_evaluator_blip_never_retracts() -> None:
    ev = rollout.RolloutEvaluator(
        threshold=3.0, consecutive=2, min_samples=1, min_gap=0.05
    )
    assert ev.observe_window(4, 4)["bad"] is True
    assert ev.bad_streak == 1
    # One healthy window resets the streak: a transient blip can never
    # reach the K-window latch.
    verdict = ev.observe_window(4, 0)
    assert verdict["bad"] is False and verdict["action"] is None
    assert ev.bad_streak == 0 and ev.good_streak == 1
    assert ev.observe_window(4, 4)["action"] is None  # bad_streak back to 1


def test_evaluator_requires_threshold_and_gap() -> None:
    ev = rollout.RolloutEvaluator(
        threshold=3.0, consecutive=1, min_samples=1, min_gap=0.05
    )
    # Multiplicative bound cleared, absolute gap NOT: 3x a per-mille
    # noise rate is not a verdict.
    v = ev.observe_window(100, 4)  # canary 4%, stable 0% -> gap 0.04 < 0.05
    assert v["bad"] is False
    # Gap cleared, multiplicative NOT: a uniformly failing fleet never
    # blames its canary.
    v = ev.observe_window(10, 5, stable_reads=10, stable_failures=4)
    assert v["bad"] is False
    # Both cleared -> bad, and consecutive=1 latches immediately.
    v = ev.observe_window(10, 5, stable_reads=10, stable_failures=0)
    assert v["bad"] is True and v["action"] == "retract"


def test_evaluator_k_windows_latch_both_verdicts() -> None:
    ev = rollout.RolloutEvaluator(
        threshold=3.0, consecutive=3, min_samples=1, min_gap=0.05
    )
    assert ev.observe_window(4, 4)["action"] is None
    assert ev.observe_window(4, 4)["action"] is None
    assert ev.observe_window(4, 4)["action"] == "retract"
    ev.reset()
    assert ev.observe_window(4, 0)["action"] is None
    assert ev.observe_window(4, 0)["action"] is None
    assert ev.observe_window(4, 0)["action"] == "promote"


# ---------------------------------------------------------------------------
# director: promote, poisoned retract + hold, alerting-only
# ---------------------------------------------------------------------------


def test_director_lifecycle_promote_then_poisoned_wave_retracts(
    tmp_path, monkeypatch
) -> None:
    """The full deterministic lifecycle against a real publisher: a
    healthy wave auto-promotes after K windows; the punisher-armed
    poisoned wave auto-retracts (the whole wave, younger healthy canary
    included), sets the canary hold, and readers converge to the
    surviving stable version."""
    fault_file = tmp_path / "fault"
    monkeypatch.setenv(faultinject.ENV_FAULT_FILE, str(fault_file))
    monkeypatch.setenv(rollout.ENV_POLICY, "*:stable")
    pub = WeightPublisher(num_chunks=2, timeout=5.0, keep_versions=8)
    director = rollout.RolloutDirector(
        pub,
        evaluator=rollout.RolloutEvaluator(consecutive=2, min_samples=1),
        mode="actuate",
    )
    try:
        before = rollout_counters()
        # Healthy wave: publishes under an active policy ship canary.
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        assert pub.stream_of(1) == rollout.STREAM_CANARY
        assert director.tick()["judgeable"]
        assert director.state == "watch"
        pub.publish(step=2, quorum_id=0, state=state_for(2))
        # The second canary JOINS the wave (oldest step = the wave
        # identity) — it must not reset the evidence streak.
        director.tick()
        assert director.state == "promoted"
        assert pub.stream_of(1) == rollout.STREAM_STABLE
        assert pub.stream_of(2) == rollout.STREAM_STABLE
        assert rollout_counters()["promotions"] - before["promotions"] == 1
        assert (
            rollout_counters()["verdict_promote"] - before["verdict_promote"]
            == 1
        )

        # Poisoned wave: CRC-valid bytes, bad-quality marker — only the
        # verdict loop reacts, the integrity chain stays green.
        assert punisher.arm_stream_fault("poison_canary", str(fault_file))
        pub.publish(step=3, quorum_id=0, state=state_for(3))
        assert rollout_counters()["poisoned"] - before["poisoned"] == 1
        assert pub.version_descriptor(3).get("poisoned")
        director.tick()
        assert director.state == "suspect"
        # A younger HEALTHY canary joins the suspect wave; the poisoned
        # member stays visible to the probe (whole-wave self-probe).
        pub.publish(step=4, quorum_id=0, state=state_for(4))
        director.tick()
        assert director.state == "retracted"
        assert pub.is_retracted(3) and pub.is_retracted(4)
        assert rollout_counters()["retractions"] - before["retractions"] == 1
        assert (
            rollout_counters()["verdict_retract"] - before["verdict_retract"]
            == 1
        )
        assert pub.latest()["step"] == 2
        assert metrics.gauge_value("tpuft_rollout_state") == (
            rollout.STATE_CODES["retracted"]
        )
        # The hold: the failed wave must not immediately re-ship itself.
        pub.publish(step=5, quorum_id=0, state=state_for(5))
        assert pub.stream_of(5) == rollout.STREAM_STABLE
        # A stable (tokenless -> default tenant) reader converges to the
        # surviving stream and never held a retracted version.
        sub = WeightSubscriber([pub.address()], timeout=5.0, notify=False)
        assert_version_is(sub.poll(), 5)
    finally:
        pub.shutdown()


def test_director_alert_mode_suppresses_actuation(tmp_path, monkeypatch) -> None:
    fault_file = tmp_path / "fault"
    monkeypatch.setenv(faultinject.ENV_FAULT_FILE, str(fault_file))
    monkeypatch.setenv(rollout.ENV_POLICY, "*:stable")
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    director = rollout.RolloutDirector(
        pub,
        evaluator=rollout.RolloutEvaluator(consecutive=2, min_samples=1),
        mode="alert",
    )
    try:
        before = rollout_counters()
        assert punisher.arm_stream_fault("poison_canary", str(fault_file))
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        director.tick()
        director.tick()  # bad streak 2 -> verdict latches, actuation suppressed
        after = rollout_counters()
        assert after["suppressed"] - before["suppressed"] == 1
        assert after["verdict_retract"] - before["verdict_retract"] == 1
        # The publisher was not touched: canary live, nothing retracted.
        assert after["retractions"] - before["retractions"] == 0
        assert not pub.is_retracted(1)
        assert pub.canary_steps() == [1]
    finally:
        pub.shutdown()


def test_director_refuses_on_starved_evidence(monkeypatch) -> None:
    """min_samples above what a window can supply: every window is
    REFUSED (counted), streaks never advance, nothing actuates."""
    monkeypatch.setenv(rollout.ENV_POLICY, "*:stable")
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    director = rollout.RolloutDirector(
        pub,
        evaluator=rollout.RolloutEvaluator(consecutive=1, min_samples=50),
        mode="actuate",
    )
    try:
        before = rollout_counters()
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        for _ in range(3):
            verdict = director.tick()
            assert verdict["judgeable"] is False and verdict["action"] is None
        after = rollout_counters()
        assert after["refused"] - before["refused"] == 3
        assert after["retractions"] - before["retractions"] == 0
        assert after["promotions"] - before["promotions"] == 0
        assert pub.canary_steps() == [1]
    finally:
        pub.shutdown()


# ---------------------------------------------------------------------------
# wrong-stream refusal at every seam
# ---------------------------------------------------------------------------


def test_announce_seam_refuses_wrong_stream(monkeypatch) -> None:
    monkeypatch.setenv("TPUFT_SERVING_TENANT_TOKENS", TOKENS)
    monkeypatch.setenv(
        rollout.ENV_POLICY,
        "team-stable:stable,team-canary:canary,team-pin:pin@1",
    )
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        base = pub.address()
        before = rollout_counters()
        # A stable tenant requesting the canary (or full) view: 403.
        assert _http_status(f"{base}/serving/latest?stream=canary", "tok-stable") == 403
        assert _http_status(f"{base}/serving/latest?stream=all", "tok-stable") == 403
        # A pinned tenant requesting any other stream: 403.
        assert _http_status(f"{base}/serving/latest?stream=stable", "tok-pin") == 403
        after = rollout_counters()
        assert after["wrong_announce"] - before["wrong_announce"] == 3
        # The PR-12 discipline: unknown tokens are 401, not 403.
        assert _http_status(f"{base}/serving/latest", "tok-bogus") == 401
        assert after["auth_rejects"] <= rollout_counters()["auth_rejects"]
        # A canary tenant reads its own stream fine.
        with _get(f"{base}/serving/latest?stream=canary", "tok-canary") as resp:
            assert json.loads(resp.read())["step"] == 1
    finally:
        pub.shutdown()


def test_relay_seam_refuses_wrong_stream_and_subscriber_surfaces(
    monkeypatch,
) -> None:
    monkeypatch.setenv("TPUFT_SERVING_TENANT_TOKENS", TOKENS)
    monkeypatch.setenv(
        rollout.ENV_POLICY, "team-stable:stable,team-canary:canary"
    )
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    relay = CachingRelay([pub.address()], timeout=5.0, start=False)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))  # canary wave
        assert relay.poll_once() is True
        before = rollout_counters()
        # Direct 403 at the relay seam.
        assert (
            _http_status(
                f"{relay.address()}/serving/latest?stream=canary", "tok-stable"
            )
            == 403
        )
        assert rollout_counters()["wrong_announce"] == before["wrong_announce"]
        assert rollout_counters()["wrong_relay"] - before["wrong_relay"] == 1
        # A stable-tenant subscriber asking for the canary stream: the
        # 403 surfaces as a failed poll (None), never an adoption.
        sub = WeightSubscriber(
            [relay.address()],
            timeout=5.0,
            token="tok-stable",
            stream="canary",
            notify=False,
        )
        assert sub.poll() is None
        assert sub.current() is None
        assert rollout_counters()["wrong_relay"] - before["wrong_relay"] >= 2
        # The same tenant on its OWN stream adopts fine.
        ok = WeightSubscriber(
            [relay.address()],
            timeout=5.0,
            token="tok-canary",
            stream="canary",
            notify=False,
        )
        assert_version_is(ok.poll(), 1)
    finally:
        relay.shutdown()
        pub.shutdown()


def test_reader_side_fence_refuses_misrouted_canary(monkeypatch) -> None:
    """A stable-stream reader refuses a canary-tagged descriptor
    CLIENT-side, before the verification pipeline starts — a misrouted
    or compromised tier cannot push a canary onto a stable reader."""
    monkeypatch.setenv(rollout.ENV_POLICY, "*:stable")
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        descriptor = pub.publish(step=1, quorum_id=0, state=state_for(1))
        assert descriptor.get("stream") == rollout.STREAM_CANARY
        sub = WeightSubscriber(
            [pub.address()], timeout=5.0, stream="stable", notify=False
        )
        before = rollout_counters()
        assert sub._poll(latest=descriptor) is None
        assert sub.current() is None
        assert rollout_counters()["wrong_reader"] - before["wrong_reader"] == 1
    finally:
        pub.shutdown()


# ---------------------------------------------------------------------------
# shadow reads: observed, never served
# ---------------------------------------------------------------------------


def test_shadow_tee_reports_divergence_and_isolates_failures(
    tmp_path, monkeypatch
) -> None:
    fault_file = tmp_path / "fault"
    monkeypatch.setenv(faultinject.ENV_FAULT_FILE, str(fault_file))
    monkeypatch.setenv("TPUFT_SERVING_TENANT_TOKENS", TOKENS)
    monkeypatch.setenv(
        rollout.ENV_POLICY, "team-shadow:shadow,*:stable"
    )
    pub = WeightPublisher(num_chunks=4, timeout=5.0, keep_versions=8)
    relay = CachingRelay([pub.address()], timeout=5.0, start=False)
    try:
        # A promoted stable baseline + a live canary with different bytes.
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        pub.promote_version(1)
        assert relay.poll_once() is True
        pub.publish(step=2, quorum_id=0, state=state_for(2))
        assert relay.poll_once() is True
        before = rollout_counters()
        sub = WeightSubscriber(
            [relay.address()], timeout=5.0, token="tok-shadow", notify=False
        )
        # The shadow tenant is SERVED the stable version...
        assert_version_is(sub.poll(), 1)
        after = wait_rollout_counters(
            lambda c: c["shadow_reads"] - before["shadow_reads"] >= 1
        )
        # ...while its fetch teed a verified canary observation: every
        # chunk differs between step-1 and step-2 states.
        assert after["shadow_reads"] - before["shadow_reads"] >= 1
        assert after["shadow_failures"] == before["shadow_failures"]
        assert metrics.gauge_value("tpuft_rollout_shadow_divergence") == 1.0

        # A poisoned canary: the tee FAILS (counted evidence), the
        # stable path is unharmed.
        assert punisher.arm_stream_fault("poison_canary", str(fault_file))
        pub.publish(step=3, quorum_id=0, state=state_for(3))
        assert relay.poll_once() is True
        mid = rollout_counters()
        assert sub.poll() is None  # nothing new on the stable stream
        assert sub.current().step == 1
        after = wait_rollout_counters(
            lambda c: c["shadow_reads"] - mid["shadow_reads"] >= 1
            and c["shadow_failures"] - mid["shadow_failures"] >= 1
        )
        assert after["shadow_reads"] - mid["shadow_reads"] >= 1
        assert after["shadow_failures"] - mid["shadow_failures"] >= 1
    finally:
        relay.shutdown()
        pub.shutdown()


# ---------------------------------------------------------------------------
# flagship: mixed pinned/canary/stable churn + auto-retraction
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("depth", [0, 2], ids=["strict", "pipelined2"])
def test_progressive_delivery_churn_drill(depth, tmp_path, monkeypatch) -> None:
    """The progressive-delivery chaos drill in strict AND pipelined
    depth-2 orderings: a training manager publishes canary waves under
    an active rollout policy while stable/canary/pinned readers poll; a
    punisher-armed poison_canary fires mid-run and the verdict loop
    auto-retracts the wave fleet-wide. Stable readers must never observe
    a canary-stream or retracted version; the pinned reader never drifts
    off its pin; every reader on a live stream converges to the
    surviving stable version."""
    fault_file = tmp_path / "fault"
    monkeypatch.setenv(faultinject.ENV_FAULT_FILE, str(fault_file))
    monkeypatch.setenv("TPUFT_SERVING_TENANT_TOKENS", TOKENS)
    monkeypatch.setenv(
        rollout.ENV_POLICY,
        "team-stable:stable,team-canary:canary,team-pin:pin@2",
    )
    monkeypatch.setenv(rollout.ENV_WINDOWS, "2")
    manager = scripted_manager(commit_pipeline_depth=depth)
    pub = WeightPublisher(every=1, num_chunks=2, timeout=5.0, keep_versions=8)
    director = rollout.RolloutDirector(pub, mode="actuate")
    opt = Optimizer(
        manager, optax.sgd(0.1), {"w": jnp.array([1.0, 1.0], jnp.float32)}
    )
    manager.attach_publisher(pub, lambda: {"params": opt.params})

    stop = threading.Event()
    observed: list = []  # (reader, step)

    def reader(name: str, **sub_kwargs) -> None:
        sub = WeightSubscriber(
            [pub.address()], timeout=5.0, notify=False, **sub_kwargs
        )
        while not stop.is_set():
            version = sub.poll()
            if version is None:
                time.sleep(0.005)
                continue
            observed.append((name, version.step))

    threads = [
        threading.Thread(target=reader, args=("stable",), kwargs={"token": "tok-stable"}),
        threading.Thread(target=reader, args=("canary",), kwargs={"token": "tok-canary"}),
        threading.Thread(
            target=reader, args=("pin",), kwargs={"token": "tok-pin", "pin": 2}
        ),
    ]
    for t in threads:
        t.start()
    try:
        step_fn = opt.make_step_fn(_loss_fn)
        before = rollout_counters()
        for i in range(6):
            if i == 3:
                # Pin the drill's shape across orderings: make sure a
                # stable baseline exists before the poisoned wave ships
                # (auto-promotion may already have done this), then arm.
                if pub.canary_steps():
                    pub.promote_version(max(pub.canary_steps()))
                punisher.arm_stream_fault("poison_canary", str(fault_file))
            step_fn(jnp.full((2,), float(i), jnp.float32))
        opt.flush_pipeline()
        manager.start_quorum()
        manager.wait_quorum()
        # The poisoned wave may have shipped on the last boundary: give
        # the verdict loop the windows it needs (the same tick the
        # manager's step boundary drives).
        for _ in range(4):
            if rollout_counters()["retractions"] > before["retractions"]:
                break
            director.tick()
        after = rollout_counters()
        assert after["poisoned"] - before["poisoned"] == 1
        assert after["retractions"] - before["retractions"] == 1
        assert after["verdict_retract"] - before["verdict_retract"] == 1
        retracted = [s for s in range(1, 8) if pub.is_retracted(s)]
        assert retracted, "the poisoned wave was never retracted"
        survivor = pub.latest()["step"]
        assert survivor not in retracted
        assert pub.stream_of(survivor) == rollout.STREAM_STABLE
        # Post-retraction hold: no canary is live.
        assert pub.canary_steps() == []
        # Stable + canary readers converge to the survivor.
        deadline = time.monotonic() + 10.0
        converged: set = set()
        while time.monotonic() < deadline and len(converged) < 2:
            converged = {
                name
                for name, step in observed
                if step == survivor and name in ("stable", "canary")
            }
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert converged == {"stable", "canary"}, (converged, survivor)
        # Zero wrong-version adoptions: the stable reader never observed
        # a retracted (canary-wave) version; the pinned reader never
        # drifted off its pin.
        stable_steps = {s for n, s in observed if n == "stable"}
        assert not (stable_steps & set(retracted)), (stable_steps, retracted)
        pin_steps = {s for n, s in observed if n == "pin"}
        assert pin_steps <= {2}, pin_steps
    finally:
        stop.set()
        manager.shutdown(wait=False)
        pub.shutdown(wait=False)


# ---------------------------------------------------------------------------
# observability goldens: fleet_status ROLLOUT column, fleet_trace lines
# ---------------------------------------------------------------------------


def _load_script(name: str):
    spec = importlib.util.spec_from_file_location(
        name,
        Path(__file__).resolve().parent.parent / "scripts" / f"{name}.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_status_rollout_column() -> None:
    fleet_status = _load_script("fleet_status")
    snap = {
        "metrics": {
            "gauges": {
                "tpuft_rollout_state": [{"value": 3.0}],
                "tpuft_rollout_canary_step": [{"value": 7.0}],
            },
            "counters": {
                "tpuft_rollout_retractions_total": [{"value": 1.0}],
            },
        }
    }
    assert fleet_status._rollout_state(snap) == "retracted@s7/r1"
    suspect = {
        "metrics": {
            "gauges": {
                "tpuft_rollout_state": [{"value": 2.0}],
                "tpuft_rollout_canary_step": [{"value": -1.0}],
            },
            "counters": {
                "tpuft_rollout_alert_suppressed_total": [{"value": 2.0}],
            },
        }
    }
    assert fleet_status._rollout_state(suspect) == "suspect!"
    # No rollout director on the replica: no column noise.
    assert fleet_status._rollout_state({"metrics": {"gauges": {}}}) is None
    assert ("rollout", "ROLLOUT") in fleet_status._COLUMNS


def test_fleet_trace_explain_prints_canary_lines() -> None:
    fleet_trace = _load_script("fleet_trace")

    def event(seq, name, **kw):
        base = {
            "seq": seq, "name": name, "ph": "i", "cat": "ft",
            "t_wall": 100.0 + seq, "t_mono": float(seq),
            "replica_id": "train_0", "group_rank": 0,
            "step": 7, "quorum_id": 2, "args": {},
        }
        base.update(kw)
        return base

    merged = fleet_trace.merge_events(
        [
            event(1, "canary_promoted"),
            event(
                2,
                "canary_retracted",
                args={"bad_streak": 2, "canary_rate": 0.5},
            ),
            event(3, "rollout_alert", args={"action": "retract", "bad_streak": 2}),
            event(
                4,
                "shadow_divergence",
                args={"stable_step": 6, "divergence": 0.25},
            ),
            event(
                5,
                "shadow_divergence",
                args={"stable_step": -1, "divergence": -1.0},
            ),
        ]
    )
    text = fleet_trace.explain_step(merged, 7)
    assert "canary PROMOTED: train_0/0 flipped canary wave step 7" in text
    assert (
        "canary RETRACTED: train_0/0 auto-retracted canary wave step 7 "
        "after 2 consecutive bad evidence windows" in text
    )
    assert "rollout ALERT: train_0/0 reached a retract verdict" in text
    assert "suppressed the actuation" in text
    assert "25% of chunk CRCs differ" in text
    assert "divergence unknown" in text
