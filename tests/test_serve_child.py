"""Donor sidecar (out-of-process heal serving) unit suite — pure Python,
tier-1 in a container without the native toolchain.

Covers the process-lifecycle plane the subsystem adds: snapshot handoff
through shared-memory files, era fencing verified IN the child, epoch
swap + cleanup on restage/disallow, crash detection funneling into the
registered error callback with bounded respawn, inline fallback when the
child is unavailable, and the child-registry scrape merged into the
donor's /metrics. The kill-mid-heal chaos drill lives in
tests/test_heal_hardening.py next to the other heal drills.
"""

import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from test_checkpointing import assert_state_equal, chunked_state, heal_counters
from torchft_tpu import metrics
from torchft_tpu.checkpointing import (
    HealEraMismatch,
    HTTPTransport,
    ServeChildCrashed,
)
from torchft_tpu.checkpointing import serve_child as sc


def make_child_transport(**kw):
    kw.setdefault("num_chunks", 4)
    return HTTPTransport(serve_mode="child", **kw)


def wait_until(predicate, deadline_s: float = 10.0, what: str = "condition"):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def test_child_mode_roundtrip_preserves_integrity_meta() -> None:
    """A heal served by the sidecar is byte-identical and its /meta is the
    same format-2 integrity root the inline handler serves (per-chunk
    CRCs, digest, staged era)."""
    from torchft_tpu._safe_pickle import safe_loads

    state = chunked_state()
    donor = make_child_transport()
    joiner = HTTPTransport()
    try:
        assert donor.serve_mode == "child"
        assert donor._child_serving()
        donor.send_checkpoint(
            [1], step=5, state_dict=state, timeout=10, quorum_id=7
        )
        raw = urllib.request.urlopen(
            donor.metadata() + "/checkpoint/5/meta", timeout=5
        ).read()
        meta = safe_loads(raw)
        assert meta["format"] == 2
        assert meta["quorum_id"] == 7
        assert len(meta["chunk_crcs"]) == meta["num_chunks"] == 4
        assert meta["digest"]
        out = joiner.recv_checkpoint(
            0, donor.metadata(), 5, timeout=10, quorum_id=7
        )
        assert_state_equal(state, out)
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_child_full_route_streams_all_chunks() -> None:
    """/full from the sidecar: 8-byte size prefix + serialized chunk per
    chunk, wire-identical to inline (the paced bench legs drain it)."""
    state = chunked_state()
    donor = make_child_transport()
    inline = HTTPTransport(num_chunks=4)
    try:
        donor.send_checkpoint(
            [1], step=5, state_dict=state, timeout=10, quorum_id=7
        )
        inline.send_checkpoint(
            [1], step=5, state_dict=state, timeout=10, quorum_id=7
        )
        child_bytes = urllib.request.urlopen(
            donor.metadata() + "/checkpoint/5/full", timeout=10
        ).read()
        inline_bytes = urllib.request.urlopen(
            inline.metadata() + "/checkpoint/5/full", timeout=10
        ).read()
        assert child_bytes == inline_bytes
    finally:
        donor.shutdown()
        inline.shutdown()


def test_child_era_fence_at_meta_and_chunk() -> None:
    """Era fencing holds ACROSS the process boundary: the child answers a
    mismatched-era chunk GET 409, and a joiner healing in a different era
    rejects the child's /meta outright."""
    state = chunked_state()
    donor = make_child_transport()
    joiner = HTTPTransport()
    try:
        donor.send_checkpoint(
            [1], step=5, state_dict=state, timeout=10, quorum_id=7
        )
        before = metrics.counter_total("tpuft_heal_era_rejects_total")
        with pytest.raises(HealEraMismatch):
            joiner.recv_checkpoint(
                0, donor.metadata(), 5, timeout=5, quorum_id=9
            )
        assert (
            metrics.counter_total("tpuft_heal_era_rejects_total") - before == 1
        )
        # Chunk URLs are era-fenced in-child (the stale-era-after-quorum-
        # change failure row: a sidecar left behind answers 409, never
        # bytes its /meta does not describe).
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                donor.metadata() + "/checkpoint/5/0?quorum_id=9", timeout=5
            )
        assert err.value.code == 409
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_child_restage_swaps_epoch_and_deletes_old(tmp_path) -> None:
    """A quorum change re-stages a fresh epoch; the child atomically swaps
    and deletes the previous snapshot (no unbounded tmpfs growth)."""
    state = chunked_state()
    donor = make_child_transport()
    joiner = HTTPTransport()
    try:
        root = donor._serve_child._root
        donor.send_checkpoint(
            [1], step=5, state_dict=state, timeout=10, quorum_id=7
        )
        wait_until(
            lambda: sorted(p.name for p in root.iterdir()) == ["epoch-000001"],
            what="first epoch staged",
        )
        state2 = {**state, "w": state["w"] + 1.0}
        donor.send_checkpoint(
            [1], step=6, state_dict=state2, timeout=10, quorum_id=8
        )
        wait_until(
            lambda: sorted(p.name for p in root.iterdir()) == ["epoch-000002"],
            what="old epoch deleted after restage",
        )
        out = joiner.recv_checkpoint(
            0, donor.metadata(), 6, timeout=10, quorum_id=8
        )
        assert_state_equal(state2, out)
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_child_disallow_stops_serving_and_deletes_snapshot() -> None:
    state = chunked_state()
    donor = make_child_transport()
    joiner = HTTPTransport()
    try:
        root = donor._serve_child._root
        donor.send_checkpoint(
            [1], step=5, state_dict=state, timeout=10, quorum_id=7
        )
        donor.disallow_checkpoint()
        wait_until(
            lambda: not any(root.iterdir()), what="snapshot deleted on disallow"
        )
        # Later GETs park (nothing staged) until the joiner's own fetch
        # timeout expires — same contract as inline.
        with pytest.raises(Exception):
            joiner.recv_checkpoint(
                0, donor.metadata(), 5, timeout=1.0, quorum_id=7
            )
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_child_crash_funnels_error_and_respawns() -> None:
    """An unexpected child death reaches the registered error callback as
    ServeChildCrashed (the Manager funnels this into report_error), the
    crash/restart counters move, a fresh child respawns, and the next
    stage serves heals again — the donor process itself never raises."""
    state = chunked_state()
    errors = []
    donor = make_child_transport()
    donor.register_error_callback(errors.append)
    joiner = HTTPTransport()
    try:
        donor.send_checkpoint(
            [1], step=5, state_dict=state, timeout=10, quorum_id=7
        )
        c0 = metrics.counter_total("tpuft_heal_serve_child_crashes_total")
        old_pid = donor._serve_child._proc.pid
        donor._serve_child._proc.kill()
        wait_until(lambda: errors, what="crash funneled into the callback")
        assert isinstance(errors[0], ServeChildCrashed)
        assert (
            metrics.counter_total("tpuft_heal_serve_child_crashes_total") - c0
            == 1
        )
        wait_until(lambda: donor._serve_child.alive(), what="respawn")
        assert donor._serve_child._proc.pid != old_pid
        # The respawned child starts empty; a restage serves again.
        donor.send_checkpoint(
            [1], step=6, state_dict=state, timeout=10, quorum_id=8
        )
        out = joiner.recv_checkpoint(
            0, donor.metadata(), 6, timeout=10, quorum_id=8
        )
        assert_state_equal(state, out)
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_child_spawn_failure_degrades_to_inline(monkeypatch) -> None:
    """A box where the sidecar cannot spawn still heals: construction
    falls back to inline serving (counter + in-process address)."""
    from torchft_tpu.checkpointing import http_transport as ht

    def boom(*a, **kw):
        raise OSError("no fork for you")

    monkeypatch.setattr(ht, "ServeChild", boom)
    before = metrics.counter_total("tpuft_heal_serve_fallbacks_total")
    state = chunked_state()
    donor = HTTPTransport(num_chunks=4, serve_mode="child")
    joiner = HTTPTransport()
    try:
        assert donor._serve_child is None
        assert not donor._child_serving()
        assert (
            metrics.counter_total("tpuft_heal_serve_fallbacks_total") - before
            == 1
        )
        donor.send_checkpoint(
            [1], step=5, state_dict=state, timeout=10, quorum_id=7
        )
        out = joiner.recv_checkpoint(
            0, donor.metadata(), 5, timeout=10, quorum_id=7
        )
        assert_state_equal(state, out)
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_child_metrics_merged_into_donor_scrape() -> None:
    """The donor's /metrics (transport port) carries the child-side
    serve counters labeled process="serve_child"; /metrics.json gains a
    serve_child section (docs/observability.md)."""
    import json

    state = chunked_state()
    donor = make_child_transport()
    joiner = HTTPTransport()
    try:
        donor.send_checkpoint(
            [1], step=5, state_dict=state, timeout=10, quorum_id=7
        )
        joiner.recv_checkpoint(0, donor.metadata(), 5, timeout=10, quorum_id=7)
        port = donor._server.server_address[1]
        text = urllib.request.urlopen(
            f"http://localhost:{port}/metrics", timeout=5
        ).read().decode()
        assert 'process="serve_child"' in text
        assert "tpuft_heal_serve_requests_total" in text
        assert "tpuft_heal_serve_bytes_total" in text
        payload = json.loads(
            urllib.request.urlopen(
                f"http://localhost:{port}/metrics.json", timeout=5
            ).read().decode()
        )
        child = payload["serve_child"]
        reqs = child["counters"]["tpuft_heal_serve_requests_total"]
        assert sum(e["value"] for e in reqs) >= 5  # 1 meta + 4 chunks
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_file_armed_corrupt_stream_consumed_by_child(
    tmp_path, monkeypatch
) -> None:
    """The punisher's fault-file arming crosses the process boundary: the
    CHILD's chunk serve consumes corrupt_stream, the joiner's checksum
    rejects + re-fetches exactly once, and corruption is never adopted."""
    from torchft_tpu.punisher import arm_stream_fault
    from torchft_tpu.utils import faultinject

    fault_file = str(tmp_path / "fault_cmd")
    monkeypatch.setenv(faultinject.ENV_FAULT_FILE, fault_file)
    state = chunked_state()
    donor = make_child_transport(num_chunks=2)
    joiner = HTTPTransport()
    try:
        donor.send_checkpoint(
            [1], step=5, state_dict=state, timeout=10, quorum_id=7
        )
        assert arm_stream_fault("corrupt_stream", fault_file)
        before = heal_counters()
        out = joiner.recv_checkpoint(
            0, donor.metadata(), 5, timeout=10, quorum_id=7
        )
        after = heal_counters()
        assert_state_equal(state, out)
        assert after["checksum"] - before["checksum"] == 1
        assert after["refetch"] - before["refetch"] == 1
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_serve_mode_env_selection_and_validation(monkeypatch) -> None:
    monkeypatch.setenv(sc.ENV_SERVE_MODE, "child")
    donor = HTTPTransport(num_chunks=2)
    try:
        assert donor.serve_mode == "child"
        assert donor._serve_child is not None
    finally:
        donor.shutdown()
    monkeypatch.delenv(sc.ENV_SERVE_MODE)
    with pytest.raises(ValueError):
        HTTPTransport(serve_mode="sideways")
    inline = HTTPTransport()
    try:
        assert inline.serve_mode == "inline"
        assert inline._serve_child is None
        assert metrics.gauge_value("tpuft_heal_serve_mode") == 0
    finally:
        inline.shutdown()


def test_manager_wires_report_error_into_transport() -> None:
    """The Manager registers report_error as the transport's error
    callback at construction, so a sidecar crash poisons the step instead
    of raising past the step boundary."""
    from test_manager import make_manager
    from torchft_tpu.parallel.process_group import ProcessGroupDummy

    manager, _client, _pg, transport = make_manager(pg=ProcessGroupDummy())
    try:
        transport.register_error_callback.assert_called_once()
        (cb,) = transport.register_error_callback.call_args[0]
        assert manager.errored() is None
        cb(ServeChildCrashed("sidecar died"))
        err = manager.errored()
        assert err is not None
        assert isinstance(err.original_exception, ServeChildCrashed)
    finally:
        manager.shutdown(wait=False)


def test_serve_dir_root_resolution(monkeypatch, tmp_path) -> None:
    monkeypatch.setenv(sc.ENV_SERVE_DIR, str(tmp_path))
    assert sc.serve_dir_root() == str(tmp_path)
    monkeypatch.delenv(sc.ENV_SERVE_DIR)
    root = sc.serve_dir_root()
    assert os.path.isdir(root)


def test_serve_rate_pacer_bounds_throughput() -> None:
    """The egress bound (TPUFT_HEAL_SERVE_GBPS) paces writes: 1 MB at
    8 Gbps must take ~1 ms of injected sleep, not zero."""

    class Sink:
        def __init__(self) -> None:
            self.n = 0

        def write(self, data) -> None:
            self.n += len(data)

    sink = Sink()
    w = sc._RateWriter(sink, sc._ServePacer(8.0))
    t0 = time.perf_counter()
    w.write(b"\0" * (1 << 20))
    elapsed = time.perf_counter() - t0
    assert sink.n == 1 << 20
    assert elapsed >= 0.0008


def test_serve_rate_bound_is_process_aggregate() -> None:
    """The egress bound is an AGGREGATE bound: two streams writing through
    the same pacer share the configured rate (a striped or pooled joiner
    cannot multiply a donor's egress by its connection count)."""
    import threading as _threading

    class Sink:
        def write(self, data) -> None:
            pass

    pacer = sc._ServePacer(8.0)  # 1 GB/s -> 2 MB total = ~2 ms minimum
    writers = [sc._RateWriter(Sink(), pacer) for _ in range(2)]
    t0 = time.perf_counter()
    threads = [
        _threading.Thread(target=lambda w=w: w.write(b"\0" * (1 << 20)))
        for w in writers
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    # Per-connection pacing would finish both in ~1 ms wall; the shared
    # bucket needs ~2 ms for 2 MB.
    assert elapsed >= 0.0016


def test_two_heal_peers_each_get_half_the_heal_share() -> None:
    """Intra-class fairness (not just the class split): two concurrent
    heal streams from DISTINCT peers through one pacer each run at ~half
    the heal rate — asserted on the pacer's returned virtual delays, so
    the 1-core box's scheduler cannot flake it."""
    pacer = sc._ServePacer(8.0, heal_share=0.8)  # 8 Gb/s = 1 GB/s aggregate
    mb = 1 << 20
    # Interleave debits so both peers stay inside the activity window.
    for _ in range(4):
        delay_a = pacer.debit(mb, cls="heal", peer="joiner-a")
        delay_b = pacer.debit(mb, cls="heal", peer="joiner-b")
    # Each peer pushed 4 MB; at half of 1 GB/s each needs ~8 ms of
    # virtual delay (first debit of each ran uncontended at full rate,
    # so allow that 1 MB at 1 GB/s = ~1 ms of slack under the ideal).
    for delay in (delay_a, delay_b):
        assert 0.005 <= delay <= 0.010, (delay_a, delay_b)
    # ...and the split is fair: neither peer is ahead of the other by
    # more than one debit's worth.
    assert abs(delay_a - delay_b) <= 0.003, (delay_a, delay_b)


def test_fast_heal_peer_cannot_starve_a_late_one() -> None:
    """A joiner that got to the bucket first with a big backlog must not
    queue a second joiner behind its whole virtual backlog: the late
    peer's first debit pays only its own sub-bucket share."""
    pacer = sc._ServePacer(8.0, heal_share=0.8)
    # Peer A rams 16 MB through while alone (full heal rate).
    delay_a = 0.0
    for _ in range(16):
        delay_a = pacer.debit(1 << 20, cls="heal", peer="joiner-a")
    assert delay_a >= 0.014  # ~16 ms of backlog on A's own clock
    # Peer B arrives: its 1 MB debit must NOT inherit A's backlog (the
    # single-class-clock design would charge it ~17 ms).
    delay_b = pacer.debit(1 << 20, cls="heal", peer="joiner-b")
    assert delay_b <= 0.006, delay_b
