"""Committed-weights serving plane drills (pure Python — carries tier-1
in a container without the native toolchain):

- publisher/relay/subscriber roundtrips over loopback HTTP: bitwise
  adoption, descriptor integrity binding, delta-aware version bumps with
  exact bytes-saved accounting;
- resilience: upstream dying mid-pull fails over across the fleet like a
  striped heal; era regressions are rejected at the relay AND the
  reader; rapid version bumps under concurrent readers never produce a
  torn observation (leaves are a function of the step — any mix would
  show);
- chaos: the punisher's file-armed kill_relay drops a relay abruptly
  under live readers, who fail over without ever observing a bad
  version;
- manager integration: commits mark publications due at the cadence, the
  step boundary publishes AFTER a full speculative-window drain (R7's
  publish extension pins the ordering lexically; here we pin it
  observationally — published params always sit on the committed
  trajectory), publish failures never poison a commit, and a
  rollback-unwind retracts the due-but-unpublished version;
- the flagship chaos drill in strict AND pipelined depth-2 orderings:
  kill_relay + a refused commit + a mid-run heal while subscribers poll;
  every observed version is digest-valid, era-monotonic, and never the
  discarded speculation;
- shared-egress fairness: the serve pacer's heal-priority split
  (a healing joiner cannot be starved by N serving readers);
- the parameter-server fix: session errors narrate through the
  telemetry logger with their session id, and shutdown joins session
  threads.
"""

import json
import logging
import threading
import time
import urllib.request

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from test_ddp import scripted_manager

from torchft_tpu import metrics
from torchft_tpu import punisher
from torchft_tpu.checkpointing import serve_child as sc
from torchft_tpu.checkpointing.http_transport import _checkpoint_digest
from torchft_tpu.optim import Optimizer
from torchft_tpu.serving import (
    CachingRelay,
    WeightPublisher,
    WeightSubscriber,
)
from torchft_tpu.serving._wire import validate_latest
from torchft_tpu.utils import faultinject

_COUNTERS = {
    "pulls": "tpuft_serving_pulls_total",
    "pull_failures": "tpuft_serving_pull_failures_total",
    "failovers": "tpuft_serving_upstream_failovers_total",
    "delta_chunks": "tpuft_serving_delta_chunks_reused_total",
    "delta_bytes": "tpuft_serving_delta_bytes_saved_total",
    "stale_era": "tpuft_serving_stale_era_rejects_total",
    "integrity": "tpuft_serving_integrity_rejects_total",
    "reader_versions": "tpuft_serving_reader_versions_total",
    "reader_bytes": "tpuft_serving_reader_bytes_total",
    "relay_deaths": "tpuft_serving_relay_deaths_total",
    "publishes": "tpuft_publish_total",
    "publish_failures": "tpuft_publish_failures_total",
    "retracted": "tpuft_publish_retracted_total",
}


def counters() -> dict:
    return {k: metrics.counter_total(name) for k, name in _COUNTERS.items()}


def state_for(step: int, n_leaves: int = 4, leaf_elems: int = 512) -> dict:
    """Every leaf filled with ``step`` — a torn (mixed-version) read or a
    wrong-version adoption is visible in any single element."""
    return {
        f"w{i}": np.full(leaf_elems, float(step), np.float32)
        for i in range(n_leaves)
    }


def assert_version_is(version, step: int) -> None:
    assert version is not None
    assert version.step == step
    for leaf in version.params.values():
        np.testing.assert_array_equal(np.asarray(leaf), float(step))


# ---------------------------------------------------------------------------
# publisher -> relay -> subscriber roundtrips
# ---------------------------------------------------------------------------


def test_publish_subscribe_roundtrip_bitwise() -> None:
    pub = WeightPublisher(num_chunks=4, timeout=5.0)
    try:
        descriptor = pub.publish(step=3, quorum_id=7, state=state_for(3))
        assert descriptor["step"] == 3 and descriptor["quorum_id"] == 7
        assert validate_latest(descriptor) is None
        sub = WeightSubscriber([pub.address()], timeout=5.0)
        assert_version_is(sub.poll(), 3)
        assert sub.current().quorum_id == 7
        assert sub.current().digest == descriptor["digest"]
        # Nothing new: poll is a no-op, held version untouched.
        assert sub.poll() is None
        assert sub.current().step == 3
    finally:
        pub.shutdown()


def test_descriptor_digest_binding_rejected_when_tampered() -> None:
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        descriptor = pub.publish(step=1, quorum_id=0, state=state_for(1))
        bad = dict(descriptor)
        bad["chunk_crcs"] = list(bad["chunk_crcs"])
        bad["chunk_crcs"][0] ^= 1
        assert validate_latest(bad) is not None
        bad2 = dict(descriptor)
        bad2["step"] = 99
        assert validate_latest(bad2) is not None
    finally:
        pub.shutdown()


def test_relay_pulls_and_fans_out_bitwise() -> None:
    pub = WeightPublisher(num_chunks=4, timeout=5.0)
    relay = CachingRelay([pub.address()], timeout=5.0, start=False)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        before = counters()
        assert relay.poll_once() is True
        assert relay.poll_once() is False  # same version: no re-pull
        after = counters()
        assert after["pulls"] - before["pulls"] == 1
        # Many readers, one relay: all bitwise identical, publisher idle.
        subs = [WeightSubscriber([relay.address()], timeout=5.0) for _ in range(4)]
        for sub in subs:
            assert_version_is(sub.poll(), 1)
    finally:
        relay.shutdown()
        pub.shutdown()


def test_delta_version_bump_moves_only_changed_bytes() -> None:
    """Steady-state version bumps: chunks whose (crc, size) match the
    cached previous version are reused, not refetched — at the relay AND
    the reader; the saved bytes are pinned by the counters."""
    pub = WeightPublisher(num_chunks=4, timeout=5.0)
    relay = CachingRelay([pub.address()], timeout=5.0, start=False)
    try:
        state = state_for(1)
        pub.publish(step=1, quorum_id=0, state=state)
        relay.poll_once()
        sub = WeightSubscriber([relay.address()], timeout=5.0)
        assert_version_is(sub.poll(), 1)

        # Change ONE leaf of four; with 4 round-robin chunks the other
        # three chunks are byte-identical and must not cross the wire.
        state2 = dict(state)
        state2["w2"] = np.full(512, 2.0, np.float32)
        before = counters()
        pub.publish(step=2, quorum_id=0, state=state2)
        assert relay.poll_once() is True
        version = sub.poll()
        assert version is not None and version.step == 2
        np.testing.assert_array_equal(np.asarray(version.params["w2"]), 2.0)
        np.testing.assert_array_equal(np.asarray(version.params["w1"]), 1.0)
        after = counters()
        # Relay reused 3 chunks; the subscriber reused the same 3.
        assert after["delta_chunks"] - before["delta_chunks"] == 3
        full_bytes = sum(pub.latest()["chunk_sizes"])
        saved = after["delta_bytes"] - before["delta_bytes"]
        fetched = after["reader_bytes"] - before["reader_bytes"]
        # Saved on both legs: ~2x (3/4 of the payload each).
        assert saved > full_bytes
        assert 0 < fetched < full_bytes / 2
    finally:
        relay.shutdown()
        pub.shutdown()


# ---------------------------------------------------------------------------
# resilience: upstream death, era fencing, torn reads
# ---------------------------------------------------------------------------


def test_relay_fails_over_when_upstream_dies_mid_pull() -> None:
    """Two publishers announce the same committed version (bitwise
    identical, interchangeable — the striped-heal argument); one dies
    mid-pull and the relay finishes from the survivor."""
    pub_a = WeightPublisher(num_chunks=6, timeout=5.0)
    pub_b = WeightPublisher(num_chunks=6, timeout=5.0)
    relay = None
    try:
        state = state_for(5)
        desc_a = pub_a.publish(step=5, quorum_id=1, state=state)
        desc_b = pub_b.publish(step=5, quorum_id=1, state=state)
        assert desc_a["digest"] == desc_b["digest"]

        # pub_a's transport cuts the connection on its first chunk serve
        # (one-shot): whichever chunk the relay's round-robin hands it.
        died = []

        def fault(step: int, index: int):
            if not died:
                died.append(index)
                return "die"
            return None

        pub_a._transport._fault_hook = fault
        relay = CachingRelay(
            [pub_a.address(), pub_b.address()], timeout=5.0, start=False
        )
        before = counters()
        assert relay.poll_once() is True
        after = counters()
        assert after["failovers"] - before["failovers"] >= 1
        sub = WeightSubscriber([relay.address()], timeout=5.0)
        assert_version_is(sub.poll(), 5)
    finally:
        if relay is not None:
            relay.shutdown()
        pub_a.shutdown()
        pub_b.shutdown()


def test_relay_rejects_era_regression() -> None:
    """A stale-era survivor announcing a higher step must not roll the
    relay (and therefore every reader) backwards across quorum eras."""
    pub_new = WeightPublisher(num_chunks=2, timeout=5.0)
    pub_stale = WeightPublisher(num_chunks=2, timeout=5.0)
    relay = None
    try:
        pub_new.publish(step=10, quorum_id=5, state=state_for(10))
        relay = CachingRelay([pub_new.address()], timeout=5.0, start=False)
        assert relay.poll_once() is True
        # The fleet moves on; only a stale-era publisher remains visible.
        pub_stale.publish(step=12, quorum_id=4, state=state_for(12))
        relay._upstreams = [pub_stale.address()]
        before = counters()
        assert relay.poll_once() is False
        after = counters()
        assert after["stale_era"] - before["stale_era"] == 1
        assert relay.current().step == 10 and relay.current().quorum_id == 5
    finally:
        if relay is not None:
            relay.shutdown()
        pub_new.shutdown()
        pub_stale.shutdown()


def test_subscriber_rejects_era_regression() -> None:
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        pub.publish(step=10, quorum_id=5, state=state_for(10))
        sub = WeightSubscriber([pub.address()], timeout=5.0)
        assert_version_is(sub.poll(), 10)
        pub.publish(step=12, quorum_id=4, state=state_for(12))
        before = counters()
        assert sub.poll() is None
        after = counters()
        assert after["stale_era"] - before["stale_era"] == 1
        assert sub.current().step == 10
    finally:
        pub.shutdown()


def test_concurrent_readers_never_observe_torn_versions() -> None:
    """Rapid version bumps under a concurrent reader population: every
    adopted version must be internally consistent (all leaves equal its
    step) and step-monotone per reader — the verify-then-swap contract
    under real races."""
    pub = WeightPublisher(num_chunks=4, timeout=5.0)
    stop = threading.Event()
    torn: list = []
    observed: list = []

    def reader() -> None:
        sub = WeightSubscriber([pub.address()], timeout=5.0)
        last = 0
        while not stop.is_set():
            version = sub.poll()
            if version is None:
                continue
            values = {
                float(np.asarray(leaf).ravel()[0])
                for leaf in version.params.values()
            } | {
                float(np.asarray(leaf).ravel()[-1])
                for leaf in version.params.values()
            }
            if values != {float(version.step)}:
                torn.append((version.step, values))
            if version.step <= last:
                torn.append(("non-monotone", last, version.step))
            last = version.step
            observed.append(version.step)

    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        threads = [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        for step in range(2, 30):
            pub.publish(step=step, quorum_id=0, state=state_for(step))
            time.sleep(0.005)
        # Readers racing the bump storm abort those polls (the torn-read
        # fence); once the version stream settles every reader converges.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and 29 not in observed:
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not torn, torn
        assert observed, "readers never adopted anything"
        assert 29 in observed, sorted(set(observed))
    finally:
        stop.set()
        pub.shutdown()


def test_punisher_kill_relay_fault_file_and_reader_failover(
    tmp_path, monkeypatch
) -> None:
    """The punisher's kill_relay arm: the relay consumes the file-armed
    ``die`` at its next poll round and drops abruptly; subscribers fail
    over to the surviving endpoint (here: the publisher itself) without
    observing anything invalid."""
    fault_file = tmp_path / "fault"
    monkeypatch.setenv(faultinject.ENV_FAULT_FILE, str(fault_file))
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    relay = CachingRelay([pub.address()], timeout=5.0, start=False)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        assert relay.poll_once() is True
        sub = WeightSubscriber([relay.address(), pub.address()], timeout=5.0)
        assert_version_is(sub.poll(), 1)

        assert punisher.arm_stream_fault("kill_relay", str(fault_file))
        before = counters()
        assert relay.poll_once() is False
        assert relay.dead
        after = counters()
        assert after["relay_deaths"] - before["relay_deaths"] == 1

        # Reader fails over to the publisher endpoint for the next bump.
        pub.publish(step=2, quorum_id=0, state=state_for(2))
        assert_version_is(sub.poll(), 2)
    finally:
        relay.shutdown()
        pub.shutdown()


def test_punisher_kill_relay_targets_one_relay_by_tag(
    tmp_path, monkeypatch
) -> None:
    """A port-tagged kill_relay hits exactly the targeted relay of a
    fan-out tier; the untargeted one keeps serving."""
    fault_file = tmp_path / "fault"
    monkeypatch.setenv(faultinject.ENV_FAULT_FILE, str(fault_file))
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    relay_a = CachingRelay([pub.address()], timeout=5.0, start=False)
    relay_b = CachingRelay([pub.address()], timeout=5.0, start=False)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        assert relay_a.poll_once() and relay_b.poll_once()
        tag = relay_a._server.server_address[1]
        assert punisher.arm_stream_fault(
            "kill_relay", str(fault_file), donor_tag=str(tag)
        )
        relay_b.poll_once()  # wrong site: must NOT consume the arm
        assert not relay_b.dead
        relay_a.poll_once()
        assert relay_a.dead
        sub = WeightSubscriber([relay_b.address()], timeout=5.0)
        assert_version_is(sub.poll(), 1)
    finally:
        relay_a.shutdown()
        relay_b.shutdown()
        pub.shutdown()


# ---------------------------------------------------------------------------
# manager integration: cadence, drain-first publication, retraction
# ---------------------------------------------------------------------------


def _loss_fn(p, b):
    return jnp.sum((p["w"] - b) ** 2)  # grad = 2(w - b); sgd(0.1): w -= 0.2(w-b)


def _expected_trajectory(batches, w0=1.0) -> list:
    """Committed params after each step of the scripted loss above."""
    w = np.array([w0, w0], np.float32)
    out = []
    for b in batches:
        w = w - 0.1 * 2 * (w - b)
        out.append(w.copy())
    return out


def test_manager_publishes_on_commit_cadence() -> None:
    """every=2: publications land only for even committed steps, at the
    NEXT step boundary, carrying the committed params."""
    manager = scripted_manager()
    pub = WeightPublisher(every=2, num_chunks=2, timeout=5.0)
    opt = Optimizer(manager, optax.sgd(0.1), {"w": jnp.array([1.0, 1.0], jnp.float32)})
    manager.attach_publisher(pub, lambda: {"params": opt.params})
    published: list = []
    real_publish = pub.publish

    def spy(step, quorum_id, state):
        published.append((step, np.asarray(state["params"]["w"]).copy()))
        return real_publish(step, quorum_id, state)

    pub.publish = spy
    step_fn = opt.make_step_fn(_loss_fn)
    try:
        for i in range(5):
            step_fn(jnp.full((2,), float(i), jnp.float32))
        # Publication of the step-4 commit needs one more boundary.
        manager.start_quorum()
        manager.wait_quorum()
        assert [p[0] for p in published] == [2, 4]
        trajectory = _expected_trajectory([0.0, 1.0, 2.0, 3.0, 4.0])
        np.testing.assert_allclose(published[0][1], trajectory[1], rtol=1e-6)
        np.testing.assert_allclose(published[1][1], trajectory[3], rtol=1e-6)
        assert pub.latest()["step"] == 4
    finally:
        manager.shutdown(wait=False)


def test_publish_failure_never_poisons_commits() -> None:
    manager = scripted_manager()
    pub = WeightPublisher(every=1, num_chunks=2, timeout=5.0)
    opt = Optimizer(manager, optax.sgd(0.1), {"w": jnp.array([1.0, 1.0], jnp.float32)})
    manager.attach_publisher(pub, lambda: {"params": opt.params})

    def broken_publish(step, quorum_id, state):
        raise RuntimeError("publication plane down")

    pub.publish = broken_publish
    step_fn = opt.make_step_fn(_loss_fn)
    before = counters()
    try:
        committed = [step_fn(jnp.full((2,), float(i), jnp.float32))[1] for i in range(3)]
        assert committed == [True, True, True]
        assert manager.current_step() == 3
        assert manager.errored() is None
        after = counters()
        assert after["publish_failures"] - before["publish_failures"] >= 2
    finally:
        manager.shutdown(wait=False)


@pytest.mark.parametrize("depth", [2, 3], ids=["depth2", "depth3"])
def test_pipelined_publication_samples_only_committed_state(depth) -> None:
    """The R7 ordering, observed: with a depth-N window and every-step
    publication, every published state sits exactly on the committed
    trajectory — never a speculative value the window had in flight."""
    manager = scripted_manager(commit_pipeline_depth=depth)
    pub = WeightPublisher(every=1, num_chunks=2, timeout=5.0)
    opt = Optimizer(manager, optax.sgd(0.1), {"w": jnp.array([1.0, 1.0], jnp.float32)})
    manager.attach_publisher(pub, lambda: {"params": opt.params})
    published: list = []
    real_publish = pub.publish

    def spy(step, quorum_id, state):
        published.append((step, np.asarray(state["params"]["w"]).copy()))
        return real_publish(step, quorum_id, state)

    pub.publish = spy
    step_fn = opt.make_step_fn(_loss_fn)
    batches = [float(i) for i in range(6)]
    try:
        for b in batches:
            step_fn(jnp.full((2,), b, jnp.float32))
        opt.flush_pipeline()
        manager.start_quorum()
        manager.wait_quorum()
        trajectory = _expected_trajectory(batches)
        assert published, "nothing published"
        for step, w in published:
            assert 1 <= step <= len(batches)
            np.testing.assert_allclose(w, trajectory[step - 1], rtol=1e-6)
    finally:
        manager.shutdown(wait=False)


def test_retract_after_drops_due_version() -> None:
    pub = WeightPublisher(every=1, num_chunks=2, timeout=5.0)
    try:
        pub.note_commit(7, 1)
        assert pub.due()
        before = counters()
        pub.retract_after(5)
        assert not pub.due()
        assert counters()["retracted"] - before["retracted"] == 1
        # Retraction is bounded: a due version AT the surviving committed
        # step is kept.
        pub.note_commit(5, 1)
        pub.retract_after(5)
        assert pub.due()
    finally:
        pub.shutdown()


def test_rollback_unwind_reaches_retract_hook() -> None:
    """A refused pipelined commit's unwind calls the attached publisher's
    retract_after with the surviving committed step."""
    manager = scripted_manager(commit_pipeline_depth=1)
    votes = iter([True, False, True, True])
    manager._client.should_commit.side_effect = (
        lambda rank, step, vote, timeout: vote and next(votes)
    )
    pub = WeightPublisher(every=1, num_chunks=2, timeout=5.0)
    retracts: list = []
    real_retract = pub.retract_after

    def spy(committed_step):
        retracts.append(committed_step)
        return real_retract(committed_step)

    pub.retract_after = spy
    opt = Optimizer(manager, optax.sgd(0.1), {"w": jnp.array([1.0, 1.0], jnp.float32)})
    manager.attach_publisher(pub, lambda: {"params": opt.params})
    step_fn = opt.make_step_fn(_loss_fn)
    try:
        for i in range(4):
            step_fn(jnp.full((2,), float(i), jnp.float32))
        opt.flush_pipeline()
        assert opt.rollback_count == 1
        assert retracts, "rollback never reached the publisher"
    finally:
        manager.shutdown(wait=False)


# ---------------------------------------------------------------------------
# the flagship chaos drill: kill/heal + kill_relay under live readers
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("depth", [0, 2], ids=["strict", "pipelined2"])
def test_serving_chaos_drill(depth, tmp_path, monkeypatch) -> None:
    """Fleet chaos while subscribers poll: a refused commit (rollback in
    the pipelined ordering), a mid-run heal, a quorum-era change, and a
    punisher kill_relay. Every version any reader observed must be
    digest-valid, era-monotonic, and never the refused step's discarded
    speculation; after the relay dies readers fail over to the publisher
    endpoint and keep adopting."""
    fault_file = tmp_path / "fault"
    monkeypatch.setenv(faultinject.ENV_FAULT_FILE, str(fault_file))
    manager = scripted_manager(commit_pipeline_depth=depth)
    refused_dispatch = 3  # 0-indexed dispatch that the barrier refuses
    dispatches = {"n": 0}

    def voting(rank, step, vote, timeout):
        refuse = dispatches["n"] == refused_dispatch
        dispatches["n"] += 1
        return vote and not refuse

    manager._client.should_commit.side_effect = voting
    pub = WeightPublisher(every=1, num_chunks=2, timeout=5.0)
    opt = Optimizer(manager, optax.sgd(0.1), {"w": jnp.array([1.0, 1.0], jnp.float32)})
    manager.attach_publisher(pub, lambda: {"params": opt.params})
    relay = CachingRelay([pub.address()], poll_interval=0.02, timeout=5.0)

    stop = threading.Event()
    bad: list = []
    observed: list = []

    def reader() -> None:
        sub = WeightSubscriber([relay.address(), pub.address()], timeout=5.0)
        last_era = -1
        last_step = 0
        while not stop.is_set():
            version = sub.poll()
            if version is None:
                continue
            # Digest validity: recompute the binding from what we hold.
            values = {
                float(np.asarray(leaf).ravel()[0])
                for leaf in version.params["params"].values()
            }
            observed.append(
                (version.step, version.quorum_id, sorted(values))
            )
            if version.quorum_id is not None:
                if version.quorum_id < last_era:
                    bad.append(("era regression", last_era, version.quorum_id))
                last_era = version.quorum_id
            if version.step <= last_step:
                bad.append(("non-monotone step", last_step, version.step))
            last_step = version.step

    readers = [threading.Thread(target=reader) for _ in range(3)]
    for t in readers:
        t.start()
    try:
        step_fn = opt.make_step_fn(_loss_fn)
        batches = [float(i) for i in range(8)]
        pre_refusal_w = None
        for i, b in enumerate(batches):
            if i == 5:
                # Mid-run heal: a donor state lands (rebinds under the
                # writer, bumps the heal count) — later publications must
                # follow the healed trajectory, never a stale one.
                opt._load_state_dict(
                    {
                        "params": {"w": jnp.array([5.0, 5.0], jnp.float32)},
                        "opt_state": opt.opt_state,
                    }
                )
            if i == 4:
                # punisher: kill the relay under the live readers.
                punisher.arm_stream_fault("kill_relay", str(fault_file))
            if i == refused_dispatch:
                pre_refusal_w = np.asarray(opt.params["w"]).copy()
            step_fn(jnp.full((2,), b, jnp.float32))
        opt.flush_pipeline()
        manager.start_quorum()
        manager.wait_quorum()
        # Let readers catch the final version, then stop.
        deadline = time.monotonic() + 5.0
        final_step = pub.latest()["step"]
        while time.monotonic() < deadline and not any(
            step == final_step for step, _era, _v in observed
        ):
            time.sleep(0.05)
        stop.set()
        for t in readers:
            t.join(timeout=10)

        assert not bad, bad
        assert observed, "no reader ever adopted a version"
        # The refused dispatch's speculation was discarded quorum-wide:
        # its would-have-been params must never surface.
        assert pre_refusal_w is not None
        discarded = pre_refusal_w - 0.2 * (
            pre_refusal_w - batches[refused_dispatch]
        )
        for _step, _era, values in observed:
            for v in values:
                assert not np.allclose(v, discarded[0]), (
                    "a reader observed the discarded speculation",
                    v,
                    discarded,
                )
        # The heal is visible downstream: some post-heal version carries
        # the healed trajectory (values derived from w=5.0), which the
        # pre-heal trajectory never produces.
        assert any(v and v[0] > 3.0 for _s, _e, v in observed), observed
        # The relay did die under the readers.
        assert relay.dead
    finally:
        stop.set()
        relay.shutdown()
        manager.shutdown(wait=False)


# ---------------------------------------------------------------------------
# shared-egress fairness: heal priority on the serve pacer
# ---------------------------------------------------------------------------


def test_pacer_heal_priority_split() -> None:
    """While both classes are active the heal class gets its configured
    share of the paced rate (80% here) and serving readers get the rest
    (20% — 5x the per-byte cost); a lone class gets the full rate."""
    pacer = sc._ServePacer(8.0, heal_share=0.8)  # 8 Gb/s = 1 GB/s aggregate
    chunk = 1 << 20  # 1 MiB
    per_mib = chunk / 1e9  # seconds per MiB at the full rate
    # Serving alone: full rate.
    solo = pacer.debit(chunk, cls="serving")
    assert solo == pytest.approx(per_mib, rel=0.25), (solo, per_mib)
    # Heal joins: both classes active from here on. Heal pays 1/0.8x.
    h1 = pacer.debit(chunk, cls="heal")
    assert h1 == pytest.approx(per_mib / 0.8, rel=0.25), (h1, per_mib)
    # A contended serving MiB pays 1/0.2x = 5x the full-rate cost.
    s2 = pacer.debit(chunk, cls="serving")
    assert s2 - solo == pytest.approx(per_mib / 0.2, rel=0.25), (s2, solo)
    # Heal's incremental cost stays at its share: readers cannot starve it.
    h2 = pacer.debit(chunk, cls="heal")
    assert h2 - h1 == pytest.approx(per_mib / 0.8, rel=0.25), (h2, h1)
    assert (s2 - solo) > 3 * (h2 - h1)


def test_pacer_single_class_keeps_full_rate_and_shared_bucket() -> None:
    """Heal-only traffic is unchanged by the split (full rate), and two
    heal writers still share one clock — the PR-8 aggregate-egress
    contract."""
    pacer = sc._ServePacer(8.0)
    chunk = 1 << 20
    d1 = pacer.debit(chunk, cls="heal")
    d2 = pacer.debit(chunk, cls="heal")
    per_mib = chunk / 1e9
    assert d2 - d1 == pytest.approx(per_mib, rel=0.2)


def test_maybe_pace_serve_carries_class(monkeypatch) -> None:
    monkeypatch.setenv(sc.ENV_SERVE_GBPS, "8.0")
    # Fresh shared pacer for the configured rate.
    out = sc.maybe_pace_serve(object(), cls="serving")
    assert isinstance(out, sc._RateWriter)
    assert out._cls == "serving"
    default = sc.maybe_pace_serve(object())
    assert default._cls == "heal"


# ---------------------------------------------------------------------------
# parameter server: diagnosable sessions + bounded shutdown
# ---------------------------------------------------------------------------


def test_parameter_server_session_error_logged_and_threads_joined(caplog) -> None:
    from torchft_tpu.parameter_server import ParameterServer

    class FailingPS(ParameterServer):
        def forward(self, session_id, pg):
            raise RuntimeError("session wedged")

    server = FailingPS(timeout=5.0)
    try:
        with caplog.at_level(logging.ERROR, logger="tpuft_errors"):
            req = urllib.request.Request(
                f"{server.address()}/new_session", method="POST"
            )
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                session = json.loads(resp.read())
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not caplog.records:
                time.sleep(0.02)
        records = [r for r in caplog.records if r.name == "tpuft_errors"]
        assert records, "session failure never narrated"
        record = records[0]
        assert session["session_id"] in getattr(record, "replica_id", "")
        assert "session wedged" in getattr(record, "error", "")
    finally:
        server.shutdown()
    # Bounded shutdown: no session thread left running.
    live = [t.name for t in threading.enumerate() if t.name.startswith("ps-session")]
    assert not live, live


def test_parameter_server_session_error_narrates_unit(caplog, monkeypatch) -> None:
    """Native-free seam test of the same fix (the e2e above skips without
    the toolchain): _serve_session funnels a forward() crash into the
    telemetry error logger with the session id and drops the session from
    the live-thread registry."""
    from unittest.mock import MagicMock

    from torchft_tpu import parameter_server as ps_mod

    class FailingPS(ps_mod.ParameterServer):
        def forward(self, session_id, pg):
            raise RuntimeError("session wedged")

    monkeypatch.setattr(ps_mod, "ProcessGroupTCP", MagicMock())
    server = FailingPS.__new__(FailingPS)
    server.timeout = 1.0
    server._sessions_lock = threading.Lock()
    server._sessions = {"deadbeef": threading.current_thread()}
    server._store = MagicMock()
    server._store.address.return_value = "store:0"
    with caplog.at_level(logging.ERROR, logger="tpuft_errors"):
        server._serve_session("deadbeef")
    records = [r for r in caplog.records if r.name == "tpuft_errors"]
    assert records, "session failure never narrated"
    assert "deadbeef" in getattr(records[0], "replica_id", "")
    assert "session wedged" in getattr(records[0], "error", "")
    assert "deadbeef" not in server._sessions


def test_fleet_status_publish_column() -> None:
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "fleet_status",
        Path(__file__).resolve().parent.parent / "scripts" / "fleet_status.py",
    )
    fleet_status = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet_status)
    now = 1000.0
    snap = {
        "metrics": {
            "gauges": {
                "tpuft_publish_last_step": [{"value": 12.0}],
                "tpuft_publish_last_time": [{"value": 997.0}],
            }
        }
    }
    assert fleet_status._publish_state(snap, now) == "s12@3.0s"
    assert fleet_status._publish_state({"metrics": {"gauges": {}}}, now) is None
    assert ("publish", "PUBLISH") in fleet_status._COLUMNS


def test_fleet_trace_explain_prints_publish_lines() -> None:
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "fleet_trace",
        Path(__file__).resolve().parent.parent / "scripts" / "fleet_trace.py",
    )
    fleet_trace = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet_trace)

    def event(seq, name, **kw):
        base = {
            "seq": seq, "name": name, "ph": "i", "cat": "ft",
            "t_wall": 100.0 + seq, "t_mono": float(seq),
            "replica_id": "train_0", "group_rank": 0,
            "step": 7, "quorum_id": 2, "args": {},
        }
        base.update(kw)
        return base

    merged = fleet_trace.merge_events(
        [
            event(1, "commit"),
            event(
                2, "publish",
                args={"bytes": 2 << 20, "digest": "abcdef123456"},
            ),
            event(3, "publish_retracted"),
        ]
    )
    text = fleet_trace.explain_step(merged, 7)
    assert "published: train_0/0 staged version step 7" in text
    assert "abcdef123456" in text
    assert "publish RETRACTED: train_0/0" in text


def test_checkpoint_digest_matches_descriptor() -> None:
    """The /serving/latest digest is exactly the heal plane's binding —
    one integrity chain from donor staging to reader adoption."""
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        descriptor = pub.publish(step=4, quorum_id=2, state=state_for(4))
        assert descriptor["digest"] == _checkpoint_digest(
            4, descriptor["crc_algo"], descriptor["chunk_crcs"]
        )
    finally:
        pub.shutdown()


# ---------------------------------------------------------------------------
# versioned history: pinned reads, latest-1, retraction, delta chains
# ---------------------------------------------------------------------------


def test_pinned_version_reader_exact_and_wrong_version_refused() -> None:
    """pin=<step> follows exactly that resident version; any other step
    offered under the route is refused (wrong-version counter), so a
    canary reader structurally cannot drift."""
    pub = WeightPublisher(num_chunks=4, timeout=5.0)
    try:
        for s in (1, 2, 3):
            pub.publish(step=s, quorum_id=0, state=state_for(s))
        sub = WeightSubscriber([pub.address()], timeout=5.0, pin=2)
        assert_version_is(sub.poll(), 2)
        # Later bumps do not move a pinned reader.
        pub.publish(step=4, quorum_id=0, state=state_for(4))
        assert sub.poll() is None
        assert sub.current().step == 2
        # A descriptor for another step is refused outright.
        before = counters_history()
        other = pub.latest()
        assert sub._poll(latest=other) is None
        after = counters_history()
        assert (
            after["wrong_version"] - before["wrong_version"] == 1
        )
    finally:
        pub.shutdown()


def test_latest_minus_one_reader_trails_by_one() -> None:
    pub = WeightPublisher(num_chunks=4, timeout=5.0)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        sub = WeightSubscriber([pub.address()], timeout=5.0, pin="latest-1")
        assert sub.poll() is None  # only one resident version: no latest-1
        pub.publish(step=2, quorum_id=0, state=state_for(2))
        assert_version_is(sub.poll(), 1)
        pub.publish(step=3, quorum_id=0, state=state_for(3))
        assert_version_is(sub.poll(), 2)
    finally:
        pub.shutdown()


def counters_history() -> dict:
    names = {
        "retractions": "tpuft_history_retractions_total",
        "retracted_reads": "tpuft_history_retracted_reads_total",
        "retraction_adoptions": "tpuft_serving_retraction_adoptions_total",
        "wrong_version": "tpuft_serving_wrong_version_rejects_total",
        "meta_skips": "tpuft_serving_meta_fetches_skipped_total",
        "chain_hops": "tpuft_history_delta_chain_hops_total",
        "delta_bytes": "tpuft_serving_delta_bytes_saved_total",
    }
    return {k: metrics.counter_total(n) for k, n in names.items()}


def test_retract_version_converges_readers_and_relay_to_previous() -> None:
    """retract_version(V): the publisher drops V everywhere (descriptors,
    chunks), re-announces V-1 seq-newer, and BOTH a direct reader and a
    relay-backed reader converge to V-1; pinned-V readers get the 410
    tombstone, never retracted bytes."""
    pub = WeightPublisher(num_chunks=4, timeout=5.0)
    relay = CachingRelay([pub.address()], timeout=5.0, start=False)
    try:
        for s in (1, 2, 3):
            pub.publish(step=s, quorum_id=0, state=state_for(s))
        relay.poll_once()
        direct = WeightSubscriber([pub.address()], timeout=5.0)
        via_relay = WeightSubscriber([relay.address()], timeout=5.0)
        assert_version_is(direct.poll(), 3)
        assert_version_is(via_relay.poll(), 3)
        pinned = WeightSubscriber([pub.address()], timeout=5.0, pin=3)
        assert_version_is(pinned.poll(), 3)

        before = counters_history()
        assert pub.retract_version(3)
        # Direct reader converges immediately (seq-newer V-1).
        v = direct.poll()
        assert_version_is(v, 2)
        # The relay adopts the retraction and fans V-1 out.
        assert relay.poll_once() is True
        assert relay.current().step == 2
        assert_version_is(via_relay.poll(), 2)
        # The pinned-3 reader is told the version is GONE (410), never
        # served stale bytes and never silently failed over.
        assert pinned.poll() is None
        assert pinned.pin_retracted
        after = counters_history()
        assert after["retractions"] - before["retractions"] == 1
        assert after["retraction_adoptions"] - before["retraction_adoptions"] >= 2
        assert after["retracted_reads"] - before["retracted_reads"] >= 1
        # Forward recovery: the next publish moves everyone ahead again.
        pub.publish(step=4, quorum_id=0, state=state_for(4))
        assert_version_is(direct.poll(), 4)
        assert relay.poll_once() is True
        assert_version_is(via_relay.poll(), 4)
    finally:
        relay.shutdown()
        pub.shutdown()


def test_punisher_retract_version_armed_via_fault_file(
    tmp_path, monkeypatch
) -> None:
    """The punisher's retract_version arm: the NEXT publish consumes it
    and immediately retracts the just-published version — readers only
    ever converge to V-1 ("canary shipped and was found bad")."""
    fault_file = tmp_path / "fault"
    monkeypatch.setenv(faultinject.ENV_FAULT_FILE, str(fault_file))
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        sub = WeightSubscriber([pub.address()], timeout=5.0)
        assert_version_is(sub.poll(), 1)
        assert punisher.arm_stream_fault("retract_version", str(fault_file))
        before = counters_history()
        pub.publish(step=2, quorum_id=0, state=state_for(2))
        after = counters_history()
        assert after["retractions"] - before["retractions"] == 1
        assert pub.latest()["step"] == 1
        assert pub.is_retracted(2)
        # The reader never adopts the retracted canary.
        v = sub.poll()
        assert v is None or v.step == 1
        assert sub.current().step == 1
    finally:
        pub.shutdown()


@pytest.mark.parametrize("depth", [0, 2], ids=["strict", "pipelined2"])
def test_rollback_storm_drill(depth, tmp_path, monkeypatch) -> None:
    """The rollback-storm chaos drill in strict AND pipelined orderings:
    a training manager publishes every commit while >= 2 readers poll; a
    punisher-armed retract_version fires mid-run. Every reader must end
    on the surviving version with zero torn / stale-era / wrong-version
    adoptions, and the only step regressions any reader observes are
    seq-sanctioned retractions."""
    fault_file = tmp_path / "fault"
    monkeypatch.setenv(faultinject.ENV_FAULT_FILE, str(fault_file))
    manager = scripted_manager(commit_pipeline_depth=depth)
    pub = WeightPublisher(every=1, num_chunks=2, timeout=5.0)
    opt = Optimizer(manager, optax.sgd(0.1), {"w": jnp.array([1.0, 1.0], jnp.float32)})
    manager.attach_publisher(pub, lambda: {"params": opt.params})

    stop = threading.Event()
    bad: list = []
    readers_state: list = []

    def reader(slot: int) -> None:
        sub = WeightSubscriber([pub.address()], timeout=5.0)
        last = None
        while not stop.is_set():
            version = sub.poll()
            if version is None:
                time.sleep(0.005)
                continue
            values = {
                float(np.asarray(leaf).ravel()[0])
                for leaf in version.params["params"].values()
            }
            if last is not None:
                if version.step <= last.step:
                    # Only a seq-sanctioned retraction may regress.
                    sanctioned = (
                        version.pub_seq is not None
                        and last.pub_seq is not None
                        and version.pub_id == last.pub_id
                        and version.pub_seq > last.pub_seq
                    )
                    if not sanctioned:
                        bad.append(("unsanctioned regression", last.step, version.step))
                if (
                    version.quorum_id is not None
                    and last.quorum_id is not None
                    and version.quorum_id < last.quorum_id
                    and version.step > last.step
                ):
                    bad.append(("era regression", last.quorum_id, version.quorum_id))
            last = version
            readers_state.append((slot, version.step))

    threads = [threading.Thread(target=reader, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    try:
        step_fn = opt.make_step_fn(_loss_fn)
        retract_before = counters_history()["retractions"]
        for i in range(6):
            if i == 3:
                punisher.arm_stream_fault("retract_version", str(fault_file))
            step_fn(jnp.full((2,), float(i), jnp.float32))
        opt.flush_pipeline()
        manager.start_quorum()
        manager.wait_quorum()
        assert counters_history()["retractions"] - retract_before >= 1
        survivor = pub.latest()["step"]
        retracted = [s for s in range(1, 7) if pub.is_retracted(s)]
        assert retracted, "the armed retraction never fired"
        # Every reader converges to the surviving latest version.
        deadline = time.monotonic() + 10.0
        converged = set()
        while time.monotonic() < deadline and len(converged) < 3:
            converged = {
                slot for slot, step in readers_state if step == survivor
            }
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not bad, bad[:5]
        assert len(converged) == 3, (converged, survivor, readers_state[-10:])
        # Zero wrong-version adoptions: nothing retracted is held.
        assert survivor not in retracted
    finally:
        stop.set()
        manager.shutdown(wait=False)
        pub.shutdown(wait=False)


def test_lying_notify_body_cannot_cause_bad_adoption() -> None:
    """The delta-aware notify body is ADVISORY: a forged descriptor with
    tampered CRCs fails digest binding; a forged changed-chunk set on a
    valid descriptor cannot corrupt the adoption — the reader's own
    (crc, size) comparison decides what to fetch and every chunk still
    verifies."""
    pub = WeightPublisher(num_chunks=4, timeout=5.0)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        sub = WeightSubscriber([pub.address()], timeout=5.0)
        assert_version_is(sub.poll(), 1)
        state2 = state_for(1)
        state2["w2"] = np.full(512, 2.0, np.float32)
        descriptor = pub.publish(step=2, quorum_id=0, state=state2)
        # Forged body 1: tampered CRC — rejected before any transfer.
        forged = dict(descriptor)
        forged["chunk_crcs"] = list(forged["chunk_crcs"])
        forged["chunk_crcs"][0] ^= 1
        before = counters()
        assert sub._poll(latest=forged) is None
        assert counters()["integrity"] - before["integrity"] == 1
        # Forged body 2: a lying changed-chunk hint on a VALID descriptor
        # (claims nothing changed). Adoption still lands the correct
        # bytes: the hint cannot override the reader's own crc diff.
        lying = dict(descriptor)
        lying["delta_base_step"] = 1
        lying["changed_chunks"] = []
        v = sub._poll(latest=lying)
        assert v is not None and v.step == 2
        np.testing.assert_array_equal(np.asarray(v.params["w2"]), 2.0)
        np.testing.assert_array_equal(np.asarray(v.params["w1"]), 1.0)
    finally:
        pub.shutdown()


def test_meta_skip_on_sparse_bumps_and_notify_delta_hint() -> None:
    """Sparse version bumps skip the /meta RTT (tree_token cache) and a
    long-poll wake carries the changed-chunk set computed from the
    server's history ring."""
    pub = WeightPublisher(num_chunks=4, timeout=5.0)
    try:
        state = state_for(1)
        pub.publish(step=1, quorum_id=0, state=state)
        sub = WeightSubscriber([pub.address()], timeout=5.0)
        assert_version_is(sub.poll(), 1)
        before = counters_history()
        state2 = dict(state)
        state2["w1"] = np.full(512, 2.0, np.float32)
        pub.publish(step=2, quorum_id=0, state=state2)
        v = sub.wait_for_update(hold=5.0)
        assert v is not None and v.step == 2
        after = counters_history()
        assert after["meta_skips"] - before["meta_skips"] == 1
        # The notify body itself carries the changed-chunk set vs the
        # reader's watermark (advisory; verified by the lying-body test).
        from torchft_tpu.serving._wire import fetch_notify

        body = fetch_notify(pub.address(), 1, 5.0, hold=0.2)
        assert body is not None and body["step"] == 2
        assert body.get("delta_base_step") == 1
        assert body.get("changed_chunks") == [1]
    finally:
        pub.shutdown()


def test_delta_chain_lagging_reader_moves_only_changed_bytes() -> None:
    """A reader that SKIPPED a published version (held V-2) adopts the
    newest moving strictly fewer bytes than a full refetch — the
    chunk-level (crc, size) match composes across the ring, counted by
    the delta-chain hop counter."""
    pub = WeightPublisher(num_chunks=8, timeout=5.0)
    try:
        state = {f"w{i}": np.full(512, 1.0, np.float32) for i in range(8)}
        pub.publish(step=1, quorum_id=0, state=state)
        lagger = WeightSubscriber([pub.address()], timeout=5.0)
        assert lagger.poll().step == 1
        # Two bumps while the lagger sleeps; each changes ONE leaf.
        state2 = dict(state)
        state2["w2"] = np.full(512, 22.0, np.float32)
        pub.publish(step=2, quorum_id=0, state=state2)
        state3 = dict(state2)
        state3["w5"] = np.full(512, 35.0, np.float32)
        pub.publish(step=3, quorum_id=0, state=state3)
        before = counters_history()
        reader_before = counters()["reader_bytes"]
        v = lagger.poll()  # V-2 -> V in ONE adoption
        assert v is not None and v.step == 3
        np.testing.assert_array_equal(np.asarray(v.params["w2"]), 22.0)
        np.testing.assert_array_equal(np.asarray(v.params["w5"]), 35.0)
        after = counters_history()
        fetched = counters()["reader_bytes"] - reader_before
        full = sum(pub.latest()["chunk_sizes"])
        # Only the two changed chunks moved: strictly fewer bytes than a
        # full refetch, pinned by the counters.
        assert 0 < fetched < full / 2
        assert after["delta_bytes"] - before["delta_bytes"] > 0
        assert after["chain_hops"] - before["chain_hops"] == 2
    finally:
        pub.shutdown()


def test_child_mode_staged_history_serves_pinned_versions() -> None:
    """Child serve mode: the resident history versions live as the serve
    child's /dev/shm epoch dirs — a pinned reader fetches an OLDER
    version's chunks from the sidecar, and retraction removes the epoch
    (the version 410s instead of serving deleted bytes)."""
    from torchft_tpu.checkpointing.http_transport import HTTPTransport

    transport = HTTPTransport(
        timeout=5.0, num_chunks=2, serve_mode="child", keep_versions=4
    )
    if not transport._child_serving():
        transport.shutdown(wait=False)
        pytest.skip("serve child unavailable on this box")
    pub = WeightPublisher(timeout=5.0, transport=transport)
    try:
        for s in (1, 2, 3):
            pub.publish(step=s, quorum_id=0, state=state_for(s))
        pinned = WeightSubscriber([pub.address()], timeout=5.0, pin=1)
        assert_version_is(pinned.poll(), 1)
        latest = WeightSubscriber([pub.address()], timeout=5.0)
        assert_version_is(latest.poll(), 3)
        # Retract the newest: readers converge to 2, the pinned-3 route
        # answers 410 and the child's epoch for 3 is gone.
        pub.retract_version(3)
        assert_version_is(latest.poll(), 2)
        pinned3 = WeightSubscriber([pub.address()], timeout=5.0, pin=3)
        assert pinned3.poll() is None
        assert pinned3.pin_retracted
    finally:
        pub.shutdown(wait=False)
        transport.shutdown(wait=False)


def test_fleet_trace_explain_prints_history_and_retraction_lines() -> None:
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "fleet_trace",
        Path(__file__).resolve().parent.parent / "scripts" / "fleet_trace.py",
    )
    fleet_trace = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet_trace)

    def event(seq, name, **kw):
        base = {
            "seq": seq, "name": name, "ph": "i", "cat": "ft",
            "t_wall": 100.0 + seq, "t_mono": float(seq),
            "replica_id": "train_0", "group_rank": 0,
            "step": 7, "quorum_id": 2, "args": {},
        }
        base.update(kw)
        return base

    merged = fleet_trace.merge_events(
        [
            event(1, "history_exact_serve", args={"drained_step": 9}),
            event(2, "version_retracted", args={"survivor": 6}),
        ]
    )
    text = fleet_trace.explain_step(merged, 7)
    assert "served step 7 EXACTLY from its committed ring" in text
    assert "drained to step 9" in text
    assert "version RETRACTED" in text
    assert "readers converge to step 6" in text
