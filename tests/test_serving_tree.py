"""Planet-scale read fan-out drills: hierarchical relay tree, long-poll
push, multi-tenant fairness (pure Python — carries tier-1 in a container
without the native toolchain):

- long-poll push edge: ``/serving/notify`` answers immediately when a
  newer version exists, parks until a publish and wakes in ~a wire RTT,
  expires its bounded hold with a 204 (the client re-arms), and NEVER
  changes the trust story — a notify-delivered descriptor runs the same
  verify-then-swap pipeline, so era regressions are rejected on the push
  path too;
- relay tree at depth: relays stack (publisher -> root -> edge), depth
  is announced and learned per tier, a notify chain propagates a publish
  down the tree far faster than the poll cadence, and an interior relay
  dying re-homes its children to a sibling announcing the same digest
  with zero invalid adoptions (the striped-heal failover argument,
  composed transitively);
- jittered poll fallback: deterministic per-reader seeds spread the
  herd, exponential backoff caps the hammering of a dead tier;
- netem at the client fetch seam: every serving pull charges the
  emulated link, and a server that already paced the body is not
  double-billed;
- multi-tenant fairness + auth: per-tenant sub-buckets of the serving
  class split within 10% of their configured entitlements while a
  healing joiner keeps its TPUFT_HEAL_SERVE_PRIORITY_SHARE above ALL
  tenants; bearer tokens identify tenants at every serve seam (relay,
  publisher announce, inline transport, serve-child sidecar) and an
  unknown token is refused 401 everywhere.

The >=100-reader deep-tree drill is marked ``slow`` (tier-1 keeps the
depth-2 / fan-out-2 drill); benchmarks/relay_tree_bench.py measures the
same topology with out-of-process relays and SIGKILL chaos.
"""

import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from torchft_tpu import metrics, punisher
from torchft_tpu.checkpointing import serve_child as sc
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.serving import (
    CachingRelay,
    PollPacer,
    WeightPublisher,
    WeightSubscriber,
)
from torchft_tpu.serving import _wire
from torchft_tpu.utils import faultinject, netem


def state_for(step: int, n_leaves: int = 4, leaf_elems: int = 256) -> dict:
    """Every leaf filled with ``step`` — a torn or wrong-version adoption
    is visible in any single element."""
    return {
        f"w{i}": np.full(leaf_elems, float(step), np.float32)
        for i in range(n_leaves)
    }


def assert_version_is(version, step: int) -> None:
    assert version is not None
    assert version.step == step
    for leaf in version.params.values():
        np.testing.assert_array_equal(np.asarray(leaf), float(step))


def wait_counter_above(name: str, floor: float, deadline_s: float = 5.0) -> float:
    """Poll a counter past ``floor``: the serve-side debit for a body's
    final slice lands a beat AFTER the client finished reading it, so
    exact-count asserts must wait for the server thread, not race it."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        value = metrics.counter_total(name)
        if value > floor:
            return value
        time.sleep(0.02)
    raise AssertionError(f"{name} never rose above {floor}")


# ---------------------------------------------------------------------------
# jittered poll pacing (the fallback path must not herd)
# ---------------------------------------------------------------------------


def test_poll_pacer_deterministic_and_jittered() -> None:
    """Same seed -> same delay sequence (reproducible drills); distinct
    seeds -> spread delays (no synchronized herd); every delay inside
    the 0.5-1.5x jitter window."""
    a = [PollPacer(1.0, seed=7).next_delay() for _ in range(16)]
    b = [PollPacer(1.0, seed=7).next_delay() for _ in range(16)]
    assert a == b
    c = [PollPacer(1.0, seed=8).next_delay() for _ in range(16)]
    assert a != c
    for delay in a + c:
        assert 0.5 <= delay <= 1.5
    # 16 readers with distinct seeds do not collapse onto one instant.
    first = [PollPacer(1.0, seed=s).next_delay() for s in range(16)]
    assert len({round(d, 3) for d in first}) > 8


def test_poll_pacer_backoff_grows_caps_and_resets() -> None:
    pacer = PollPacer(1.0, seed=0)
    delays = [pacer.next_delay(failed=True) for _ in range(8)]
    # Consecutive failures double the cadence (jitter-scaled) up to 16x.
    assert delays[0] <= 3.0  # 2x mult, jitter <= 1.5
    assert max(delays) <= 16.0 * 1.5
    assert delays[5] > 4.0  # deep backoff is well past the base cadence
    ok = pacer.next_delay(failed=False)
    assert 0.5 <= ok <= 1.5  # clean round resets the multiplier


# ---------------------------------------------------------------------------
# long-poll notify edge
# ---------------------------------------------------------------------------


def test_notify_immediate_when_newer_exists() -> None:
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        pub.publish(step=3, quorum_id=0, state=state_for(3))
        descriptor = _wire.fetch_notify(pub.address(), after=0, timeout=5.0)
        assert descriptor is not None and descriptor["step"] == 3
        assert _wire.validate_latest(descriptor) is None
        assert descriptor["depth"] == 0
    finally:
        pub.shutdown()


def test_notify_parks_until_publish_then_wakes() -> None:
    """A waiter armed BEFORE the publish wakes with the new descriptor
    in well under the hold — push, not poll, delivered it."""
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        wakeups_before = metrics.counter_total("tpuft_serving_notify_wakeups_total")
        result: list = []

        def waiter() -> None:
            t0 = time.perf_counter()
            descriptor = _wire.fetch_notify(
                pub.address(), after=1, timeout=5.0, hold=10.0
            )
            result.append((descriptor, time.perf_counter() - t0))

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.3)
        pub.publish(step=2, quorum_id=0, state=state_for(2))
        t.join(timeout=10)
        assert result, "waiter never returned"
        descriptor, elapsed = result[0]
        assert descriptor is not None and descriptor["step"] == 2
        assert elapsed < 5.0, elapsed  # far under the 10 s hold
        assert (
            metrics.counter_total("tpuft_serving_notify_wakeups_total")
            > wakeups_before
        )
    finally:
        pub.shutdown()


def test_notify_hold_expires_204_and_client_rearms() -> None:
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        requests_before = metrics.counter_total(
            "tpuft_serving_notify_requests_total"
        )
        assert (
            _wire.fetch_notify(pub.address(), after=1, timeout=5.0, hold=0.2)
            is None
        )
        assert (
            metrics.counter_total("tpuft_serving_notify_requests_total")
            > requests_before
        )
    finally:
        pub.shutdown()


def test_subscriber_wait_for_update_adopts_via_push() -> None:
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        sub = WeightSubscriber([pub.address()], timeout=5.0, notify=True)
        assert_version_is(sub.poll(), 1)
        adopted: list = []

        def reader() -> None:
            adopted.append(sub.wait_for_update(hold=10.0))

        t = threading.Thread(target=reader)
        t.start()
        time.sleep(0.2)
        pub.publish(step=2, quorum_id=0, state=state_for(2))
        t.join(timeout=10)
        assert adopted and adopted[0] is not None
        assert_version_is(adopted[0], 2)
    finally:
        pub.shutdown()


def test_notify_path_still_rejects_era_regression() -> None:
    """Push is a latency plane, never a trust plane: a notify wake into a
    stale-era descriptor goes through the identical poll verification and
    is rejected; the held version stays."""
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        pub.publish(step=5, quorum_id=3, state=state_for(5))
        sub = WeightSubscriber([pub.address()], timeout=5.0, notify=True)
        assert_version_is(sub.poll(), 5)
        rejects_before = metrics.counter_total(
            "tpuft_serving_stale_era_rejects_total"
        )
        pub.publish(step=6, quorum_id=1, state=state_for(6))  # era regressed
        assert sub.wait_for_update(hold=2.0) is None
        assert_version_is(sub.current(), 5)
        assert (
            metrics.counter_total("tpuft_serving_stale_era_rejects_total")
            > rejects_before
        )
    finally:
        pub.shutdown()


def test_relay_refuses_meta_digest_mismatch() -> None:
    """The relay's /meta fetch is digest-bound to the validated descriptor
    BEFORE adoption (tpuft_check R9 verify-before-adopt): a corrupt or
    torn upstream meta is a counted pull failure — the relay keeps serving
    its held version and never caches the bad bytes."""
    import pickle

    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    relay = CachingRelay([pub.address()], timeout=5.0, start=False)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        assert relay.poll_once()
        assert_version_is_cached(relay, 1)

        orig = relay._fetch_failover

        def corrupt_meta(live, route, expect_crc, algo, expect_size=None):
            data = orig(
                live, route, expect_crc, algo, expect_size=expect_size
            )
            if route.endswith("/meta"):
                return pickle.dumps({"step": -1, "digest": "bogus"})
            return data

        relay._fetch_failover = corrupt_meta
        rejects_before = metrics.counter_total(
            "tpuft_serving_meta_digest_rejects_total"
        )
        pub.publish(step=2, quorum_id=0, state=state_for(2))
        with pytest.raises(Exception, match="descriptor digest"):
            relay.poll_once()
        # Held state untouched; the refusal is visible on the dashboard.
        assert relay.current().step == 1
        assert (
            metrics.counter_total("tpuft_serving_meta_digest_rejects_total")
            > rejects_before
        )
        # A healed upstream converges normally on the next poll.
        relay._fetch_failover = orig
        assert relay.poll_once()
        assert relay.current().step == 2
    finally:
        relay.shutdown(wait=False)
        pub.shutdown()


def assert_version_is_cached(relay, step: int) -> None:
    current = relay.current()
    assert current is not None and current.step == step


def test_relay_wait_notify_every_upstream_dead_falls_back() -> None:
    relay = CachingRelay(["http://127.0.0.1:9"], timeout=0.5, start=False)
    try:
        # None = no upstream spoke the route; the poll loop falls back to
        # the jittered poll cadence instead of spinning.
        assert relay._wait_notify(0) is None
    finally:
        relay.shutdown(wait=False)


# ---------------------------------------------------------------------------
# relay tree at depth
# ---------------------------------------------------------------------------


def test_tree_depth_learned_per_tier() -> None:
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    root = CachingRelay([pub.address()], timeout=5.0, start=False)
    edge = CachingRelay([root.address()], timeout=5.0, start=False)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        assert root.poll_once()
        assert edge.poll_once()
        assert root.current().depth == 1
        assert edge.current().depth == 2
        assert root._descriptor()["depth"] == 1
        assert edge._descriptor()["depth"] == 2
        # origin_ts is preserved down the tree (propagation reference).
        assert edge._descriptor()["origin_ts"] == pub.latest()["origin_ts"]
    finally:
        edge.shutdown(wait=False)
        root.shutdown(wait=False)
        pub.shutdown()


def test_notify_chain_beats_poll_cadence_through_tree() -> None:
    """Depth-2 tree with a deliberately huge poll interval: a publish
    reaches the edge via the notify chain in seconds where polling would
    take >= 2 poll intervals (20 s here) — propagation is push-bound."""
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    pub.publish(step=1, quorum_id=0, state=state_for(1))
    root = CachingRelay(
        [pub.address()], poll_interval=10.0, timeout=5.0, notify=True
    )
    edge = CachingRelay(
        [root.address()], poll_interval=10.0, timeout=5.0, notify=True
    )
    try:
        # First adoption rides the loop's immediate first poll.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and (
            edge.current() is None or edge.current().step < 1
        ):
            time.sleep(0.05)
        assert edge.current() is not None and edge.current().step == 1
        t0 = time.perf_counter()
        pub.publish(step=2, quorum_id=0, state=state_for(2))
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline and edge.current().step < 2:
            time.sleep(0.02)
        elapsed = time.perf_counter() - t0
        assert edge.current().step == 2, "edge never adopted via the notify chain"
        assert elapsed < 8.0 < root._poll_interval, elapsed
        sub = WeightSubscriber([edge.address()], timeout=5.0)
        assert_version_is(sub.poll(), 2)
    finally:
        edge.shutdown(wait=False)
        root.shutdown(wait=False)
        pub.shutdown()


def test_interior_relay_death_rehomes_edges_to_sibling() -> None:
    """Depth-2 fan-out-2 tree: an interior (regional) relay dies; its
    edges re-home to the SIBLING regional announcing the same digest and
    keep adopting — the mid-pull failover argument composed up the tree.
    Zero invalid adoptions throughout."""
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    region_a = CachingRelay([pub.address()], timeout=5.0, start=False)
    region_b = CachingRelay([pub.address()], timeout=5.0, start=False)
    edges = [
        CachingRelay([region_a.address(), region_b.address()], timeout=5.0, start=False),
        CachingRelay([region_b.address(), region_a.address()], timeout=5.0, start=False),
    ]
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        assert region_a.poll_once() and region_b.poll_once()
        for edge in edges:
            assert edge.poll_once()
        subs = [WeightSubscriber([e.address()], timeout=5.0) for e in edges]
        for sub in subs:
            assert_version_is(sub.poll(), 1)

        region_a.die()  # interior kill mid-tree
        pub.publish(step=2, quorum_id=0, state=state_for(2))
        assert region_b.poll_once()
        for edge in edges:
            assert edge.poll_once(), "edge failed to re-home to the sibling"
            assert edge.current().step == 2
        for sub in subs:
            assert_version_is(sub.poll(), 2)
    finally:
        for node in edges + [region_b, region_a]:
            node.shutdown(wait=False)
        pub.shutdown()


@pytest.mark.slow
def test_hundred_readers_through_deep_tree() -> None:
    """>=100 concurrent watch() readers through a depth-2 fan-out-2 tree
    under a version-bump stream: every reader converges on the final
    version, zero torn / non-monotone adoptions. (The bench measures the
    same shape out-of-process with SIGKILL chaos.)"""
    pub = WeightPublisher(num_chunks=4, timeout=5.0)
    pub.publish(step=1, quorum_id=0, state=state_for(1))
    regions = [
        CachingRelay([pub.address()], poll_interval=0.1, timeout=5.0)
        for _ in range(2)
    ]
    edges = [
        CachingRelay(
            [regions[i % 2].address(), regions[(i + 1) % 2].address()],
            poll_interval=0.1,
            timeout=5.0,
        )
        for i in range(4)
    ]
    stop = threading.Event()
    bad: list = []
    last_by_reader: dict = {}
    lock = threading.Lock()

    def reader(seed: int) -> None:
        sub = WeightSubscriber(
            [edges[seed % len(edges)].address()],
            timeout=5.0,
            jitter_seed=seed,
            poll_interval=0.1,
        )
        last = 0

        def on_version(version) -> None:
            nonlocal last
            values = {
                float(np.asarray(leaf).ravel()[0])
                for leaf in version.params.values()
            }
            with lock:
                if values != {float(version.step)}:
                    bad.append(("torn", version.step, values))
                if version.step <= last:
                    bad.append(("non-monotone", last, version.step))
                last_by_reader[seed] = version.step
            last = version.step

        sub.watch(stop, on_version=on_version)

    threads = [threading.Thread(target=reader, args=(i,)) for i in range(100)]
    try:
        for t in threads:
            t.start()
        final_step = 1
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline:
            final_step += 1
            pub.publish(step=final_step, quorum_id=0, state=state_for(final_step))
            time.sleep(0.4)
        # Convergence is gated on OBSERVED adoption state, never sleeps
        # (a loaded box stretches wall time, not correctness): first the
        # tree, then every reader.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and any(
            e.current() is None or e.current().step < final_step for e in edges
        ):
            time.sleep(0.1)
        while time.monotonic() < deadline:
            with lock:
                caught_up = sum(
                    1 for s in last_by_reader.values() if s == final_step
                )
            if caught_up == 100:
                break
            time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not bad, bad[:5]
        assert len(last_by_reader) == 100
        assert all(s == final_step for s in last_by_reader.values()), (
            final_step,
            sorted(set(last_by_reader.values())),
        )
    finally:
        stop.set()
        for node in edges + regions:
            node.shutdown(wait=False)
        pub.shutdown()


def test_punisher_kill_relay_consumed_at_notify_route(tmp_path, monkeypatch) -> None:
    """A parked long-poll must not shield a relay from the punisher: the
    armed die is consumed by the next GET — including a notify — and the
    hub wakes every waiter instead of stranding them to the hold."""
    fault_file = tmp_path / "fault"
    monkeypatch.setenv(faultinject.ENV_FAULT_FILE, str(fault_file))
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    relay = CachingRelay([pub.address()], timeout=5.0, start=False)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        assert relay.poll_once()
        assert punisher.arm_stream_fault("kill_relay", str(fault_file))
        with pytest.raises(Exception):
            # The serving GET consumes the arm and the connection dies.
            _wire.fetch_notify(relay.address(), after=1, timeout=2.0, hold=5.0)
        assert relay.dead
    finally:
        relay.shutdown(wait=False)
        pub.shutdown()


# ---------------------------------------------------------------------------
# netem at the client fetch seam
# ---------------------------------------------------------------------------


def test_netem_paces_client_fetch_seam() -> None:
    """The serving pull seam charges the emulated link: a descriptor
    fetch against an UNpaced server costs >= one full RTT client-side."""
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        netem.configure(rtt_ms=120, gbps=0)
        t0 = time.perf_counter()
        descriptor = _wire.fetch_json(
            f"{pub.address()}{_wire.LATEST_ROUTE}", timeout=5.0
        )
        elapsed = time.perf_counter() - t0
        assert descriptor["step"] == 1
        # Request leg (RTT/2) + response leg (RTT/2): lower bound exact.
        assert elapsed >= 0.12, elapsed
    finally:
        netem.configure(0, 0)
        pub.shutdown()


def test_netem_server_declared_pacing_not_double_billed(monkeypatch) -> None:
    """A body the server already paced (it declares netem.PACED_HEADER)
    is NOT re-charged at the client seam — only the request leg is."""
    calls = {"pace": 0, "latency": 0}
    real_latency = netem.pace_latency
    monkeypatch.setattr(
        _wire.netem, "pace", lambda n: calls.__setitem__("pace", calls["pace"] + 1)
    )

    def latency(peer_region=None) -> None:
        calls["latency"] += 1
        real_latency(peer_region)

    monkeypatch.setattr(_wire.netem, "pace_latency", latency)

    transport = HTTPTransport(timeout=5.0, num_chunks=2)
    try:
        transport.send_checkpoint(
            dst_ranks=[], step=1, state_dict=state_for(1), timeout=5.0, quorum_id=0
        )
        netem.configure(rtt_ms=10, gbps=0)
        base = transport.metadata()
        # Chunk bodies: the transport paces server-side (one pace_latency
        # + PacingWriter in the handler) and declares it — the client
        # charges ONLY its request leg, never a second response leg.
        _wire.fetch_bytes(f"{base}/checkpoint/1/0", timeout=5.0)
        assert calls["latency"] == 2  # client request leg + server response leg
        assert calls["pace"] == 0  # response leg NOT double-billed
        # /meta is not server-paced: the client charges the response leg.
        _wire.fetch_bytes(f"{base}/checkpoint/1/meta", timeout=5.0)
        assert calls["pace"] == 1
        assert calls["latency"] == 3  # +the client request leg only
    finally:
        netem.configure(0, 0)
        transport.shutdown(wait=False)


# ---------------------------------------------------------------------------
# multi-tenant fairness + auth
# ---------------------------------------------------------------------------


def test_tenant_env_parsers(monkeypatch) -> None:
    monkeypatch.setenv(sc.ENV_SERVING_TENANT_TOKENS, "tokA:acme, tokB:beta,bad")
    monkeypatch.setenv(sc.ENV_SERVING_TENANT_GBPS, "acme:3.0,beta:1,junk:x")
    assert sc.serving_tenant_tokens() == {"tokA": "acme", "tokB": "beta"}
    assert sc.serving_tenant_gbps() == {"acme": 3.0, "beta": 1.0}
    assert sc.tenant_of_authorization("Bearer tokA") == "acme"
    assert sc.tenant_of_authorization(None) is None
    with pytest.raises(sc.UnknownTenantToken):
        sc.tenant_of_authorization("Bearer nope")
    with pytest.raises(sc.UnknownTenantToken):
        sc.tenant_of_authorization("Basic dXNlcg==")


def test_two_tenant_contention_split_with_heal_priority() -> None:
    """The acceptance drill at the pacer: tenants acme:3 / beta:1 split
    the serving class within 10% of 3:1 while a healing joiner
    concurrently keeps its 0.8 priority share above BOTH — per-byte
    costs derive from the virtual clocks, so the assert is
    deterministic."""
    pacer = sc._ServePacer(
        8.0, heal_share=0.8, tenant_gbps={"acme": 3.0, "beta": 1.0}
    )
    chunk = 1 << 20
    per_mib_full = chunk / 1e9  # seconds per MiB at the full 8 Gb/s
    # Activate all three streams (heal peer + two tenants).
    pacer.debit(chunk, cls="heal", peer="joiner")
    pacer.debit(chunk, cls="serving", tenant="acme")
    pacer.debit(chunk, cls="serving", tenant="beta")
    # Steady-state increments:
    h1 = pacer.debit(chunk, cls="heal", peer="joiner")
    h2 = pacer.debit(chunk, cls="heal", peer="joiner")
    a1 = pacer.debit(chunk, cls="serving", tenant="acme")
    a2 = pacer.debit(chunk, cls="serving", tenant="acme")
    b1 = pacer.debit(chunk, cls="serving", tenant="beta")
    b2 = pacer.debit(chunk, cls="serving", tenant="beta")
    heal_cost = h2 - h1
    acme_cost = a2 - a1
    beta_cost = b2 - b1
    # Heal keeps 0.8 of the aggregate: per-MiB cost = 1/(0.8*8 Gb/s).
    assert heal_cost == pytest.approx(per_mib_full / 0.8, rel=0.1)
    # Tenants split the 0.2 serving share 3:1 (weights = entitlements):
    # acme at 0.2*8*3/4 = 1.2 Gb/s, beta at 0.4 Gb/s.
    assert acme_cost == pytest.approx(chunk * 8 / (1.2e9), rel=0.1)
    assert beta_cost == pytest.approx(chunk * 8 / (0.4e9), rel=0.1)
    # The achieved-rate ratio is the configured 3:1 split within 10%.
    assert beta_cost / acme_cost == pytest.approx(3.0, rel=0.1)
    # Heal-over-tenants ordering: the healing joiner's per-byte cost is
    # strictly below EVERY tenant's.
    assert heal_cost < acme_cost < beta_cost


def test_tenant_entitlement_caps_without_aggregate_bound() -> None:
    """With no TPUFT_HEAL_SERVE_GBPS, per-tenant entitlements pace
    standalone: a configured tenant is bounded by its absolute cap, an
    unconfigured tenant (and heal traffic) is unpaced."""
    pacer = sc._ServePacer(0.0, tenant_gbps={"acme": 1.0})
    chunk = 1 << 20
    pacer.debit(chunk, cls="serving", tenant="acme")
    a1 = pacer.debit(chunk, cls="serving", tenant="acme")
    a2 = pacer.debit(chunk, cls="serving", tenant="acme")
    assert a2 - a1 == pytest.approx(chunk * 8 / 1e9, rel=0.1)  # 1 Gb/s cap
    assert pacer.debit(chunk, cls="serving", tenant="other") == 0.0
    assert pacer.debit(chunk, cls="heal", peer="j") == 0.0


def test_maybe_pace_serve_engages_on_tenant_config_alone(monkeypatch) -> None:
    monkeypatch.delenv(sc.ENV_SERVE_GBPS, raising=False)
    monkeypatch.setenv(sc.ENV_SERVING_TENANT_GBPS, "acme:2.0")
    out = sc.maybe_pace_serve(object(), cls="serving", tenant="acme")
    assert isinstance(out, sc._RateWriter)
    assert out._tenant == "acme"
    # Heal traffic is untouched by tenant-only config.
    assert not isinstance(sc.maybe_pace_serve(object(), cls="heal"), sc._RateWriter)


def test_relay_rejects_unknown_token_and_charges_known_tenant(
    monkeypatch,
) -> None:
    monkeypatch.setenv(sc.ENV_SERVING_TENANT_TOKENS, "tokA:acme")
    monkeypatch.setenv(sc.ENV_SERVING_TENANT_GBPS, "acme:100.0")
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    relay = CachingRelay([pub.address()], timeout=5.0, start=False)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        assert relay.poll_once()
        rejects_before = metrics.counter_total("tpuft_serving_auth_rejects_total")
        request = urllib.request.Request(f"{relay.address()}/checkpoint/1/0")
        request.add_header("Authorization", "Bearer wrong")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(request, timeout=5.0)
        assert err.value.code == 401
        assert (
            metrics.counter_total("tpuft_serving_auth_rejects_total")
            > rejects_before
        )
        # A known token reads fine and its bytes land on its tenant.
        bytes_before = metrics.counter_total("tpuft_serving_tenant_bytes_total")
        sub = WeightSubscriber([relay.address()], timeout=5.0, token="tokA")
        assert_version_is(sub.poll(), 1)
        wait_counter_above("tpuft_serving_tenant_bytes_total", bytes_before)
    finally:
        relay.shutdown(wait=False)
        pub.shutdown()


def test_transport_inline_tenant_seam(monkeypatch) -> None:
    """The inline donor transport: a bearer GET is serving-class traffic
    charged to its tenant; an unknown token is 401; a tokenless GET stays
    heal-class (the tenant counter does not move)."""
    monkeypatch.setenv(sc.ENV_SERVING_TENANT_TOKENS, "tokA:acme")
    monkeypatch.setenv(sc.ENV_SERVING_TENANT_GBPS, "acme:100.0")
    transport = HTTPTransport(timeout=5.0, num_chunks=2)
    try:
        transport.send_checkpoint(
            dst_ranks=[], step=1, state_dict=state_for(1), timeout=5.0, quorum_id=0
        )
        base = transport.metadata()
        before = metrics.counter_total("tpuft_serving_tenant_bytes_total")
        request = urllib.request.Request(f"{base}/checkpoint/1/0")
        request.add_header("Authorization", "Bearer tokA")
        with urllib.request.urlopen(request, timeout=5.0) as resp:
            body = resp.read()
            assert body
        # The server debits the final slice just after the client's read
        # completes — wait for the settled count (every body byte charged).
        mid = wait_counter_above(
            "tpuft_serving_tenant_bytes_total", before + len(body) - 1
        )
        # Tokenless = heal class: tenant accounting untouched.
        with urllib.request.urlopen(f"{base}/checkpoint/1/0", timeout=5.0) as resp:
            assert resp.read()
        time.sleep(0.3)  # give a (wrong) debit time to land before asserting
        assert metrics.counter_total("tpuft_serving_tenant_bytes_total") == mid
        # Unknown token: refused before any body.
        request = urllib.request.Request(f"{base}/checkpoint/1/1")
        request.add_header("Authorization", "Bearer wrong")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(request, timeout=5.0)
        assert err.value.code == 401
    finally:
        transport.shutdown(wait=False)


def test_serve_child_tenant_seam(monkeypatch) -> None:
    """Sidecar parity: the serving child enforces the same bearer/tenant
    seam in-child — known tenants are charged in the CHILD's registry,
    unknown tokens are 401 from the child itself."""
    monkeypatch.setenv(sc.ENV_SERVING_TENANT_TOKENS, "tokA:acme")
    monkeypatch.setenv(sc.ENV_SERVING_TENANT_GBPS, "acme:100.0")
    transport = HTTPTransport(timeout=5.0, num_chunks=2, serve_mode="child")
    try:
        transport.send_checkpoint(
            dst_ranks=[], step=1, state_dict=state_for(1), timeout=5.0, quorum_id=0
        )
        base = transport.metadata()
        assert transport._child_serving(), "sidecar did not come up"
        request = urllib.request.Request(f"{base}/checkpoint/1/0")
        request.add_header("Authorization", "Bearer tokA")
        with urllib.request.urlopen(request, timeout=10.0) as resp:
            assert resp.read()
        request = urllib.request.Request(f"{base}/checkpoint/1/1")
        request.add_header("Authorization", "Bearer wrong")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(request, timeout=10.0)
        assert err.value.code == 401
        # The child's own scrape shows the tenant accounting (the final
        # slice's debit lands just after the client read — poll for it).
        deadline = time.monotonic() + 5.0
        text = ""
        while time.monotonic() < deadline:
            with urllib.request.urlopen(f"{base}/metrics", timeout=10.0) as resp:
                text = resp.read().decode()
            if "tpuft_serving_tenant_bytes_total" in text:
                break
            time.sleep(0.05)
        assert "tpuft_serving_tenant_bytes_total" in text
        assert 'tenant="acme"' in text
        assert "tpuft_serving_auth_rejects_total" in text
    finally:
        transport.shutdown(wait=False)


def test_publisher_announce_rejects_unknown_token() -> None:
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        pub.publish(step=1, quorum_id=0, state=state_for(1))
        import os

        os.environ[sc.ENV_SERVING_TENANT_TOKENS] = "tokA:acme"
        try:
            request = urllib.request.Request(
                f"{pub.address()}{_wire.LATEST_ROUTE}"
            )
            request.add_header("Authorization", "Bearer wrong")
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(request, timeout=5.0)
            assert err.value.code == 401
        finally:
            del os.environ[sc.ENV_SERVING_TENANT_TOKENS]
    finally:
        pub.shutdown()


# ---------------------------------------------------------------------------
# doctor: relay-tree loopback probe + knob validation (WARN never FAIL)
# ---------------------------------------------------------------------------


def test_doctor_serving_probe_runs_tree_and_validates_knobs(monkeypatch) -> None:
    from torchft_tpu import doctor

    status, detail = doctor._check_serving()
    assert status == "PASS", detail
    assert "tree probe ok" in detail
    monkeypatch.setenv("TPUFT_SERVING_NOTIFY_HOLD_SEC", "not-a-number")
    status, detail = doctor._check_serving()
    assert status == "WARN" and "TPUFT_SERVING_NOTIFY_HOLD_SEC" in detail
    monkeypatch.setenv("TPUFT_SERVING_NOTIFY_HOLD_SEC", "5")
    monkeypatch.setenv(sc.ENV_SERVING_TENANT_GBPS, "acme:not-a-number")
    status, detail = doctor._check_serving()
    assert status == "WARN" and "malformed" in detail
    monkeypatch.setenv(sc.ENV_SERVING_TENANT_GBPS, "acme:2.0")
    status, detail = doctor._check_serving()
    assert status == "PASS" and "1 tenant entitlement(s)" in detail


# ---------------------------------------------------------------------------
# fleet_status RELAY column
# ---------------------------------------------------------------------------


def test_fleet_status_relay_column() -> None:
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "fleet_status",
        Path(__file__).resolve().parent.parent / "scripts" / "fleet_status.py",
    )
    fleet_status = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet_status)
    snap = {
        "metrics": {
            "gauges": {
                "tpuft_serving_relay_depth": [{"value": 2.0}],
                "tpuft_serving_relay_upstreams": [{"value": 3.0}],
                "tpuft_serving_notify_waiters": [{"value": 17.0}],
            }
        }
    }
    assert fleet_status._relay_state(snap) == "d2/u3/s17"
    assert fleet_status._relay_state({"metrics": {"gauges": {}}}) is None
    assert ("relay", "RELAY") in fleet_status._COLUMNS
