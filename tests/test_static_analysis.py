"""tpuft_check (torchft_tpu.analysis) tier-1 suite.

Per-rule positive/negative fixture tests (tests/fixtures/analysis/), the
suppression + baseline machinery, the CLI contract (one-line findings,
exit code), and the load-bearing guarantee: the shipped package scans
clean — CLAUDE.md's invariants hold as enforced properties.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from torchft_tpu.analysis import (
    ALL_RULES,
    RULES_BY_ID,
    apply_baseline,
    run_analysis,
    save_baseline,
)
from torchft_tpu.analysis.core import REPO_ROOT

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"
ABSENT_REFERENCE = Path("/nonexistent/tpuft-reference")


def scan(name: str, rules=None, reference_root=ABSENT_REFERENCE):
    return run_analysis(
        paths=[FIXTURES / name], rules=rules, reference_root=reference_root
    )


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# per-rule positive / negative fixtures
# ---------------------------------------------------------------------------


def test_r1_violation_fixture() -> None:
    # Unguarded thread target + lambda callback + unguarded heal/recv
    # worker (the heal-plane shape: a joiner's checkpoint fetch thread
    # must funnel donor-death/checksum/watchdog failures) + unguarded
    # serve-child supervisor watcher (the sidecar shape: child death must
    # funnel into report_error, not kill the watcher thread). Golden
    # count updated DELIBERATELY with the serve-child subsystem — the
    # new shape is pinned, not baselined away.
    findings = scan("r1_violation.py", rules=["step-boundary-escape"])
    assert len(findings) == 4
    assert rules_of(findings) == ["step-boundary-escape"]
    lines = sorted(f.line for f in findings)
    assert any("thread target" in f.message for f in findings)
    assert any("lambda" in f.message for f in findings)
    assert any("recv_worker" in f.message for f in findings)
    assert any("watch_child" in f.message for f in findings)
    assert all(f.file.endswith("r1_violation.py") for f in findings)
    assert lines == [10, 16, 29, 46]


def test_r1_clean_fixture() -> None:
    assert scan("r1_clean.py") == []


def test_r2_violation_fixture() -> None:
    findings = scan("r2_violation.py", rules=["op-worker-self-wait"])
    assert len(findings) == 2  # .then callback wait + op-worker submit wait
    assert {f.line for f in findings} == {12, 20}


def test_r2_clean_fixture() -> None:
    assert scan("r2_clean.py") == []


def test_r3_violation_fixture() -> None:
    findings = scan("r3_violation.py", rules=["lock-discipline"])
    messages = [f.message for f in findings]
    # Two unlocked mutations (params + opt_state lines) and one barrier
    # inside the lock.
    assert sum("without the state-dict writer" in m for m in messages) == 2
    assert sum("barrier" in m for m in messages) == 1


def test_r3_clean_fixture() -> None:
    assert scan("r3_clean.py") == []


def test_r3_trace_violation_fixture() -> None:
    """The trace-plane lock invariant: journal recording sites never hold
    the state-dict lock across a commit barrier. A tracing span wrapped
    around a barrier inside the writer is still a barrier inside the
    writer, and a journal append before an unlocked rebind is not a
    lock."""
    findings = scan("r3_trace_violation.py", rules=["lock-discipline"])
    messages = [f.message for f in findings]
    assert sum("barrier" in m for m in messages) == 1
    assert sum("without the state-dict writer" in m for m in messages) == 1


def test_r3_trace_clean_fixture() -> None:
    """Recording around the barrier (and inside the locked adopt) is the
    shipped pattern — a lock-free deque append, clean under R3."""
    assert scan("r3_trace_clean.py") == []


def test_r4_violation_fixture() -> None:
    findings = scan("r4_violation.py", rules=["unjitted-optax"])
    assert len(findings) == 2
    assert any(".update()" in f.message for f in findings)
    assert any("apply_updates" in f.message for f in findings)


def test_r4_clean_fixture() -> None:
    assert scan("r4_clean.py") == []


def test_r5_violation_fixture() -> None:
    findings = scan("r5_violation.py", rules=["replica-axis-in-mesh"])
    assert len(findings) == 1
    assert "replica" in findings[0].message


def test_r5_clean_fixture() -> None:
    assert scan("r5_clean.py") == []


def test_r5_zero_violation_fixture() -> None:
    # Shard-spec-shaped code (the ZeRO plane, torchft_tpu/zero.py)
    # leaking the replica axis into a Mesh: exactly ONE finding, at the
    # Mesh construction — the downstream spec dicts naming "replica" as
    # data are not Mesh axes and must not fire. Golden count added
    # DELIBERATELY with the ZeRO subsystem: the new shard-plane shape is
    # pinned, not baselined away.
    findings = scan("r5_zero_violation.py", rules=["replica-axis-in-mesh"])
    assert len(findings) == 1
    assert "replica" in findings[0].message
    assert findings[0].file.endswith("r5_zero_violation.py")


def test_r5_zero_clean_fixture() -> None:
    # The real plane's shape: range bookkeeping + an intra-slice Mesh.
    assert scan("r5_zero_clean.py") == []


def test_r6_violation_parse_level() -> None:
    # Reference snapshot absent: only the parse-level (inverted range)
    # finding fires; reference citations skip cleanly.
    findings = scan("r6_violation.py", rules=["citation-lint"])
    assert len(findings) == 1
    assert "inverted" in findings[0].message


def test_r6_violation_resolves_against_reference(tmp_path) -> None:
    ref = tmp_path / "reference"
    (ref / "torchft").mkdir(parents=True)
    (ref / "torchft" / "manager.py").write_text("\n".join(f"# {i}" for i in range(10)))
    findings = scan(
        "r6_violation.py", rules=["citation-lint"], reference_root=ref
    )
    messages = sorted(f.message for f in findings)
    assert len(findings) == 3
    assert any("inverted" in m for m in messages)
    assert any("manager.py:999" in m and "stale" in m for m in messages)
    assert any("nosuch_module.py:3" in m and "resolves nowhere" in m for m in messages)


def test_r7_violation_fixture() -> None:
    # The manager's quorum-path shape with the drain REMOVED: a wire
    # reconfigure, a donor checkpoint send, and a sidecar heal staging,
    # all reachable inside an undrained speculative window — three
    # findings, one per unsafe call. Golden count added DELIBERATELY with
    # the depth-N window generalization: the speculation-discipline shape
    # is pinned, not baselined away.
    findings = scan("r7_pipeline_violation.py", rules=["speculation-discipline"])
    assert len(findings) == 3
    assert rules_of(findings) == ["speculation-discipline"]
    messages = sorted(f.message for f in findings)
    assert sum("pg.configure" in m for m in messages) == 1
    assert sum("send_checkpoint" in m for m in messages) == 1
    assert sum("stage" in m and "send_checkpoint" not in m for m in messages) == 1
    assert all("drain" in m for m in messages)


def test_r7_clean_fixture() -> None:
    # Both drain shapes (the inline quorum-change-hooks loop and the named
    # helper) lexically precede every unsafe call — clean under all rules.
    assert scan("r7_pipeline_clean.py") == []


def test_r7_publish_violation_fixture() -> None:
    # The serving-plane extension: a committed-weights publish reachable
    # with the window undrained is the reader-facing twin of an undrained
    # donor send — one finding at the publish call.
    findings = scan("r7_publish_violation.py", rules=["speculation-discipline"])
    assert len(findings) == 1
    assert rules_of(findings) == ["speculation-discipline"]
    assert "publish" in findings[0].message
    assert "drain" in findings[0].message


def test_r7_publish_clean_fixture() -> None:
    # The manager's _maybe_publish shape: drain lexically precedes the
    # state sample + publish — clean under all rules.
    assert scan("r7_publish_clean.py") == []


def test_r6_clean_fixture(tmp_path) -> None:
    # Clean with the snapshot absent...
    assert scan("r6_clean.py") == []
    # ...and with a synthetic snapshot present.
    ref = tmp_path / "reference"
    (ref / "torchft").mkdir(parents=True)
    (ref / "torchft" / "manager.py").write_text("\n".join(f"# {i}" for i in range(10)))
    assert scan("r6_clean.py", reference_root=ref) == []


def test_r9_violation_fixture() -> None:
    # The taint pass: a relay-shaped meta pull with expect_crc=None adopted
    # into self._current, a raw fetch deserialized unverified, and the
    # derived state swapped in — three findings, each naming its source.
    findings = scan("r9_violation.py", rules=["verify-before-adopt"])
    assert len(findings) == 3
    assert rules_of(findings) == ["verify-before-adopt"]
    assert sorted(f.line for f in findings) == [17, 21, 22]
    messages = sorted(f.message for f in findings)
    assert sum("self._current" in m for m in messages) == 1
    assert sum("load_state_dict" in m for m in messages) == 1
    assert sum("self._version" in m for m in messages) == 1
    assert all("_fetch_failover" in m or "fetch_bytes" in m for m in messages)


def test_r9_clean_fixture() -> None:
    # CRC+size compare, digest fence, verifying-fetch kwarg, and codec
    # decode_state all cleanse before the swap — clean under ALL rules.
    assert scan("r9_clean.py") == []


def test_r10_violation_fixture() -> None:
    findings = scan("r10_violation.py", rules=["era-fence"])
    assert len(findings) == 1
    assert findings[0].line == 6
    assert "quorum_id" in findings[0].message


def test_r10_clean_fixture() -> None:
    # The fenced handler passes; the non-checkpoint handler is out of the
    # rule's bind entirely — clean under ALL rules.
    assert scan("r10_clean.py") == []


def test_r11_violation_fixture() -> None:
    findings = scan("r11_violation.py", rules=["stale-suppression"])
    assert len(findings) == 2
    assert {f.line for f in findings} == {6, 11}
    messages = sorted(f.message for f in findings)
    assert sum("no longer matches" in m for m in messages) == 1
    assert sum("unknown rule" in m for m in messages) == 1


def test_r11_clean_fixture() -> None:
    # A live suppression: its rule still fires at the covered line, so
    # the whole-file scan (R5 suppressed, R11 satisfied) is empty.
    assert scan("r11_clean.py") == []


def test_module_cache_shares_ast_and_invalidates_on_edit(tmp_path) -> None:
    """Satellite: one parse per (file, mtime) shared across rules and
    re-scans; an edited file re-parses rather than serving stale findings."""
    import os

    from torchft_tpu.analysis.core import load_module

    target = tmp_path / "cached.py"
    target.write_text("x = 1\n")
    first = load_module(target)
    assert first is not None and load_module(target) is first
    # Same content, bumped mtime: the cache key is (mtime, size), so this
    # re-parses — correctness over micro-optimality.
    target.write_text("y = 2\n")
    os.utime(target, (1, 1))
    second = load_module(target)
    assert second is not None and second is not first
    assert "y = 2" in second.source


# ---------------------------------------------------------------------------
# suppressions + baseline
# ---------------------------------------------------------------------------


def test_inline_suppression_needs_reason() -> None:
    findings = scan("r5_suppressed.py")
    # The justified violation is suppressed; the reason-less one surfaces
    # BOTH as a malformed suppression and as the un-suppressed violation.
    assert rules_of(findings) == ["replica-axis-in-mesh", "suppression"]
    assert len(findings) == 2
    by_rule = {f.rule: f for f in findings}
    assert "missing its reason" in by_rule["suppression"].message
    assert by_rule["replica-axis-in-mesh"].line == 13


def test_baseline_roundtrip(tmp_path) -> None:
    baseline = tmp_path / "baseline.json"
    findings = scan("r5_violation.py")
    assert findings
    save_baseline(findings, baseline)
    payload = json.loads(baseline.read_text())
    assert payload["findings"]
    fresh, suppressed = apply_baseline(findings, baseline)
    assert fresh == []
    assert suppressed == len(findings)
    # A new finding (different fingerprint) is NOT masked by the baseline.
    other = scan("r3_violation.py")
    fresh, _ = apply_baseline(other, baseline)
    assert fresh == other


# ---------------------------------------------------------------------------
# the shipped tree is clean + CLI contract
# ---------------------------------------------------------------------------


def test_package_scans_clean() -> None:
    """CLAUDE.md's invariants hold over torchft_tpu/ with an EMPTY baseline
    (reference resolution pinned absent so the result is deterministic on
    boxes with and without the snapshot)."""
    findings = run_analysis(reference_root=ABSENT_REFERENCE)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_rule_registry_covers_r1_to_r11() -> None:
    assert len(ALL_RULES) == 11
    assert set(RULES_BY_ID) == {
        "step-boundary-escape",
        "op-worker-self-wait",
        "lock-discipline",
        "unjitted-optax",
        "replica-axis-in-mesh",
        "citation-lint",
        "speculation-discipline",
        "metric-doc-drift",
        "verify-before-adopt",
        "era-fence",
        "stale-suppression",
    }


def _run_cli(*args: str, env_extra=None):
    import os

    env = dict(os.environ)
    env["TPUFT_ANALYSIS_REFERENCE"] = str(ABSENT_REFERENCE)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "torchft_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=str(REPO_ROOT),
        env=env,
        timeout=120,
    )


@pytest.mark.slow
def test_cli_exit_codes() -> None:
    clean = _run_cli()
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "0 finding(s)" in clean.stdout

    dirty = _run_cli(str(FIXTURES / "r5_violation.py"))
    assert dirty.returncode == 1
    assert "replica-axis-in-mesh" in dirty.stdout

    listing = _run_cli("--list-rules")
    assert listing.returncode == 0
    for rule in RULES_BY_ID:
        assert rule in listing.stdout


def test_cli_inprocess_contract() -> None:
    """The same contract as test_cli_exit_codes without subprocess cost
    (kept unconditionally in tier-1)."""
    from torchft_tpu.analysis.__main__ import main

    import os

    old = os.environ.get("TPUFT_ANALYSIS_REFERENCE")
    os.environ["TPUFT_ANALYSIS_REFERENCE"] = str(ABSENT_REFERENCE)
    try:
        assert main([]) == 0
        assert main([str(FIXTURES / "r5_violation.py")]) == 1
        assert main(["--list-rules"]) == 0
        assert main(["--rules", "bogus-rule"]) == 2
    finally:
        if old is None:
            os.environ.pop("TPUFT_ANALYSIS_REFERENCE", None)
        else:
            os.environ["TPUFT_ANALYSIS_REFERENCE"] = old
