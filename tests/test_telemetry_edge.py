"""Telemetry export edge cases: exporter failures must never reach the
step boundary.

The telemetry plane narrates (telemetry.py); the invariant under test is
that a broken NARRATOR cannot break TRAINING: a sink raising mid-record,
an unattachable OTLP exporter, or a handler raising inside emit must all
degrade to lost/partial telemetry — never to an exception crossing
``logger.info(...)`` call sites on the train/quorum threads (CLAUDE.md:
nothing may raise past the step boundary except quorum timeouts).
"""

from __future__ import annotations

import io
import json
import logging

import pytest

from torchft_tpu import goodput, telemetry, tracing


@pytest.fixture
def detached_slo_logger():
    """Run each test against a clean tpuft_slo logger; restore after."""
    logger = telemetry.slo_logger
    saved = list(logger.handlers)
    for h in saved:
        logger.removeHandler(h)
    logger.setLevel(logging.INFO)
    yield logger
    for h in list(logger.handlers):
        logger.removeHandler(h)
    for h in saved:
        logger.addHandler(h)


class _BrokenStream:
    """A sink that dies mid-record after N good writes (disk full, closed
    pipe, rotated file) — the classic silent telemetry failure."""

    def __init__(self, good_writes: int = 0) -> None:
        self.good = good_writes
        self.lines: list[str] = []

    def write(self, data: str) -> None:
        if self.good <= 0:
            raise OSError("sink gone: no space left on device")
        self.good -= 1
        self.lines.append(data)

    def flush(self) -> None:
        if self.good < 0:
            raise OSError("sink gone")


def test_sink_raising_mid_record_never_raises(detached_slo_logger, capsys):
    """_JsonLinesHandler funnels stream failures into logging.handleError
    (stderr note), never up through the logging call on the train thread."""
    stream = _BrokenStream(good_writes=1)
    handler = telemetry._JsonLinesHandler(stream)
    detached_slo_logger.addHandler(handler)
    # First record lands...
    detached_slo_logger.info("slo_breach", extra={"slo": "goodput"})
    assert len(stream.lines) == 1
    # ...then the sink dies. The logging call must still return cleanly.
    detached_slo_logger.info("slo_breach", extra={"slo": "goodput"})
    detached_slo_logger.info("slo_breach", extra={"slo": "goodput"})
    assert len(stream.lines) == 1  # lost, not raised


def test_slo_breach_record_shape(detached_slo_logger):
    """The SLO-breach record type flows through the JSON-lines exporter
    with every goodput field _EVENT_FIELDS names (a field the exporter
    drops is a field no pager can route on)."""
    sink = io.StringIO()
    detached_slo_logger.addHandler(telemetry._JsonLinesHandler(sink))
    detached_slo_logger.info(
        "slo_breach",
        extra={
            "slo": "goodput",
            "slo_target": 0.95,
            "burn_rate": 3.2,
            "goodput": 0.84,
            "windows": 3,
            "replica_id": "r0",
            "step": 41,
            "quorum_id": 7,
        },
    )
    event = json.loads(sink.getvalue())
    assert event["event"] == "tpuft_slo"
    assert event["message"] == "slo_breach"
    assert event["slo"] == "goodput"
    assert event["slo_target"] == 0.95
    assert event["burn_rate"] == 3.2
    assert event["goodput"] == 0.84
    assert event["windows"] == 3
    assert event["replica_id"] == "r0"
    assert event["step"] == 41 and event["quorum_id"] == 7


def test_slo_fire_survives_raising_handler(detached_slo_logger):
    """SloEvaluator._fire wraps its telemetry emit: a handler raising
    inside emit (the one failure _JsonLinesHandler's own try/except cannot
    see) still latches the breach, bumps the counter, and returns."""

    class _ExplodingHandler(logging.Handler):
        def emit(self, record: logging.LogRecord) -> None:
            raise RuntimeError("exporter wedged")

    detached_slo_logger.addHandler(_ExplodingHandler())
    journal = tracing.TraceJournal(maxlen=64, enabled=True)
    slo = goodput.SloEvaluator(target=0.95, windows=1)
    latched = slo.observe(0.5, step=3, quorum_id=1, journal=journal)
    assert latched is True
    assert slo.breaches == 1 and slo.latched


def test_otlp_attach_failure_leaves_loggers_clean(detached_slo_logger):
    """configure_telemetry('otlp') with the SDK absent raises the guidance
    RuntimeError and attaches NOTHING — a failed exporter must not leave
    half the event loggers wired to a dead handler."""
    try:
        import opentelemetry.sdk  # noqa: F401

        pytest.skip("opentelemetry-sdk installed; attach would succeed")
    except ImportError:
        pass
    before = {
        logger.name: list(logger.handlers)
        for logger in (
            telemetry.quorums_logger,
            telemetry.commits_logger,
            telemetry.errors_logger,
            telemetry.slo_logger,
        )
    }
    with pytest.raises(RuntimeError, match="opentelemetry-sdk"):
        telemetry.configure_telemetry("otlp")
    for logger in (
        telemetry.quorums_logger,
        telemetry.commits_logger,
        telemetry.errors_logger,
        telemetry.slo_logger,
    ):
        assert logger.handlers == before[logger.name]
