"""Fleet trace plane unit tests (torchft_tpu/tracing.py).

Pure python, no native toolchain: journal ring semantics, the causal
tuple, per-event cost bound, thread-local journals, store-mediated clock
sampling, deterministic incident ids + auto-capture dumps (including the
flight-recorder filename satellite), the /trace.json HTTP surface, and the
Manager-level integration (events recorded at the real call sites, trace
segments pushed to the group store on the metrics cadence).
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from test_manager import _FakeStore, make_manager, make_quorum

from torchft_tpu import metrics, tracing
from torchft_tpu.parallel.process_group import ProcessGroupDummy
from torchft_tpu.utils import flight_recorder


# ---------------------------------------------------------------------------
# journal semantics
# ---------------------------------------------------------------------------


def test_journal_records_causal_tuple_and_identity() -> None:
    j = tracing.TraceJournal(maxlen=128)
    j.configure(job_id="job1", replica_id="r0", group_rank=3)
    j.set_step(7, 2)
    j.record("vote_send", vote=True)
    with j.span("commit_barrier", step=7, quorum_id=2):
        pass
    events = j.snapshot()
    assert [e["name"] for e in events] == ["vote_send", "commit_barrier"]
    instant = events[0]
    assert instant["job_id"] == "job1"
    assert instant["replica_id"] == "r0"
    assert instant["group_rank"] == 3
    assert instant["step"] == 7 and instant["quorum_id"] == 2
    assert instant["seq"] == 0 and events[1]["seq"] == 1
    assert instant["args"] == {"vote": True}
    assert "t_wall" in instant and "t_mono" in instant and "thread" in instant
    span = events[1]
    assert span["ph"] == "X" and span["dur"] >= 0
    # Span stamps are the START (merged timelines sort by entry).
    assert span["t_mono"] <= instant["t_mono"] + 10  # sanity: monotonic scale


def test_journal_ring_bound_and_drop_accounting() -> None:
    j = tracing.TraceJournal(maxlen=64)
    for i in range(200):
        j.record("e", i=i)
    assert len(j.snapshot()) == 64
    assert j.dropped() == 200 - 64
    # Everything still in the ring drains; the overwritten events count as
    # dropped-before-export exactly once.
    metrics.REGISTRY.reset()
    segment = j.drain_segment()
    assert len(segment) == 64
    assert metrics.counter_total("tpuft_trace_events_total") == 64
    assert metrics.counter_total("tpuft_trace_dropped_total") == 200 - 64
    # Incremental: nothing new -> empty segment, no double counting.
    assert j.drain_segment() == []
    j.record("late")
    seg2 = j.drain_segment()
    assert [e["name"] for e in seg2] == ["late"]
    assert metrics.counter_total("tpuft_trace_dropped_total") == 200 - 64


def test_journal_disabled_records_nothing(monkeypatch) -> None:
    j = tracing.TraceJournal(maxlen=64, enabled=False)
    j.record("e")
    with j.span("s"):
        pass
    assert j.snapshot() == []
    # Env switch honored at construction.
    monkeypatch.setenv(tracing.ENV_TRACE, "0")
    j2 = tracing.TraceJournal(maxlen=64)
    j2.record("e")
    assert j2.snapshot() == [] and not j2.enabled


def test_journal_never_raises_on_unjsonable_args() -> None:
    class Bad:
        def __repr__(self) -> str:
            raise RuntimeError("no repr")

    j = tracing.TraceJournal(maxlen=16)
    j.record("e", weird=Bad(), ok=1)
    event = j.snapshot()[0]
    assert event["args"]["ok"] == 1
    assert "unreprable" in event["args"]["weird"]
    json.dumps(event)  # the whole record stays JSON-safe


def test_recording_overhead_is_bounded() -> None:
    """The acceptance bound: recording is a dict build + deque append.
    Measured ~2 us/event on this box; the pin is 50x that so a loaded
    1-core CI container cannot flake it, while still guaranteeing the
    per-event cost cannot silently grow to something step-visible."""
    j = tracing.TraceJournal(maxlen=4096)
    n = 20000
    t0 = time.perf_counter()
    for i in range(n):
        j.record("device_sync", ph="X", dur=0.001, step=1, quorum_id=2)
    per_event = (time.perf_counter() - t0) / n
    assert per_event < 100e-6, f"record() cost {per_event * 1e6:.1f} us/event"
    t0 = time.perf_counter()
    for i in range(n):
        with j.span("s", step=1):
            pass
    per_span = (time.perf_counter() - t0) / n
    assert per_span < 200e-6, f"span() cost {per_span * 1e6:.1f} us/span"


def test_thread_local_journals_isolate_replicas() -> None:
    """Threads-as-replicas: each replica thread installs its own journal;
    module-level record() routes to it, and a Manager created on that
    thread keeps recording there from its quorum thread."""
    j_a, j_b = tracing.TraceJournal(maxlen=64), tracing.TraceJournal(maxlen=64)

    def replica(journal, tag):
        with tracing.use_journal(journal):
            assert tracing.current() is journal
            tracing.record("hello", tag=tag)

    threads = [
        threading.Thread(target=replica, args=(j_a, "a")),
        threading.Thread(target=replica, args=(j_b, "b")),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert [e["args"]["tag"] for e in j_a.snapshot()] == ["a"]
    assert [e["args"]["tag"] for e in j_b.snapshot()] == ["b"]
    assert tracing.current() is tracing.default()


def test_phase_rollup_groups_by_step() -> None:
    j = tracing.TraceJournal(maxlen=256)
    for step in (1, 2):
        with j.span("quorum", step=step, quorum_id=5):
            pass
        j.record("commit_barrier", ph="X", dur=0.25 * step, step=step, quorum_id=5)
        j.record("wire_bucket", ph="X", dur=0.1, step=step)
        j.record("wire_bucket", ph="X", dur=0.2, step=step)
        j.record("commit" if step == 1 else "commit_failed", step=step)
    rollup = j.phase_rollup()
    assert [r["step"] for r in rollup] == [1, 2]
    assert rollup[0]["committed"] is True and rollup[1]["committed"] is False
    assert rollup[0]["phases"]["commit_barrier"] == pytest.approx(0.25)
    # Repeated spans at one step accumulate.
    assert rollup[0]["phases"]["wire_bucket"] == pytest.approx(0.3)
    assert rollup[1]["phases"]["commit_barrier"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# incidents + dumps (flight-recorder filename satellite)
# ---------------------------------------------------------------------------


def test_incident_id_is_deterministic_across_processes() -> None:
    a = tracing.incident_id("rollback", 12, 4)
    b = tracing.incident_id("rollback", 12, 4)
    assert a == b == "inc-rollback-q4-s12"
    assert tracing.incident_id("heal_exhausted", 12, 4) != a


def test_open_incident_dumps_journal_and_flight_recorder(
    tmp_path, monkeypatch
) -> None:
    monkeypatch.setenv("TPUFT_FLIGHT_RECORDER", str(tmp_path))
    j = tracing.TraceJournal(maxlen=64)
    j.configure(replica_id="train_0", group_rank=1)
    j.record("rollback", step=9, quorum_id=3)
    with tracing.use_journal(j):
        iid = tracing.open_incident("rollback", 9, 3, journal=j, reason="refused")
        assert iid == "inc-rollback-q3-s9"
        assert tracing.active_incident(j) == iid

        trace_dumps = list(tmp_path.glob("tpuft_trace_*.jsonl"))
        fr_dumps = list(tmp_path.glob("tpuft_fr_*.jsonl"))
    assert len(trace_dumps) == 1 and len(fr_dumps) == 1
    # Satellite: both filenames carry the replica identity AND the
    # incident id — correlatable across hosts by name alone.
    for dump in (trace_dumps[0], fr_dumps[0]):
        assert "train_0" in dump.name and iid in dump.name
    lines = [json.loads(l) for l in trace_dumps[0].read_text().splitlines()]
    assert lines[0]["trace_header"] and lines[0]["incident"] == iid
    assert any(rec.get("name") == "incident" for rec in lines[1:])
    fr_lines = [json.loads(l) for l in fr_dumps[0].read_text().splitlines()]
    assert fr_lines[0]["incident"] == iid
    # A commit clears the incident window: the next dump gets no stamp.
    tracing.clear_incident(j)
    assert tracing.active_incident(j) is None


def test_dump_on_failure_reuses_active_incident(tmp_path, monkeypatch) -> None:
    monkeypatch.setenv("TPUFT_FLIGHT_RECORDER", str(tmp_path))
    j = tracing.TraceJournal(maxlen=64)
    j.configure(replica_id="train_1", group_rank=0)
    with tracing.use_journal(j):
        j.active_incident = "inc-rollback-q1-s5"
        path = flight_recorder.dump_on_failure("test", "late failure")
        assert path is not None
        assert "inc-rollback-q1-s5" in os.path.basename(path)
        assert "train_1_0" in os.path.basename(path)
        j.active_incident = None
        path2 = flight_recorder.dump_on_failure("test", "clean era")
        assert "inc-" not in os.path.basename(path2)


# ---------------------------------------------------------------------------
# store-mediated clock sampling
# ---------------------------------------------------------------------------


def test_clock_sampler_recovers_gross_skew() -> None:
    """Two processes sharing a store, one 7.5 s ahead: the beacon owner
    claims the key, the skewed sampler estimates its offset within the
    sampling window bound."""
    store = _FakeStore()
    j_ref = tracing.TraceJournal(maxlen=64)  # reference clock: real time
    skew = 7.5
    j_skew = tracing.TraceJournal(maxlen=64, wall=lambda: time.time() + skew)
    ref = tracing.StoreClockSampler(j_ref, owner_key="a/0", claim=True)
    other = tracing.StoreClockSampler(j_skew, owner_key="b/0", claim=False)

    ref.tick(store)  # writes the beacon
    assert store.data.get(tracing.CLOCK_REF_KEY) is not None
    other.tick(store)  # first read: no prev window yet -> no sample
    assert other.last_offset_s is None
    ref.tick(store)  # beacon counter advances
    other.tick(store)  # second read: write landed inside (prev, now]
    assert other.last_offset_s == pytest.approx(skew, abs=0.5)
    assert j_skew.clock_offset_s == pytest.approx(skew, abs=0.5)
    samples = [e for e in j_skew.snapshot() if e["name"] == "clock_sample"]
    assert len(samples) == 1
    assert samples[0]["args"]["offset_s"] == pytest.approx(skew, abs=0.5)
    # The owner's own frame is the reference: offset 0.
    ref.tick(store)
    assert ref.last_offset_s == 0.0


def test_clock_beacon_ownership_converges_to_smallest_claimer() -> None:
    store = _FakeStore()
    j1, j2 = tracing.TraceJournal(maxlen=16), tracing.TraceJournal(maxlen=16)
    big = tracing.StoreClockSampler(j1, owner_key="zz/0", claim=True)
    small = tracing.StoreClockSampler(j2, owner_key="aa/0", claim=True)
    big.tick(store)
    small.tick(store)  # smaller key takes over
    big.tick(store)  # larger key backs off
    beacon = json.loads(store.data[tracing.CLOCK_REF_KEY].decode())
    assert beacon["owner"] == "aa/0"


def test_clock_beacon_stale_takeover() -> None:
    store = _FakeStore()
    j = tracing.TraceJournal(maxlen=16)
    backup = tracing.StoreClockSampler(j, owner_key="zz/0", claim=True)
    # A dead owner's beacon: counter never advances.
    store.data[tracing.CLOCK_REF_KEY] = json.dumps(
        {"owner": "aa/0", "n": 5, "wall": time.time()}
    ).encode()
    for _ in range(backup.STALE_TAKEOVER_READS + 1):
        backup.tick(store)
    beacon = json.loads(store.data[tracing.CLOCK_REF_KEY].decode())
    assert beacon["owner"] == "zz/0"


def test_clock_sampler_survives_dead_store() -> None:
    class DeadStore:
        def get(self, *a, **k):
            raise ConnectionError("down")

        def set(self, *a, **k):
            raise ConnectionError("down")

    j = tracing.TraceJournal(maxlen=16)
    sampler = tracing.StoreClockSampler(j, owner_key="a/0", claim=True)
    sampler.tick(DeadStore())  # must not raise


# ---------------------------------------------------------------------------
# /trace.json HTTP surface
# ---------------------------------------------------------------------------


def test_trace_json_served_on_metrics_http() -> None:
    default = tracing.default()
    default.record("probe_event", step=1)
    server = metrics.start_http_server(0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/trace.json", timeout=5
        ) as resp:
            payload = json.loads(resp.read().decode())
    finally:
        server.shutdown()
    assert payload["replica_id"] == default.replica_id
    assert "clock" in payload and "wall" in payload["clock"]
    assert any(e["name"] == "probe_event" for e in payload["events"])
    assert isinstance(payload["phases"], list)


# ---------------------------------------------------------------------------
# Manager integration: real call sites + store push
# ---------------------------------------------------------------------------


def _run_manager_steps(monkeypatch, steps=2):
    monkeypatch.setenv("TPUFT_METRICS_PUSH_SEC", "0.001")
    journal = tracing.TraceJournal(maxlen=1024)
    with tracing.use_journal(journal):
        manager, client, pg, transport = make_manager(
            pg=ProcessGroupDummy(), min_replica_size=1
        )
        client._quorum.return_value = make_quorum(
            quorum_id=4, replica_rank=0, replica_world_size=2,
            max_rank=0, max_world_size=2,
        )
        client.should_commit.side_effect = (
            lambda rank, step, vote, timeout: vote
        )
        for _ in range(steps):
            manager.start_quorum()
            manager.wait_quorum()
            manager.allreduce(np.ones(2, np.float32)).wait()
            assert manager.should_commit()
            time.sleep(0.002)  # past the push rate limit
    return manager, journal


def test_manager_records_ft_phases_and_pushes_trace(monkeypatch) -> None:
    manager, journal = _run_manager_steps(monkeypatch)
    assert manager._trace is journal  # captured the constructing thread's
    names = [e["name"] for e in journal.snapshot()]
    for expected in (
        "quorum", "quorum_ready", "quorum_change", "pg_configure",
        "vote_send", "commit_barrier", "commit",
    ):
        assert expected in names, f"missing {expected} in {names}"
    # The causal tuple tracks the manager: commits at steps 0..N, era 4.
    commits = [e for e in journal.snapshot() if e["name"] == "commit"]
    assert [c["step"] for c in commits] == [0, 1]
    assert all(c["quorum_id"] == 4 for c in commits)
    assert all(c["replica_id"] == "test_replica" for c in commits)
    # Straggler gauge: the barrier wait landed.
    assert (
        metrics.gauge_value(
            "tpuft_trace_barrier_wait_seconds",
            replica_id="test_replica", group_rank="1",
        )
        is not None
    )
    # Trace segments rode the metrics push cadence into the group store.
    key = f"trace/{manager._replica_id}/1"
    raw = manager._store.data.get(key)
    assert raw is not None, f"no trace push at {key}"
    payload = json.loads(raw.decode())
    assert payload["replica_id"] == manager._replica_id
    assert any(e["name"] == "commit" for e in payload["events"])
    assert isinstance(payload["phases"], list) and payload["phases"]
    assert "commit_barrier" in payload["phases"][-1]["phases"]


def test_manager_report_error_lands_in_journal(monkeypatch) -> None:
    journal = tracing.TraceJournal(maxlen=256)
    with tracing.use_journal(journal):
        manager, client, pg, transport = make_manager(pg=ProcessGroupDummy())
        manager.report_error(RuntimeError("injected kill"))
    events = [e for e in journal.snapshot() if e["name"] == "report_error"]
    assert len(events) == 1
    assert "injected kill" in events[0]["args"]["error"]
    assert events[0]["args"]["error_type"] == "RuntimeError"


def test_quorum_timeout_stamps_incident(tmp_path, monkeypatch) -> None:
    monkeypatch.setenv("TPUFT_FLIGHT_RECORDER", str(tmp_path))
    journal = tracing.TraceJournal(maxlen=256)
    with tracing.use_journal(journal):
        manager, client, pg, transport = make_manager(pg=ProcessGroupDummy())
        client._quorum.side_effect = TimeoutError("quorum timed out after 5s")
        # make_manager's sync-quorum mode resolves the future inside
        # start_quorum, so the timeout surfaces right there.
        with pytest.raises(TimeoutError):
            manager.start_quorum()
    incidents = [e for e in journal.snapshot() if e["name"] == "incident"]
    assert len(incidents) == 1
    assert incidents[0]["args"]["kind"] == "quorum_timeout"
    iid = incidents[0]["args"]["incident"]
    # Auto-capture: journal + flight recorder dumped under the incident id.
    assert any(iid in p.name for p in tmp_path.glob("tpuft_trace_*.jsonl"))
    assert any(iid in p.name for p in tmp_path.glob("tpuft_fr_*.jsonl"))


def test_rollback_stamps_shared_incident(tmp_path, monkeypatch) -> None:
    """The pipelined ordering's refused commit: rollback event + the
    deterministic incident id every survivor derives independently."""
    import jax.numpy as jnp
    import optax

    from torchft_tpu.optim import Optimizer

    monkeypatch.setenv("TPUFT_FLIGHT_RECORDER", str(tmp_path))
    monkeypatch.setenv("TPUFT_STRICT_COMMIT", "0")
    journal = tracing.TraceJournal(maxlen=1024)
    with tracing.use_journal(journal):
        manager, client, pg, transport = make_manager(
            pg=ProcessGroupDummy(), min_replica_size=1,
            commit_pipeline_depth=1,
        )
        client._quorum.return_value = make_quorum(
            quorum_id=2, replica_rank=0, replica_world_size=1,
            max_rank=0, max_world_size=1,
        )
        votes = iter([True, False, True])
        client.should_commit.side_effect = (
            lambda rank, step, vote, timeout: vote and next(votes)
        )
        opt = Optimizer(
            manager, optax.sgd(0.1), {"w": jnp.ones(2, jnp.float32)}
        )
        step_fn = opt.make_step_fn(lambda p, b: jnp.sum((p["w"] - b) ** 2))
        for i in range(3):
            step_fn(jnp.full((2,), float(i), jnp.float32))
        opt.flush_pipeline()
    rollbacks = [e for e in journal.snapshot() if e["name"] == "rollback"]
    assert len(rollbacks) == 1
    incidents = [
        e for e in journal.snapshot()
        if e["name"] == "incident" and e["args"]["kind"] == "rollback"
    ]
    assert len(incidents) == 1
    # Deterministic: another process at the same (step, quorum) derives it.
    assert incidents[0]["args"]["incident"] == tracing.incident_id(
        "rollback", rollbacks[0]["step"], rollbacks[0]["quorum_id"]
    )
    assert any(
        incidents[0]["args"]["incident"] in p.name
        for p in tmp_path.glob("tpuft_trace_*.jsonl")
    )
