"""Region-aware WAN training drills (pure Python — no native plane):

- netem topology matrix: env/programmatic parse, directed-link
  precedence (exact pair -> intra/cross default -> global single link),
  stable-prefix region lookup, malformed-env error collection, and the
  no-topology degenerate case being byte-identical to the single link;
- bandwidth-weighted stripe planner: equal weights produce the EXACT
  unweighted plan (the degenerate pin), invalid weights fall back,
  skewed weights split bytes ~proportionally, the per-donor EWMA folds
  and resets, unknown donors inherit the known mean;
- manager donor resolution: same-region donors sort first (stable —
  the storm rotation survives within each region class), zero
  same-region donors keep the cross-region set (never a stuck heal),
  and no topology keeps the region-blind order byte-identical;
- serving relay tiers: descriptor region advertisement, learned
  upstream regions, same-region-first upstream ordering;
- cross-region DiLoCo: ``cross_region_fleet``/``region_split`` resolve
  from the topology map and DiLoCo's ``should_quantize=None`` follows;
- doctor: WARN-never-FAIL topology probe (names the single-region
  degenerate case), per-pair link envs recognized;
- fleet_status REGION column + fleet_trace stripe-weight/region lines
  (golden-style substring pins).
"""

import importlib.util
import os
from pathlib import Path
from unittest.mock import MagicMock

import numpy as np
import pytest

from test_fleet_trace import _Journal
from test_heal_striping import (
    member,
    patched_manager_client,
    stripe_quorum,
)
from test_manager import make_manager
from torchft_tpu import doctor
from torchft_tpu.checkpointing import http_transport as ht
from torchft_tpu.parallel.process_group import ProcessGroupDummy
from torchft_tpu.utils import netem


@pytest.fixture(autouse=True)
def _clean_topology(monkeypatch):
    """Every test starts region-blind with a cold bandwidth EWMA and no
    leaked topology envs, and leaves the module state the same way."""
    for name in list(os.environ):
        if name.startswith(netem.LINK_ENV_PREFIX) or name in (
            netem.ENV_TOPOLOGY,
            netem.ENV_REGION,
        ):
            monkeypatch.delenv(name, raising=False)
    netem.reset_topology()
    netem.set_local_replica_id(None)
    ht.reset_donor_bandwidth()
    yield
    netem.reset_topology()
    netem.set_local_replica_id(None)
    ht.reset_donor_bandwidth()


def _load_script(name: str):
    spec = importlib.util.spec_from_file_location(
        name,
        Path(__file__).resolve().parent.parent / "scripts" / f"{name}.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# netem topology matrix
# ---------------------------------------------------------------------------


def test_topology_env_parse_and_region_lookup(monkeypatch) -> None:
    monkeypatch.setenv(netem.ENV_TOPOLOGY, "r0=us, r1=us, r2=eu, *=ap")
    netem.reset_topology()
    assert netem.topology_enabled()
    assert netem.region_of("r0") == "us"
    assert netem.region_of("r2") == "eu"
    # Stable-prefix fallback: the manager's full replica id carries a
    # per-process uuid after the first ":".
    assert netem.region_of("r1:deadbeef-uuid") == "us"
    # Unlisted replicas take the "*" default region.
    assert netem.region_of("r99") == "ap"
    # Self identity: the manager registers its replica id.
    netem.set_local_replica_id("r2:some-uuid")
    assert netem.local_region() == "eu"


def test_topology_explicit_self_region_wins(monkeypatch) -> None:
    monkeypatch.setenv(netem.ENV_TOPOLOGY, "r0=us")
    monkeypatch.setenv(netem.ENV_REGION, "EU")
    netem.reset_topology()
    netem.set_local_replica_id("r0")
    assert netem.local_region() == "eu"  # explicit env beats the map


def test_link_params_precedence(monkeypatch) -> None:
    monkeypatch.setenv(netem.ENV_TOPOLOGY, "r0=us,r1=eu,r2=ap")
    monkeypatch.setenv(netem.LINK_ENV_PREFIX + "US_EU", "100,0.5")
    monkeypatch.setenv(netem.LINK_ENV_PREFIX + "LOCAL", "2,1.0")
    monkeypatch.setenv(netem.LINK_ENV_PREFIX + "CROSS", "80,0.1")
    monkeypatch.setenv("TPUFT_EMULATED_RTT_MS", "10")
    monkeypatch.setenv("TPUFT_EMULATED_GBPS", "0.2")
    netem.reset_topology()
    netem.configure(10, 0.2)
    # Exact directed pair wins.
    delay, spb = netem.link_params("us", "eu")
    assert delay == pytest.approx(0.05)
    assert spb == pytest.approx(8.0 / (0.5 * 1e9))
    # The REVERSE direction has no exact entry: cross default.
    delay, _ = netem.link_params("eu", "us")
    assert delay == pytest.approx(0.04)
    # Intra-region default.
    delay, _ = netem.link_params("ap", "ap")
    assert delay == pytest.approx(0.001)
    # Unknown side degrades to the global single link.
    delay, spb = netem.link_params(None, "eu")
    assert delay == pytest.approx(0.005)
    assert spb == pytest.approx(8.0 / (0.2 * 1e9))


def test_no_topology_is_byte_identical_to_global_link() -> None:
    netem.configure(20, 0.4)
    assert not netem.topology_enabled()
    assert netem.region_of("anything") is None
    assert netem.local_region() is None
    # Every per-peer lookup answers with the single global link.
    assert netem.link_params("us", "eu") == netem._resolve()
    assert netem._link_for_peer("eu") == netem._resolve()
    netem.configure(0, 0)


def test_topology_malformed_env_collects_errors_stays_servable(
    monkeypatch,
) -> None:
    monkeypatch.setenv(netem.ENV_TOPOLOGY, "r0=us,garbage,r1=eu")
    monkeypatch.setenv(netem.LINK_ENV_PREFIX + "US_EU", "not,numbers")
    monkeypatch.setenv(netem.LINK_ENV_PREFIX + "A_B_C", "1,1")
    netem.reset_topology()
    desc = netem.describe_topology()
    assert desc["configured"]
    assert len(desc["errors"]) == 3
    # The parsable part still serves.
    assert netem.region_of("r0") == "us"
    assert netem.region_of("r1") == "eu"


def test_configure_topology_programmatic_and_reset() -> None:
    netem.configure_topology(
        regions={"a": "us", "b": "eu"},
        links={("us", "eu"): (100, 0.5)},
        intra=(2, 1.0),
        self_region="us",
    )
    assert netem.topology_enabled()
    assert netem.local_region() == "us"
    assert netem.link_params("us", "eu")[0] == pytest.approx(0.05)
    netem.configure_topology()  # empty = region-blind
    assert not netem.topology_enabled()
    netem.reset_topology()


# ---------------------------------------------------------------------------
# bandwidth-weighted stripe planner + per-donor EWMA
# ---------------------------------------------------------------------------


def test_plan_stripes_equal_weights_identical_to_unweighted() -> None:
    """THE degenerate pin: uniform weights (what a cold EWMA or a
    topology-less fleet produces) yield the byte-identical plan."""
    chunks = list(range(17))
    sizes = [(i * 37) % 90 + 10 for i in chunks]
    for donors in (1, 2, 3, 5):
        for rotation in (0, 1, 3):
            base = ht._plan_stripes(chunks, sizes, donors, rotation=rotation)
            for w in (1.0, 7.5):
                assert (
                    ht._plan_stripes(
                        chunks, sizes, donors, rotation=rotation,
                        weights=[w] * donors,
                    )
                    == base
                )


def test_plan_stripes_invalid_weights_fall_back() -> None:
    chunks = [0, 1, 2, 3]
    sizes = [10, 20, 30, 40]
    base = ht._plan_stripes(chunks, sizes, 2)
    # Wrong length and non-positive entries both keep the old path.
    assert ht._plan_stripes(chunks, sizes, 2, weights=[1.0]) == base
    assert ht._plan_stripes(chunks, sizes, 2, weights=[1.0, 0.0]) == base
    assert ht._plan_stripes(chunks, sizes, 2, weights=[1.0, -2.0]) == base


def test_plan_stripes_weighted_skew_splits_bytes_proportionally() -> None:
    chunks = list(range(40))
    sizes = [100] * 40
    stripes = ht._plan_stripes(chunks, sizes, 2, weights=[3.0, 1.0])
    loads = [sum(sizes[i] for i in s) for s in stripes]
    assert sorted(i for s in stripes for i in s) == chunks  # complete
    # 3:1 weights → ~30/10 chunks; LPT keeps it within one chunk.
    assert abs(loads[0] - 3000) <= 100
    assert abs(loads[1] - 1000) <= 100


def test_plan_stripes_without_sizes_ignores_weights() -> None:
    assert ht._plan_stripes([0, 1, 2, 3], None, 2, weights=[9.0, 1.0]) == (
        ht._plan_stripes([0, 1, 2, 3], None, 2)
    )


def test_donor_bandwidth_ewma_fold_and_reset(monkeypatch) -> None:
    key = ht.donor_bw_key("donor0:uuid", "http://x:1")
    assert key == "donor0"  # stable prefix, not the per-process uuid
    assert ht.donor_bandwidth(key) is None
    assert ht.observe_donor_bandwidth(key, 100.0) == pytest.approx(100.0)
    folded = ht.observe_donor_bandwidth(key, 200.0)
    assert folded == pytest.approx(0.3 * 200.0 + 0.7 * 100.0)
    assert ht.donor_bandwidth(key) == pytest.approx(folded)
    ht.reset_donor_bandwidth()
    assert ht.donor_bandwidth(key) is None
    # URL-keyed fallback when no replica id is known.
    assert ht.donor_bw_key(None, "http://x:1") == "http://x:1"
    # Alpha env: invalid values keep the default.
    monkeypatch.setenv(ht.ENV_HEAL_BW_ALPHA, "2.5")
    assert ht.heal_bw_alpha() == pytest.approx(0.3)
    monkeypatch.setenv(ht.ENV_HEAL_BW_ALPHA, "0.5")
    assert ht.heal_bw_alpha() == pytest.approx(0.5)


def test_donor_weights_unknown_inherits_known_mean() -> None:
    ht.observe_donor_bandwidth("a", 100.0)
    ht.observe_donor_bandwidth("b", 300.0)
    weights = ht._donor_weights(["a", "b", "newcomer"])
    assert weights == pytest.approx([100.0, 300.0, 200.0])
    # All-unknown (cold start) → no weights → the unweighted plan.
    assert ht._donor_weights(["x", "y"]) is None
    assert ht._donor_weights([]) is None


# ---------------------------------------------------------------------------
# manager donor resolution: region preference
# ---------------------------------------------------------------------------


def _region_manager_run(url_by_addr, participants):
    manager, client, _, transport = make_manager(
        pg=ProcessGroupDummy(), min_replica_size=1
    )
    transport.recv_checkpoint.return_value = {
        "user": {"model": {"w": np.zeros(2)}},
        "tpuft": {"step": 3, "batches_committed": 6},
    }
    with patched_manager_client(url_by_addr):
        client._quorum.return_value = stripe_quorum(participants=participants)
        manager.start_quorum()
    assert manager.errored() is None
    kwargs = transport.recv_checkpoint.call_args[1]
    manager.shutdown(wait=False)
    return manager, kwargs


def _stripe_participants(self_id):
    return [
        member("ra", "donor_a:1", 3),  # assigned donor: excluded
        member("rb", "donor_b:1", 3),
        member("rc", "donor_c:1", 3),
        member("rd", "donor_d:1", 3),
        member(self_id, "me:1", 0),  # self: excluded
    ]


_STRIPE_URLS = {
    "donor_a:1": "http://a:0",
    "donor_b:1": "http://b:0",
    "donor_c:1": "http://c:0",
    "donor_d:1": "http://d:0",
}


def test_manager_prefers_same_region_donors() -> None:
    """Same-region donors sort to the front of the rotated order (stable
    within each region class), and donor_info labels every donor —
    including the assigned anchor — with replica id + region."""
    netem.configure_topology(
        regions={"ra": "eu", "rb": "us", "rc": "eu", "rd": "us"},
        intra=(2, 1.0),
        cross=(100, 0.1),
        self_region="us",
    )
    manager, kwargs = _region_manager_run(
        _STRIPE_URLS, _stripe_participants("test_replica:x")
    )
    # Candidate order [b, c, d] (no joiners besides self → rotation 0);
    # same-region-first (us: b, d / eu: c) keeps the order WITHIN each
    # region class.
    assert kwargs["donors"] == ["http://b:0", "http://d:0", "http://c:0"]
    info = kwargs["donor_info"]
    assert info["http://d:0"] == {"replica_id": "rd", "region": "us"}
    assert info["http://c:0"] == {"replica_id": "rc", "region": "eu"}
    # The assigned donor (metadata url) rides the same advisory map.
    assert info[kwargs["metadata"]]["replica_id"] == "ra"
    assert info[kwargs["metadata"]]["region"] == "eu"


def test_manager_zero_same_region_donors_falls_back_cross_region() -> None:
    """A joiner whose region holds no live donors keeps the cross-region
    candidates — the preference narrows WHERE bytes come from, never
    WHETHER they come (a region outage must not wedge the heal)."""
    netem.configure_topology(
        regions={"ra": "eu", "rb": "eu", "rc": "eu", "rd": "eu"},
        cross=(100, 0.1),
        self_region="us",
    )
    _, kwargs = _region_manager_run(
        _STRIPE_URLS, _stripe_participants("test_replica:x")
    )
    # All donors cross-region: the rotated order is untouched.
    assert kwargs["donors"] == ["http://b:0", "http://c:0", "http://d:0"]


def test_manager_without_topology_keeps_region_blind_order() -> None:
    """No topology → the sort key is uniform → the donor order is
    byte-identical to the pre-topology plan (and donor_info carries no
    regions)."""
    _, kwargs = _region_manager_run(
        _STRIPE_URLS, _stripe_participants("test_replica:x")
    )
    assert kwargs["donors"] == ["http://b:0", "http://c:0", "http://d:0"]
    assert all(
        v["region"] is None for v in kwargs["donor_info"].values()
    )


# ---------------------------------------------------------------------------
# serving relay tiers
# ---------------------------------------------------------------------------


def test_relay_orders_same_region_upstreams_first(monkeypatch) -> None:
    from torchft_tpu.serving.relay import CachingRelay

    monkeypatch.setenv("TPUFT_SERVING_NOTIFY", "0")
    relay = CachingRelay(
        ["http://u0:1", "http://u1:1", "http://u2:1"],
        start=False,
        region="US",
    )
    assert relay._region == "us"
    # Regions are LEARNED from upstream descriptors during discovery;
    # until then the configured order stands.
    assert relay._ordered_upstreams() == [
        "http://u0:1", "http://u1:1", "http://u2:1"
    ]
    relay._upstream_regions = {
        "http://u0:1": "eu",
        "http://u1:1": "us",
        "http://u2:1": None,
    }
    assert relay._ordered_upstreams() == [
        "http://u1:1", "http://u0:1", "http://u2:1"
    ]


def test_relay_without_region_keeps_configured_order(monkeypatch) -> None:
    from torchft_tpu.serving.relay import CachingRelay

    monkeypatch.setenv("TPUFT_SERVING_NOTIFY", "0")
    relay = CachingRelay(["http://u0:1", "http://u1:1"], start=False)
    assert relay._region is None
    relay._upstream_regions = {"http://u0:1": "eu", "http://u1:1": "us"}
    assert relay._ordered_upstreams() == ["http://u0:1", "http://u1:1"]


def test_descriptor_advertises_region_and_validates() -> None:
    from torchft_tpu.serving import _wire

    manifest = {
        "step": 3,
        "digest": "abc",
        "crc_algo": "crc32",
        "chunk_crcs": [1],
        "chunk_sizes": [2],
    }
    desc = _wire.latest_descriptor(
        manifest, "/serving/chunk", published_ts=10.0, region="us"
    )
    assert desc["region"] == "us"
    _wire.validate_latest(desc)  # advisory key passes validation
    no_region = _wire.latest_descriptor(
        manifest, "/serving/chunk", published_ts=10.0
    )
    assert "region" not in no_region
    _wire.validate_latest(no_region)


# ---------------------------------------------------------------------------
# cross-region DiLoCo
# ---------------------------------------------------------------------------


def test_cross_region_fleet_and_region_split() -> None:
    from torchft_tpu.local_sgd import cross_region_fleet, region_split

    assert not cross_region_fleet()  # no topology
    netem.configure_topology(regions={"r0": "us", "r1": "us"})
    assert not cross_region_fleet()  # single-region degenerate case
    netem.configure_topology(regions={"r0": "us", "r1": "eu", "r2": "us"})
    assert cross_region_fleet()
    assert region_split(["r0", "r1:uuid", "r2", "rx"]) == {
        "us": ["r0", "r2"],
        "eu": ["r1:uuid"],
        "": ["rx"],
    }


def test_diloco_auto_quantize_resolves_from_topology(monkeypatch) -> None:
    """should_quantize=None rides the topology: quantized outer syncs on
    a cross-region fleet, full-precision on a region-blind one. Explicit
    True/False always wins."""
    import optax

    from torchft_tpu import local_sgd

    captured = {}

    class _Frag:
        def __init__(
            self, manager, fragment_id, leaf_indices, outer_tx,
            initial_leaves, should_quantize, fragment_update_alpha,
        ):
            captured["should_quantize"] = should_quantize
            self.leaf_indices = leaf_indices

    monkeypatch.setattr(local_sgd, "_Fragment", _Frag, raising=True)
    manager = MagicMock()
    manager._use_async_quorum = False
    params = {"w": np.zeros(2, dtype=np.float32)}

    def make(should_quantize):
        local_sgd.DiLoCo(
            manager,
            optax.sgd(0.1),
            optax.sgd(0.7),
            params,
            sync_every=2,
            should_quantize=should_quantize,
        )
        return captured["should_quantize"]

    assert make(None) is False  # no topology → full precision
    netem.configure_topology(regions={"r0": "us", "r1": "eu"})
    assert make(None) is True  # cross-region → quantized wire
    assert make(False) is False  # explicit always wins
    netem.configure_topology()
    assert make(True) is True


# ---------------------------------------------------------------------------
# doctor
# ---------------------------------------------------------------------------


def test_doctor_topology_check_warn_never_fail(monkeypatch) -> None:
    status, detail = doctor._check_topology()
    assert status == "PASS" and "region-blind" in detail
    monkeypatch.setenv(netem.ENV_TOPOLOGY, "r0=us,r1=us")
    netem.reset_topology()
    status, detail = doctor._check_topology()
    assert status == "WARN" and "degenerate" in detail
    monkeypatch.setenv(netem.ENV_TOPOLOGY, "r0=us,r1=eu")
    monkeypatch.setenv(netem.LINK_ENV_PREFIX + "US_EU", "100,0.5")
    netem.reset_topology()
    status, detail = doctor._check_topology()
    assert status == "PASS" and "2 regions" in detail
    monkeypatch.setenv(netem.ENV_TOPOLOGY, "busted")
    netem.reset_topology()
    status, detail = doctor._check_topology()
    assert status == "WARN" and "malformed" in detail


def test_doctor_env_check_recognizes_topology_envs(monkeypatch) -> None:
    monkeypatch.setenv("TPUFT_EMULATED_TOPOLOGY", "r0=us")
    monkeypatch.setenv("TPUFT_EMULATED_REGION", "us")
    monkeypatch.setenv("TPUFT_SERVING_REGION", "us")
    monkeypatch.setenv("TPUFT_HEAL_BW_EWMA_ALPHA", "0.3")
    # Per-pair link envs embed region names: prefix-matched, not
    # enumerated.
    monkeypatch.setenv("TPUFT_EMULATED_LINK_US_EU", "100,0.5")
    monkeypatch.setenv("TPUFT_EMULATED_LINK_LOCAL", "2,1.0")
    status, detail = doctor._check_env()
    assert status == "PASS", detail


# ---------------------------------------------------------------------------
# observability: fleet_status REGION column, fleet_trace stripe lines
# ---------------------------------------------------------------------------


def test_fleet_status_region_column() -> None:
    fleet_status = _load_script("fleet_status")
    assert ("region", "REGION") in fleet_status._COLUMNS
    table = {
        "lighthouse": "lh:1",
        "quorum_id": 4,
        "has_quorum": True,
        "rows": [
            {"replica_id": "r0", "rank": 0, "region": "us", "step": 7},
            {"replica_id": "r1", "rank": 0, "region": None, "step": 7},
        ],
    }
    text = fleet_status.render(table)
    _, header, _, r0_line, r1_line = text.splitlines()[:5]
    assert "REGION" in header
    region_col = header.split().index("REGION")
    assert r0_line.split()[region_col] == "us"
    assert r1_line.split()[region_col] == "-"  # topology-less fleet


def test_fleet_trace_explains_stripe_weights_and_regions() -> None:
    """--explain-step names the bandwidth-weighted plan (per-donor
    EWMA + region) and tags each stripe line with the donor's region."""
    fleet_trace = _load_script("fleet_trace")
    j = _Journal("train_2", 0.0, 900.0)
    j.ev(
        "heal_stripe_plan", 0.1, step=4, q=5, donors=2, chunks=16,
        rotation=0, weights=[20971520.0, 2097152.0], regions=["us", "eu"],
    )
    j.ev(
        "heal_stripe", 0.5, step=4, q=5, donor="http://d0:1", chunks=14,
        bytes=14 << 20, duration_s=0.4, fenced=False, region="us",
    )
    j.ev(
        "heal_stripe", 0.55, step=4, q=5, donor="http://d1:2", chunks=2,
        bytes=2 << 20, duration_s=0.35, fenced=False, region="eu",
    )
    merged = fleet_trace.merge_events(j.events)
    text = fleet_trace.explain_step(merged, 4)
    assert (
        "stripe weights: train_2/0 planned 16 chunk(s) over 2 donor(s) "
        "by measured bandwidth: d0[us]=20.0 MB/s d1[eu]=2.0 MB/s" in text
    )
    assert "from http://d0:1 [us]" in text
    assert "from http://d1:2 [eu]" in text


def test_fleet_trace_stripe_lines_without_topology_unchanged() -> None:
    """Region-blind journals render the pre-topology lines verbatim —
    no weights line, no region tag."""
    fleet_trace = _load_script("fleet_trace")
    j = _Journal("train_2", 0.0, 900.0)
    j.ev(
        "heal_stripe_plan", 0.1, step=4, q=5, donors=2, chunks=16,
        rotation=1, weights=None, regions=[None, None],
    )
    j.ev(
        "heal_stripe", 0.5, step=4, q=5, donor="http://d0:1", chunks=8,
        bytes=1 << 20, duration_s=0.4, fenced=False, region=None,
    )
    merged = fleet_trace.merge_events(j.events)
    text = fleet_trace.explain_step(merged, 4)
    assert "stripe weights" not in text
    assert "from http://d0:1 in 0.40s" in text
