"""Quantized wire plane drills (torchft_tpu/wire_codec.py).

Covers the ISSUE-14 acceptance bars end to end, pure Python:

- **default-off proof**: with every codec knob unset, the staged /meta
  bytes and chunk bytes are bit-for-bit the pre-codec format-2 wire
  (pinned against a hand-built format-2 pickle), and the ZeRO wire never
  enters the quantized path;
- **integrity drills**: a bit-flipped ENCODED chunk is caught by the CRC
  and re-fetched (counter-exact); a lying codec tag fails structural
  decode validation and is never adopted; a tampered /meta codec list
  breaks the digest binding before any transfer;
- **mixed-fleet negotiation**: a codec-aware joiner heals from a
  codec-less (format-2) donor bit-exactly — fp32 is negotiated through
  /meta — while an encoded stage bumps /meta to format 3 so a codec-less
  peer refuses cleanly instead of misdecoding;
- **composition**: delta rejoin matches (crc, size) on the ENCODED
  layout, skip_parts still skips, and the serving plane (publisher →
  relay → subscriber) adopts decoded versions whose descriptors bind
  their codec tags into the digest.
"""

import io
import json
import pickle
import threading
import time

import numpy as np
import pytest

import jax

from torchft_tpu import metrics, wire_codec
from torchft_tpu.checkpointing import _serialization
from torchft_tpu.checkpointing.http_transport import (
    HealIntegrityError,
    HTTPTransport,
    _checkpoint_digest,
    _meta_bytes,
    _plan_chunks,
)
from torchft_tpu.ops import quantization as q
from torchft_tpu.serving import CachingRelay, WeightPublisher, WeightSubscriber
from torchft_tpu.serving._wire import validate_latest


def big_state(seed: int = 0) -> dict:
    """Two codec-eligible float leaves + a tiny leaf + an int leaf (both
    must pass through unencoded)."""
    rng = np.random.default_rng(seed)
    return {
        "w": rng.normal(0, 1.5, (64, 256)).astype(np.float32),
        "v": rng.normal(0, 0.2, (8192,)).astype(np.float32),
        "b": np.arange(8, dtype=np.float32),
        "step": 41,
    }


def codec_reference(state: dict, codec: str) -> dict:
    """What a lossless wire would deliver after one encode/decode trip."""
    enc, _ = wire_codec.encode_state(state, codec)
    return wire_codec.decode_state(enc)


def heal_counters() -> dict:
    return {
        "checksum": metrics.counter_total("tpuft_heal_checksum_failures_total"),
        "refetch": metrics.counter_total("tpuft_heal_chunk_refetches_total"),
        "decode_fail": metrics.counter_total("tpuft_codec_decode_failures_total"),
        "delta_saved": metrics.counter_total("tpuft_heal_delta_bytes_saved_total"),
    }


# ---------------------------------------------------------------------------
# registry + encode/decode units
# ---------------------------------------------------------------------------


def test_codec_env_knobs_default_fp32(monkeypatch) -> None:
    for env in (
        wire_codec.ENV_HEAL_CODEC,
        wire_codec.ENV_SERVING_CODEC,
        wire_codec.ENV_ZERO_CODEC,
    ):
        monkeypatch.delenv(env, raising=False)
    assert wire_codec.heal_codec() == "fp32"
    assert wire_codec.serving_codec() == "fp32"
    assert wire_codec.zero_codec() == "fp32"
    monkeypatch.setenv(wire_codec.ENV_HEAL_CODEC, "int8")
    monkeypatch.setenv(wire_codec.ENV_ZERO_CODEC, "fp8")
    assert wire_codec.heal_codec() == "int8"
    assert wire_codec.zero_codec() == "fp8"
    monkeypatch.setenv(wire_codec.ENV_SERVING_CODEC, "banana")
    with pytest.raises(ValueError):
        wire_codec.serving_codec()


@pytest.mark.parametrize("codec", ["fp8", "int8", "int4"])
def test_encode_decode_roundtrip_and_eligibility(codec) -> None:
    state = big_state()
    enc, stats = wire_codec.encode_state(state, codec)
    # Exactly the two big float leaves encoded; tiny + int pass through.
    assert stats["encoded_leaves"] == 2
    assert enc["b"] is state["b"] and enc["step"] == 41
    assert wire_codec.is_encoded_leaf(enc["w"])
    # The wire actually narrows (scales overhead included).
    expected = {"fp8": 4, "int8": 4, "int4": 8}[codec]
    ratio = stats["pre_bytes"] / stats["post_bytes"]
    assert ratio > expected * 0.75
    dec = wire_codec.decode_state(enc)
    assert dec["w"].dtype == np.float32 and dec["w"].shape == (64, 256)
    # Bounded by the format's per-block resolution, not exactness.
    err = np.max(np.abs(dec["w"] - state["w"]))
    assert err < (1.0 if codec in ("int4", "fp8") else 0.1)
    np.testing.assert_array_equal(dec["b"], state["b"])


def test_fp32_passthrough_is_identity() -> None:
    state = big_state()
    enc, stats = wire_codec.encode_state(state, None)
    assert enc is state and stats["encoded_leaves"] == 0
    enc2, _ = wire_codec.encode_state(state, "fp32")
    assert enc2 is state
    assert wire_codec.chunk_codecs_for(5, None) is None
    assert wire_codec.chunk_codecs_for(5, "fp32") is None
    assert wire_codec.chunk_codecs_for(2, "int8") == ["int8", "int8"]


def test_lying_codec_tag_raises_never_decodes() -> None:
    """The tag is self-verifying: payload dtype/geometry must match the
    claimed codec or decode raises — fabricating state from mismatched
    bytes is structurally impossible."""
    state = {"w": np.ones((4096,), np.float32)}
    enc, _ = wire_codec.encode_state(state, "int8")
    lying = {"w": dict(enc["w"])}
    lying["w"][wire_codec.CODEC_KEY] = "fp8"  # int8 bytes, fp8 tag
    before = metrics.counter_total("tpuft_codec_decode_failures_total")
    with pytest.raises(wire_codec.WireCodecError, match="lying codec tag"):
        wire_codec.decode_state(lying)
    assert (
        metrics.counter_total("tpuft_codec_decode_failures_total") - before == 1
    )
    # Wrong geometry (truncated payload) is equally fatal.
    short = {"w": dict(enc["w"])}
    short["w"]["payload"] = np.asarray(short["w"]["payload"])[:-1]
    with pytest.raises(wire_codec.WireCodecError):
        wire_codec.decode_state(short)
    # A skipped part's nulled marker decodes to None, not an error.
    nulled = {"w": {wire_codec.CODEC_KEY: None, "payload": None, "scales": None,
                    "shape": None, "dtype": None}}
    assert wire_codec.decode_state(nulled)["w"] is None


def test_digest_binds_codec_tags() -> None:
    crcs = [1, 2, 3]
    base = _checkpoint_digest(7, "crc32", crcs)
    # fp32/None tags keep the pre-codec binding byte-identical.
    assert _checkpoint_digest(7, "crc32", crcs, None) == base
    assert _checkpoint_digest(7, "crc32", crcs, ["fp32"] * 3) == base
    tagged = _checkpoint_digest(7, "crc32", crcs, ["int8"] * 3)
    assert tagged != base
    assert tagged != _checkpoint_digest(7, "crc32", crcs, ["fp8"] * 3)


# ---------------------------------------------------------------------------
# default-off proof: bit-for-bit the pre-codec wire
# ---------------------------------------------------------------------------


def test_default_off_meta_and_chunks_bit_identical(monkeypatch) -> None:
    """With every codec knob unset, the staged /meta is byte-equal to a
    hand-built FORMAT-2 pickle (no codec fields anywhere) and the chunk
    bytes are exactly the raw-leaf serialization — the pre-codec wire,
    bit for bit."""
    monkeypatch.delenv(wire_codec.ENV_HEAL_CODEC, raising=False)
    state = big_state()
    donor = HTTPTransport(timeout=10.0, num_chunks=3)
    try:
        donor.send_checkpoint([1], step=9, state_dict=state, timeout=10.0)
        staged = donor._staged
        assert staged.chunk_codecs is None
        # Chunk bytes == the raw plan's serialization, byte for byte.
        treedef, chunk_dicts, _parts = _plan_chunks(state, 3)
        for got, chunk in zip(staged.chunks, chunk_dicts):
            ref = io.BytesIO()
            _serialization.write_prepared(_serialization.prepare(chunk), ref)
            out = io.BytesIO()
            _serialization.write_prepared(got, out)
            assert out.getvalue() == ref.getvalue()
        # /meta bytes == the hand-built format-2 body with NO codec keys.
        expected = pickle.dumps(
            {
                "format": 2,
                "num_chunks": 3,
                "treedef": treedef,
                "step": 9,
                "quorum_id": None,
                "crc_algo": staged.crc_algo,
                "chunk_crcs": staged.chunk_crcs,
                "digest": staged.digest,
                "parts": {},
                "chunk_sizes": staged.chunk_sizes,
            }
        )
        assert staged.meta_bytes() == expected
    finally:
        donor.shutdown()


def test_default_off_zero_wire_payload_identical(monkeypatch) -> None:
    """Codec knob unset -> the ZeRO allgather payload is the raw f32
    ranges (no packing, no alltoall);  the flat plane's bytes are
    untouched by this PR's default path."""
    monkeypatch.delenv(wire_codec.ENV_ZERO_CODEC, raising=False)
    from test_zero import LoopbackPG, _LoopbackWorld, _make_rank, _parallel

    import jax.numpy as jnp
    import optax

    params = {"w": jnp.arange(4096, dtype=jnp.float32) / 7}

    def loss(p, b):
        return jnp.sum((p["w"] - b) ** 2)

    grad = jax.jit(jax.grad(loss))
    world = _LoopbackWorld(2)
    ranks = [
        _make_rank(world, r, 2, params, optax.sgd(0.1), num_shards=4)
        for r in range(2)
    ]

    def run(r):
        manager, opt, _pg = ranks[r]

        def go():
            manager.start_quorum()
            manager.wait_quorum()
            assert opt.step(grad(opt.params, jnp.zeros((4096,), jnp.float32)))
            return np.asarray(opt.params["w"])

        return go

    _parallel([run(r) for r in range(2)])
    for _m, _o, pg in ranks:
        assert pg.op_counts.get("alltoall", 0) == 0


# ---------------------------------------------------------------------------
# heal-path drills
# ---------------------------------------------------------------------------


def test_quantized_heal_roundtrip_and_format3(monkeypatch) -> None:
    monkeypatch.setenv(wire_codec.ENV_HEAL_CODEC, "int8")
    state = big_state()
    donor = HTTPTransport(timeout=10.0, num_chunks=3)
    joiner = HTTPTransport(timeout=10.0)
    try:
        manifest = donor.send_checkpoint(
            [1], step=5, state_dict=state, timeout=10.0, quorum_id=2
        )
        staged = donor._staged
        assert staged.chunk_codecs == ["int8"] * len(staged.chunks)
        meta = pickle.loads(staged.meta_bytes())
        # Format bump: a codec-less peer REFUSES this stage outright
        # (its format check), never misdecodes encoded bytes.
        assert meta["format"] == 3 and meta["codec"] == "int8"
        assert manifest["chunk_codecs"] == ["int8"] * len(staged.chunks)
        # The wire moved meaningfully fewer bytes than the raw payload.
        raw = sum(
            int(np.asarray(v).nbytes)
            for v in (state["w"], state["v"], state["b"])
        )
        assert sum(staged.chunk_sizes) < raw * 0.4
        out = joiner.recv_checkpoint(
            0, donor.metadata(), 5, timeout=10.0, quorum_id=2
        )
        ref = codec_reference(state, "int8")
        np.testing.assert_array_equal(out["w"], ref["w"])
        np.testing.assert_array_equal(out["v"], ref["v"])
        np.testing.assert_array_equal(out["b"], state["b"])  # passthrough
        assert out["step"] == state["step"]
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_corrupt_encoded_chunk_caught_by_crc_and_refetched(monkeypatch) -> None:
    """The punisher's corrupt_quantized_chunk drill: a bit flip inside an
    ENCODED chunk is caught by the CRC (computed over encoded bytes),
    re-fetched within the window, and the adopted state equals the clean
    decode — counter-exact, zero wrong adoptions."""
    monkeypatch.setenv(wire_codec.ENV_HEAL_CODEC, "int8")
    state = big_state()
    donor = HTTPTransport(timeout=10.0, num_chunks=3)
    joiner = HTTPTransport(timeout=10.0)
    try:
        donor.send_checkpoint([1], step=5, state_dict=state, timeout=10.0)
        injected = []

        def corrupt_once(step: int, index: int):
            if index == 1 and not injected:
                injected.append(index)
                return "corrupt_stream"
            return None

        donor._fault_hook = corrupt_once
        before = heal_counters()
        out = joiner.recv_checkpoint(0, donor.metadata(), 5, timeout=10.0)
        after = heal_counters()
        ref = codec_reference(state, "int8")
        np.testing.assert_array_equal(out["w"], ref["w"])
        assert after["checksum"] - before["checksum"] == 1  # exact
        assert after["refetch"] - before["refetch"] == 1
        assert after["decode_fail"] - before["decode_fail"] == 0
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_lying_codec_tag_end_to_end_never_adopted(monkeypatch) -> None:
    """A donor whose encoded payload does not match its (digest-bound,
    CRC-clean) tags: every chunk verifies its CRC — the bytes are what
    the donor staged — but decode raises and recv_checkpoint surfaces
    HealIntegrityError (the manager's report_error funnel), never a
    fabricated state dict."""
    monkeypatch.setenv(wire_codec.ENV_HEAL_CODEC, "int8")
    real_encode = wire_codec.encode_state

    def lying_encode(state, codec, wire="heal"):
        enc, stats = real_encode(state, codec, wire=wire)

        def lie(node):
            if wire_codec.is_encoded_leaf(node):
                node = dict(node)
                node[wire_codec.CODEC_KEY] = "fp8"  # int8 bytes, fp8 tag
            return node

        return (
            jax.tree_util.tree_map(
                lie, enc, is_leaf=wire_codec.is_encoded_leaf
            ),
            stats,
        )

    state = big_state()
    import torchft_tpu.checkpointing.http_transport as ht

    monkeypatch.setattr(ht.wire_codec, "encode_state", lying_encode)
    donor = HTTPTransport(timeout=10.0, num_chunks=2)
    monkeypatch.setattr(ht.wire_codec, "encode_state", real_encode)
    joiner = HTTPTransport(timeout=10.0)
    try:
        monkeypatch.setattr(ht.wire_codec, "encode_state", lying_encode)
        donor.send_checkpoint([1], step=5, state_dict=state, timeout=10.0)
        monkeypatch.setattr(ht.wire_codec, "encode_state", real_encode)
        before = heal_counters()
        with pytest.raises(HealIntegrityError, match="codec validation"):
            joiner.recv_checkpoint(0, donor.metadata(), 5, timeout=3.0)
        after = heal_counters()
        assert after["decode_fail"] - before["decode_fail"] == 1
        assert after["checksum"] - before["checksum"] == 0  # CRCs were clean
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_tampered_meta_codec_list_breaks_digest_binding() -> None:
    """A /meta whose chunk_codecs were swapped after staging fails the
    digest binding check — rejected before any payload transfer."""
    crcs = [11, 22]
    digest = _checkpoint_digest(3, "crc32", crcs, ["int8", "int8"])
    meta = pickle.loads(
        _meta_bytes(
            step=3, quorum_id=None, num_chunks=2, treedef=None,
            crc_algo="crc32", chunk_crcs=crcs, digest=digest,
            chunk_sizes=[10, 10], chunk_codecs=["int8", "int8"],
        )
    )
    assert meta["format"] == 3
    assert _checkpoint_digest(3, "crc32", crcs, meta["chunk_codecs"]) == digest
    # The tamper: claim fp32 (or another codec) after the fact.
    assert _checkpoint_digest(3, "crc32", crcs, ["fp8", "fp8"]) != digest
    assert _checkpoint_digest(3, "crc32", crcs, None) != digest


def test_new_joiner_heals_from_old_format2_donor_bit_exact(monkeypatch) -> None:
    """Mixed-fleet negotiation: the donor staged WITHOUT a codec (the
    format-2 wire); a joiner whose TPUFT_HEAL_CODEC is set adopts the
    donor's bytes bit-exactly — the /meta (no codec field) is the
    negotiation, and the joiner's own preference never reinterprets the
    donor's raw bytes."""
    state = big_state()
    monkeypatch.delenv(wire_codec.ENV_HEAL_CODEC, raising=False)
    donor = HTTPTransport(timeout=10.0, num_chunks=3)
    try:
        donor.send_checkpoint([1], step=5, state_dict=state, timeout=10.0)
        monkeypatch.setenv(wire_codec.ENV_HEAL_CODEC, "int8")
        joiner = HTTPTransport(timeout=10.0)
        try:
            out = joiner.recv_checkpoint(0, donor.metadata(), 5, timeout=10.0)
            np.testing.assert_array_equal(out["w"], state["w"])
            np.testing.assert_array_equal(out["v"], state["v"])
        finally:
            joiner.shutdown()
    finally:
        donor.shutdown()


def test_delta_rejoin_matches_on_encoded_layout(monkeypatch) -> None:
    """Delta rejoin composes with the codec: a rejoiner holding the same
    committed state plans it through the donor's codec and adopts every
    unchanged ENCODED chunk without fetching — (crc, size) matching on
    the compressed bytes."""
    monkeypatch.setenv(wire_codec.ENV_HEAL_CODEC, "int8")
    state = big_state()
    donor = HTTPTransport(timeout=10.0, num_chunks=4)
    joiner = HTTPTransport(timeout=10.0)
    try:
        donor.send_checkpoint([1], step=5, state_dict=state, timeout=10.0)
        staged_bytes = sum(donor._staged.chunk_sizes)
        before = heal_counters()
        out = joiner.recv_checkpoint(
            0, donor.metadata(), 5, timeout=10.0, local_state=state
        )
        after = heal_counters()
        # EVERY chunk delta-matched: zero fetched payload bytes.
        assert after["delta_saved"] - before["delta_saved"] == staged_bytes
        ref = codec_reference(state, "int8")
        np.testing.assert_array_equal(out["w"], ref["w"])
    finally:
        donor.shutdown()
        joiner.shutdown()


# ---------------------------------------------------------------------------
# serving-plane drills
# ---------------------------------------------------------------------------


def test_quantized_serving_publisher_relay_subscriber(monkeypatch) -> None:
    """The full fan-out path at int8: publisher stages encoded chunks,
    the byte-level relay caches them verbatim (codec tags preserved
    across the tier), and the subscriber decodes after verify-then-swap."""
    monkeypatch.setenv(wire_codec.ENV_SERVING_CODEC, "int8")
    state = {"params": big_state()["w"]}
    pub = WeightPublisher(num_chunks=4, timeout=5.0)
    relay = None
    try:
        pub.publish(step=1, quorum_id=0, state=state)
        latest = pub.latest()
        assert latest["chunk_codecs"] == ["int8"] * 4
        assert validate_latest(latest) is None
        relay = CachingRelay([pub.address()], poll_interval=0.05, timeout=5.0)
        deadline = time.monotonic() + 10
        while relay.current() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        assert relay.current() is not None
        assert relay.current().chunk_codecs == ["int8"] * 4
        sub = WeightSubscriber([relay.address()], timeout=5.0, notify=False)
        version = sub.poll()
        assert version is not None
        ref = codec_reference(state, "int8")
        np.testing.assert_array_equal(version.params["params"], ref["params"])
    finally:
        if relay is not None:
            relay.shutdown()
        pub.shutdown()


def test_descriptor_codec_tamper_rejected_by_digest(monkeypatch) -> None:
    monkeypatch.setenv(wire_codec.ENV_SERVING_CODEC, "int8")
    pub = WeightPublisher(num_chunks=2, timeout=5.0)
    try:
        pub.publish(step=1, quorum_id=0, state={"params": big_state()["w"]})
        latest = dict(pub.latest())
        assert validate_latest(latest) is None
        tampered = dict(latest)
        tampered["chunk_codecs"] = ["fp8"] * len(latest["chunk_codecs"])
        assert "digest" in (validate_latest(tampered) or "digest")
        stripped = {k: v for k, v in latest.items()
                    if k not in ("chunk_codecs", "codec")}
        reason = validate_latest(stripped)
        assert reason is not None and "digest" in reason
        bogus = dict(latest)
        bogus["chunk_codecs"] = ["banana"] * len(latest["chunk_codecs"])
        assert "invalid chunk_codecs" in validate_latest(bogus)
    finally:
        pub.shutdown()


# ---------------------------------------------------------------------------
# punisher arm
# ---------------------------------------------------------------------------


def test_punisher_corrupt_quantized_chunk_arm(tmp_path, monkeypatch) -> None:
    """The corrupt_quantized_chunk arm is the corrupt_stream bit-flip at
    the heal_stream site — the drill's semantic weight is that it fires
    against an ENCODED chunk, where the CRC-over-encoded-bytes design is
    what catches it (test_corrupt_encoded_chunk... proves the catch)."""
    from torchft_tpu import punisher
    from torchft_tpu.utils import faultinject

    fault_file = tmp_path / "faults.json"
    monkeypatch.setenv("TPUFT_FAULT_FILE", str(fault_file))
    assert "corrupt_quantized_chunk" in punisher.HEAL_FAULT_MODES
    assert "corrupt_quantized_chunk" in punisher.ALL_FAULT_MODES
    assert punisher.arm_stream_fault(
        "corrupt_quantized_chunk", fault_file=str(fault_file)
    )
    assert faultinject.consume("heal_stream:1234") == "corrupt_stream"
    assert faultinject.consume("heal_stream:1234") is None


def test_serve_child_stages_and_serves_encoded_chunks(monkeypatch) -> None:
    """Serve-child isolation composes with the codec: the sidecar's
    /dev/shm epoch files ARE the encoded bytes (CRC'd in the same
    staging pass), its /delta names the codec, and a joiner heals the
    decoded state through the identical verification pipeline."""
    import json
    import urllib.request

    monkeypatch.setenv(wire_codec.ENV_HEAL_CODEC, "int8")
    state = big_state()
    donor = HTTPTransport(timeout=10.0, num_chunks=3, serve_mode="child")
    try:
        if not donor._child_serving():
            pytest.skip("serve child unavailable in this environment")
        manifest = donor.send_checkpoint(
            [1], step=4, state_dict=state, timeout=10.0
        )
        assert manifest["chunk_codecs"] == ["int8"] * 3
        addr = donor.metadata()
        body = json.loads(
            urllib.request.urlopen(
                f"{addr}/checkpoint/4/delta?crcs=1,2,3&algo=crc32", timeout=5
            ).read()
        )
        assert body.get("chunk_codecs") == ["int8"] * 3
        joiner = HTTPTransport(timeout=10.0)
        try:
            out = joiner.recv_checkpoint(0, addr, 4, timeout=10.0)
            ref = codec_reference(state, "int8")
            np.testing.assert_array_equal(out["w"], ref["w"])
        finally:
            joiner.shutdown()
    finally:
        donor.shutdown()
