"""ZeRO plane tests (torchft_tpu/zero.py).

Pure-python coverage that runs without the native toolchain: shard
assignment determinism, flat-plane pack/unpack, N=1 degeneration against
the plain Optimizer, bitwise identity across commit orderings, the
re-balance transfer plan, shard-addressable heal (skip_parts), and REAL
multi-rank wire behavior over an in-process loopback ProcessGroup (each
replica a thread — no native store needed). The full kill/heal drill on
the real coordination plane lives in test_zero_integ.py (native-gated).
"""

import os
import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from test_manager import make_manager, make_quorum

from torchft_tpu import metrics
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.optim import Optimizer, _align_opt_state, make_jit_shard_update
from torchft_tpu.parallel.process_group import (
    ProcessGroup,
    ProcessGroupDummy,
    ReduceOp,
)
from torchft_tpu.work import _DummyWork
from torchft_tpu.zero import (
    ShardSpec,
    ZeroOptimizer,
    plan_shard_moves,
    shard_assignment,
    shard_part_name,
)


def scripted_manager(num_participants=1, rank=0, pg=None, **kwargs):
    """One-replica-group manager against a scripted coordination client."""
    kwargs.setdefault("min_replica_size", 1)
    manager, client, _pg, transport = make_manager(
        pg=pg if pg is not None else ProcessGroupDummy(), **kwargs
    )
    client._quorum.return_value = make_quorum(
        replica_rank=rank,
        replica_world_size=num_participants,
        max_rank=rank,
        max_world_size=num_participants,
    )
    client.should_commit.side_effect = lambda rank, step, vote, timeout: vote
    return manager


# ---------------------------------------------------------------------------
# shard assignment + transfer plan (pure functions)
# ---------------------------------------------------------------------------


def test_shard_assignment_deterministic_and_complete() -> None:
    for n in (1, 2, 3, 4, 7, 8):
        for policy in ("block", "strided"):
            a = shard_assignment(8, n, step=3, policy=policy)
            b = shard_assignment(8, n, step=3, policy=policy)
            np.testing.assert_array_equal(a, b)  # no communication, no state
            assert a.shape == (8,)
            # Complete: every shard has exactly one owner in range.
            assert set(np.unique(a)) <= set(range(min(n, 8)))
            # Balanced: owner loads differ by at most one shard.
            counts = np.bincount(a, minlength=min(n, 8))
            assert counts.max() - counts.min() <= 1


def test_shard_assignment_block_is_contiguous() -> None:
    owners = shard_assignment(8, 4, policy="block")
    np.testing.assert_array_equal(owners, [0, 0, 1, 1, 2, 2, 3, 3])
    owners = shard_assignment(8, 3, policy="block")
    np.testing.assert_array_equal(owners, [0, 0, 0, 1, 1, 1, 2, 2])


def test_shard_assignment_n1_owns_everything() -> None:
    np.testing.assert_array_equal(shard_assignment(8, 1), np.zeros(8))


def test_shard_assignment_rejects_bad_policy() -> None:
    with pytest.raises(ValueError):
        shard_assignment(8, 2, policy="roulette")


def test_plan_shard_moves_only_moves_changed_ownership() -> None:
    # 2 ranks each holding their block at step 5; same assignment again:
    # nothing moves.
    manifests = [
        (0, 5, [(0, 5), (1, 5)]),
        (1, 5, [(2, 5), (3, 5)]),
    ]
    owners = shard_assignment(4, 2, policy="block")
    moves, lost = plan_shard_moves(manifests, owners, {0: 0, 1: 1}, 5)
    assert moves == [] and lost == []


def test_plan_shard_moves_shrink_reassigns_and_reports_lost() -> None:
    # Rank 1 died holding shards 2, 3: the survivor owns everything under
    # N=1; its held shards stay put, the dead ones are lost.
    manifests = [(0, 5, [(0, 5), (1, 5)])]
    owners = shard_assignment(4, 1)
    moves, lost = plan_shard_moves(manifests, owners, {0: 0}, 5)
    assert moves == [] and lost == [2, 3]


def test_plan_shard_moves_grow_moves_only_new_owners_shards() -> None:
    # Survivor (pg 0) holds all 4 at step 9; a joiner lands at
    # participant rank 1 / pg rank 1: exactly the joiner's block moves.
    manifests = [(0, 9, [(0, 9), (1, 9), (2, 9), (3, 9)]), (1, 9, [])]
    owners = shard_assignment(4, 2, policy="block")
    moves, lost = plan_shard_moves(manifests, owners, {0: 0, 1: 1}, 9)
    assert moves == [(2, 0, 1), (3, 0, 1)] and lost == []


def test_plan_shard_moves_fences_stale_holders() -> None:
    # A rejoiner kept shards from before it died (step 3 < current 7):
    # never chosen as a source; its shards count as lost.
    manifests = [(0, 7, []), (1, 3, [(0, 3), (1, 3)])]
    owners = shard_assignment(2, 1)
    moves, lost = plan_shard_moves(manifests, owners, {0: 0}, 7)
    assert moves == [] and lost == [0, 1]


# ---------------------------------------------------------------------------
# ShardSpec flat plane
# ---------------------------------------------------------------------------


def test_shard_spec_pack_unpack_roundtrip_mixed_dtypes() -> None:
    params = {
        "w": jnp.arange(10, dtype=jnp.float32).reshape(2, 5) / 7,
        "b": jnp.ones((3,), jnp.bfloat16),
        "scalar": jnp.float32(2.5),
    }
    spec = ShardSpec(params, num_shards=4)
    assert spec.total == 14
    assert spec.padded == spec.num_shards * spec.shard_len >= spec.total
    flat = spec.pack(params)
    assert flat.shape == (spec.padded,) and flat.dtype == jnp.float32
    back = spec.unpack(flat)
    for key in params:
        got, want = back[key], params[key]
        assert got.dtype == want.dtype and got.shape == want.shape
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_shard_spec_rejects_non_array_leaves() -> None:
    with pytest.raises(ValueError, match="non-array"):
        ShardSpec({"w": jnp.ones(3), "name": "layer0"}, num_shards=2)


def test_make_jit_shard_update_matches_per_shard_eager() -> None:
    tx = optax.adam(0.1)
    update = make_jit_shard_update(tx)
    masters = [jnp.arange(4, dtype=jnp.float32), jnp.ones(4, jnp.float32)]
    states = [tx.init(m) for m in masters]
    grads = [jnp.full((4,), 0.5, jnp.float32), jnp.full((4,), -1.0, jnp.float32)]
    new_masters, new_states = update(grads, states, masters)
    for g, s, m, nm in zip(grads, states, masters, new_masters):
        upd, _ = tx.update(g, s, m)
        np.testing.assert_allclose(
            np.asarray(nm), np.asarray(optax.apply_updates(m, upd)), rtol=1e-6
        )


# ---------------------------------------------------------------------------
# N=1 degeneration + commit orderings (scripted manager, no wire)
# ---------------------------------------------------------------------------

_PARAMS = {"w": jnp.array([1.0, -2.0, 3.0], jnp.float32)}


def _loss(p, batch):
    return jnp.sum((p["w"] - batch) ** 2)


_BATCHES = [jnp.full((3,), 0.1 * i, jnp.float32) for i in range(5)]


_DEPTH_OF = {"strict": 0, "overlapped": 0, "pipelined": 1, "pipelined-deep": 3}


def _run_zero(mode, monkeypatch, tx=None, num_shards=4):
    monkeypatch.setenv("TPUFT_STRICT_COMMIT", "1" if mode == "strict" else "0")
    manager = scripted_manager(commit_pipeline_depth=_DEPTH_OF[mode])
    opt = ZeroOptimizer(
        manager, tx or optax.sgd(0.2, momentum=0.9), _PARAMS,
        num_shards=num_shards,
    )
    step_fn = opt.make_step_fn(_loss)
    losses = []
    for batch in _BATCHES:
        loss, _committed = step_fn(batch)
        losses.append(float(loss))
    if _DEPTH_OF[mode]:
        assert opt.flush_pipeline() is True
    return np.asarray(opt.params["w"]), losses, manager.current_step(), opt


def test_zero_lone_replica_matches_plain_optimizer(monkeypatch) -> None:
    """N=1 degenerates to today's behavior: same trajectory as the plain
    Optimizer (float tolerance — the flat-plane program differs from the
    fused tree program by XLA scheduling, not by math) and full shard
    ownership with zero wire traffic."""
    import torchft_tpu.ddp as ddp_mod

    def _boom(*a, **k):
        raise AssertionError("wire path used on the lone-replica zero step")

    monkeypatch.setattr(ddp_mod, "ft_allreduce_gradients", _boom)
    ref_manager = scripted_manager()
    ref = Optimizer(ref_manager, optax.sgd(0.2, momentum=0.9), _PARAMS)
    ref_fn = ref.make_step_fn(_loss)
    ref_losses = [float(ref_fn(b)[0]) for b in _BATCHES]

    w, losses, step, opt = _run_zero("overlapped", monkeypatch)
    np.testing.assert_allclose(w, np.asarray(ref.params["w"]), rtol=1e-6)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-6)
    assert step == 5
    # Sole participant owns every shard — the degenerate (unsharded) case.
    assert sorted(opt.opt_state.held) == [0, 1, 2, 3]


@pytest.mark.parametrize(
    "mode", ["strict", "overlapped", "pipelined", "pipelined-deep"]
)
def test_zero_orderings_produce_identical_trajectories(monkeypatch, mode) -> None:
    """The sharded step commits bitwise-identical params under all four
    commit orderings — strict / overlapped / pipelined depth 1 / depth 3
    (rollback snapshots of a sharded opt_state included in the pipelined
    window machinery at every depth)."""
    w_ref, losses_ref, _, _ = _run_zero("strict", monkeypatch)
    w, losses, step, _ = _run_zero(mode, monkeypatch)
    np.testing.assert_array_equal(w, w_ref)
    assert losses == losses_ref
    assert step == 5


def test_zero_pipelined_rollback_restores_sharded_state(monkeypatch) -> None:
    monkeypatch.setenv("TPUFT_STRICT_COMMIT", "0")
    manager = scripted_manager(commit_pipeline_depth=1)
    votes = iter([True, False, True, True])
    manager._client.should_commit.side_effect = (
        lambda rank, step, vote, timeout: vote and next(votes)
    )
    opt = ZeroOptimizer(
        manager, optax.sgd(0.1), {"w": jnp.array([1.0, 1.0], jnp.float32)},
        num_shards=2,
    )
    step_fn = opt.make_step_fn(lambda p, b: jnp.sum((p["w"] - b) ** 2))
    flags = []
    for i in range(4):
        _, prev = step_fn(jnp.full((2,), float(i), jnp.float32))
        flags.append(prev)
    assert opt.flush_pipeline() is True
    assert flags == [None, True, False, True]
    assert opt.rollback_count == 1
    assert manager.current_step() == 3
    # The sharded state's committed-step tag tracks the manager exactly
    # (the re-balance manifest's freshness fence).
    assert opt.opt_state.step == 3
    w = np.array([1.0, 1.0], np.float32)
    for b in (0.0, 2.0, 3.0):
        w = w - 0.1 * 2 * (w - b)
    np.testing.assert_allclose(np.asarray(opt.params["w"]), w, rtol=1e-6)


def test_zero_heal_during_barrier_recomputes_on_healed_state() -> None:
    """A heal landing inside the commit barrier: params adopt the
    allgathered (committed) ranges, the healed shard-less state forces a
    re-balance, and nothing stale survives."""
    manager = scripted_manager()
    opt = ZeroOptimizer(
        manager, optax.sgd(0.1), {"w": jnp.array([1.0, 1.0], jnp.float32)},
        num_shards=2,
    )
    step_fn = opt.make_step_fn(lambda p, b: jnp.sum((p["w"] - b) ** 2))
    loss, committed = step_fn(jnp.array([1.0, 2.0], jnp.float32))
    assert committed

    donor_manager = scripted_manager()
    donor = ZeroOptimizer(
        donor_manager, optax.sgd(0.1),
        {"w": jnp.array([10.0, 10.0], jnp.float32)}, num_shards=2,
    )
    donor_fn = donor.make_step_fn(lambda p, b: jnp.sum((p["w"] - b) ** 2))
    donor_fn(jnp.zeros(2, jnp.float32))
    donor_state = donor._state_dict()

    real_should_commit = manager.should_commit
    healed_once = []

    def healing_should_commit(timeout=None):
        ok = real_should_commit(timeout=timeout)
        if not healed_once:
            healed_once.append(True)
            opt._load_state_dict(donor_state)
        return ok

    manager.should_commit = healing_should_commit
    _, committed = step_fn(jnp.array([0.0, 0.0], jnp.float32))
    assert committed
    assert opt._heal_count == 1
    # The healed state forces a fresh re-balance at the next step.
    assert opt.opt_state.balance_key is None
    _, committed = step_fn(jnp.array([0.0, 0.0], jnp.float32))
    assert committed
    assert sorted(opt.opt_state.held) == [0, 1]


# ---------------------------------------------------------------------------
# loopback multi-rank wire (threads as replicas, no native store)
# ---------------------------------------------------------------------------


class _LoopbackWorld:
    """In-memory rendezvous for N thread-replicas: collectives match up by
    per-rank op sequence number (every replica runs the same deterministic
    op order — the same assumption the real byte-stream PG makes)."""

    def __init__(self, world_size: int, timeout: float = 30.0) -> None:
        self.n = world_size
        self.timeout = timeout
        self._cv = threading.Condition()
        self._slots: Dict[int, Dict[int, Any]] = {}
        self._results: Dict[int, Any] = {}
        self._p2p: Dict[tuple, List[Any]] = {}
        # Per-rank collective sequence numbers live on the WORLD (not the
        # PG) so a freshly-joined replica's first collective matches the
        # survivors' next one in this world's epoch.
        self._seq: Dict[int, int] = {}

    def collective(self, rank: int, payload: Any, combine) -> Any:
        with self._cv:
            op_id = self._seq.get(rank, 0)
            self._seq[rank] = op_id + 1
            slot = self._slots.setdefault(op_id, {})
            slot[rank] = payload
            if len(slot) == self.n:
                self._results[op_id] = combine(slot)
                self._cv.notify_all()
            elif not self._cv.wait_for(
                lambda: op_id in self._results, timeout=self.timeout
            ):
                raise TimeoutError(f"loopback collective {op_id} timed out")
            return self._results[op_id]

    def send(self, src: int, dst: int, tag: int, arrays: List[np.ndarray]) -> None:
        with self._cv:
            self._p2p[(src, dst, tag)] = [np.array(a) for a in arrays]
            self._cv.notify_all()

    def recv(self, src: int, dst: int, tag: int) -> List[np.ndarray]:
        with self._cv:
            if not self._cv.wait_for(
                lambda: (src, dst, tag) in self._p2p, timeout=self.timeout
            ):
                raise TimeoutError(f"loopback recv ({src}->{dst}, {tag}) timed out")
            return self._p2p.pop((src, dst, tag))


class LoopbackPG(ProcessGroup):
    """ProcessGroup over a shared :class:`_LoopbackWorld` — real N-rank
    collective semantics, zero sockets. reduce_scatter splits along axis 0
    like the TCP backend; all reductions are bitwise identical across
    ranks (single accumulation order)."""

    def __init__(self, world: _LoopbackWorld, rank: int) -> None:
        super().__init__()
        self._world = world
        self._rank = rank
        self._op = 0
        self.op_counts: Dict[str, int] = {}

    def configure(self, store_addr, replica_id, rank, world_size) -> None:
        pass

    def abort(self) -> None:
        pass

    def shutdown(self) -> None:
        pass

    def errored(self) -> Optional[Exception]:
        return None

    def size(self) -> int:
        return self._world.n

    def rank(self) -> int:
        return self._rank

    def _next(self, name: str) -> int:
        self.op_counts[name] = self.op_counts.get(name, 0) + 1
        self._op += 1
        return self._op

    def allreduce(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM):
        self._next("allreduce")

        def combine(slot):
            out = []
            for i in range(len(arrays)):
                acc = np.array(slot[0][i], dtype=np.float64)
                for r in range(1, self._world.n):
                    acc = acc + slot[r][i]
                out.append(acc)
            return out

        result = self._world.collective(
            self._rank, [np.asarray(a) for a in arrays], combine
        )
        return _DummyWork([r.astype(np.asarray(a).dtype) for r, a in zip(result, arrays)])

    def reduce_scatter(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM):
        self._next("reduce_scatter")

        def combine(slot):
            out = []
            for i in range(len(arrays)):
                acc = np.array(slot[0][i], dtype=np.float64)
                for r in range(1, self._world.n):
                    acc = acc + slot[r][i]
                out.append(acc)
            return out

        reduced = self._world.collective(
            self._rank, [np.asarray(a) for a in arrays], combine
        )
        outs = []
        for full, a in zip(reduced, arrays):
            outs.append(
                np.split(full.astype(np.asarray(a).dtype), self._world.n, axis=0)[
                    self._rank
                ].copy()
            )
        return _DummyWork(outs)

    def allgather(self, arrays: Sequence[np.ndarray]):
        self._next("allgather")

        def combine(slot):
            return [
                [np.array(a) for a in slot[r]] for r in range(self._world.n)
            ]

        result = self._world.collective(
            self._rank, [np.asarray(a) for a in arrays], combine
        )
        return _DummyWork(result)

    def broadcast(self, arrays, root: int = 0):
        self._next("broadcast")

        def combine(slot):
            return [np.array(a) for a in slot[root]]

        return _DummyWork(
            self._world.collective(self._rank, list(arrays), combine)
        )

    def alltoall(self, arrays):
        # Rank r sends arrays[d] to rank d and receives every rank's
        # chunk r — the quantized-allreduce wire shape (TPUFT_ZERO_CODEC
        # rides parallel/collectives.allreduce_quantized over this).
        self._next("alltoall")

        def combine(slot):
            return [
                [np.array(a) for a in slot[r]] for r in range(self._world.n)
            ]

        matrix = self._world.collective(
            self._rank, [np.asarray(a) for a in arrays], combine
        )
        return _DummyWork([matrix[r][self._rank] for r in range(self._world.n)])

    def send(self, arrays, dst: int, tag: int = 0):
        self._next("send")
        self._world.send(self._rank, dst, tag, list(arrays))
        return _DummyWork(None)

    def recv(self, shapes_like, src: int, tag: int = 0):
        self._next("recv")
        return _DummyWork(self._world.recv(src, self._rank, tag))

    def barrier(self):
        return self.allreduce([np.zeros(1, np.float32)])


def _make_rank(world, rank, nparts, params, tx, num_shards=4, quorum_id=1,
               **manager_kwargs):
    pg = LoopbackPG(world, rank)
    manager = scripted_manager(
        num_participants=nparts, rank=rank, pg=pg, **manager_kwargs
    )
    manager._client._quorum.return_value = make_quorum(
        quorum_id=quorum_id,
        replica_rank=rank,
        replica_world_size=nparts,
        max_rank=rank,
        max_world_size=nparts,
    )
    opt = ZeroOptimizer(manager, tx, params, num_shards=num_shards)
    return manager, opt, pg


def _parallel(fns):
    """Runs one callable per replica on its own thread; re-raises the
    first failure."""
    results: List[Any] = [None] * len(fns)
    errors: List[BaseException] = []

    def runner(i):
        try:
            results[i] = fns[i]()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=runner, args=(i,)) for i in range(len(fns))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    if errors:
        raise errors[0]
    return results


@pytest.mark.parametrize("nparts", [2, 4])
def test_zero_multi_rank_bitwise_identical_params(nparts) -> None:
    """The construction invariant at real multi-rank wire semantics: every
    committed step ends with bitwise-identical params on every replica
    (each range computed once by its owner and allgathered), and each
    replica persists only ~1/N of the optimizer state."""
    tx = optax.adam(0.05)
    params = {"w": jnp.arange(24, dtype=jnp.float32).reshape(4, 6) / 11}
    world = _LoopbackWorld(nparts)
    ranks = [
        _make_rank(world, r, nparts, params, tx, num_shards=4)
        for r in range(nparts)
    ]

    def loss(p, b):
        return jnp.sum((p["w"] - b) ** 2)

    grad = jax.jit(jax.grad(loss))

    def run(r):
        manager, opt, _pg = ranks[r]

        def go():
            for step in range(3):
                manager.start_quorum()
                manager.wait_quorum()
                batch = jnp.full((4, 6), 0.1 * (step + r), jnp.float32)
                assert opt.step(grad(opt.params, batch))
            return np.asarray(opt.params["w"]), opt.opt_state

        return go

    results = _parallel([run(r) for r in range(nparts)])
    w0 = results[0][0]
    for w, _state in results[1:]:
        np.testing.assert_array_equal(w, w0)
    held_sets = [sorted(state.held) for _w, state in results]
    assert sorted(sum(held_sets, [])) == [0, 1, 2, 3]  # disjoint + complete
    sizes = [state.owned_bytes() for _w, state in results]
    if nparts == 4:
        assert all(s == sizes[0] for s in sizes)  # 1 shard each
    # Fast path engaged: the grad reduce rode pg.reduce_scatter.
    assert all(
        pg.op_counts.get("reduce_scatter", 0) >= 2 for _m, _o, pg in ranks
    )


def test_zero_identical_batches_match_lone_trajectory(monkeypatch) -> None:
    """World-size independence of the math: two replicas feeding identical
    batches commit the exact trajectory of a lone replica ((g+g)/2 == g in
    f32), bitwise."""
    tx = optax.sgd(0.2, momentum=0.9)
    params = {"w": jnp.arange(10, dtype=jnp.float32) / 3}

    def loss(p, b):
        return jnp.sum((p["w"] - b) ** 2)

    grad = jax.jit(jax.grad(loss))
    batches = [jnp.full((10,), 0.3 * i, jnp.float32) for i in range(3)]

    lone_manager = scripted_manager()
    lone = ZeroOptimizer(lone_manager, tx, params, num_shards=4)
    for b in batches:
        lone_manager.start_quorum()
        lone_manager.wait_quorum()
        assert lone.step(grad(lone.params, b))

    world = _LoopbackWorld(2)
    ranks = [_make_rank(world, r, 2, params, tx, num_shards=4) for r in range(2)]

    def run(r):
        manager, opt, _pg = ranks[r]

        def go():
            for b in batches:
                manager.start_quorum()
                manager.wait_quorum()
                assert opt.step(grad(opt.params, b))
            return np.asarray(opt.params["w"])

        return go

    results = _parallel([run(r) for r in range(2)])
    np.testing.assert_array_equal(results[0], results[1])
    np.testing.assert_array_equal(results[0], np.asarray(lone.params["w"]))


def test_zero_rebalance_shrink_then_grow_moves_only_needed_shards() -> None:
    """The elasticity protocol end to end on the loopback wire: shrink
    re-owns the dead replica's shards (reinit counter moves — masters
    re-pack from committed params), grow hands the joiner exactly its
    block over the PG (moved counter), and params stay bitwise identical
    throughout."""
    tx = optax.adam(0.05)
    params = {"w": jnp.arange(16, dtype=jnp.float32) / 5}

    def loss(p, b):
        return jnp.sum((p["w"] - b) ** 2)

    grad = jax.jit(jax.grad(loss))

    # Phase 1: two replicas, two steps.
    world = _LoopbackWorld(2)
    ranks = [_make_rank(world, r, 2, params, tx, num_shards=4) for r in range(2)]

    def run_phase(ranks, batches, quorum_id):
        def make(r):
            manager, opt, _pg = ranks[r]
            manager._client._quorum.return_value = make_quorum(
                quorum_id=quorum_id,
                replica_rank=r,
                replica_world_size=len(ranks),
                max_rank=r,
                max_world_size=len(ranks),
            )

            def go():
                for b in batches:
                    manager.start_quorum()
                    manager.wait_quorum()
                    assert opt.step(grad(opt.params, b))
                return np.asarray(opt.params["w"])

            return go

        return _parallel([make(r) for r in range(len(ranks))])

    batches1 = [jnp.full((16,), 0.2 * i, jnp.float32) for i in range(2)]
    run_phase(ranks, batches1, quorum_id=1)
    m0, opt0, _pg0 = ranks[0]
    assert sorted(opt0.opt_state.held) == [0, 1]

    # Phase 2: replica 1 dies. Survivor re-owns everything; shards 2, 3
    # were lost with their holder -> deterministic reconstruction.
    reinits_before = metrics.counter_total("tpuft_zero_shard_reinits_total")
    lone_world = _LoopbackWorld(1)
    opt0.manager._pg._world = lone_world  # type: ignore[attr-defined]
    opt0.manager._pg._rank = 0
    m0._client._quorum.return_value = make_quorum(
        quorum_id=2, replica_rank=0, replica_world_size=1,
        max_rank=0, max_world_size=1,
    )
    for b in [jnp.full((16,), 0.5, jnp.float32)]:
        m0.start_quorum()
        m0.wait_quorum()
        assert opt0.step(grad(opt0.params, b))
    assert sorted(opt0.opt_state.held) == [0, 1, 2, 3]
    reinits = metrics.counter_total("tpuft_zero_shard_reinits_total") - reinits_before
    assert reinits == 2  # exactly the dead replica's shards

    # Phase 3: a fresh replica joins (healed params via the checkpoint
    # path, shard states skipped); re-balance moves exactly its block.
    moved_before = metrics.counter_total("tpuft_zero_shards_moved_total")
    grow_world = _LoopbackWorld(2)
    opt0.manager._pg._world = grow_world
    joiner_manager, joiner, _jpg = _make_rank(
        grow_world, 1, 2, params, tx, num_shards=4, quorum_id=3
    )
    # Simulated heal: adopt the survivor's params + accounting, shard
    # payloads skipped (the skip_parts path) — then balance on the wire.
    donor_payload = opt0._state_dict()
    donor_payload = {
        "params": donor_payload["params"],
        "zero": donor_payload["zero"],
        "shards": {name: None for name in donor_payload["shards"]},
    }
    joiner._load_state_dict(donor_payload)
    joiner_manager.load_state_dict(m0.state_dict())
    m0._client._quorum.return_value = make_quorum(
        quorum_id=3, replica_rank=0, replica_world_size=2,
        max_rank=0, max_world_size=2,
    )

    def run2(r, ranks2):
        manager, opt = ranks2[r]

        def go():
            for i in range(2):
                manager.start_quorum()
                manager.wait_quorum()
                b = jnp.full((16,), 0.1 * (i + r), jnp.float32)
                assert opt.step(grad(opt.params, b))
            return np.asarray(opt.params["w"]), sorted(opt.opt_state.held)

        return go

    ranks2 = [(m0, opt0), (joiner_manager, joiner)]
    results = _parallel([run2(r, ranks2) for r in range(2)])
    np.testing.assert_array_equal(results[0][0], results[1][0])
    assert results[0][1] == [0, 1] and results[1][1] == [2, 3]
    moved = metrics.counter_total("tpuft_zero_shards_moved_total") - moved_before
    assert moved == 2  # ONLY the joiner's block crossed the wire


# ---------------------------------------------------------------------------
# shard-addressable heal (transport parts + manager filter)
# ---------------------------------------------------------------------------


def test_transport_parts_roundtrip_and_skip(tmp_path) -> None:
    manager = scripted_manager()
    opt = ZeroOptimizer(
        manager, optax.adam(0.1), {"w": jnp.arange(20, dtype=jnp.float32)},
        num_shards=4,
    )
    step_fn = opt.make_step_fn(lambda p, b: jnp.sum((p["w"] - b) ** 2))
    for i in range(2):
        step_fn(jnp.full((20,), float(i), jnp.float32))

    donor = HTTPTransport(timeout=10.0, num_chunks=2)
    joiner = HTTPTransport(timeout=10.0)
    try:
        state = {"user": {"zero": opt._state_dict()}, "tpuft": manager.state_dict()}
        donor.send_checkpoint([1], step=2, state_dict=state, timeout=10.0,
                              quorum_id=7)
        addr = donor.metadata()

        full = joiner.recv_checkpoint(0, addr, 2, 10.0, quorum_id=7)
        payload = full["user"]["zero"]["shards"][shard_part_name(0)]
        assert payload is not None and payload["master"] is not None

        skip = {shard_part_name(s) for s in range(4)}
        saved_before = metrics.counter_total("tpuft_zero_heal_bytes_saved_total")
        partial = joiner.recv_checkpoint(
            0, addr, 2, 10.0, quorum_id=7, skip_parts=skip
        )
        saved = (
            metrics.counter_total("tpuft_zero_heal_bytes_saved_total")
            - saved_before
        )
        assert saved > 0
        skipped = partial["user"]["zero"]["shards"][shard_part_name(0)]
        assert skipped is not None and skipped["master"] is None
        np.testing.assert_array_equal(
            np.asarray(partial["user"]["zero"]["params"]["w"]),
            np.asarray(full["user"]["zero"]["params"]["w"]),
        )

        # The joiner-side load treats skipped shards as absent and forces
        # a re-balance; params land exactly.
        manager2 = scripted_manager()
        healed = ZeroOptimizer(
            manager2, optax.adam(0.1), {"w": jnp.zeros(20, jnp.float32)},
            num_shards=4,
        )
        healed._load_state_dict(partial["user"]["zero"])
        assert healed.opt_state.held == {}
        assert healed.opt_state.balance_key is None
        np.testing.assert_array_equal(
            np.asarray(healed.params["w"]), np.asarray(opt.params["w"])
        )
    finally:
        donor.shutdown()
        joiner.shutdown()


def test_transport_part_chunks_fetch_measurably_less() -> None:
    """Acceptance pin: a skip-all-shards heal fetches measurably fewer
    bytes than the full checkpoint (the ~1/N heal-payload claim at its
    strongest — adam carries 2x moments + the f32 masters)."""
    manager = scripted_manager()
    opt = ZeroOptimizer(
        manager, optax.adam(0.1),
        {"w": jnp.arange(4096, dtype=jnp.float32)}, num_shards=4,
    )
    step_fn = opt.make_step_fn(lambda p, b: jnp.sum((p["w"] - b) ** 2))
    step_fn(jnp.zeros(4096, jnp.float32))
    donor = HTTPTransport(timeout=10.0)
    try:
        state = {"user": {"zero": opt._state_dict()}, "tpuft": manager.state_dict()}
        donor.send_checkpoint([1], step=1, state_dict=state, timeout=10.0)
        staged = donor._staged
        total = sum(c.total_size for c in staged.chunks)
        part_bytes = sum(info["nbytes"] for info in staged.parts.values())
        assert len(staged.parts) == 4
        # Shard parts (masters + adam moments, all f32) dominate: the
        # skip-all heal moves less than half the full payload.
        assert part_bytes > total / 2
    finally:
        donor.shutdown()


def test_manager_passes_skip_parts_to_transport() -> None:
    manager, client, pg, transport = make_manager()
    manager.register_heal_parts_filter(lambda: {shard_part_name(0)})
    manager.register_heal_parts_filter(lambda: {shard_part_name(1)})
    manager.register_heal_parts_filter(lambda: (_ for _ in ()).throw(RuntimeError))
    assert manager._heal_skip_parts() == {shard_part_name(0), shard_part_name(1)}

    client._quorum.return_value = make_quorum(
        quorum_id=3, replica_rank=1, replica_world_size=2, heal=True,
        max_step=5, max_world_size=1, max_rank=None,
        recover_src_manager_address="fake:1", recover_src_replica_rank=0,
    )
    pg.errored.return_value = None
    transport.recv_checkpoint.return_value = {
        "user": {"model": {"w": np.ones(2)}},
        "tpuft": {"step": 5, "batches_committed": 5},
    }
    from unittest.mock import patch

    with patch("torchft_tpu.manager.ManagerClient") as client_cls:
        client_cls.return_value._checkpoint_metadata.return_value = "http://d:1"
        manager.start_quorum()
        manager.wait_quorum()
    assert transport.recv_checkpoint.call_count == 1
    kwargs = transport.recv_checkpoint.call_args.kwargs
    assert kwargs["skip_parts"] == {shard_part_name(0), shard_part_name(1)}


# ---------------------------------------------------------------------------
# plumbing satellites
# ---------------------------------------------------------------------------


def test_align_opt_state_passes_sharded_leaves_through() -> None:
    """_align_opt_state must treat opaque sharded containers (ZeroState)
    and non-array leaves as pass-through, aligning only jax.Array moments;
    single-device states come back unchanged."""
    params = {"w": jnp.ones((4,), jnp.float32)}
    manager = scripted_manager()
    opt = ZeroOptimizer(manager, optax.adam(0.1), params, num_shards=2)
    state = opt.opt_state
    aligned = _align_opt_state(state, params)
    assert aligned is state  # opaque container untouched

    tx = optax.adam(0.1)
    plain = tx.init(params)
    aligned = _align_opt_state(plain, params)
    chex_leaves = jax.tree_util.tree_leaves(aligned)
    assert len(chex_leaves) == len(jax.tree_util.tree_leaves(plain))


def test_zero_coexists_with_local_sgd_registration() -> None:
    """DiLoCo/LocalSGD registration composes: distinct manager state-dict
    keys, both serialize into one checkpoint."""
    from torchft_tpu.local_sgd import LocalSGD

    manager = scripted_manager()
    zero = ZeroOptimizer(
        manager, optax.sgd(0.1), {"w": jnp.ones(4, jnp.float32)},
        num_shards=2, register_key="zero_outer",
    )
    local = LocalSGD(
        manager, optax.sgd(0.1), {"v": jnp.ones(3, jnp.float32)}, sync_every=2,
    )
    state = manager._manager_state_dict()
    assert {"zero_outer", "local_sgd"} <= set(state["user"])
    assert shard_part_name(0) in state["user"]["zero_outer"]["shards"]


def test_zero_num_shards_mismatch_rejected() -> None:
    manager = scripted_manager()
    opt = ZeroOptimizer(
        manager, optax.sgd(0.1), {"w": jnp.ones(4, jnp.float32)}, num_shards=2
    )
    payload = opt._state_dict()
    payload["zero"]["num_shards"] = 3
    with pytest.raises(ValueError, match="num_shards"):
        opt._load_state_dict(payload)


def test_zero_quantize_flag_warns_and_runs_f32(monkeypatch, caplog) -> None:
    manager = scripted_manager()
    manager.is_lone_replica = lambda: False
    opt = ZeroOptimizer(
        manager, optax.sgd(0.1), {"w": jnp.ones(4, jnp.float32)}, num_shards=2
    )
    import logging

    import torchft_tpu.zero as zero_mod

    monkeypatch.setattr(zero_mod, "_WARNED_QUANTIZE", [False])
    with caplog.at_level(logging.WARNING, logger="torchft_tpu.zero"):
        step_fn = opt.make_step_fn(
            lambda p, b: jnp.sum(p["w"] * b), should_quantize=True
        )
        manager.start_quorum()
        step_fn(jnp.ones(4, jnp.float32))
    assert any("should_quantize" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# quantized shard wire (TPUFT_ZERO_CODEC)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("codec", ["int8", "fp8"])
def test_zero_codec_multi_rank_bitwise_identical_params(monkeypatch, codec) -> None:
    """THE acceptance drill: with the shard wire quantized, every
    committed step still ends with bitwise-identical params on every
    replica — each master range is encoded once by its owner and EVERY
    replica (owner included) dequantizes the same allgather bytes — and
    the grad reduce actually rode the quantized alltoall pipeline."""
    monkeypatch.setenv("TPUFT_ZERO_CODEC", codec)
    tx = optax.adam(0.05)
    params = {"w": jnp.arange(4096, dtype=jnp.float32).reshape(64, 64) / 977}

    def loss(p, b):
        return jnp.sum((p["w"] - b) ** 2)

    grad = jax.jit(jax.grad(loss))
    world = _LoopbackWorld(2)
    ranks = [_make_rank(world, r, 2, params, tx, num_shards=4) for r in range(2)]

    def run(r):
        manager, opt, _pg = ranks[r]

        def go():
            for step in range(3):
                manager.start_quorum()
                manager.wait_quorum()
                batch = jnp.full((64, 64), 0.1 * (step + r), jnp.float32)
                assert opt.step(grad(opt.params, batch))
            return np.asarray(opt.params["w"])

        return go

    results = _parallel([run(r) for r in range(2)])
    np.testing.assert_array_equal(results[0], results[1])
    # The quantized wire was actually used: alltoall (the quantized
    # allreduce's exchange) ran, the f32 reduce_scatter fast path did not.
    for _m, _o, pg in ranks:
        assert pg.op_counts.get("alltoall", 0) >= 3
        assert pg.op_counts.get("reduce_scatter", 0) == 0
    # And the byte accounting moved: encoded bytes a fraction of raw.
    pre = metrics.counter_total(
        "tpuft_codec_bytes_pre_total", wire="zero", codec=codec
    )
    post = metrics.counter_total(
        "tpuft_codec_bytes_post_total", wire="zero", codec=codec
    )
    assert pre > 0 and post > 0 and post < pre * 0.35


def test_zero_codec_pipelined_ordering_matches_strict(monkeypatch) -> None:
    """Bitwise identity survives the commit orderings under the quantized
    wire: a depth-2 pipelined 2-rank run and a strict-ordered 2-rank run
    commit the IDENTICAL param trajectory (same batches, same codec)."""
    monkeypatch.setenv("TPUFT_ZERO_CODEC", "int8")
    tx = optax.sgd(0.2, momentum=0.9)
    params = {"w": jnp.arange(2048, dtype=jnp.float32) / 311}

    def loss(p, b):
        return jnp.sum((p["w"] - b) ** 2)

    batches = [jnp.full((2048,), 0.25 * i, jnp.float32) for i in range(4)]

    def run_world(mode):
        if mode == "strict":
            monkeypatch.setenv("TPUFT_STRICT_COMMIT", "1")
            mk = {}
        else:
            monkeypatch.delenv("TPUFT_STRICT_COMMIT", raising=False)
            mk = {"commit_pipeline_depth": 2}
        world = _LoopbackWorld(2)
        ranks = [
            _make_rank(world, r, 2, params, tx, num_shards=4, **mk)
            for r in range(2)
        ]

        def run(r):
            manager, opt, _pg = ranks[r]
            step_fn = opt.make_step_fn(loss)

            def go():
                for b in batches:
                    step_fn(b)
                # None in strict mode (no window), True once drained.
                assert opt.flush_pipeline() in (None, True)
                return np.asarray(opt.params["w"])

            return go

        results = _parallel([run(r) for r in range(2)])
        np.testing.assert_array_equal(results[0], results[1])
        return results[0]

    w_strict = run_world("strict")
    w_pipe = run_world("pipelined")
    np.testing.assert_array_equal(w_strict, w_pipe)


def test_zero_codec_kill_rejoin_rebalance_bitwise(monkeypatch) -> None:
    """Kill/rejoin under the quantized wire: the survivor re-owns the dead
    holder's shards, a fresh joiner heals params (skip_parts) and
    re-balances its block from the survivor — and every subsequent
    committed step is bitwise identical across both replicas, because
    params always come from the shared encoded allgather payload."""
    monkeypatch.setenv("TPUFT_ZERO_CODEC", "int8")
    tx = optax.adam(0.05)
    params = {"w": jnp.arange(4096, dtype=jnp.float32) / 631}

    def loss(p, b):
        return jnp.sum((p["w"] - b) ** 2)

    grad = jax.jit(jax.grad(loss))
    world = _LoopbackWorld(2)
    ranks = [_make_rank(world, r, 2, params, tx, num_shards=4) for r in range(2)]

    def run_phase(pairs, batches, quorum_id, world_size):
        def make(i):
            manager, opt = pairs[i]
            manager._client._quorum.return_value = make_quorum(
                quorum_id=quorum_id,
                replica_rank=i,
                replica_world_size=world_size,
                max_rank=i,
                max_world_size=world_size,
            )

            def go():
                for b in batches:
                    manager.start_quorum()
                    manager.wait_quorum()
                    assert opt.step(grad(opt.params, b))
                return np.asarray(opt.params["w"])

            return go

        return _parallel([make(i) for i in range(len(pairs))])

    batches1 = [jnp.full((4096,), 0.2 * i, jnp.float32) for i in range(2)]
    pairs = [(m, o) for m, o, _pg in ranks]
    results = run_phase(pairs, batches1, quorum_id=1, world_size=2)
    np.testing.assert_array_equal(results[0], results[1])

    # Replica 1 dies; the survivor re-owns everything and keeps stepping.
    m0, opt0 = pairs[0]
    lone_world = _LoopbackWorld(1)
    m0._pg._world = lone_world  # type: ignore[attr-defined]
    m0._pg._rank = 0
    m0._client._quorum.return_value = make_quorum(
        quorum_id=2, replica_rank=0, replica_world_size=1,
        max_rank=0, max_world_size=1,
    )
    m0.start_quorum()
    m0.wait_quorum()
    assert opt0.step(grad(opt0.params, jnp.full((4096,), 0.5, jnp.float32)))
    assert sorted(opt0.opt_state.held) == [0, 1, 2, 3]

    # A fresh joiner rejoins: params via the (skip-parts) heal path,
    # shard states via re-balance — then two more lockstep steps.
    grow_world = _LoopbackWorld(2)
    m0._pg._world = grow_world
    joiner_manager, joiner, _jpg = _make_rank(
        grow_world, 1, 2, params, tx, num_shards=4, quorum_id=3
    )
    donor_payload = opt0._state_dict()
    donor_payload = {
        "params": donor_payload["params"],
        "zero": donor_payload["zero"],
        "shards": {name: None for name in donor_payload["shards"]},
    }
    joiner._load_state_dict(donor_payload)
    joiner_manager.load_state_dict(m0.state_dict())
    batches2 = [jnp.full((4096,), 0.15 * i, jnp.float32) for i in range(2)]
    results2 = run_phase(
        [(m0, opt0), (joiner_manager, joiner)], batches2,
        quorum_id=3, world_size=2,
    )
    np.testing.assert_array_equal(results2[0], results2[1])
