"""ZeRO plane integration drills on the real coordination plane
(threads-as-replicas, native lighthouse — skips cleanly when the
toolchain is absent; the loopback-wire equivalents in test_zero.py run
everywhere).

The acceptance drill: kill + rejoin with ZeRO enabled, in BOTH strict
and pipelined commit orderings, asserting (a) bitwise-identical params
across replica groups after every committed step, (b) shard re-balance
on the quorum shrink AND the re-grow, and (c) the joiner's heal moved
measurably fewer bytes than a full checkpoint (the shard parts were
skipped and re-balanced over the PG instead)."""

import jax
import numpy as np
import pytest

from torchft_tpu.coordination import LighthouseServer

from ft_harness import (
    EventInjector,
    Runner,
    ft_counter_delta,
    ft_counter_snapshot,
    run_replica_groups,
    zero_ddp_train_loop,
)


@pytest.fixture()
def lighthouse():
    server = LighthouseServer(
        min_replicas=1,
        join_timeout_ms=10000,
        heartbeat_timeout_ms=1000,
        quorum_tick_ms=20,
    )
    yield server
    server.shutdown()


def assert_pytree_equal(a, b) -> None:
    leaves_a, tree_a = jax.tree_util.tree_flatten(a)
    leaves_b, tree_b = jax.tree_util.tree_flatten(b)
    assert tree_a == tree_b
    for la, lb in zip(leaves_a, leaves_b):
        assert np.asarray(la).tobytes() == np.asarray(lb).tobytes()


def _assert_zero_converged(results, num_steps: int) -> None:
    reference = results[0][0]["state_dict"]["params"]
    for group_result in results:
        rank_result = group_result[0]
        assert rank_result["manager_state"]["step"] == num_steps
        assert_pytree_equal(rank_result["state_dict"]["params"], reference)
    # Disjoint, complete shard ownership across the final cohort.
    held = [g[0]["state_dict"]["held_shards"] for g in results]
    flat = sorted(sum(held, []))
    assert flat == sorted(set(flat)), f"overlapping shard ownership: {held}"


def test_zero_two_groups_healthy_shards_split(lighthouse) -> None:
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=zero_ddp_train_loop,
            num_steps=3,
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners)
    _assert_zero_converged(results, 3)
    held = [g[0]["state_dict"]["held_shards"] for g in results]
    # Both groups own a non-empty block: the state is actually sharded.
    assert all(h for h in held)
    assert sorted(sum(held, [])) == [0, 1, 2, 3]
    # History bitwise identical at every committed step, not just the end.
    h0, h1 = results[0][0]["history"], results[1][0]["history"]
    for step in set(h0) & set(h1):
        assert_pytree_equal(h0[step], h1[step])


@pytest.mark.parametrize("pipelined", [False, True], ids=["strict", "pipelined"])
def test_zero_kill_rejoin_rebalances_and_heals_shard_wise(
    lighthouse, pipelined, monkeypatch
) -> None:
    """The acceptance drill (see module docstring)."""
    if not pipelined:
        # Pin the strict ordering explicitly (vote after observed
        # completion); the pipelined leg runs commit_pipeline_depth=1.
        monkeypatch.setenv("TPUFT_STRICT_COMMIT", "1")
    before = ft_counter_snapshot("zero_0")
    saved_before = ft_counter_snapshot()["zero_heal_bytes_saved"]
    injector = EventInjector().fail_at(group=1, step=2)
    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=zero_ddp_train_loop,
            num_steps=6,
            injector=injector,
            train_loop_args={"pipelined": pipelined},
        )
        for i in range(2)
    ]
    results = run_replica_groups(runners, timeout=240)
    assert injector.count == 1
    _assert_zero_converged(results, 6)

    delta = ft_counter_delta(before, ft_counter_snapshot("zero_0"))
    # (b) the survivor re-balanced at least twice: once when the peer
    # died (taking over its shards — reinits or moves), once when it
    # rejoined (handing its block back — moves).
    assert delta["zero_rebalances"] >= 2, delta
    assert delta["zero_shards_moved"] + delta["zero_shard_reinits"] >= 1, delta
    # (c) the joiner's heal skipped the shard parts: bytes saved over a
    # full checkpoint, pinned by the transport's counter.
    saved = ft_counter_snapshot()["zero_heal_bytes_saved"] - saved_before
    assert saved > 0, "joiner heal did not skip any shard bytes"


def test_zero_upscale_rebalances_without_heal_loss(lighthouse) -> None:
    """Grow-only elasticity: a third group joining mid-run triggers a
    re-balance (ownership moves, nothing reconstructs) and the fleet
    converges bitwise."""
    import time
    from concurrent.futures import ThreadPoolExecutor

    runners = [
        Runner(
            replica_group=i,
            lighthouse_addr=lighthouse.address(),
            train_loop=zero_ddp_train_loop,
            num_steps=6,
        )
        for i in range(3)
    ]

    with ThreadPoolExecutor(max_workers=3, thread_name_prefix="group") as pool:
        early = [pool.submit(runners[i].run_replica) for i in range(2)]
        time.sleep(1.5)  # let the first two commit a few steps
        late = pool.submit(runners[2].run_replica)
        results = [f.result(timeout=240) for f in (*early, late)]
    _assert_zero_converged(results, 6)
