"""torchft_tpu — TPU-native per-step fault tolerance for JAX training.

A ground-up rebuild of the capabilities of pytorch/torchft for TPU:
a native (C++) Lighthouse computes dynamic quorums of replica groups via
heartbeats; a native per-replica-group ManagerServer arbitrates quorum,
recovery assignments, and commit votes; the Python :class:`Manager` embeds in
the train loop, resizes the replica axis on membership changes, and live-heals
joining replicas by streaming parameter pytrees from a healthy peer.
"""

__version__ = "0.1.0"
