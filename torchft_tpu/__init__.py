"""torchft_tpu — TPU-native per-step fault tolerance for JAX training.

A ground-up rebuild of the capabilities of pytorch/torchft for TPU:
a native (C++) Lighthouse computes dynamic quorums of replica groups via
heartbeats; a native per-replica-group ManagerServer arbitrates quorum,
recovery assignments, and commit votes; the Python :class:`Manager` embeds in
the train loop, resizes the replica axis on membership changes, and live-heals
joining replicas by streaming parameter pytrees from a healthy peer.

Public surface (parity with the reference's ``torchft/__init__.py``)::

    from torchft_tpu import (
        Manager, Optimizer, DistributedSampler,
        ProcessGroupTCP, ProcessGroupBaby, ProcessGroupDummy,
    )

Heavier pieces import from their modules: ``torchft_tpu.local_sgd`` (LocalSGD,
DiLoCo), ``torchft_tpu.zero`` (ZeroOptimizer — cross-replica optimizer-state
sharding, docs/zero.md), ``torchft_tpu.serving`` (the committed-weights
serving plane — WeightPublisher/CachingRelay/WeightSubscriber,
docs/serving.md), ``torchft_tpu.wire_codec`` (the quantized wire plane —
codec-tagged heal/serving chunks and the fp8/int8/int4 ZeRO wire,
``TPUFT_HEAL_CODEC``/``TPUFT_SERVING_CODEC``/``TPUFT_ZERO_CODEC``,
default fp32 bit-for-bit), ``torchft_tpu.tracing`` (the fleet trace plane —
per-process step-event journals merged by scripts/fleet_trace.py,
docs/observability.md), ``torchft_tpu.parallel.mesh`` (FTMesh/HSDP),
``torchft_tpu.models``, ``torchft_tpu.checkpointing``, ``torchft_tpu.ops``.
"""

# Honor $TPUFT_LOCK_CHECK for ANY entry point before lock-creating modules
# import: the runtime lock-order detector only instruments locks created
# AFTER enable() (docs/static_analysis.md). Off by default outside the
# test harness.
from torchft_tpu.utils import lockcheck as _lockcheck

_lockcheck.maybe_enable_from_env(default="0")

from torchft_tpu.data import DevicePrefetcher, DistributedSampler  # noqa: E402
from torchft_tpu.ddp import DistributedDataParallel, ft_allreduce_gradients
from torchft_tpu.manager import Manager, WorldSizeMode
from torchft_tpu.optim import (
    Optimizer,
    OptimizerWrapper,
    make_jit_fused_step,
    make_microbatch_grad,
)
from torchft_tpu.parallel.baby import ProcessGroupBaby
from torchft_tpu.parallel.native_pg import ProcessGroupNative
from torchft_tpu.parallel.process_group import (
    ProcessGroup,
    ProcessGroupDummy,
    ProcessGroupTCP,
    ReduceOp,
)

__version__ = "0.1.0"

__all__ = [
    "Manager",
    "WorldSizeMode",
    "Optimizer",
    "OptimizerWrapper",
    "DistributedDataParallel",
    "ft_allreduce_gradients",
    "DistributedSampler",
    "DevicePrefetcher",
    "make_jit_fused_step",
    "make_microbatch_grad",
    "ProcessGroup",
    "ProcessGroupTCP",
    "ProcessGroupNative",
    "ProcessGroupBaby",
    "ProcessGroupDummy",
    "ReduceOp",
]
