"""ctypes loader for the native coordination plane (libtpuft.so).

Role-equivalent of the reference's pyo3 module ``torchft._torchft``
(/root/reference/src/lib.rs): embeds the C++ Lighthouse and ManagerServer in
Python processes. Only server lifecycles cross the C ABI; clients speak the
framed RPC protocol directly from Python (see torchft_tpu/coordination.py).
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading
from pathlib import Path
from typing import Tuple

_REPO_ROOT = Path(__file__).resolve().parent.parent
_NATIVE_DIR = _REPO_ROOT / "native"
_BUILD_DIR = _NATIVE_DIR / "build"

_lib = None
_lib_lock = threading.Lock()
_has_sim_hooks = False


class NativeToolchainMissing(RuntimeError):
    """libtpuft.so is not prebuilt and the build toolchain (cmake/ninja) is
    absent, so the native plane cannot come up. tests/conftest.py converts
    this into a pytest skip ("native toolchain absent") instead of the
    opaque FileNotFoundError subprocess used to raise; ``doctor`` reports
    the same state in its toolchain check."""


def toolchain_state() -> Tuple[bool, str]:
    """(available, detail): whether the native plane can be loaded or built.

    Available means a prebuilt libtpuft.so exists at any candidate path, or
    both cmake and ninja are on PATH to build one."""
    for path in _candidate_paths():
        if path.exists():
            return True, f"prebuilt libtpuft.so at {path}"
    missing = [tool for tool in ("cmake", "ninja") if shutil.which(tool) is None]
    if missing:
        return False, (
            f"no prebuilt libtpuft.so and {'/'.join(missing)} not on PATH "
            "(native plane unbuildable)"
        )
    return True, "no prebuilt libtpuft.so; cmake+ninja available to build"


def has_sim_hooks() -> bool:
    """True when the loaded libtpuft.so exports the pure-function quorum
    test hooks (tpuft_quorum_compute / tpuft_compute_quorum_results)."""
    load()
    return _has_sim_hooks


def _candidate_paths() -> list[Path]:
    paths = []
    env = os.environ.get("TPUFT_NATIVE_LIB")
    if env:
        paths.append(Path(env))
    paths.append(Path(__file__).resolve().parent / "libtpuft.so")
    paths.append(_BUILD_DIR / "libtpuft.so")
    return paths


def ensure_built() -> Path:
    """Returns the path to libtpuft.so, building it if necessary.

    Raises :class:`NativeToolchainMissing` (not FileNotFoundError from a
    doomed subprocess) when there is nothing to load and no toolchain to
    build with — callers and the test suite key on that type."""
    for path in _candidate_paths():
        if path.exists():
            return path
    available, detail = toolchain_state()
    if not available:
        raise NativeToolchainMissing(detail)
    # Build from source (dev / CI path).
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    if not (_BUILD_DIR / "build.ninja").exists():
        subprocess.run(
            ["cmake", "-B", str(_BUILD_DIR), "-G", "Ninja", str(_NATIVE_DIR)],
            check=True,
            capture_output=True,
        )
    subprocess.run(
        ["ninja", "-C", str(_BUILD_DIR), "tpuft"], check=True, capture_output=True
    )
    lib_path = _BUILD_DIR / "libtpuft.so"
    if not lib_path.exists():
        raise RuntimeError(f"native build succeeded but {lib_path} is missing")
    return lib_path


def load() -> ctypes.CDLL:
    """Loads (building if needed) and configures the native library."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(str(ensure_built()))

        lib.tpuft_last_error.restype = ctypes.c_char_p

        lib.tpuft_lighthouse_new.restype = ctypes.c_void_p
        lib.tpuft_lighthouse_new.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_uint64,
        ]
        lib.tpuft_lighthouse_address.restype = ctypes.c_int
        lib.tpuft_lighthouse_address.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.tpuft_lighthouse_shutdown.argtypes = [ctypes.c_void_p]
        lib.tpuft_lighthouse_free.argtypes = [ctypes.c_void_p]

        lib.tpuft_manager_new.restype = ctypes.c_void_p
        lib.tpuft_manager_new.argtypes = [
            ctypes.c_char_p,  # replica_id
            ctypes.c_char_p,  # lighthouse_addr
            ctypes.c_char_p,  # hostname
            ctypes.c_char_p,  # bind
            ctypes.c_char_p,  # store_addr
            ctypes.c_uint64,  # world_size
            ctypes.c_uint64,  # heartbeat_interval_ms
            ctypes.c_uint64,  # connect_timeout_ms
            ctypes.c_int64,  # quorum_retries
            ctypes.c_int,  # exit_on_kill
        ]
        lib.tpuft_manager_address.restype = ctypes.c_int
        lib.tpuft_manager_address.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.tpuft_manager_shutdown.argtypes = [ctypes.c_void_p]
        lib.tpuft_manager_free.argtypes = [ctypes.c_void_p]

        lib.tpuft_store_new.restype = ctypes.c_void_p
        lib.tpuft_store_new.argtypes = [ctypes.c_char_p]
        lib.tpuft_store_address.restype = ctypes.c_int
        lib.tpuft_store_address.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.tpuft_store_shutdown.argtypes = [ctypes.c_void_p]
        lib.tpuft_store_free.argtypes = [ctypes.c_void_p]

        # Pure-function test hooks (serialized protos in/out). Guarded: a
        # stale libtpuft.so from before these symbols existed must not take
        # down the production plane (servers/collectives) — only the sim
        # functions, which check `has_sim_hooks` and raise a clear error.
        try:
            lib.tpuft_quorum_compute.restype = ctypes.c_int
            lib.tpuft_quorum_compute.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int,
                ctypes.c_char_p,
                ctypes.c_int,
            ]
            lib.tpuft_compute_quorum_results.restype = ctypes.c_int
            lib.tpuft_compute_quorum_results.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int64,
                ctypes.c_char_p,
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_char_p,
                ctypes.c_int,
            ]
            has_sim_hooks = True
        except AttributeError:
            has_sim_hooks = False

        global _has_sim_hooks
        _has_sim_hooks = has_sim_hooks
        _lib = lib
        return _lib


def last_error() -> str:
    lib = load()
    err = lib.tpuft_last_error()
    return err.decode() if err else "unknown native error"
