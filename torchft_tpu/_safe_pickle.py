"""Restricted unpickling for bytes received from network peers.

The checkpoint/recovery wire formats carry pickled pytree structure
(treedefs, metadata dataclasses, non-array leaves). Plain ``pickle.loads``
on attacker-controlled bytes is remote code execution, and the transport
servers bind ``[::]`` — the reference accepts this under a trusted-network
assumption (torch.load ``weights_only=False``,
/root/reference/torchft/checkpointing/http_transport.py:155-162). We keep
the same *trust model* (run the coordination and transport planes on a
private, trusted network — see docs/security.md) but reduce the blast
radius: network-received pickles are decoded with an allowlisting
Unpickler that only resolves classes from ML-ecosystem modules, which
blocks the classic ``os.system``/``subprocess``/``getattr`` reduce gadgets.

State dicts whose leaves are instances of other modules' classes can opt
out with ``TPUFT_ALLOW_UNSAFE_PICKLE=1`` (only on trusted networks) or by
extending the allowlist via :func:`allow_module`.
"""

from __future__ import annotations

import io
import os
import pickle
from typing import Any, Set

__all__ = ["safe_loads", "allow_module", "RestrictedUnpicklingError"]

UNSAFE_ENV = "TPUFT_ALLOW_UNSAFE_PICKLE"

# Top-level modules whose classes may be resolved during unpickling. These
# cover everything tpuft itself puts on the wire (numpy arrays + dtypes,
# jax treedefs, flax/optax state containers, our meta dataclasses) plus the
# stdlib containers they serialize through.
_ALLOWED_ROOTS: Set[str] = {
    "numpy",
    "jax",
    "jaxlib",
    "ml_dtypes",
    "flax",
    "optax",
    "chex",
    "torchft_tpu",
    "collections",
    "functools",
}

# Safe builtins: literal constructors only. Notably absent: getattr, eval,
# exec, compile, open, __import__ — the standard pickle RCE gadgets.
_SAFE_BUILTINS: Set[str] = {
    "complex",
    "bytearray",
    "set",
    "frozenset",
    "slice",
    "range",
    "tuple",
    "list",
    "dict",
    "bool",
    "int",
    "float",
    "str",
    "bytes",
    "object",
}


# Non-class globals (functions/registries) that legitimate payloads resolve
# during unpickling. Exact (module, name) pairs only — REDUCE can call any
# resolved callable, so arbitrary functions under allowed roots must NOT
# resolve (e.g. torchft_tpu's own allow_module would be a one-call
# allowlist bypass; process-spawning helpers would be gadgets).
_ALLOWED_FUNCTIONS: Set[tuple] = {
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "_reconstruct"),  # pre-2.0 pickles
    ("numpy.core.multiarray", "scalar"),
    ("jax._src.array", "_reconstruct_array"),
    ("jax._src.tree_util", "default_registry"),
}

# Modules that must never resolve even though their root is allowed: this
# module itself (its allow_module is an allowlist-widening gadget).
_DENIED_MODULES = ("torchft_tpu._safe_pickle",)


class RestrictedUnpicklingError(pickle.UnpicklingError):
    """A network pickle referenced a global outside the allowlist."""


def allow_module(root: str) -> None:
    """Extends the unpickling allowlist with a top-level module name (for
    user state dicts carrying custom leaf types). Only classes under the
    module resolve; module-level functions stay blocked."""
    _ALLOWED_ROOTS.add(root.split(".", 1)[0])


def allow_function(module: str, name: str) -> None:
    """Allows one exact module-level function to resolve (for user leaf
    types whose ``__reduce__`` goes through a reconstruction function)."""
    _ALLOWED_FUNCTIONS.add((module, name))


class _RestrictedUnpickler(pickle.Unpickler):
    def __init__(self, file: Any) -> None:
        super().__init__(file)
        # Snapshot at construction: a payload that somehow widens the
        # process-global allowlists mid-load gains nothing for this (or any
        # concurrent) load.
        self._roots = frozenset(_ALLOWED_ROOTS)
        self._functions = frozenset(_ALLOWED_FUNCTIONS)

    def find_class(self, module: str, name: str) -> Any:
        if module == "builtins":
            if name in _SAFE_BUILTINS:
                return super().find_class(module, name)
            raise self._refuse(module, name, "builtin outside the safe set")
        if module.split(".", 1)[0] not in self._roots:
            raise self._refuse(module, name, "module root not allowlisted")
        if module in _DENIED_MODULES:
            raise self._refuse(module, name, "explicitly denied module")
        obj = super().find_class(module, name)
        if isinstance(obj, type):
            return obj
        if (module, name) in self._functions:
            return obj
        raise self._refuse(
            module, name, "non-class global (REDUCE gadget surface)"
        )

    @staticmethod
    def _refuse(module: str, name: str, why: str) -> RestrictedUnpicklingError:
        return RestrictedUnpicklingError(
            f"refusing to unpickle {module}.{name} from the network ({why}). "
            f"If this type is part of your state dict, call torchft_tpu."
            f"_safe_pickle.allow_module/allow_function, or set {UNSAFE_ENV}=1 "
            f"on a trusted network (see docs/security.md)."
        )


def safe_loads(data: bytes) -> Any:
    """``pickle.loads`` for network-received bytes, allowlist-restricted
    unless ``TPUFT_ALLOW_UNSAFE_PICKLE=1``."""
    if os.environ.get(UNSAFE_ENV) == "1":
        return pickle.loads(data)
    return _RestrictedUnpickler(io.BytesIO(data)).load()
