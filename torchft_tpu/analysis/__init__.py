"""tpuft_check: semantic invariant plane for the Python coordination code.

The native plane has TSAN; this package is the Python side's mechanical
check — eleven rules (R1-R11) that turn CLAUDE.md's concurrency/
architecture invariants into enforced properties: R1-R8 are lexical AST
rules, R9-R11 ride the intraprocedural taint pass in
:mod:`torchft_tpu.analysis.dataflow` (verify-before-adopt, era-fence,
stale-suppression). See docs/static_analysis.md for the rule table and
suppression syntax. Runs in tier-1 (tests/test_static_analysis.py) and
as a CLI::

    python -m torchft_tpu.analysis            # scan the package, exit != 0
                                              # on unbaselined findings
    python -m torchft_tpu.analysis --list-rules
    python -m torchft_tpu.analysis path/...   # scan explicit files/dirs
    python -m torchft_tpu.analysis --explore  # interleaving explorer (below)

Dynamic counterparts: :mod:`torchft_tpu.utils.lockcheck`
(``TPUFT_LOCK_CHECK=1``; default-on in the ft_harness drills) and the
deterministic interleaving explorer :mod:`torchft_tpu.analysis.explore`
(``--explore``): the real commit/quorum protocol under the controlled
scheduler in :mod:`torchft_tpu.utils.schedules`, every explored schedule
asserting the invariants the static rules can only pin lexically, with a
replay token printed for any violating interleaving.
"""

from torchft_tpu.analysis.core import (
    Finding,
    Module,
    apply_baseline,
    load_baseline,
    load_module,
    run_analysis,
    save_baseline,
)
from torchft_tpu.analysis.rules import ALL_RULES, RULES_BY_ID, Rule

__all__ = [
    "Finding",
    "Module",
    "Rule",
    "ALL_RULES",
    "RULES_BY_ID",
    "run_analysis",
    "load_module",
    "load_baseline",
    "save_baseline",
    "apply_baseline",
]
