"""tpuft_check: static invariant analyzer for the Python coordination plane.

The native plane has TSAN; this package is the Python side's mechanical
check — six AST rules that turn CLAUDE.md's concurrency/architecture
invariants into enforced properties (see docs/static_analysis.md for the
rule table and suppression syntax). Runs in tier-1
(tests/test_static_analysis.py) and as a CLI::

    python -m torchft_tpu.analysis            # scan the package, exit != 0
                                              # on unbaselined findings
    python -m torchft_tpu.analysis --list-rules
    python -m torchft_tpu.analysis path/...   # scan explicit files/dirs

Runtime counterpart: :mod:`torchft_tpu.utils.lockcheck`
(``TPUFT_LOCK_CHECK=1``; default-on in the ft_harness drills).
"""

from torchft_tpu.analysis.core import (
    Finding,
    Module,
    apply_baseline,
    load_baseline,
    load_module,
    run_analysis,
    save_baseline,
)
from torchft_tpu.analysis.rules import ALL_RULES, RULES_BY_ID, Rule

__all__ = [
    "Finding",
    "Module",
    "Rule",
    "ALL_RULES",
    "RULES_BY_ID",
    "run_analysis",
    "load_module",
    "load_baseline",
    "save_baseline",
    "apply_baseline",
]
