"""CLI for tpuft_check: one-line findings, non-zero exit for CI.

    python -m torchft_tpu.analysis [paths...] [--rules id,id] [--list-rules]
        [--baseline FILE] [--write-baseline] [--no-baseline]
    python -m torchft_tpu.analysis --explore [scenario ...]
    python -m torchft_tpu.analysis --explore SCENARIO --replay TOKEN

The first form runs the static rules (R1-R11); ``--explore`` runs the
deterministic interleaving explorer over the named commit/quorum
scenarios (default: every real-protocol one) and exits 1 if any
schedule violates an invariant, printing the replay token; ``--replay``
re-runs one scenario under a previously printed token.

Env: ``TPUFT_ANALYSIS_REFERENCE`` (reference snapshot root, default
/root/reference; citation resolution skips cleanly when absent),
``TPUFT_ANALYSIS_BASELINE`` (baseline path override), and the
``TPUFT_EXPLORE_*`` budget knobs (see
``torchft_tpu.utils.schedules.explore_defaults``).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from torchft_tpu.analysis import core, rules


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m torchft_tpu.analysis")
    parser.add_argument(
        "paths", nargs="*", help="files/dirs to scan (default: the package)"
    )
    parser.add_argument(
        "--rules", default=None, help="comma-separated rule ids (default: all)"
    )
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--baseline", default=None, help="baseline file override")
    parser.add_argument(
        "--no-baseline", action="store_true", help="report baselined findings too"
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="record the current findings as the new baseline",
    )
    parser.add_argument(
        "--reference",
        default=None,
        help="reference snapshot root for citation-lint (default: "
        f"${core.REFERENCE_ENV} or /root/reference)",
    )
    parser.add_argument(
        "--explore",
        action="store_true",
        help="run the interleaving explorer over the named scenarios "
        "(positional args; default: all real-protocol scenarios)",
    )
    parser.add_argument(
        "--replay",
        default=None,
        metavar="TOKEN",
        help="with --explore and exactly one scenario: replay this "
        "tpuft-sched: token instead of exploring",
    )
    args = parser.parse_args(argv)

    if args.replay and not args.explore:
        print("--replay requires --explore", file=sys.stderr)
        return 2

    if args.explore:
        # Lazy import: the explorer pulls in jax + the manager plane,
        # which the pure static-analysis legs must not pay for.
        from torchft_tpu.analysis import explore

        try:
            return explore.run_explore_cli(
                args.paths, replay_token=args.replay
            )
        except KeyError as e:
            print(str(e.args[0]) if e.args else str(e), file=sys.stderr)
            return 2

    if args.list_rules:
        for rule in rules.ALL_RULES:
            print(f"{rule.id:22s} {rule.summary}  [{rule.anchor}]")
        return 0

    selected = args.rules.split(",") if args.rules else None
    if selected:
        unknown = [r for r in selected if r not in rules.RULES_BY_ID]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    findings = core.run_analysis(
        paths=[Path(p) for p in args.paths] or None,
        rules=selected,
        reference_root=Path(args.reference) if args.reference else None,
    )

    if args.write_baseline:
        path = core.save_baseline(findings, args.baseline)
        print(f"wrote {len(findings)} finding(s) to {path}")
        return 0

    baselined = 0
    if not args.no_baseline:
        findings, baselined = core.apply_baseline(findings, args.baseline)

    for finding in findings:
        print(finding.format())
    tail = f" ({baselined} baselined)" if baselined else ""
    print(f"tpuft_check: {len(findings)} finding(s){tail}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
