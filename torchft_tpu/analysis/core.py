"""tpuft_check core: module loading, suppressions, baseline, rule driver.

The analyzer turns CLAUDE.md's prose invariants into enforced properties:
each rule in :mod:`torchft_tpu.analysis.rules` is a pure function over a
parsed module (AST + source), returning :class:`Finding`\\ s. Three escape
hatches keep it honest rather than noisy:

- inline suppressions — ``# tpuft: allow(<rule-id>): <why>`` on the finding
  line (or alone on the line above it). The reason is MANDATORY: a
  suppression without one is itself reported.
- a findings baseline (``baseline.json`` next to this file, or
  ``$TPUFT_ANALYSIS_BASELINE``) for debt that is tracked but not yet fixed;
  the shipped tree keeps it empty.
- per-rule scoping: rules whose invariant only binds specific layers (e.g.
  R1 over the comm layer) skip out-of-scope package files, but apply fully
  to explicitly given paths (how the test fixtures exercise them).

Runtime counterpart: :mod:`torchft_tpu.utils.lockcheck` checks the same
lock-discipline invariants on live interleavings.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "Module",
    "load_module",
    "iter_package_files",
    "run_analysis",
    "load_baseline",
    "save_baseline",
    "apply_baseline",
    "PACKAGE_ROOT",
    "REPO_ROOT",
    "REFERENCE_ENV",
    "BASELINE_ENV",
    "default_reference_root",
]

PACKAGE_ROOT = Path(__file__).resolve().parent.parent  # torchft_tpu/
REPO_ROOT = PACKAGE_ROOT.parent

REFERENCE_ENV = "TPUFT_ANALYSIS_REFERENCE"
BASELINE_ENV = "TPUFT_ANALYSIS_BASELINE"
_DEFAULT_REFERENCE = "/root/reference"
_DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

# ``# tpuft: allow(<rule-id>): <reason>`` — the reason is mandatory.
_SUPPRESS_RE = re.compile(r"#\s*tpuft:\s*allow\(([\w-]+)\)\s*(?::\s*(\S.*))?")

# Generated / vendored files the package scan never visits.
_EXCLUDED_PARTS = ("__pycache__",)
_EXCLUDED_NAMES = ("tpuft_pb2.py",)


def default_reference_root() -> Path:
    return Path(os.environ.get(REFERENCE_ENV, _DEFAULT_REFERENCE))


@dataclass(frozen=True)
class Finding:
    """One rule violation, stable enough to baseline across line drift."""

    rule: str
    file: str  # repo-root-relative when possible
    line: int
    message: str
    context: str = ""  # stripped source line the finding anchors to

    def format(self) -> str:
        return f"{self.rule} {self.file}:{self.line} {self.message}"

    @property
    def fingerprint(self) -> str:
        # File + rule + anchored source text: survives pure line drift,
        # invalidates when the flagged code itself changes.
        return f"{self.rule}::{self.file}::{self.context}"


@dataclass
class Module:
    """A parsed source module plus everything rules need to scope and
    suppress findings."""

    path: Path
    rel: str  # repo-root-relative posix path ("" prefix for external files)
    source: str
    lines: List[str]
    tree: ast.AST
    in_package: bool
    suppressions: Dict[int, List[Tuple[str, str]]] = field(default_factory=dict)
    # (start, end, rule): a suppression on (or just above) a ``def`` line
    # covers the whole function body — for invariants like lock-discipline
    # where one justification covers every mutation in a load fn.
    span_suppressions: List[Tuple[int, int, str]] = field(default_factory=list)
    malformed_suppressions: List[Tuple[int, str]] = field(default_factory=list)
    parents: Dict[ast.AST, ast.AST] = field(default_factory=dict)

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def is_suppressed(self, rule: str, lineno: int) -> bool:
        for probe in (lineno, lineno - 1):
            for rid, _reason in self.suppressions.get(probe, []):
                if rid == rule:
                    # A comment-only line suppresses the next line; an
                    # end-of-line comment suppresses its own line.
                    if probe == lineno or self.line_at(probe).startswith("#"):
                        return True
        return any(
            start <= lineno <= end and rid == rule
            for start, end, rid in self.span_suppressions
        )


def _collect_suppressions(module: Module) -> None:
    for idx, raw in enumerate(module.lines, start=1):
        match = _SUPPRESS_RE.search(raw)
        if not match:
            continue
        rule, reason = match.group(1), (match.group(2) or "").strip()
        if not reason:
            module.malformed_suppressions.append(
                (idx, f"suppression for {rule!r} is missing its reason")
            )
            continue
        module.suppressions.setdefault(idx, []).append((rule, reason))


# Shared-AST cache: every rule (and every re-scan in one process — the
# tier-1 suite runs the full package scan more than once, and R8/R11
# re-load modules from inside their checkers) reuses one parsed Module
# per file, keyed by (mtime_ns, size) so an edited file re-parses.
# Rules treat Modules as read-only, which is what makes sharing safe.
_MODULE_CACHE: Dict[Path, Tuple[Tuple[int, int], "Module"]] = {}


def load_module(path: Path) -> Optional[Module]:
    """Parses one file; returns None when it isn't valid Python (a syntax
    error is a build problem, not an analysis finding). Parsed modules are
    cached process-wide keyed by (path, mtime, size)."""
    path = Path(path).resolve()
    try:
        stat = path.stat()
    except OSError:
        return None
    key = (stat.st_mtime_ns, stat.st_size)
    cached = _MODULE_CACHE.get(path)
    if cached is not None and cached[0] == key:
        return cached[1]
    try:
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError, ValueError):
        return None
    try:
        rel = path.relative_to(REPO_ROOT).as_posix()
        in_package = path.is_relative_to(PACKAGE_ROOT)
    except ValueError:
        rel = path.name
        in_package = False
    module = Module(
        path=path,
        rel=rel,
        source=source,
        lines=source.splitlines(),
        tree=tree,
        in_package=in_package,
    )
    _collect_suppressions(module)
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            module.parents[child] = parent
    # Function-scoped suppressions: an allow comment on the def line (or
    # comment-only just above it) covers the whole body.
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for probe in (node.lineno, node.lineno - 1):
                for rid, _reason in module.suppressions.get(probe, []):
                    if probe == node.lineno or module.line_at(probe).startswith("#"):
                        module.span_suppressions.append(
                            (node.lineno, getattr(node, "end_lineno", node.lineno), rid)
                        )
    _MODULE_CACHE[path] = (key, module)
    return module


def iter_package_files() -> Iterable[Path]:
    for path in sorted(PACKAGE_ROOT.rglob("*.py")):
        parts = set(path.parts)
        if parts & set(_EXCLUDED_PARTS) or path.name in _EXCLUDED_NAMES:
            continue
        yield path


def run_analysis(
    paths: Optional[Sequence[Path]] = None,
    rules: Optional[Sequence[str]] = None,
    reference_root: Optional[Path] = None,
) -> List[Finding]:
    """Runs the (selected) rules over ``paths`` (default: the whole
    package). Inline-suppressed findings are dropped; malformed
    suppressions surface as ``suppression`` findings so a typo'd allow
    cannot silently disable a rule."""
    from torchft_tpu.analysis.rules import ALL_RULES

    if reference_root is None:
        reference_root = default_reference_root()
    selected = [
        rule
        for rule in ALL_RULES
        if rules is None or rule.id in rules
    ]
    targets = [Path(p) for p in paths] if paths is not None else list(iter_package_files())
    findings: List[Finding] = []
    for target in targets:
        if target.is_dir():
            files = sorted(target.rglob("*.py"))
        else:
            files = [target]
        for file in files:
            module = load_module(file)
            if module is None:
                continue
            for lineno, msg in module.malformed_suppressions:
                findings.append(
                    Finding(
                        rule="suppression",
                        file=module.rel,
                        line=lineno,
                        message=msg,
                        context=module.line_at(lineno),
                    )
                )
            for rule in selected:
                for finding in rule.check(module, reference_root=reference_root):
                    if not module.is_suppressed(finding.rule, finding.line):
                        findings.append(finding)
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def _baseline_path(path: Optional[Path] = None) -> Path:
    if path is not None:
        return Path(path)
    return Path(os.environ.get(BASELINE_ENV, str(_DEFAULT_BASELINE)))


def load_baseline(path: Optional[Path] = None) -> List[str]:
    """Baselined finding fingerprints (empty when the file is absent)."""
    baseline = _baseline_path(path)
    if not baseline.exists():
        return []
    data = json.loads(baseline.read_text())
    return list(data.get("findings", []))


def save_baseline(findings: Sequence[Finding], path: Optional[Path] = None) -> Path:
    baseline = _baseline_path(path)
    payload = {
        "comment": (
            "tpuft_check findings baseline: tracked-but-unfixed debt. Ship "
            "empty; every entry that stays needs an inline justification at "
            "the flagged site."
        ),
        "findings": sorted(f.fingerprint for f in findings),
    }
    baseline.write_text(json.dumps(payload, indent=2) + "\n")
    return baseline


def apply_baseline(
    findings: Sequence[Finding], path: Optional[Path] = None
) -> Tuple[List[Finding], int]:
    """(new findings, number suppressed by the baseline)."""
    known = set(load_baseline(path))
    fresh = [f for f in findings if f.fingerprint not in known]
    return fresh, len(findings) - len(fresh)
