"""tpuft_check semantic rules R9–R11: intraprocedural dataflow over the
shared per-file ASTs.

Unlike R1–R8 (purely lexical), these rules track how values FLOW inside a
function:

- **R9 verify-before-adopt** — wire bytes (HTTP/socket chunk reads in the
  heal/serving transports) are *tainted* until a sanitizer touches them
  (a CRC/size/digest/era comparison, ``validate_latest``, or the wire
  codec's self-verifying ``decode_state``); a tainted value reaching an
  adoption sink (``_apply_pending_state_dict``, a ``self._current`` /
  ``self._version`` swap, history-ring ``note_state``, deserialization
  via ``load_state_dict``) is a finding. This is CLAUDE.md's "corrupt /
  stale / stalled donors funnel into report_error — never adopted state"
  made structural.
- **R10 era-fence** — every HTTP route handler that serves checkpoint
  bytes (heal chunks, serving chunks, /meta) must consult the staged
  quorum_id/era somewhere in its body; a new route cannot silently skip
  the 409 fence the shipped handlers all implement.
- **R11 stale-suppression** — a ``# tpuft: allow(<rule>)`` comment whose
  rule no longer fires at the covered site is itself a finding, so the
  suppression inventory cannot rot as the code under it changes.

The taint pass is deliberately *lexical-order* flow ("on the source-order
path", not a full CFG): a sanitizer cleanses every line after it, and a
finding means no sanitizer appeared between the fetch and the sink in
source order. That is the same granularity bar R7 sets for drain-before-
reconfigure, and it is exactly how the shipped verify-then-adopt sites
are written (fetch → compare → raise → adopt).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from torchft_tpu.analysis.core import Finding, Module

__all__ = [
    "check_verify_before_adopt",
    "check_era_fence",
    "check_stale_suppression",
]


# ---------------------------------------------------------------------------
# shared helpers (kept in sync with rules.py's lexical pass)
# ---------------------------------------------------------------------------


def _finding(module: Module, rule: str, node_line: int, message: str) -> Finding:
    return Finding(
        rule=rule,
        file=module.rel,
        line=node_line,
        message=message,
        context=module.line_at(node_line),
    )


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _func_defs(tree: ast.AST) -> Iterable[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _innermost_def(module: Module, node: ast.AST) -> Optional[ast.AST]:
    cursor = module.parents.get(node)
    while cursor is not None:
        if isinstance(cursor, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cursor
        cursor = module.parents.get(cursor)
    return None


def _own_statements(module: Module, fn: ast.AST) -> List[ast.stmt]:
    """``fn``'s statements in source order, excluding statements that
    belong to a nested def (each def gets its own taint pass)."""
    out: List[ast.stmt] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.stmt) and node is not fn:
            if _innermost_def(module, node) is fn:
                out.append(node)
    out.sort(key=lambda n: (n.lineno, n.col_offset))
    return out


# ---------------------------------------------------------------------------
# R9 verify-before-adopt
# ---------------------------------------------------------------------------

_R9_SCOPE_FILES = (
    "torchft_tpu/checkpointing/http_transport.py",
    "torchft_tpu/serving/_wire.py",
    "torchft_tpu/serving/relay.py",
    "torchft_tpu/serving/subscriber.py",
    "torchft_tpu/serving/rollout.py",
    "torchft_tpu/manager.py",
    "torchft_tpu/history.py",
    "torchft_tpu/zero.py",
)

# Calls that produce unverified wire bytes.
_R9_SOURCE_CALLS = {
    "fetch_bytes",
    "fetch_json",
    "fetch_notify",
    "urlopen",
    "_fetch",
    "_fetch_failover",
    "_fetch_retry",
}

# A source call parameterized by a verifier is the *verifying-fetch*
# idiom (``expect_crc=crcs[i]``, ``consume=<crc-checking closure>``) and
# yields verified bytes; the same kwarg explicitly set to None does not.
_R9_VERIFY_KWARG_MARKERS = ("crc", "digest", "era", "quorum", "consume", "verify")

# Function params that ARE wire receivers (the ``consume(resp)`` shape).
_R9_TAINTED_PARAMS = {"resp", "response", "sock", "conn", "rfile"}

# Tokens whose presence in a Compare marks it as a verification of the
# tainted value it mentions (CRC check, size check, digest binding,
# era/quorum-id fence).
_R9_VERIFY_TOKENS = ("crc", "digest", "era", "quorum", "size")

# Calls that verify their argument (or return self-verified data).
_R9_SANITIZER_CALLS = {"validate_latest", "decode_state"}

# Adoption sinks: committed-state swaps and deserialization of raw bytes.
_R9_SINK_CALLS = {"_apply_pending_state_dict", "note_state", "load_state_dict"}
_R9_SINK_ATTRS = {"_version", "_current", "params", "opt_state", "_state"}


def _expr_names(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _call_is_verifying_source(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg is None:
            continue
        low = kw.arg.lower()
        if any(marker in low for marker in _R9_VERIFY_KWARG_MARKERS):
            if not (isinstance(kw.value, ast.Constant) and kw.value.value is None):
                return True
    return False


def _source_calls(node: ast.AST) -> List[ast.Call]:
    """Unverified source calls anywhere under ``node``."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = _terminal_name(n.func)
            if name in _R9_SOURCE_CALLS and not _call_is_verifying_source(n):
                out.append(n)
    return out


class _Taint:
    """Per-function taint state: tainted names, their derivation closure
    (so verifying a parsed value also cleanses the bytes it was parsed
    from), and the source each taint originated at (for messages)."""

    def __init__(self) -> None:
        self.names: Set[str] = set()
        self.deriv: Dict[str, Set[str]] = {}
        self.origin: Dict[str, Tuple[int, str]] = {}

    def taint(self, name: str, via: Set[str], line: int, what: str) -> None:
        closure: Set[str] = set()
        for v in via:
            closure.add(v)
            closure |= self.deriv.get(v, set())
        self.deriv[name] = closure
        self.names.add(name)
        src = next(
            (self.origin[v] for v in via if v in self.origin), (line, what)
        )
        self.origin[name] = src

    def cleanse(self, name: str) -> None:
        self.names.discard(name)
        for other in self.deriv.get(name, ()):  # verified-derived → origin too
            self.names.discard(other)

    def tainted_in(self, node: ast.AST) -> Set[str]:
        return _expr_names(node) & self.names


def _compare_is_sanitizer(node: ast.Compare) -> bool:
    for n in ast.walk(node):
        tok = None
        if isinstance(n, ast.Name):
            tok = n.id
        elif isinstance(n, ast.Attribute):
            tok = n.attr
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            tok = n.value
        if tok and any(v in tok.lower() for v in _R9_VERIFY_TOKENS):
            return True
    return False


def _apply_sanitizers(taint: _Taint, expr: ast.AST) -> None:
    for n in ast.walk(expr):
        if isinstance(n, ast.Compare) and _compare_is_sanitizer(n):
            for name in taint.tainted_in(n):
                taint.cleanse(name)
        elif isinstance(n, ast.Call):
            if _terminal_name(n.func) in _R9_SANITIZER_CALLS:
                for arg in n.args:
                    for name in taint.tainted_in(arg):
                        taint.cleanse(name)


def _value_taints(taint: _Taint, value: ast.AST) -> Tuple[Set[str], Optional[Tuple[int, str]]]:
    """(tainted names the value mentions, fresh-source origin if the value
    itself contains an unverified source call). A value whose outermost
    producer is a sanitizer call is clean."""
    if isinstance(value, ast.Call) and _terminal_name(value.func) in _R9_SANITIZER_CALLS:
        return set(), None
    via = taint.tainted_in(value)
    fresh = _source_calls(value)
    origin = None
    if fresh:
        call = fresh[0]
        origin = (call.lineno, _terminal_name(call.func) or "fetch")
    return via, origin


def _sink_findings(module: Module, taint: _Taint, stmt: ast.stmt) -> List[Finding]:
    out: List[Finding] = []
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call):
            name = _terminal_name(n.func)
            if name in _R9_SINK_CALLS:
                tainted = set()
                for arg in list(n.args) + [kw.value for kw in n.keywords]:
                    tainted |= taint.tainted_in(arg)
                    if _source_calls(arg):
                        tainted.add("<fetch result>")
                if tainted:
                    first = sorted(tainted)[0]
                    src_line, src_what = taint.origin.get(
                        first, (n.lineno, "fetch")
                    )
                    out.append(
                        _finding(
                            module,
                            "verify-before-adopt",
                            n.lineno,
                            f"unverified wire bytes ({first!s}, from "
                            f"{src_what} at line {src_line}) reach "
                            f"{name}() without a CRC/digest/era check on "
                            "the path",
                        )
                    )
    return out


def _assign_targets(stmt: ast.stmt) -> Tuple[List[ast.expr], Optional[ast.expr]]:
    if isinstance(stmt, ast.Assign):
        return stmt.targets, stmt.value
    if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        return ([stmt.target], stmt.value) if stmt.value is not None else ([], None)
    return [], None


def _own_exprs(stmt: ast.stmt) -> List[ast.expr]:
    """The expressions evaluated AT ``stmt`` itself — compound statements
    contribute only their headers (test / iter / context managers), never
    their bodies, which appear separately in source order. This is what
    keeps the pass flow-sensitive: a CRC check at the bottom of a ``try``
    must not cleanse a decode at its top."""
    if isinstance(stmt, ast.Assign):
        return [stmt.value, *stmt.targets]
    if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        return [e for e in (stmt.value, stmt.target) if e is not None]
    if isinstance(stmt, (ast.Expr, ast.Return)):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.Raise):
        return [e for e in (stmt.exc, stmt.cause) if e is not None]
    if isinstance(stmt, ast.Assert):
        return [stmt.test] + ([stmt.msg] if stmt.msg is not None else [])
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    return []


def _taint_function(module: Module, fn: ast.AST) -> List[Finding]:
    taint = _Taint()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in list(args.args) + list(args.kwonlyargs):
            if a.arg in _R9_TAINTED_PARAMS:
                taint.taint(a.arg, set(), fn.lineno, f"wire receiver param {a.arg!r}")

    findings: List[Finding] = []
    for stmt in _own_statements(module, fn):
        exprs = _own_exprs(stmt)
        # 1) sanitizers in THIS statement's own expressions cleanse first
        #    (the fetch-compare-raise-adopt idiom has the compare in an If
        #    test lexically before the adoption statement).
        for expr in exprs:
            _apply_sanitizers(taint, expr)
        # 2) sinks see the post-sanitize taint state.
        for expr in exprs:
            findings.extend(_sink_findings(module, taint, expr))
        # 3) assignments propagate (or introduce) taint.
        targets, value = _assign_targets(stmt)
        if value is None:
            # for-loop targets derive from the iterable
            if isinstance(stmt, ast.For):
                via = taint.tainted_in(stmt.iter)
                if via:
                    for name in _expr_names(stmt.target):
                        taint.taint(name, via, stmt.lineno, "loop over tainted")
            continue
        via, fresh_origin = _value_taints(taint, value)
        is_tainted = bool(via) or fresh_origin is not None
        for target in targets:
            if isinstance(target, ast.Name):
                if is_tainted:
                    line, what = fresh_origin or (stmt.lineno, "derived")
                    taint.taint(target.id, via, line, what)
                    if fresh_origin is not None:
                        taint.origin[target.id] = fresh_origin
                else:
                    taint.cleanse(target.id)
            elif isinstance(target, ast.Tuple):
                for elt in target.elts:
                    if isinstance(elt, ast.Name):
                        if is_tainted:
                            line, what = fresh_origin or (stmt.lineno, "derived")
                            taint.taint(elt.id, via, line, what)
                        else:
                            taint.cleanse(elt.id)
            elif isinstance(target, ast.Attribute):
                if is_tainted and target.attr in _R9_SINK_ATTRS:
                    name = sorted(via)[0] if via else "<fetch result>"
                    line, what = fresh_origin or taint.origin.get(
                        name, (stmt.lineno, "fetch")
                    )
                    findings.append(
                        _finding(
                            module,
                            "verify-before-adopt",
                            stmt.lineno,
                            f"unverified wire bytes ({name}, from {what} at "
                            f"line {line}) adopted into "
                            f"self.{target.attr} without a CRC/digest/era "
                            "check on the path",
                        )
                    )
            elif isinstance(target, ast.Subscript) and is_tainted:
                base = target.value
                if isinstance(base, ast.Name):
                    line, what = fresh_origin or (stmt.lineno, "derived")
                    taint.taint(base.id, via, line, what)
    return findings


def check_verify_before_adopt(
    module: Module, reference_root: Optional[Path] = None
) -> List[Finding]:
    del reference_root
    if module.in_package and module.rel not in _R9_SCOPE_FILES:
        return []
    findings: List[Finding] = []
    for fn in _func_defs(module.tree):
        findings.extend(_taint_function(module, fn))
    return findings


# ---------------------------------------------------------------------------
# R10 era-fence
# ---------------------------------------------------------------------------

_R10_HANDLER_NAMES = {"do_GET", "do_POST"}
_R10_ERA_RE = re.compile(r"(^|_)era($|_)")


def _r10_tokens(fn: ast.AST) -> Iterable[str]:
    for n in ast.walk(fn):
        if isinstance(n, ast.Name):
            yield n.id
        elif isinstance(n, ast.Attribute):
            yield n.attr
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n.value


def check_era_fence(
    module: Module, reference_root: Optional[Path] = None
) -> List[Finding]:
    del reference_root
    findings: List[Finding] = []
    for fn in _func_defs(module.tree):
        if fn.name not in _R10_HANDLER_NAMES:  # type: ignore[union-attr]
            continue
        serves_checkpoint = any(
            isinstance(n, ast.Constant)
            and isinstance(n.value, str)
            and "checkpoint" in n.value
            for n in ast.walk(fn)
        )
        if not serves_checkpoint:
            continue
        fenced = any(
            "quorum_id" in tok.lower() or _R10_ERA_RE.search(tok.lower())
            for tok in _r10_tokens(fn)
        )
        if not fenced:
            findings.append(
                _finding(
                    module,
                    "era-fence",
                    fn.lineno,
                    f"route handler {fn.name} serves checkpoint bytes "
                    "without consulting the staged quorum_id/era (stale-era "
                    "requests must be refused, http_transport.py do_GET "
                    "fence)",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# R11 stale-suppression
# ---------------------------------------------------------------------------

_R11_SELF = {"stale-suppression", "suppression"}


def _suppression_covers(module: Module, comment_line: int, finding_line: int) -> bool:
    """Mirrors Module.is_suppressed coverage for ONE specific comment:
    its own line (end-of-line form), the next line (comment-only form),
    and the span of any def whose header sits on a covered line."""
    comment_only = module.line_at(comment_line).startswith("#")
    direct = {comment_line}
    if comment_only:
        direct.add(comment_line + 1)
    if finding_line in direct:
        return True
    for node in _func_defs(module.tree):
        if node.lineno in direct:
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= finding_line <= end:
                return True
    return False


def check_stale_suppression(
    module: Module, reference_root: Optional[Path] = None
) -> List[Finding]:
    if not module.suppressions:
        return []
    # Late import: rules.py registers THIS checker, so the registry import
    # must not run at module import time.
    from torchft_tpu.analysis.rules import RULES_BY_ID

    findings: List[Finding] = []
    live_cache: Dict[str, List[Finding]] = {}
    for comment_line in sorted(module.suppressions):
        for rule_id, _reason in module.suppressions[comment_line]:
            if rule_id in _R11_SELF:
                continue
            rule = RULES_BY_ID.get(rule_id)
            if rule is None:
                findings.append(
                    _finding(
                        module,
                        "stale-suppression",
                        comment_line,
                        f"suppression names unknown rule {rule_id!r} — it "
                        "can never fire; fix the rule id or delete the "
                        "comment",
                    )
                )
                continue
            if rule_id not in live_cache:
                # Checkers are suppression-blind (run_analysis filters after
                # they return), so this re-run sees the pre-suppression
                # findings the comment claims to cover.
                live_cache[rule_id] = rule.check(
                    module, reference_root=reference_root
                )
            covered = any(
                _suppression_covers(module, comment_line, f.line)
                for f in live_cache[rule_id]
            )
            if not covered:
                findings.append(
                    _finding(
                        module,
                        "stale-suppression",
                        comment_line,
                        f"suppression for {rule_id!r} no longer matches a "
                        "finding at this site — the code it excused has "
                        "changed; delete the comment (or re-justify it at "
                        "the new site)",
                    )
                )
    return findings
