"""Deterministic interleaving explorer for the commit/quorum protocol.

The static rules (R1-R11) prove invariants lexically; this module checks
the ones only an *interleaving* can break, by running the REAL Manager +
pipelined Optimizer protocol under the controlled scheduler in
:mod:`torchft_tpu.utils.schedules` and enumerating thread orders at the
instrumented seams (lock acquisitions, commit-barrier entry, pipeline
push/drain, window resolution, tentative adoption, publication, pending
state apply).

Every scenario drives mocked-coordination managers — the exact harness
the manager state-machine tests use (scripted ``ManagerClient``, dummy
PG, fake store) — through a micro-protocol with at least two scheduled
threads, then asserts CLAUDE.md invariants that must hold under EVERY
schedule:

- ``commit-vs-drain``     depth-2 pipelined commits racing the
                          quorum-change window drain: the committed
                          trajectory is schedule-independent (the
                          replica-identity invariant seen from one
                          replica: resolution order never changes
                          committed state).
- ``rollback-unwind``     a scripted barrier refusal racing the drain:
                          exactly one rollback, and the final state is
                          one of the two lawful unwind outcomes (the
                          younger in-flight speculation either discarded
                          with the refusal or re-dispatched after it) —
                          never a half-unwound hybrid.
- ``adopt-vs-capture``    a joiner applying its pending (healed) state
                          dict while a donor-style capture samples under
                          the state-dict read lock: every sample is a
                          consistent (params, opt_state) pair — torn
                          reads are impossible.
- ``publish-vs-drain``    ``Manager._maybe_publish`` racing the window
                          drain: every published state lies exactly on
                          the committed trajectory at its published step
                          (publication never samples speculation — R7's
                          runtime face).

``DEMO_SCENARIOS`` hold *seeded* violations — deliberately buggy
mini-protocols (a torn two-field write, a verify-then-adopt TOCTOU) the
explorer must catch deterministically and print a replay token for; the
tests pin that, and the docs use them to demonstrate the replay
workflow.

CLI: ``python -m torchft_tpu.analysis --explore [scenario ...]`` (see
``--replay`` there for token replay). A violating schedule opens a
``schedule`` incident (:func:`torchft_tpu.tracing.open_incident`), so
the journal + flight-recorder dump correlates with the printed token.
Budgets come from the ``TPUFT_EXPLORE_*`` env knobs
(:func:`torchft_tpu.utils.schedules.explore_defaults`).
"""

from __future__ import annotations

import concurrent.futures
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence

from torchft_tpu.utils import schedules

__all__ = [
    "SCENARIOS",
    "DEMO_SCENARIOS",
    "REAL_STALL_TIMEOUT",
    "explore_scenarios",
    "replay_scenario",
    "run_explore_cli",
]

# Real-protocol scenarios re-trace tiny jitted programs per schedule; give
# the controller more slack than the toy default before it declares a
# thread stalled on a real lock.
REAL_STALL_TIMEOUT = 2.0

# Golden outcomes are computed ONCE per scenario by a serial twin run
# (same jit pipeline => bitwise-identical trajectories) — this also warms
# the XLA executable cache before the first scheduled run, so scheduled
# threads never sit in a multi-second compile mid-schedule.
_GOLDEN: Dict[str, Any] = {}


def _force_cpu() -> None:
    """Pin jax to CPU before any backend init: the CLI runs outside the
    test suite's conftest, on a machine whose sitecustomize pins
    ``JAX_PLATFORMS`` to the tunneled TPU."""
    import jax

    try:
        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already initialized elsewhere
        pass


# ---------------------------------------------------------------------------
# mocked-coordination harness (the manager state-machine tests' pattern)
# ---------------------------------------------------------------------------


class _FakeStore:
    def __init__(self) -> None:
        self.data = {
            "manager_addr": b"fake:1234",
            "replica_id": b"explore_replica:uuid",
        }

    def get(self, key: str, timeout: float = 0, wait: bool = True):
        return self.data.get(key)

    def set(self, key: str, value: bytes, timeout: float = 0) -> None:
        self.data[key] = value


def _scripted_manager(depth: int, refuse_step: Optional[int] = None):
    """A real Manager over a scripted ManagerClient + dummy PG, lone
    topology (the fused single-group step: fully deterministic compute).
    ``refuse_step`` refuses the FIRST barrier vote claiming that step —
    keyed by step, not call order, so concurrent commit-pool deliveries
    cannot reorder the script."""
    from unittest import mock

    from torchft_tpu.checkpointing.transport import CheckpointTransport
    from torchft_tpu.coordination import QuorumResult
    from torchft_tpu.manager import Manager
    from torchft_tpu.parallel.process_group import ProcessGroupDummy

    _force_cpu()
    transport = mock.create_autospec(CheckpointTransport, instance=True)
    transport.metadata.return_value = "http://fake:0"
    with mock.patch("torchft_tpu.manager.ManagerClient", autospec=True):
        manager = Manager(
            pg=ProcessGroupDummy(),
            min_replica_size=1,
            store=_FakeStore(),
            store_addr="store:0",
            use_async_quorum=False,
            group_rank=1,  # no native ManagerServer
            group_world_size=2,
            checkpoint_transport=transport,
            timeout=5.0,
            quorum_timeout=5.0,
            commit_pipeline_depth=depth,
        )
    client = manager._client
    client._quorum.return_value = QuorumResult(
        quorum_id=1,
        replica_rank=0,
        replica_world_size=1,
        store_address="store:0",
        max_step=0,
        max_rank=0,
        max_world_size=1,
        heal=False,
    )
    refused: List[int] = []

    def should_commit(rank, step, vote, timeout):
        if refuse_step is not None and step == refuse_step and not refused:
            refused.append(step)
            return False
        return vote

    client.should_commit.side_effect = should_commit
    return manager


def _build_opt(manager, momentum: float = 0.0):
    import jax.numpy as jnp
    import optax

    from torchft_tpu.optim import Optimizer

    tx = optax.sgd(0.1, momentum=momentum) if momentum else optax.sgd(0.1)
    return Optimizer(manager, tx, {"w": jnp.array([1.0, 1.0], jnp.float32)})


def _loss_fn(p, b):
    import jax.numpy as jnp

    return jnp.sum((p["w"] - b) ** 2)  # grad = 2(w - b)


def _batch(i: int):
    import jax.numpy as jnp

    return jnp.full((2,), float(i), jnp.float32)


def _w(opt) -> Any:
    import numpy as np

    return np.asarray(opt.params["w"]).copy()


def _golden_train(
    key: str,
    depth: int,
    nsteps: int,
    refuse_step: Optional[int] = None,
    flush_after: Optional[int] = None,
) -> Dict[str, Any]:
    """Serial twin run: same jit pipeline, no scheduler => the bitwise
    reference outcome. ``flush_after`` forces the window resolved right
    after that loop iteration — modelling the drain thread winning the
    race before the next dispatch."""
    if key in _GOLDEN:
        return _GOLDEN[key]
    manager = _scripted_manager(depth, refuse_step)
    opt = _build_opt(manager)
    step_fn = opt.make_step_fn(_loss_fn)
    trajectory = [_w(opt)]
    for i in range(nsteps):
        step_fn(_batch(i))
        if flush_after is not None and i == flush_after:
            opt.flush_pipeline(raise_on_error=False)
        trajectory.append(_w(opt))
    opt.flush_pipeline(raise_on_error=False)
    result = {
        "params": _w(opt),
        "step": manager.current_step(),
        "rollbacks": opt.rollback_count,
        # Post-flush live state per prefix is only the committed
        # trajectory when every vote commits; refusal goldens use
        # params/step only.
        "trajectory": trajectory + [_w(opt)],
    }
    manager.shutdown()
    _GOLDEN[key] = result
    return result


# ---------------------------------------------------------------------------
# real-protocol scenarios
# ---------------------------------------------------------------------------


def _scenario_commit_vs_drain(sched: schedules.Scheduler):
    """Depth-2 pipelined commits, then the quorum-change window drain
    racing the train loop's own flush: both may resolve the same window
    records concurrently (the idempotency `_resolve_pipelined_record`
    claims), and the committed trajectory must be schedule-independent.

    The drain thread is GATED until every dispatch has happened: the
    production contract is that the quorum-change drain never overlaps
    *new* dispatches (the train thread is parked in ``wait_quorum`` while
    the hook runs — optim._drain_pipeline_for_quorum_change's docstring)
    — an ungated drain mid-dispatch skews speculative vote labels, which
    is a scenario modelling error, not a protocol bug."""
    import numpy as np

    nsteps = 3
    golden = _golden_train("commit_vs_drain", depth=2, nsteps=nsteps)
    manager = _scripted_manager(depth=2)
    opt = _build_opt(manager)
    step_fn = opt.make_step_fn(_loss_fn)
    dispatched = threading.Event()

    def train():
        for i in range(nsteps):
            step_fn(_batch(i))
        dispatched.set()
        opt.flush_pipeline(raise_on_error=False)

    def drain():
        # The quorum thread's drain hook: held behind the dispatch gate
        # (see scenario docstring), then racing the flush and a second
        # drain pass at every schedule point.
        schedules.point("drain.gate", until=dispatched.is_set)
        dispatched.wait(timeout=10.0)
        opt._drain_pipeline_for_quorum_change()
        schedules.point("drain.again")
        opt._drain_pipeline_for_quorum_change()

    sched.spawn("train", train)
    sched.spawn("drain", drain)

    def check():
        assert opt.pending_commits() == 0, "window not drained"
        assert opt.rollback_count == 0, "spurious rollback"
        assert manager.current_step() == golden["step"], (
            f"committed-step drift: {manager.current_step()} != "
            f"{golden['step']}"
        )
        assert np.array_equal(_w(opt), golden["params"]), (
            "committed trajectory depends on the schedule: "
            f"{_w(opt)} != {golden['params']}"
        )

    check.cleanup = manager.shutdown
    return check


def _scenario_rollback_unwind(sched: schedules.Scheduler):
    """A scripted barrier refusal at claimed step 1 racing the drain:
    exactly one rollback, and the final state is one of the two lawful
    unwind outcomes — the younger in-flight speculation discarded with
    the refusal (batches 0,3,4 commit) or, when the refusal resolved
    before the next dispatch, re-speculated on the rolled-back state
    (batches 0,2,3,4 commit). Anything else is a half-unwound hybrid."""
    nsteps = 5
    # Twin A: refusal resolves under window pressure (younger discarded).
    late = _golden_train(
        "rollback_late", depth=2, nsteps=nsteps, refuse_step=1
    )
    # Twin B: refusal resolved right after its dispatch (a quorum-change
    # drain lands before batch 2 is dispatched — nothing younger to
    # discard). The gated live run below always realizes twin A; twin B
    # keeps the lawful-outcome set honest about the envelope a real
    # quorum change can produce.
    early = _golden_train(
        "rollback_early", depth=2, nsteps=nsteps, refuse_step=1,
        flush_after=1,
    )
    manager = _scripted_manager(depth=2, refuse_step=1)
    opt = _build_opt(manager)
    step_fn = opt.make_step_fn(_loss_fn)
    dispatched = threading.Event()

    def train():
        for i in range(nsteps):
            step_fn(_batch(i))
        dispatched.set()
        opt.flush_pipeline(raise_on_error=False)

    def drain():
        # Gated like commit-vs-drain: the quorum-change drain never
        # overlaps new dispatches, but its resolution of the refused
        # window tail races the train loop's flush freely.
        schedules.point("drain.gate", until=dispatched.is_set)
        dispatched.wait(timeout=10.0)
        opt._drain_pipeline_for_quorum_change()
        schedules.point("drain.again")
        opt._drain_pipeline_for_quorum_change()

    sched.spawn("train", train)
    sched.spawn("drain", drain)

    def check():
        assert opt.pending_commits() == 0, "window not drained"
        assert opt.rollback_count == 1, (
            f"refusal must roll back exactly once, saw {opt.rollback_count}"
        )
        outcome = (manager.current_step(), tuple(_w(opt)))
        lawful = {
            (late["step"], tuple(late["params"])),
            (early["step"], tuple(early["params"])),
        }
        assert outcome in lawful, (
            f"unlawful unwind outcome {outcome}; lawful: {sorted(lawful)}"
        )

    check.cleanup = manager.shutdown
    return check


def _scenario_adopt_vs_capture(sched: schedules.Scheduler):
    """A joiner applying its pending (healed) state dict while a
    donor-style capture samples under the state-dict read lock: every
    sample must be a consistent (params, opt_state) pair — the write
    lock makes torn reads structurally impossible."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    manager = _scripted_manager(depth=0)
    opt = _build_opt(manager, momentum=0.9)  # momentum: paired trace state

    def _paint(tree, value):
        return jax.tree_util.tree_map(
            lambda x: jnp.full_like(x, value)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )

    pending = {
        "optimizer": {
            "params": _paint(opt.params, 5.0),
            "opt_state": _paint(opt.opt_state, 7.0),
        }
    }
    done: concurrent.futures.Future = concurrent.futures.Future()
    done.set_result(None)
    manager._healing = True
    manager._quorum_future = done
    manager._pending_state_dict = {"user": pending}

    def _sample():
        state = opt._state_dict()
        w = float(np.asarray(state["params"]["w"])[0])
        traces = [
            leaf
            for leaf in jax.tree_util.tree_leaves(state["opt_state"])
            if hasattr(leaf, "dtype")
            and jnp.issubdtype(leaf.dtype, jnp.floating)
        ]
        m = float(np.asarray(traces[0]).ravel()[0])
        return w, m

    samples: List[Any] = []

    def joiner():
        manager._apply_pending_state_dict()

    def capture():
        for _ in range(3):
            schedules.point("capture.sample")
            with manager._state_dict_lock.r_lock(timeout=5.0):
                samples.append(_sample())

    sched.spawn("capture", capture)
    sched.spawn("joiner", joiner)

    def check():
        consistent = {(1.0, 0.0), (5.0, 7.0)}  # pre-heal / post-heal pairs
        for pair in samples:
            assert pair in consistent, (
                f"torn state capture {pair}: params and opt_state from "
                f"different heal epochs (lawful: {sorted(consistent)})"
            )
        assert _sample() == (5.0, 7.0), "pending state not adopted"
        assert manager._pending_state_dict is None

    check.cleanup = manager.shutdown
    return check


class _RecordingPublisher:
    """Minimal publisher: records every sampled state so the check can
    prove publication only ever sees committed-trajectory points."""

    def __init__(self) -> None:
        import numpy as np

        self._np = np
        self._due = False
        self.published: List[Any] = []
        self.retracted: List[int] = []

    def register_error_callback(self, cb) -> None:  # Manager.attach seam
        pass

    def note_commit(self, step: int, quorum_id: int) -> None:
        self._due = True

    def due(self) -> bool:
        return self._due

    def publish(self, step: int, quorum_id: int, state: Any) -> None:
        self._due = False
        w = self._np.asarray(state["optimizer"]["params"]["w"]).copy()
        self.published.append((step, w))

    def retract_after(self, step: int) -> None:
        self.retracted.append(step)

    def shutdown(self, wait: bool = True) -> None:
        pass


def _scenario_publish_vs_drain(sched: schedules.Scheduler):
    """``Manager._maybe_publish`` racing the window drain: every
    published state must lie exactly on the committed trajectory at its
    published step — the drain inside publication (R7's runtime face)
    means speculation can never be sampled."""
    import numpy as np

    nsteps = 5
    golden = _golden_train(
        "publish_traj", depth=2, nsteps=nsteps, flush_after=-1
    )
    # flush_after=-1 never matches an iteration: trajectory[k] is the
    # LIVE state after dispatch k, which for an all-commit run equals the
    # committed state after k steps (speculative adoption IS the serial
    # application when every vote commits). trajectory[0] is the init.
    manager = _scripted_manager(depth=2)
    opt = _build_opt(manager)
    publisher = _RecordingPublisher()
    manager.attach_publisher(publisher)
    step_fn = opt.make_step_fn(_loss_fn)
    dispatched = threading.Event()

    def train():
        for i in range(nsteps):
            step_fn(_batch(i))
        dispatched.set()
        opt.flush_pipeline(raise_on_error=False)
        # The loop-boundary publication a real trainer runs after its
        # final flush.
        manager._maybe_publish()

    def drain():
        # Dispatch-gated (see commit-vs-drain); the drain races the
        # flush AND the publication sampling the post-flush state.
        schedules.point("drain.gate", until=dispatched.is_set)
        dispatched.wait(timeout=10.0)
        opt._drain_pipeline_for_quorum_change()
        schedules.point("drain.again")
        opt._drain_pipeline_for_quorum_change()

    sched.spawn("train", train)
    sched.spawn("drain", drain)

    def check():
        assert publisher.published, "publisher never ran"
        assert not publisher.retracted, "spurious retraction"
        trajectory = golden["trajectory"]
        for step, w in publisher.published:
            assert 0 <= step < len(trajectory), f"published step {step}"
            assert np.array_equal(w, trajectory[step]), (
                f"published state at step {step} is off the committed "
                f"trajectory: {w} != {trajectory[step]} — speculation "
                "was sampled"
            )
        steps = [s for s, _ in publisher.published]
        assert steps == sorted(steps), f"publication went backwards: {steps}"

    check.cleanup = manager.shutdown
    return check


SCENARIOS: Dict[str, schedules.Scenario] = {
    "commit-vs-drain": _scenario_commit_vs_drain,
    "rollback-unwind": _scenario_rollback_unwind,
    "adopt-vs-capture": _scenario_adopt_vs_capture,
    "publish-vs-drain": _scenario_publish_vs_drain,
}


# ---------------------------------------------------------------------------
# seeded-violation demos (buggy by construction; the explorer must catch
# each one and print a replay token — pinned by tests, used by the docs)
# ---------------------------------------------------------------------------


def _demo_torn_read(sched: schedules.Scheduler):
    """A two-field version swap with no lock: a reader landing between
    the writes observes a torn pair."""
    box = {"a": 0, "b": 0}
    seen: List[Any] = []

    def writer():
        for i in (1, 2):
            schedules.point("demo.write_a")
            box["a"] = i
            schedules.point("demo.write_b")
            box["b"] = i

    def reader():
        schedules.point("demo.read")
        seen.append((box["a"], box["b"]))

    sched.spawn("reader", reader)
    sched.spawn("writer", writer)

    def check():
        for a, b in seen:
            assert a == b, f"torn read: a={a} b={b}"

    return check


def _demo_unverified_adopt(sched: schedules.Scheduler):
    """A verify-then-adopt TOCTOU: the reader CRC-checks the payload it
    fetched, then adopts a RE-READ of the store — a donor swapping the
    payload between the check and the adopt slips unverified bytes in
    (the dynamic twin of analyzer rule R9)."""
    good = b"committed-state"
    store = {"payload": good, "crc": zlib.crc32(good)}
    adopted: List[bytes] = []

    def donor():
        schedules.point("demo.donor_swap")
        store["payload"] = b"corrupt-state"

    def reader():
        data = store["payload"]
        schedules.point("demo.verify")
        if zlib.crc32(data) == store["crc"]:
            schedules.point("demo.adopt")
            adopted.append(store["payload"])  # BUG: re-read, not `data`

    sched.spawn("donor", donor)
    sched.spawn("reader", reader)

    def check():
        for blob in adopted:
            assert zlib.crc32(blob) == store["crc"], (
                f"adopted unverified bytes: {blob!r}"
            )

    return check


DEMO_SCENARIOS: Dict[str, schedules.Scenario] = {
    "demo-torn-read": _demo_torn_read,
    "demo-unverified-adopt": _demo_unverified_adopt,
}


# ---------------------------------------------------------------------------
# driver + CLI
# ---------------------------------------------------------------------------


def _open_schedule_incident(name: str, v: schedules.ScheduleViolation) -> str:
    from torchft_tpu import tracing

    return tracing.open_incident(
        "schedule", step=-1, quorum_id=-1,
        reason=f"{name}: {v.error} (replay: {v.token})",
    )


def explore_scenarios(
    names: Optional[Sequence[str]] = None,
    budget: Optional[int] = None,
    preemption_bounds: Optional[Sequence[int]] = None,
    random_runs: Optional[int] = None,
    seed: Optional[int] = None,
    emit: Optional[Callable[[str], None]] = None,
    incidents: bool = True,
    include_demos: bool = False,
) -> List[schedules.ExploreResult]:
    """Explores the named scenarios (default: every real-protocol one)
    under the ``TPUFT_EXPLORE_*`` budgets. Violations open a ``schedule``
    tracing incident so the journal dump correlates with the replay
    token."""
    registry = dict(SCENARIOS)
    if include_demos:
        registry.update(DEMO_SCENARIOS)
    if names:
        unknown = [n for n in names if n not in registry]
        if unknown:
            raise KeyError(
                f"unknown scenario(s): {', '.join(unknown)}; known: "
                + ", ".join(sorted(registry))
            )
        selected = {n: registry[n] for n in names}
    else:
        selected = dict(SCENARIOS)
    say = emit or (lambda line: None)
    results = []
    for name, scenario in selected.items():
        result = schedules.explore(
            scenario,
            name=name,
            budget=budget,
            preemption_bounds=preemption_bounds,
            random_runs=random_runs,
            seed=seed,
            stall_timeout=REAL_STALL_TIMEOUT,
        )
        if result.violation is not None:
            say(f"{name}: VIOLATION after {result.schedules_run} schedule(s)")
            say("  " + result.violation.format().replace("\n", "\n  "))
            if incidents:
                iid = _open_schedule_incident(name, result.violation)
                say(f"  incident: {iid}")
        else:
            say(
                f"{name}: ok ({result.schedules_run} schedule(s), "
                f"{result.tokens_seen} unique prefixes)"
            )
        results.append(result)
    return results


def replay_scenario(
    name: str, token: str
) -> Optional[schedules.ScheduleViolation]:
    """Replays ``token`` against ``name`` (real or demo scenario);
    returns the reproduced violation or None when the schedule passes."""
    registry = {**SCENARIOS, **DEMO_SCENARIOS}
    if name not in registry:
        raise KeyError(f"unknown scenario: {name}")
    return schedules.replay(
        registry[name], token, stall_timeout=REAL_STALL_TIMEOUT
    )


def run_explore_cli(
    scenario_names: Sequence[str],
    replay_token: Optional[str] = None,
    emit: Callable[[str], None] = print,
) -> int:
    """The ``python -m torchft_tpu.analysis --explore`` leg: explore (or
    replay) and return the process exit code (0 clean, 1 violation)."""
    if replay_token:
        if len(scenario_names) != 1:
            emit("--replay needs exactly one scenario name")
            return 2
        violation = replay_scenario(scenario_names[0], replay_token)
        if violation is None:
            emit(f"{scenario_names[0]}: schedule passed (no violation)")
            return 0
        emit(violation.format())
        _open_schedule_incident(scenario_names[0], violation)
        return 1
    results = explore_scenarios(
        names=scenario_names or None, emit=emit, include_demos=True
    )
    return 1 if any(not r.ok for r in results) else 0
