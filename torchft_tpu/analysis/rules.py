"""tpuft_check rules: CLAUDE.md invariants as AST properties.

R1–R8 are deliberately *lexical*: each proves what can be proven from one
function's source order and flags the rest, so a clean run is a real
guarantee at the granularity the rule states (and the runtime lockcheck
covers the interleavings the AST cannot see). R9–R11 (registered here,
implemented in :mod:`torchft_tpu.analysis.dataflow`) add an
intraprocedural dataflow layer over the same shared per-file ASTs.
Scoping: rules whose invariant binds specific layers consult
``Module.rel``; files outside the package (test fixtures, explicit CLI
paths) are always in scope, which is how the per-rule fixture tests
drive them.

| id                  | invariant (CLAUDE.md anchor)                        |
|---------------------|-----------------------------------------------------|
| step-boundary-escape| comm-layer worker threads / work callbacks funnel   |
|                     | errors (report_error / a Future / an error bucket), |
|                     | never raise past the step boundary                  |
| op-worker-self-wait | nothing that runs ON the PG op-worker thread may    |
|                     | wait on PG work (parallel/collectives.py:42 pool)   |
| lock-discipline     | registered-state mutations hold the RWLock writer;  |
|                     | commit barriers run provably outside it             |
| unjitted-optax      | optax updates go through one jitted dispatch        |
|                     | (optim.make_jit_update)                             |
| replica-axis-in-mesh| the replica axis is never a jax Mesh dim            |
| citation-lint       | docstring ``file.py:line`` citations parse and      |
|                     | resolve (reference tree when present)               |
| speculation-        | no pg.configure / send_checkpoint / sidecar staging |
| discipline          | / serving publish reachable inside an undrained     |
|                     | speculative window                                  |
| metric-doc-drift    | every emitted tpuft_* metric name has a METRICS.md  |
|                     | table row and every row a live emission site        |
| verify-before-adopt | wire bytes pass a CRC/digest/era sanitizer before   |
|                     | any adoption sink (taint pass, dataflow.py)         |
| era-fence           | checkpoint-serving route handlers consult the       |
|                     | staged quorum_id/era before answering               |
| stale-suppression   | every ``tpuft: allow`` comment still covers a live  |
|                     | finding of its rule                                 |
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from torchft_tpu.analysis import dataflow
from torchft_tpu.analysis.core import Finding, Module

__all__ = ["Rule", "ALL_RULES", "RULES_BY_ID"]


@dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    anchor: str  # CLAUDE.md / code anchor the invariant comes from
    checker: Callable[..., List[Finding]]

    def check(self, module: Module, reference_root: Optional[Path] = None) -> List[Finding]:
        return self.checker(module, reference_root=reference_root)


def _finding(module: Module, rule: str, node_line: int, message: str) -> Finding:
    return Finding(
        rule=rule,
        file=module.rel,
        line=node_line,
        message=message,
        context=module.line_at(node_line),
    )


def _func_defs(tree: ast.AST) -> Iterable[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _terminal_name(node: ast.AST) -> Optional[str]:
    """Rightmost identifier of a Name / attribute chain (``a.b.c`` -> "c",
    ``self._epoch`` -> "_epoch")."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _resolve_local_callable(
    module: Module, node: ast.AST
) -> Optional[ast.AST]:
    """Maps a Name / ``self.<method>`` reference to a def in this module;
    lambdas resolve to themselves."""
    if isinstance(node, ast.Lambda):
        return node
    name: Optional[str] = None
    if isinstance(node, ast.Name):
        name = node.id
    elif (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        name = node.attr
    if name is None:
        return None
    for fn in _func_defs(module.tree):
        if fn.name == name:  # type: ignore[union-attr]
            return fn
    return None


def _enclosing_functions(module: Module, node: ast.AST) -> List[ast.AST]:
    """Innermost-first chain of function defs containing ``node``."""
    chain: List[ast.AST] = []
    cursor = module.parents.get(node)
    while cursor is not None:
        if isinstance(cursor, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            chain.append(cursor)
        cursor = module.parents.get(cursor)
    return chain


# ---------------------------------------------------------------------------
# R1 step-boundary-escape
# ---------------------------------------------------------------------------

_R1_SCOPE_PREFIXES = ("torchft_tpu/parallel/", "torchft_tpu/checkpointing/")
_R1_SCOPE_FILES = ("torchft_tpu/ddp.py",)

# A handler "funnels" when its body visibly routes the error somewhere the
# step boundary can observe: the manager's error state, a Future, an error
# bucket, or at minimum the log (worker loops that must survive).
_R1_FUNNEL_CALLS = {
    "report_error",
    "set_exception",
    "with_error_handler",
    "exception",  # logger.exception
    "append",  # error-bucket pattern (accept_err.append(e), ...)
    "put",  # error queues
    "send",  # pipe-based error replies (parallel/baby.py)
    "record",  # flight recorder
}


def _handler_funnels(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Call):
            name = _terminal_name(node.func)
            if name in _R1_FUNNEL_CALLS:
                return True
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for target in targets:
                tname = _terminal_name(target)
                if tname and "err" in tname.lower():
                    return True
    return False


def _handler_catches_broadly(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    names = []
    probes = (
        handler.type.elts if isinstance(handler.type, ast.Tuple) else [handler.type]
    )
    for probe in probes:
        name = _terminal_name(probe)
        if name:
            names.append(name)
    return any(name in ("Exception", "BaseException") for name in names)


def _guarded_line_spans(fn: ast.AST) -> List[Tuple[int, int]]:
    """Line spans of try-bodies whose handlers both catch broadly and
    funnel — code inside them cannot raise past the worker."""
    spans: List[Tuple[int, int]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Try):
            if any(
                _handler_catches_broadly(h) and _handler_funnels(h)
                for h in node.handlers
            ):
                # The whole try statement counts: the handlers ARE the
                # funnel, and their own calls (err.append, logger) are the
                # mechanism, not an escape.
                spans.append(
                    (node.lineno, getattr(node, "end_lineno", node.lineno))
                )
    return spans


def _check_r1(module: Module, reference_root: Optional[Path] = None) -> List[Finding]:
    if module.in_package:
        if not (
            module.rel in _R1_SCOPE_FILES
            or any(module.rel.startswith(p) for p in _R1_SCOPE_PREFIXES)
        ):
            return []
    findings: List[Finding] = []
    # Collect dispatch targets: thread entry points and Work/Future done
    # callbacks. (Callables handed to executor.submit are excluded: the
    # returned Future captures their exception, which IS the funnel.)
    targets: List[Tuple[ast.AST, str]] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _terminal_name(node.func)
        if fname == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    resolved = _resolve_local_callable(module, kw.value)
                    if resolved is not None:
                        targets.append((resolved, "thread target"))
        elif fname == "add_done_callback" and node.args:
            resolved = _resolve_local_callable(module, node.args[0])
            if resolved is not None:
                targets.append((resolved, "done-callback"))
    seen: Set[int] = set()
    for fn, kind in targets:
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        if isinstance(fn, ast.Lambda):
            calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)]
            if calls:
                findings.append(
                    _finding(
                        module,
                        "step-boundary-escape",
                        fn.lineno,
                        f"lambda used as {kind} cannot funnel its errors; "
                        "use a def with a try/except routing into "
                        "report_error / a Future / an error bucket",
                    )
                )
            continue
        spans = _guarded_line_spans(fn)
        offending: Optional[ast.Call] = None
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if any(lo <= node.lineno <= hi for lo, hi in spans):
                continue
            # Skip calls living in NESTED defs (they run when called, on
            # whoever calls them — not necessarily this worker).
            enclosing = _enclosing_functions(module, node)
            if enclosing and enclosing[0] is not fn:
                continue
            offending = node
            break
        if offending is not None:
            findings.append(
                _finding(
                    module,
                    "step-boundary-escape",
                    offending.lineno,
                    f"{getattr(fn, 'name', '<lambda>')} runs as a {kind} but "
                    "this call is outside any try/except that funnels errors "
                    "(report_error / Future.set_exception / error bucket / "
                    "logger.exception) — an exception here escapes the step "
                    "boundary (manager.py report_error contract)",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# R2 op-worker-self-wait
# ---------------------------------------------------------------------------

_R2_OP_WORKER_SUBMIT_RECEIVERS = {"epoch", "_epoch"}


def _check_r2(module: Module, reference_root: Optional[Path] = None) -> List[Finding]:
    findings: List[Finding] = []

    def flag_waits(fn: ast.AST, context: str, allow_receiver: Optional[str]) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _terminal_name(node.func) not in ("wait", "result"):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            receiver = _terminal_name(node.func.value)
            if allow_receiver is not None and receiver == allow_receiver:
                # The callback's own (already-completed) future parameter.
                continue
            enclosing = _enclosing_functions(module, node)
            if enclosing and enclosing[0] is not fn:
                continue
            findings.append(
                _finding(
                    module,
                    "op-worker-self-wait",
                    node.lineno,
                    f"{context} must not block on .{_terminal_name(node.func)}(): "
                    "it runs on the single PG op-worker thread, and waiting "
                    "there on work this group enqueues deadlocks the worker "
                    "(parallel/collectives.py:42 — run pipelines on their own "
                    "pool)",
                )
            )

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _terminal_name(node.func)
        if fname in ("then", "add_done_callback") and node.args:
            resolved = _resolve_local_callable(module, node.args[0])
            if resolved is None:
                continue
            first_param: Optional[str] = None
            args_node = getattr(resolved, "args", None)
            if args_node is not None and args_node.args:
                first_param = args_node.args[0].arg
            flag_waits(
                resolved,
                f"callback passed to .{fname}()",
                allow_receiver=first_param,
            )
        elif fname == "submit" and isinstance(node.func, ast.Attribute):
            receiver = _terminal_name(node.func.value)
            if receiver in _R2_OP_WORKER_SUBMIT_RECEIVERS and node.args:
                resolved = _resolve_local_callable(module, node.args[0])
                if resolved is not None:
                    flag_waits(
                        resolved,
                        "callable submitted to the PG op-worker",
                        allow_receiver=None,
                    )
    return findings


# ---------------------------------------------------------------------------
# R3 lock-discipline
# ---------------------------------------------------------------------------

# Attributes that hold state registered with the manager (the state-dict
# registry the RWLock guards): Optimizer/LocalSGD/DiLoCo/_Fragment owned
# state. Assigning them without the writer tears a concurrent checkpoint.
_R3_REGISTERED_ATTRS = {
    "params",
    "opt_state",
    "inner_opt_state",
    "outer_opt_state",
    "backup",
    "_leaves",
}
_R3_ACQUIRES = {"disallow_state_dict_read", "w_acquire", "w_lock"}
_R3_RELEASES = {"allow_state_dict_read", "w_release"}
_R3_BARRIERS = {"should_commit", "should_commit_async", "speculative_commit_async"}


def _check_r3(module: Module, reference_root: Optional[Path] = None) -> List[Finding]:
    findings: List[Finding] = []
    for fn in _func_defs(module.tree):
        name = fn.name  # type: ignore[union-attr]
        if name == "__init__":
            continue  # construction precedes sharing
        events: List[Tuple[int, str, ast.AST]] = []
        for node in ast.walk(fn):
            enclosing = _enclosing_functions(module, node)
            if enclosing and enclosing[0] is not fn:
                continue  # nested defs run on their caller's schedule
            if isinstance(node, ast.Call):
                cname = _terminal_name(node.func)
                if cname in _R3_ACQUIRES:
                    events.append((node.lineno, "acquire", node))
                    if cname == "w_lock":
                        # `with x.w_lock():` — lexical release at the end
                        # of the with body.
                        parent = module.parents.get(node)
                        grand = module.parents.get(parent) if parent is not None else None
                        for probe in (parent, grand):
                            if isinstance(probe, ast.With):
                                events.append(
                                    (getattr(probe, "end_lineno", node.lineno), "release", node)
                                )
                                break
                elif cname in _R3_RELEASES:
                    events.append((node.lineno, "release", node))
                elif cname in _R3_BARRIERS:
                    events.append((node.lineno, "barrier", node))
                elif cname == "result" and isinstance(node.func, ast.Attribute):
                    receiver = _terminal_name(node.func.value) or ""
                    if "commit" in receiver:
                        events.append((node.lineno, "barrier", node))
            elif isinstance(node, ast.Assign):
                for target in node.targets:
                    elts = target.elts if isinstance(target, ast.Tuple) else [target]
                    for elt in elts:
                        if (
                            isinstance(elt, ast.Attribute)
                            and isinstance(elt.value, ast.Name)
                            and elt.value.id == "self"
                            and elt.attr in _R3_REGISTERED_ATTRS
                        ):
                            events.append((node.lineno, "mutate", node))
                            break
        if not events:
            continue
        events.sort(key=lambda e: e[0])
        depth = 0
        for lineno, kind, _node in events:
            if kind == "acquire":
                depth += 1
            elif kind == "release":
                depth = max(0, depth - 1)
            elif kind == "mutate" and depth == 0:
                findings.append(
                    _finding(
                        module,
                        "lock-discipline",
                        lineno,
                        f"{name} rebinds registered state without the "
                        "state-dict writer (manager.disallow_state_dict_read) "
                        "— a concurrent checkpoint capture can read a torn "
                        "params/opt pair (manager.py RWLock registry)",
                    )
                )
            elif kind == "barrier" and depth > 0:
                findings.append(
                    _finding(
                        module,
                        "lock-discipline",
                        lineno,
                        f"{name} reaches a commit barrier while lexically "
                        "inside the state-dict write lock — barriers must "
                        "run unlocked (they may heal, and peer serves need "
                        "the read lock meanwhile)",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# R4 unjitted-optax
# ---------------------------------------------------------------------------

_R4_TX_NAMES = {
    "tx",
    "_tx",
    "inner_tx",
    "_inner_tx",
    "outer_tx",
    "_outer_tx",
}


def _jitted_names(module: Module) -> Set[str]:
    """Function names that get jax.jit-wrapped anywhere in the module."""
    jitted: Set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and _terminal_name(node.func) == "jit":
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    jitted.add(arg.id)
    return jitted


def _has_jit_decorator(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        probe = dec.func if isinstance(dec, ast.Call) else dec
        if _terminal_name(probe) == "jit":
            return True
        if isinstance(dec, ast.Call):
            for arg in dec.args:
                if _terminal_name(arg) == "jit":
                    return True
    return False


def _check_r4(module: Module, reference_root: Optional[Path] = None) -> List[Finding]:
    findings: List[Finding] = []
    jitted = _jitted_names(module)

    def in_jitted_context(node: ast.AST) -> bool:
        for fn in _enclosing_functions(module, node):
            name = getattr(fn, "name", None)
            if name is None:
                continue
            if name in jitted or name.startswith("make_jit") or _has_jit_decorator(fn):
                return True
        return False

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        is_tx_update = (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "update"
            and _terminal_name(node.func.value) in _R4_TX_NAMES
        )
        is_apply_updates = (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "apply_updates"
            and _terminal_name(node.func.value) == "optax"
        )
        if not (is_tx_update or is_apply_updates):
            continue
        if in_jitted_context(node):
            continue
        what = "optimizer transform .update()" if is_tx_update else "optax.apply_updates"
        findings.append(
            _finding(
                module,
                "unjitted-optax",
                node.lineno,
                f"{what} dispatched outside a jitted step — unjitted optax "
                "issues hundreds of tiny device ops (~100x slower on the "
                "tunneled device); route through optim.make_jit_update / "
                "make_jit_fused_step",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# R5 replica-axis-in-mesh
# ---------------------------------------------------------------------------

_R5_RESERVED_AXES = {"replica", "replicas", "dp_replica"}


def _literal_axis_names(node: ast.AST) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        names: List[str] = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                names.append(elt.value)
            else:
                return None  # non-literal member: cannot prove
        return names
    return None


def _check_r5(module: Module, reference_root: Optional[Path] = None) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _terminal_name(node.func)
        if fname not in ("Mesh", "make_mesh"):
            continue
        axis_arg: Optional[ast.AST] = None
        if len(node.args) >= 2:
            axis_arg = node.args[1]
        for kw in node.keywords:
            if kw.arg == "axis_names":
                axis_arg = kw.value
        if axis_arg is None:
            continue
        names = _literal_axis_names(axis_arg)
        if not names:
            continue
        bad = [n for n in names if n in _R5_RESERVED_AXES]
        if bad:
            findings.append(
                _finding(
                    module,
                    "replica-axis-in-mesh",
                    node.lineno,
                    f"Mesh axis names {bad} include the replica axis: the "
                    "replica dimension must stay OUT of the jax mesh so "
                    "membership changes never recompile XLA programs "
                    "(parallel/mesh.py FTMesh contract)",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# R6 citation-lint
# ---------------------------------------------------------------------------

_CITATION_RE = re.compile(
    r"(?P<path>[A-Za-z_][\w./-]*\.(?:py|rs|h|cc|cpp|proto))"
    r":(?P<line>\d+)(?:-(?P<end>\d+))?"
)


def _docstrings(module: Module) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    for node in ast.walk(module.tree):
        if isinstance(
            node, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            body = getattr(node, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.append((body[0].lineno, body[0].value.value))
    return out


def _file_line_count(path: Path) -> Optional[int]:
    try:
        with path.open("rb") as fh:
            return sum(1 for _ in fh)
    except OSError:
        return None


def _resolve_citation(
    cited: str, module: Module, reference_root: Path, is_reference: bool
) -> Tuple[Optional[Path], bool]:
    """(resolved file, resolution_was_attempted).

    Citations marked ``is_reference`` (the docstring says "reference"
    nearby — the CLAUDE.md citation convention) resolve ONLY against the
    reference snapshot, and are skipped cleanly when it is absent: a
    same-named repo file must not shadow the reference's line numbering.
    Repo-internal citations resolve against the repo immediately."""
    from torchft_tpu.analysis.core import PACKAGE_ROOT, REPO_ROOT

    if cited.startswith("/"):
        p = Path(cited)
        if str(p).startswith(str(reference_root)) and not reference_root.exists():
            return None, False  # snapshot absent: cannot disprove
        return (p if p.exists() else None), True
    if is_reference:
        if not reference_root.exists():
            return None, False
        for sub in ("torchft", "", "src"):
            candidate = reference_root / sub / cited
            if candidate.exists():
                return candidate, True
        return None, True
    for base in (PACKAGE_ROOT, REPO_ROOT, module.path.parent):
        candidate = base / cited
        if candidate.exists():
            return candidate, True
    if reference_root.exists():
        for sub in ("", "torchft", "src"):
            candidate = reference_root / sub / cited
            if candidate.exists():
                return candidate, True
        return None, True
    return None, False


def _check_r6(module: Module, reference_root: Optional[Path] = None) -> List[Finding]:
    assert reference_root is not None
    findings: List[Finding] = []
    for start_line, text in _docstrings(module):
        for match in _CITATION_RE.finditer(text):
            cited = match.group("path")
            line_no = int(match.group("line"))
            end_no = int(match.group("end")) if match.group("end") else None
            # Docstring line offset: count newlines before the match.
            at_line = start_line + text[: match.start()].count("\n")
            token = match.group(0)
            if end_no is not None and end_no < line_no:
                findings.append(
                    _finding(
                        module,
                        "citation-lint",
                        at_line,
                        f"citation {token!r} has an inverted line range",
                    )
                )
                continue
            preceding = text[max(0, match.start() - 200) : match.start()]
            is_reference = "reference" in preceding.lower()
            resolved, attempted = _resolve_citation(
                cited, module, reference_root, is_reference
            )
            if resolved is None:
                if attempted:
                    findings.append(
                        _finding(
                            module,
                            "citation-lint",
                            at_line,
                            f"citation {token!r} resolves nowhere (repo or "
                            f"reference snapshot at {reference_root})",
                        )
                    )
                # Resolution not attempted (reference snapshot absent):
                # skip cleanly — cannot disprove.
                continue
            count = _file_line_count(resolved)
            if count is not None and line_no > count:
                findings.append(
                    _finding(
                        module,
                        "citation-lint",
                        at_line,
                        f"citation {token!r} is stale: {resolved.name} has "
                        f"only {count} lines",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# R7 speculation-discipline
# ---------------------------------------------------------------------------

# The invariant (CLAUDE.md pipelined-commit paragraph): a joiner must never
# heal from — and the wire must never reconfigure under — an undrained
# speculative window. Lexically: inside any function that reconfigures the
# replica PG, serves a donor checkpoint, or stages a heal-serving sidecar
# snapshot, a window drain must come FIRST. Scoped to the manager (the one
# place those calls legitimately live on the quorum path); fixtures and
# explicit CLI paths are always in scope.
_R7_SCOPE_FILES = ("torchft_tpu/manager.py",)
_R7_DRAIN_CALLS = {
    "_run_quorum_drain_hooks",
    "_drain_pipeline_for_quorum_change",
    "flush_pipeline",
}
_R7_HOOK_ITER_MARK = "quorum_change_hook"
_R7_PG_RECEIVERS = {"pg", "_pg"}
# stage = sidecar heal-part staging; publish = the serving plane's
# committed-weights publication (Manager._maybe_publish) — a publish
# sampling an undrained window would hand READERS speculative state,
# the serving twin of a donor send doing the same to a joiner.
_R7_UNSAFE_CALLS = {"send_checkpoint", "stage", "publish"}


def _check_r7(module: Module, reference_root: Optional[Path] = None) -> List[Finding]:
    if module.in_package and module.rel not in _R7_SCOPE_FILES:
        return []
    findings: List[Finding] = []
    for fn in _func_defs(module.tree):
        drains: List[int] = []
        unsafe: List[Tuple[int, str]] = []
        for node in ast.walk(fn):
            enclosing = _enclosing_functions(module, node)
            if enclosing and enclosing[0] is not fn:
                continue  # nested defs run on their caller's schedule
            if isinstance(node, ast.For):
                # The manager's inline drain shape: iterating the
                # registered quorum-change hooks and calling each.
                iter_name = _terminal_name(node.iter) or ""
                if _R7_HOOK_ITER_MARK in iter_name and any(
                    isinstance(inner, ast.Call) for inner in ast.walk(node)
                ):
                    drains.append(node.lineno)
                continue
            if not isinstance(node, ast.Call):
                continue
            cname = _terminal_name(node.func)
            if cname in _R7_DRAIN_CALLS:
                drains.append(node.lineno)
            elif (
                cname == "configure"
                and isinstance(node.func, ast.Attribute)
                and _terminal_name(node.func.value) in _R7_PG_RECEIVERS
            ):
                unsafe.append((node.lineno, "pg.configure (wire reconfigure)"))
            elif cname in _R7_UNSAFE_CALLS:
                label = (
                    "publish (serving-plane publication)"
                    if cname == "publish"
                    else f"{cname} (donor/heal staging)"
                )
                unsafe.append((node.lineno, label))
        for lineno, what in unsafe:
            if any(drain_line < lineno for drain_line in drains):
                continue
            findings.append(
                _finding(
                    module,
                    "speculation-discipline",
                    lineno,
                    f"{fn.name} reaches {what} with no speculative-window "  # type: ignore[union-attr]
                    "drain before it: a membership change or donor send "
                    "inside an undrained commit-pipeline window lets a "
                    "joiner heal from (or the wire reconfigure under) "
                    "uncommitted speculative state — drain first "
                    "(Manager._run_quorum_drain_hooks; CLAUDE.md pipelined-"
                    "commit invariant)",
                )
            )
    return findings


# --- R8: metric-doc-drift ---------------------------------------------------
# METRICS.md is the canonical metric registry (metrics.py module docstring):
# every metric name the package emits must have a table row, and every table
# row must correspond to a live emission site — else dashboards, the bench's
# ft_phase_* fields, and fleet_status cells silently drift from the code.
# Anchored at torchft_tpu/metrics.py so the repo-wide scan runs exactly once
# per analysis (the rule is a whole-tree property, not a per-module one);
# findings anchor at the offending emission site / METRICS.md row, so the
# baseline is the sanctioned escape hatch for legacy gaps.
_R8_SCOPE_FILE = "torchft_tpu/metrics.py"
_R8_DOC_FILE = "METRICS.md"
_R8_EMIT_RE = re.compile(
    r"metrics\.(?:inc|observe|set_gauge|timer|counter|gauge|histogram)\(\s*"
    r'"(tpuft_[a-z0-9_]+)"'
)
_R8_ROW_RE = re.compile(r"\| `(tpuft_[a-z0-9_]+)` \|")


def _check_r8(module: Module, reference_root: Optional[Path] = None) -> List[Finding]:
    if module.rel != _R8_SCOPE_FILE:
        return []
    from torchft_tpu.analysis import core

    repo = core.REPO_ROOT
    findings: List[Finding] = []
    emitted: Dict[str, Tuple[str, int, str]] = {}
    for py in sorted((repo / "torchft_tpu").rglob("*.py")):
        if "__pycache__" in py.parts or py.name == "tpuft_pb2.py":
            continue
        try:
            text = py.read_text(encoding="utf-8")
        except OSError:
            continue
        names = set(_R8_EMIT_RE.findall(text))
        if not names:
            continue
        rel = py.relative_to(repo).as_posix()
        file_lines = text.splitlines()
        for name in names:
            if name in emitted:
                continue
            anchor, context = 1, ""
            for lineno, line in enumerate(file_lines, start=1):
                if f'"{name}"' in line:
                    anchor, context = lineno, line.strip()
                    break
            emitted[name] = (rel, anchor, context)

    doc_path = repo / _R8_DOC_FILE
    if not doc_path.exists():
        return [
            Finding(
                rule="metric-doc-drift",
                file=_R8_DOC_FILE,
                line=1,
                message=(
                    f"{_R8_DOC_FILE} is missing: it is the canonical metric "
                    f"registry for {len(emitted)} emitted metric name(s)"
                ),
                context=_R8_DOC_FILE,
            )
        ]
    table: Dict[str, Tuple[int, str]] = {}
    for lineno, line in enumerate(doc_path.read_text().splitlines(), start=1):
        for name in _R8_ROW_RE.findall(line):
            table.setdefault(name, (lineno, line.strip()))

    for name in sorted(set(emitted) - set(table)):
        rel, lineno, context = emitted[name]
        findings.append(
            Finding(
                rule="metric-doc-drift",
                file=rel,
                line=lineno,
                message=(
                    f"metric {name} is emitted here but has no METRICS.md "
                    "row — document it (name, kind, labels, emitted-from, "
                    "meaning) or dashboards silently drift from the code"
                ),
                context=context or name,
            )
        )
    for name in sorted(set(table) - set(emitted)):
        lineno, context = table[name]
        findings.append(
            Finding(
                rule="metric-doc-drift",
                file=_R8_DOC_FILE,
                line=lineno,
                message=(
                    f"METRICS.md documents {name} but no live emission site "
                    "remains in torchft_tpu/ — delete the row or restore the "
                    "metric"
                ),
                context=context or name,
            )
        )
    return findings


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

ALL_RULES: Sequence[Rule] = (
    Rule(
        id="step-boundary-escape",
        summary="comm-layer worker threads and done-callbacks funnel errors, never raise",
        anchor="CLAUDE.md 'Comm-layer errors funnel into Manager.report_error'",
        checker=_check_r1,
    ),
    Rule(
        id="op-worker-self-wait",
        summary="nothing running on the PG op-worker thread waits on PG work",
        anchor="parallel/collectives.py:42 (dedicated pipeline pool)",
        checker=_check_r2,
    ),
    Rule(
        id="lock-discipline",
        summary="registered-state mutations hold the writer; barriers run unlocked",
        anchor="CLAUDE.md 'mutations take the state-dict write lock; commit barriers run unlocked'",
        checker=_check_r3,
    ),
    Rule(
        id="unjitted-optax",
        summary="optax updates go through one jitted dispatch",
        anchor="CLAUDE.md 'Optax updates must go through one jitted dispatch'",
        checker=_check_r4,
    ),
    Rule(
        id="replica-axis-in-mesh",
        summary="the replica axis is never a jax Mesh dimension",
        anchor="CLAUDE.md 'The replica axis is NOT a jax mesh dim'",
        checker=_check_r5,
    ),
    Rule(
        id="citation-lint",
        summary="docstring file.py:line citations parse and resolve",
        anchor="CLAUDE.md conventions ('Docstrings cite reference behavior')",
        checker=_check_r6,
    ),
    Rule(
        id="speculation-discipline",
        summary="no pg.configure / donor send / heal staging / serving publish inside an undrained speculative window",
        anchor="CLAUDE.md 'quorum membership changes drain the FULL window ... BEFORE pg.configure / any donor send'",
        checker=_check_r7,
    ),
    Rule(
        id="metric-doc-drift",
        summary="every emitted tpuft_* metric has a METRICS.md row and vice versa",
        anchor="metrics.py module docstring ('canonical metric names ... tabulated in METRICS.md')",
        checker=_check_r8,
    ),
    Rule(
        id="verify-before-adopt",
        summary="wire bytes pass a CRC/digest/era sanitizer before any adoption sink",
        anchor="CLAUDE.md 'Corrupt/stale/stalled donors funnel into report_error — never adopted state'",
        checker=dataflow.check_verify_before_adopt,
    ),
    Rule(
        id="era-fence",
        summary="checkpoint-serving route handlers consult the staged quorum_id/era",
        anchor="CLAUDE.md 'quorum-era tags on meta and chunk URLs' (http_transport do_GET 409 fence)",
        checker=dataflow.check_era_fence,
    ),
    Rule(
        id="stale-suppression",
        summary="every tpuft allow comment still covers a live finding of its rule",
        anchor="core.py suppression contract (the inventory must not rot)",
        checker=dataflow.check_stale_suppression,
    ),
)

RULES_BY_ID: Dict[str, Rule] = {rule.id: rule for rule in ALL_RULES}
