"""Environment-driven Manager bootstrap.

The reference rides torchelastic/torchrun for process bootstrap (MASTER_ADDR,
RANK, WORLD_SIZE + its TCPStore); this module is the tpuft equivalent:
:func:`init_manager` reads the topology env set by ``torchft_tpu.launch``
(or by hand) and wires the rendezvous store correctly for both single-host
and multi-host replica groups:

  REPLICA_GROUP_ID       this group's id (informational / replica_id prefix)
  GROUP_RANK             this process's rank within the group (default 0)
  GROUP_WORLD_SIZE       processes per group (default 1)
  TPUFT_LIGHTHOUSE       lighthouse address (rank 0 needs it)
  TPUFT_STORE_ADDR       group store "host:port". Rank 0 binds a StoreServer
                         here (or an ephemeral port when unset); other ranks
                         connect to it.
  TPUFT_JAX_COORDINATOR  optional "host:port": when set, the group's ranks
                         form one jax.distributed cluster (multi-host mesh
                         inside the replica group — the TPU-pod topology)
                         before the manager starts.

Usage::

    pg = ProcessGroupNative()
    manager, store_server = init_manager(pg, min_replica_size=1)
    ...
    manager.shutdown(); (store_server and store_server.shutdown())
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import ProcessGroup
from torchft_tpu.parallel.store import StoreClient, StoreServer

__all__ = ["init_manager", "init_group_jax_cluster"]


def init_group_jax_cluster(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Joins this group's ranks into one jax.distributed cluster so the
    intra-group mesh spans all the group's hosts/chips (defaults read the
    topology env). Returns whether initialization ran. Must be called before
    any jax backend use; no-op when no coordinator is configured."""
    coordinator = coordinator or os.environ.get("TPUFT_JAX_COORDINATOR")
    if not coordinator:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=(
            num_processes
            if num_processes is not None
            else int(os.environ.get("GROUP_WORLD_SIZE", "1"))
        ),
        process_id=(
            process_id
            if process_id is not None
            else int(os.environ.get("GROUP_RANK", "0"))
        ),
    )
    return True


def _wait_for_store(store_addr: str, timeout: float) -> None:
    """Polls until rank 0's store accepts connections: ranks launch
    concurrently, so a non-zero rank routinely dials before rank 0 binds."""
    import socket
    import time

    host, _, port = store_addr.rpartition(":")
    host = host.strip("[]") or "localhost"
    deadline = time.monotonic() + timeout
    last_error: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, int(port)), timeout=2.0):
                return
        except OSError as e:
            last_error = e
            time.sleep(0.2)
    raise TimeoutError(
        f"group store at {store_addr} not reachable within {timeout}s: {last_error}"
    )


def init_manager(
    pg: ProcessGroup,
    min_replica_size: int,
    replica_id: Optional[str] = None,
    group_rank: Optional[int] = None,
    group_world_size: Optional[int] = None,
    store_addr: Optional[str] = None,
    **manager_kwargs: Any,
) -> Tuple[Manager, Optional[StoreServer]]:
    """Builds the group store per topology (explicit args override the env)
    and returns (manager, store_server-or-None). The caller owns both
    lifecycles; only group rank 0 gets a server instance."""
    group_rank = (
        group_rank if group_rank is not None else int(os.environ.get("GROUP_RANK", "0"))
    )
    group_world_size = (
        group_world_size
        if group_world_size is not None
        else int(os.environ.get("GROUP_WORLD_SIZE", "1"))
    )
    group_id = os.environ.get("REPLICA_GROUP_ID", "0")
    store_addr = store_addr or os.environ.get("TPUFT_STORE_ADDR")

    store_server: Optional[StoreServer] = None
    if group_rank == 0:
        bind = "[::]:0"
        if store_addr:
            _, _, port = store_addr.rpartition(":")
            bind = f"[::]:{port}"
        store_server = StoreServer(bind)
        # Advertise the operator-provided address when given: gethostname()
        # may not be routable across hosts, which is exactly why the
        # operator would pin TPUFT_STORE_ADDR to an IP.
        advertised = store_addr if store_addr else store_server.address()
    else:
        if not store_addr:
            raise ValueError(
                "GROUP_RANK != 0 requires TPUFT_STORE_ADDR (or store_addr=) "
                "pointing at group rank 0's store"
            )
        advertised = store_addr
        _wait_for_store(advertised, timeout=float(manager_kwargs.get("connect_timeout", 60.0)))

    manager = Manager(
        pg=pg,
        min_replica_size=min_replica_size,
        store=StoreClient(advertised),
        store_addr=advertised,
        group_rank=group_rank,
        group_world_size=group_world_size,
        replica_id=replica_id or f"group_{group_id}",
        **manager_kwargs,
    )
    return manager, store_server
