"""Checkpoint transports: live peer-to-peer healing of parameter pytrees.

Two axes (reference: SURVEY.md §5 checkpoint/resume):
 (a) live healing via :class:`CheckpointTransport` — peer-to-peer, never
     touches disk;
 (b) user periodic checkpoints — persist model/optim *and* the manager
     state_dict (step/batches_committed), e.g. with orbax.
"""

from torchft_tpu.checkpointing.http_transport import (
    HealChecksumError,
    HealEraMismatch,
    HealIntegrityError,
    HealStalledError,
    HTTPTransport,
    heal_delta_enabled,
    heal_stripe_enabled,
    heal_stripe_max_donors,
)
from torchft_tpu.checkpointing.pg_transport import PGTransport
from torchft_tpu.checkpointing.serve_child import (
    ServeChild,
    ServeChildCrashed,
    ServeChildUnavailable,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport

__all__ = [
    "CheckpointTransport",
    "HTTPTransport",
    "PGTransport",
    "HealChecksumError",
    "HealEraMismatch",
    "HealIntegrityError",
    "HealStalledError",
    "ServeChild",
    "ServeChildCrashed",
    "ServeChildUnavailable",
    "heal_delta_enabled",
    "heal_stripe_enabled",
    "heal_stripe_max_donors",
]
