"""Readers-writer lock with timeouts.

Guards the live state_dict against concurrent optimizer mutation while a
checkpoint is being served (reference: checkpointing/_rwlock.py:41-131,
used at manager.py:341-353 and local_sgd.py:112-128). Writer-preferring:
a waiting writer blocks new readers.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Generator

from torchft_tpu.utils import lockcheck

__all__ = ["RWLock"]


class RWLock:
    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0
        # Lock-order-detector identity: every RWLock created at one source
        # line shares a node in the order graph (lockcheck docs). The
        # LOGICAL reader/writer holds are reported below — the internal
        # condition's microsecond holds would hide the real hold window.
        self._lc_site = lockcheck.creation_site(skip=2) + "[RWLock]"

    def r_acquire(self, timeout: float = -1) -> bool:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: not self._writer and self._writers_waiting == 0,
                timeout=None if timeout < 0 else timeout,
            )
            if not ok:
                return False
            self._readers += 1
        try:
            lockcheck.note_acquired(self, self._lc_site)
        except BaseException:
            self.r_release()
            raise
        return True

    def r_release(self) -> None:
        lockcheck.note_released(self)
        with self._cond:
            assert self._readers > 0, "r_release without matching r_acquire"
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def w_acquire(self, timeout: float = -1) -> bool:
        with self._cond:
            self._writers_waiting += 1
            try:
                ok = self._cond.wait_for(
                    lambda: not self._writer and self._readers == 0,
                    timeout=None if timeout < 0 else timeout,
                )
                if not ok:
                    return False
                self._writer = True
            finally:
                self._writers_waiting -= 1
        try:
            lockcheck.note_acquired(self, self._lc_site)
        except BaseException:
            self.w_release()
            raise
        return True

    def w_release(self) -> None:
        lockcheck.note_released(self)
        with self._cond:
            assert self._writer, "w_release without matching w_acquire"
            self._writer = False
            self._cond.notify_all()

    @contextmanager
    def r_lock(self, timeout: float = -1) -> Generator[None, None, None]:
        if not self.r_acquire(timeout):
            raise TimeoutError(f"read lock not acquired within {timeout}s")
        try:
            yield
        finally:
            self.r_release()

    @contextmanager
    def w_lock(self, timeout: float = -1) -> Generator[None, None, None]:
        if not self.w_acquire(timeout):
            raise TimeoutError(f"write lock not acquired within {timeout}s")
        try:
            yield
        finally:
            self.w_release()
