"""Streaming (de)serialization of state pytrees.

Wire format (role of the reference's ``_streaming_save/_load``,
checkpointing/_serialization.py): a pickled header describing the pytree
structure and per-leaf array metadata, followed by the raw array buffers in
order. Array leaves stream as raw bytes (no pickle copy of the payload);
non-array leaves ride in the header. jax arrays are staged device→host and
come back as numpy — the caller is responsible for any device_put.
"""

from __future__ import annotations

import io
import pickle
import struct
from dataclasses import dataclass
from typing import Any, BinaryIO, List, Optional, Tuple

import numpy as np

from torchft_tpu._safe_pickle import safe_loads

__all__ = [
    "save_state_dict",
    "load_state_dict",
    "state_dict_meta",
    "ArrayMeta",
    "ShardedLeaf",
    "ShardedLeafMeta",
]

_LEN = struct.Struct("!Q")
_MAGIC = b"TPFT1\n"


@dataclass
class ArrayMeta:
    shape: Tuple[int, ...]
    dtype: str  # np.dtype name (ml_dtypes names resolve via registry)
    nbytes: int


@dataclass
class ShardedLeaf:
    """Host capture of a multi-host-sharded jax.Array: only this process's
    addressable shards (each rank serves/receives its own shard of the
    state, the per-rank transport contract). Reassembled on the receiver
    against its matching local sharding (optim.Optimizer._load_state_dict).
    """

    global_shape: Tuple[int, ...]
    dtype: str
    # Per-shard ((start, stop) per dim, host array) in index order.
    shards: List[Tuple[Tuple[Tuple[int, int], ...], Any]]

    @staticmethod
    def index_key(index, shape) -> Tuple[Tuple[int, int], ...]:
        return tuple(
            (sl.start or 0, sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(index, shape)
        )


@dataclass
class ShardedLeafMeta:
    """Header entry for a ShardedLeaf whose shard buffers ride the raw-bytes
    section (large multi-host states must stream, not pickle)."""

    global_shape: Tuple[int, ...]
    dtype: str
    shard_keys: List[Tuple[Tuple[int, int], ...]]
    shard_shapes: List[Tuple[int, ...]]
    shard_nbytes: List[int]


def _to_host(leaf: Any) -> Any:
    """Stages array-like leaves to host numpy; passes others through.
    Multi-host sharded arrays (remote shards not addressable) capture only
    the local shards as a :class:`ShardedLeaf`."""
    if isinstance(leaf, np.ndarray):
        return leaf
    if hasattr(leaf, "addressable_shards") and hasattr(leaf, "is_fully_addressable"):
        if not leaf.is_fully_addressable:
            shards = sorted(
                (
                    (ShardedLeaf.index_key(s.index, leaf.shape), np.asarray(s.data))
                    for s in leaf.addressable_shards
                ),
                key=lambda kv: kv[0],
            )
            # Replicated copies on multiple local devices dedupe by index.
            deduped = []
            seen = set()
            for key, data in shards:
                if key not in seen:
                    seen.add(key)
                    deduped.append((key, data))
            return ShardedLeaf(tuple(leaf.shape), np.dtype(leaf.dtype).name, deduped)
    # jax.Array without importing jax at module load.
    if hasattr(leaf, "__array__") and hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
        return np.asarray(leaf)
    return leaf


def _flatten(state_dict: Any) -> Tuple[List[Any], Any]:
    import jax

    return jax.tree_util.tree_flatten(state_dict)


def state_dict_meta(state_dict: Any) -> Tuple[Any, List[Optional[ArrayMeta]], List[Any]]:
    """Returns (treedef, per-leaf meta, host leaves). Metas are ArrayMeta for
    plain arrays, ShardedLeafMeta for multi-host shard captures, None for
    header-riding (pickled) leaves."""
    leaves, treedef = _flatten(state_dict)
    leaves = [_to_host(leaf) for leaf in leaves]
    metas: List[Optional[ArrayMeta]] = []
    for leaf in leaves:
        if isinstance(leaf, np.ndarray):
            leaf_c = np.ascontiguousarray(leaf)
            metas.append(ArrayMeta(leaf_c.shape, leaf_c.dtype.name, leaf_c.nbytes))
        elif isinstance(leaf, ShardedLeaf):
            metas.append(
                ShardedLeafMeta(
                    leaf.global_shape,
                    leaf.dtype,
                    [key for key, _ in leaf.shards],
                    [tuple(data.shape) for _, data in leaf.shards],
                    [int(np.ascontiguousarray(data).nbytes) for _, data in leaf.shards],
                )
            )
        else:
            metas.append(None)
    return treedef, metas, leaves


def save_state_dict(state_dict: Any, stream: BinaryIO) -> None:
    treedef, metas, leaves = state_dict_meta(state_dict)
    non_array = [leaf for leaf, meta in zip(leaves, metas) if meta is None]
    header = pickle.dumps((treedef, metas, non_array))
    stream.write(_MAGIC)
    stream.write(_LEN.pack(len(header)))
    stream.write(header)
    for leaf, meta in zip(leaves, metas):
        if isinstance(meta, ArrayMeta):
            stream.write(np.ascontiguousarray(leaf).tobytes())
        elif isinstance(meta, ShardedLeafMeta):
            for _, data in leaf.shards:
                stream.write(np.ascontiguousarray(data).tobytes())


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def load_state_dict(stream: BinaryIO) -> Any:
    import jax

    magic = stream.read(len(_MAGIC))
    if magic != _MAGIC:
        raise ValueError("bad checkpoint stream magic")
    (header_len,) = _LEN.unpack(stream.read(_LEN.size))
    treedef, metas, non_array = safe_loads(stream.read(header_len))
    non_array_iter = iter(non_array)
    leaves = []
    for meta in metas:
        if meta is None:
            leaves.append(next(non_array_iter))
        elif isinstance(meta, ShardedLeafMeta):
            dtype = _resolve_dtype(meta.dtype)
            shards = []
            for key, shape, nbytes in zip(
                meta.shard_keys, meta.shard_shapes, meta.shard_nbytes
            ):
                buf = stream.read(nbytes)
                if len(buf) != nbytes:
                    raise EOFError("truncated checkpoint stream (sharded leaf)")
                shards.append((key, np.frombuffer(buf, dtype=dtype).reshape(shape).copy()))
            leaves.append(ShardedLeaf(meta.global_shape, meta.dtype, shards))
        else:
            dtype = _resolve_dtype(meta.dtype)
            buf = stream.read(meta.nbytes)
            if len(buf) != meta.nbytes:
                raise EOFError(
                    f"truncated checkpoint stream: wanted {meta.nbytes} bytes, got {len(buf)}"
                )
            leaves.append(np.frombuffer(buf, dtype=dtype).reshape(meta.shape).copy())
    return jax.tree_util.tree_unflatten(treedef, leaves)


def dumps(state_dict: Any) -> bytes:
    buf = io.BytesIO()
    save_state_dict(state_dict, buf)
    return buf.getvalue()


def loads(data: bytes) -> Any:
    return load_state_dict(io.BytesIO(data))
