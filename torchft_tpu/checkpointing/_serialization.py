"""Streaming (de)serialization of state pytrees.

Wire format (role of the reference's ``_streaming_save/_load``,
checkpointing/_serialization.py): a pickled header describing the pytree
structure and per-leaf array metadata, followed by the raw array buffers in
order. Array leaves stream as raw bytes (no pickle copy of the payload);
non-array leaves ride in the header. jax arrays are staged device→host and
come back as numpy — the caller is responsible for any device_put.
"""

from __future__ import annotations

import io
import pickle
import struct
from dataclasses import dataclass
from typing import Any, BinaryIO, List, Optional, Tuple

import numpy as np

from torchft_tpu._safe_pickle import safe_loads

__all__ = [
    "save_state_dict",
    "load_state_dict",
    "state_dict_meta",
    "ArrayMeta",
    "ShardedLeaf",
    "ShardedLeafMeta",
]

_LEN = struct.Struct("!Q")
_MAGIC = b"TPFT1\n"


@dataclass
class ArrayMeta:
    shape: Tuple[int, ...]
    dtype: str  # np.dtype name (ml_dtypes names resolve via registry)
    nbytes: int


@dataclass
class ShardedLeaf:
    """Host capture of a multi-host-sharded jax.Array: only this process's
    addressable shards (each rank serves/receives its own shard of the
    state, the per-rank transport contract). Reassembled on the receiver
    against its matching local sharding (optim.Optimizer._load_state_dict).
    """

    global_shape: Tuple[int, ...]
    dtype: str
    # Per-shard ((start, stop) per dim, host array) in index order.
    shards: List[Tuple[Tuple[Tuple[int, int], ...], Any]]

    @staticmethod
    def index_key(index, shape) -> Tuple[Tuple[int, int], ...]:
        return tuple(
            (sl.start or 0, sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(index, shape)
        )


@dataclass
class ShardedLeafMeta:
    """Header entry for a ShardedLeaf whose shard buffers ride the raw-bytes
    section (large multi-host states must stream, not pickle)."""

    global_shape: Tuple[int, ...]
    dtype: str
    shard_keys: List[Tuple[Tuple[int, int], ...]]
    shard_shapes: List[Tuple[int, ...]]
    shard_nbytes: List[int]


def _to_host(leaf: Any) -> Any:
    """Stages array-like leaves to host numpy; passes others through.
    Multi-host sharded arrays (remote shards not addressable) capture only
    the local shards as a :class:`ShardedLeaf`."""
    if isinstance(leaf, np.ndarray):
        return leaf
    if hasattr(leaf, "addressable_shards") and hasattr(leaf, "is_fully_addressable"):
        if not leaf.is_fully_addressable:
            shards = sorted(
                (
                    (ShardedLeaf.index_key(s.index, leaf.shape), np.asarray(s.data))
                    for s in leaf.addressable_shards
                ),
                key=lambda kv: kv[0],
            )
            # Replicated copies on multiple local devices dedupe by index.
            deduped = []
            seen = set()
            for key, data in shards:
                if key not in seen:
                    seen.add(key)
                    deduped.append((key, data))
            return ShardedLeaf(tuple(leaf.shape), np.dtype(leaf.dtype).name, deduped)
    # jax.Array without importing jax at module load.
    if hasattr(leaf, "__array__") and hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
        return np.asarray(leaf)
    return leaf


def _flatten(state_dict: Any) -> Tuple[List[Any], Any]:
    import jax

    return jax.tree_util.tree_flatten(state_dict)


def state_dict_meta(state_dict: Any) -> Tuple[Any, List[Optional[ArrayMeta]], List[Any]]:
    """Returns (treedef, per-leaf meta, host leaves). Metas are ArrayMeta for
    plain arrays, ShardedLeafMeta for multi-host shard captures, None for
    header-riding (pickled) leaves."""
    leaves, treedef = _flatten(state_dict)
    leaves = [_to_host(leaf) for leaf in leaves]
    metas: List[Optional[ArrayMeta]] = []
    for leaf in leaves:
        if isinstance(leaf, np.ndarray):
            leaf_c = np.ascontiguousarray(leaf)
            metas.append(ArrayMeta(leaf_c.shape, leaf_c.dtype.name, leaf_c.nbytes))
        elif isinstance(leaf, ShardedLeaf):
            metas.append(
                ShardedLeafMeta(
                    leaf.global_shape,
                    leaf.dtype,
                    [key for key, _ in leaf.shards],
                    [tuple(data.shape) for _, data in leaf.shards],
                    [int(np.ascontiguousarray(data).nbytes) for _, data in leaf.shards],
                )
            )
        else:
            metas.append(None)
    return treedef, metas, leaves


@dataclass
class Prepared:
    """A staged-for-serving state dict: header bytes + host leaves, with the
    exact serialized size known up front. Holds ONE host copy of the data
    (the leaves themselves) — serving writes straight from these buffers, so
    no second serialized copy ever exists (the round-1 2x-peak-memory
    finding on HTTPTransport, reference http_transport.py:128-137)."""

    header: bytes
    leaves: List[Any]
    metas: List[Optional[ArrayMeta]]
    total_size: int


def prepare(state_dict: Any) -> Prepared:
    treedef, metas, leaves = state_dict_meta(state_dict)
    non_array = [leaf for leaf, meta in zip(leaves, metas) if meta is None]
    header = pickle.dumps((treedef, metas, non_array))
    payload = 0
    for meta in metas:
        if isinstance(meta, ArrayMeta):
            payload += meta.nbytes
        elif isinstance(meta, ShardedLeafMeta):
            payload += sum(meta.shard_nbytes)
    total = len(_MAGIC) + _LEN.size + len(header) + payload
    return Prepared(header, leaves, metas, total)


def _bytes_view(arr: np.ndarray) -> memoryview:
    """Raw-byte memoryview of an array without copying (works for ml_dtypes
    custom dtypes, which reject the buffer protocol directly)."""
    arr = np.ascontiguousarray(arr)
    try:
        return memoryview(arr).cast("B")
    except (ValueError, TypeError):
        return np.atleast_1d(arr).view(np.uint8).reshape(-1).data


def write_prepared(prepared: Prepared, stream: BinaryIO) -> None:
    """Streams a :class:`Prepared` state dict; writes are memoryviews of the
    staged host arrays (no payload-sized intermediate buffers)."""
    stream.write(_MAGIC)
    stream.write(_LEN.pack(len(prepared.header)))
    stream.write(prepared.header)
    for leaf, meta in zip(prepared.leaves, prepared.metas):
        if isinstance(meta, ArrayMeta):
            stream.write(_bytes_view(leaf))
        elif isinstance(meta, ShardedLeafMeta):
            for _, data in leaf.shards:
                stream.write(_bytes_view(data))


def save_state_dict(state_dict: Any, stream: BinaryIO) -> None:
    write_prepared(prepare(state_dict), stream)


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _read_array(stream: BinaryIO, shape, dtype: np.dtype, nbytes: int, out=None) -> np.ndarray:
    """Reads ``nbytes`` straight into the final (or provided) buffer — no
    intermediate bytes object, so decode peak stays at one payload copy.
    ``out`` enables in-place receive (zero allocation when shapes match)."""
    if out is not None and (
        tuple(out.shape) != tuple(shape)
        or out.dtype != dtype
        # A non-contiguous template would make _bytes_view fill a copy and
        # silently return the untouched original.
        or not out.flags["C_CONTIGUOUS"]
        or not out.flags.writeable
    ):
        out = None
    arr = out if out is not None else np.empty(shape, dtype=dtype)
    view = _bytes_view(arr)
    if len(view) != nbytes:
        raise ValueError(f"buffer/wire size mismatch: {len(view)} != {nbytes}")
    got = 0
    readinto = getattr(stream, "readinto", None)
    while got < nbytes:
        if readinto is not None:
            n = readinto(view[got:])
        else:
            chunk = stream.read(nbytes - got)
            n = len(chunk)
            view[got : got + n] = chunk
        if not n:
            raise EOFError(
                f"truncated checkpoint stream: wanted {nbytes} bytes, got {got}"
            )
        got += n
    return arr


def load_state_dict(stream: BinaryIO, template: Any = None) -> Any:
    """Decodes a state pytree from ``stream``. With ``template`` (a pytree of
    same-structure arrays), matching leaves are received **in place** into
    the template's buffers — the PGTransport fast path
    (reference pg_transport.py:230-286)."""
    import jax

    magic = stream.read(len(_MAGIC))
    if magic != _MAGIC:
        raise ValueError("bad checkpoint stream magic")
    (header_len,) = _LEN.unpack(stream.read(_LEN.size))
    treedef, metas, non_array = safe_loads(stream.read(header_len))
    template_leaves: List[Any] = []
    if template is not None:
        # is_leaf on None: the wire's non-array leaves may be None, which
        # tree_flatten would otherwise drop, misaligning leaf indices.
        template_leaves = jax.tree_util.tree_flatten(
            template, is_leaf=lambda x: x is None
        )[0]
        if len(template_leaves) != len(metas):
            template_leaves = []
    non_array_iter = iter(non_array)
    leaves = []
    for i, meta in enumerate(metas):
        if meta is None:
            leaves.append(next(non_array_iter))
        elif isinstance(meta, ShardedLeafMeta):
            dtype = _resolve_dtype(meta.dtype)
            shards = []
            for key, shape, nbytes in zip(
                meta.shard_keys, meta.shard_shapes, meta.shard_nbytes
            ):
                shards.append((key, _read_array(stream, shape, dtype, nbytes)))
            leaves.append(ShardedLeaf(meta.global_shape, meta.dtype, shards))
        else:
            dtype = _resolve_dtype(meta.dtype)
            out = None
            if template_leaves:
                candidate = template_leaves[i]
                if isinstance(candidate, np.ndarray):
                    out = candidate
            leaves.append(_read_array(stream, meta.shape, dtype, meta.nbytes, out=out))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def dumps(state_dict: Any) -> bytes:
    buf = io.BytesIO()
    save_state_dict(state_dict, buf)
    return buf.getvalue()


def loads(data: bytes) -> Any:
    return load_state_dict(io.BytesIO(data))
