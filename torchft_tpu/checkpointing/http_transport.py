"""HTTP checkpoint transport.

Role-equivalent of the reference's ``HTTPTransport``
(checkpointing/http_transport.py:39-299): a threaded HTTP server streams the
staged state pytree to healing peers; an RWLock-style gate keeps the staged
data immutable while serving and blocks serving while the trainer mutates
state. Chunked mode splits flattened pytree leaves round-robin into N
independently-fetchable chunks pulled in parallel.

Routes: ``/checkpoint/{step}/meta``, ``/checkpoint/{step}/full``,
``/checkpoint/{step}/{chunk_index}`` (chunk URLs accept a
``?quorum_id=N`` era tag; a mismatch against the staged era answers 409).

Heal-path hardening (beyond the reference, which trusts the stream):

- **Integrity**: the donor stages a per-chunk CRC32C (crc32 fallback when
  google_crc32c is absent) plus a whole-checkpoint digest, served in
  ``/meta``; the joiner checksums every chunk on receive. A mismatched
  chunk is re-fetched within its bounded retry window; an exhausted
  window raises — corrupt state is never adopted (the caller funnels the
  error into Manager.report_error).
- **Resume + donor failover**: verified chunks are cached per chunk,
  keyed by ``(step, digest)``. When a donor dies mid-stream the heal
  fails cleanly; the next attempt — any donor, any quorum era —
  re-fetches only the missing chunks (committed state at a step is
  bitwise identical across donors, and the digest proves it).
- **Multi-donor striping** (``$TPUFT_HEAL_STRIPE``, default on): when
  the manager hands ``recv_checkpoint`` more than one donor address,
  the chunk index is partitioned byte-balanced across the donor set and
  fetched by one worker per donor in parallel — recovery bandwidth
  scales with healthy-donor count instead of being bounded by one
  donor's egress. Every chunk still verifies independently (the CRC +
  progress watchdog apply per stripe, so a gray donor fences only its
  own stripe); a donor that dies, stalls, serves a stale era, or
  corrupts a chunk mid-stripe has its unfetched ranges reassigned to
  the surviving donors, and the per-chunk resume cache guarantees only
  missing chunks are ever re-fetched. One healthy donor degrades to
  exactly the single-donor path.
- **Delta rejoin** (``$TPUFT_HEAL_DELTA``, default on): a rejoiner that
  still holds stale-but-recent state passes it as ``local_state``; the
  transport plans it into the donor's exact chunk layout, checksums
  each local chunk, and adopts chunks whose ``(crc, size)`` matches the
  donor's ``/meta`` manifest WITHOUT fetching them — composing with the
  ZeRO ``skip_parts`` filter so a rejoiner fetches neither shard parts
  nor unchanged chunks. A layout mismatch (different tree, chunking, or
  checksum algo) falls back to the full fetch, never to a wrong one.
  The donor side serves the symmetric ``/checkpoint/{step}/delta``
  manifest-diff endpoint for operators and drills.
- **Gray-failure fencing**: every chunk stream runs under a
  minimum-progress watchdog (``$TPUFT_HEAL_MIN_BYTES_PER_SEC``, default
  1024): a hung or drip-feeding donor is fenced within the watchdog
  window instead of stalling the joiner for the full fetch timeout.
  (A netem-emulated link below the floor would self-fence: raise the
  floor env accordingly for extreme emulations.)
- **Era fencing**: ``/meta`` carries the staged ``quorum_id``; a joiner
  healing in era E rejects a donor staged for era != E instead of
  healing backwards from a stale survivor.

Serve modes (``$TPUFT_HEAL_SERVE_MODE`` / the ``serve_mode`` ctor arg):

- ``inline`` (default): today's in-process serving, unchanged — the
  threaded server above answers heal traffic from the donor process.
- ``child``: a pre-spawned serving child (checkpointing/serve_child.py)
  owns an immutable snapshot of the staged checkpoint (serialized once
  into shared-memory-backed files, integrity metadata computed in the
  same pass) and answers all heal traffic from its own process, so
  GIL/core contention from serving structurally cannot touch the
  donor's step loop. The in-process server remains as the fallback: a
  crashed-out child degrades serving back to inline (reported through
  the registered error callback), never to "no heals".
"""

from __future__ import annotations

import functools
import hashlib
import logging
import os
import pickle
import socket
import time
import threading
import urllib.error
import urllib.parse
import urllib.request
import uuid
import zlib
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import jax

from torchft_tpu import metrics, tracing, wire_codec
from torchft_tpu._safe_pickle import safe_loads
from torchft_tpu.utils import faultinject, netem
from torchft_tpu.checkpointing import _serialization
from torchft_tpu.checkpointing.serve_child import (
    ENV_SERVE_MODE,
    ServeChild,
    ServeChildUnavailable,
    UnknownTenantToken,
    _CorruptingWriter,
    _DripWriter,
    _TruncatingWriter,
    _delta_response,
    maybe_pace_serve,
    tenant_of_authorization,
)
from torchft_tpu.checkpointing.transport import (
    HEAL_PART_PREFIX,
    CheckpointTransport,
)
from torchft_tpu.history import StagedVersionStore
from torchft_tpu.serving import rollout

__all__ = [
    "HTTPTransport",
    "HealIntegrityError",
    "HealChecksumError",
    "HealEraMismatch",
    "HealStalledError",
]

ENV_HEAL_MIN_BPS = "TPUFT_HEAL_MIN_BYTES_PER_SEC"
# Multi-donor striping: enable switch + a cap on how many donors one
# joiner stripes across (each extra donor costs one metadata-resolution
# RPC and one worker thread; past ~8 the joiner's ingress is the
# bottleneck anyway).
ENV_HEAL_STRIPE = "TPUFT_HEAL_STRIPE"
ENV_HEAL_STRIPE_MAX_DONORS = "TPUFT_HEAL_STRIPE_MAX_DONORS"
# Delta rejoin: adopt local chunks whose (crc, size) matches the donor's
# manifest instead of fetching them.
ENV_HEAL_DELTA = "TPUFT_HEAL_DELTA"
# Joiner-side ingress bound (Gbps; <= 0 = unbounded): a joiner striping
# across many donors must not swamp its own link — N uncapped donor
# streams contending for one NIC collapse per-stream throughput until
# the minimum-progress watchdog fences HEALTHY donors. One token bucket
# per heal attempt bounds the aggregate; pacer-injected sleep is credited
# back to the watchdog so self-pacing can never read as a gray donor.
ENV_HEAL_INGRESS = "TPUFT_HEAL_INGRESS_GBPS"
# Smoothing factor for the per-donor bandwidth EWMA that weights the
# stripe plan (0 < alpha <= 1; higher = favor the latest observation).
ENV_HEAL_BW_ALPHA = "TPUFT_HEAL_BW_EWMA_ALPHA"


def _env_flag(env: str, default: bool = True) -> bool:
    value = os.environ.get(env)
    if value is None:
        return default
    return value.strip().lower() not in ("0", "false", "off", "no", "")


def heal_stripe_enabled() -> bool:
    """Multi-donor striped heals (``$TPUFT_HEAL_STRIPE``, default on)."""
    return _env_flag(ENV_HEAL_STRIPE, True)


def heal_stripe_max_donors(default: int = 8) -> int:
    """Donor-set cap for one striped heal (``$TPUFT_HEAL_STRIPE_MAX_DONORS``)."""
    try:
        n = int(os.environ.get(ENV_HEAL_STRIPE_MAX_DONORS, str(default)))
    except ValueError:
        return default
    return max(1, n)


def heal_delta_enabled() -> bool:
    """Delta rejoin (``$TPUFT_HEAL_DELTA``, default on)."""
    return _env_flag(ENV_HEAL_DELTA, True)


def heal_ingress_gbps(default: float = 0.0) -> float:
    """Joiner-side heal ingress bound (``$TPUFT_HEAL_INGRESS_GBPS``;
    <= 0 = unbounded; malformed values fall back)."""
    try:
        return float(os.environ.get(ENV_HEAL_INGRESS, str(default)))
    except ValueError:
        return default


def heal_bw_alpha(default: float = 0.3) -> float:
    """Per-donor bandwidth EWMA smoothing (``$TPUFT_HEAL_BW_EWMA_ALPHA``)."""
    try:
        alpha = float(os.environ.get(ENV_HEAL_BW_ALPHA, str(default)))
    except ValueError:
        return default
    return alpha if 0.0 < alpha <= 1.0 else default


# ---------------------------------------------------------------------------
# Per-donor bandwidth EWMA: the stripe workers already measure bytes/sec per
# verified chunk; persisting it per STABLE donor id (the replica-id prefix
# before the first ':', so a donor restart keeps its history — falls back to
# the donor URL when the manager did not resolve an id) lets the NEXT stripe
# plan weight each donor by what it actually delivered. Process-local and
# advisory: a cold cache (weights all None) degrades to the byte-balanced
# plan, never a stall.
# ---------------------------------------------------------------------------

_donor_bw_lock = threading.Lock()
_donor_bw: Dict[str, float] = {}  # stable donor key -> bytes/sec EWMA


def donor_bw_key(replica_id: Optional[str], url: str) -> str:
    """Stable EWMA key: replica-id prefix when known, else the donor URL."""
    if replica_id:
        return replica_id.split(":", 1)[0] or url
    return url


def observe_donor_bandwidth(key: str, bytes_per_sec: float) -> float:
    """Folds one bytes/sec observation into the donor's EWMA; returns the
    updated estimate (also exported as ``tpuft_heal_donor_bw_bytes_per_sec``)."""
    if bytes_per_sec <= 0.0:
        with _donor_bw_lock:
            return _donor_bw.get(key, 0.0)
    alpha = heal_bw_alpha()
    with _donor_bw_lock:
        prev = _donor_bw.get(key)
        est = bytes_per_sec if prev is None else prev + alpha * (bytes_per_sec - prev)
        _donor_bw[key] = est
    metrics.set_gauge("tpuft_heal_donor_bw_bytes_per_sec", est, donor=key)
    return est


def donor_bandwidth(key: str) -> Optional[float]:
    with _donor_bw_lock:
        return _donor_bw.get(key)


def reset_donor_bandwidth() -> None:
    """Drop all per-donor EWMA state (tests/benches between legs)."""
    with _donor_bw_lock:
        _donor_bw.clear()


def _donor_weights(keys: List[str]) -> Optional[List[float]]:
    """Relative stripe weights from the EWMA store: donors with no history
    get the mean of the known ones (neutral, not penalized). All-unknown
    (or degenerate) -> None, which keeps the plan byte-balanced."""
    with _donor_bw_lock:
        known = [_donor_bw[k] for k in keys if k in _donor_bw and _donor_bw[k] > 0]
        if not known:
            return None
        mean = sum(known) / len(known)
        weights = [
            _donor_bw[k] if _donor_bw.get(k, 0) > 0 else mean for k in keys
        ]
    if min(weights) <= 0.0:
        return None
    return weights

logger = logging.getLogger(__name__)

# Sliding window the progress watchdog averages over; fencing decisions
# never fire before one full window has elapsed, so a legit slow start
# (TLS, first-byte latency) is not a stall.
_WATCHDOG_WINDOW_SEC = 2.0


class HealIntegrityError(RuntimeError):
    """Checkpoint integrity verification failed; the state was NOT adopted."""


class HealChecksumError(HealIntegrityError):
    """One chunk's checksum mismatched (retryable within the fetch window)."""


class HealEraMismatch(RuntimeError):
    """The donor's staged checkpoint belongs to a different quorum era."""


class HealStalledError(RuntimeError):
    """The heal stream fell below the minimum-progress floor (gray donor)."""


# ---------------------------------------------------------------------------
# Checksums: CRC32C when google_crc32c is importable, zlib crc32 otherwise.
# Donor and joiner agree via the /meta "crc_algo" field, so a mixed fleet
# verifies with the donor's algorithm or fails loudly (never silently).
# ---------------------------------------------------------------------------

# google_crc32c's C extension only takes `bytes`; feed it bounded slices so
# checksumming never materializes a payload-sized copy.
_CRC_SLICE = 1 << 20


def _crc32_update(crc: int, data: Any) -> int:
    return zlib.crc32(data, crc) & 0xFFFFFFFF


try:  # pragma: no cover - exercised via whichever algo the box has
    import google_crc32c as _google_crc32c

    def _crc32c_update(crc: int, data: Any) -> int:
        if isinstance(data, bytes):
            return _google_crc32c.extend(crc, data)
        mv = memoryview(data)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        for off in range(0, len(mv), _CRC_SLICE):
            crc = _google_crc32c.extend(crc, mv[off : off + _CRC_SLICE].tobytes())
        return crc

    _CRC_UPDATERS: Dict[str, Callable[[int, Any], int]] = {
        "crc32c": _crc32c_update,
        "crc32": _crc32_update,
    }
    _CRC_ALGO = "crc32c"
except ImportError:  # pragma: no cover
    _CRC_UPDATERS = {"crc32": _crc32_update}
    _CRC_ALGO = "crc32"


class _CRCWriter:
    """File-like sink that checksums everything written through it (used to
    stage per-chunk CRCs without a serialized copy)."""

    __slots__ = ("crc", "_update")

    def __init__(self, update: Callable[[int, Any], int]) -> None:
        self.crc = 0
        self._update = update

    def write(self, data: Any) -> None:
        self.crc = self._update(self.crc, data)


def _checkpoint_digest(
    step: int,
    algo: str,
    chunk_crcs: List[int],
    chunk_codecs: Optional[List[str]] = None,
) -> str:
    """Whole-checkpoint digest binding the per-chunk checksums to (step,
    algo) — and, when the stage is codec-encoded, the per-chunk codec
    tags, so a tampered/lying tag in ``/meta`` breaks the digest binding
    before any payload transfer. Deliberately quorum-era independent:
    committed state at a step is bitwise identical across donors and
    eras, which is exactly what makes cross-donor resume valid. With
    ``chunk_codecs`` None/all-fp32 the binding is byte-identical to the
    pre-codec format (old metas verify unchanged)."""
    h = hashlib.sha256()
    binding = f"{step}:{algo}:{','.join(str(c) for c in chunk_crcs)}"
    if chunk_codecs and any(c != "fp32" for c in chunk_codecs):
        binding += f":codecs={','.join(chunk_codecs)}"
    h.update(binding.encode())
    return h.hexdigest()


def _heal_min_bps(default: float = 1024.0) -> float:
    """Minimum-progress floor (bytes/s) from ``$TPUFT_HEAL_MIN_BYTES_PER_SEC``
    (<= 0 disables the watchdog; malformed values fall back)."""
    try:
        return float(os.environ.get(ENV_HEAL_MIN_BPS, str(default)))
    except ValueError:
        return default


class _IngressPacer:
    """Per-heal-attempt token bucket for the joiner's ingress bound
    (``$TPUFT_HEAL_INGRESS_GBPS``): every stripe worker of one
    ``recv_checkpoint`` debits the SAME clock, so striping across N
    donors shares the configured rate instead of multiplying it by the
    donor count — the bound stands for the joiner's NIC, which all the
    stripes arrive through. One instance per heal attempt (not process-
    global): a joiner process runs one heal at a time, and tests run
    many joiners in one process."""

    __slots__ = ("gbps", "_lock", "_ready")

    def __init__(self, gbps: float) -> None:
        self.gbps = gbps
        self._lock = threading.Lock()
        self._ready = time.monotonic()

    def debit(self, nbytes: int) -> float:
        with self._lock:
            now = time.monotonic()
            start = self._ready if self._ready > now else now
            self._ready = start + nbytes * 8.0 / (self.gbps * 1e9)
            return max(self._ready - now, 0.0)


class _GuardedReader:
    """Wraps an HTTP response stream: checksums bytes on the fly and fences
    the fetch when progress falls below the bytes/s floor for a full
    watchdog window (the gray-failure case a per-recv socket timeout
    cannot see — a dripping donor resets that timeout with every byte).

    ``ingress`` (an :class:`_IngressPacer`) bounds the joiner's own read
    rate; the pacer's injected sleep is subtracted from the watchdog
    window before the floor check, so a self-paced stream is judged by
    what the DONOR delivered in the time we were actually willing to
    read — self-pacing can never fence a healthy donor as gray."""

    def __init__(
        self,
        raw: Any,
        crc_update: Optional[Callable[[int, Any], int]] = None,
        min_bps: float = 0.0,
        window: float = _WATCHDOG_WINDOW_SEC,
        ingress: Optional[_IngressPacer] = None,
    ) -> None:
        self._raw = raw
        self._update = crc_update
        self.crc = 0
        self.total = 0
        self._min_bps = float(min_bps)
        self._window = float(window)
        self._ingress = ingress
        self._start = time.monotonic()
        self._events: deque = deque()  # (t, nbytes) inside the window
        self._paced: deque = deque()  # (t, sleep_s) inside the window

    def _read1(self, n: int) -> bytes:
        # read1 returns whatever ONE underlying read yields; plain read(n)
        # on a BufferedReader loops until n bytes arrived, which would let
        # a dripping donor hide from the watchdog inside one giant read.
        read1 = getattr(self._raw, "read1", None)
        return read1(n) if read1 is not None else self._raw.read(n)

    def read(self, n: int = -1) -> bytes:
        parts: List[bytes] = []
        want = n
        while want != 0:
            data = self._read1(want if want > 0 else _CRC_SLICE)
            if not data:
                break
            if self._update is not None:
                self.crc = self._update(self.crc, data)
            self._account(len(data))
            parts.append(data)
            if want > 0:
                want -= len(data)
        return b"".join(parts)

    def readinto(self, buf: Any) -> int:
        # Single bounded-granularity read; callers (_serialization) loop.
        readinto1 = getattr(self._raw, "readinto1", None)
        n = readinto1(buf) if readinto1 is not None else self._raw.readinto(buf)
        if n:
            if self._update is not None:
                self.crc = self._update(self.crc, memoryview(buf)[:n])
            self._account(n)
        return n

    def _account(self, n: int) -> None:
        self.total += n
        if self._ingress is not None and n > 0:
            delay = self._ingress.debit(n)
            metrics.inc("tpuft_heal_ingress_bytes_total", n)
            if delay > 0:
                metrics.inc("tpuft_heal_ingress_paced_seconds_total", delay)
                time.sleep(delay)
                self._paced.append((time.monotonic(), delay))
        if self._min_bps <= 0:
            return
        now = time.monotonic()
        self._events.append((now, n))
        cutoff = now - self._window
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()
        while self._paced and self._paced[0][0] < cutoff:
            self._paced.popleft()
        if now - self._start >= self._window:
            # Credit ingress-pacer sleep back: the donor only had
            # (window - paced) seconds of our attention.
            paced = min(
                sum(s for _, s in self._paced), self._window - 1e-3
            )
            rate = sum(nb for _, nb in self._events) / (self._window - paced)
            if rate < self._min_bps:
                metrics.inc("tpuft_heal_stalled_fetches_total")
                raise HealStalledError(
                    f"heal stream below the progress floor: {rate:.0f} B/s < "
                    f"{self._min_bps:.0f} B/s over the last {self._window:.1f}s "
                    f"(floor from ${ENV_HEAL_MIN_BPS}); fencing the donor"
                )


# Donor-side fault writers (chaos drills) live in serve_child.py so the
# serving child shares the exact same seams; imported above for the
# inline handler (and for tests that reach them via this module).


class _TeeCRCWriter:
    """File sink that also checksums everything written through it — the
    child-mode staging path computes the PR-4 per-chunk CRC in the same
    single pass that serializes the chunk into shared memory (no second
    pass over the payload, matching inline's one-CRC-pass staging cost)."""

    __slots__ = ("crc", "_raw", "_update")

    def __init__(self, raw: Any, update: Callable[[int, Any], int]) -> None:
        self.crc = 0
        self._raw = raw
        self._update = update

    def write(self, data: Any) -> None:
        self._raw.write(data)
        self.crc = self._update(self.crc, data)


class _Staged:
    """Prepared (header + host leaves) per chunk — ONE host copy total; the
    HTTP handlers stream straight from these buffers (no serialized copy,
    the round-1 2x-peak-memory finding). Integrity sidecar: per-chunk
    checksums + sizes + the whole-checkpoint digest, computed once at
    stage time."""

    def __init__(
        self,
        step: int,
        chunks: List[Any],
        treedef: Any,
        quorum_id: Optional[int] = None,
        parts: Optional[Dict[str, int]] = None,
        codec: Optional[str] = None,
    ) -> None:
        self.step = step
        self.chunks = chunks  # List[_serialization.Prepared]
        self.treedef = treedef
        self.quorum_id = quorum_id
        self.crc_algo = _CRC_ALGO
        self.parts = {
            name: {"chunk": index, "nbytes": chunks[index].total_size}
            for name, index in (parts or {}).items()
        }
        self.chunk_sizes = [int(chunk.total_size) for chunk in chunks]
        # CRCs (and the digest below) are computed over the ENCODED bytes
        # when a wire codec staged this checkpoint: integrity, delta
        # matching, and stripe reassignment all operate on what actually
        # crosses the wire. None = fp32 passthrough, bit-for-bit the
        # pre-codec format.
        self.chunk_codecs = wire_codec.chunk_codecs_for(len(chunks), codec)
        self.chunk_crcs: List[int] = []
        for chunk in chunks:
            w = _CRCWriter(_CRC_UPDATERS[_CRC_ALGO])
            _serialization.write_prepared(chunk, w)
            self.chunk_crcs.append(w.crc)
        self.digest = _checkpoint_digest(
            step, self.crc_algo, self.chunk_crcs, self.chunk_codecs
        )
        self.tree_token = _tree_token(treedef)

    def meta_bytes(self) -> bytes:
        return _meta_bytes(
            step=self.step,
            quorum_id=self.quorum_id,
            num_chunks=len(self.chunks),
            treedef=self.treedef,
            crc_algo=self.crc_algo,
            chunk_crcs=self.chunk_crcs,
            digest=self.digest,
            parts=self.parts,
            chunk_sizes=self.chunk_sizes,
            chunk_codecs=self.chunk_codecs,
        )


def _meta_bytes(
    step: int,
    quorum_id: Optional[int],
    num_chunks: int,
    treedef: Any,
    crc_algo: str,
    chunk_crcs: List[int],
    digest: str,
    parts: Optional[Dict[str, Dict[str, int]]] = None,
    chunk_sizes: Optional[List[int]] = None,
    chunk_codecs: Optional[List[str]] = None,
) -> bytes:
    """The exact ``/meta`` response body. Built once per stage in BOTH
    serve modes (the serving child receives these bytes pre-pickled over
    the control pipe and serves them verbatim — it never needs to
    unpickle a treedef, so it never needs jax). ``parts`` maps heal-part
    name -> {"chunk", "nbytes"} so a joiner can address (or skip) exactly
    one part's payload; ``chunk_sizes`` lets the stripe planner balance
    donors by bytes and pins the reassigned-remainder accounting exactly.

    ``chunk_codecs`` (the quantized wire plane) bumps the format to 3:
    every chunk's bytes are codec-encoded (fp8/int8/int4) and the tags
    are digest-bound. A codec-less peer refuses format 3 outright — it
    can never misdecode encoded bytes as raw arrays — and negotiates
    fp32 by healing from a donor staged without a codec (the default).
    With ``chunk_codecs`` None these bytes are bit-for-bit the format-2
    body (pinned by tests)."""
    meta: Dict[str, Any] = {
        "format": 3 if chunk_codecs else 2,
        "num_chunks": num_chunks,
        "treedef": treedef,
        "step": step,
        "quorum_id": quorum_id,
        "crc_algo": crc_algo,
        "chunk_crcs": chunk_crcs,
        "digest": digest,
        "parts": parts or {},
        "chunk_sizes": chunk_sizes,
    }
    if chunk_codecs:
        meta["chunk_codecs"] = list(chunk_codecs)
        meta["codec"] = chunk_codecs[0]
    return pickle.dumps(meta)


def _tree_token(treedef: Any) -> Optional[str]:
    """Content token of a pytree STRUCTURE (sha256 of the pickled
    treedef): two stages with equal tokens flatten identically, so a
    serving reader that cached the treedef under this token can skip the
    ``/meta`` fetch on version bumps that only changed leaf bytes — one
    less RTT per hop. Purely an optimization key: a reader that cannot
    (or will not) match tokens fetches ``/meta`` exactly as before."""
    try:
        return hashlib.sha256(pickle.dumps(treedef)).hexdigest()
    except Exception:  # noqa: BLE001 — token absence just costs the /meta RTT
        return None


def _stage_manifest(
    step: int,
    quorum_id: Optional[int],
    crc_algo: str,
    chunk_crcs: List[int],
    chunk_sizes: List[int],
    digest: str,
    tree_token: Optional[str] = None,
    chunk_codecs: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """JSON-safe summary of one staged checkpoint (no treedef — readers
    that need it fetch the pickled ``/meta``). ``send_checkpoint`` returns
    it so the serving plane's publisher can announce the staged version
    without a second pass over the payload. ``chunk_codecs`` rides into
    the serving descriptor only when the stage was codec-encoded (the
    default descriptor stays field-identical to the pre-codec wire)."""
    manifest: Dict[str, Any] = {
        "step": int(step),
        "quorum_id": quorum_id,
        "crc_algo": crc_algo,
        "chunk_crcs": [int(c) for c in chunk_crcs],
        "chunk_sizes": [int(s) for s in chunk_sizes],
        "num_chunks": len(chunk_crcs),
        "digest": digest,
        "tree_token": tree_token,
    }
    if chunk_codecs:
        manifest["chunk_codecs"] = list(chunk_codecs)
        manifest["codec"] = chunk_codecs[0]
    return manifest


def _plan_chunks(
    state_dict: Any, num_chunks: int, codec: Optional[str] = None, wire: str = "heal"
) -> Tuple[Any, List[Dict[int, Any]], Dict[str, int]]:
    """Splits a state dict's leaves into servable chunks, part-aware.

    Leaves under a dict key starting with :data:`HEAL_PART_PREFIX` form a
    named *part* and get their own dedicated chunk (appended after the
    base chunks), so a joiner can address — or skip — exactly that
    payload; everything else round-robins into ``num_chunks`` base chunks
    exactly as before (with no part keys the layout is bit-identical to
    the pre-part format). Returns ``(treedef, chunk_dicts, parts)`` where
    ``parts`` maps part name -> chunk index.

    ``codec`` (the quantized wire plane, torchft_tpu/wire_codec.py)
    encodes every eligible float leaf BEFORE planning, so the chunk
    layout, CRCs, sizes, and the delta/stripe machinery all operate on
    the encoded bytes. Both sides plan through this one function — a
    delta-rejoining peer encodes its local state with the donor's codec
    and lands on the identical layout. None/"fp32" is the bit-for-bit
    passthrough.
    """
    state_dict, _stats = wire_codec.encode_state(state_dict, codec, wire=wire)
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(state_dict)

    def part_of(path: Any) -> Optional[str]:
        for entry in path:
            key = getattr(entry, "key", None)
            if isinstance(key, str) and key.startswith(HEAL_PART_PREFIX):
                return key
        return None

    rest: List[int] = []
    part_members: Dict[str, List[int]] = {}
    for index, (path, _leaf) in enumerate(leaves_with_paths):
        name = part_of(path)
        if name is None:
            rest.append(index)
        else:
            part_members.setdefault(name, []).append(index)
    leaves = [_serialization._to_host(leaf) for _path, leaf in leaves_with_paths]
    n = num_chunks if num_chunks > 0 else 1
    n = min(n, max(len(rest), 1))
    chunk_dicts: List[Dict[int, Any]] = [dict() for _ in range(n)]
    for slot, index in enumerate(rest):
        chunk_dicts[slot % n][index] = leaves[index]
    parts: Dict[str, int] = {}
    for name in sorted(part_members):
        parts[name] = len(chunk_dicts)
        chunk_dicts.append({i: leaves[i] for i in part_members[name]})
    return treedef, chunk_dicts, parts


def _plan_stripes(
    chunks: List[int],
    sizes: Optional[List[int]],
    num_donors: int,
    rotation: int = 0,
    weights: Optional[List[float]] = None,
) -> List[List[int]]:
    """Partitions chunk indices across ``num_donors`` stripes, byte-balanced
    when ``sizes`` is known (greedy longest-processing-time: biggest chunk
    to the currently lightest stripe, ties broken by ``rotation``-offset
    donor slot) and count-balanced round-robin otherwise. Pure and
    deterministic — the same inputs always yield the same plan, so drills
    can pin exactly which donor owned which chunks. Within a stripe,
    chunks fetch in ascending index order.

    ``rotation`` is the coordinated-storm offset: with it zero this is
    exactly the PR-8 plan; N concurrent joiners pass N distinct offsets
    (the manager derives each from its joiner ordinal / group rank /
    quorum id — a pure function, never negotiated) so they seed their
    plans at DIFFERENT donors instead of all hammering donor 0's first
    stripe at the same instant.

    ``weights`` (per-donor relative bandwidth, from the per-donor EWMA)
    turns byte balance into TIME balance: each chunk goes to the donor
    whose finish time (load + chunk) / weight is smallest, so a donor
    twice as fast takes ~twice the bytes. Equal weights produce exactly
    the unweighted plan (argmin of load+c equals argmin of load when c
    is common), so a cold or uniform EWMA changes nothing; ignored
    without ``sizes``."""
    num_donors = max(1, num_donors)
    rotation = rotation % num_donors
    stripes: List[List[int]] = [[] for _ in range(num_donors)]
    if sizes is None:
        for slot, index in enumerate(chunks):
            stripes[(slot + rotation) % num_donors].append(index)
        return stripes
    if weights is not None and (
        len(weights) != num_donors or min(weights) <= 0.0
    ):
        weights = None
    loads = [0] * num_donors
    by_weight = sorted(chunks, key=lambda i: (-sizes[i], i))
    for index in by_weight:
        if weights is None:
            slot = min(
                range(num_donors),
                key=lambda d: (loads[d], (d - rotation) % num_donors),
            )
        else:
            slot = min(
                range(num_donors),
                key=lambda d: (
                    (loads[d] + sizes[index]) / weights[d],
                    (d - rotation) % num_donors,
                ),
            )
        stripes[slot].append(index)
        loads[slot] += sizes[index]
    for stripe in stripes:
        stripe.sort()
    return stripes


class _HealCacheEntry:
    """Joiner-side per-chunk resume/accounting state for one (step,
    digest): verified chunks (so a failover re-fetches only what is
    missing), which chunk indices ever started transferring (so the
    re-fetch counter stays exact), and where each verified chunk came
    from (a donor URL, or ``"delta"`` for chunks adopted from the
    rejoiner's own stale state). ``lock`` guards mutation — striped
    heals verify chunks from several per-donor workers concurrently."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.chunks: Dict[int, Tuple[Any, int]] = {}  # index -> (chunk, nbytes)
        self.attempted: Set[int] = set()
        self.sources: Dict[int, str] = {}  # index -> donor url | "delta"


class HTTPTransport(CheckpointTransport[Any]):
    """Serves the staged checkpoint over HTTP; IPv6 dual-stack like the
    reference so it works across heterogeneous TPU pods."""

    def __init__(
        self,
        timeout: float = 60.0,
        num_chunks: int = 0,
        serve_mode: Optional[str] = None,
        keep_versions: int = 1,
        codec: Optional[str] = None,
        wire: str = "heal",
    ) -> None:
        self._timeout = timeout
        self._num_chunks = num_chunks
        # Quantized wire plane: the codec this transport stages with. An
        # explicit ctor codec pins it; otherwise the env knob for this
        # transport's wire class ($TPUFT_HEAL_CODEC / $TPUFT_SERVING_CODEC,
        # via `wire`) is read at STAGE time, so tests and operators can
        # flip it without rebuilding transports. Default fp32 =
        # bit-for-bit the pre-codec wire.
        if codec is not None:
            wire_codec.resolve_codec(codec)  # validate eagerly
        self._codec_arg = codec
        self._wire = wire
        # Versioned staged history (torchft_tpu/history.py): with
        # keep_versions > 1 the last K staged checkpoints stay servable
        # (the serving plane's pinned-version / rollback reads), budgeted
        # by TPUFT_HISTORY_BYTES / TPUFT_HISTORY_MAX_VERSIONS. The
        # default (1) is the pre-history single-stage donor behavior. In
        # child serve mode the resident versions are the child's /dev/shm
        # epoch dirs; this store then mirrors manifests for bookkeeping.
        self._keep_versions = max(1, int(keep_versions))
        self._staged_store: Optional[StagedVersionStore] = (
            StagedVersionStore(max_versions=self._keep_versions)
            if self._keep_versions > 1
            else None
        )
        # Fairness identity this JOINER sends on its fetch URLs (?peer=):
        # per transport instance, so every joiner of a storm — one per
        # process in production, many per process in threads-as-replicas
        # drills — owns exactly one sub-bucket of a donor's paced egress
        # no matter how many parallel chunk streams it opens.
        self._peer_tag = uuid.uuid4().hex[:12]
        serve_mode = serve_mode or os.environ.get(ENV_SERVE_MODE, "inline")
        if serve_mode not in ("inline", "child"):
            raise ValueError(
                f"{ENV_SERVE_MODE} must be 'inline' or 'child', got {serve_mode!r}"
            )
        self._serve_mode = serve_mode
        # Donor sidecar (serve_mode="child"): pre-spawned serving child;
        # heal traffic goes to ITS address (see metadata()) so serving
        # contention structurally cannot touch this process. Spawn
        # failure degrades to inline — serving must never be the reason
        # a fleet cannot heal.
        self._serve_child: Optional[ServeChild] = None
        self._child_staged = False
        self._child_degraded = False
        self._error_cb: Optional[Callable[[Exception], None]] = None
        metrics.set_gauge(
            "tpuft_heal_serve_mode", 1 if serve_mode == "child" else 0
        )
        if serve_mode == "child":
            try:
                self._serve_child = ServeChild(
                    timeout=timeout, on_error=self._dispatch_serve_error
                )
            except Exception as e:  # noqa: BLE001 — degrade, never fail init
                logger.warning(
                    "heal-serve child spawn failed (%s); serving inline", e
                )
                metrics.inc("tpuft_heal_serve_fallbacks_total")
                self._child_degraded = True
        # Condition gates serving: a GET for step S parks until the trainer
        # stages S (send_checkpoint) — the reference's RWLock allow/disallow
        # gate (http_transport.py:182-242). Without this the joiner's fetch
        # races the donor's staging inside the same quorum round.
        self._cond = threading.Condition()
        self._staged: Optional[_Staged] = None
        self._served_event = threading.Event()
        # Joiner-side resume cache: per-chunk accounting for the one
        # (step, digest) heal currently in flight — each verified chunk
        # (fetched from any donor, or delta-matched from local state) is
        # reusable against ANY donor serving the same digest. Partials of
        # an older (step, digest) are dropped when a new heal starts.
        self._heal_cache: Dict[Tuple[int, str], _HealCacheEntry] = {}
        # Chaos seam: tests set a callable (step, chunk_index) -> mode to
        # inject donor-side stream faults deterministically; when unset the
        # punisher's file-armed faults apply (faultinject.consume).
        self._fault_hook: Optional[Callable[[int, int], Optional[str]]] = None
        # Progressive delivery: stream tag per staged step ("canary" /
        # "stable"), recorded by the publisher BEFORE it announces the
        # version so a stable tenant can never race canary chunks in the
        # announce window. Untagged steps (heal stages) are ungated.
        self._step_streams: Dict[int, str] = {}

        transport = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: Any) -> None:  # silence
                pass

            def do_GET(self) -> None:
                # The transport's port doubles as this process's scrape
                # endpoint: every training replica already listens here for
                # heals, so /metrics needs no extra server or port. In
                # child mode the serving child's registry is scraped and
                # merged in (labeled process="serve_child").
                if metrics._serve_metrics_http(
                    self,
                    metrics.REGISTRY,
                    self.path,
                    extra_text=transport._child_metrics_text,
                    extra_json=transport._child_metrics_json,
                ):
                    return
                split = urllib.parse.urlsplit(self.path)
                parts = split.path.strip("/").split("/")
                if len(parts) != 3 or parts[0] != "checkpoint":
                    self.send_error(404, "unknown route")
                    return
                try:
                    step = int(parts[1])
                except ValueError:
                    self.send_error(400, "bad step")
                    return
                stall_t0 = time.perf_counter()
                with transport._cond:
                    # Park only for a step that may still arrive: staged
                    # steps are monotone, so a request for an OLDER step
                    # than the current stage can never be satisfied —
                    # it either lives in the staged-version history ring
                    # (answered below) or 404s immediately instead of
                    # holding the reader (or a stale joiner) for the
                    # full timeout. A reader racing a serving-plane
                    # version bump refetches the new descriptor on its
                    # next poll.
                    transport._cond.wait_for(
                        lambda: (
                            transport._staged is not None
                            and transport._staged.step >= step
                        )
                        or transport._staged_version(step) is not None,
                        timeout=transport._timeout,
                    )
                    staged = transport._staged
                # Donor-side stall: how long this GET parked waiting for the
                # trainer to stage the requested step.
                metrics.observe(
                    "tpuft_ckpt_donor_stall_seconds",
                    time.perf_counter() - stall_t0,
                )
                if staged is None or staged.step != step:
                    historical = transport._staged_version(step)
                    if historical is not None:
                        staged = historical
                    elif transport._staged_retracted(step):
                        metrics.inc("tpuft_history_retracted_reads_total")
                        self.send_error(
                            410, f"version {step} was retracted"
                        )
                        return
                    else:
                        self.send_error(
                            404,
                            f"no checkpoint staged for step {step}"
                            + (f" (have {staged.step})" if staged else ""),
                        )
                        return
                # Era fence: a joiner tags its chunk fetches with the quorum
                # era it is healing in; serving a different staged era would
                # hand it bytes its /meta checksums do not describe (the
                # stage could have moved between its meta and chunk GETs).
                want_era = urllib.parse.parse_qs(split.query).get("quorum_id")
                if (
                    want_era
                    and staged.quorum_id is not None
                    and str(staged.quorum_id) != want_era[0]
                ):
                    self.send_error(
                        409,
                        f"stale quorum era: staged {staged.quorum_id}, "
                        f"joiner wants {want_era[0]}",
                    )
                    return
                # Multi-tenant serving seam: a bearer token marks this GET
                # as serving-class read traffic charged to its tenant's
                # egress sub-bucket; no token = heal traffic (per-peer
                # fairness, unchanged). An unknown token is refused — a
                # misconfigured credential must surface, not silently
                # ride the anonymous bucket.
                try:
                    tenant = tenant_of_authorization(
                        self.headers.get("Authorization")
                    )
                except UnknownTenantToken as e:
                    metrics.inc("tpuft_serving_auth_rejects_total")
                    self.send_error(401, f"unknown serving tenant: {e}")
                    return
                # Progressive-delivery seam: a tenant whose rollout policy
                # does not cover this version's stream is refused BEFORE
                # any bytes move (the PR-12 401 discipline, answering 403).
                # Tokenless fetches stay ungated — they are the heal plane
                # and relay-tree pulls, which must see every stream.
                if tenant is not None:
                    deny = rollout.wrong_stream_chunk_reason(
                        tenant, step, transport._step_streams.get(step)
                    )
                    if deny is not None:
                        metrics.inc(
                            "tpuft_rollout_wrong_stream_rejects_total",
                            seam="transport",
                        )
                        self.send_error(403, deny)
                        return
                if parts[2] == "meta":
                    body = staged.meta_bytes()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif parts[2] == "delta":
                    # Delta-manifest diff: the caller passes its local
                    # per-chunk CRCs (?crcs=a,b,...&algo=...) and gets back
                    # which chunks differ from the staged checkpoint —
                    # the operator-facing twin of the joiner-side delta
                    # match (same era fence as every other route).
                    body = _delta_response(
                        split.query,
                        crc_algo=staged.crc_algo,
                        chunk_crcs=staged.chunk_crcs,
                        chunk_sizes=staged.chunk_sizes,
                        digest=staged.digest,
                        chunk_codecs=staged.chunk_codecs,
                    )
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif parts[2] == "full":
                    # WAN topology: the joiner tags its region on the URL
                    # so this donor paces the DIRECTED (donor, joiner)
                    # link; untagged requests ride the global single link.
                    peer_reg = urllib.parse.parse_qs(split.query).get("region")
                    peer_region = peer_reg[0] if peer_reg else None
                    total = sum(8 + c.total_size for c in staged.chunks)
                    self.send_response(200)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(total))
                    if netem.enabled():
                        self.send_header(netem.PACED_HEADER, "1")
                    self.end_headers()
                    out = self.wfile
                    if netem.enabled():  # emulated-DCN heal path
                        netem.pace_latency(peer_region)
                        out = netem.PacingWriter(out, peer_region=peer_region)
                    if tenant is not None:
                        out = maybe_pace_serve(out, cls="serving", tenant=tenant)
                    else:
                        out = maybe_pace_serve(
                            out, peer=transport._peer_of(self, split)
                        )
                    try:
                        for chunk in staged.chunks:
                            out.write(chunk.total_size.to_bytes(8, "big"))
                            _serialization.write_prepared(chunk, out)
                    except (ConnectionError, TimeoutError, OSError):
                        # The joiner went away (fenced us, failed over, or
                        # died); serving is best-effort, never donor-fatal.
                        self.close_connection = True
                else:
                    try:
                        index = int(parts[2])
                        chunk = staged.chunks[index]
                    except (ValueError, IndexError):
                        self.send_error(400, "bad chunk index")
                        return
                    fault = transport._chunk_fault(step, index)
                    if fault == "die":
                        # A donor dying mid-heal: cut the connection before
                        # (or instead of) the body.
                        self.close_connection = True
                        return
                    # WAN topology: pace the directed (donor, joiner) link
                    # when the joiner tagged its region on the chunk URL.
                    peer_reg = urllib.parse.parse_qs(split.query).get("region")
                    peer_region = peer_reg[0] if peer_reg else None
                    self.send_response(200)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(chunk.total_size))
                    if netem.enabled():
                        self.send_header(netem.PACED_HEADER, "1")
                    self.end_headers()
                    out = self.wfile
                    if netem.enabled():  # emulated-DCN heal path
                        netem.pace_latency(peer_region)
                        # Serialization time interleaves with the writes —
                        # one up-front sleep would hold the wire silent
                        # past the joiner's per-recv inactivity timeout.
                        out = netem.PacingWriter(out, peer_region=peer_region)
                    if tenant is not None:
                        out = maybe_pace_serve(out, cls="serving", tenant=tenant)
                    else:
                        out = maybe_pace_serve(
                            out, peer=transport._peer_of(self, split)
                        )
                    if fault == "corrupt_stream":
                        # Flip a payload bit (the LAST byte is raw array
                        # data whenever the chunk carries arrays): the
                        # joiner's CRC must reject and re-fetch.
                        out = _CorruptingWriter(out, chunk.total_size - 1)
                    elif fault == "stall_donor":
                        out = _DripWriter(out)
                    elif fault == "truncate":
                        out = _TruncatingWriter(out, chunk.total_size // 2)
                        self.close_connection = True
                    try:
                        # Streams directly from the staged host arrays.
                        _serialization.write_prepared(chunk, out)
                    except (ConnectionError, TimeoutError, OSError):
                        self.close_connection = True
                    else:
                        # Donor-side heal progress for the fleet timeline
                        # (pairs with the joiner's heal_chunk_recv).
                        tracing.record(
                            "heal_chunk_serve",
                            step=step,
                            chunk=index,
                            bytes=int(chunk.total_size),
                        )
                transport._served_event.set()

        class DualStackServer(ThreadingHTTPServer):
            address_family = socket.AF_INET6
            daemon_threads = True

        self._server = DualStackServer(("::", 0), Handler)
        self._thread = threading.Thread(
            target=functools.partial(self._server.serve_forever, poll_interval=0.05), daemon=True, name="tpuft-http-ckpt"
        )
        self._thread.start()

    @staticmethod
    def _peer_of(handler: Any, split: Any) -> str:
        """Fairness identity of the requesting joiner: the ``?peer=`` tag
        its transport sent, falling back to the client address (so an
        untagged fetcher — curl, an old joiner — still gets exactly one
        sub-bucket per host instead of bypassing the fairness split)."""
        tags = urllib.parse.parse_qs(split.query).get("peer")
        return tags[0] if tags else str(handler.client_address[0])

    def _stage_codec(self) -> Optional[str]:
        """The codec for the NEXT stage: the pinned ctor codec, else this
        wire class's env knob (heal vs serving), read fresh per stage."""
        if self._codec_arg is not None:
            return self._codec_arg
        if self._wire == "serving":
            return wire_codec.serving_codec()
        return wire_codec.heal_codec()

    def _chunk_fault(self, step: int, index: int) -> Optional[str]:
        hook = self._fault_hook
        if hook is not None:
            return hook(step, index)
        # The serve port tags this donor's fault site, so the punisher can
        # target one donor of a stripe set (`heal_stream:<port>`); an
        # untargeted `heal_stream` arm still matches by site-family prefix.
        return faultinject.consume(
            f"heal_stream:{self._server.server_address[1]}"
        )

    # -- staged-version history (torchft_tpu/history.py) -------------------

    def _staged_version(self, step: int) -> Optional[_Staged]:
        """A resident HISTORICAL staged checkpoint for ``step`` (inline
        payloads only — in child mode the chunk bytes live in the child's
        /dev/shm ring and this process's store holds manifests)."""
        store = self._staged_store
        if store is None:
            return None
        payload = store.get(step)
        return payload if isinstance(payload, _Staged) else None

    def _staged_retracted(self, step: int) -> bool:
        store = self._staged_store
        return store is not None and store.is_retracted(step)

    def staged_steps(self) -> List[int]:
        """Resident staged versions, oldest first (the serving plane's
        pinned-version inventory)."""
        store = self._staged_store
        if store is not None:
            return store.steps()
        with self._cond:
            return [self._staged.step] if self._staged is not None else []

    def mark_stream(self, step: int, stream: str) -> None:
        """Progressive delivery: tags a staged version's stream
        ("canary"/"stable") for the wrong-stream chunk gate. The
        publisher calls this BEFORE announcing the version (and again on
        promotion), and forwards the tag in-child when a serve child owns
        the bytes — policy enforcement must hold at EVERY seam."""
        with self._cond:
            self._step_streams[int(step)] = str(stream)
        if self._serve_child is not None:
            try:
                self._serve_child.mark_stream(step, stream)
            except (ServeChildUnavailable, OSError):
                pass  # degraded child = inline serving, gated above

    def drop_staged(self, step: int, retracted: bool = True) -> None:
        """Retraction: removes one resident staged version (inline ring
        AND the child's /dev/shm ring) so it can never be served again;
        later reads answer 410 instead of 404."""
        store = self._staged_store
        if store is not None:
            store.drop(step, retracted=retracted)
        if self._serve_child is not None:
            self._serve_child.drop_staged(step)
        with self._cond:
            if self._staged is not None and self._staged.step == step:
                self._staged = None
            self._step_streams.pop(step, None)

    # -- serve-child plumbing ----------------------------------------------

    def register_error_callback(self, cb: Callable[[Exception], None]) -> None:
        """Funnel for serving-plane failures (serve-child crashes): the
        manager registers :meth:`Manager.report_error` here so the step
        loop observes a crashed sidecar only as a poisoned step, never as
        an exception past the step boundary."""
        self._error_cb = cb

    def _dispatch_serve_error(self, e: Exception) -> None:
        cb = self._error_cb
        if cb is not None:
            cb(e)
        else:
            logger.warning("heal-serve child error (no callback bound): %s", e)

    @property
    def serve_mode(self) -> str:
        return self._serve_mode

    def _child_serving(self) -> bool:
        child = self._serve_child
        return child is not None and child.alive() and not self._child_degraded

    def _child_metrics_text(self) -> Optional[str]:
        child = self._serve_child
        if child is None:
            return None
        snap = child.fetch_metrics_snapshot()
        if snap is None:
            return None
        return metrics.snapshot_to_prometheus(
            snap.get("metrics", {}),
            extra_labels={"process": "serve_child"},
            skip_type_names=metrics.REGISTRY.metric_names(),
        )

    def _child_metrics_json(self) -> Optional[Dict[str, Any]]:
        child = self._serve_child
        if child is None:
            return None
        snap = child.fetch_metrics_snapshot()
        if snap is None:
            return None
        return {"serve_child": snap.get("metrics", {})}

    def _stage_to_child(
        self, step: int, state_dict: Any, quorum_id: Optional[int]
    ) -> Dict[str, Any]:
        """Child-mode staging: serialize each chunk ONCE into a fresh
        epoch directory on the shared-memory filesystem (tmpfs pages, so
        this is a memcpy + C-speed CRC, not disk I/O), computing the
        per-chunk CRCs in the same pass, then hand the file names + the
        pre-pickled /meta bytes to the serving child. The Prepared chunk
        (and its host leaf refs) is dropped as soon as its file is
        written, so donor peak memory stays at one chunk beyond the
        caller's state."""
        child = self._serve_child
        if child is None or not child.alive():
            raise ServeChildUnavailable("no live serving child")
        codec = self._stage_codec()
        treedef, chunk_dicts, parts = _plan_chunks(
            state_dict, self._num_chunks, codec=codec, wire=self._wire
        )
        epoch, epoch_dir = child.new_epoch_dir()
        update = _CRC_UPDATERS[_CRC_ALGO]
        files: List[str] = []
        sizes: List[int] = []
        crcs: List[int] = []
        for i, chunk_dict in enumerate(chunk_dicts):
            prepared = _serialization.prepare(chunk_dict)
            name = f"chunk{i}.bin"
            with open(epoch_dir / name, "wb") as f:
                w = _TeeCRCWriter(f, update)
                _serialization.write_prepared(prepared, w)
            files.append(name)
            sizes.append(prepared.total_size)
            crcs.append(w.crc)
            chunk_dicts[i] = None  # type: ignore[call-overload]
            del prepared
        chunk_codecs = wire_codec.chunk_codecs_for(len(files), codec)
        digest = _checkpoint_digest(step, _CRC_ALGO, crcs, chunk_codecs)
        meta = _meta_bytes(
            step=step,
            quorum_id=quorum_id,
            num_chunks=len(files),
            treedef=treedef,
            crc_algo=_CRC_ALGO,
            chunk_crcs=crcs,
            digest=digest,
            parts={
                name: {"chunk": index, "nbytes": sizes[index]}
                for name, index in parts.items()
            },
            chunk_sizes=sizes,
            chunk_codecs=chunk_codecs,
        )
        child.stage(
            step=step,
            quorum_id=quorum_id,
            epoch=epoch,
            epoch_dir=epoch_dir,
            files=files,
            sizes=sizes,
            meta_bytes=meta,
            crc_algo=_CRC_ALGO,
            crcs=crcs,
            digest=digest,
            keep=self._keep_versions,
            chunk_codecs=chunk_codecs,
        )
        self._child_staged = True
        manifest = _stage_manifest(
            step, quorum_id, _CRC_ALGO, crcs, sizes, digest,
            tree_token=_tree_token(treedef),
            chunk_codecs=chunk_codecs,
        )
        if self._staged_store is not None:
            # Child mode: payload bytes live in the child's /dev/shm
            # ring; mirror the manifest here (same budget, same order)
            # for the serving plane's pinned-version inventory.
            self._staged_store.put(step, manifest, sum(sizes))
        return manifest

    # -- CheckpointTransport -----------------------------------------------

    def metadata(self) -> str:
        # In child mode peers heal from the SIDECAR's address; re-fetched
        # every quorum round, so a respawned (new port) or degraded
        # (fallen back to inline) sidecar is re-advertised within one
        # round.
        if self._child_serving():
            return self._serve_child.address()  # type: ignore[union-attr]
        host = socket.gethostname()
        port = self._server.server_address[1]
        return f"http://{host}:{port}"

    def send_checkpoint(
        self,
        dst_ranks: List[int],
        step: int,
        state_dict: Any,
        timeout: float,
        quorum_id: Optional[int] = None,
    ) -> Optional[Dict[str, Any]]:
        """Stages host copies of the state and starts serving them for
        ``step`` (tagged with ``quorum_id`` when the manager provides the
        era). Serving continues until :meth:`disallow_checkpoint`. In
        child mode the snapshot is handed to the serving child; any
        failure on that path degrades THIS stage (and the advertised
        address, from the next quorum round) to inline serving.

        Returns the staged integrity manifest (step, quorum_id, digest,
        per-chunk CRCs + sizes) — the serving plane's publisher announces
        it as the version descriptor; heal callers ignore it (the ABC
        return contract stays ``None``-compatible)."""
        if self._serve_child is not None:
            try:
                with metrics.timer(
                    "tpuft_heal_serve_stage_seconds", mode="child"
                ):
                    manifest = self._stage_to_child(step, state_dict, quorum_id)
                self._child_degraded = False
                metrics.inc("tpuft_heal_serve_stages_total", mode="child")
                return manifest
            except Exception as e:  # noqa: BLE001 — degrade to inline serving
                logger.warning(
                    "child-mode stage failed (%s); staging inline instead", e
                )
                metrics.inc("tpuft_heal_serve_fallbacks_total")
                self._child_degraded = True
        codec = self._stage_codec()
        with metrics.timer("tpuft_heal_serve_stage_seconds", mode="inline"):
            treedef, chunk_dicts, parts = _plan_chunks(
                state_dict, self._num_chunks, codec=codec, wire=self._wire
            )
            # prepare() keeps the host leaves + a small header per chunk;
            # the serialized bytes never exist as a second whole-payload
            # copy.
            chunks = [_serialization.prepare(chunk) for chunk in chunk_dicts]
            staged = _Staged(
                step, chunks, treedef, quorum_id=quorum_id, parts=parts,
                codec=codec,
            )
        if staged.chunk_codecs:
            tracing.record(
                "codec_stage",
                step=step,
                wire=self._wire,
                codec=staged.chunk_codecs[0],
                encoded_bytes=sum(staged.chunk_sizes),
            )
        metrics.inc("tpuft_heal_serve_stages_total", mode="inline")
        with self._cond:
            self._staged = staged
            self._cond.notify_all()
        if self._staged_store is not None:
            self._staged_store.put(step, staged, sum(staged.chunk_sizes))
        return _stage_manifest(
            step,
            quorum_id,
            staged.crc_algo,
            staged.chunk_crcs,
            staged.chunk_sizes,
            staged.digest,
            tree_token=staged.tree_token,
            chunk_codecs=staged.chunk_codecs,
        )

    def disallow_checkpoint(self) -> None:
        with self._cond:
            self._staged = None
        if self._serve_child is not None and self._child_staged:
            self._child_staged = False
            self._serve_child.disallow()

    def recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        quorum_id: Optional[int] = None,
        skip_parts: Optional[Set[str]] = None,
        donors: Optional[List[str]] = None,
        local_state: Optional[Any] = None,
        stripe_rotation: int = 0,
        donor_info: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> Any:
        # Donor set: the assigned donor first (it is the one the quorum
        # proved holds max_step state), then every other advertised donor,
        # deduped and capped. The digest is donor-independent by design,
        # so any of them can serve any chunk. ``donor_info`` (url ->
        # {"replica_id", "region"}, from the manager's quorum view) is
        # advisory: it keys the bandwidth EWMA by stable id and labels
        # same- vs cross-region bytes; absent entries degrade to
        # URL-keyed, region-less accounting.
        donor_info = donor_info or {}
        local_reg = netem.local_region()
        donor_urls = [metadata]
        if donors and heal_stripe_enabled():
            for url in donors:
                if url and url not in donor_urls:
                    donor_urls.append(url)
            donor_urls = donor_urls[: heal_stripe_max_donors()]
        meta, meta_url = self._fetch_meta(donor_urls, step, timeout, quorum_id)
        if meta_url != donor_urls[0]:
            # Donors whose /meta failed (dead, stale era, corrupt) are
            # dropped from the stripe set — their chunks would only burn
            # a reassignment cycle.
            donor_urls = donor_urls[donor_urls.index(meta_url):]
        num_chunks: int = meta["num_chunks"]
        treedef = meta["treedef"]
        chunk_crcs: Optional[List[int]] = meta.get("chunk_crcs")
        chunk_sizes: Optional[List[int]] = meta.get("chunk_sizes")
        digest: Optional[str] = meta.get("digest")
        algo: str = meta.get("crc_algo", "crc32")

        crc_update = _CRC_UPDATERS.get(algo)
        if chunk_crcs is not None and crc_update is None:
            raise HealIntegrityError(
                f"donor checksums use {algo!r}, unavailable on this host"
            )

        # Resume: reuse verified chunks from a previous failed attempt at
        # the same (step, digest) — valid across donors and quorum eras
        # because committed state at a step is bitwise identical.
        key = (step, digest) if digest is not None else None
        entry = self._heal_cache.get(key) if key is not None else None
        if entry is None:
            entry = _HealCacheEntry()
        # One in-flight heal total: stale (step, digest) partials are
        # dropped here; the surviving entry keeps per-chunk state.
        self._heal_cache = {key: entry} if key is not None else {}
        # Resumed-ness is decided by what a PREVIOUS attempt left behind,
        # before this attempt's delta matching adds local chunks (else a
        # delta match would make every genuine first fetch count as a
        # re-fetch and break the drills' exactness).
        resumed = bool(entry.chunks)
        # Shard-addressable skip: parts the joiner reconstructs through a
        # cheaper plane (ZeRO shard re-balance) are never fetched at all —
        # their chunks' leaves come back as None and the saved wire bytes
        # are pinned in tpuft_zero_heal_bytes_saved_total.
        parts_meta: Dict[str, Any] = meta.get("parts") or {}
        skipped_chunks: Dict[int, int] = {}
        if skip_parts:
            for name in skip_parts:
                info = parts_meta.get(name)
                if info is not None:
                    skipped_chunks[int(info["chunk"])] = int(
                        info.get("nbytes", 0)
                    )
            if skipped_chunks:
                metrics.inc(
                    "tpuft_zero_heal_bytes_saved_total",
                    sum(skipped_chunks.values()),
                )
        if resumed:
            for _chunk, nbytes in entry.chunks.values():
                metrics.inc("tpuft_heal_resumed_bytes_total", nbytes)

        # Delta rejoin: adopt chunks whose (crc, size) matches the donor's
        # manifest from the caller's stale-but-recent local state instead
        # of fetching them. Composes with skip_parts (neither shard parts
        # nor unchanged chunks cross the wire) and with the resume cache
        # (already-verified chunks are never re-checksummed).
        if (
            local_state is not None
            and heal_delta_enabled()
            and chunk_crcs is not None
            and crc_update is not None
        ):
            self._delta_match(
                entry=entry,
                local_state=local_state,
                meta=meta,
                crc_update=crc_update,
                skipped_chunks=skipped_chunks,
                step=step,
            )

        missing = [
            i
            for i in range(num_chunks)
            if i not in entry.chunks and i not in skipped_chunks
        ]

        # Chunk-URL query: the era fence plus this joiner's fairness tag
        # (the donor's pacer keys its per-joiner sub-bucket on it) plus —
        # under a WAN topology — the joiner's region, so the donor's
        # emulated-link shim can charge the (donor, joiner) pair's link.
        query: Dict[str, Any] = {"peer": self._peer_tag}
        if quorum_id is not None:
            query["quorum_id"] = quorum_id
        if local_reg is not None:
            query["region"] = local_reg
        era_tag = "?" + urllib.parse.urlencode(query)
        min_bps = _heal_min_bps()
        ingress_gbps = heal_ingress_gbps()
        ingress = _IngressPacer(ingress_gbps) if ingress_gbps > 0 else None

        def fetch_chunk(
            i: int, base: str, stripe_retry: bool = False
        ) -> int:
            # Stream-decode straight off the socket into final buffers: peak
            # memory = final leaves + one in-flight read window per chunk.
            expected = chunk_crcs[i] if chunk_crcs is not None else None
            attempts = [0]

            def consume(resp: Any) -> Tuple[Any, int]:
                attempts[0] += 1
                # A re-fetch is any transfer the first clean pass would not
                # have needed: a retry within this call's window, a chunk
                # that already streamed bytes in a failed attempt, or any
                # transfer of a RESUMED heal (the drill invariant: after a
                # failover this counter moves by exactly the missing
                # chunks). The not-yet-staged 404 race never reaches here,
                # so it never inflates the counter.
                with entry.lock:
                    if resumed or i in entry.attempted or attempts[0] > 1:
                        metrics.inc("tpuft_heal_chunk_refetches_total")
                    entry.attempted.add(i)
                reader = _GuardedReader(
                    resp,
                    crc_update=crc_update if expected is not None else None,
                    min_bps=min_bps,
                    ingress=ingress,
                )
                t0 = time.perf_counter()
                try:
                    # tpuft: allow(verify-before-adopt): stream-decode into discardable buffers — reader.crc is compared against the manifest CRC below before the chunk can be returned, and a mismatch raises HealChecksumError (the decoded object never escapes)
                    chunk = _serialization.load_state_dict(reader)
                except (HealStalledError, EOFError, ConnectionError):
                    # Fence and truncation classify themselves; the retry
                    # loop already knows which of them to re-try.
                    raise
                except Exception as decode_err:
                    # The decoder crashed mid-stream (e.g. a bit flip inside
                    # the pickled header renders it unreadable before any
                    # checksum comparison). Drain the rest of the body and
                    # let the checksum arbitrate: a mismatch is corruption
                    # (counted + re-fetched), a match is a real protocol
                    # bug that retrying cannot fix.
                    if expected is None:
                        raise
                    try:
                        while reader.read(1 << 16):
                            pass
                    except Exception:  # noqa: BLE001 — the CRC decides
                        pass
                    if reader.crc != expected:
                        metrics.inc("tpuft_heal_checksum_failures_total")
                        raise HealChecksumError(
                            f"chunk {i} stream corrupt (decode failed: "
                            f"{decode_err}; checksum {reader.crc:#010x} != "
                            f"{expected:#010x}); discarding the chunk"
                        ) from decode_err
                    raise
                if expected is not None and reader.crc != expected:
                    metrics.inc("tpuft_heal_checksum_failures_total")
                    raise HealChecksumError(
                        f"chunk {i} checksum mismatch: got {reader.crc:#010x}, "
                        f"want {expected:#010x} ({algo}); discarding the chunk"
                    )
                elapsed = time.perf_counter() - t0
                if elapsed > 0:
                    bps = reader.total / elapsed
                    metrics.histogram(
                        "tpuft_heal_stream_bytes_per_sec",
                        buckets=metrics.DEFAULT_BYTES_PER_SEC_BUCKETS,
                    ).observe(bps)
                    # Feed the per-donor EWMA the stripe planner weights by.
                    info = donor_info.get(base, {})
                    observe_donor_bandwidth(
                        donor_bw_key(info.get("replica_id"), base), bps
                    )
                # Same- vs cross-region byte attribution: only when both
                # sides' regions are known (a region-less fleet emits
                # neither label, keeping pre-topology dashboards exact).
                donor_reg = donor_info.get(base, {}).get("region")
                if local_reg is not None and donor_reg is not None:
                    metrics.inc(
                        "tpuft_wan_heal_bytes_total",
                        reader.total,
                        link=(
                            "same_region"
                            if donor_reg == local_reg
                            else "cross_region"
                        ),
                    )
                return chunk, reader.total

            # Same bounded retry as the meta fetch — the donor's serve
            # window can close and reopen between our GETs — widened to the
            # retryable failure set (404, connection refused/reset from a
            # restarting donor, truncation, checksum mismatch). Striped
            # workers narrow it: with other donors standing by, a dying
            # donor is fenced and reassigned instead of betting the window
            # on its supervised comeback.
            verified = _fetch_retry(
                f"{base}/checkpoint/{step}/{i}{era_tag}",
                timeout,
                consume=consume,
                retryable=_stripe_retryable if stripe_retry else None,
            )
            with entry.lock:
                entry.chunks[i] = verified
                entry.sources[i] = base
            # Heal progress in the fleet timeline: one instant per verified
            # chunk, so --explain-step can show how far along a heal was at
            # any moment (and which chunk a stall died on).
            tracing.record(
                "heal_chunk_recv",
                step=step,
                chunk=i,
                bytes=int(verified[1]),
                total_chunks=num_chunks,
                donor=base,
                region=donor_info.get(base, {}).get("region"),
            )
            return int(verified[1])

        if len(donor_urls) > 1 and len(missing) > 1:
            # Striped heal: one worker per donor over a byte-balanced
            # partition of the missing chunks; a failed donor's unfetched
            # ranges reassign to the survivors. ``stripe_rotation`` seeds
            # the plan so concurrent storm joiners spread across the
            # donor set instead of colliding on the same first stripe.
            self._striped_fetch(
                donor_urls=donor_urls,
                missing=missing,
                chunk_sizes=chunk_sizes,
                fetch_chunk=fetch_chunk,
                step=step,
                rotation=stripe_rotation,
                donor_info=donor_info,
            )
        elif len(missing) <= 1:
            for i in missing:
                fetch_chunk(i, donor_urls[0])
        else:
            base = donor_urls[0]
            with ThreadPoolExecutor(max_workers=min(len(missing), 8)) as pool:
                futs = [pool.submit(fetch_chunk, i, base) for i in missing]
                try:
                    for f in futs:
                        f.result()
                except BaseException:
                    # Fail fast: without this, the pool's __exit__ would run
                    # every QUEUED fetch to completion — each burning its
                    # own full retry window against a donor that may be
                    # gone — before the error reaches the manager. Verified
                    # chunks stay in the resume cache for the next attempt.
                    pool.shutdown(wait=False, cancel_futures=True)
                    raise

        merged: Dict[int, Any] = {}
        for chunk, _nbytes in entry.chunks.values():
            merged.update(chunk)
        if skipped_chunks:
            # Skipped parts' leaves substitute as None (the part owner
            # reconstructs them; see CheckpointTransport.recv_checkpoint).
            leaves = [merged.get(i) for i in range(treedef.num_leaves)]
        else:
            leaves = [merged[i] for i in range(len(merged))]
        result = jax.tree_util.tree_unflatten(treedef, leaves)
        # Quantized wire plane: decode AFTER every chunk verified its CRC
        # (and the digest bound the codec tags). Decode is structure-
        # driven and self-verifying — a lying tag raises here and the
        # state is never adopted (the caller funnels HealIntegrityError
        # into Manager.report_error like any other corrupt donor).
        if meta.get("chunk_codecs"):
            try:
                result = wire_codec.decode_state(result, wire=self._wire)
            except wire_codec.WireCodecError as e:
                raise HealIntegrityError(
                    f"encoded checkpoint failed codec validation: {e}"
                ) from e
            tracing.record(
                "codec_decode",
                step=step,
                wire=self._wire,
                codec=meta.get("codec"),
                encoded_bytes=sum(chunk_sizes or []),
            )
        if key is not None:
            self._heal_cache.pop(key, None)
        return result

    def _fetch_meta(
        self,
        donor_urls: List[str],
        step: int,
        timeout: float,
        quorum_id: Optional[int],
    ) -> Tuple[Dict[str, Any], str]:
        """Fetches and validates ``/meta`` from the first donor that serves
        an acceptable one (format, quorum era, digest binding). With one
        donor this is exactly the old behavior — the first failure raises;
        with a stripe set a dead or stale-era primary falls through to the
        next donor (the digest is donor-independent, so whichever meta
        wins describes every donor's bytes)."""
        last: Optional[BaseException] = None
        for url in donor_urls:
            try:
                meta = safe_loads(
                    _fetch_retry(f"{url}/checkpoint/{step}/meta", timeout)
                )
                # Format 2 = the pre-codec wire (raw-array chunks); 3 =
                # codec-encoded chunks with digest-bound tags. Anything
                # else is refused — this check is what makes a codec-less
                # peer fail CLEANLY against an encoded donor instead of
                # misdecoding encoded bytes as raw arrays.
                if not isinstance(meta, dict) or meta.get("format") not in (2, 3):
                    raise HealIntegrityError(
                        f"unrecognized checkpoint /meta format from {url}: "
                        f"{type(meta).__name__}"
                    )
                meta_codecs = meta.get("chunk_codecs")
                if meta.get("format") == 3:
                    if (
                        not isinstance(meta_codecs, list)
                        or len(meta_codecs) != meta.get("num_chunks")
                        or any(c not in wire_codec.CODECS for c in meta_codecs)
                    ):
                        raise HealIntegrityError(
                            f"format-3 /meta from {url} carries an invalid "
                            f"chunk_codecs list: {meta_codecs!r}"
                        )
                donor_era = meta.get("quorum_id")
                # Era fence: never heal backwards from a survivor still
                # staged for an older quorum (its state may predate
                # commits we must match).
                if (
                    quorum_id is not None
                    and donor_era is not None
                    and donor_era != quorum_id
                ):
                    metrics.inc("tpuft_heal_era_rejects_total")
                    raise HealEraMismatch(
                        f"donor staged quorum era {donor_era}, joiner is "
                        f"healing in era {quorum_id}: rejecting the "
                        "stale-era heal"
                    )
                digest = meta.get("digest")
                chunk_crcs = meta.get("chunk_crcs")
                # The digest must be exactly the checksums' binding —
                # verified BEFORE any transfer so a tampered/buggy meta
                # never costs a payload fetch and mismatched state is
                # never adopted.
                if digest is not None and chunk_crcs is not None:
                    algo = meta.get("crc_algo", "crc32")
                    if (
                        _checkpoint_digest(step, algo, chunk_crcs, meta_codecs)
                        != digest
                    ):
                        raise HealIntegrityError(
                            "whole-checkpoint digest does not match the "
                            "per-chunk checksums (and codec tags) in /meta: "
                            "refusing the heal"
                        )
                return meta, url
            except Exception as e:  # noqa: BLE001 — re-raised when last
                last = e
                if url != donor_urls[-1]:
                    logger.warning(
                        "heal /meta from %s failed (%s); trying the next "
                        "donor in the stripe set",
                        url,
                        e,
                    )
        assert last is not None
        raise last

    def _delta_match(
        self,
        entry: _HealCacheEntry,
        local_state: Any,
        meta: Dict[str, Any],
        crc_update: Callable[[int, Any], int],
        skipped_chunks: Dict[int, int],
        step: int,
    ) -> None:
        """Delta rejoin: plans ``local_state`` into the donor's exact chunk
        layout, checksums each still-needed local chunk, and adopts those
        whose (crc, size) matches the donor's manifest — serialized-byte
        equality implies bitwise-equal leaves, so the post-heal state is
        identical to a full fetch. Any layout mismatch (different tree,
        chunk count, part map, or a failed local plan) falls back to the
        full fetch: matching is an optimization, never a correctness
        dependency."""
        num_chunks: int = meta["num_chunks"]
        chunk_crcs: List[int] = meta["chunk_crcs"]
        chunk_sizes: Optional[List[int]] = meta.get("chunk_sizes")
        parts_meta: Dict[str, Any] = meta.get("parts") or {}
        t0 = time.perf_counter()

        def fall_back(reason: str) -> None:
            metrics.inc("tpuft_heal_delta_fallbacks_total")
            logger.warning(
                "delta rejoin manifest mismatch (%s); falling back to the "
                "full fetch",
                reason,
            )

        try:
            base_n = num_chunks - len(parts_meta)
            # Plan with the DONOR's codec: both sides encode through the
            # same deterministic host codec, so a committed-equal chunk
            # serializes to identical encoded bytes and the (crc, size)
            # match works unchanged on the compressed payload. An
            # unknown donor codec falls back to the full fetch below.
            treedef, chunk_dicts, local_parts = _plan_chunks(
                local_state, base_n,
                codec=meta.get("codec"), wire=self._wire,
            )
        except Exception as e:  # noqa: BLE001 — never fail the heal here
            fall_back(f"local chunk plan failed: {e}")
            return
        donor_parts = {
            name: int(info["chunk"]) for name, info in parts_meta.items()
        }
        if (
            treedef != meta["treedef"]
            or len(chunk_dicts) != num_chunks
            or local_parts != donor_parts
        ):
            fall_back(
                "local state plans into a different chunk layout than the "
                "donor's manifest"
            )
            return
        matched = 0
        saved = 0
        for i, chunk_dict in enumerate(chunk_dicts):
            if i in skipped_chunks or i in entry.chunks:
                continue
            prepared = _serialization.prepare(chunk_dict)
            w = _CRCWriter(crc_update)
            _serialization.write_prepared(prepared, w)
            if w.crc == chunk_crcs[i] and (
                chunk_sizes is None
                or int(prepared.total_size) == int(chunk_sizes[i])
            ):
                with entry.lock:
                    entry.chunks[i] = (chunk_dict, int(prepared.total_size))
                    entry.sources[i] = "delta"
                matched += 1
                saved += int(prepared.total_size)
        metrics.observe(
            "tpuft_heal_delta_manifest_seconds", time.perf_counter() - t0
        )
        if matched:
            metrics.inc("tpuft_heal_delta_chunks_matched_total", matched)
            metrics.inc("tpuft_heal_delta_bytes_saved_total", saved)
        tracing.record(
            "heal_delta",
            step=step,
            matched=matched,
            total_chunks=num_chunks,
            bytes_saved=saved,
        )

    def _striped_fetch(
        self,
        donor_urls: List[str],
        missing: List[int],
        chunk_sizes: Optional[List[int]],
        fetch_chunk: Callable[..., int],
        step: int,
        rotation: int = 0,
        donor_info: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> None:
        """Fetches ``missing`` striped across ``donor_urls``: one worker per
        donor walks its byte-balanced stripe; each chunk verifies through
        the same CRC + progress-watchdog path as a single-donor heal (a
        gray donor fences only its own stripe). A donor that fails mid-
        stripe has its unfetched chunks reassigned round-robin to the
        surviving donors; when the last donor dies the remaining error
        raises to the caller (the resume cache keeps everything already
        verified). When the per-donor bandwidth EWMA has history for this
        donor set, the plan is TIME-balanced (bytes proportional to
        measured bandwidth); a cold cache keeps the byte-balanced plan."""
        cond = threading.Condition()
        donor_info = donor_info or {}
        donor_keys = [
            donor_bw_key(donor_info.get(u, {}).get("replica_id"), u)
            for u in donor_urls
        ]
        weights = _donor_weights(donor_keys)
        stripes = _plan_stripes(
            missing,
            chunk_sizes,
            len(donor_urls),
            rotation=rotation,
            weights=weights,
        )
        # The plan in the fleet timeline: which rotation this joiner
        # derived, how wide its donor set is, and (when the EWMA had
        # history) the per-donor bandwidth weights + regions the plan
        # used — --explain-step pairs concurrent joiners' plans to show
        # a storm's donor spread and WHY the byte split is skewed.
        tracing.record(
            "heal_stripe_plan",
            step=step,
            donors=len(donor_urls),
            rotation=rotation % max(len(donor_urls), 1),
            chunks=len(missing),
            weights=(
                [round(w, 1) for w in weights] if weights is not None else None
            ),
            regions=[donor_info.get(u, {}).get("region") for u in donor_urls],
        )
        queues: Dict[str, deque] = {
            url: deque(stripe) for url, stripe in zip(donor_urls, stripes)
        }
        live: Set[str] = set(donor_urls)
        state = {"inflight": 0, "error": None}
        reassigned: Set[int] = set()

        def size_of(i: int) -> int:
            return int(chunk_sizes[i]) if chunk_sizes is not None else 0

        def work_left() -> bool:
            return state["inflight"] > 0 or any(queues.values())

        def worker(url: str) -> None:
            fetched = 0
            fetched_bytes = 0
            t0 = time.perf_counter()
            while True:
                with cond:
                    if state["error"] is not None:
                        break
                    queue = queues[url]
                    if queue:
                        i = queue.popleft()
                        state["inflight"] += 1
                    elif url not in live or not work_left():
                        cond.notify_all()
                        break
                    else:
                        # Park until a reassignment lands in our queue or
                        # the heal completes; the timeout is a liveness
                        # backstop, not a pacing decision.
                        cond.wait(0.1)
                        continue
                try:
                    nbytes = fetch_chunk(i, url, stripe_retry=True)
                except BaseException as e:  # noqa: BLE001 — donor-fatal
                    with cond:
                        state["inflight"] -= 1
                        live.discard(url)
                        orphans = [i] + list(queues[url])
                        queues[url].clear()
                        orphan_bytes = sum(size_of(c) for c in orphans)
                        metrics.inc("tpuft_heal_stripe_donor_failures_total")
                        metrics.inc(
                            "tpuft_heal_stripe_reassigned_chunks_total",
                            len(orphans),
                        )
                        if orphan_bytes:
                            metrics.inc(
                                "tpuft_heal_stripe_reassigned_bytes_total",
                                orphan_bytes,
                            )
                        tracing.record(
                            "heal_stripe_reassign",
                            step=step,
                            donor=url,
                            chunks=len(orphans),
                            bytes=orphan_bytes,
                            survivors=len(live),
                            reason=f"{type(e).__name__}: {e}"[:200],
                        )
                        logger.warning(
                            "striped heal: donor %s failed mid-stripe (%s); "
                            "reassigning %d chunk(s) to %d survivor(s)",
                            url,
                            e,
                            len(orphans),
                            len(live),
                        )
                        if live:
                            reassigned.update(orphans)
                            targets = sorted(live)
                            for j, c in enumerate(orphans):
                                queues[targets[j % len(targets)]].append(c)
                        else:
                            state["error"] = e
                        cond.notify_all()
                    break
                with cond:
                    state["inflight"] -= 1
                    fetched += 1
                    fetched_bytes += nbytes
                    metrics.inc("tpuft_heal_stripe_chunks_total")
                    metrics.inc("tpuft_heal_stripe_bytes_total", nbytes)
                    if i in reassigned:
                        # The acceptance invariant: bytes re-fetched after
                        # a donor death equal exactly its unverified
                        # remainder — this counter is the observable side.
                        metrics.inc(
                            "tpuft_heal_stripe_refetched_bytes_total", nbytes
                        )
                    if not work_left():
                        cond.notify_all()
            # One span per donor stripe for the fleet timeline: who served
            # how much, and how long their stripe ran.
            tracing.record(
                "heal_stripe",
                step=step,
                donor=url,
                chunks=fetched,
                bytes=fetched_bytes,
                duration_s=round(time.perf_counter() - t0, 6),
                fenced=url not in live,
                region=donor_info.get(url, {}).get("region"),
            )

        with ThreadPoolExecutor(
            max_workers=len(donor_urls), thread_name_prefix="tpuft-stripe"
        ) as pool:
            futs = [pool.submit(worker, url) for url in donor_urls]
            for f in futs:
                f.result()
        if state["error"] is not None:
            raise state["error"]

    def shutdown(self, wait: bool = True) -> None:
        if self._serve_child is not None:
            self._serve_child.shutdown(wait=wait)
        self._server.shutdown()
        self._server.server_close()
        if wait:
            self._thread.join(timeout=5)


def _is_retryable_fetch_error(e: BaseException) -> bool:
    """Failures worth re-trying against the same URL within the bounded
    window: not-yet-staged (404), a dying/restarting donor (refused, reset,
    truncated stream), and a checksum mismatch (re-fetch the chunk). A
    watchdog fence is NOT retryable — a gray donor will drip again and the
    whole point is failing over fast — and neither are timeouts (the
    per-recv inactivity bound) or other HTTP statuses (400/409 are
    protocol-level rejections, e.g. a stale era)."""
    if isinstance(e, HealStalledError):
        return False
    if isinstance(e, urllib.error.HTTPError):
        return e.code == 404
    if isinstance(e, HealChecksumError):
        return True
    if isinstance(e, urllib.error.URLError):
        return isinstance(e.reason, ConnectionError)
    # RemoteDisconnected/IncompleteRead surface as ConnectionError
    # subclasses; EOFError is _serialization's truncated-stream signal.
    return isinstance(e, (ConnectionError, EOFError))


def _stripe_retryable(e: BaseException) -> bool:
    """Retry policy for a fetch inside a STRIPE set: with other donors
    standing by, a dying/refusing/truncating donor is fenced immediately
    and its chunks reassigned — betting the bounded window on its
    supervised comeback (the single-donor rationale) would stall the whole
    stripe on one dead peer. Only the staging race (404: the donor has not
    staged this step yet) and a transient checksum mismatch re-try against
    the same donor."""
    if isinstance(e, HealStalledError):
        return False
    if isinstance(e, urllib.error.HTTPError):
        return e.code == 404
    return isinstance(e, HealChecksumError)


def _fetch_retry(
    url: str,
    timeout: float,
    consume: Optional[Callable[[Any], Any]] = None,
    retryable: Optional[Callable[[BaseException], bool]] = None,
) -> Any:
    """Fetch with bounded retry on transient failures; ``consume`` (default:
    read all bytes) processes the open response, letting chunk fetches
    stream-decode off the socket through the same retry loop as the meta
    fetch. ``retryable`` overrides the failure classification (default
    :func:`_is_retryable_fetch_error`; striped fetches pass the narrower
    :func:`_stripe_retryable`).

    Retryable failures (see :func:`_is_retryable_fetch_error`): a 404 from
    the donor means "nothing staged for this step" — often *not yet*: the
    joiner's fetch races the donor staging inside its own quorum round, and
    under a loaded host the donor's serve window can even close (commit →
    disallow) and REOPEN on the retry round before a slow fetcher gets
    through. A connection refused/reset or truncated stream means the donor
    is dying or restarting mid-heal — the same bounded window covers its
    supervised comeback instead of failing the heal on the first dropped
    byte. A checksum mismatch re-fetches the chunk. A real wrong-step/
    never-staged/corrupt-forever fetch still fails when the window expires.

    The retry window is PER FETCH and opens at this fetch's FIRST failure,
    so time spent actually transferring bytes (legitimate on a slow link)
    never charges anyone's retry budget, and a chunk whose turn in the
    fetch pool comes late gets a full window against the reopen race —
    leftovers of a window shared with the meta fetch could not span the
    donor's reopen interval. The resulting worst-case retry waiting for a
    whole recv_checkpoint is (1 + ceil(num_chunks / pool_width)) x
    timeout — bounded by pool waves, not by chunk count, since in-pool
    chunks wait out the same wall-clock window concurrently. The socket
    timeout stays ``timeout`` per attempt (urllib's timeout is a per-recv
    inactivity bound, not a wall-time bound)."""
    delay = 0.05
    retry_deadline: Optional[float] = None
    is_retryable = retryable if retryable is not None else _is_retryable_fetch_error
    while True:
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return consume(resp) if consume is not None else resp.read()
        except Exception as e:
            now = time.monotonic()
            if retry_deadline is None:
                retry_deadline = now + timeout
            if not is_retryable(e) or now + delay >= retry_deadline:
                raise
        time.sleep(delay)
        delay = min(delay * 1.5, 1.0)
