"""HTTP checkpoint transport.

Role-equivalent of the reference's ``HTTPTransport``
(checkpointing/http_transport.py:39-299): a threaded HTTP server streams the
staged state pytree to healing peers; an RWLock-style gate keeps the staged
data immutable while serving and blocks serving while the trainer mutates
state. Chunked mode splits flattened pytree leaves round-robin into N
independently-fetchable chunks pulled in parallel.

Routes: ``/checkpoint/{step}/meta``, ``/checkpoint/{step}/full``,
``/checkpoint/{step}/{chunk_index}``.
"""

from __future__ import annotations

import pickle
import socket
import time
import threading
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

import jax

from torchft_tpu import metrics
from torchft_tpu._safe_pickle import safe_loads
from torchft_tpu.utils import netem
from torchft_tpu.checkpointing import _serialization
from torchft_tpu.checkpointing.transport import CheckpointTransport

__all__ = ["HTTPTransport"]


class _Staged:
    """Prepared (header + host leaves) per chunk — ONE host copy total; the
    HTTP handlers stream straight from these buffers (no serialized copy,
    the round-1 2x-peak-memory finding)."""

    def __init__(self, step: int, chunks: List[Any], treedef: Any) -> None:
        self.step = step
        self.chunks = chunks  # List[_serialization.Prepared]
        self.treedef = treedef


class HTTPTransport(CheckpointTransport[Any]):
    """Serves the staged checkpoint over HTTP; IPv6 dual-stack like the
    reference so it works across heterogeneous TPU pods."""

    def __init__(self, timeout: float = 60.0, num_chunks: int = 0) -> None:
        self._timeout = timeout
        self._num_chunks = num_chunks
        # Condition gates serving: a GET for step S parks until the trainer
        # stages S (send_checkpoint) — the reference's RWLock allow/disallow
        # gate (http_transport.py:182-242). Without this the joiner's fetch
        # races the donor's staging inside the same quorum round.
        self._cond = threading.Condition()
        self._staged: Optional[_Staged] = None
        self._served_event = threading.Event()

        transport = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: Any) -> None:  # silence
                pass

            def do_GET(self) -> None:
                # The transport's port doubles as this process's scrape
                # endpoint: every training replica already listens here for
                # heals, so /metrics needs no extra server or port.
                if metrics._serve_metrics_http(self, metrics.REGISTRY, self.path):
                    return
                parts = self.path.strip("/").split("/")
                if len(parts) != 3 or parts[0] != "checkpoint":
                    self.send_error(404, "unknown route")
                    return
                try:
                    step = int(parts[1])
                except ValueError:
                    self.send_error(400, "bad step")
                    return
                stall_t0 = time.perf_counter()
                with transport._cond:
                    transport._cond.wait_for(
                        lambda: transport._staged is not None
                        and transport._staged.step == step,
                        timeout=transport._timeout,
                    )
                    staged = transport._staged
                # Donor-side stall: how long this GET parked waiting for the
                # trainer to stage the requested step.
                metrics.observe(
                    "tpuft_ckpt_donor_stall_seconds",
                    time.perf_counter() - stall_t0,
                )
                if staged is None or staged.step != step:
                    self.send_error(
                        404,
                        f"no checkpoint staged for step {step}"
                        + (f" (have {staged.step})" if staged else ""),
                    )
                    return
                if parts[2] == "meta":
                    body = pickle.dumps((len(staged.chunks), staged.treedef))
                    self.send_response(200)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif parts[2] == "full":
                    total = sum(8 + c.total_size for c in staged.chunks)
                    self.send_response(200)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(total))
                    self.end_headers()
                    out = self.wfile
                    if netem.enabled():  # emulated-DCN heal path
                        netem.pace_latency()
                        out = netem.PacingWriter(out)
                    for chunk in staged.chunks:
                        out.write(chunk.total_size.to_bytes(8, "big"))
                        _serialization.write_prepared(chunk, out)
                else:
                    try:
                        chunk = staged.chunks[int(parts[2])]
                    except (ValueError, IndexError):
                        self.send_error(400, "bad chunk index")
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(chunk.total_size))
                    self.end_headers()
                    out = self.wfile
                    if netem.enabled():  # emulated-DCN heal path
                        netem.pace_latency()
                        # Serialization time interleaves with the writes —
                        # one up-front sleep would hold the wire silent
                        # past the joiner's per-recv inactivity timeout.
                        out = netem.PacingWriter(out)
                    # Streams directly from the staged host arrays.
                    _serialization.write_prepared(chunk, out)
                transport._served_event.set()

        class DualStackServer(ThreadingHTTPServer):
            address_family = socket.AF_INET6
            daemon_threads = True

        self._server = DualStackServer(("::", 0), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="tpuft-http-ckpt"
        )
        self._thread.start()

    # -- CheckpointTransport -----------------------------------------------

    def metadata(self) -> str:
        host = socket.gethostname()
        port = self._server.server_address[1]
        return f"http://{host}:{port}"

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: Any, timeout: float
    ) -> None:
        """Stages host copies of the state and starts serving them for
        ``step``. Serving continues until :meth:`disallow_checkpoint`."""
        leaves, treedef = jax.tree_util.tree_flatten(state_dict)
        leaves = [_serialization._to_host(leaf) for leaf in leaves]
        n = self._num_chunks if self._num_chunks > 0 else 1
        n = min(n, max(len(leaves), 1))
        chunk_dicts: List[Dict[int, Any]] = [dict() for _ in range(n)]
        for i, leaf in enumerate(leaves):
            chunk_dicts[i % n][i] = leaf
        # prepare() keeps the host leaves + a small header per chunk; the
        # serialized bytes never exist as a second whole-payload copy.
        chunks = [_serialization.prepare(chunk) for chunk in chunk_dicts]
        with self._cond:
            self._staged = _Staged(step, chunks, treedef)
            self._cond.notify_all()

    def disallow_checkpoint(self) -> None:
        with self._cond:
            self._staged = None

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: float
    ) -> Any:
        base = f"{metadata}/checkpoint/{step}"
        num_chunks, treedef = safe_loads(_fetch_retry_404(f"{base}/meta", timeout))

        def fetch_chunk(i: int) -> Any:
            # Stream-decode straight off the socket into final buffers: peak
            # memory = final leaves + one in-flight read window per chunk.
            # Same 404 retry as the meta fetch: the donor's serve window can
            # close (commit -> disallow) BETWEEN our meta and chunk requests
            # — nothing pins the staged object across GETs — and reopen on
            # its retry round.
            return _fetch_retry_404(
                f"{base}/{i}", timeout, consume=_serialization.load_state_dict
            )

        if num_chunks == 1:
            chunks = [fetch_chunk(0)]
        else:
            with ThreadPoolExecutor(max_workers=min(num_chunks, 8)) as pool:
                futs = [pool.submit(fetch_chunk, i) for i in range(num_chunks)]
                try:
                    chunks = [f.result() for f in futs]
                except BaseException:
                    # Fail fast: without this, the pool's __exit__ would run
                    # every QUEUED fetch to completion — each burning its
                    # own full retry window against a donor that may be
                    # gone — before the error reaches the manager.
                    pool.shutdown(wait=False, cancel_futures=True)
                    raise
        merged: Dict[int, Any] = {}
        for chunk in chunks:
            merged.update(chunk)
        leaves = [merged[i] for i in range(len(merged))]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def shutdown(self, wait: bool = True) -> None:
        self._server.shutdown()
        self._server.server_close()
        if wait:
            self._thread.join(timeout=5)


def _fetch_retry_404(
    url: str,
    timeout: float,
    consume: Optional[Callable[[Any], Any]] = None,
) -> Any:
    """Fetch with bounded retry on 404; ``consume`` (default: read all
    bytes) processes the open response, letting chunk fetches stream-decode
    off the socket through the same retry loop as the meta fetch.

    A 404 from the donor means "nothing staged for this step" — which is
    often *not yet*: the joiner's fetch races the donor staging inside its
    own quorum round, and under a loaded host (many GIL-scheduled ranks)
    the donor's serve window can even close (commit → disallow) and REOPEN
    on the retry round — up to a training step later — before a slow
    fetcher gets through. Retrying turns both races into a wait; a real
    wrong-step/never-staged fetch still fails when the window expires.

    The retry window is PER FETCH and opens at this fetch's FIRST 404, so
    time spent actually transferring bytes (legitimate on a slow link)
    never charges anyone's retry budget, and a chunk whose turn in the
    fetch pool comes late gets a full window against the reopen race —
    leftovers of a window shared with the meta fetch could not span the
    donor's reopen interval. The resulting worst-case retry waiting for a
    whole recv_checkpoint is (1 + ceil(num_chunks / pool_width)) x
    timeout — bounded by pool waves, not by chunk count, since in-pool
    chunks wait out the same wall-clock window concurrently. The socket
    timeout stays ``timeout`` per attempt (urllib's timeout is a per-recv
    inactivity bound, not a wall-time bound)."""
    delay = 0.05
    retry_deadline: Optional[float] = None
    while True:
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return consume(resp) if consume is not None else resp.read()
        except urllib.error.HTTPError as e:
            now = time.monotonic()
            if retry_deadline is None:
                retry_deadline = now + timeout
            if e.code != 404 or now + delay >= retry_deadline:
                raise
        time.sleep(delay)
        delay = min(delay * 1.5, 1.0)
