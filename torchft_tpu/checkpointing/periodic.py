"""Periodic (disk) checkpointing for cold restarts.

The second checkpoint axis (reference SURVEY §5: live healing never touches
disk; users must separately persist model/optimizer state *plus the manager
state_dict* for job-level restarts). This helper wraps orbax with the
manager bookkeeping so a restore resumes at the right committed step::

    ckpt = PeriodicCheckpointer(manager, "/ckpts/run1", save_every=100)
    restored = ckpt.restore_or_none(        # on startup
        template={"params": opt.params, "opt_state": opt.opt_state}
    )
    ...
    ckpt.maybe_save({"params": opt.params, "opt_state": opt.opt_state})

Only one replica group needs to write (they are bitwise identical after any
committed step); by convention the participating rank-0 group saves —
``maybe_save`` checks ``manager.participating_rank() == 0``.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Dict, Optional

from torchft_tpu.manager import Manager

logger = logging.getLogger(__name__)

__all__ = ["PeriodicCheckpointer"]


class PeriodicCheckpointer:
    def __init__(
        self,
        manager: Manager,
        directory: str,
        save_every: int = 100,
        max_to_keep: int = 3,
        only_replica_rank_zero: bool = True,
    ) -> None:
        import orbax.checkpoint as ocp

        self._manager = manager
        self._save_every = save_every
        self._only_rank_zero = only_replica_rank_zero
        self._mngr = ocp.CheckpointManager(
            Path(directory).absolute(),
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def maybe_save(self, state: Dict[str, Any], force: bool = False) -> bool:
        """Saves when the committed step hits the cadence (and this group is
        the designated writer). Returns whether a save happened."""
        import orbax.checkpoint as ocp

        step = self._manager.current_step()
        if not force and (step == 0 or step % self._save_every != 0):
            return False
        if self._only_rank_zero and self._manager.participating_rank() != 0:
            return False
        import jax

        if self._only_rank_zero and jax.process_count() == 1 and self._manager._group_rank != 0:
            # Single-process-jax groups: exactly one writer (local rank 0 of
            # the participating-rank-0 group) — concurrent writers racing one
            # orbax step dir corrupt the checkpoint. Under a multi-process
            # jax cluster, saves of group-sharded arrays are COLLECTIVE, so
            # every rank of the writing group must call save together.
            return False
        payload = {
            "user": state,
            "tpuft": self._manager.state_dict(),
        }
        self._mngr.save(step, args=ocp.args.StandardSave(payload))
        logger.info("saved periodic checkpoint at step %d", step)
        return True

    def restore_or_none(
        self, template: Optional[Dict[str, Any]] = None
    ) -> Optional[Dict[str, Any]]:
        """Restores the latest checkpoint: loads the manager bookkeeping and
        returns the user state (None when no checkpoint exists).

        Pass the live user-state pytree as ``template`` to get the restored
        state back in ITS structure — without one, orbax launders containers
        (optax named-tuples come back as lists), which breaks loaders that
        tree-map the result against live state (e.g.
        ``Optimizer._load_state_dict``)."""
        import orbax.checkpoint as ocp

        step = self._mngr.latest_step()
        if step is None:
            return None
        if template is not None:
            args = ocp.args.StandardRestore(
                {"user": template, "tpuft": self._manager.state_dict()}
            )
        else:
            args = ocp.args.StandardRestore()
        payload = self._mngr.restore(step, args=args)
        self._manager.load_state_dict(
            {k: int(v) for k, v in payload["tpuft"].items()}
        )
        logger.info("restored periodic checkpoint from step %d", step)
        return payload["user"]

    def wait_until_finished(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()
