"""Checkpoint transport over the ProcessGroup itself.

Role-equivalent of the reference's ``PGTransport``
(checkpointing/pg_transport.py:163-300): the donor sends a pickled structure
header followed by the raw leaf buffers as point-to-point messages on the
(already-configured) replica process group; the receiver can optionally
receive **in place** into an existing same-structure state dict, avoiding
allocation for large models.

On TPU this is the DCN device-to-device path: arrays stage device→host on
the donor and host→device on the joiner.
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

import jax
import numpy as np

from torchft_tpu._safe_pickle import safe_loads

from torchft_tpu.checkpointing import _serialization
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.parallel.process_group import ProcessGroup

__all__ = ["PGTransport"]


@dataclass
class _StateDictMeta:
    step: int
    treedef_bytes: bytes  # pickled treedef
    leaf_metas: List[Optional[_serialization.ArrayMeta]]
    non_array: List[Any]


class PGTransport(CheckpointTransport[Any]):
    """Sends checkpoints over PG send/recv.

    Args:
        pg: the (configured) process group to ride.
        state_dict_template: optional zero-arg callable returning a pytree of
            arrays to receive into (in-place path, reference pg_transport.py:
            230-286); shapes/dtypes must match the sender's.
    """

    def __init__(
        self,
        pg: ProcessGroup,
        timeout: float = 60.0,
        state_dict_template: Optional[Callable[[], Any]] = None,
    ) -> None:
        self._pg = pg
        self._timeout = timeout
        self._template = state_dict_template

    def metadata(self) -> str:
        return "<pg>"

    def send_checkpoint(
        self,
        dst_ranks: List[int],
        step: int,
        state_dict: Any,
        timeout: float,
        quorum_id: Optional[int] = None,
    ) -> None:
        # quorum_id is accepted for CheckpointTransport API parity and
        # ignored: PG send/recv pairs are matched inside one already-
        # configured (single-era) process group, so a cross-era transfer
        # cannot form in the first place.
        treedef, metas, leaves = _serialization.state_dict_meta(state_dict)
        meta = _StateDictMeta(
            step=step,
            treedef_bytes=pickle.dumps(treedef),
            leaf_metas=metas,
            non_array=[leaf for leaf, m in zip(leaves, metas) if m is None],
        )
        from torchft_tpu.checkpointing._serialization import ShardedLeafMeta

        meta_buf = np.frombuffer(pickle.dumps(meta), dtype=np.uint8).copy()
        arrays = []
        for leaf, m in zip(leaves, metas):
            if isinstance(m, ShardedLeafMeta):
                arrays.extend(np.ascontiguousarray(data) for _, data in leaf.shards)
            elif m is not None:
                arrays.append(np.ascontiguousarray(leaf))
        for dst in dst_ranks:
            self._pg.send([np.array([len(meta_buf)], dtype=np.int64)], dst).wait(timeout)
            self._pg.send([meta_buf], dst).wait(timeout)
            for arr in arrays:
                self._pg.send([arr], dst).wait(timeout)

    def recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        quorum_id: Optional[int] = None,
        skip_parts: Optional[Any] = None,
        donors: Optional[Any] = None,
        local_state: Optional[Any] = None,
        stripe_rotation: int = 0,
        donor_info: Optional[Any] = None,
    ) -> Any:
        # skip_parts / donors / local_state / stripe_rotation / donor_info
        # ignored: the PG stream is positional, so parts are not
        # independently addressable, there is exactly one sender, and a
        # delta diff has nothing to key on — fetch everything (the
        # ABC-documented degradation).
        (length_arr,) = self._pg.recv([np.empty(1, dtype=np.int64)], src_rank).wait(timeout)
        (meta_buf,) = self._pg.recv(
            [np.empty(int(length_arr[0]), dtype=np.uint8)], src_rank
        ).wait(timeout)
        meta: _StateDictMeta = safe_loads(meta_buf.tobytes())
        if meta.step != step:
            raise ValueError(f"checkpoint step mismatch: wanted {step}, got {meta.step}")
        treedef = safe_loads(meta.treedef_bytes)

        # In-place template: reuse existing buffers where shapes match.
        template_leaves: Optional[List[Any]] = None
        if self._template is not None:
            t_leaves, t_treedef = jax.tree_util.tree_flatten(self._template())
            if pickle.dumps(t_treedef) == meta.treedef_bytes:
                template_leaves = t_leaves

        from torchft_tpu.checkpointing._serialization import ShardedLeaf, ShardedLeafMeta

        non_array_iter = iter(meta.non_array)
        leaves: List[Any] = []
        for i, leaf_meta in enumerate(meta.leaf_metas):
            if leaf_meta is None:
                leaves.append(next(non_array_iter))
                continue
            if isinstance(leaf_meta, ShardedLeafMeta):
                dtype = _serialization._resolve_dtype(leaf_meta.dtype)
                shards = []
                for key, shape in zip(leaf_meta.shard_keys, leaf_meta.shard_shapes):
                    (received,) = self._pg.recv(
                        [np.empty(shape, dtype=dtype)], src_rank
                    ).wait(timeout)
                    shards.append((key, received))
                leaves.append(
                    ShardedLeaf(leaf_meta.global_shape, leaf_meta.dtype, shards)
                )
                continue
            dtype = _serialization._resolve_dtype(leaf_meta.dtype)
            if (
                template_leaves is not None
                and isinstance(template_leaves[i], np.ndarray)
                and template_leaves[i].shape == tuple(leaf_meta.shape)
                and template_leaves[i].dtype == dtype
            ):
                target = template_leaves[i]
            else:
                target = np.empty(leaf_meta.shape, dtype=dtype)
            (received,) = self._pg.recv([target], src_rank).wait(timeout)
            # The PG decodes into `target`'s storage when shape/dtype match
            # (true in-place receive); otherwise it returns a fresh array.
            leaves.append(received)
        return jax.tree_util.tree_unflatten(treedef, leaves)
