"""Donor sidecar: out-of-process heal serving.

The reference's heal design rests on "serving never perturbs the donor"
(reference http_transport.py:226-242 stages CPU copies precisely so the
step loop keeps running), but in-process serving still shares the donor's
GIL and, on a core-starved host, its CPU: TRANSPORT_BENCH_12GB measured a
1088% donor step inflation while serving a 12 GB heal from the inline
threads. This module makes the isolation *structural*: a pre-spawned
**serving child process** takes ownership of an immutable snapshot of the
staged checkpoint and answers all ``/meta``, ``/chunk``, ``/full`` and
``/metrics`` heal traffic from its own interpreter, so GIL or core
contention from serving cannot touch the donor's step loop even on a
one-core box.

Snapshot handoff is POSIX shared memory by way of the filesystem: the
donor serializes each staged chunk once into a file under a
shared-memory-backed directory (``$TPUFT_HEAL_SERVE_DIR``, default
``/dev/shm`` when present — tmpfs pages, i.e. RAM, not disk), computing
the PR-4 integrity metadata (per-chunk CRCs + whole-checkpoint digest +
staged ``quorum_id``) in the same single pass, and hands the child the
file names plus the exact pre-pickled ``/meta`` bytes over a stdin/stdout
JSON control pipe. The child never unpickles anything (it needs neither
jax nor numpy — it is spawned as a plain script and stays import-light),
it just era-fences and streams bytes; the joiner-side verification path
is unchanged, so a corrupt, stale, or crashed child can never produce
adopted state that the inline mode would have refused.

Lifecycle (the donor-side :class:`ServeChild` handle):

- **spawn**: at transport construction (pre-spawned, so its address is
  known before the first quorum advertises metadata);
- **restage**: every ``send_checkpoint`` writes a fresh epoch directory
  and the child atomically swaps to it (deleting the old epoch), so a
  quorum change re-stages the era the same way the inline path does —
  and the manager's quorum-change drain hooks run *before* the donor
  send, so the child never sees speculative pipelined state;
- **disallow**: forwarded at the commit boundary; the child drops (and
  deletes) its snapshot, later GETs park/404 exactly like inline;
- **crash**: a watcher thread funnels unexpected child death into the
  registered error callback (Manager.report_error) — never raises past
  the step boundary — and respawns up to ``$TPUFT_HEAL_SERVE_MAX_RESTARTS``
  times; while degraded the transport falls back to inline serving so
  heals keep working;
- **shutdown**: control-pipe shutdown, bounded wait, then SIGKILL; the
  donor removes the serve directory.

The child deprioritizes itself (``os.nice``, ``$TPUFT_HEAL_SERVE_NICE``,
default 10) and can bound its egress rate (``$TPUFT_HEAL_SERVE_GBPS``):
recovery traffic yields to training for CPU and for the wire, which is
the same isolation highly-available DP training systems apply to their
recovery planes (PAPERS.md: HA data-parallel training on mesh networks;
Prime's collective communications library).
"""

from __future__ import annotations

import base64
import functools
import importlib.util
import json
import logging
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "ServeChild",
    "ServeChildCrashed",
    "ServeChildUnavailable",
    "ENV_SERVE_MODE",
    "ENV_SERVE_DIR",
    "ENV_SERVE_NICE",
    "ENV_SERVE_GBPS",
    "ENV_SERVE_MAX_RESTARTS",
    "ENV_SERVING_TENANT_TOKENS",
    "ENV_SERVING_TENANT_GBPS",
    "DEFAULT_TENANT",
    "UnknownTenantToken",
    "serve_dir_root",
    "serve_rate_gbps",
    "heal_priority_share",
    "serving_tenant_tokens",
    "serving_tenant_gbps",
    "tenant_of_authorization",
    "maybe_pace_serve",
]

ENV_SERVE_MODE = "TPUFT_HEAL_SERVE_MODE"
ENV_SERVE_DIR = "TPUFT_HEAL_SERVE_DIR"
ENV_SERVE_NICE = "TPUFT_HEAL_SERVE_NICE"
ENV_SERVE_GBPS = "TPUFT_HEAL_SERVE_GBPS"
ENV_SERVE_PRIORITY_SHARE = "TPUFT_HEAL_SERVE_PRIORITY_SHARE"
ENV_SERVE_MAX_RESTARTS = "TPUFT_HEAL_SERVE_MAX_RESTARTS"
ENV_SERVING_TENANT_TOKENS = "TPUFT_SERVING_TENANT_TOKENS"
ENV_SERVING_TENANT_GBPS = "TPUFT_SERVING_TENANT_GBPS"

# Serving readers that present no bearer token all share one sub-bucket.
DEFAULT_TENANT = "default"

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Dual-context imports: in the donor this module is part of the package;
# in the spawned child it runs as a bare script (``python serve_child.py``)
# and must NOT import torchft_tpu/__init__ (which pulls jax — seconds of
# import and a backend the serving plane has no use for). The three
# runtime deps (metrics / faultinject / netem) are stdlib-only modules, so
# the child loads them straight from their files.
# ---------------------------------------------------------------------------


def _load_by_path(name: str, path: Path):
    spec = importlib.util.spec_from_file_location(name, path)
    assert spec is not None and spec.loader is not None, path
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


if __package__:
    from torchft_tpu import metrics, tracing
    from torchft_tpu.utils import faultinject, netem
    from torchft_tpu.serving import rollout
else:  # pragma: no cover - exercised only inside the spawned child
    _PKG = Path(__file__).resolve().parent.parent
    metrics = _load_by_path("tpuft_serve_metrics", _PKG / "metrics.py")
    faultinject = _load_by_path(
        "tpuft_serve_faultinject", _PKG / "utils" / "faultinject.py"
    )
    netem = _load_by_path("tpuft_serve_netem", _PKG / "utils" / "netem.py")
    # rollout reuses the already-loaded tpuft_serve_metrics module (its own
    # dual-context header checks sys.modules), staying jax-free in-child.
    rollout = _load_by_path("tpuft_serve_rollout", _PKG / "serving" / "rollout.py")


class ServeChildCrashed(RuntimeError):
    """The serving child died unexpectedly; funneled into report_error by
    the watcher (the step loop itself never observes the crash)."""


class ServeChildUnavailable(RuntimeError):
    """No live serving child to hand a snapshot to (crashed out of its
    respawn budget, or still degraded); callers fall back to inline."""


def serve_dir_root() -> str:
    """Root for serve snapshots: ``$TPUFT_HEAL_SERVE_DIR``, else the
    shared-memory tmpfs when the platform has one (RAM-backed — staging a
    snapshot is a memcpy, not disk I/O), else the temp dir."""
    configured = os.environ.get(ENV_SERVE_DIR)
    if configured:
        return configured
    if os.path.isdir("/dev/shm"):
        return "/dev/shm"
    return tempfile.gettempdir()


def serve_rate_gbps(default: float = 0.0) -> float:
    """Egress bound for heal serving (``$TPUFT_HEAL_SERVE_GBPS``; <= 0 =
    unthrottled). Applies in BOTH serve modes at the chunk/full write
    seam, so recovery traffic can be bounded away from the training
    wire's share."""
    try:
        return float(os.environ.get(ENV_SERVE_GBPS, str(default)))
    except ValueError:
        return default


def serving_tenant_tokens() -> Dict[str, str]:
    """Bearer-token descriptor table for serving URLs
    (``$TPUFT_SERVING_TENANT_TOKENS`` = ``token:tenant,token:tenant``).
    A reader (or a relay pulling on a tenant's behalf) sends
    ``Authorization: Bearer <token>``; the serve seam maps it to the
    tenant whose egress sub-bucket the bytes charge against. Malformed
    entries are skipped (fairness must not die on a typo — the doctor
    WARNs on them)."""
    raw = os.environ.get(ENV_SERVING_TENANT_TOKENS, "")
    table: Dict[str, str] = {}
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        token, sep, tenant = entry.partition(":")
        if sep and token.strip() and tenant.strip():
            table[token.strip()] = tenant.strip()
    return table


def serving_tenant_gbps() -> Dict[str, float]:
    """Per-tenant egress entitlements
    (``$TPUFT_SERVING_TENANT_GBPS`` = ``tenant:gbps,tenant:gbps``). Each
    value is the tenant's absolute Gbps cap AND its weight in the
    proportional split of the serving class's share of a paced aggregate
    (``TPUFT_HEAL_SERVE_GBPS``); unlisted tenants weigh 1.0 and are
    bounded only by the class share. Non-numeric values are skipped."""
    raw = os.environ.get(ENV_SERVING_TENANT_GBPS, "")
    table: Dict[str, float] = {}
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        tenant, sep, value = entry.partition(":")
        if not (sep and tenant.strip()):
            continue
        try:
            gbps = float(value)
        except ValueError:
            continue
        if gbps > 0:
            table[tenant.strip()] = gbps
    return table


class UnknownTenantToken(Exception):
    """A serving request carried a bearer token the tenant table does not
    know — answered 401 (a misconfigured credential must surface, not
    silently ride the anonymous bucket)."""


def tenant_of_authorization(authorization: Optional[str]) -> Optional[str]:
    """Maps a request's ``Authorization`` header to its tenant: ``None``
    for an anonymous request (no bearer token — heal traffic and
    tokenless readers), the tenant name for a known token, and
    :class:`UnknownTenantToken` for a present-but-unknown one."""
    if not authorization:
        return None
    scheme, _, token = authorization.partition(" ")
    if scheme.lower() != "bearer" or not token.strip():
        raise UnknownTenantToken(f"unsupported Authorization scheme {scheme!r}")
    tenant = serving_tenant_tokens().get(token.strip())
    if tenant is None:
        raise UnknownTenantToken("bearer token not in the tenant table")
    return tenant


def heal_priority_share(default: float = 0.8) -> float:
    """Fraction of the paced egress reserved for HEAL streams while both
    traffic classes are active (``$TPUFT_HEAL_SERVE_PRIORITY_SHARE``,
    clamped to (0, 1)). Serving readers are throughput traffic; a healing
    joiner is the fleet's recovery path — it must never be starved by a
    reader fan-out that got to the bucket first."""
    try:
        share = float(os.environ.get(ENV_SERVE_PRIORITY_SHARE, str(default)))
    except ValueError:
        return default
    return min(max(share, 0.01), 0.99)


class _ServePacer:
    """Process-wide token bucket for the serve-egress bound: every paced
    stream debits the SAME clock, so N parallel chunk streams (a striped
    or pooled joiner) share the configured rate instead of each getting
    it — ``TPUFT_HEAL_SERVE_GBPS`` bounds the donor's aggregate egress,
    like the NIC share it stands for.

    Two traffic classes share the bucket with a priority split instead of
    first-come-first-served: ``heal`` (joiner recovery streams) and
    ``serving`` (committed-weights readers, torchft_tpu/serving). While
    both classes are active — a class counts as active while it debited
    within the last :data:`_ACTIVE_WINDOW_SEC` — heal streams get
    :func:`heal_priority_share` of the rate and serving readers split the
    remainder, so N concurrent readers structurally cannot starve a
    healing joiner; a lone class gets the full rate. Each class keeps its
    own virtual-finish-time clock, so the split holds regardless of which
    class's writes arrive first.

    Inside the heal class the rate splits AGAIN into per-peer sub-buckets
    (the mass-rejoin storm case): each healing peer — identified by the
    ``peer`` tag its joiner sends on chunk URLs, falling back to the
    client address — gets an equal share of the heal rate while it is
    active, so one fast joiner (or one joiner with more parallel chunk
    streams) structurally cannot starve the other N-1 joiners of a storm.
    A peer idle past the activity window stops counting against the
    split, so a lone joiner still gets the full heal share. Sub-bucket
    state is pruned on the same window, bounding memory by the number of
    CONCURRENTLY active peers, not by fleet history.

    The serving class splits the SAME way into per-tenant sub-buckets
    (the multi-tenant read fan-out): each tenant — identified by the
    bearer token its readers send (``TPUFT_SERVING_TENANT_TOKENS``);
    tokenless readers share :data:`DEFAULT_TENANT` — gets a share of the
    serving rate weighted by its ``TPUFT_SERVING_TENANT_GBPS``
    entitlement (unlisted tenants weigh 1.0), bounded by that
    entitlement as an absolute cap, so one tenant's fan-out structurally
    cannot starve another's while the heal class keeps its priority
    share above ALL tenants. With no aggregate bound configured
    (``gbps <= 0``) only the absolute per-tenant caps pace — the
    tenancy plane works standalone."""

    _ACTIVE_WINDOW_SEC = 0.5

    def __init__(
        self,
        gbps: float,
        heal_share: Optional[float] = None,
        tenant_gbps: Optional[Dict[str, float]] = None,
    ) -> None:
        self.gbps = gbps
        self._share = heal_share if heal_share is not None else heal_priority_share()
        self.tenant_gbps = (
            dict(tenant_gbps) if tenant_gbps is not None else serving_tenant_gbps()
        )
        self._lock = threading.Lock()
        self._last_debit = {"heal": float("-inf"), "serving": float("-inf")}
        # Per-class sub-buckets: key -> [virtual-ready clock, last debit]
        # (heal keys are peers; serving keys are tenants).
        self._peers: Dict[str, List[float]] = {}
        self._tenants: Dict[str, List[float]] = {}

    @staticmethod
    def _touch(
        buckets: Dict[str, List[float]], key: str, now: float, window: float
    ) -> List[float]:
        entry = buckets.setdefault(key, [now, float("-inf")])
        entry[1] = now
        for k in [k for k, v in buckets.items() if now - v[1] >= window]:
            del buckets[k]
        return entry

    @staticmethod
    def _charge(entry: List[float], nbytes: int, rate_gbps: float, now: float) -> float:
        if rate_gbps <= 0 or rate_gbps == float("inf"):
            return 0.0
        spb = 8.0 / (rate_gbps * 1e9)
        start = entry[0] if entry[0] > now else now
        entry[0] = start + nbytes * spb
        return max(entry[0] - now, 0.0)

    def debit(
        self,
        nbytes: int,
        cls: str = "heal",
        peer: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> float:
        """Charges ``nbytes`` against ``cls``'s share of the bucket (and
        against ``peer``'s / ``tenant``'s sub-bucket of that share);
        returns how long the caller must sleep so the aggregate rate, the
        heal-priority split, and the per-peer / per-tenant fairness
        splits all hold."""
        other = "serving" if cls == "heal" else "heal"
        with self._lock:
            now = time.monotonic()
            self._last_debit[cls] = now
            contended = now - self._last_debit[other] < self._ACTIVE_WINDOW_SEC
            if self.gbps > 0:
                rate = self.gbps
                if contended:
                    rate *= self._share if cls == "heal" else 1.0 - self._share
            else:
                rate = float("inf")  # only per-tenant caps (if any) pace
            if cls == "heal":
                key = peer if peer is not None else "_anon"
                entry = self._touch(self._peers, key, now, self._ACTIVE_WINDOW_SEC)
                metrics.set_gauge("tpuft_heal_serve_active_peers", len(self._peers))
                # Equal per-peer shares of the heal rate.
                per_peer = (
                    rate / max(len(self._peers), 1)
                    if rate != float("inf")
                    else float("inf")
                )
                return self._charge(entry, nbytes, per_peer, now)
            key = tenant if tenant is not None else DEFAULT_TENANT
            entry = self._touch(self._tenants, key, now, self._ACTIVE_WINDOW_SEC)
            metrics.set_gauge("tpuft_serving_active_tenants", len(self._tenants))
            metrics.inc("tpuft_serving_tenant_bytes_total", nbytes, tenant=key)
            # Weighted share of the serving rate, capped by the tenant's
            # absolute entitlement.
            weight = self.tenant_gbps.get(key, 1.0)
            total_weight = sum(
                self.tenant_gbps.get(k, 1.0) for k in self._tenants
            )
            share = (
                rate * weight / total_weight if rate != float("inf") else float("inf")
            )
            cap = self.tenant_gbps.get(key, float("inf"))
            return self._charge(entry, nbytes, min(share, cap), now)


_pacer: Optional[_ServePacer] = None
_pacer_lock = threading.Lock()


def _shared_pacer(gbps: float) -> _ServePacer:
    global _pacer
    tenant_cfg = serving_tenant_gbps()
    with _pacer_lock:
        if (
            _pacer is None
            or _pacer.gbps != gbps
            or _pacer.tenant_gbps != tenant_cfg
        ):
            _pacer = _ServePacer(gbps, tenant_gbps=tenant_cfg)
        return _pacer


class _RateWriter:
    """Paces writes through the process-wide bucket in bounded slices
    (sleep released between slices, so a paced serve is IO-bound, not a
    CPU hog)."""

    def __init__(
        self,
        raw: Any,
        pacer: _ServePacer,
        slice_bytes: int = 1 << 18,
        cls: str = "heal",
        peer: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> None:
        self._raw = raw
        self._pacer = pacer
        self._slice = slice_bytes
        self._cls = cls
        self._peer = peer
        self._tenant = tenant

    def write(self, data: Any) -> None:
        mv = memoryview(data)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        for off in range(0, len(mv), self._slice):
            part = mv[off : off + self._slice]
            self._raw.write(part)
            delay = self._pacer.debit(
                len(part), cls=self._cls, peer=self._peer, tenant=self._tenant
            )
            if delay > 0:
                time.sleep(delay)


def maybe_pace_serve(
    out: Any,
    cls: str = "heal",
    peer: Optional[str] = None,
    tenant: Optional[str] = None,
) -> Any:
    """Wraps ``out`` with the (process-aggregate) serve-rate bound when
    configured. ``cls`` is the traffic class the bytes charge against:
    ``heal`` (default — every existing heal-serve seam) or ``serving``
    (committed-weights readers); ``peer`` identifies the healing joiner
    for the per-peer fairness split inside the heal class, ``tenant``
    the reader's tenant for the per-tenant split inside the serving
    class (see :class:`_ServePacer`). Serving traffic is paced whenever
    EITHER the aggregate bound or a per-tenant entitlement is
    configured; heal traffic only under the aggregate bound."""
    gbps = serve_rate_gbps()
    if gbps > 0 or (cls == "serving" and serving_tenant_gbps()):
        return _RateWriter(out, _shared_pacer(gbps), cls=cls, peer=peer, tenant=tenant)
    return out


def _delta_response(
    query: str,
    crc_algo: str,
    chunk_crcs: Optional[List[int]],
    chunk_sizes: Optional[List[int]],
    digest: Optional[str],
    chunk_codecs: Optional[List[str]] = None,
) -> bytes:
    """The ``/checkpoint/{step}/delta`` manifest-diff body, shared by the
    inline handler and the serving child (stdlib-only by construction):
    the caller sends its local per-chunk CRCs (``?crcs=a,b,...&algo=...``)
    and gets back which chunk indices differ from the staged checkpoint —
    the donor-side twin of the joiner's delta-rejoin match, usable from
    curl when debugging why a delta rejoin fetched more than expected."""
    params = urllib.parse.parse_qs(query)
    algo = params.get("algo", [crc_algo])[0]
    try:
        crcs = [
            int(c) for c in params.get("crcs", [""])[0].split(",") if c
        ]
    except ValueError:
        crcs = None  # type: ignore[assignment]
    body: Dict[str, Any] = {
        "crc_algo": crc_algo,
        "num_chunks": len(chunk_crcs) if chunk_crcs is not None else 0,
        "digest": digest,
    }
    if chunk_codecs:
        # Quantized stage: a caller diffing raw-f32 CRCs against encoded
        # chunks would see everything differ — name the codec so the
        # operator knows which format the staged manifest speaks.
        body["chunk_codecs"] = list(chunk_codecs)
    if (
        crcs is None
        or chunk_crcs is None
        or algo != crc_algo
        or len(crcs) != len(chunk_crcs)
    ):
        # A manifest the staged layout cannot be diffed against: the
        # caller must fall back to the full fetch.
        body["compatible"] = False
    else:
        differing = [i for i, (a, b) in enumerate(zip(crcs, chunk_crcs)) if a != b]
        body["compatible"] = True
        body["differing"] = differing
        if chunk_sizes is not None:
            body["differing_bytes"] = sum(chunk_sizes[i] for i in differing)
    return json.dumps(body).encode()


# ---------------------------------------------------------------------------
# Donor-side fault writers (chaos drills). Shared by the inline handler
# (http_transport) and the serving child; stdlib-only by construction.
# ---------------------------------------------------------------------------


class _CorruptingWriter:
    """Flips one bit of the byte at ``flip_at`` — the injected fault the
    joiner's per-chunk checksum must catch."""

    def __init__(self, raw: Any, flip_at: int) -> None:
        self._raw = raw
        self._off = 0
        self._flip_at = flip_at
        self.flipped = False

    def write(self, data: Any) -> None:
        mv = memoryview(data)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        n = len(mv)
        if not self.flipped and self._off <= self._flip_at < self._off + n:
            buf = bytearray(mv)
            buf[self._flip_at - self._off] ^= 0x01
            self.flipped = True
            self._raw.write(bytes(buf))
        else:
            self._raw.write(mv)
        self._off += n


class _DripWriter:
    """Serves at a trickle (default 256 B/s) — the gray donor the joiner's
    minimum-progress watchdog must fence."""

    def __init__(self, raw: Any, bps: float = 256.0, slice_bytes: int = 64) -> None:
        self._raw = raw
        self._delay = slice_bytes / float(bps)
        self._slice = slice_bytes

    def write(self, data: Any) -> None:
        mv = memoryview(data)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        for off in range(0, len(mv), self._slice):
            self._raw.write(mv[off : off + self._slice])
            time.sleep(self._delay)


class _TruncatingWriter:
    """Writes only the first ``limit`` bytes then swallows the rest — with
    the connection closed after the handler returns, the joiner sees a
    truncated stream (EOF mid-chunk)."""

    def __init__(self, raw: Any, limit: int) -> None:
        self._raw = raw
        self._left = limit

    def write(self, data: Any) -> None:
        if self._left <= 0:
            return
        mv = memoryview(data)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        take = mv[: self._left]
        self._left -= len(take)
        self._raw.write(take)


# ---------------------------------------------------------------------------
# Child process (runs as a bare script; stdlib + the path-loaded modules).
# ---------------------------------------------------------------------------


class _FileStaged:
    """One immutable staged snapshot: epoch directory of serialized chunk
    files + the exact pre-pickled /meta bytes + the era tag + the chunk
    checksums (so the child can answer /delta without unpickling /meta,
    which would need jax for the treedef)."""

    def __init__(self, cmd: Dict[str, Any]) -> None:
        self.epoch: int = cmd["epoch"]
        self.step: int = cmd["step"]
        self.quorum_id: Optional[int] = cmd["quorum_id"]
        self.dir = Path(cmd["dir"])
        self.files: List[str] = cmd["files"]
        self.sizes: List[int] = cmd["sizes"]
        self.meta_bytes: bytes = base64.b64decode(cmd["meta_b64"])
        self.crc_algo: str = cmd.get("crc_algo", "crc32")
        self.chunk_crcs: Optional[List[int]] = cmd.get("crcs")
        self.digest: Optional[str] = cmd.get("digest")
        self.chunk_codecs: Optional[List[str]] = cmd.get("chunk_codecs")
        # Progressive delivery: the version's stream tag ("canary"/
        # "stable"; None = heal stage, ungated). Mutated by the "stream"
        # control op on promotion — the ONE mutable field, policy routing
        # only, never integrity metadata.
        self.stream: Optional[str] = cmd.get("stream")

    def delete(self) -> None:
        shutil.rmtree(self.dir, ignore_errors=True)


def _child_stream_file(path: Path, out: Any, slice_bytes: int = 1 << 20) -> int:
    total = 0
    with open(path, "rb") as f:
        while True:
            data = f.read(slice_bytes)
            if not data:
                return total
            out.write(data)
            total += len(data)


def _child_main(argv: Optional[List[str]] = None) -> int:
    import argparse
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    parser = argparse.ArgumentParser(description="tpuft heal-serving child")
    parser.add_argument("--timeout", type=float, default=60.0)
    parser.add_argument("--nice", type=int, default=0)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.WARNING, format="[tpuft-serve-child %(levelname)s] %(message)s"
    )
    if args.nice > 0:
        try:
            os.nice(args.nice)
        except OSError:
            pass
    # Batch scheduling where available: serving is throughput work; it
    # must never wakeup-preempt a training step mid-flight on a shared
    # core (it still gets its nice-weighted share).
    try:
        os.sched_setscheduler(0, os.SCHED_BATCH, os.sched_param(0))
    except (AttributeError, OSError, PermissionError):
        pass

    cond = threading.Condition()
    # "staged" = the current (newest) snapshot — the heal-gating target;
    # "history" = the step-labeled ring of resident snapshots (epoch
    # dirs stay on the shared-memory filesystem until the budget evicts
    # them), so pinned-version serving reads old versions from /dev/shm.
    state: Dict[str, Any] = {"staged": None, "history": {}, "closing": False}

    def wait_for_staged(step: int) -> Optional[_FileStaged]:
        t0 = time.perf_counter()
        with cond:
            cond.wait_for(
                lambda: step in state["history"] or state["closing"],
                timeout=args.timeout,
            )
            staged = state["history"].get(step, state["staged"])
        metrics.observe(
            "tpuft_ckpt_donor_stall_seconds", time.perf_counter() - t0
        )
        return staged

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt: str, *a: Any) -> None:  # silence
            pass

        def do_GET(self) -> None:
            if metrics._serve_metrics_http(self, metrics.REGISTRY, self.path):
                return
            split = urllib.parse.urlsplit(self.path)
            parts = split.path.strip("/").split("/")
            if len(parts) != 3 or parts[0] != "checkpoint":
                self.send_error(404, "unknown route")
                return
            try:
                step = int(parts[1])
            except ValueError:
                self.send_error(400, "bad step")
                return
            staged = wait_for_staged(step)
            if staged is None or staged.step != step:
                self.send_error(
                    404,
                    f"no checkpoint staged for step {step}"
                    + (f" (have {staged.step})" if staged else ""),
                )
                return
            # Era fence, verified IN-CHILD: the snapshot carries the
            # quorum era it was staged for, so even a child left behind
            # by a quorum change answers a mismatched joiner 409 instead
            # of bytes its /meta does not describe.
            want_era = urllib.parse.parse_qs(split.query).get("quorum_id")
            if (
                want_era
                and staged.quorum_id is not None
                and str(staged.quorum_id) != want_era[0]
            ):
                metrics.inc("tpuft_heal_serve_era_rejects_total")
                self.send_error(
                    409,
                    f"stale quorum era: staged {staged.quorum_id}, "
                    f"joiner wants {want_era[0]}",
                )
                return
            route = parts[2] if parts[2] in ("meta", "full", "delta") else "chunk"
            metrics.inc("tpuft_heal_serve_requests_total", route=route)
            # Per-joiner fairness identity: the joiner tags its fetches
            # (?peer=...), falling back to the client address — either
            # way, one joiner's parallel chunk streams share ONE
            # sub-bucket of the paced heal share.
            peer = urllib.parse.parse_qs(split.query).get(
                "peer", [str(self.client_address[0])]
            )[0]
            # WAN topology parity with the inline handler: the joiner's
            # ?region= tag selects the directed (donor, joiner) emulated
            # link (the child inherits the topology envs; its own region
            # comes from TPUFT_EMULATED_REGION or the replica-id map).
            peer_reg = urllib.parse.parse_qs(split.query).get("region")
            peer_region = peer_reg[0] if peer_reg else None
            # Tenant/auth parity with the inline handler: a bearer token
            # marks serving-class read traffic (per-tenant sub-bucket);
            # an unknown token is refused in-child too.
            try:
                tenant = tenant_of_authorization(self.headers.get("Authorization"))
            except UnknownTenantToken as e:
                metrics.inc("tpuft_serving_auth_rejects_total")
                self.send_error(401, f"unknown serving tenant: {e}")
                return
            # Progressive-delivery seam, enforced IN-CHILD like the era
            # fence above: a tenant whose rollout policy does not cover
            # this version's stream is refused 403 before any bytes move;
            # tokenless fetches (heal plane, relay tree) stay ungated.
            if tenant is not None:
                deny = rollout.wrong_stream_chunk_reason(
                    tenant, step, staged.stream
                )
                if deny is not None:
                    metrics.inc(
                        "tpuft_rollout_wrong_stream_rejects_total",
                        seam="child",
                    )
                    self.send_error(403, deny)
                    return
            if route == "meta":
                body = staged.meta_bytes
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                metrics.inc("tpuft_heal_serve_bytes_total", len(body))
                return
            if route == "delta":
                # Manifest diff, era-fenced like every stripe route above.
                body = _delta_response(
                    split.query,
                    crc_algo=staged.crc_algo,
                    chunk_crcs=staged.chunk_crcs,
                    chunk_sizes=staged.sizes,
                    digest=staged.digest,
                    chunk_codecs=staged.chunk_codecs,
                )
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if route == "full":
                total = sum(8 + size for size in staged.sizes)
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(total))
                if netem.enabled():
                    self.send_header(netem.PACED_HEADER, "1")
                self.end_headers()
                out = self.wfile
                if netem.enabled():
                    netem.pace_latency(peer_region)
                    out = netem.PacingWriter(out, peer_region=peer_region)
                if tenant is not None:
                    out = maybe_pace_serve(out, cls="serving", tenant=tenant)
                else:
                    out = maybe_pace_serve(out, peer=peer)
                try:
                    for name, size in zip(staged.files, staged.sizes):
                        out.write(size.to_bytes(8, "big"))
                        _child_stream_file(staged.dir / name, out)
                    metrics.inc("tpuft_heal_serve_bytes_total", total)
                except (ConnectionError, TimeoutError, OSError):
                    self.close_connection = True
                return
            try:
                index = int(parts[2])
                name, size = staged.files[index], staged.sizes[index]
            except (ValueError, IndexError):
                self.send_error(400, "bad chunk index")
                return
            # Chaos seams. kill_serve_child serves this chunk COMPLETELY
            # and then dies (flush + immediate exit): the drill gets at
            # least one verified chunk in the joiner's resume cache while
            # concurrent streams are cut mid-flight — the donor process
            # observes the death only through its watcher's report_error.
            die_after = (
                faultinject.consume("serve_child") == "kill_serve_child"
            )
            # The serve port tags this donor's fault site so the punisher
            # can target ONE donor of a stripe set (`heal_stream:<port>`);
            # an untargeted `heal_stream` arm matches by site-family prefix.
            fault = faultinject.consume(
                f"heal_stream:{self.server.server_address[1]}"
            )
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(size))
            if netem.enabled():
                self.send_header(netem.PACED_HEADER, "1")
            self.end_headers()
            out = self.wfile
            if netem.enabled():
                netem.pace_latency(peer_region)
                out = netem.PacingWriter(out, peer_region=peer_region)
            if tenant is not None:
                out = maybe_pace_serve(out, cls="serving", tenant=tenant)
            else:
                out = maybe_pace_serve(out, peer=peer)
            if fault == "corrupt_stream":
                out = _CorruptingWriter(out, size - 1)
            elif fault == "stall_donor":
                out = _DripWriter(out)
            elif fault == "truncate":
                out = _TruncatingWriter(out, size // 2)
                self.close_connection = True
            try:
                sent = _child_stream_file(staged.dir / name, out)
                metrics.inc("tpuft_heal_serve_bytes_total", sent)
            except (ConnectionError, TimeoutError, OSError):
                self.close_connection = True
                return
            if die_after:
                try:
                    self.wfile.flush()
                except OSError:
                    pass
                os._exit(3)

    class DualStackServer(ThreadingHTTPServer):
        address_family = socket.AF_INET6
        daemon_threads = True

        def handle_error(self, request: Any, client_address: Any) -> None:
            # Joiners being fenced / failing over close connections mid
            # stream; that is routine, not stderr-traceback-worthy.
            pass

    server = DualStackServer(("::", 0), Handler)
    server_thread = threading.Thread(
        target=functools.partial(server.serve_forever, poll_interval=0.05), daemon=True, name="tpuft-serve-child-http"
    )
    server_thread.start()
    sys.stdout.write(
        json.dumps(
            {"event": "ready", "port": server.server_address[1], "pid": os.getpid()}
        )
        + "\n"
    )
    sys.stdout.flush()

    def _emit(event: Dict[str, Any]) -> None:
        try:
            sys.stdout.write(json.dumps(event) + "\n")
            sys.stdout.flush()
        except OSError:
            pass

    # Control loop on the MAIN thread; stdin EOF (donor died, even by
    # SIGKILL) is the orphan guard: clean up the snapshot and exit.
    try:
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                cmd = json.loads(line)
            except json.JSONDecodeError:
                logging.warning("bad control line: %r", line[:200])
                continue
            op = cmd.get("cmd")
            if op == "stage":
                staged = _FileStaged(cmd)
                keep = max(1, int(cmd.get("keep", 1)))
                doomed: List[_FileStaged] = []
                with cond:
                    # Restage at the same step swaps its epoch; the ring
                    # keeps the newest `keep` steps resident (keep=1 is
                    # exactly the pre-history donor behavior).
                    old = state["history"].pop(staged.step, None)
                    if old is not None:
                        doomed.append(old)
                    state["history"][staged.step] = staged
                    for s in sorted(state["history"])[:-keep]:
                        doomed.append(state["history"].pop(s))
                    state["staged"] = staged
                    cond.notify_all()
                for d in doomed:
                    d.delete()
                _emit({"event": "staged", "step": staged.step, "epoch": staged.epoch})
            elif op == "drop":
                # Retraction: one resident version leaves the ring (and
                # /dev/shm) — later reads of it fail instead of serving
                # retracted bytes.
                with cond:
                    dropped = state["history"].pop(int(cmd.get("step", -1)), None)
                    if state["staged"] is dropped and dropped is not None:
                        remaining = sorted(state["history"])
                        state["staged"] = (
                            state["history"][remaining[-1]] if remaining else None
                        )
                    cond.notify_all()
                if dropped is not None:
                    dropped.delete()
                _emit({"event": "dropped", "step": cmd.get("step")})
            elif op == "stream":
                # Promotion/tagging: re-labels a resident version's stream
                # (routing metadata only — bytes, CRCs, and the era tag
                # are immutable).
                with cond:
                    resident = state["history"].get(int(cmd.get("step", -1)))
                    if resident is not None:
                        resident.stream = cmd.get("stream")
                    cond.notify_all()
                _emit({"event": "stream", "step": cmd.get("step")})
            elif op == "disallow":
                with cond:
                    doomed = list(state["history"].values())
                    state["history"].clear()
                    state["staged"] = None
                    cond.notify_all()
                for d in doomed:
                    d.delete()
                _emit({"event": "disallowed"})
            elif op == "shutdown":
                break
            else:
                logging.warning("unknown control cmd: %r", op)
    finally:
        with cond:
            doomed = list(state["history"].values())
            state["history"].clear()
            state["staged"] = None
            state["closing"] = True
            cond.notify_all()
        for d in doomed:
            d.delete()
        server.shutdown()
        server.server_close()
    return 0


# ---------------------------------------------------------------------------
# Donor-side handle.
# ---------------------------------------------------------------------------


class ServeChild:
    """Owns the serving child's lifecycle from the donor process.

    Not thread-safe for concurrent stage() calls (the manager stages from
    its single quorum thread); disallow()/shutdown()/the watcher may run
    from other threads and take the control lock.
    """

    def __init__(
        self,
        timeout: float = 60.0,
        on_error: Optional[Callable[[Exception], None]] = None,
        root_dir: Optional[str] = None,
        nice: Optional[int] = None,
        max_restarts: Optional[int] = None,
        ready_timeout: float = 20.0,
    ) -> None:
        self._timeout = timeout
        self._on_error = on_error
        self._nice = (
            nice
            if nice is not None
            else int(os.environ.get(ENV_SERVE_NICE, "10") or 0)
        )
        self._max_restarts = (
            max_restarts
            if max_restarts is not None
            else int(os.environ.get(ENV_SERVE_MAX_RESTARTS, "5") or 0)
        )
        self._ready_timeout = ready_timeout
        self._root = Path(
            tempfile.mkdtemp(prefix="tpuft-serve-", dir=root_dir or serve_dir_root())
        )
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._proc: Optional[subprocess.Popen] = None
        self._port: Optional[int] = None
        self._epoch = 0
        self._staged_epoch: Optional[int] = None
        self._closing = False
        self._restarts = 0
        self.crashes = 0
        try:
            self._spawn()
        except Exception:
            shutil.rmtree(self._root, ignore_errors=True)
            raise

    # -- lifecycle ---------------------------------------------------------

    def _spawn(self) -> None:
        self._ready.clear()
        proc = subprocess.Popen(
            [
                sys.executable,
                str(Path(__file__).resolve()),
                "--timeout",
                str(self._timeout),
                "--nice",
                str(self._nice),
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=None,  # child logs ride the donor's stderr
            text=True,
        )
        self._proc = proc
        watcher = threading.Thread(
            target=self._watch, args=(proc,), daemon=True, name="tpuft-serve-watch"
        )
        watcher.start()
        if not self._ready.wait(self._ready_timeout):
            proc.kill()
            raise ServeChildUnavailable(
                f"serving child not ready within {self._ready_timeout}s"
            )
        metrics.set_gauge("tpuft_heal_serve_child_up", 1)
        tracing.record(
            "serve_child_spawn", cat="serve_child",
            pid=proc.pid, port=self._port,
        )

    def _watch(self, proc: subprocess.Popen) -> None:
        try:
            assert proc.stdout is not None
            for line in proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if event.get("event") == "ready":
                    self._port = int(event["port"])
                    self._ready.set()
            rc = proc.wait()
            with self._lock:
                if self._closing or proc is not self._proc:
                    return
                self._staged_epoch = None
            self.crashes += 1
            metrics.inc("tpuft_heal_serve_child_crashes_total")
            metrics.set_gauge("tpuft_heal_serve_child_up", 0)
            tracing.record(
                "serve_child_crash", cat="serve_child", rc=rc, pid=proc.pid
            )
            crash = ServeChildCrashed(
                f"heal-serving child exited rc={rc} with a heal window "
                f"possibly open; joiners fail over via the resume cache"
            )
            cb = self._on_error
            if cb is not None:
                cb(crash)
            else:
                logger.warning("%s", crash)
            if self._restarts < self._max_restarts:
                self._restarts += 1
                metrics.inc("tpuft_heal_serve_child_restarts_total")
                tracing.record(
                    "serve_child_respawn", cat="serve_child",
                    restart=self._restarts,
                )
                self._spawn()
            else:
                tracing.record(
                    "serve_child_degraded", cat="serve_child",
                    restarts=self._restarts,
                )
        except Exception as e:  # noqa: BLE001 — watcher must not die silently
            logger.exception(f"serve-child watcher failed: {e}")

    def alive(self) -> bool:
        proc = self._proc
        return (
            proc is not None
            and proc.poll() is None
            and self._ready.is_set()
            and not self._closing
        )

    def address(self) -> str:
        return f"http://{socket.gethostname()}:{self._port}"

    @property
    def port(self) -> Optional[int]:
        return self._port

    def shutdown(self, wait: bool = True) -> None:
        with self._lock:
            self._closing = True
            proc = self._proc
        if proc is not None and proc.poll() is None:
            try:
                self._send({"cmd": "shutdown"})
                assert proc.stdin is not None
                proc.stdin.close()
            except OSError:
                pass
            try:
                proc.wait(timeout=5 if wait else 0.5)
            except subprocess.TimeoutExpired:
                proc.kill()
        metrics.set_gauge("tpuft_heal_serve_child_up", 0)
        shutil.rmtree(self._root, ignore_errors=True)

    # -- control -----------------------------------------------------------

    def _send(self, cmd: Dict[str, Any]) -> None:
        proc = self._proc
        if proc is None or proc.stdin is None:
            raise ServeChildUnavailable("no serving child process")
        with self._lock:
            proc.stdin.write(json.dumps(cmd) + "\n")
            proc.stdin.flush()

    def new_epoch_dir(self) -> Tuple[int, Path]:
        """Fresh directory for the next snapshot's chunk files."""
        self._epoch += 1
        path = self._root / f"epoch-{self._epoch:06d}"
        path.mkdir(parents=True, exist_ok=True)
        return self._epoch, path

    def stage(
        self,
        step: int,
        quorum_id: Optional[int],
        epoch: int,
        epoch_dir: Path,
        files: List[str],
        sizes: List[int],
        meta_bytes: bytes,
        crc_algo: str = "crc32",
        crcs: Optional[List[int]] = None,
        digest: Optional[str] = None,
        keep: int = 1,
        chunk_codecs: Optional[List[str]] = None,
    ) -> None:
        """Hands the snapshot to the child (which owns — and eventually
        deletes — the epoch directory from here on). ``crcs``/``digest``
        ride along in the clear (not only inside the pickled meta) so the
        jax-free child can answer ``/delta`` manifest diffs. ``keep`` is
        the child-side history-ring width: the newest ``keep`` staged
        steps stay resident as /dev/shm epoch dirs (pinned-version
        serving); 1 = the pre-history single-snapshot behavior."""
        if not self.alive():
            raise ServeChildUnavailable("serving child is not alive")
        try:
            self._send(
                {
                    "cmd": "stage",
                    "epoch": epoch,
                    "step": step,
                    "quorum_id": quorum_id,
                    "dir": str(epoch_dir),
                    "files": files,
                    "sizes": sizes,
                    "meta_b64": base64.b64encode(meta_bytes).decode(),
                    "crc_algo": crc_algo,
                    "crcs": crcs,
                    "digest": digest,
                    "keep": max(1, int(keep)),
                    "chunk_codecs": chunk_codecs,
                }
            )
        except OSError as e:
            raise ServeChildUnavailable(f"serving child pipe broken: {e}") from e
        self._staged_epoch = epoch

    def drop_staged(self, step: int) -> None:
        """Retraction: removes one resident version from the child's ring
        (its /dev/shm epoch dir is deleted) so a retracted published
        version can never be served again."""
        try:
            self._send({"cmd": "drop", "step": int(step)})
        except (OSError, ServeChildUnavailable):
            pass  # child death is the watcher's to report

    def mark_stream(self, step: int, stream: str) -> None:
        """Progressive delivery: tags (or, on promotion, re-labels) a
        resident version's stream so the in-child wrong-stream gate
        matches the donor's — policy enforcement holds at every seam."""
        self._send({"cmd": "stream", "step": int(step), "stream": str(stream)})

    def disallow(self) -> None:
        if self._staged_epoch is None:
            return
        self._staged_epoch = None
        try:
            self._send({"cmd": "disallow"})
        except (OSError, ServeChildUnavailable):
            pass  # child death is the watcher's to report

    def fetch_metrics_snapshot(self, timeout: float = 1.0) -> Optional[Dict[str, Any]]:
        """The child's /metrics.json snapshot (merged into the donor's
        scrape), or None when unreachable."""
        if not self.alive():
            return None
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://localhost:{self._port}/metrics.json", timeout=timeout
            ) as resp:
                return json.loads(resp.read().decode())
        except Exception:  # noqa: BLE001 — scrape merge is best-effort
            return None


if __name__ == "__main__":
    sys.exit(_child_main())
