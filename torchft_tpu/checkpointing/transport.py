"""CheckpointTransport ABC (reference: checkpointing/transport.py:14-68)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Callable, Generic, List, Optional, Set, TypeVar

T = TypeVar("T")

__all__ = ["CheckpointTransport", "HEAL_PART_PREFIX"]

# Heal-part naming convention: a dict key anywhere in a staged state dict
# that starts with this prefix marks its subtree as an independently
# addressable *part* — transports that support parts (HTTPTransport) stage
# each part as its own integrity-checked chunk and advertise a part ->
# chunk map in /meta, so a joiner can skip parts it can reconstruct more
# cheaply elsewhere (the ZeRO plane's shard-wise heal,
# torchft_tpu/zero.py). Transports without part support simply treat the
# keys as ordinary dict keys — the format degrades to a full fetch, never
# to a wrong one.
HEAL_PART_PREFIX = "heal_part:"


class CheckpointTransport(ABC, Generic[T]):
    """Live peer-to-peer state transfer used for healing joining replicas.

    The donor stages its state and serves it without pausing training; the
    joiner fetches and applies it before its first committed step.

    ``quorum_id`` (optional on both sides) tags the transfer with the
    quorum era it belongs to: transports that can carry it (HTTPTransport)
    fence a joiner from adopting a stale-era donor's state; transports
    that cannot simply ignore it.
    """

    @abstractmethod
    def metadata(self) -> str:
        """Transport metadata handed to peers via the manager (e.g. the
        donor's serving address)."""

    @abstractmethod
    def send_checkpoint(
        self,
        dst_ranks: List[int],
        step: int,
        state_dict: T,
        timeout: float,
        quorum_id: Optional[int] = None,
    ) -> Optional[dict]:
        """Stages/sends ``state_dict`` for ``dst_ranks`` at ``step``.

        May return a JSON-safe staging manifest (step/era/digest/per-chunk
        CRCs; HTTPTransport does) for the serving plane's publisher; heal
        callers ignore the return value and ``None`` is always a valid
        answer."""

    @abstractmethod
    def recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        quorum_id: Optional[int] = None,
        skip_parts: Optional[Set[str]] = None,
        donors: Optional[List[str]] = None,
        local_state: Optional[T] = None,
        stripe_rotation: int = 0,
        donor_info: Optional[dict] = None,
    ) -> T:
        """Fetches the state for ``step`` from ``src_rank``.

        ``skip_parts``: names of :data:`HEAL_PART_PREFIX` parts whose
        payloads the joiner does not need (it reconstructs them through a
        cheaper plane — e.g. the ZeRO re-balance exchange). A part-aware
        transport substitutes ``None`` for every leaf of a skipped part;
        transports without part support MUST ignore the argument and
        fetch everything — skipping is an optimization, never a
        correctness requirement.

        ``donors``: additional transport addresses serving the same
        committed state; a stripe-capable transport (HTTPTransport)
        partitions the fetch across them, others MUST ignore the
        argument and fetch from ``metadata`` alone.

        ``local_state``: the joiner's stale-but-recent state for delta
        rejoin; a delta-capable transport adopts provably identical
        pieces locally instead of fetching them, others MUST ignore it.
        Both are optimizations with the same contract as ``skip_parts``:
        degrading means a full single-donor fetch, never a wrong one.

        ``stripe_rotation``: the coordinated mass-rejoin-storm offset — a
        pure function the manager derives from (joiner ordinal, group
        rank, quorum id) so N simultaneous joiners seed their stripe
        plans at different donors. Stripe-capable transports fold it
        into their chunk partition; others MUST ignore it (it never
        changes WHAT is fetched, only the donor ordering).

        ``donor_info``: advisory per-donor identity map (donor URL ->
        {"replica_id", "region"}) from the manager's quorum view; a
        topology-aware transport uses it to key bandwidth estimates and
        label same- vs cross-region bytes, others MUST ignore it (it
        never changes what is fetched or verified)."""

    def disallow_checkpoint(self) -> None:
        """Stops serving the staged checkpoint (called at commit)."""

    def register_error_callback(self, cb: Callable[[Exception], None]) -> None:
        """Funnel for asynchronous serving-plane failures (e.g. a
        heal-serving sidecar crash). The manager registers
        ``report_error`` here; transports without background serving
        machinery have nothing to report and keep this default no-op."""

    def shutdown(self, wait: bool = True) -> None:
        """Tears the transport down."""
