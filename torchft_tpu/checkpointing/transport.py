"""CheckpointTransport ABC (reference: checkpointing/transport.py:14-68)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")

__all__ = ["CheckpointTransport"]


class CheckpointTransport(ABC, Generic[T]):
    """Live peer-to-peer state transfer used for healing joining replicas.

    The donor stages its state and serves it without pausing training; the
    joiner fetches and applies it before its first committed step.

    ``quorum_id`` (optional on both sides) tags the transfer with the
    quorum era it belongs to: transports that can carry it (HTTPTransport)
    fence a joiner from adopting a stale-era donor's state; transports
    that cannot simply ignore it.
    """

    @abstractmethod
    def metadata(self) -> str:
        """Transport metadata handed to peers via the manager (e.g. the
        donor's serving address)."""

    @abstractmethod
    def send_checkpoint(
        self,
        dst_ranks: List[int],
        step: int,
        state_dict: T,
        timeout: float,
        quorum_id: Optional[int] = None,
    ) -> None:
        """Stages/sends ``state_dict`` for ``dst_ranks`` at ``step``."""

    @abstractmethod
    def recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        quorum_id: Optional[int] = None,
    ) -> T:
        """Fetches the state for ``step`` from ``src_rank``."""

    def disallow_checkpoint(self) -> None:
        """Stops serving the staged checkpoint (called at commit)."""

    def register_error_callback(self, cb: Callable[[Exception], None]) -> None:
        """Funnel for asynchronous serving-plane failures (e.g. a
        heal-serving sidecar crash). The manager registers
        ``report_error`` here; transports without background serving
        machinery have nothing to report and keep this default no-op."""

    def shutdown(self, wait: bool = True) -> None:
        """Tears the transport down."""
