"""Python surface of the tpuft coordination plane.

Servers (``LighthouseServer``, ``ManagerServer``) are the native C++
implementations embedded via ctypes — the reference embeds its Rust servers the
same way via pyo3 (/root/reference/src/lib.rs:80-144, :593-668). Clients
(``ManagerClient``, ``LighthouseClient``) are pure Python speaking the framed
protobuf-over-TCP protocol (native/src/rpc.h) — the "low level API" surface of
the reference (/root/reference/torchft/coordination.py, _torchft.pyi).

All timeouts are float seconds.
"""

from __future__ import annotations

import ctypes
import socket
import struct
import time
from dataclasses import dataclass, field
from typing import List, Optional

from torchft_tpu import _native
from torchft_tpu.proto import tpuft_pb2

__all__ = [
    "LighthouseServer",
    "ManagerServer",
    "LighthouseClient",
    "ManagerClient",
    "QuorumResult",
    "Quorum",
    "QuorumMember",
]

# Wire method ids — must match native/src/rpc.h.
LIGHTHOUSE_QUORUM = 1
LIGHTHOUSE_HEARTBEAT = 2
LIGHTHOUSE_STATUS = 3
LIGHTHOUSE_KILL_REPLICA = 4
MANAGER_QUORUM = 16
MANAGER_CHECKPOINT_METADATA = 17
MANAGER_SHOULD_COMMIT = 18
MANAGER_KILL = 19

_STATUS_OK = 0
_STATUS_ERROR = 1
_STATUS_TIMEOUT = 2
_STATUS_BAD_METHOD = 3
_STATUS_NOT_FOUND = 4

_REQ_MAGIC = ord("T")
_RESP_MAGIC = ord("R")


class _FramedClient:
    """Persistent-connection framed-RPC client (one in-flight call)."""

    def __init__(self, addr: str, connect_timeout: float = 10.0) -> None:
        self._addr = addr
        self._connect_timeout = connect_timeout
        self._sock: Optional[socket.socket] = None

    @property
    def addr(self) -> str:
        return self._addr

    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        host, _, port = self._addr.rpartition(":")
        host = host.strip("[]")
        sock = socket.create_connection((host, int(port)), timeout=self._connect_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        return sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _recv_exact(self, sock: socket.socket, n: int, deadline: float) -> bytes:
        chunks = []
        remaining = n
        while remaining > 0:
            sock.settimeout(max(0.001, deadline - time.monotonic()))
            chunk = sock.recv(remaining)
            if not chunk:
                raise ConnectionError(f"connection closed by {self._addr}")
            chunks.append(chunk)
            remaining -= len(chunk)
        return b"".join(chunks)

    def call(self, method: int, payload: bytes, timeout: float) -> bytes:
        """One RPC round trip; raises TimeoutError / RuntimeError on failure."""
        deadline = time.monotonic() + timeout
        try:
            sock = self._connect()
            frame = struct.pack("!BBI", _REQ_MAGIC, method, len(payload)) + payload
            sock.settimeout(max(0.001, deadline - time.monotonic()))
            sock.sendall(frame)
        except (OSError, ConnectionError) as e:
            # Stale connection (e.g. server restart): redial once.
            self.close()
            try:
                sock = self._connect()
                sock.settimeout(max(0.001, deadline - time.monotonic()))
                sock.sendall(
                    struct.pack("!BBI", _REQ_MAGIC, method, len(payload)) + payload
                )
            except socket.timeout as e2:
                self.close()
                raise TimeoutError(f"send to {self._addr} timed out") from e2
            except (OSError, ConnectionError) as e2:
                self.close()
                raise RuntimeError(f"connect to {self._addr} failed: {e2}") from e
        try:
            header = self._recv_exact(sock, 6, deadline)
            magic, status, length = struct.unpack("!BBI", header)
            if magic != _RESP_MAGIC:
                raise ConnectionError("bad response magic")
            body = self._recv_exact(sock, length, deadline) if length else b""
        except socket.timeout as e:
            self.close()
            raise TimeoutError(f"rpc to {self._addr} timed out after {timeout}s") from e
        except (OSError, ConnectionError) as e:
            self.close()
            raise RuntimeError(f"rpc to {self._addr} failed: {e}") from e

        if status == _STATUS_OK:
            return body
        message = body.decode(errors="replace")
        if status == _STATUS_TIMEOUT:
            raise TimeoutError(message)
        if status == _STATUS_NOT_FOUND:
            raise LookupError(message)
        raise RuntimeError(message)


# ---------------------------------------------------------------------------
# Data classes mirroring the reference's pyo3 data surface (lib.rs:283-424).
# ---------------------------------------------------------------------------


@dataclass
class QuorumMember:
    replica_id: str
    address: str = ""
    store_address: str = ""
    step: int = 0
    world_size: int = 1
    shrink_only: bool = False
    commit_failures: int = 0
    data: str = ""

    @classmethod
    def _from_proto(cls, proto: tpuft_pb2.QuorumMember) -> "QuorumMember":
        return cls(
            replica_id=proto.replica_id,
            address=proto.address,
            store_address=proto.store_address,
            step=proto.step,
            world_size=proto.world_size,
            shrink_only=proto.shrink_only,
            commit_failures=proto.commit_failures,
            data=proto.data,
        )

    def _to_proto(self) -> tpuft_pb2.QuorumMember:
        return tpuft_pb2.QuorumMember(
            replica_id=self.replica_id,
            address=self.address,
            store_address=self.store_address,
            step=self.step,
            world_size=self.world_size,
            shrink_only=self.shrink_only,
            commit_failures=self.commit_failures,
            data=self.data,
        )


@dataclass
class Quorum:
    quorum_id: int
    participants: List[QuorumMember]
    created_unix_nanos: int = 0

    @classmethod
    def _from_proto(cls, proto: tpuft_pb2.Quorum) -> "Quorum":
        return cls(
            quorum_id=proto.quorum_id,
            participants=[QuorumMember._from_proto(p) for p in proto.participants],
            created_unix_nanos=proto.created.unix_nanos,
        )


@dataclass
class QuorumResult:
    """Per-rank recovery plan (reference: lib.rs:283-316)."""

    quorum_id: int = 0
    replica_rank: int = 0
    replica_world_size: int = 1
    recover_src_manager_address: str = ""
    recover_src_replica_rank: Optional[int] = None
    recover_dst_replica_ranks: List[int] = field(default_factory=list)
    store_address: str = ""
    max_step: int = 0
    max_rank: Optional[int] = None
    max_world_size: int = 1
    heal: bool = False
    commit_failures: int = 0
    quorum: Optional[Quorum] = None

    @classmethod
    def _from_proto(cls, resp: tpuft_pb2.ManagerQuorumResponse) -> "QuorumResult":
        return cls(
            quorum_id=resp.quorum_id,
            replica_rank=resp.replica_rank,
            replica_world_size=resp.replica_world_size,
            recover_src_manager_address=(
                resp.recover_src_manager_address
                if resp.HasField("recover_src_manager_address")
                else ""
            ),
            recover_src_replica_rank=(
                resp.recover_src_replica_rank
                if resp.HasField("recover_src_replica_rank")
                else None
            ),
            recover_dst_replica_ranks=list(resp.recover_dst_replica_ranks),
            store_address=resp.store_address,
            max_step=resp.max_step,
            max_rank=(
                resp.max_replica_rank if resp.HasField("max_replica_rank") else None
            ),
            max_world_size=resp.max_world_size,
            heal=resp.heal,
            commit_failures=resp.commit_failures,
            quorum=Quorum._from_proto(resp.quorum) if resp.HasField("quorum") else None,
        )


# ---------------------------------------------------------------------------
# Servers (native, via ctypes)
# ---------------------------------------------------------------------------


class LighthouseServer:
    """Embedded native Lighthouse (reference: lib.rs:593-668).

    Defaults follow the reference's embedded test server: short join timeout so
    in-process clusters converge fast.
    """

    def __init__(
        self,
        bind: str = "[::]:0",
        min_replicas: int = 1,
        join_timeout_ms: int = 100,
        quorum_tick_ms: int = 100,
        heartbeat_timeout_ms: int = 5000,
    ) -> None:
        lib = _native.load()
        self._lib = lib
        self._handle = lib.tpuft_lighthouse_new(
            bind.encode(),
            min_replicas,
            join_timeout_ms,
            quorum_tick_ms,
            heartbeat_timeout_ms,
        )
        if not self._handle:
            raise RuntimeError(f"failed to start lighthouse: {_native.last_error()}")

    def address(self) -> str:
        buf = ctypes.create_string_buffer(512)
        self._lib.tpuft_lighthouse_address(self._handle, buf, len(buf))
        return buf.value.decode()

    def shutdown(self) -> None:
        if self._handle:
            self._lib.tpuft_lighthouse_shutdown(self._handle)
            self._lib.tpuft_lighthouse_free(self._handle)
            self._handle = None

    def __del__(self) -> None:
        try:
            self.shutdown()
        except Exception:
            pass


class ManagerServer:
    """Embedded native per-replica-group manager (reference: lib.rs:80-144)."""

    def __init__(
        self,
        replica_id: str,
        lighthouse_addr: str,
        address: str = "",
        bind: str = "[::]:0",
        store_addr: str = "",
        world_size: int = 1,
        heartbeat_interval: float = 0.1,
        connect_timeout: float = 10.0,
        quorum_retries: int = 0,
        exit_on_kill: bool = True,
    ) -> None:
        lib = _native.load()
        self._lib = lib
        self._handle = lib.tpuft_manager_new(
            replica_id.encode(),
            lighthouse_addr.encode(),
            address.encode(),
            bind.encode(),
            store_addr.encode(),
            world_size,
            int(heartbeat_interval * 1000),
            int(connect_timeout * 1000),
            quorum_retries,
            1 if exit_on_kill else 0,
        )
        if not self._handle:
            raise RuntimeError(f"failed to start manager server: {_native.last_error()}")

    def address(self) -> str:
        buf = ctypes.create_string_buffer(512)
        self._lib.tpuft_manager_address(self._handle, buf, len(buf))
        return buf.value.decode()

    def shutdown(self) -> None:
        if self._handle:
            self._lib.tpuft_manager_shutdown(self._handle)
            self._lib.tpuft_manager_free(self._handle)
            self._handle = None

    def __del__(self) -> None:
        try:
            self.shutdown()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Clients (pure Python)
# ---------------------------------------------------------------------------


class LighthouseClient:
    """Direct lighthouse access (reference: lib.rs:476-591)."""

    def __init__(self, addr: str, connect_timeout: float = 10.0) -> None:
        self._client = _FramedClient(addr, connect_timeout)

    def quorum(self, requester: QuorumMember, timeout: float = 60.0) -> Quorum:
        req = tpuft_pb2.LighthouseQuorumRequest(
            requester=requester._to_proto(), timeout_ms=int(timeout * 1000)
        )
        body = self._client.call(
            LIGHTHOUSE_QUORUM, req.SerializeToString(), timeout + 5.0
        )
        resp = tpuft_pb2.LighthouseQuorumResponse()
        resp.ParseFromString(body)
        return Quorum._from_proto(resp.quorum)

    def heartbeat(self, replica_id: str, timeout: float = 5.0) -> None:
        req = tpuft_pb2.LighthouseHeartbeatRequest(replica_id=replica_id)
        self._client.call(LIGHTHOUSE_HEARTBEAT, req.SerializeToString(), timeout)

    def status(self, timeout: float = 5.0) -> tpuft_pb2.LighthouseStatusResponse:
        req = tpuft_pb2.LighthouseStatusRequest()
        body = self._client.call(LIGHTHOUSE_STATUS, req.SerializeToString(), timeout)
        resp = tpuft_pb2.LighthouseStatusResponse()
        resp.ParseFromString(body)
        return resp

    def kill(self, replica_id: str, timeout: float = 10.0, mode: str = "exit") -> None:
        """Injects a fault into ``replica_id``'s manager. Modes (reference
        failure menu, examples/monarch/utils/failure.py:25-100): "exit"
        (process death), "segfault" (crash with core), "deadlock"
        (coordination wedges while heartbeats continue), "partition"
        (heartbeats and RPC serving stop, as if the host dropped off the
        network)."""
        req = tpuft_pb2.KillRequest(replica_id=replica_id, mode=mode)
        self._client.call(LIGHTHOUSE_KILL_REPLICA, req.SerializeToString(), timeout)

    def close(self) -> None:
        self._client.close()


class ManagerClient:
    """Client of a (possibly remote) ManagerServer (reference: lib.rs:146-281)."""

    def __init__(self, addr: str, connect_timeout: float = 10.0) -> None:
        self._client = _FramedClient(addr, connect_timeout)

    @property
    def addr(self) -> str:
        return self._client.addr

    def _quorum(
        self,
        group_rank: int,
        step: int,
        checkpoint_metadata: str,
        shrink_only: bool,
        init_sync: bool,
        commit_failures: int,
        timeout: float,
    ) -> QuorumResult:
        req = tpuft_pb2.ManagerQuorumRequest(
            group_rank=group_rank,
            step=step,
            checkpoint_metadata=checkpoint_metadata,
            shrink_only=shrink_only,
            init_sync=init_sync,
            commit_failures=commit_failures,
            timeout_ms=int(timeout * 1000),
        )
        body = self._client.call(MANAGER_QUORUM, req.SerializeToString(), timeout + 5.0)
        resp = tpuft_pb2.ManagerQuorumResponse()
        resp.ParseFromString(body)
        return QuorumResult._from_proto(resp)

    def _checkpoint_metadata(self, rank: int, timeout: float) -> str:
        req = tpuft_pb2.CheckpointMetadataRequest(
            group_rank=rank, timeout_ms=int(timeout * 1000)
        )
        body = self._client.call(
            MANAGER_CHECKPOINT_METADATA, req.SerializeToString(), timeout
        )
        resp = tpuft_pb2.CheckpointMetadataResponse()
        resp.ParseFromString(body)
        return resp.checkpoint_metadata

    def should_commit(
        self, group_rank: int, step: int, should_commit: bool, timeout: float
    ) -> bool:
        req = tpuft_pb2.ShouldCommitRequest(
            group_rank=group_rank,
            step=step,
            should_commit=should_commit,
            timeout_ms=int(timeout * 1000),
        )
        body = self._client.call(
            MANAGER_SHOULD_COMMIT, req.SerializeToString(), timeout + 5.0
        )
        resp = tpuft_pb2.ShouldCommitResponse()
        resp.ParseFromString(body)
        return resp.should_commit

    def close(self) -> None:
        self._client.close()


# ---------------------------------------------------------------------------
# Pure-function test hooks (differential testing of the native quorum logic)
# ---------------------------------------------------------------------------


@dataclass
class SimParticipant:
    """One replica's standing for :func:`quorum_compute_sim`: ``member`` plus
    how long before "now" it joined (requested quorum) and last heartbeat.
    ``heartbeat_only`` models a replica that heartbeats without having
    requested quorum (it counts toward the split-brain denominator and the
    join-timeout wait, like the reference's heartbeats map)."""

    member: QuorumMember
    joined_age_ms: int = 0
    heartbeat_age_ms: int = 0
    heartbeat_only: bool = False


def quorum_compute_sim(
    participants: List[SimParticipant],
    prev_quorum: Optional[Quorum] = None,
    min_replicas: int = 1,
    join_timeout_ms: int = 60000,
    heartbeat_timeout_ms: int = 5000,
) -> tuple[Optional[List[QuorumMember]], str]:
    """Drives the native ``quorum_compute`` (native/src/quorum.cc, contract of
    reference lighthouse.rs:141-269) as a pure function. Returns
    ``(members or None, reason)``."""
    req = tpuft_pb2.QuorumSimRequest(
        min_replicas=min_replicas,
        join_timeout_ms=join_timeout_ms,
        heartbeat_timeout_ms=heartbeat_timeout_ms,
    )
    for p in participants:
        sim = req.participants.add()
        sim.member.CopyFrom(p.member._to_proto())
        sim.joined_age_ms = p.joined_age_ms
        sim.heartbeat_age_ms = p.heartbeat_age_ms
        sim.heartbeat_only = p.heartbeat_only
    if prev_quorum is not None:
        req.prev_quorum.quorum_id = prev_quorum.quorum_id
        for m in prev_quorum.participants:
            req.prev_quorum.participants.add().CopyFrom(m._to_proto())

    lib = _native.load()
    if not _native.has_sim_hooks():
        raise RuntimeError(
            "libtpuft.so is stale (no quorum sim hooks) — rebuild native/build"
        )
    payload = req.SerializeToString()
    out = ctypes.create_string_buffer(max(len(payload) * 2, 1 << 16))
    n = lib.tpuft_quorum_compute(payload, len(payload), out, len(out))
    if n < 0:
        raise RuntimeError(_native.last_error())
    resp = tpuft_pb2.QuorumSimResponse()
    resp.ParseFromString(out.raw[:n])
    if not resp.has_quorum:
        return None, resp.reason
    return [QuorumMember._from_proto(m) for m in resp.participants], resp.reason


def compute_quorum_results_sim(
    replica_id: str,
    group_rank: int,
    quorum: Quorum,
    init_sync: bool = True,
) -> QuorumResult:
    """Drives the native ``compute_quorum_results`` (native/src/quorum.cc,
    contract of reference manager.rs:489-624) as a pure function. Raises
    ``RuntimeError`` when the replica is not in the quorum."""
    q = tpuft_pb2.Quorum(quorum_id=quorum.quorum_id)
    for m in quorum.participants:
        q.participants.add().CopyFrom(m._to_proto())
    lib = _native.load()
    if not _native.has_sim_hooks():
        raise RuntimeError(
            "libtpuft.so is stale (no quorum sim hooks) — rebuild native/build"
        )
    payload = q.SerializeToString()
    out = ctypes.create_string_buffer(max(len(payload) * 2, 1 << 16))
    n = lib.tpuft_compute_quorum_results(
        replica_id.encode(), group_rank, payload, len(payload),
        1 if init_sync else 0, out, len(out),
    )
    if n < 0:
        raise RuntimeError(_native.last_error())
    resp = tpuft_pb2.ManagerQuorumResponse()
    resp.ParseFromString(out.raw[:n])
    return QuorumResult._from_proto(resp)
