"""Data sharding across replica groups.

Role-equivalent of the reference's ``DistributedSampler``
(/root/reference/torchft/data.py:24-77): shards a dataset over
``num_replica_groups x num_replicas`` workers by computing a global rank
``group_rank + num_replicas * replica_rank``. As in the reference, this is
a best-effort scheme — on membership change the dataset offsets shift, so
some samples may repeat or be skipped (documented lossiness, data.py:33-37);
exact accounting belongs to a stateful loader checkpointed per replica.
"""

from __future__ import annotations

import queue
import threading
import weakref
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

__all__ = ["DistributedSampler", "DevicePrefetcher"]


class DistributedSampler:
    """Deterministic, shardable index sampler.

    Args:
        dataset_size: total examples.
        replica_rank: which replica group this worker belongs to.
        num_replica_groups: total replica groups in the job.
        group_rank: this worker's rank within its replica group.
        num_replicas: workers per replica group.
        shuffle: permute indices per epoch (seeded by epoch for determinism).
        seed: base RNG seed shared by all workers.
    """

    def __init__(
        self,
        dataset_size: int,
        replica_rank: int,
        num_replica_groups: int,
        group_rank: int = 0,
        num_replicas: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        batch_size: Optional[int] = None,
    ) -> None:
        self.dataset_size = dataset_size
        self.global_rank = group_rank + num_replicas * replica_rank
        self.global_world_size = num_replicas * num_replica_groups
        self.shuffle = shuffle
        self.seed = seed
        self.batch_size = batch_size
        self.epoch = 0
        self.num_samples = dataset_size // self.global_world_size

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.num_samples

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            order = rng.permutation(self.dataset_size)
        else:
            order = np.arange(self.dataset_size)
        shard = order[self.global_rank :: self.global_world_size][: self.num_samples]
        return iter(shard.tolist())

    def state_dict(self) -> dict:
        """Per-replica loader state for user checkpoints (the reference
        delegates this to torchdata's StatefulDataLoader; position within an
        epoch is intentionally not tracked — resume restarts the epoch,
        consistent with the documented lossiness under membership change)."""
        return {"epoch": self.epoch, "seed": self.seed}

    def load_state_dict(self, state: dict) -> None:
        self.epoch = int(state["epoch"])
        self.seed = int(state["seed"])

    def batches(self) -> Iterator[np.ndarray]:
        """Yields index batches of ``batch_size`` (requires batch_size)."""
        assert self.batch_size is not None, "batch_size not set"
        batch = []
        for index in self:
            batch.append(index)
            if len(batch) == self.batch_size:
                yield np.array(batch)
                batch = []


class DevicePrefetcher:
    """Double-buffered host→device input pipeline.

    Wraps any iterator of host batches (arrays or pytrees) and keeps up to
    ``depth`` batches transferred ahead on a background thread, so the h2d
    copy for step N+1 overlaps step N's compute — the standard TPU input
    lever (the reference's role-equivalent is torch DataLoader's
    pin_memory + non_blocking H2D prefetch, which torchft inherits from
    upstream rather than implementing). ``sharding`` (any
    ``jax.sharding.Sharding`` or a pytree of them matching the batch
    structure) places each batch directly, e.g. ``NamedSharding(mesh,
    P('dp', None))`` for data-parallel inputs.

    Iteration order is preserved; an exception in the source iterator or
    the transfer re-raises at the consuming ``__next__``. ``close()``
    (also called on exhaustion and by ``with``) stops the worker; a
    blocked worker is released by draining. An *abandoned* prefetcher
    (consumer drops its reference without closing) is also cleaned up:
    the worker thread shares only a ``_PrefetchState`` holder — never the
    prefetcher itself — so garbage collection triggers a
    ``weakref.finalize`` that closes the state, releasing the worker and
    the queued device batches.
    """

    def __init__(
        self,
        source: Iterable[Any],
        depth: int = 2,
        sharding: Optional[Any] = None,
        device_put: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        import jax

        if device_put is None:
            # jax.device_put broadcasts a single sharding over a batch
            # pytree and also accepts a matching pytree of shardings.
            if sharding is not None:
                device_put = lambda batch: jax.device_put(batch, sharding)
            else:
                device_put = jax.device_put
        self._state = _PrefetchState(depth)
        self._thread = threading.Thread(
            target=_prefetch_worker,
            args=(self._state, iter(source), device_put),
            daemon=True,
        )
        self._thread.start()
        self._finalizer = weakref.finalize(self, self._state.close)

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self) -> Any:
        state = self._state
        if state.closed:
            raise StopIteration
        item = state.q.get()
        if item is _PREFETCH_DONE:
            state.closed = True
            if state.err is not None:
                raise state.err
            raise StopIteration
        return item

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def close(self) -> None:
        self._finalizer()  # idempotent: closes + drains the shared state
        self._thread.join(timeout=5)


_PREFETCH_DONE = object()


class _PrefetchState:
    """Queue + flags shared between a prefetcher and its worker thread.

    Deliberately does NOT reference the ``DevicePrefetcher``: the worker
    holding only this object lets an abandoned prefetcher be collected,
    firing its finalizer (→ ``close``) so the worker exits instead of
    polling forever with ``depth`` device batches pinned.
    """

    def __init__(self, depth: int) -> None:
        self.q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self.err: Optional[BaseException] = None
        self.closed = False

    def enqueue(self, item: Any) -> bool:
        """Blocking put that gives up when the consumer closed (False) —
        dropping ``item`` rather than pinning a device batch in the dead
        queue."""
        while not self.closed:
            try:
                self.q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def close(self) -> None:
        self.closed = True
        # Release a worker blocked on a full queue.
        try:
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass


def _prefetch_worker(
    state: _PrefetchState, it: Iterator[Any], put: Callable[[Any], Any]
) -> None:
    try:
        for batch in it:
            if not state.enqueue(put(batch)):
                return
    except BaseException as e:  # noqa: BLE001 — re-raised at __next__
        state.err = e
    finally:
        if not state.enqueue(_PREFETCH_DONE):
            # Closed consumer no longer waits on get(); best-effort
            # only — the sentinel is tiny, unlike a device batch.
            try:
                state.q.put_nowait(_PREFETCH_DONE)
            except queue.Full:
                pass
