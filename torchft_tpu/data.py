"""Data sharding across replica groups.

Role-equivalent of the reference's ``DistributedSampler``
(/root/reference/torchft/data.py:24-77): shards a dataset over
``num_replica_groups x num_replicas`` workers by computing a global rank
``group_rank + num_replicas * replica_rank``. As in the reference, this is
a best-effort scheme — on membership change the dataset offsets shift, so
some samples may repeat or be skipped (documented lossiness, data.py:33-37);
exact accounting belongs to a stateful loader checkpointed per replica.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

__all__ = ["DistributedSampler"]


class DistributedSampler:
    """Deterministic, shardable index sampler.

    Args:
        dataset_size: total examples.
        replica_rank: which replica group this worker belongs to.
        num_replica_groups: total replica groups in the job.
        group_rank: this worker's rank within its replica group.
        num_replicas: workers per replica group.
        shuffle: permute indices per epoch (seeded by epoch for determinism).
        seed: base RNG seed shared by all workers.
    """

    def __init__(
        self,
        dataset_size: int,
        replica_rank: int,
        num_replica_groups: int,
        group_rank: int = 0,
        num_replicas: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        batch_size: Optional[int] = None,
    ) -> None:
        self.dataset_size = dataset_size
        self.global_rank = group_rank + num_replicas * replica_rank
        self.global_world_size = num_replicas * num_replica_groups
        self.shuffle = shuffle
        self.seed = seed
        self.batch_size = batch_size
        self.epoch = 0
        self.num_samples = dataset_size // self.global_world_size

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.num_samples

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            order = rng.permutation(self.dataset_size)
        else:
            order = np.arange(self.dataset_size)
        shard = order[self.global_rank :: self.global_world_size][: self.num_samples]
        return iter(shard.tolist())

    def state_dict(self) -> dict:
        """Per-replica loader state for user checkpoints (the reference
        delegates this to torchdata's StatefulDataLoader; position within an
        epoch is intentionally not tracked — resume restarts the epoch,
        consistent with the documented lossiness under membership change)."""
        return {"epoch": self.epoch, "seed": self.seed}

    def load_state_dict(self, state: dict) -> None:
        self.epoch = int(state["epoch"])
        self.seed = int(state["seed"])

    def batches(self) -> Iterator[np.ndarray]:
        """Yields index batches of ``batch_size`` (requires batch_size)."""
        assert self.batch_size is not None, "batch_size not set"
        batch = []
        for index in self:
            batch.append(index)
            if len(batch) == self.batch_size:
                yield np.array(batch)
                batch = []
