"""Fault-tolerant data parallelism over the replica axis.

The reference's ``DistributedDataParallel`` (/root/reference/torchft/ddp.py:
32-79) hooks torch's backward to route gradient buckets through
``manager.allreduce``. In JAX gradients are explicit pytrees, so the
equivalent surface is a gradient-sync transform applied between ``grad_fn``
and the optimizer:

- :func:`ft_allreduce_gradients` — bucketed sync of the whole gradient pytree
  (one flat wire message; the analogue of DDP's frozen buckets). The flatten
  order of a pytree is deterministic across replicas for identical models,
  which is the invariant DDP's bucket-freezing trick protects.
- :class:`DistributedDataParallel` — module wrapper carrying the manager;
  forwards apply the wrapped flax module.
- :class:`PureDistributedDataParallel` — per-parameter allreduce works
  (reference ddp.py:82-105), more overlap-friendly for giant leaves.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, List

import jax
import numpy as np

from torchft_tpu import health, metrics, tracing
from torchft_tpu.manager import Manager
from torchft_tpu.utils.transfer import prefetch_to_host
from torchft_tpu.work import Work

# One FIFO wire worker per Manager (see _wire_worker_for).
_WIRE_WORKERS: "weakref.WeakKeyDictionary[Manager, Any]" = weakref.WeakKeyDictionary()
_WIRE_WORKERS_LOCK = threading.Lock()

__all__ = [
    "ft_allreduce_gradients",
    "prefetch_gradients",
    "DistributedDataParallel",
    "PureDistributedDataParallel",
]


def prefetch_gradients(grads: Any) -> None:
    """Starts the async device→host copy of every float array leaf of a
    gradient pytree without blocking — the staging half of the bucket
    schedule, exposed so the pipelined-commit step can launch it for the
    NEXT step's gradients before the previous step's vote has even
    resolved. By the time :func:`ft_allreduce_gradients` runs for real,
    its per-bucket ``np.asarray`` calls drain copies already in flight
    instead of starting them cold."""
    prefetch_to_host(
        [
            leaf
            for leaf in jax.tree_util.tree_leaves(grads)
            if isinstance(leaf, jax.Array)
        ]
    )


def _single_participant_identity(manager: Manager) -> bool:
    """True when the allreduce would be an exact identity (see
    Manager.is_lone_replica — sole participant AND a wire group of one).
    Skipping the stage/wire round trip makes single-group FT overhead just
    the quorum + commit RPCs — the reference's 'FT for free' design point."""
    if manager.errored() is not None:
        return False
    manager.wait_quorum()
    return manager.is_lone_replica()


BUCKET_BYTES_ENV = "TPUFT_BUCKET_MB"
_DEFAULT_BUCKET_BYTES = 16 * 1024 * 1024


def _bucket_cap_bytes() -> int:
    import os

    return int(float(os.environ.get(BUCKET_BYTES_ENV, "0")) * 1024 * 1024) or (
        _DEFAULT_BUCKET_BYTES
    )


def _plan_buckets(leaves: List[Any], cap_bytes: int) -> List[List[int]]:
    """Greedy same-dtype buckets of at most ``cap_bytes`` each, in flatten
    order (deterministic across replicas — DDP's frozen-bucket invariant)."""
    buckets: List[List[int]] = []
    open_bucket: dict = {}  # dtype -> (bucket index, bytes so far)
    for index, leaf in enumerate(leaves):
        dtype = np.asarray(leaf).dtype if not hasattr(leaf, "dtype") else leaf.dtype
        nbytes = int(np.prod(leaf.shape)) * np.dtype(dtype).itemsize if hasattr(leaf, "shape") else leaf.nbytes
        slot = open_bucket.get(dtype)
        if slot is not None and slot[1] + nbytes <= cap_bytes:
            buckets[slot[0]].append(index)
            open_bucket[dtype] = (slot[0], slot[1] + nbytes)
        else:
            buckets.append([index])
            open_bucket[dtype] = (len(buckets) - 1, nbytes)
    return buckets


def ft_allreduce_gradients(
    manager: Manager, grads: Any, should_quantize: bool = False
) -> Any:
    """Averages a gradient pytree across replica groups; returns jax arrays
    on the devices of the inputs. On error the step is poisoned (the commit
    will fail) and the *local* gradients come back — callers never branch.

    The sync is a **pipelined bucket schedule** (the analogue of the
    reference's overlapped per-bucket DDP comm hook, ddp.py:67-79): every
    leaf's device→host copy starts asynchronously up front, then buckets of
    at most ``TPUFT_BUCKET_MB`` are enqueued on the wire as their copies
    land — bucket k rides the network while bucket k+1 is still copying out
    and bucket k−1's averaged result is already copying back in. Nothing
    waits for the whole gradient set at once.

    With ``should_quantize``, gradients are fp8-quantized **on device**
    (Pallas on TPU) so only payload + block scales cross the host boundary
    (~4x less traffic than f32) and dequantization happens on device too.
    """
    if _single_participant_identity(manager):
        return grads
    if should_quantize:
        return _ft_allreduce_gradients_fp8(manager, grads)

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    # NOTE: the bucket planning below intentionally stays separate from
    # manager.allreduce_pytree's single-shot bucketing — this path's
    # contract is pipelined per-bucket works + device-sharding restore,
    # that one's is one wire message resolving to numpy. Non-float or
    # non-array leaves (python scalars have neither shape nor nbytes) take
    # the whole-tree path, which np.asarray's everything.
    if any(
        not hasattr(leaf, "shape")
        or np.dtype(getattr(leaf, "dtype", None) or np.asarray(leaf).dtype).kind
        not in ("f", "V")
        for leaf in leaves
    ):
        averaged = manager.allreduce_pytree(grads).wait()
        return jax.tree_util.tree_map(
            lambda avg, orig: jax.device_put(avg, orig.sharding)
            if isinstance(orig, jax.Array)
            else avg,
            averaged,
            grads,
        )

    # Stage 1: launch all d2h copies without blocking.
    prefetch_to_host(leaves)

    # Stage 2: enqueue one wire collective per bucket. np.asarray completes
    # the (already in-flight) copy for that bucket only; the PG op worker
    # starts bucket 0 on the wire while later buckets are still landing.
    buckets = _plan_buckets(leaves, _bucket_cap_bytes())
    works: List[Work] = []
    for members in buckets:
        if len(members) == 1:
            flat = np.asarray(leaves[members[0]]).reshape(-1)
        else:
            flat = np.concatenate(
                [np.asarray(leaves[i]).reshape(-1) for i in members]
            )
        metrics.inc("tpuft_wire_bytes_total", flat.nbytes, path="bucket")
        works.append(manager.allreduce(flat))

    # Stage 3: consume buckets in completion order; each averaged bucket's
    # host→device transfer dispatches (async) while later buckets are still
    # on the wire. The per-bucket wait below is the OBSERVED wire time —
    # later buckets' waits overlap earlier returns, so the histogram reads
    # as "time this bucket held the step up", not raw link occupancy.
    out: List[Any] = [None] * len(leaves)
    journal = getattr(manager, "_trace", None) or tracing.current()
    for bucket_index, (members, work) in enumerate(zip(buckets, works)):
        wire_t0 = time.perf_counter()
        # Gray-failure chaos seam: a punisher-armed drip_wire installs a
        # persistent per-replica per-bucket stall here — a dripping NIC,
        # visible in the wire_bucket histogram and the health scorer.
        health.injected_stall("wire")
        flat = np.asarray(work.wait())
        wire_dt = time.perf_counter() - wire_t0
        metrics.observe("tpuft_wire_bucket_seconds", wire_dt, path="bucket")
        journal.record(
            "wire_bucket", ph="X", dur=wire_dt,
            bucket=bucket_index, bytes=int(flat.nbytes), path="bucket",
        )
        offset = 0
        for i in members:
            orig = leaves[i]
            size = int(np.prod(orig.shape)) if hasattr(orig, "shape") else orig.size
            chunk = flat[offset : offset + size].reshape(orig.shape)
            offset += size
            out[i] = (
                jax.device_put(chunk, orig.sharding)
                if isinstance(orig, jax.Array)
                else chunk.copy()
            )
    return jax.tree_util.tree_unflatten(treedef, out)


# One jitted (quantize, dequantize) codec per bucket leaf-set + wire format.
_FP8_CODECS: dict = {}


def _bucket_codec(bucket_leaves: List[Any], wire: str):
    from torchft_tpu.ops.quantization import make_tree_fp8_codec

    key = (
        wire,
        tuple((leaf.shape, str(leaf.dtype)) for leaf in bucket_leaves),
    )
    codec = _FP8_CODECS.get(key)
    if codec is None:
        # Pass the wire captured in the key: a second env read inside the
        # codec could race a concurrent flip and cache a mismatched codec.
        codec = make_tree_fp8_codec(bucket_leaves, wire=wire)
        _FP8_CODECS[key] = codec
    return codec


def _wire_worker_for(manager: Manager):
    """The single FIFO wire worker for one Manager (= one replica group).

    One worker per GROUP, not per process: threads-as-replicas tests run
    several replica groups in one process, and a shared worker would
    serialize group A's exchange ahead of group B's while A's collective
    cannot complete until B reaches it — deadlock. One worker per group,
    not per CALL: the old per-call executor added thread create/destroy
    churn to every training step (round-2 advisor). Torn down by
    Manager.shutdown (a retired manager held by a fixture list must not
    leak its idle thread), with a GC finalizer as the backstop for
    managers that are dropped without shutdown."""
    import concurrent.futures

    with _WIRE_WORKERS_LOCK:
        worker = _WIRE_WORKERS.get(manager)
        if worker is None:
            worker = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpuft-fp8-order"
            )
            _WIRE_WORKERS[manager] = worker
            manager.register_shutdown_hook(
                lambda w=worker: w.shutdown(wait=False)
            )
            weakref.finalize(manager, worker.shutdown, wait=False)
        return worker


def _ft_allreduce_gradients_fp8(manager: Manager, grads: Any) -> Any:
    """Quantized sync, bucketed: all buckets' device quantizes + async d2h
    copies launch up front (they overlap each other and the wire), then the
    wire exchanges run STRICTLY in flatten order, one at a time, on the
    group's single FIFO worker — while the caller dequantizes bucket k, the
    worker runs bucket k+1's exchange.

    The wire phases must not overlap each other: the PG collectives are
    order-matched byte streams with no op tags, so concurrent bucket
    pipelines could enqueue their ops in different orders on different
    replicas and average mismatched buckets (or desync the stream). The
    single FIFO worker pins the op order to flatten order on every replica.

    No wire op may outlive the step boundary: on a failed bucket the
    remaining queued exchanges are cancelled and the in-flight one drained
    before returning, so a stale bucket can never enqueue a collective on a
    freshly reconfigured PG out of lockstep with peers (round-2 advisor)."""
    import concurrent.futures

    import jax.numpy as jnp

    from torchft_tpu.ops.quantization import default_wire

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    wire = default_wire()  # read once: env can flip between calls (tests do)
    buckets = _plan_buckets(leaves, _bucket_cap_bytes())

    quantized = []
    for members in buckets:
        bucket_leaves = [leaves[i] for i in members]
        quantize, dequantize = _bucket_codec(bucket_leaves, wire)
        payload, scales = quantize(bucket_leaves)
        prefetch_to_host((payload, scales))
        quantized.append((members, dequantize, payload, scales))

    out: List[Any] = [None] * len(leaves)
    wire_worker = _wire_worker_for(manager)
    futures: List["concurrent.futures.Future"] = []
    try:
        # Submit INSIDE the try: a submit that raises mid-loop (e.g. a
        # concurrent Manager.shutdown closed the executor) must still hit
        # the finally's cancel+drain for the exchanges already queued, or a
        # stale bucket could outlive the step boundary (round-3 advisor).
        for members, dequantize, payload, scales in quantized:
            futures.append(
                wire_worker.submit(
                    lambda p=payload, s=scales: manager.allreduce_prequantized(
                        p, s
                    ).wait()
                )
            )
        journal = getattr(manager, "_trace", None) or tracing.current()
        for bucket_index, ((members, dequantize, _, _), future) in enumerate(
            zip(quantized, futures)
        ):
            wire_t0 = time.perf_counter()
            health.injected_stall("wire")
            result = future.result()
            wire_dt = time.perf_counter() - wire_t0
            metrics.observe("tpuft_wire_bucket_seconds", wire_dt, path="fp8")
            journal.record(
                "wire_bucket", ph="X", dur=wire_dt,
                bucket=bucket_index, path="fp8",
            )
            if result is None:
                # Allreduce failed (error already reported; the step will
                # not commit): hand back the local gradients, same contract
                # as above.
                return grads
            avg_payload, avg_scales = result
            averaged = dequantize(jnp.asarray(avg_payload), jnp.asarray(avg_scales))
            for slot, i in enumerate(members):
                leaf = leaves[i]
                out[i] = (
                    jax.device_put(averaged[slot], leaf.sharding)
                    if isinstance(leaf, jax.Array)
                    else averaged[slot]
                )
    finally:
        # Success: every future is done — cancel/wait are no-ops. Failure:
        # cancel the queued exchanges and drain the in-flight one (its PG op
        # carries its own timeout) so the worker is quiescent at the step
        # boundary and reusable next step.
        for f in futures:
            f.cancel()
        concurrent.futures.wait(futures)
    return jax.tree_util.tree_unflatten(treedef, out)


class DistributedDataParallel:
    """Carries (module, manager); forward is ``module.apply``. The gradient
    path is :meth:`sync_gradients`, mirroring the comm-hook flow."""

    def __init__(self, manager: Manager, module: Any) -> None:
        self._manager = manager
        self._module = module

    @property
    def module(self) -> Any:
        return self._module

    def apply(self, params: Any, *args: Any, **kwargs: Any) -> Any:
        return self._module.apply(params, *args, **kwargs)

    def __call__(self, params: Any, *args: Any, **kwargs: Any) -> Any:
        return self.apply(params, *args, **kwargs)

    def sync_gradients(self, grads: Any, should_quantize: bool = False) -> Any:
        return ft_allreduce_gradients(self._manager, grads, should_quantize)


class PureDistributedDataParallel:
    """Per-parameter gradient sync: one allreduce work per leaf, waited
    together — lets large leaves overlap on the comm worker."""

    def __init__(self, manager: Manager, module: Any) -> None:
        self._manager = manager
        self._module = module

    def apply(self, params: Any, *args: Any, **kwargs: Any) -> Any:
        return self._module.apply(params, *args, **kwargs)

    __call__ = apply

    def sync_gradients(self, grads: Any) -> Any:
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        works: List[Work] = [self._manager.allreduce(leaf) for leaf in leaves]
        averaged = [np.asarray(w.wait()) for w in works]
        out = jax.tree_util.tree_unflatten(treedef, averaged)
        return jax.tree_util.tree_map(
            lambda avg, orig: jax.device_put(avg, orig.sharding)
            if isinstance(orig, jax.Array)
            else avg,
            out,
            grads,
        )
