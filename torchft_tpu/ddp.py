"""Fault-tolerant data parallelism over the replica axis.

The reference's ``DistributedDataParallel`` (/root/reference/torchft/ddp.py:
32-79) hooks torch's backward to route gradient buckets through
``manager.allreduce``. In JAX gradients are explicit pytrees, so the
equivalent surface is a gradient-sync transform applied between ``grad_fn``
and the optimizer:

- :func:`ft_allreduce_gradients` — bucketed sync of the whole gradient pytree
  (one flat wire message; the analogue of DDP's frozen buckets). The flatten
  order of a pytree is deterministic across replicas for identical models,
  which is the invariant DDP's bucket-freezing trick protects.
- :class:`DistributedDataParallel` — module wrapper carrying the manager;
  forwards apply the wrapped flax module.
- :class:`PureDistributedDataParallel` — per-parameter allreduce works
  (reference ddp.py:82-105), more overlap-friendly for giant leaves.
"""

from __future__ import annotations

from typing import Any, List

import jax
import numpy as np

from torchft_tpu.manager import Manager
from torchft_tpu.work import Work

__all__ = [
    "ft_allreduce_gradients",
    "DistributedDataParallel",
    "PureDistributedDataParallel",
]


def ft_allreduce_gradients(
    manager: Manager, grads: Any, should_quantize: bool = False
) -> Any:
    """Averages a gradient pytree across replica groups; returns jax arrays
    on the devices of the inputs. On error the step is poisoned (the commit
    will fail) and the *local* gradients come back — callers never branch.

    With ``should_quantize``, gradients are fp8-quantized **on device**
    (Pallas on TPU) so only payload + block scales cross the host boundary
    (~4x less traffic than f32) and dequantization happens on device too.
    """
    if should_quantize:
        return _ft_allreduce_gradients_fp8(manager, grads)
    work = manager.allreduce_pytree(grads)
    averaged = work.wait()

    def restore(avg_leaf: Any, orig_leaf: Any) -> Any:
        if isinstance(orig_leaf, jax.Array):
            return jax.device_put(avg_leaf, orig_leaf.sharding)
        return avg_leaf

    return jax.tree_util.tree_map(restore, averaged, grads)


# One jitted (quantize, dequantize) codec per gradient pytree structure.
_FP8_CODECS: dict = {}


def _ft_allreduce_gradients_fp8(manager: Manager, grads: Any) -> Any:
    import jax.numpy as jnp

    from torchft_tpu.ops.quantization import make_tree_fp8_codec

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    key = (treedef, tuple((leaf.shape, str(leaf.dtype)) for leaf in leaves))
    codec = _FP8_CODECS.get(key)
    if codec is None:
        codec = make_tree_fp8_codec(leaves)
        _FP8_CODECS[key] = codec
    quantize, dequantize = codec

    payload, scales = quantize(leaves)
    result = manager.allreduce_prequantized(payload, scales).wait()
    if result is None:
        # Allreduce failed (error already reported; the step will not
        # commit): hand back the local gradients, same contract as above.
        return grads
    avg_payload, avg_scales = result
    averaged = dequantize(jnp.asarray(avg_payload), jnp.asarray(avg_scales))
    # Restore the inputs' shardings/devices (contract: outputs live where
    # the inputs lived, so the jitted optimizer update never retraces).
    averaged = [
        jax.device_put(avg, leaf.sharding) if isinstance(leaf, jax.Array) else avg
        for avg, leaf in zip(averaged, leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, averaged)


class DistributedDataParallel:
    """Carries (module, manager); forward is ``module.apply``. The gradient
    path is :meth:`sync_gradients`, mirroring the comm-hook flow."""

    def __init__(self, manager: Manager, module: Any) -> None:
        self._manager = manager
        self._module = module

    @property
    def module(self) -> Any:
        return self._module

    def apply(self, params: Any, *args: Any, **kwargs: Any) -> Any:
        return self._module.apply(params, *args, **kwargs)

    def __call__(self, params: Any, *args: Any, **kwargs: Any) -> Any:
        return self.apply(params, *args, **kwargs)

    def sync_gradients(self, grads: Any, should_quantize: bool = False) -> Any:
        return ft_allreduce_gradients(self._manager, grads, should_quantize)


class PureDistributedDataParallel:
    """Per-parameter gradient sync: one allreduce work per leaf, waited
    together — lets large leaves overlap on the comm worker."""

    def __init__(self, manager: Manager, module: Any) -> None:
        self._manager = manager
        self._module = module

    def apply(self, params: Any, *args: Any, **kwargs: Any) -> Any:
        return self._module.apply(params, *args, **kwargs)

    __call__ = apply

    def sync_gradients(self, grads: Any) -> Any:
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        works: List[Work] = [self._manager.allreduce(leaf) for leaf in leaves]
        averaged = [np.asarray(w.wait()) for w in works]
        out = jax.tree_util.tree_unflatten(treedef, averaged)
        return jax.tree_util.tree_map(
            lambda avg, orig: jax.device_put(avg, orig.sharding)
            if isinstance(orig, jax.Array)
            else avg,
            out,
            grads,
        )
