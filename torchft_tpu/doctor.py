"""Preflight diagnostics: ``python -m torchft_tpu.doctor``.

Checks the things that actually break real deployments — native plane,
control-plane connectivity, accelerator backend, kernel sanity, env-var
typos — and prints one PASS/WARN/FAIL line each, exiting non-zero iff
something FAILed. Beyond-reference ops tooling (torchft debugging leans
on torchrun/NCCL envs; this stack's moving parts are different), built
from the failure modes the round logs actually hit: dead relay backends,
unbuildable native lib, unreachable lighthouse, misspelled ``TPUFT_*``
vars silently ignored.

Usage::

    python -m torchft_tpu.doctor [--lighthouse host:port] [--skip-device]
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Callable, List, Tuple

# Everything this process recognizes; drift is caught by the test that
# greps the tree for os.environ reads of TPUFT_* names.
KNOWN_ENV = {
    "TPUFT_LIGHTHOUSE", "TPUFT_MANAGER_PORT", "TPUFT_TIMEOUT_SEC",
    "TPUFT_QUORUM_TIMEOUT_SEC", "TPUFT_CONNECT_TIMEOUT_SEC",
    "TPUFT_QUORUM_RETRIES", "TPUFT_WATCHDOG_TIMEOUT_SEC", "TPUFT_BUCKET_MB",
    "TPUFT_TELEMETRY", "TPUFT_LOG", "TPUFT_STORE_ADDR", "TPUFT_WIRE_DTYPE",
    "TPUFT_JAX_COORDINATOR", "TPUFT_TCP_RING_MIN_MB", "TPUFT_TRACE_LOG",
    "TPUFT_NATIVE_LIB", "TPUFT_ALLOW_UNSAFE_PICKLE", "TPUFT_SOAK",
    "TPUFT_FLIGHT_RECORDER", "TPUFT_FLIGHT_RECORDER_SIZE",
    "TPUFT_HEARTBEAT_INTERVAL", "TPUFT_INIT_SYNC", "TPUFT_STRICT_COMMIT",
    "TPUFT_COMMIT_PIPELINE", "TPUFT_EMULATED_DEVICE_RTT_MS",
    # Depth-N commit pipelining: window depth (int or "auto") and the
    # adaptive controller's depth ceiling.
    "TPUFT_COMMIT_PIPELINE_DEPTH", "TPUFT_COMMIT_PIPELINE_ADAPTIVE",
    # Heal-path hardening: joiner-side progress floor, bounded failover
    # attempts, and the punisher's stream-fault arming channel.
    "TPUFT_HEAL_MIN_BYTES_PER_SEC", "TPUFT_HEAL_MAX_ATTEMPTS",
    "TPUFT_FAULT_FILE",
    # Multi-donor striped heal + delta rejoin (checkpointing/
    # http_transport.py): stripe switch, donor-set cap, delta switch.
    "TPUFT_HEAL_STRIPE", "TPUFT_HEAL_STRIPE_MAX_DONORS", "TPUFT_HEAL_DELTA",
    # Mass-rejoin storm plane: joiner-side aggregate ingress bound (the
    # stripe workers of one heal share one token bucket) and the storm
    # soak's round count (tests/test_chaos_soak.py).
    "TPUFT_HEAL_INGRESS_GBPS", "TPUFT_STORM_SOAK_ROUNDS",
    # Donor sidecar (out-of-process heal serving, checkpointing/
    # serve_child.py): mode switch, snapshot dir (shared-memory tmpfs),
    # child niceness, egress bound, respawn budget.
    "TPUFT_HEAL_SERVE_MODE", "TPUFT_HEAL_SERVE_DIR", "TPUFT_HEAL_SERVE_NICE",
    "TPUFT_HEAL_SERVE_GBPS", "TPUFT_HEAL_SERVE_MAX_RESTARTS",
    # Paced-egress fairness: heal streams' guaranteed share of the
    # serve-rate bucket while serving readers are also active.
    "TPUFT_HEAL_SERVE_PRIORITY_SHARE",
    # Committed-weights serving plane (torchft_tpu/serving): publication
    # cadence + chunking, relay poll cadence, long-poll push edge
    # (switch + bounded server-side hold), multi-tenant fairness + auth
    # (bearer-token table + per-tenant egress entitlements).
    "TPUFT_PUBLISH_EVERY", "TPUFT_PUBLISH_CHUNKS", "TPUFT_SERVING_POLL_SEC",
    "TPUFT_SERVING_NOTIFY", "TPUFT_SERVING_NOTIFY_HOLD_SEC",
    "TPUFT_SERVING_TENANT_TOKENS", "TPUFT_SERVING_TENANT_GBPS",
    # Versioned weight history (torchft_tpu/history.py): resident-bytes
    # budget + version-count cap for the committed-snapshot rings
    # (manager state ring, serving staged ring, relay ring).
    "TPUFT_HISTORY_BYTES", "TPUFT_HISTORY_MAX_VERSIONS",
    "TPUFT_METRICS_PORT", "TPUFT_METRICS_PUSH_SEC",
    # ZeRO plane (torchft_tpu/zero.py): enable flag for the harness/bench
    # loops, fleet-wide shard count, assignment policy, joiner heal
    # policy for shard parts, bench sizing.
    "TPUFT_ZERO", "TPUFT_ZERO_SHARDS", "TPUFT_ZERO_REBALANCE",
    "TPUFT_ZERO_HEAL_SHARDS", "TPUFT_ZERO_BENCH_ELEMS",
    # Quantized wire plane (torchft_tpu/wire_codec.py): per-wire-class
    # codecs for heal chunks, serving fan-out, and the ZeRO shard legs
    # (fp32 default = bit-for-bit the pre-codec wire).
    "TPUFT_HEAL_CODEC", "TPUFT_SERVING_CODEC", "TPUFT_ZERO_CODEC",
    "TPUFT_BENCH_CHILD",
    "TPUFT_BENCH_MODEL", "TPUFT_BENCH_STEPS", "TPUFT_BENCH_BATCH",
    "TPUFT_BENCH_SEQ", "TPUFT_BENCH_SYNC_EVERY", "TPUFT_BENCH_SYNC_DELAY",
    "TPUFT_BENCH_TPU_DEADLINE", "TPUFT_BENCH_TPU_DEADLINE_LARGE",
    "TPUFT_BENCH_CPU_DEADLINE", "TPUFT_BENCH_CPU_FULL_DEADLINE",
    "TPUFT_BENCH_NO_PROBE",
    "TPUFT_EMULATED_RTT_MS", "TPUFT_EMULATED_GBPS",
    # WAN topology matrix (utils/netem.py): replica-id -> region map,
    # explicit self-region override, relay-tier region pin, and the heal
    # plane's per-donor bandwidth EWMA smoothing factor. Per-pair link
    # envs (TPUFT_EMULATED_LINK_<SRC>_<DST> / _LOCAL / _CROSS) are
    # prefix-matched in _check_env rather than enumerated here.
    "TPUFT_EMULATED_TOPOLOGY", "TPUFT_EMULATED_REGION",
    "TPUFT_SERVING_REGION", "TPUFT_HEAL_BW_EWMA_ALPHA",
    # Correctness tooling: runtime lock-order detector + static analyzer
    # (python -m torchft_tpu.analysis; docs/static_analysis.md).
    "TPUFT_LOCK_CHECK", "TPUFT_ANALYSIS_REFERENCE", "TPUFT_ANALYSIS_BASELINE",
    # Interleaving explorer budgets (python -m torchft_tpu.analysis
    # --explore; utils/schedules.explore_defaults): schedule budget, RNG
    # seed, max preemption bound, random long-tail count.
    "TPUFT_EXPLORE_BUDGET", "TPUFT_EXPLORE_SEED", "TPUFT_EXPLORE_PREEMPTIONS",
    "TPUFT_EXPLORE_RANDOM",
    # Fleet trace plane (torchft_tpu/tracing.py): recording switch, journal
    # ring size, store clock-beacon sampling switch.
    "TPUFT_TRACE", "TPUFT_TRACE_SIZE", "TPUFT_TRACE_CLOCK",
    # Goodput ledger + SLO plane (torchft_tpu/goodput.py): ledger window
    # width, retained-window count + byte budget, and the declarative
    # goodput SLO (target fraction, K-consecutive-windows hysteresis,
    # burn-rate trip multiplier).
    "TPUFT_GOODPUT_WINDOW_SEC", "TPUFT_GOODPUT_WINDOWS", "TPUFT_GOODPUT_BYTES",
    "TPUFT_SLO_GOODPUT", "TPUFT_SLO_WINDOWS", "TPUFT_SLO_BURN_RATE",
    # Gray-failure ejection plane (torchft_tpu/health.py): master switch,
    # verdict knobs (fleet-relative threshold / hysteresis windows / peer
    # freshness / absolute gap floor), board push cadence, wedge watchdog
    # (deadline scale + floor + escalation action), injected-stall size,
    # self-probe toggles, and the quarantine gate (backoff base/cap,
    # crash-loop sliding window + park cooldown, state dir).
    "TPUFT_HEALTH", "TPUFT_HEALTH_THRESHOLD", "TPUFT_HEALTH_CONSECUTIVE",
    "TPUFT_HEALTH_MIN_PEERS", "TPUFT_HEALTH_EWMA_ALPHA",
    "TPUFT_HEALTH_PEER_TTL_SEC", "TPUFT_HEALTH_PUSH_SEC",
    "TPUFT_HEALTH_MIN_GAP_SEC", "TPUFT_HEALTH_WEDGE_SCALE",
    "TPUFT_HEALTH_WEDGE_FLOOR_SEC", "TPUFT_HEALTH_WEDGE_ACTION",
    "TPUFT_HEALTH_SLOW_MS", "TPUFT_HEALTH_PROBE",
    "TPUFT_HEALTH_PROBE_TIMEOUT_SEC", "TPUFT_QUARANTINE_BASE_SEC",
    "TPUFT_QUARANTINE_CAP_SEC", "TPUFT_QUARANTINE_MAX_EJECTS",
    "TPUFT_QUARANTINE_WINDOW_SEC", "TPUFT_QUARANTINE_PARK_SEC",
    "TPUFT_QUARANTINE_DIR",
    # Progressive delivery (torchft_tpu/serving/rollout.py): per-tenant
    # stream policy table, sha256 canary-cohort width, shadow-tenant
    # list, verdict actuation mode (actuate|alert), and the rollout
    # evaluator's hysteresis knobs (multiplicative threshold /
    # K-consecutive windows / absolute gap floor / evidence floor).
    "TPUFT_ROLLOUT_POLICY", "TPUFT_ROLLOUT_CANARY_PERCENT",
    "TPUFT_ROLLOUT_SHADOW_TENANTS", "TPUFT_ROLLOUT_MODE",
    "TPUFT_ROLLOUT_THRESHOLD", "TPUFT_ROLLOUT_WINDOWS",
    "TPUFT_ROLLOUT_MIN_GAP", "TPUFT_ROLLOUT_MIN_SAMPLES",
    # Repo tooling outside the package (tests/benchmarks/sentinel) — real
    # knobs a user may have exported; not typos.
    "TPUFT_SOAK_SECONDS", "TPUFT_SOAK_SEED",
    "TPUFT_REGEN_FIXTURES", "TPUFT_SENTINEL_INTERVAL",
    "TPUFT_TRANSPORT_BENCH_GB", "TPUFT_TRANSPORT_BENCH_MODE",
    "TPUFT_TRANSPORT_BENCH_DEADLINE", "TPUFT_TRANSPORT_RSS_BOUND",
    "TPUFT_TRANSPORT_BENCH_PACE_GBPS", "TPUFT_TRANSPORT_BENCH_STRIPE_GBPS",
    "TPUFT_CPS_REPLICAS", "TPUFT_CPS_ROUNDS", "TPUFT_CPS_GROUP_WORLD_SIZE",
    "TPUFT_STORM_BENCH_MB", "TPUFT_STORM_BENCH_GBPS",
    "TPUFT_STORM_BENCH_INGRESS_GBPS", "TPUFT_STORM_BENCH_DEADLINE",
    "TPUFT_WAN_BENCH_MB", "TPUFT_WAN_BENCH_DEADLINE",
    "TPUFT_QUANT_BENCH_BYTES",
}

Check = Tuple[str, Callable[[], Tuple[str, str]]]  # name -> (status, detail)


def _check_toolchain() -> Tuple[str, str]:
    """Native build toolchain state. WARN, not FAIL, when absent: the
    pure-python planes still work and the test suite skips (not errors) the
    native-gated cases — but the operator should know why."""
    from torchft_tpu import _native

    available, detail = _native.toolchain_state()
    return ("PASS" if available else "WARN"), detail


def _check_native() -> Tuple[str, str]:
    from torchft_tpu import _native

    try:
        path = _native.ensure_built()
    except _native.NativeToolchainMissing as e:
        return "FAIL", f"native plane unavailable: {e}"
    return "PASS", f"libtpuft loaded ({path})"


def _check_lighthouse(address: str) -> Tuple[str, str]:
    if not address:
        return "WARN", "no --lighthouse / TPUFT_LIGHTHOUSE set; skipped"
    from torchft_tpu.coordination import LighthouseClient

    client = LighthouseClient(address, connect_timeout=5.0)
    status = client.status(timeout=5.0)
    return (
        "PASS",
        f"lighthouse at {address} answered "
        f"({len(status.members)} members, has_quorum={status.has_quorum})",
    )


def _check_store() -> Tuple[str, str]:
    from torchft_tpu.parallel.store import StoreClient, StoreServer

    server = StoreServer()
    try:
        client = StoreClient(server.address())
        client.set("doctor/ping", b"ok")
        if client.get("doctor/ping", timeout=5.0) != b"ok":
            return "FAIL", "KV roundtrip returned wrong value"
        return "PASS", "native KV store roundtrip ok"
    finally:
        server.shutdown()


def _check_device() -> Tuple[str, str]:
    import subprocess

    from torchft_tpu.utils.platform import probe_accelerator

    if probe_accelerator(timeout=120.0):
        # Device detail from a deadline-bounded child, never in-process:
        # the relay can wedge BETWEEN the probe and a naive jax.devices()
        # here (its documented mid-run death mode), and the doctor must
        # not hang — it is the tool for diagnosing exactly that.
        detail = "device detail fetch timed out"
        try:
            out = subprocess.run(
                [
                    sys.executable, "-c",
                    "import jax; d = jax.devices()[0];"
                    "print(d.platform, d.device_kind)",
                ],
                timeout=60,
                capture_output=True,
                text=True,
            )
            if out.returncode == 0:
                detail = out.stdout.strip()
        except subprocess.TimeoutExpired:
            pass
        return "PASS", f"accelerator probe ok ({detail})"
    return (
        "WARN",
        "accelerator probe failed (relay down or no TPU) — CPU fallback "
        "paths still work; see CLAUDE.md relay notes",
    )


def _check_kernels() -> Tuple[str, str]:
    import numpy as np

    from torchft_tpu.ops import quantization as q

    x = np.linspace(-3, 3, 1000, dtype=np.float32)
    for wire in ("fp8", "int8", "int4"):
        payload, scales = q.quantize_blocks(x, wire=wire)
        back = q.dequantize_blocks(payload, scales, x.shape, x.dtype)
        if not np.allclose(back, x, atol=0.5):
            return "FAIL", f"{wire} codec roundtrip error"
    return "PASS", "host wire codecs (fp8/int8/int4) roundtrip ok"


def _check_wire_codec_negotiation() -> Tuple[str, str]:
    """Quantized-wire-plane preflight. WARN, never FAIL: the codec knobs
    change the wire FORMAT, so the thing that breaks real deployments is
    a mixed fleet — a codec-less (format-2) peer refuses an encoded
    donor's format-3 /meta cleanly and the heal retries elsewhere, which
    in a fully mixed fleet means "falls back to operators setting fp32",
    never a silent misdecode. This check names that, probes an
    encode/decode roundtrip per configured codec, and flags the
    bitwise-heal envelope."""
    from torchft_tpu import wire_codec

    knobs = []
    for env in (
        wire_codec.ENV_HEAL_CODEC,
        wire_codec.ENV_SERVING_CODEC,
        wire_codec.ENV_ZERO_CODEC,
    ):
        raw = os.environ.get(env)
        if raw is None or raw.strip() == "":
            continue
        try:
            codec = wire_codec._env_codec(env)
        except ValueError:
            return (
                "WARN",
                f"{env}={raw!r} is not one of {sorted(wire_codec.CODECS)}; "
                "the plane would refuse to stage — unset it or pick a "
                "valid codec",
            )
        if codec != "fp32":
            knobs.append(f"{env}={codec}")
    if not knobs:
        return (
            "PASS",
            "all bulk wires fp32 (bit-for-bit pre-codec format; "
            "TPUFT_HEAL_CODEC/TPUFT_SERVING_CODEC/TPUFT_ZERO_CODEC unset)",
        )
    try:
        import numpy as np

        probe = {"w": np.linspace(-2, 2, 4096, dtype=np.float32)}
        for knob in knobs:
            codec = knob.split("=", 1)[1]
            enc, stats = wire_codec.encode_state(probe, codec)
            wire_codec.decode_state(enc)
            if stats["encoded_leaves"] != 1:
                return "WARN", f"{codec} probe encoded nothing"
    except Exception as e:  # noqa: BLE001 — WARN-never-FAIL probe
        return "WARN", f"codec roundtrip probe failed: {e}"
    return (
        "WARN",
        f"{', '.join(knobs)}: encoded stages are /meta format 3 — "
        "codec-less peers refuse them cleanly and a MIXED fleet must fall "
        "back to fp32 (unset the knob) until every peer is codec-aware; "
        "quantized HEALS are lossy per adoption (pair with ZeRO, whose "
        "next allgather re-syncs params bitwise, or DiLoCo outer syncs)",
    )


def _check_metrics() -> Tuple[str, str]:
    """Probes the local /metrics endpoint when TPUFT_METRICS_PORT is set.
    Never FAILs: the metrics plane is optional, and a dead scrape endpoint
    must not block a launch the way a dead native plane should."""
    from torchft_tpu import metrics

    value = os.environ.get(metrics.ENV_PORT, "")
    if not value:
        return (
            "PASS",
            f"metrics export off (set {metrics.ENV_PORT} to serve /metrics)",
        )
    try:
        port = int(value)
    except ValueError:
        return "WARN", f"{metrics.ENV_PORT}={value!r} is not an integer"
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as resp:
            body = resp.read().decode(errors="replace")
    except Exception as e:  # noqa: BLE001 — WARN, never FAIL, on any probe error
        return (
            "WARN",
            f"no /metrics listener on 127.0.0.1:{port} ({e}) — is a "
            "replica (or metrics.maybe_start_http_server) running here?",
        )
    n_series = sum(
        1 for line in body.splitlines() if line and not line.startswith("#")
    )
    return "PASS", f"/metrics on :{port} serving {n_series} series"


def _check_trace() -> Tuple[str, str]:
    """Fleet trace plane preflight: validates the TPUFT_TRACE* knobs and
    probes the local /trace.json surface when a metrics port is up.
    WARN, never FAIL: the trace plane is observability — a dead journal
    endpoint must not block a launch."""
    from torchft_tpu import tracing

    if os.environ.get(tracing.ENV_TRACE, "1") == "0":
        return "PASS", f"trace plane off ({tracing.ENV_TRACE}=0)"
    size_raw = os.environ.get(tracing.ENV_SIZE)
    if size_raw is not None:
        try:
            if int(size_raw) < 1:
                raise ValueError
        except ValueError:
            return "WARN", f"{tracing.ENV_SIZE}={size_raw!r} is not a positive int"
    value = os.environ.get("TPUFT_METRICS_PORT", "")
    if not value:
        return (
            "PASS",
            "trace plane on (journal in-process; set TPUFT_METRICS_PORT to "
            "also serve GET /trace.json)",
        )
    try:
        port = int(value)
    except ValueError:
        return "PASS", "trace plane on (metrics port unparseable; see metrics check)"
    import json as _json
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/trace.json", timeout=5
        ) as resp:
            payload = _json.loads(resp.read().decode(errors="replace"))
    except Exception as e:  # noqa: BLE001 — WARN, never FAIL, on any probe error
        return (
            "WARN",
            f"no /trace.json listener on 127.0.0.1:{port} ({e}) — is a "
            "replica (or metrics.maybe_start_http_server) running here?",
        )
    n_events = len(payload.get("events", []))
    return (
        "PASS",
        f"/trace.json on :{port} serving {n_events} journal events "
        f"(replica {payload.get('replica_id')}/{payload.get('group_rank')})",
    )


def _check_goodput() -> Tuple[str, str]:
    """Goodput ledger + SLO plane preflight: names any unparsable
    ``TPUFT_SLO_*`` / ledger-budget env, and warns when the trace plane is
    disabled (the ledger is a fold over the trace ring, so it degrades
    with it). WARN, never FAIL: accounting and alerting are observability
    — a bad knob must not block a launch."""
    from torchft_tpu import goodput, tracing

    problems: List[str] = []
    for name, floor in (
        (goodput.ENV_WINDOW_SEC, 1e-3),
        (goodput.ENV_SLO_BURN_RATE, 1e-9),
    ):
        raw = os.environ.get(name)
        if raw is None:
            continue
        try:
            if float(raw) < floor:
                raise ValueError
        except ValueError:
            problems.append(f"{name}={raw!r} is not a float >= {floor:g}")
    for name in (goodput.ENV_WINDOWS, goodput.ENV_BYTES, goodput.ENV_SLO_WINDOWS):
        raw = os.environ.get(name)
        if raw is None:
            continue
        try:
            if int(raw) < 1:
                raise ValueError
        except ValueError:
            problems.append(f"{name}={raw!r} is not a positive int")
    slo_raw = os.environ.get(goodput.ENV_SLO_GOODPUT)
    slo_state = "unset (SLO alerting off)"
    if slo_raw is not None:
        try:
            target = float(slo_raw)
            if not 0.0 < target <= 1.0:
                raise ValueError
            slo_state = f"target {target:g}"
        except ValueError:
            problems.append(
                f"{goodput.ENV_SLO_GOODPUT}={slo_raw!r} is not a fraction in "
                "(0, 1] — SLO alerting stays OFF"
            )
    if problems:
        return "WARN", "; ".join(problems)
    if os.environ.get(tracing.ENV_TRACE, "1") == "0":
        return (
            "WARN",
            f"trace plane off ({tracing.ENV_TRACE}=0): the goodput ledger "
            "is a fold over the trace ring, so windows degrade to "
            "{'enabled': False} and SLO alerting never evaluates",
        )
    return "PASS", f"ledger armed; SLO {slo_state}"


def _check_heal_serve() -> Tuple[str, str]:
    """Heal-serving sidecar preflight: validates the mode switch and
    probes the shared-memory snapshot directory (a write + unlink).
    WARN, never FAIL: inline serving always remains as the fallback, so
    a missing tmpfs must not block a launch."""
    import tempfile

    from torchft_tpu.checkpointing import serve_child

    mode = os.environ.get(serve_child.ENV_SERVE_MODE, "inline")
    if mode not in ("inline", "child"):
        return (
            "WARN",
            f"{serve_child.ENV_SERVE_MODE}={mode!r} is not inline|child "
            "(transports will refuse it; unset or fix)",
        )
    root = serve_child.serve_dir_root()
    shm = "shared-memory tmpfs" if root.startswith("/dev/shm") else "plain dir"
    try:
        with tempfile.NamedTemporaryFile(dir=root, prefix="tpuft-doctor-"):
            pass
        import shutil

        free_gb = shutil.disk_usage(root).free / (1 << 30)
        detail = (
            f"serve mode {mode}; snapshot dir {root} ({shm}) writable, "
            f"{free_gb:.1f} GB free"
        )
        if mode == "child" and free_gb < 1.0:
            return "WARN", detail + " — low for a checkpoint snapshot"
        return "PASS", detail
    except OSError as e:
        status = "WARN" if mode == "child" else "PASS"
        return (
            status,
            f"serve mode {mode}; snapshot dir {root} not writable ({e}) — "
            "child mode would degrade to inline serving",
        )


def _check_zero(lighthouse: str) -> Tuple[str, str]:
    """ZeRO plane preflight. WARN, never FAIL: the plane degrades to
    unsharded math, it never breaks training — but an operator who set
    TPUFT_ZERO expecting 1/N memory should hear that a cohort of one (or
    a bad knob) silently degenerates to full state on every replica."""
    from torchft_tpu import zero

    enabled = os.environ.get(zero.ENV_ZERO, "0") not in ("", "0")
    shards_raw = os.environ.get(zero.ENV_ZERO_SHARDS)
    if not enabled and shards_raw is None:
        return "PASS", f"ZeRO off (set {zero.ENV_ZERO}=1 to shard the update)"
    try:
        num_shards = int(shards_raw) if shards_raw else zero.DEFAULT_NUM_SHARDS
        if num_shards < 1:
            raise ValueError
    except ValueError:
        return "WARN", f"{zero.ENV_ZERO_SHARDS}={shards_raw!r} is not a positive int"
    policy = os.environ.get(zero.ENV_ZERO_REBALANCE, "block")
    if policy not in ("block", "strided"):
        return "WARN", f"{zero.ENV_ZERO_REBALANCE}={policy!r} is not block|strided"
    heal = os.environ.get(zero.ENV_ZERO_HEAL_SHARDS, "skip")
    if heal not in ("skip", "fetch"):
        return "WARN", f"{zero.ENV_ZERO_HEAL_SHARDS}={heal!r} is not skip|fetch"
    if not lighthouse:
        return (
            "PASS",
            f"ZeRO on: {num_shards} shards, policy {policy} (no lighthouse "
            "to probe cohort size)",
        )
    try:
        from torchft_tpu.coordination import LighthouseClient

        client = LighthouseClient(lighthouse, connect_timeout=5.0)
        try:
            members = len(client.status(timeout=5.0).members)
        finally:
            client.close()
    except Exception as e:  # noqa: BLE001 — WARN-never-FAIL probe
        return "WARN", f"ZeRO on but lighthouse probe failed ({e})"
    if members <= 1:
        return (
            "WARN",
            f"ZeRO on with a cohort of {members}: one replica owns all "
            f"{num_shards} shards — memory/heal savings silently degenerate "
            "to unsharded until more replicas join",
        )
    return (
        "PASS",
        f"ZeRO on: {num_shards} shards over {members} replicas "
        f"(~1/{members} opt state each), policy {policy}",
    )


def _check_heal_stripe(lighthouse: str) -> Tuple[str, str]:
    """Striped-heal preflight. WARN, never FAIL: the heal plane degrades
    to the single-donor path, it never breaks recovery — but an operator
    expecting recovery bandwidth to scale with fleet size should hear
    that the donor set is degenerate (striping off, cap of one, or a
    fleet with at most one donor-capable member)."""
    from torchft_tpu.checkpointing import http_transport as ht

    stripe = ht.heal_stripe_enabled()
    delta = ht.heal_delta_enabled()
    cap = ht.heal_stripe_max_donors()
    knobs = f"stripe={'on' if stripe else 'off'}, cap={cap}, delta={'on' if delta else 'off'}"
    if not stripe:
        return (
            "WARN",
            f"{knobs}: heals run single-donor — recovery time will not "
            f"improve with fleet size (unset {ht.ENV_HEAL_STRIPE}=0 to "
            "re-enable)",
        )
    if cap <= 1:
        return (
            "WARN",
            f"{knobs}: {ht.ENV_HEAL_STRIPE_MAX_DONORS}={cap} caps every "
            "stripe set to the assigned donor — striping is effectively off",
        )
    if not lighthouse:
        return "PASS", f"{knobs} (no lighthouse to probe the donor set)"
    try:
        from torchft_tpu.coordination import LighthouseClient

        client = LighthouseClient(lighthouse, connect_timeout=5.0)
        try:
            members = client.status(timeout=5.0).members
        finally:
            client.close()
    except Exception as e:  # noqa: BLE001 — WARN-never-FAIL probe
        return "WARN", f"{knobs} but lighthouse probe failed ({e})"
    donors = sum(1 for m in members if not m.joining)
    if donors <= 1:
        return (
            "WARN",
            f"{knobs}: only {donors} donor-capable member(s) in the fleet "
            "— heals degrade to the single-donor path until more replicas "
            "join",
        )
    return (
        "PASS",
        f"{knobs}: {min(donors, cap)} donors available per striped heal "
        f"({donors} donor-capable members)",
    )


def _check_rejoin_storm(lighthouse: str) -> Tuple[str, str]:
    """Mass-rejoin storm preflight. WARN, never FAIL: a degenerate storm
    (more joiners than donor-capable members) still converges — the
    per-joiner fairness split keeps every joiner progressing and
    ``TPUFT_HEAL_MAX_ATTEMPTS`` still bounds each heal — but the
    operator should hear that time-to-full-strength is donor-egress
    bound, not joiner-count bound, in that regime."""
    from torchft_tpu.checkpointing import http_transport as ht

    raw = os.environ.get(ht.ENV_HEAL_INGRESS)
    if raw is not None:
        try:
            gbps = float(raw)
        except ValueError:
            return (
                "WARN",
                f"{ht.ENV_HEAL_INGRESS}={raw!r} is not a number (the "
                "joiner ingress bound will silently fall back to "
                "unbounded)",
            )
        ingress = f"ingress={gbps} Gbps" if gbps > 0 else "ingress=unbounded"
    else:
        ingress = "ingress=unbounded"
    if not lighthouse:
        return (
            "PASS",
            f"{ingress} (no lighthouse to probe the joiner/donor balance)",
        )
    try:
        from torchft_tpu.coordination import LighthouseClient

        client = LighthouseClient(lighthouse, connect_timeout=5.0)
        try:
            members = client.status(timeout=5.0).members
        finally:
            client.close()
    except Exception as e:  # noqa: BLE001 — WARN-never-FAIL probe
        return "WARN", f"{ingress} but lighthouse probe failed ({e})"
    joiners = sum(1 for m in members if m.joining)
    donors = len(members) - joiners
    if joiners > max(donors, 0):
        return (
            "WARN",
            f"{ingress}: degenerate storm in flight — {joiners} joiner(s) "
            f"vs {donors} donor-capable member(s); every joiner still "
            "progresses (per-joiner share of the paced donor egress), but "
            "time-to-full-strength is bound by aggregate donor egress "
            "(TPUFT_HEAL_SERVE_GBPS x donors), not by joiner parallelism",
        )
    return (
        "PASS",
        f"{ingress}: {joiners} joiner(s) / {donors} donor-capable "
        "member(s) — storm headroom ok",
    )


def _check_serving() -> Tuple[str, str]:
    """Committed-weights serving-plane preflight: validates the serving
    knobs, then runs one in-process relay-TREE roundtrip over loopback
    HTTP (publisher -> root relay -> edge relay -> subscriber, tiny
    payload) so tier stacking — the depth chain every production fan-out
    relies on — is probed, not assumed. WARN, never FAIL — serving is a
    read path; a broken relay means readers lag, not that training is
    wrong."""
    import numpy as np

    from torchft_tpu.checkpointing import serve_child
    from torchft_tpu.serving import (
        CachingRelay,
        WeightPublisher,
        WeightSubscriber,
        notify_enabled,
        publish_every,
    )

    hold_raw = os.environ.get("TPUFT_SERVING_NOTIFY_HOLD_SEC")
    if hold_raw is not None:
        try:
            if float(hold_raw) <= 0:
                raise ValueError
        except ValueError:
            return (
                "WARN",
                f"TPUFT_SERVING_NOTIFY_HOLD_SEC={hold_raw!r} is not a "
                "positive number (the long-poll hold will fall back to its "
                "default)",
            )
    for env, parser in (
        (serve_child.ENV_SERVING_TENANT_TOKENS, serve_child.serving_tenant_tokens),
        (serve_child.ENV_SERVING_TENANT_GBPS, serve_child.serving_tenant_gbps),
    ):
        raw = os.environ.get(env, "")
        configured = [e for e in raw.split(",") if e.strip()]
        if len(configured) != len(parser()):
            return (
                "WARN",
                f"{env}={raw!r} has malformed entries (parsed "
                f"{len(parser())} of {len(configured)}) — the skipped "
                "tenants silently lose their identity/entitlement",
            )

    pub = None
    root = None
    edge = None
    try:
        pub = WeightPublisher(num_chunks=2, timeout=5.0)
        pub.publish(
            step=1, quorum_id=0, state={"doctor": np.arange(8, dtype=np.float32)}
        )
        root = CachingRelay([pub.address()], timeout=5.0, start=False)
        if not root.poll_once():
            return "WARN", "root relay failed to pull the probe version"
        edge = CachingRelay([root.address()], timeout=5.0, start=False)
        if not edge.poll_once():
            return "WARN", "edge relay failed to pull through the root tier"
        version = WeightSubscriber([edge.address()], timeout=5.0).poll()
        if version is None or version.step != 1:
            return "WARN", "subscriber failed to adopt through the 2-deep tree"
        tenants = serve_child.serving_tenant_gbps()
        return (
            "PASS",
            "publisher->root->edge->subscriber tree probe ok (publish "
            f"cadence: every {publish_every()} committed step(s); push "
            f"{'on' if notify_enabled() else 'off'}; "
            + (
                f"{len(tenants)} tenant entitlement(s)"
                if tenants
                else "single-tenant egress"
            )
            + ")",
        )
    except Exception as e:  # noqa: BLE001 — WARN, never FAIL
        return "WARN", f"serving probe failed: {type(e).__name__}: {e}"
    finally:
        for node in (edge, root):
            if node is not None:
                node.shutdown(wait=False)
        if pub is not None:
            pub.shutdown(wait=False)


def _check_rollout() -> Tuple[str, str]:
    """Progressive-delivery preflight (serving/rollout.py). WARN, never
    FAIL: rollout is serving-plane policy — a broken table means readers
    see the wrong stream view (or the full pre-rollout view), never that
    training is wrong. Validates the policy table + cohort/hysteresis
    knobs and names the two intentional degenerate modes: no policy at
    all (the exact pre-rollout wire — every publish is stream-less) and
    alerting-only actuation (verdicts counted + traced, publisher never
    touched)."""
    from torchft_tpu.serving import rollout

    policy = rollout.RolloutPolicy.from_env()
    if policy.errors:
        return (
            "WARN",
            f"{rollout.ENV_POLICY} has malformed entries "
            f"({'; '.join(policy.errors)}) — the skipped tenants silently "
            "fall back to the percent-cohort/stable default",
        )
    problems = []
    for env, floor in (
        (rollout.ENV_THRESHOLD, 1.01),
        (rollout.ENV_MIN_GAP, 0.0),
    ):
        raw = os.environ.get(env)
        if raw is None:
            continue
        try:
            if float(raw) < floor:
                raise ValueError
        except ValueError:
            problems.append(f"{env}={raw!r} is not a float >= {floor:g}")
    for env in (rollout.ENV_WINDOWS, rollout.ENV_MIN_SAMPLES):
        raw = os.environ.get(env)
        if raw is None:
            continue
        try:
            if int(raw) < 1:
                raise ValueError
        except ValueError:
            problems.append(f"{env}={raw!r} is not a positive int")
    percent_raw = os.environ.get(rollout.ENV_CANARY_PERCENT)
    if percent_raw is not None:
        try:
            if not 0.0 <= float(percent_raw) <= 100.0:
                raise ValueError
        except ValueError:
            problems.append(
                f"{rollout.ENV_CANARY_PERCENT}={percent_raw!r} is not a "
                "percentage in [0, 100]"
            )
    mode = os.environ.get(rollout.ENV_MODE, "actuate").strip().lower()
    if mode not in ("actuate", "alert"):
        problems.append(
            f"{rollout.ENV_MODE}={mode!r} is not actuate|alert "
            "(falls back to actuate)"
        )
    if problems:
        return "WARN", "; ".join(problems)
    if not policy.active():
        return (
            "PASS",
            "no rollout policy configured — publishes are stream-less and "
            "every tenant sees the full view (the exact pre-rollout wire)",
        )
    pieces = [
        f"{len(policy.entries)} explicit tenant entr(y/ies)",
        f"{policy.percent:g}% sha256 canary cohort",
        f"{len(policy.shadows)} shadow tenant(s)",
    ]
    if mode == "alert":
        pieces.append(
            "ALERTING-ONLY verdicts (bad canaries are counted + traced "
            "but never auto-retracted)"
        )
    return "PASS", "rollout policy active: " + "; ".join(pieces)


def _check_commit_pipeline() -> Tuple[str, str]:
    """Commit-pipeline window preflight. WARN, never FAIL: any depth
    trains correctly — but the snapshot ring holds one full
    ``(params, opt_state)`` copy per window slot (resident bytes ~=
    depth x (params + optimizer state); watch
    ``tpuft_pipeline_snapshot_bytes``), so an operator who set a deep
    window should hear the memory formula before HBM does."""
    from torchft_tpu import manager as mgr

    raw = os.environ.get(mgr.COMMIT_PIPELINE_DEPTH_ENV)
    legacy = os.environ.get(mgr.COMMIT_PIPELINE_ENV)
    if raw is None:
        raw = legacy
    adaptive_raw = os.environ.get(mgr.COMMIT_PIPELINE_ADAPTIVE_ENV)
    adaptive_max = mgr.DEFAULT_ADAPTIVE_MAX_DEPTH
    if adaptive_raw is not None:
        try:
            adaptive_max = int(adaptive_raw)
            if adaptive_max < 1:
                raise ValueError
        except ValueError:
            return (
                "WARN",
                f"{mgr.COMMIT_PIPELINE_ADAPTIVE_ENV}={adaptive_raw!r} is not "
                "a positive int (the adaptive depth ceiling)",
            )
    if raw is None:
        return (
            "PASS",
            "commit pipeline off (set "
            f"{mgr.COMMIT_PIPELINE_DEPTH_ENV}=N|auto to hide commit RTTs "
            "behind an N-step speculative window)",
        )
    if raw.strip().lower() == "auto":
        depth = adaptive_max  # the ceiling is what bounds the ring
        label = f"auto (ceiling {adaptive_max})"
    else:
        try:
            depth = int(raw)
            if depth < 0:
                raise ValueError
        except ValueError:
            return (
                "WARN",
                f"commit pipeline depth {raw!r} is not an int >= 0 or "
                "'auto' (Manager will refuse it)",
            )
        label = str(depth)
    if depth > 8:
        return (
            "WARN",
            f"commit pipeline depth {label}: the rollback snapshot ring "
            f"holds {depth} full (params, opt_state) copies — resident "
            f"bytes ~= {depth} x (params + optimizer state). Past ~8 the "
            "memory bill usually dwarfs the hidden RTT; watch "
            "tpuft_pipeline_snapshot_bytes and size against HBM",
        )
    return (
        "PASS",
        f"commit pipeline depth {label} (phantom-commit envelope <= "
        f"{depth} step(s); snapshot ring ~= {max(depth, 1)} x "
        "(params + opt_state) resident)",
    )


def _check_history() -> Tuple[str, str]:
    """Versioned weight-history preflight (torchft_tpu/history.py).
    WARN, never FAIL: any budget trains and serves correctly — but every
    resident ring version is one full ``(params, opt_state)`` copy, the
    same K x (params + opt_state) formula as the commit-pipeline snapshot
    ring (watch ``tpuft_history_bytes``), so an operator who pinned a
    deep history should hear the memory bill before HBM does."""
    from torchft_tpu import history as hist
    from torchft_tpu import manager as mgr

    raw_versions = os.environ.get(hist.ENV_HISTORY_MAX_VERSIONS)
    raw_bytes = os.environ.get(hist.ENV_HISTORY_BYTES)
    if raw_versions is not None:
        try:
            if int(raw_versions) < 1:
                raise ValueError
        except ValueError:
            return (
                "WARN",
                f"{hist.ENV_HISTORY_MAX_VERSIONS}={raw_versions!r} is not a "
                "positive int (rings will fall back to their defaults)",
            )
    if raw_bytes is not None:
        try:
            float(raw_bytes)
        except ValueError:
            return (
                "WARN",
                f"{hist.ENV_HISTORY_BYTES}={raw_bytes!r} is not a number "
                "(rings will fall back to count-bounded budgets)",
            )
    # Effective manager-ring width: env override, else window depth + 1.
    depth_raw = os.environ.get(mgr.COMMIT_PIPELINE_DEPTH_ENV) or os.environ.get(
        mgr.COMMIT_PIPELINE_ENV
    )
    if depth_raw and depth_raw.strip().lower() == "auto":
        depth = mgr.DEFAULT_ADAPTIVE_MAX_DEPTH
    else:
        try:
            depth = int(depth_raw) if depth_raw else 0
        except ValueError:
            depth = 0
    k = hist.history_max_versions(max(1, depth) + 1)
    serving_k = hist.history_max_versions(hist.DEFAULT_SERVING_VERSIONS)
    budget = hist.history_bytes_budget()
    budget_note = (
        f"; byte budget {budget} ({hist.ENV_HISTORY_BYTES})"
        if budget is not None
        else "; count-bounded (set TPUFT_HISTORY_BYTES for a byte budget)"
    )
    if k > 8 and budget is None:
        # Same threshold as the commit-pipeline snapshot probe: past ~8
        # resident copies the memory bill dwarfs what the history buys.
        return (
            "WARN",
            f"history ring keeps {k} committed versions with no byte "
            f"budget — resident bytes ~= {k} x (params + opt_state); "
            "watch tpuft_history_bytes, or set TPUFT_HISTORY_BYTES",
        )
    return (
        "PASS",
        f"history ring: manager keeps {k} committed version(s) (exact "
        f"deep-window donor serves), serving keeps {serving_k} staged "
        f"version(s) (pinned/latest-1/rollback reads){budget_note}",
    )


def _check_health(lighthouse: str) -> Tuple[str, str]:
    """Gray-failure ejection plane preflight. WARN, never FAIL: the
    plane only ever REMOVES a replica that judged itself degraded, and
    every refusal path keeps training — but an operator who armed it
    should hear about knob typos, a probe that cannot run, and the N=2
    degenerate regime where a verdict can never actuate: with two
    participants and ``min_replica_size=2``, ejecting would drop the
    quorum below min_replica, so the verdict latches and is REFUSED
    (counted in ``tpuft_health_ejections_refused_total``) while
    training continues degraded."""
    from torchft_tpu import health

    if not health.enabled():
        return (
            "PASS",
            f"health plane off (set {health.ENV_HEALTH}=1 for "
            "slow-is-the-new-dead straggler verdicts + self-ejection)",
        )
    threshold = os.environ.get(health.ENV_THRESHOLD)
    if threshold is not None:
        try:
            if float(threshold) <= 1.0:
                raise ValueError
        except ValueError:
            return (
                "WARN",
                f"{health.ENV_THRESHOLD}={threshold!r} must be a number > 1 "
                "(a multiplicative bound vs the fleet median)",
            )
    for env, floor in (
        (health.ENV_CONSECUTIVE, 1),
        (health.ENV_MIN_PEERS, 1),
        (health.ENV_QUARANTINE_MAX_EJECTS, 1),
    ):
        raw = os.environ.get(env)
        if raw is not None:
            try:
                if int(raw) < floor:
                    raise ValueError
            except ValueError:
                return "WARN", f"{env}={raw!r} is not an int >= {floor}"
    for env in (
        health.ENV_QUARANTINE_BASE,
        health.ENV_QUARANTINE_CAP,
        health.ENV_QUARANTINE_WINDOW,
        health.ENV_QUARANTINE_PARK,
        health.ENV_WEDGE_FLOOR,
    ):
        raw = os.environ.get(env)
        if raw is not None:
            try:
                if float(raw) <= 0:
                    raise ValueError
            except ValueError:
                return "WARN", f"{env}={raw!r} is not a positive number"
    knobs = (
        f"threshold {os.environ.get(health.ENV_THRESHOLD, '3.0')}x, "
        f"K={os.environ.get(health.ENV_CONSECUTIVE, '3')} windows, "
        f"wedge floor {os.environ.get(health.ENV_WEDGE_FLOOR, '30')}s, "
        f"probe {'off' if os.environ.get(health.ENV_PROBE, '1') == '0' else 'on'}"
    )
    if not lighthouse:
        return "PASS", f"health plane on ({knobs}; no lighthouse to probe fleet size)"
    try:
        from torchft_tpu.coordination import LighthouseClient

        client = LighthouseClient(lighthouse, connect_timeout=5.0)
        try:
            members = len(client.status(timeout=5.0).members)
        finally:
            client.close()
    except Exception as e:  # noqa: BLE001 — WARN-never-FAIL probe
        return "WARN", f"health plane on but lighthouse probe failed ({e})"
    if members <= 2:
        return (
            "WARN",
            f"health plane on with only {members} member(s): the N=2 "
            "degenerate regime — under min_replica_size=2 an ejection "
            "would drop the quorum below min_replica, so degraded "
            "verdicts are REFUSED (counted, training continues slow); "
            "self-ejection needs ejectable headroom (N-1 >= min_replica)",
        )
    return (
        "PASS",
        f"health plane on ({knobs}; {members} members — ejectable headroom ok)",
    )


def _check_topology() -> Tuple[str, str]:
    """WAN topology matrix state. WARN, never FAIL: a malformed topology
    env degrades to the global single link at runtime (heals still work,
    just region-blind), so the doctor's job is to make that visible."""
    from torchft_tpu.utils import netem

    desc = netem.describe_topology()
    if not desc.get("configured"):
        return (
            "PASS",
            "no WAN topology (TPUFT_EMULATED_TOPOLOGY unset; wire planes "
            "region-blind, single global link applies)",
        )
    errors = desc.get("errors") or []
    if errors:
        return (
            "WARN",
            "topology configured but partially malformed (falls back to "
            f"the global link where unparsable): {'; '.join(errors)}",
        )
    names = desc.get("region_names") or []
    if desc.get("single_region"):
        return (
            "WARN",
            f"topology maps every replica to one region ({names[0] if names else '?'}) "
            "— degenerate case: region-aware striping/relay/DiLoCo routing "
            "all reduce to the region-blind path (is a region missing?)",
        )
    pieces = [
        f"{len(names)} regions ({', '.join(names)})",
        f"{desc.get('num_links', 0)} per-pair links",
    ]
    if desc.get("has_intra_default") or desc.get("has_cross_default"):
        pieces.append(
            "defaults: "
            + "/".join(
                n for n, on in (
                    ("intra", desc.get("has_intra_default")),
                    ("cross", desc.get("has_cross_default")),
                ) if on
            )
        )
    self_region = desc.get("self_region")
    if self_region:
        pieces.append(f"self={self_region}")
    return "PASS", "WAN topology: " + ", ".join(pieces)


def _check_explore() -> Tuple[str, str]:
    """Interleaving-explorer budget knobs. WARN, never FAIL: an
    unparsable TPUFT_EXPLORE_* value silently falls back to its default
    at runtime (schedules.explore_defaults), so the operator should hear
    about the typo without the preflight going red."""
    from torchft_tpu.utils.schedules import explore_defaults

    bad = []
    for env in (
        "TPUFT_EXPLORE_BUDGET", "TPUFT_EXPLORE_SEED",
        "TPUFT_EXPLORE_PREEMPTIONS", "TPUFT_EXPLORE_RANDOM",
    ):
        raw = os.environ.get(env, "")
        if not raw:
            continue
        try:
            int(raw)
        except ValueError:
            bad.append(f"{env}={raw!r}")
    d = explore_defaults()
    budgets = (
        f"budget={d['budget']} preemptions<={d['preemptions']} "
        f"random={d['random']} seed={d['seed']}"
    )
    if bad:
        return (
            "WARN",
            "unparsable TPUFT_EXPLORE_* value(s) ignored (defaults "
            f"apply): {', '.join(bad)}; effective {budgets}",
        )
    return "PASS", f"explorer budgets: {budgets}"


def _check_env() -> Tuple[str, str]:
    # Value validation first — a fatal misconfig must FAIL even when a
    # typo'd var would also WARN.
    wire = os.environ.get("TPUFT_WIRE_DTYPE")
    if wire and wire not in ("fp8", "int8", "int4"):
        return "FAIL", f"TPUFT_WIRE_DTYPE={wire!r} is invalid"
    unknown = sorted(
        name for name in os.environ
        if name.startswith("TPUFT_") and name not in KNOWN_ENV
        # Per-pair WAN link envs embed region names, so they can't be
        # enumerated in KNOWN_ENV — the topology check validates them.
        and not name.startswith("TPUFT_EMULATED_LINK_")
    )
    if unknown:
        return "WARN", f"unrecognized TPUFT_* vars (typo?): {', '.join(unknown)}"
    return "PASS", "TPUFT_* env vars recognized"


def run_checks(lighthouse: str, skip_device: bool = False) -> int:
    checks: List[Check] = [
        ("build toolchain", _check_toolchain),
        ("native plane", _check_native),
        ("kv store", _check_store),
        ("wire codecs", _check_kernels),
        ("codec negotiation", _check_wire_codec_negotiation),
        ("env vars", _check_env),
        ("wan topology", _check_topology),
        ("commit pipeline", _check_commit_pipeline),
        ("weight history", _check_history),
        ("metrics", _check_metrics),
        ("trace plane", _check_trace),
        ("interleaving explorer", _check_explore),
        ("goodput/slo", _check_goodput),
        ("heal serving", _check_heal_serve),
        ("weights serving", _check_serving),
        ("rollout policy", _check_rollout),
        ("heal striping", lambda: _check_heal_stripe(lighthouse)),
        ("health plane", lambda: _check_health(lighthouse)),
        ("rejoin storm", lambda: _check_rejoin_storm(lighthouse)),
        ("zero plane", lambda: _check_zero(lighthouse)),
        ("lighthouse", lambda: _check_lighthouse(lighthouse)),
    ]
    if not skip_device:
        checks.append(("accelerator", _check_device))
    failed = False
    for name, fn in checks:
        try:
            status, detail = fn()
        except Exception as e:  # noqa: BLE001 — each check reports, never aborts
            status, detail = "FAIL", f"{type(e).__name__}: {e}"
        failed |= status == "FAIL"
        print(f"[{status:4s}] {name}: {detail}", flush=True)
    print("doctor: " + ("FAIL" if failed else "OK"))
    return 1 if failed else 0


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--lighthouse",
        default=os.environ.get("TPUFT_LIGHTHOUSE", ""),
        help="lighthouse address to ping (default: $TPUFT_LIGHTHOUSE)",
    )
    parser.add_argument(
        "--skip-device", action="store_true",
        help="skip the accelerator probe (slow when the backend is wedged)",
    )
    args = parser.parse_args()
    sys.exit(run_checks(args.lighthouse, skip_device=args.skip_device))


if __name__ == "__main__":
    main()
