"""Fault-tolerant future/timeout engine.

Role-equivalent of the reference's ``torchft/futures.py``: a singleton
timer service that can bound any future or code region with a deadline, plus
a watchdog thread that hard-exits the process if the timer service itself
wedges — the last line of defense against undetectable hangs
(/root/reference/torchft/futures.py:97-120).

CUDA-event timeouts don't apply on TPU; the JAX analogue of "did the step
finish" is a ``jax.block_until_ready`` bounded by :func:`context_timeout`.

Env: ``TPUFT_WATCHDOG_TIMEOUT_SEC`` (default 30).
"""

from __future__ import annotations

import heapq
import itertools
import os
import sys
import threading
import time
from concurrent.futures import Future
from contextlib import contextmanager
from typing import Any, Callable, Generator, Optional

__all__ = [
    "future_timeout",
    "future_wait",
    "context_timeout",
    "stream_timeout",
    "CommitPipeline",
]

WATCHDOG_TIMEOUT_SEC = float(os.environ.get("TPUFT_WATCHDOG_TIMEOUT_SEC", "30"))


class _TimerHandle:
    __slots__ = ("cancelled",)

    def __init__(self) -> None:
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class _TimeoutManager:
    """Single scheduler thread firing deadline callbacks, watched by a
    watchdog that hard-exits the process if the scheduler stalls."""

    def __init__(self) -> None:
        self._lock = threading.Condition()
        self._heap: list = []  # (deadline, seq, handle, callback)
        self._seq = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        self._last_tick = time.monotonic()
        self._watchdog_enabled = True

    def _ensure_started(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tpuft-timeout-manager"
            )
            self._thread.start()
            self._watchdog = threading.Thread(
                target=self._run_watchdog, daemon=True, name="tpuft-watchdog"
            )
            self._watchdog.start()

    def schedule(self, delay: float, callback: Callable[[], None]) -> _TimerHandle:
        self._ensure_started()
        handle = _TimerHandle()
        deadline = time.monotonic() + delay
        with self._lock:
            heapq.heappush(self._heap, (deadline, next(self._seq), handle, callback))
            self._lock.notify()
        return handle

    def _run(self) -> None:
        while True:
            with self._lock:
                self._last_tick = time.monotonic()
                if not self._heap:
                    self._lock.wait(timeout=1.0)
                    continue
                deadline, _, handle, callback = self._heap[0]
                now = time.monotonic()
                if deadline > now:
                    self._lock.wait(timeout=min(deadline - now, 1.0))
                    continue
                heapq.heappop(self._heap)
            if not handle.cancelled:
                try:
                    callback()
                except Exception:  # noqa: BLE001
                    # A failing timeout callback must not kill the scheduler.
                    import traceback

                    traceback.print_exc()

    def _run_watchdog(self) -> None:
        while True:
            time.sleep(WATCHDOG_TIMEOUT_SEC / 4)
            if not self._watchdog_enabled:
                continue
            stalled = time.monotonic() - self._last_tick
            if stalled > WATCHDOG_TIMEOUT_SEC:
                sys.stderr.write(
                    f"tpuft watchdog: timeout scheduler stalled {stalled:.1f}s "
                    f"(> {WATCHDOG_TIMEOUT_SEC}s); exiting\n"
                )
                sys.stderr.flush()
                self._exit(1)
                # Only reachable when the exit seam is mocked (tests): end
                # the watchdog thread instead of re-firing forever.
                return

    def _exit(self, code: int) -> None:  # test seam
        # os._exit, not sys.exit: SystemExit raised in a non-main thread
        # only kills that thread — the watchdog contract is a process
        # hard-exit when the timeout scheduler is wedged.
        os._exit(code)


_TIMEOUT_MANAGER = _TimeoutManager()


def future_timeout(fut: "Future[Any]", timeout: float) -> "Future[Any]":
    """A future mirroring ``fut`` but failing with TimeoutError after
    ``timeout`` seconds (reference: futures.py:146-191)."""
    out: Future = Future()

    def on_timeout() -> None:
        if not out.done():
            out.set_exception(TimeoutError(f"future timed out after {timeout}s"))

    handle = _TIMEOUT_MANAGER.schedule(timeout, on_timeout)

    def on_done(f: "Future[Any]") -> None:
        handle.cancel()
        if out.done():
            return
        err = f.exception()
        if err is not None:
            try:
                out.set_exception(err)
            except Exception:  # noqa: BLE001  (already resolved by timeout race)
                pass
        else:
            try:
                out.set_result(f.result())
            except Exception:  # noqa: BLE001
                pass

    fut.add_done_callback(on_done)
    return out


def future_wait(fut: "Future[Any]", timeout: float) -> Any:
    """Blocks on ``fut`` up to ``timeout``; raises TimeoutError on expiry."""
    return fut.result(timeout=timeout)


@contextmanager
def context_timeout(
    callback: Callable[[], None], timeout: float
) -> Generator[None, None, None]:
    """Runs ``callback`` if the with-body hasn't finished within ``timeout``
    (reference: futures.py:228-243). Used to abort a wedged collective."""
    handle = _TIMEOUT_MANAGER.schedule(timeout, callback)
    try:
        yield
    finally:
        handle.cancel()


def stream_timeout(callback: Callable[[], None], timeout: float) -> _TimerHandle:
    """Schedules ``callback`` unless cancelled within ``timeout`` — the
    TPU analogue of the reference's CUDA-event stream timeout: pair it with
    ``jax.block_until_ready`` and cancel on completion."""
    return _TIMEOUT_MANAGER.schedule(timeout, callback)


class CommitPipeline:
    """Depth-bounded queue of pending pipelined-commit steps — the
    speculative window behind ``Manager(commit_pipeline_depth=...)``.

    At most ``depth`` steps may be awaiting their commit verdict at once:
    the owner (optim.Optimizer's pipelined step_fn) pushes one record per
    dispatched step and must resolve enough of the oldest records to make
    room before pushing past ``depth``. The bound is dynamic
    (:meth:`set_depth`) so the adaptive controller can deepen or shrink
    the window between steps; records already admitted are never evicted
    by a shrink — the owner drains down to the new bound. Records are
    opaque beyond the two idempotent phases every pipelined step has — a
    vote resolution (owner-driven, may roll state back) and a device
    bound (``bound_device(raise_on_error=...)``, safe from any thread).
    The queue itself only does thread-safe bookkeeping: the manager's
    quorum-change drain and the optimizer's step loop touch it from
    different threads.
    """

    def __init__(self, depth: int = 1) -> None:
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self._depth = depth
        self._lock = threading.Lock()
        self._records: list = []

    def _note_occupancy(self) -> None:
        # Called under self._lock. Lazy import: futures is a leaf module
        # metrics itself may one day time — keep the import edge one-way.
        from torchft_tpu import metrics

        metrics.set_gauge("tpuft_pipeline_pending", len(self._records))

    @property
    def depth(self) -> int:
        return self._depth

    def set_depth(self, depth: int) -> None:
        """Rebounds the window (the adaptive controller's lever). Growing
        takes effect on the next push; shrinking never evicts — the owner
        resolves oldest records until occupancy fits the new bound."""
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        with self._lock:
            self._depth = depth

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def push(self, record: Any) -> None:
        """Admits a newly dispatched step. The owner resolves the oldest
        record before pushing past ``depth`` — exceeding it means a step
        was dispatched with more than ``depth`` commits unaccounted, which
        the bounded envelope forbids."""
        from torchft_tpu.utils import schedules

        schedules.point("pipeline.push")
        with self._lock:
            if len(self._records) >= self._depth:
                raise RuntimeError(
                    f"commit pipeline full (depth={self._depth}); resolve the "
                    "oldest pending step before dispatching another"
                )
            self._records.append(record)
            from torchft_tpu import metrics

            metrics.inc("tpuft_pipeline_steps_total")
            self._note_occupancy()

    def oldest(self) -> Optional[Any]:
        with self._lock:
            return self._records[0] if self._records else None

    def remove(self, record: Any) -> None:
        with self._lock:
            if record in self._records:
                self._records.remove(record)
                self._note_occupancy()

    def pending(self) -> tuple:
        """Snapshot of the pending records, oldest first."""
        with self._lock:
            return tuple(self._records)

    def drain(self) -> tuple:
        """Pops every pending record (oldest first); the caller resolves
        them. Used at step-loop boundaries: flush, shutdown, switching
        step protocols."""
        from torchft_tpu.utils import schedules

        schedules.point("pipeline.drain")
        with self._lock:
            records, self._records = tuple(self._records), []
            self._note_occupancy()
            return records
