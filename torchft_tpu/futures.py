"""Fault-tolerant future/timeout engine.

Role-equivalent of the reference's ``torchft/futures.py``: a singleton
timer service that can bound any future or code region with a deadline, plus
a watchdog thread that hard-exits the process if the timer service itself
wedges — the last line of defense against undetectable hangs
(/root/reference/torchft/futures.py:97-120).

CUDA-event timeouts don't apply on TPU; the JAX analogue of "did the step
finish" is a ``jax.block_until_ready`` bounded by :func:`context_timeout`.

Env: ``TPUFT_WATCHDOG_TIMEOUT_SEC`` (default 30).
"""

from __future__ import annotations

import heapq
import itertools
import os
import sys
import threading
import time
from concurrent.futures import Future
from contextlib import contextmanager
from typing import Any, Callable, Generator, Optional

__all__ = ["future_timeout", "future_wait", "context_timeout", "stream_timeout"]

WATCHDOG_TIMEOUT_SEC = float(os.environ.get("TPUFT_WATCHDOG_TIMEOUT_SEC", "30"))


class _TimerHandle:
    __slots__ = ("cancelled",)

    def __init__(self) -> None:
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class _TimeoutManager:
    """Single scheduler thread firing deadline callbacks, watched by a
    watchdog that hard-exits the process if the scheduler stalls."""

    def __init__(self) -> None:
        self._lock = threading.Condition()
        self._heap: list = []  # (deadline, seq, handle, callback)
        self._seq = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        self._last_tick = time.monotonic()
        self._watchdog_enabled = True

    def _ensure_started(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tpuft-timeout-manager"
            )
            self._thread.start()
            self._watchdog = threading.Thread(
                target=self._run_watchdog, daemon=True, name="tpuft-watchdog"
            )
            self._watchdog.start()

    def schedule(self, delay: float, callback: Callable[[], None]) -> _TimerHandle:
        self._ensure_started()
        handle = _TimerHandle()
        deadline = time.monotonic() + delay
        with self._lock:
            heapq.heappush(self._heap, (deadline, next(self._seq), handle, callback))
            self._lock.notify()
        return handle

    def _run(self) -> None:
        while True:
            with self._lock:
                self._last_tick = time.monotonic()
                if not self._heap:
                    self._lock.wait(timeout=1.0)
                    continue
                deadline, _, handle, callback = self._heap[0]
                now = time.monotonic()
                if deadline > now:
                    self._lock.wait(timeout=min(deadline - now, 1.0))
                    continue
                heapq.heappop(self._heap)
            if not handle.cancelled:
                try:
                    callback()
                except Exception:  # noqa: BLE001
                    # A failing timeout callback must not kill the scheduler.
                    import traceback

                    traceback.print_exc()

    def _run_watchdog(self) -> None:
        while True:
            time.sleep(WATCHDOG_TIMEOUT_SEC / 4)
            if not self._watchdog_enabled:
                continue
            stalled = time.monotonic() - self._last_tick
            if stalled > WATCHDOG_TIMEOUT_SEC:
                sys.stderr.write(
                    f"tpuft watchdog: timeout scheduler stalled {stalled:.1f}s "
                    f"(> {WATCHDOG_TIMEOUT_SEC}s); exiting\n"
                )
                sys.stderr.flush()
                self._exit(1)
                # Only reachable when the exit seam is mocked (tests): end
                # the watchdog thread instead of re-firing forever.
                return

    def _exit(self, code: int) -> None:  # test seam
        # os._exit, not sys.exit: SystemExit raised in a non-main thread
        # only kills that thread — the watchdog contract is a process
        # hard-exit when the timeout scheduler is wedged.
        os._exit(code)


_TIMEOUT_MANAGER = _TimeoutManager()


def future_timeout(fut: "Future[Any]", timeout: float) -> "Future[Any]":
    """A future mirroring ``fut`` but failing with TimeoutError after
    ``timeout`` seconds (reference: futures.py:146-191)."""
    out: Future = Future()

    def on_timeout() -> None:
        if not out.done():
            out.set_exception(TimeoutError(f"future timed out after {timeout}s"))

    handle = _TIMEOUT_MANAGER.schedule(timeout, on_timeout)

    def on_done(f: "Future[Any]") -> None:
        handle.cancel()
        if out.done():
            return
        err = f.exception()
        if err is not None:
            try:
                out.set_exception(err)
            except Exception:  # noqa: BLE001  (already resolved by timeout race)
                pass
        else:
            try:
                out.set_result(f.result())
            except Exception:  # noqa: BLE001
                pass

    fut.add_done_callback(on_done)
    return out


def future_wait(fut: "Future[Any]", timeout: float) -> Any:
    """Blocks on ``fut`` up to ``timeout``; raises TimeoutError on expiry."""
    return fut.result(timeout=timeout)


@contextmanager
def context_timeout(
    callback: Callable[[], None], timeout: float
) -> Generator[None, None, None]:
    """Runs ``callback`` if the with-body hasn't finished within ``timeout``
    (reference: futures.py:228-243). Used to abort a wedged collective."""
    handle = _TIMEOUT_MANAGER.schedule(timeout, callback)
    try:
        yield
    finally:
        handle.cancel()


def stream_timeout(callback: Callable[[], None], timeout: float) -> _TimerHandle:
    """Schedules ``callback`` unless cancelled within ``timeout`` — the
    TPU analogue of the reference's CUDA-event stream timeout: pair it with
    ``jax.block_until_ready`` and cancel on completion."""
    return _TIMEOUT_MANAGER.schedule(timeout, callback)
